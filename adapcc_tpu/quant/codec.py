"""Block-wise wire codecs: shrink the bytes a collective ships.

AdapCC adapts the *shape* of the communication to the fabric; this module
adapts the *density*.  EQuARX (PAPERS.md) shows block-wise int8 with dual
quantization recovers near-full accuracy at 2-4x wire savings inside XLA
collectives; GC3-style strategy separation says the codec belongs in the
strategy/IR layer, not hard-coded in kernels.  Accordingly everything here
is a pure jittable function plus a registry the strategy plane names codecs
by (``Strategy.wire_dtype``), so the same codec definition serves the DDP
gradient hook, the engine's quantized ring, the simulator's pricing term,
and the XML artifact.

The int8 wire format
--------------------

A flat fp32 payload of ``n`` elements is padded to whole blocks of
``block_size`` elements and quantized per block:

    scale_b = max(|x| over block b) / 127        (fp32, one per block)
    q_i     = round(x_i / scale_b)               (int8, clipped to [-127, 127])

Wire bytes per element: ``1 + 4 / block_size`` (the int8 payload plus the
amortized fp32 scale) vs 4 for fp32 — a ~3.9x reduction at the default
block of 256.  An all-zero block keeps ``scale = 1`` so dequantization is
total.

Two rounding modes:

- **deterministic** (default): ``jnp.round`` (half-to-even).  Bit-exact
  across calls and ranks — the mode the data plane runs, so a replayed
  collective is reproducible.
- **stochastic**: ``floor(y + u)``, ``u ~ U[0, 1)`` from a caller-provided
  PRNG key.  Unbiased (``E[q·scale] = x``), the property gradient
  averaging over many steps prefers when no error feedback is running.

Error feedback
--------------

Quantization error is not noise to discard but signal to defer:
``compensated = grad + residual``, the wire carries
``decode(encode(compensated))``, and ``residual = compensated - wire`` is
folded into the *next* step.  The invariant (tested):
``sum(wire values over steps) + residual == sum(true gradients)`` — no
gradient mass is ever lost, which is what closes the accuracy gap of
deterministic int8 on real training loops.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

#: default quantization block (elements per fp32 scale).  Mirrored by the
#: simulator's pricing term (sim/cost_model.DEFAULT_QUANT_BLOCK — drift is
#: pinned by a test).
DEFAULT_BLOCK_SIZE = 256

#: env override for the wire codec (sweeps / operator pin); wins over both
#: the caller's value and the strategy's synthesized wire_dtype — the same
#: precedence contract as ADAPCC_RING_CHUNK_BYTES
WIRE_DTYPE_ENV = "ADAPCC_WIRE_DTYPE"


# --------------------------------------------------------------------------- #
# block-wise int8 quantize / dequantize
# --------------------------------------------------------------------------- #

def _as_blocks(flat: jnp.ndarray, block_size: int) -> jnp.ndarray:
    """[n] -> [nblocks, block_size], zero-padded to whole blocks."""
    if block_size < 1:
        raise ValueError(f"block_size must be >= 1, got {block_size}")
    n = flat.shape[0]
    nblocks = -(-n // block_size) if n else 1
    pad = nblocks * block_size - n
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat.reshape(nblocks, block_size)


def quantize_int8(
    flat: jnp.ndarray,
    block_size: int = DEFAULT_BLOCK_SIZE,
    stochastic: bool = False,
    key: Optional[jax.Array] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Block-wise int8 quantization of a flat float payload.

    Returns ``(q [nblocks, block_size] int8, scales [nblocks] fp32)``.
    Deterministic rounding is bit-exact across calls; stochastic rounding
    needs ``key`` and is unbiased in expectation.
    """
    blocks = _as_blocks(flat.astype(jnp.float32), block_size)
    absmax = jnp.max(jnp.abs(blocks), axis=1)
    scales = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    y = blocks / scales[:, None]
    if stochastic:
        if key is None:
            raise ValueError("stochastic rounding needs a PRNG key")
        u = jax.random.uniform(key, y.shape, dtype=jnp.float32)
        q = jnp.floor(y + u)
    else:
        q = jnp.round(y)
    q = jnp.clip(q, -127.0, 127.0).astype(jnp.int8)
    return q, scales


def dequantize_int8(
    q: jnp.ndarray, scales: jnp.ndarray, n: Optional[int] = None
) -> jnp.ndarray:
    """Inverse of :func:`quantize_int8`; ``n`` trims the block padding."""
    flat = (q.astype(jnp.float32) * scales[:, None]).reshape(-1)
    return flat if n is None else flat[:n]


def int8_roundtrip(
    flat: jnp.ndarray,
    block_size: int = DEFAULT_BLOCK_SIZE,
    stochastic: bool = False,
    key: Optional[jax.Array] = None,
) -> jnp.ndarray:
    """The wire *value* of a payload: decode(encode(x)).  Jittable."""
    q, scales = quantize_int8(flat, block_size, stochastic, key)
    return dequantize_int8(q, scales, flat.shape[0])


def int8_error_bound(
    flat, block_size: int = DEFAULT_BLOCK_SIZE, stochastic: bool = False
):
    """Elementwise |x - roundtrip(x)| bound: half a quantization step per
    block under deterministic rounding (a full step stochastic).  The bound
    scales with the block max — the property the block-wise format exists
    for (one outlier only coarsens its own block)."""
    import numpy as np

    blocks = np.asarray(_as_blocks(jnp.asarray(flat, jnp.float32), block_size))
    absmax = np.max(np.abs(blocks), axis=1)
    step = np.where(absmax > 0, absmax / 127.0, 1.0)
    per_block = step * (1.0 if stochastic else 0.5)
    n = np.asarray(flat).reshape(-1).shape[0]
    return np.repeat(per_block, block_size)[:n]


# --------------------------------------------------------------------------- #
# codec registry: the one place codecs are named
# --------------------------------------------------------------------------- #

@dataclass(frozen=True)
class WireCodec:
    """One wire codec: value semantics (``apply``), transport arrays
    (``encode``/``decode``), and the wire density the simulator prices.

    ``apply(x, block_size)`` is the jittable quantize->dequantize round
    trip in the input's shape and dtype — the value every rank's collective
    contribution takes when this codec is on the wire.  ``encode`` returns
    the tuple of arrays that actually crosses the fabric (each one
    ppermute-able); ``decode(wire, n)`` reverses it to a flat fp32 payload.
    """

    name: str
    apply: Callable[..., jnp.ndarray]
    encode: Callable[..., Tuple[jnp.ndarray, ...]]
    decode: Callable[..., jnp.ndarray]
    #: (block_size, elem_bytes) -> wire bytes per payload element
    wire_bytes_per_element: Callable[[int, float], float]


def _identity_apply(x, block_size: int = DEFAULT_BLOCK_SIZE):
    return x


def _bf16_apply(x, block_size: int = DEFAULT_BLOCK_SIZE):
    return x.astype(jnp.bfloat16).astype(x.dtype)


def _int8_apply(x, block_size: int = DEFAULT_BLOCK_SIZE):
    flat = x.reshape(-1).astype(jnp.float32)
    return int8_roundtrip(flat, block_size).reshape(x.shape).astype(x.dtype)


_REGISTRY: Dict[str, WireCodec] = {}


def register_codec(codec: WireCodec) -> WireCodec:
    """Add a codec to the registry (idempotent for an identical name is NOT
    allowed — a silent re-register would let two meanings of one wire_dtype
    coexist across artifacts)."""
    if codec.name in _REGISTRY:
        raise ValueError(f"codec {codec.name!r} already registered")
    _REGISTRY[codec.name] = codec
    return codec


def codec_names() -> Tuple[str, ...]:
    return tuple(_REGISTRY)


def get_codec(name: str) -> WireCodec:
    """Registry lookup; unknown names fail loudly with the known set (the
    GradSyncHook / Strategy / XML validation funnel)."""
    codec = _REGISTRY.get(name)
    if codec is None:
        raise ValueError(
            f"unknown wire codec {name!r}; registered codecs: "
            f"{'|'.join(codec_names())}"
        )
    return codec


register_codec(WireCodec(
    name="off",
    apply=_identity_apply,
    encode=lambda flat, block_size=DEFAULT_BLOCK_SIZE: (flat,),
    decode=lambda wire, n, block_size=DEFAULT_BLOCK_SIZE: wire[0][:n],
    wire_bytes_per_element=lambda block_size=DEFAULT_BLOCK_SIZE, elem_bytes=4.0: float(elem_bytes),
))

register_codec(WireCodec(
    name="bf16",
    apply=_bf16_apply,
    encode=lambda flat, block_size=DEFAULT_BLOCK_SIZE: (
        flat.astype(jnp.bfloat16),
    ),
    decode=lambda wire, n, block_size=DEFAULT_BLOCK_SIZE: (
        wire[0].astype(jnp.float32)[:n]
    ),
    wire_bytes_per_element=lambda block_size=DEFAULT_BLOCK_SIZE, elem_bytes=4.0: 2.0,
))

register_codec(WireCodec(
    name="int8",
    apply=_int8_apply,
    encode=lambda flat, block_size=DEFAULT_BLOCK_SIZE: quantize_int8(
        flat, block_size
    ),
    decode=lambda wire, n, block_size=DEFAULT_BLOCK_SIZE: dequantize_int8(
        wire[0], wire[1], n
    ),
    wire_bytes_per_element=lambda block_size=DEFAULT_BLOCK_SIZE, elem_bytes=4.0: (
        1.0 + 4.0 / block_size
    ),
))


def resolve_wire_dtype(wire_dtype: Optional[str] = None) -> str:
    """The wire codec actually in force: the ``ADAPCC_WIRE_DTYPE`` sweep /
    operator override wins, then the caller's (synthesized) value, then
    ``"off"``.  A malformed override raises — a typo silently falling back
    to the default would invalidate an A/B (the ADAPCC_RING_CHUNK_BYTES
    policy)."""
    env = os.environ.get(WIRE_DTYPE_ENV)
    if env is not None and env.strip():
        name = env.strip()
        if name not in _REGISTRY:
            raise ValueError(
                f"{WIRE_DTYPE_ENV}={env!r}: expected one of "
                f"{'|'.join(codec_names())}"
            )
        return name
    if wire_dtype is None:
        return "off"
    return get_codec(wire_dtype).name


# --------------------------------------------------------------------------- #
# error feedback
# --------------------------------------------------------------------------- #

def error_feedback_step(
    grads: Any,
    residual: Any,
    apply_fn: Callable[[jnp.ndarray], jnp.ndarray],
) -> Tuple[Any, Any]:
    """One error-feedback round over a pytree: returns ``(wire,
    new_residual)`` with ``wire = apply(grads + residual)`` and
    ``new_residual = (grads + residual) - wire``.

    Exact invariant (same-rounding fp32 arithmetic): ``wire + new_residual
    == grads + residual``, so across steps the synced wire values plus the
    carried residual always sum to the true gradient mass.
    """
    compensated = jax.tree_util.tree_map(lambda g, r: g + r, grads, residual)
    wire = jax.tree_util.tree_map(apply_fn, compensated)
    new_residual = jax.tree_util.tree_map(
        lambda c, w: c - w, compensated, wire
    )
    return wire, new_residual


# --------------------------------------------------------------------------- #
# host-side codec timing (observability satellite)
# --------------------------------------------------------------------------- #

#: process-wide default registry for codec timings, created on first use;
#: ``MetricsRegistry.snapshot()`` exposes p50/p99 over its bounded reservoir
CODEC_METRICS = None


def timed_roundtrip(
    name: str,
    x: jnp.ndarray,
    block_size: int = DEFAULT_BLOCK_SIZE,
    registry=None,
) -> jnp.ndarray:
    """Eagerly encode+decode ``x`` through codec ``name``, recording wall
    times as ``quant.<name>.quantize`` / ``quant.<name>.dequantize`` in the
    metrics registry (module default when none given).  Host-side only —
    inside a jitted program the codec is fused and has no separable time;
    this is the microbenchmark surface ``make quant-bench`` and the docs
    snippets use."""
    global CODEC_METRICS
    if registry is None:
        if CODEC_METRICS is None:
            from adapcc_tpu.utils.observability import MetricsRegistry

            CODEC_METRICS = MetricsRegistry()
        registry = CODEC_METRICS
    codec = get_codec(name)
    flat = jnp.asarray(x).reshape(-1).astype(jnp.float32)
    with registry.timer(f"quant.{name}.quantize"):
        wire = jax.block_until_ready(codec.encode(flat, block_size))
    with registry.timer(f"quant.{name}.dequantize"):
        out = jax.block_until_ready(
            codec.decode(wire, flat.shape[0], block_size)
        )
    return out.reshape(jnp.asarray(x).shape)
