"""Runtime configuration: the flag contract of the reference launcher.

The reference forwards a fixed flag set to every rank's training script
(launcher.py:19-32 → train_ddp.py:60-69): port, entry_point, strategy_file,
logical_graph, parallel_degree, profile_freq.  ``CommArgs`` carries the same
contract (plus TPU-native knobs) and accepts any argparse-style namespace
using those reference names.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

from adapcc_tpu.primitives import COORDINATOR_PORT, DEFAULT_CHUNK_BYTES, SKIP_BOOTSTRAP


@dataclass
class CommArgs:
    port: int = COORDINATOR_PORT
    strategy_file: str = "topology/strategy.xml"
    logical_graph: str = "topology/logical_graph.xml"
    entry_point: int = SKIP_BOOTSTRAP
    parallel_degree: int = 1
    profile_freq: int = 0
    #: directory holding the XML/CSV topology artifacts
    topology_dir: str = "topology"
    #: synthesis policy: par-trees | milp | ring | binary | sim-rank
    #: (sim-rank commits to whichever candidate the calibrated α-β replay
    #: predicts fastest — docs/SIMULATION.md)
    policy: str = "par-trees"
    #: BSP mode: stragglers skip the collective and reuse stale gradients;
    #: async mode replays their buckets through relay buffers later
    #: (reference is_bsp flag, commu.py:107)
    is_bsp: bool = True
    #: full-world allreduce uses lax.psum instead of the tree schedule
    use_xla_fastpath: bool = True
    default_chunk_bytes: int = DEFAULT_CHUNK_BYTES
    coordinator_ip: Optional[str] = None
    #: worker-side wait for master-published artifacts (profile gather +
    #: synthesis can take minutes at large world sizes)
    kv_timeout_ms: int = 600_000

    @classmethod
    def from_namespace(cls, ns: Any) -> "CommArgs":
        """Build from an argparse namespace using reference flag names;
        unknown fields keep their defaults."""
        kwargs = {}
        for f in cls.__dataclass_fields__:
            if hasattr(ns, f) and getattr(ns, f) is not None:
                kwargs[f] = getattr(ns, f)
        return cls(**kwargs)
