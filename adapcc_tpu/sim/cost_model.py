"""Per-link α-β transfer cost model with ICI/DCN link classes.

The α-β (a.k.a. postal / LogP-degenerate) model prices one point-to-point
transfer of ``n`` bytes over a link as

    t(n) = α + β·n          α = fixed latency [s], β = inverse bandwidth [s/B]

which is exactly the information content of the profiler's two probe points
(:mod:`adapcc_tpu.topology.profile`): the 64-float round times the latency
term, the 1M-float round times the bandwidth term, and a least-squares line
through the (bytes, seconds) points recovers (α, β) per directed link.
TACCL's and SCCL's synthesizers (PAPERS.md) rank candidate schedules with
the same model; here it also prices relay-masked and degraded scenarios.

Links are classed **ICI** (same host/slice — fast mesh) or **DCN**
(cross-host) by the rank→ip table, mirroring ``Tree.is_cross_host``.  Links
without their own probe points inherit their class's mean coefficients, so
a partial profile (or a class-level calibration artifact) still prices every
edge.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict, Mapping, Optional, Sequence, Tuple

import numpy as np

from adapcc_tpu.topology.profile import BANDWIDTH_PROBE_FLOATS, LATENCY_PROBE_FLOATS

#: link-class labels (the TPU reading of the reference's intra/inter-host split)
ICI = "ici"
DCN = "dcn"

#: probe payload sizes in bytes (float32 payloads, profile.cu:120-158 analog)
LATENCY_PROBE_BYTES = LATENCY_PROBE_FLOATS * 4
BANDWIDTH_PROBE_BYTES = BANDWIDTH_PROBE_FLOATS * 4

#: fallback coefficients when nothing was ever measured: ~v5e ICI link
#: (α ≈ 1 µs, β ≈ 1/45 GB/s) and a conservative DCN link (α ≈ 25 µs,
#: β ≈ 1/12.5 GB/s) — deliberately round numbers, replaced by any calibration
DEFAULT_COEFFS = {
    ICI: (1e-6, 1.0 / 45e9),
    DCN: (25e-6, 1.0 / 12.5e9),
}

Link = Tuple[int, int]


@dataclass(frozen=True)
class LinkCoeffs:
    """α [s] + β [s/byte] for one link (or one link class)."""

    alpha: float
    beta: float

    def time(self, nbytes: float) -> float:
        return self.alpha + self.beta * float(nbytes)

    def scaled(self, factor: float) -> "LinkCoeffs":
        """Both terms slowed by ``factor`` (degraded-link modeling)."""
        return LinkCoeffs(self.alpha * factor, self.beta * factor)


def fit_alpha_beta(points: Sequence[Tuple[float, float]]) -> LinkCoeffs:
    """Least-squares line ``t = α + β·bytes`` through (bytes, seconds) points.

    Negative coefficients (noisy probes, e.g. a big transfer that timed
    *faster* than a small one) clamp to zero — a cost model must never pay
    you to send data.  A single point is read as pure latency.
    """
    pts = [(float(b), float(t)) for b, t in points]
    if not pts:
        raise ValueError("need at least one (bytes, seconds) probe point")
    if len(pts) == 1:
        return LinkCoeffs(alpha=max(0.0, pts[0][1]), beta=0.0)
    a = np.array([[1.0, b] for b, _ in pts])
    y = np.array([t for _, t in pts])
    (alpha, beta), *_ = np.linalg.lstsq(a, y, rcond=None)
    return LinkCoeffs(alpha=max(0.0, float(alpha)), beta=max(0.0, float(beta)))


class LinkCostModel:
    """Prices point-to-point transfers: per-link coefficients where probed,
    class means elsewhere, :data:`DEFAULT_COEFFS` as the last resort."""

    def __init__(
        self,
        world: int,
        links: Optional[Mapping[Link, LinkCoeffs]] = None,
        classes: Optional[Mapping[str, LinkCoeffs]] = None,
        ips: Optional[Mapping[int, str]] = None,
        source: str = "unspecified",
    ) -> None:
        if world < 1:
            raise ValueError(f"world must be >= 1, got {world}")
        self.world = world
        self.links: Dict[Link, LinkCoeffs] = dict(links or {})
        self.classes: Dict[str, LinkCoeffs] = {
            cls: LinkCoeffs(*DEFAULT_COEFFS[cls]) for cls in (ICI, DCN)
        }
        self.classes.update(classes or {})
        self.ips = dict(ips) if ips else None
        #: provenance stamp carried into artifacts ("profile:<dir>",
        #: "battery:<file>", "synthetic", ...)
        self.source = source

    # -- pricing ---------------------------------------------------------------

    def link_class_of(self, src: int, dst: int) -> str:
        """Directed link → class, computed from the ip table on demand (an
        eager world² matrix is hostile to pod-scale ranking).  No ip table
        means one flat fast domain: everything is ICI."""
        if self.ips is None:
            return ICI
        return ICI if self.ips.get(src) == self.ips.get(dst) else DCN

    def coeffs(self, src: int, dst: int) -> LinkCoeffs:
        hit = self.links.get((src, dst))
        if hit is not None:
            return hit
        return self.classes[self.link_class_of(src, dst)]

    def time_for(self, src: int, dst: int, nbytes: float) -> float:
        """Seconds to move ``nbytes`` over the (src → dst) link, uncontended."""
        return self.coeffs(src, dst).time(nbytes)

    # -- derived models --------------------------------------------------------

    def degraded(
        self, slow_ranks: Sequence[int], slowdown: float
    ) -> "LinkCostModel":
        """A copy with every link touching a slow rank stretched by
        ``slowdown`` ≥ 1 — the straggler scenario the relay controller prices
        when deciding whether to demote a rank to a forwarding relay."""
        if slowdown < 1.0:
            raise ValueError(f"slowdown must be >= 1, got {slowdown}")
        slow = set(slow_ranks)
        links = dict(self.links)
        classes = dict(self.classes)
        model = LinkCostModel(
            self.world, links, classes, self.ips, source=self.source
        )
        for r in slow:
            for other in range(self.world):
                if other == r:
                    continue
                for link in ((r, other), (other, r)):
                    model.links[link] = self.coeffs(*link).scaled(slowdown)
        return model

    def contended(
        self, class_factors: Mapping[str, float]
    ) -> "LinkCostModel":
        """A copy with the named link classes' effective bandwidth cut by
        their factor — congestion, not degradation: β scales (a neighbor's
        traffic steals bandwidth share), α is untouched (the wire's
        propagation latency survives contention).  Per-link fits of a
        contended class scale the same way, so a per-link-fitted artifact
        prices the contention too.  Contrast :meth:`degraded`/
        :meth:`LinkCoeffs.scaled`, which stretch BOTH terms — that α/β
        signature difference is exactly what the congestion-vs-degradation
        triage keys on (docs/FABRIC.md)."""
        for cls_name, factor in class_factors.items():
            if cls_name not in self.classes:
                raise ValueError(
                    f"unknown link class {cls_name!r}; expected one of "
                    f"{sorted(self.classes)}"
                )
            if factor < 1.0:
                raise ValueError(
                    f"contention factor must be >= 1, got {factor} for "
                    f"class {cls_name!r}"
                )
        classes = {
            cls_name: (
                contended_coeffs(c, class_factors[cls_name])
                if cls_name in class_factors
                else c
            )
            for cls_name, c in self.classes.items()
        }
        links = {
            l: (
                contended_coeffs(c, class_factors[self.link_class_of(*l)])
                if self.link_class_of(*l) in class_factors
                else c
            )
            for l, c in self.links.items()
        }
        joined = ",".join(
            f"{cls}x{f:g}" for cls, f in sorted(class_factors.items())
        )
        return LinkCostModel(
            self.world, links=links, classes=classes, ips=self.ips,
            source=f"{self.source}+contended[{joined}]",
        )

    def with_ips(self, ips: Optional[Mapping[int, str]]) -> "LinkCostModel":
        """A copy pricing the same coefficients under ``ips``'s host layout
        — the one way callers (sim_collectives.sweep, the sim-rank policy's
        fallback) attach a host split to a calibration that carries none,
        so candidate shapes and replay pricing see the same network."""
        return LinkCostModel(
            self.world, links=self.links, classes=self.classes, ips=ips,
            source=self.source,
        )

    def to_graphs(self) -> Tuple[list, list]:
        """(bandwidth [GB/s], latency [s]) matrices read off the calibrated
        coefficients — the synthesizer-input spelling of this model, so
        candidate *shapes* (ParTrees master routing included) can be
        synthesized for exactly the network a replay will price.  Shared by
        the simulated bench and the online re-rank (docs/ADAPT.md)."""
        w = self.world
        bw = [[0.0] * w for _ in range(w)]
        lat = [[0.0] * w for _ in range(w)]
        for s in range(w):
            for d in range(w):
                if s == d:
                    continue
                c = self.coeffs(s, d)
                lat[s][d] = c.alpha
                bw[s][d] = 1.0 / (c.beta * 1e9) if c.beta > 0 else 1e6
        return bw, lat

    # -- construction from profiles --------------------------------------------

    @classmethod
    def from_matrices(
        cls,
        lat: np.ndarray,
        bw: np.ndarray,
        ips: Optional[Mapping[int, str]] = None,
        source: str = "matrices",
    ) -> "LinkCostModel":
        """Fit per-link (α, β) from the profiler's matrices.

        ``lat[s][d]`` is the measured small-probe round time [s]; ``bw[s][d]``
        the large-probe rate [GB/s].  Each off-diagonal pair with usable
        readings yields two (bytes, seconds) points; pairs with no usable
        readings fall back to their class coefficients.  Class means are
        recomputed from the fitted links so unprobed links of a probed class
        stay consistent with their peers.
        """
        lat = np.asarray(lat, dtype=float)
        bw = np.asarray(bw, dtype=float)
        world = lat.shape[0]
        if lat.shape != (world, world) or bw.shape != (world, world):
            raise ValueError(f"expected square world matrices, got {lat.shape}/{bw.shape}")
        model = cls(world, ips=ips, source=source)
        per_class: Dict[str, list] = {ICI: [], DCN: []}
        for s in range(world):
            for d in range(world):
                if s == d:
                    continue
                points = []
                if lat[s][d] > 0:
                    points.append((LATENCY_PROBE_BYTES, lat[s][d]))
                if bw[s][d] > 0:
                    points.append(
                        (BANDWIDTH_PROBE_BYTES, BANDWIDTH_PROBE_BYTES / (bw[s][d] * 1e9))
                    )
                if not points:
                    continue
                coeffs = fit_alpha_beta(points)
                model.links[(s, d)] = coeffs
                per_class[model.link_class_of(s, d)].append(coeffs)
        for cls_name, fitted in per_class.items():
            if fitted:
                model.classes[cls_name] = LinkCoeffs(
                    alpha=float(np.mean([c.alpha for c in fitted])),
                    beta=float(np.mean([c.beta for c in fitted])),
                )
        return model

    @classmethod
    def from_topo_profile_dir(
        cls,
        topology_dir: str,
        world: int,
        ips: Optional[Mapping[int, str]] = None,
    ) -> "LinkCostModel":
        """Fit from on-disk ``topo_profile_*`` CSV shards (the artifact chain
        the adaptive bootstrap writes, docs/OPERATIONS.md §2)."""
        from adapcc_tpu.topology.profile import gather_topo_profile

        lat, bw = gather_topo_profile(topology_dir, world)
        return cls.from_matrices(lat, bw, ips, source=f"profile:{topology_dir}")

    @classmethod
    def uniform(
        cls,
        world: int,
        alpha: float = DEFAULT_COEFFS[ICI][0],
        beta: float = DEFAULT_COEFFS[ICI][1],
        ips: Optional[Mapping[int, str]] = None,
        source: str = "synthetic",
    ) -> "LinkCostModel":
        """Every same-class link identical — the deterministic default the
        simulated bench uses when no calibration artifact exists."""
        return cls(
            world,
            classes={ICI: LinkCoeffs(alpha, beta)},
            ips=ips,
            source=source,
        )

    def __repr__(self) -> str:
        return (
            f"LinkCostModel(world={self.world}, links={len(self.links)}, "
            f"source={self.source!r})"
        )


#: HBM streaming rate used to price the staged pipeline's local DMAs when
#: no measured rate exists (~v5e class HBM; a deliberately round number,
#: replaced by any calibration the operator provides)
DEFAULT_HBM_BYTES_PER_S = 800e9


def bottleneck_ring_link(
    model: "LinkCostModel", world: Optional[int] = None
) -> Link:
    """The slowest (r → r+1) ring hop itself — the LINK that paces a
    lockstep ring.  The passive re-calibration (adapcc_tpu/adapt) assigns
    its α-β correction to this link's *class*: a collective that slowed
    down was paced here, so this is where the observed seconds localize."""
    w = model.world if world is None else int(world)
    if w < 2:
        return (0, 0)  # degenerate ring
    ring_links = [(r, (r + 1) % w) for r in range(w)]
    return max(ring_links, key=lambda l: model.coeffs(*l).time(1 << 20))


def bottleneck_ring_coeffs(
    model: "LinkCostModel", world: Optional[int] = None
) -> LinkCoeffs:
    """The slowest (r → r+1) ring hop's coefficients — a lockstep ring
    advances at its slowest link, so every ring-shaped pricing (the chunk
    sweep, the codec sweep, the tuner's prior) judges candidates there.
    One shared definition: the benches and the tuner can never disagree
    about which link paces the ring."""
    return model.coeffs(*bottleneck_ring_link(model, world))


# --------------------------------------------------------------------------- #
# lower-bound certification (SCCL, PAPERS.md): per-topology latency and
# bandwidth floors no schedule can beat, so sim-rank reports every candidate's
# optimality gap instead of "best of what we happened to generate"
# --------------------------------------------------------------------------- #

#: collectives the lower-bound terms cover (mirrors sim.replay.COLLECTIVES;
#: redefined here because replay imports this module)
_LB_COLLECTIVES = ("allreduce", "reduce", "broadcast")


def fastest_coeffs(model: "LinkCostModel") -> LinkCoeffs:
    """The per-term floor of the topology: the smallest α and the smallest β
    any link offers, taken independently — exactly what a lower bound needs
    (no schedule can start a message cheaper than the cheapest α, nor move a
    byte cheaper than the cheapest β).  Classes in use plus every per-link
    override are considered; DCN only when an ip table exists to route over
    it (a flat domain never pays DCN, so its coefficients must not loosen
    the floor... nor tighten it: mins only ever relax with more links)."""
    coeffs = [model.classes[ICI]]
    if model.ips is not None:
        coeffs.append(model.classes[DCN])
    coeffs.extend(model.links.values())
    return LinkCoeffs(
        alpha=min(c.alpha for c in coeffs),
        beta=min(c.beta for c in coeffs),
    )


def _check_lb_collective(collective: str) -> None:
    if collective not in _LB_COLLECTIVES:
        raise ValueError(
            f"unknown collective {collective!r}; expected one of "
            f"{_LB_COLLECTIVES}"
        )


def latency_lower_bound(
    model: "LinkCostModel",
    collective: str = "allreduce",
    world: Optional[int] = None,
) -> float:
    """α·⌈log₂ p⌉ — information dissemination doubles the informed set at
    best once per message generation, so every collective over ``p``
    participants needs at least ⌈log₂ p⌉ sequential message starts, each
    costing at least the cheapest link's α (SCCL's latency bound; Chan et
    al.'s postal-model argument).  ``world`` overrides the model's world
    for relay-masked collectives (p = active participants)."""
    _check_lb_collective(collective)
    p = model.world if world is None else int(world)
    if p <= 1:
        return 0.0
    return math.ceil(math.log2(p)) * fastest_coeffs(model).alpha


def bandwidth_lower_bound(
    model: "LinkCostModel",
    nbytes: float,
    collective: str = "allreduce",
    world: Optional[int] = None,
) -> float:
    """The byte floor over the busiest port: allreduce moves at least
    ``2(p−1)/p·n`` bytes through some rank's ports (reduce-scatter's
    (p−1)/p·n in plus allgather's (p−1)/p·n out — the classic duplex
    bound), reduce/broadcast at least ``(p−1)/p·n``; priced at the
    cheapest β any link offers so no topology assignment can undercut
    it."""
    _check_lb_collective(collective)
    p = model.world if world is None else int(world)
    n = float(nbytes)
    if p <= 1 or n <= 0:
        return 0.0
    factor = 2.0 * (p - 1) / p if collective == "allreduce" else (p - 1) / p
    return factor * n * fastest_coeffs(model).beta


def collective_lower_bound(
    model: "LinkCostModel",
    nbytes: float,
    collective: str = "allreduce",
    world: Optional[int] = None,
) -> float:
    """Latency + bandwidth floor — the certified denominator of every
    ``optimality_gap``.  Additive because the two terms bound disjoint
    costs (sequential message starts vs bytes on the busiest port), the
    standard α-β decomposition SCCL certifies against."""
    return latency_lower_bound(model, collective, world) + bandwidth_lower_bound(
        model, nbytes, collective, world
    )


def optimality_gap(seconds: float, lower_bound_s: float) -> float:
    """``seconds/LB − 1``: 0 means provably optimal under the α-β model,
    0.5 means 50% slower than any schedule could possibly be.  A
    degenerate bound (p ≤ 1 or zero payload → LB 0) reports gap 0 — there
    is nothing to certify against.  Never clamped: a negative gap would
    mean the bound is wrong, and tests pin that it never happens."""
    if lower_bound_s <= 0:
        return 0.0
    return seconds / lower_bound_s - 1.0


def contended_lower_bound(
    model: "LinkCostModel",
    nbytes: float,
    factors: Dict[str, float],
    collective: str = "allreduce",
    world: Optional[int] = None,
) -> float:
    """The certified floor **of the congestion window itself**:
    :func:`collective_lower_bound` evaluated on
    :meth:`LinkCostModel.contended` (β × factor on the shared class, per-
    link overrides included — :func:`fastest_coeffs` folds both).  During
    a congestion window the healthy-topology bound is unreachable — no
    schedule can move a byte cheaper than the *contended* cheapest link —
    so gapping a congested measurement against the healthy floor inflates
    every gap by the contention factor and drowns real regressions.
    Price the window against its own floor: ``optimality_gap(measured,
    contended_lower_bound(...))`` stays meaningful, and is never larger
    than the healthy-floor gap (β only grows)."""
    return collective_lower_bound(
        model.contended(factors), nbytes, collective, world
    )


# --------------------------------------------------------------------------- #
# contention pricing (adapcc_tpu/sim/congestion): background traffic on a
# shared link class — effective-bandwidth scaling, NOT latency degradation
# --------------------------------------------------------------------------- #

def contended_coeffs(coeffs: LinkCoeffs, factor: float) -> LinkCoeffs:
    """One link under background traffic: a neighbor taking
    ``(factor−1)/factor`` of the bandwidth share leaves ``β × factor``
    effective inverse bandwidth, while α — propagation, not queue depth in
    this model — is untouched.  The deliberate contrast to
    :meth:`LinkCoeffs.scaled` (degradation: both terms stretch) is the
    α/β signature the congestion-vs-degradation triage separates
    (docs/FABRIC.md §2)."""
    if factor < 1.0:
        raise ValueError(
            f"contention factor must be >= 1 (1 = no contention), got "
            f"{factor}"
        )
    return LinkCoeffs(coeffs.alpha, coeffs.beta * factor)


def congested_ring_allreduce_time(
    world: int,
    nbytes: float,
    coeffs: LinkCoeffs,
    factor: float,
    wire_dtype: str = "off",
) -> float:
    """The ring allreduce with its bottleneck hop contended by ``factor``
    — :func:`quantized_ring_allreduce_time` on
    :func:`contended_coeffs`.  ``factor=1`` is exactly the healthy price,
    so one term prices the whole congestion A/B."""
    return quantized_ring_allreduce_time(
        world, nbytes, contended_coeffs(coeffs, factor), wire_dtype
    )


def congested_two_level_allreduce_time(
    num_pods: int,
    pod_size: int,
    nbytes: float,
    ici: LinkCoeffs,
    dcn: LinkCoeffs,
    dcn_factor: float = 1.0,
    ici_factor: float = 1.0,
    pod_algo: str = "rs-ag",
    leader_algo: str = "tree",
) -> float:
    """The composed two-level allreduce under per-class contention —
    :func:`two_level_allreduce_time` with each level's class contended.
    This is the term the leader-level congestion re-solve prices: a
    contended DCN raises the β-heavy rs-ag leader ring faster than the
    α-heavy binomial tree, which is exactly the flip
    ``resolve_leader_level`` executes under a contended model."""
    return two_level_allreduce_time(
        num_pods,
        pod_size,
        nbytes,
        contended_coeffs(ici, ici_factor),
        contended_coeffs(dcn, dcn_factor),
        pod_algo=pod_algo,
        leader_algo=leader_algo,
    )


def staged_ring_allreduce_time(
    world: int,
    nbytes: float,
    coeffs: LinkCoeffs,
    chunk_bytes: float,
    hbm_bytes_per_s: float = DEFAULT_HBM_BYTES_PER_S,
) -> float:
    """Analytical latency of the HBM-streaming staged ring allreduce
    (``pallas_ring``'s hbm-stream path), pricing the pipeline fill/drain the
    fixed VMEM staging adds on top of the wire time.

    Per rank the payload splits into ``world`` chunks of ``nbytes/world``;
    each ring step moves one chunk as ``ceil(chunk / chunk_bytes)`` staging
    tiles.  One tile iteration is synchronous in the kernel:

    - **fill** — HBM work tile → VMEM send staging (1 tile over HBM),
    - wire — the RDMA hop (α + β·tile),
    - **drain** — accumulate read+write during reduce-scatter (2 tiles over
      HBM), or the adopt write during all-gather (1 tile),

    plus the one-time whole-payload seed copy (input → HBM work buffer).
    Small tiles pay the α fixed cost per tile, so predicted time falls as
    ``chunk_bytes`` grows and flattens once α is amortized — while the VMEM
    staging footprint (4 tiles) keeps growing linearly.  The sweep over
    ``chunk_bytes`` exposes that knee hardware-free: the right chunk is the
    smallest one on the flat part of the curve.  Degenerates to
    :func:`ring_allreduce_time`'s per-hop structure as ``chunk_bytes →
    chunk`` with the HBM terms added.
    """
    if world < 2:
        return 0.0
    if chunk_bytes <= 0:
        raise ValueError(f"chunk_bytes must be positive, got {chunk_bytes}")
    chunk = nbytes / world
    tiles = max(1, int(-(-chunk // chunk_bytes)))
    tile_bytes = chunk / tiles
    hbm = tile_bytes / hbm_bytes_per_s
    wire = coeffs.time(tile_bytes)
    rs_iter = hbm + wire + 2.0 * hbm       # fill + RDMA + accumulate in/out
    ag_iter = hbm + wire + hbm             # fill + RDMA + adopt write
    seed = nbytes / hbm_bytes_per_s        # input → HBM work buffer
    return seed + (world - 1) * tiles * (rs_iter + ag_iter)


# --------------------------------------------------------------------------- #
# wire-codec pricing (adapcc_tpu/quant): reduced wire bytes vs codec overhead
# --------------------------------------------------------------------------- #

#: quantization block the pricing assumes when none is given; mirrors
#: ``adapcc_tpu.quant.codec.DEFAULT_BLOCK_SIZE`` (drift pinned by a test —
#: the simulator must price the block geometry the data plane ships)
DEFAULT_QUANT_BLOCK = 256

#: throughput of one elementwise codec pass (quantize, or dequantize +
#: accumulate) over fp32 payload bytes.  A deliberately round number well
#: below HBM streaming rate: the ppermute-ring codec is XLA elementwise
#: work, not a fused kernel — replaced by any measured calibration.  Its
#: magnitude sets the break-even point: on a ~45 GB/s ICI link the saved
#: wire time does NOT pay for 4 codec passes, on a ~12.5 GB/s DCN link it
#: does — which is exactly the sim-rank flip the regression tests pin.
DEFAULT_CODEC_BYTES_PER_S = 100e9

#: candidate wire codecs the chooser prices, cheapest-risk first ("off"
#: leads so a predicted tie keeps the uncompressed plane)
WIRE_DTYPE_CANDIDATES = ("off", "bf16", "int8")


def wire_bytes_per_element(
    wire_dtype: str,
    block_size: int = DEFAULT_QUANT_BLOCK,
    elem_bytes: float = 4.0,
) -> float:
    """Wire bytes one payload element costs under a codec: fp32 passthrough,
    a bf16 cast, or int8 codes + the amortized per-block fp32 scale.  Must
    agree with the quant registry's own accounting (pinned by a test)."""
    if wire_dtype == "off":
        return float(elem_bytes)
    if wire_dtype == "bf16":
        return 2.0
    if wire_dtype == "int8":
        return 1.0 + 4.0 / block_size
    raise ValueError(
        f"unknown wire_dtype {wire_dtype!r}; "
        f"expected one of {WIRE_DTYPE_CANDIDATES}"
    )


def quantized_ring_allreduce_time(
    world: int,
    nbytes: float,
    coeffs: LinkCoeffs,
    wire_dtype: str = "int8",
    block_size: int = DEFAULT_QUANT_BLOCK,
    codec_bytes_per_s: float = DEFAULT_CODEC_BYTES_PER_S,
) -> float:
    """Analytical latency of the wire-codec ppermute ring allreduce
    (:func:`adapcc_tpu.quant.ring.wire_ring_allreduce_shard`), pricing
    reduced wire bytes against per-hop codec overhead.

    Per rank the payload splits into ``world`` chunks of ``nbytes/world``;
    each of the ``world - 1`` reduce-scatter hops pays **encode** (1 fp32
    pass) + the wire transfer of the *compressed* chunk + **decode &
    accumulate** (2 fp32 passes); each all-gather hop forwards encoded
    blocks verbatim and pays only the wire + the **decode write** (1 pass).
    ``wire_dtype="off"`` pays zero codec passes and degenerates to the plain
    chunked ring wire time — so one formula prices the whole A/B.
    """
    if world < 2:
        return 0.0
    chunk_bytes = nbytes / world
    elems = chunk_bytes / 4.0
    wire_chunk = elems * wire_bytes_per_element(wire_dtype, block_size)
    codec_pass = 0.0 if wire_dtype == "off" else chunk_bytes / codec_bytes_per_s
    rs_hop = 3.0 * codec_pass + coeffs.time(wire_chunk)
    ag_hop = 1.0 * codec_pass + coeffs.time(wire_chunk)
    return (world - 1) * (rs_hop + ag_hop)


def fused_quantized_ring_allreduce_time(
    world: int,
    nbytes: float,
    coeffs: LinkCoeffs,
    chunk_bytes: float,
    wire_dtype: str = "int8",
    block_size: int = DEFAULT_QUANT_BLOCK,
    hbm_bytes_per_s: float = DEFAULT_HBM_BYTES_PER_S,
    codec_bytes_per_s: float = DEFAULT_CODEC_BYTES_PER_S,
) -> float:
    """Analytical latency of the FUSED quantized streaming ring — the wire
    codec inside ``pallas_ring``'s staged kernels (EQuARX's shape on the
    credit-based pipeline) — pricing per-tile codec compute *overlapped*
    with the RDMA of the neighboring tile.

    Per rank the payload splits into ``world`` chunks, each moved as
    ``ceil(chunk / chunk_bytes)`` staging tiles per ring step.  One tile's
    pipeline stages:

    - **fill** — HBM→VMEM stage-in (1 tile over HBM) + encode (1 codec
      pass over the fp32 tile);
    - **wire** — the RDMA of the *compressed* tile: ``α + β · tile/4 ·
      wire_bytes_per_element`` (int8 includes the amortized fp32 scales);
    - **drain** — decode+accumulate during reduce-scatter (2 HBM tile
      moves + 2 codec passes), decode+adopt during all-gather (1 + 1).

    One ring step is the 3-stage pipeline makespan over its tiles:
    ``fill + (tiles − 1) · max(wire, fill, drain) + wire + drain`` — the
    codec hides behind the neighboring tile's RDMA in steady state (or
    vice versa), while each step still exposes one fill and one drain,
    each grown by exactly one codec stage vs the unfused staged model;
    steady-state wire bytes shrink by ``wire_bytes_per_element``.  At one
    tile per chunk this degenerates to the serial fill+wire+drain sum.
    Strictly below :func:`quantized_ring_allreduce_time`'s serial
    codec+wire sum for bandwidth-bound sizes — the regression the fused
    sweep pins.  ``wire_dtype="off"`` is rejected loudly: the unfused
    staged model (:func:`staged_ring_allreduce_time`) already prices that
    kernel.
    """
    if wire_dtype == "off":
        raise ValueError(
            "fused pricing needs a wire codec; the 'off' staged kernel is "
            "priced by staged_ring_allreduce_time"
        )
    if world < 2:
        return 0.0
    if chunk_bytes <= 0:
        raise ValueError(f"chunk_bytes must be positive, got {chunk_bytes}")
    chunk = nbytes / world
    tiles = max(1, int(-(-chunk // chunk_bytes)))
    tile_bytes = chunk / tiles
    wire_tile = (tile_bytes / 4.0) * wire_bytes_per_element(
        wire_dtype, block_size
    )
    hbm = tile_bytes / hbm_bytes_per_s
    codec = tile_bytes / codec_bytes_per_s
    wire = coeffs.time(wire_tile)
    rs_fill = hbm + codec              # stage-in + encode
    rs_drain = 2.0 * hbm + 2.0 * codec  # acc read/write + decode-accumulate
    ag_fill = hbm + codec              # stage-in + encode/requantize
    ag_drain = hbm + codec             # decode + adopt write
    rs_step = (
        rs_fill + (tiles - 1) * max(wire, rs_fill, rs_drain) + wire + rs_drain
    )
    ag_step = (
        ag_fill + (tiles - 1) * max(wire, ag_fill, ag_drain) + wire + ag_drain
    )
    seed = nbytes / hbm_bytes_per_s    # input → HBM work buffer
    return seed + (world - 1) * (rs_step + ag_step)


def choose_wire_dtype(
    world: int,
    nbytes: float,
    coeffs: LinkCoeffs,
    block_size: int = DEFAULT_QUANT_BLOCK,
    candidates: Sequence[str] = WIRE_DTYPE_CANDIDATES,
    codec_bytes_per_s: float = DEFAULT_CODEC_BYTES_PER_S,
) -> Tuple[str, Dict[str, float]]:
    """Pick the cheapest wire codec for a ring allreduce on ``coeffs`` —
    the cost-model term the sim-rank policy uses to set
    ``Strategy.wire_dtype``.  Returns ``(winner, {codec: seconds})``; ties
    break by candidate order, so "off" survives a prediction-identical
    alternative (no churn of the uncompressed plane)."""
    if not candidates:
        raise ValueError("need at least one wire_dtype candidate")
    times = {
        wd: quantized_ring_allreduce_time(
            world, nbytes, coeffs, wd, block_size, codec_bytes_per_s
        )
        for wd in candidates
    }
    winner = min(candidates, key=lambda wd: times[wd])
    return winner, times


# --------------------------------------------------------------------------- #
# overlapped-step pricing (adapcc_tpu/ddp/overlap): max(compute, comm) plus
# the exposed fill/drain fractions of the software pipeline
# --------------------------------------------------------------------------- #

#: overlap schedules the pricing understands; mirrors
#: ``adapcc_tpu.ddp.overlap.OVERLAP_MODES`` (drift pinned by a test)
OVERLAP_MODE_CANDIDATES = ("off", "bucket", "microbatch")


def _bucket_comm_times(
    world: int,
    grad_bytes: float,
    coeffs: LinkCoeffs,
    bucket_bytes: Optional[Sequence[float]],
    wire_dtype: str,
) -> Tuple[float, ...]:
    """Per-collective ring times for one gradient's sync: one entry per
    bucket (or a single whole-gradient entry when no plan is given), each
    priced as a bottleneck-link ring allreduce under the wire codec."""
    payloads = (
        tuple(float(b) for b in bucket_bytes)
        if bucket_bytes
        else (float(grad_bytes),)
    )
    if any(b < 0 for b in payloads):
        raise ValueError(f"bucket bytes must be >= 0, got {list(payloads)}")
    return tuple(
        quantized_ring_allreduce_time(world, b, coeffs, wire_dtype)
        for b in payloads
    )


def _serial_pipeline(
    ready: Sequence[float], costs: Sequence[float]
) -> float:
    """Makespan of transfers released at ``ready[i]`` onto one serial wire
    (single-port: a rank drives one collective at a time, the SCCL/TACCL
    assumption the replay shares)."""
    t = 0.0
    for r, c in zip(ready, costs):
        t = max(t, r) + c
    return t


def overlapped_step_time(
    world: int,
    grad_bytes: float,
    coeffs: LinkCoeffs,
    compute_s: float,
    accum: int = 1,
    overlap: str = "off",
    bucket_bytes: Optional[Sequence[float]] = None,
    wire_dtype: str = "off",
) -> Dict[str, float]:
    """Analytical step time under one overlap schedule (docs/OVERLAP.md):
    ``max(compute, comm)`` steady state plus the exposed fill/drain
    fractions, on the bottleneck ring link
    (:func:`bottleneck_ring_coeffs` — one pacing rule with every other
    ring-shaped pricing and the tuner's prior).

    - ``"off"``: one sync of the accumulated gradient after all compute —
      every comm second exposed (the baseline this PR removes).
    - ``"bucket"``: the accumulated gradient's buckets release uniformly
      across the *final* microbatch's backward (earlier microbatches only
      produce partial sums) and drain as independent rolling collectives;
      exposed time collapses toward the last bucket's drain as compute
      grows.
    - ``"microbatch"``: every microbatch's full-size delta syncs behind the
      next microbatch's compute; total wire volume is ``accum×`` the
      gradient, with only the final delta's drain necessarily exposed —
      the bytes-for-overlap trade the measured tuner arbitrates.

    Returns ``{step_time_s, compute_s, comm_s, exposed_comm_s, fill_s,
    drain_s}``; ``comm_s`` is total wire-busy time, ``exposed_comm_s`` is
    ``step_time_s - compute_s`` (never negative).  Deterministic — the
    overlap sweep's byte-stability rides on it.
    """
    if world < 1:
        raise ValueError(f"world must be >= 1, got {world}")
    if accum < 1:
        raise ValueError(f"accum must be >= 1, got {accum}")
    if compute_s < 0:
        raise ValueError(f"compute_s must be >= 0, got {compute_s}")
    if overlap not in OVERLAP_MODE_CANDIDATES:
        raise ValueError(
            f"overlap={overlap!r}: expected one of {OVERLAP_MODE_CANDIDATES}"
        )
    sync = _bucket_comm_times(world, grad_bytes, coeffs, bucket_bytes, wire_dtype)
    sync_total = sum(sync)
    compute_s = float(compute_s)
    if overlap == "off":
        comm = sync_total
        step = compute_s + comm
        fill, drain = 0.0, comm
    elif overlap == "bucket":
        comm = sync_total
        n = len(sync)
        # buckets finalize only during the last microbatch's backward: the
        # overlap window is that microbatch's compute slice
        window = compute_s / accum
        start = compute_s - window
        ready = [start + window * (i + 1) / n for i in range(n)]
        step = max(compute_s, _serial_pipeline(ready, sync))
        fill, drain = window / n, sync[-1]
    else:  # microbatch
        comm = sync_total * accum
        c = compute_s / accum
        # microbatch i's buckets release at the end of its compute and
        # overlap microbatch i+1 .. accum-1; the last delta only drains
        ready = [c * (i + 1) for i in range(accum) for _ in sync]
        costs = list(sync) * accum
        step = max(compute_s, _serial_pipeline(ready, costs))
        fill, drain = c, sync_total
    return {
        "step_time_s": step,
        "compute_s": compute_s,
        "comm_s": comm,
        "exposed_comm_s": max(0.0, step - compute_s),
        "fill_s": fill,
        "drain_s": drain,
    }


def exposed_comm_floor_s(
    world: int,
    grad_bytes: float,
    coeffs: LinkCoeffs,
    overlap: str = "off",
    bucket_bytes: Optional[Sequence[float]] = None,
    wire_dtype: str = "off",
) -> float:
    """The irreducible exposed communication of one step under a schedule —
    the ``compute → ∞`` limit of :func:`overlapped_step_time` (everything
    the pipeline could hide is hidden; only the drain remains).  This is
    the compute-independent number the dispatch trace records as
    ``exposed_comm_s`` next to the bucket plan: ``"off"`` exposes the whole
    sync, ``"bucket"`` only the last bucket's collective, ``"microbatch"``
    the final delta's full sync (its deltas are gradient-sized)."""
    if overlap not in OVERLAP_MODE_CANDIDATES:
        raise ValueError(
            f"overlap={overlap!r}: expected one of {OVERLAP_MODE_CANDIDATES}"
        )
    sync = _bucket_comm_times(world, grad_bytes, coeffs, bucket_bytes, wire_dtype)
    if overlap == "off":
        return sum(sync)
    if overlap == "bucket":
        return sync[-1]
    return sum(sync)  # microbatch: the drain is one delta's full sync


# --------------------------------------------------------------------------- #
# failover pricing (adapcc_tpu/elastic): detection latency + plan-swap stall
# + degraded-ring steady state, the three terms a world shrink costs
# --------------------------------------------------------------------------- #

#: dispatch-time plan swap when the standby cache holds the compiled
#: program: one cache-key switch + re-dispatch (a deliberately round
#: number well above a dict lookup and below any compile; replaced by any
#: measured calibration)
DEFAULT_PLAN_SWAP_DISPATCH_S = 250e-6

#: cold plan swap when no standby program exists: tracing + XLA compile of
#: the degraded schedule (a round number of the right order for a pod-scale
#: shard_map program; the standby cache exists to never pay it mid-run)
DEFAULT_COLD_COMPILE_S = 2.0


def detection_latency_s(
    heartbeat_timeout_s: float, step_time_s: float = 0.0
) -> float:
    """Expected time from a rank dying to the coordinator knowing: half a
    step (the death lands uniformly inside one) plus the heartbeat
    timeout the controller barrier waits out before surfacing status 0."""
    if heartbeat_timeout_s < 0 or step_time_s < 0:
        raise ValueError("heartbeat timeout / step time must be >= 0")
    return 0.5 * step_time_s + heartbeat_timeout_s


def supervised_detection_latency_s(
    heartbeat_period_s: float,
    heartbeat_timeout_s: float,
    grace: int,
    sweep_period_s: float = 0.0,
) -> float:
    """Expected time from a rank dying to the supervisor daemon
    *confirming* it dead (docs/SUPERVISOR.md): half a heartbeat period
    (the death lands uniformly between two beats), the suspicion timeout,
    the ``grace`` confirmation window (``grace`` further missed periods
    — the price of the false-positive guard), and half a supervisor
    sweep period to observe the transition.

    Against :func:`detection_latency_s` (the in-loop controller barrier),
    this is the out-of-band curve the chaos sweep prices: detection
    latency is linear in both ``period`` and ``grace``, so the sweep's
    rows make the trade — faster detection vs more false positives on a
    jittery control plane — a printed number instead of folklore.
    """
    if heartbeat_period_s <= 0 or heartbeat_timeout_s < 0 or sweep_period_s < 0:
        raise ValueError(
            "heartbeat period must be > 0, timeout/sweep period >= 0"
        )
    if grace < 1:
        raise ValueError(f"grace must be >= 1, got {grace}")
    return (
        0.5 * heartbeat_period_s
        + heartbeat_timeout_s
        + grace * heartbeat_period_s
        + 0.5 * sweep_period_s
    )


def plan_swap_stall_s(
    standby_cached: bool,
    dispatch_s: float = DEFAULT_PLAN_SWAP_DISPATCH_S,
    compile_s: float = DEFAULT_COLD_COMPILE_S,
) -> float:
    """The stall the failover step pays to start executing the degraded
    plan: a dispatch-time cache-key switch when the standby cache was
    warmed at setup, a cold trace+compile when it was not — the gap the
    standby plan cache exists to close."""
    return dispatch_s if standby_cached else dispatch_s + compile_s


def failover_cost(
    world: int,
    nbytes: float,
    coeffs: LinkCoeffs,
    n_down: int = 1,
    slowdown: Optional[float] = None,
    heartbeat_timeout_s: float = 1.0,
    step_time_s: float = 0.0,
    standby_cached: bool = True,
    wire_dtype: str = "off",
) -> Dict[str, float]:
    """Price one fault end to end: detection → swap → degraded steady
    state (docs/ELASTIC.md).

    - ``healthy_s`` — the full-world ring collective;
    - ``undetected_s`` — the collective while the fault is live but NOT
      yet handled: a slow rank (``slowdown``) stretches every hop it
      touches; a dead rank would hang forever, priced as the heartbeat
      timeout per step (the "instead of hanging" baseline);
    - ``degraded_s`` — the collective on the re-planned alive subset
      (``world - n_down`` ring; demoted relays forward but don't pace);
    - ``detection_s`` / ``swap_s`` — one-time costs of the transition;
    - ``degraded_ratio`` — degraded / healthy steady-state slowdown;
    - ``failover_total_s`` — detection + swap: the one-time price of the
      transition, amortized over every post-swap step.

    Deterministic, analytic — the fault sweep's rows ride on it.
    """
    if world < 2:
        raise ValueError(f"failover pricing needs world >= 2, got {world}")
    if not 0 < n_down < world:
        raise ValueError(f"n_down must be in (0, {world}), got {n_down}")
    healthy = quantized_ring_allreduce_time(world, nbytes, coeffs, wire_dtype)
    if slowdown is not None:
        undetected = quantized_ring_allreduce_time(
            world, nbytes, coeffs.scaled(slowdown), wire_dtype
        )
    else:
        # a dead rank's ring never completes: until detection, every step
        # burns the full heartbeat timeout instead of hanging forever
        undetected = heartbeat_timeout_s
    degraded = quantized_ring_allreduce_time(
        world - n_down, nbytes, coeffs, wire_dtype
    )
    detection = detection_latency_s(heartbeat_timeout_s, step_time_s)
    swap = plan_swap_stall_s(standby_cached)
    return {
        "healthy_s": healthy,
        "undetected_s": undetected,
        "degraded_s": degraded,
        "degraded_ratio": degraded / healthy if healthy > 0 else 1.0,
        "detection_s": detection,
        "swap_s": swap,
        "failover_total_s": detection + swap,
    }


# --------------------------------------------------------------------------- #
# online re-adaptation pricing (adapcc_tpu/adapt): the stall a strategy
# change costs, hot-swap vs full rebuild — the A/B drift_loop measures
# --------------------------------------------------------------------------- #

#: re-synthesis walltime folded into a full rebuild: candidate emission +
#: ranking on the host (a deliberately round number of the right order for
#: a sub-pod world; replaced by any measured calibration — world=64 MILP
#: synthesis measures 0.09 s, ParTrees less)
DEFAULT_RESYNTHESIS_S = 0.1


def full_rebuild_stall_s(
    world: int,
    coeffs: LinkCoeffs,
    compile_s: float = DEFAULT_COLD_COMPILE_S,
    synthesis_s: float = DEFAULT_RESYNTHESIS_S,
) -> float:
    """The stall one ``reconstruct_topology`` cycle costs: active probe
    traffic (every directed pair pays the profiler's two probe rounds),
    re-synthesis, and the cold trace+compile of the new schedule — the
    price the closed adaptation loop (docs/ADAPT.md) exists to NOT pay.
    Strictly above :func:`plan_swap_stall_s`'s cached swap by construction
    (the compile term alone dwarfs a dispatch-time cache-key switch)."""
    world = int(world)
    if world < 1:
        raise ValueError(f"world must be >= 1, got {world}")
    probes = world * max(0, world - 1) * (
        coeffs.time(LATENCY_PROBE_BYTES) + coeffs.time(BANDWIDTH_PROBE_BYTES)
    )
    return probes + synthesis_s + compile_s


def adaptation_cost(
    world: int,
    nbytes: float,
    coeffs: LinkCoeffs,
    stale_steady_s: float,
    adapted_steady_s: float,
    standby_cached: bool = True,
    compile_s: float = DEFAULT_COLD_COMPILE_S,
    synthesis_s: float = DEFAULT_RESYNTHESIS_S,
) -> Dict[str, float]:
    """Price one drift incident's re-adaptation decision (docs/ADAPT.md):
    keep running the stale strategy, hot-swap to the re-ranked one through
    the standby cache, or pay a full rebuild.

    ``stale_steady_s`` / ``adapted_steady_s`` are the caller's per-step
    predictions under the *corrected* (degraded) costs — the incumbent vs
    the re-ranked winner.  Returns the two one-time stalls plus the
    per-step gain and each arm's break-even step count (``inf`` when
    adaptation predicts no gain — then neither stall is worth paying).
    Deterministic, analytic — the adapt-sweep rows ride on it.
    """
    if stale_steady_s < 0 or adapted_steady_s < 0:
        raise ValueError("steady-state predictions must be >= 0")
    hot = plan_swap_stall_s(standby_cached)
    full = full_rebuild_stall_s(world, coeffs, compile_s, synthesis_s)
    gain = stale_steady_s - adapted_steady_s
    return {
        "stale_steady_s": float(stale_steady_s),
        "adapted_steady_s": float(adapted_steady_s),
        "gain_per_step_s": gain,
        "hot_swap_stall_s": hot,
        "full_rebuild_stall_s": full,
        "hot_swap_break_even_steps": hot / gain if gain > 0 else float("inf"),
        "full_rebuild_break_even_steps": (
            full / gain if gain > 0 else float("inf")
        ),
    }


# --------------------------------------------------------------------------- #
# latency-optimal algorithm pricing (adapcc_tpu/comm/latency): recursive
# doubling + binomial trees vs the ring, on the physical ring embedding
# --------------------------------------------------------------------------- #

#: algorithm candidates the size-adaptive selector prices, safest first
#: ("ring" leads so a predicted tie keeps the bandwidth-optimal plane);
#: mirrors ``adapcc_tpu.comm.latency.COLL_ALGOS`` minus "auto" (the
#: selector mode) and "ir" (priced per-program by
#: :func:`schedule_program_time`, not by a sized closed form) — drift
#: pinned by a test
COLL_ALGO_CANDIDATES = ("ring", "rd", "tree")


def _ring_hops(distance: int, world: int) -> int:
    """Physical ICI hops a logical exchange at XOR/tree distance ``d``
    rides on the ring/torus embedding (wraparound both ways).  This is the
    term that makes the ring win large payloads: recursive doubling's
    round-``k`` messages serialize over ``min(2^k, p−2^k)`` links, so its
    bandwidth cost grows with ``p`` while its fixed cost stays ``log2 p``."""
    d = int(distance) % int(world)
    return min(d, world - d)


def recursive_doubling_allreduce_time(
    world: int, nbytes: float, coeffs: LinkCoeffs
) -> float:
    """Analytical latency of the recursive-halving reduce-scatter +
    recursive-doubling all-gather allreduce
    (:func:`adapcc_tpu.comm.latency.rd_allreduce_shard`) on the ring
    embedding.

    Each of the ``2·log2(p)`` rounds pays one α plus the wire time of its
    message *serialized over the physical hop distance*: the halving phase
    sends ``n/2^(k+1)`` across ``min(p/2^(k+1)·2^k…)`` — concretely,
    distance ``p/2^(k+1)`` — links, the doubling phase mirrors it.  Summed:

        t(n) = 2·log2(p)·α + 2·β·n·Σ_k hops(d_k)/2^(k+1)

    — fixed cost ``2·log2(p)·α`` (vs the ring's ``2·(p−1)·α``), bandwidth
    slope ≈ ``(2p/3)·β`` (vs the ring's ``2·(p−1)/p·β``), which is exactly
    the small-wins / large-loses shape
    :func:`allreduce_crossover_bytes` solves.

    Non-power-of-two worlds price the textbook fold-in: the remainder
    ranks pre-reduce into (and re-receive from) a power-of-two core over
    one neighbor hop each way — two extra full-payload transfers — then
    the core runs the power-of-two schedule.  (The data plane itself
    rejects such worlds; this term exists so the selector can still rank
    them.)  ``world < 2`` is free.
    """
    # recursive-halving reduce-scatter (distances p/2 … 1, messages
    # n/2 … n/p) + the all-gather mirroring the same (distance, size)
    # pairs back up — one _rd_half_time term per half, fold-in included
    return 2.0 * _rd_half_time(world, nbytes, coeffs)


def _rd_half_time(world: int, nbytes: float, coeffs: LinkCoeffs) -> float:
    """One rd half-schedule on the ring embedding: the recursive-HALVING
    reduce-scatter's rounds (distances p/2 … 1, messages n/2 … n/p) — which
    the recursive-doubling all-gather mirrors exactly, so one term prices
    both halves.  Non-power-of-two worlds price one full-payload fold-in
    transfer (the data plane rejects them; the term exists so selectors can
    still rank)."""
    world = int(world)
    if world < 2:
        return 0.0
    total = 0.0
    p = 1 << (world.bit_length() - 1)
    if p != world:
        total += coeffs.time(nbytes)
    d = p // 2
    msg = float(nbytes) / 2.0
    while d >= 1:
        total += coeffs.alpha + coeffs.beta * _ring_hops(d, p) * msg
        d //= 2
        msg /= 2.0
    return total


def recursive_halving_reduce_scatter_time(
    world: int, nbytes: float, coeffs: LinkCoeffs
) -> float:
    """Analytical latency of the recursive-halving reduce-scatter
    (:func:`adapcc_tpu.comm.latency.rd_reduce_scatter_shard`): the RS half
    of :func:`recursive_doubling_allreduce_time` — ``log2(p)·α`` fixed cost
    at the ring's ``(p−1)/p·n`` wire volume, hop-serialized on the ring
    embedding.  ``nbytes`` is the full (pre-scatter) payload."""
    return _rd_half_time(world, nbytes, coeffs)


def recursive_doubling_all_gather_time(
    world: int, nbytes: float, coeffs: LinkCoeffs
) -> float:
    """Analytical latency of the recursive-doubling all-gather
    (:func:`adapcc_tpu.comm.latency.rd_all_gather_shard`): the AG mirror of
    the halving schedule — identical (distance, size) pairs, so identical
    cost.  ``nbytes`` is the full (post-gather) payload."""
    return _rd_half_time(world, nbytes, coeffs)


def binomial_tree_time(
    world: int, nbytes: float, coeffs: LinkCoeffs
) -> float:
    """Analytical latency of ONE single-shot binomial-tree phase — a
    broadcast from (or reduce to) a root
    (:func:`adapcc_tpu.comm.latency.binomial_broadcast_shard` /
    ``binomial_reduce_shard``): ``ceil(log2 p)`` rounds, each moving the
    full payload across its round's hop distance on the ring embedding:

        t(n) = ceil(log2 p)·α + β·n·Σ_k hops(2^k)

    A tree *allreduce* is two phases (reduce + broadcast): price it as
    ``2 × binomial_tree_time`` — which is what
    :func:`choose_allreduce_algo` does for the ``"tree"`` arm.  Any world
    size; ``world < 2`` is free.
    """
    world = int(world)
    if world < 2:
        return 0.0
    total = 0.0
    d = 1
    while d < world:
        total += coeffs.alpha + coeffs.beta * _ring_hops(d, world) * float(nbytes)
        d *= 2
    return total


def all_to_all_time(
    world: int, nbytes: float, coeffs: LinkCoeffs
) -> float:
    """Analytical latency of a flat all-to-all on the ring embedding — the
    tuner prior for the new ``all_to_all`` primitive (the MoE dispatch/
    combine shuffle).  ``nbytes`` is one rank's total send volume (its
    ``[world, block]`` row).

    Priced as the linear-shift schedule: ``world − 1`` rounds, round ``k``
    shipping one ``n/world`` block to the rank at logical distance ``k``
    (``min(k, p−k)`` physical hops):

        t(n) = (p−1)·α + β·(n/p)·Σ_k hops(k)  ≈  (p−1)·α + β·n·p/4

    — the ``p/4`` slope is the torus bisection showing up in the price,
    which is why expert traffic is worth tuning at all.  ``world < 2`` is
    free.
    """
    world = int(world)
    if world < 2:
        return 0.0
    block = float(nbytes) / world
    total = 0.0
    for k in range(1, world):
        total += coeffs.alpha + coeffs.beta * _ring_hops(k, world) * block
    return total


def allreduce_crossover_bytes(world: int, coeffs: LinkCoeffs) -> float:
    """The payload size where the ring allreduce catches up with recursive
    doubling: below it ``rd`` is strictly cheaper (the ``log2 p`` fixed
    cost wins), above it strictly more expensive (the hop-serialized
    bandwidth slope loses).  Both models are affine in ``n``, so the
    break-even is exact:

        n* = (ring_α_term − rd_α_term) / (rd_slope − ring_slope)

    Returns ``0.0`` when rd is never cheaper (degenerate coefficients or
    ``world < 2``) and ``inf`` when it always is (β = 0: a latency-only
    fabric).  This is the sized decision ``ADAPCC_COLL_ALGO=auto``
    executes and the ``make latency-bench`` rows stamp per row.
    """
    world = int(world)
    if world < 2:
        return 0.0

    def ring(n: float) -> float:
        return quantized_ring_allreduce_time(world, n, coeffs, "off")

    def rd(n: float) -> float:
        return recursive_doubling_allreduce_time(world, n, coeffs)

    probe = float(1 << 20)
    ring_a, rd_a = ring(0.0), rd(0.0)
    ring_slope = (ring(probe) - ring_a) / probe
    rd_slope = (rd(probe) - rd_a) / probe
    if rd_a >= ring_a:
        return 0.0  # no latency advantage: rd never wins
    if rd_slope <= ring_slope:
        return float("inf")  # no bandwidth penalty: rd always wins
    return (ring_a - rd_a) / (rd_slope - ring_slope)


def choose_allreduce_algo(
    world: int,
    nbytes: float,
    coeffs: LinkCoeffs,
    candidates: Sequence[str] = COLL_ALGO_CANDIDATES,
) -> Tuple[str, Dict[str, float]]:
    """Pick the cheapest allreduce algorithm for one payload size — the
    cost-model half of the size-adaptive selector (the measured tuner is
    the other half).  Returns ``(winner, {algo: seconds})``; ties break by
    candidate order, so "ring" survives a prediction-identical
    alternative (no churn of the bandwidth plane)."""
    if not candidates:
        raise ValueError("need at least one collective-algorithm candidate")
    pricing = {
        "ring": lambda: quantized_ring_allreduce_time(
            world, nbytes, coeffs, "off"
        ),
        "rd": lambda: recursive_doubling_allreduce_time(world, nbytes, coeffs),
        "tree": lambda: 2.0 * binomial_tree_time(world, nbytes, coeffs),
    }
    unknown = [c for c in candidates if c not in pricing]
    if unknown:
        raise ValueError(
            f"unknown algorithm(s) {unknown}; expected a subset of "
            f"{COLL_ALGO_CANDIDATES}"
        )
    times = {c: pricing[c]() for c in candidates}
    winner = min(candidates, key=lambda c: times[c])
    return winner, times


# --------------------------------------------------------------------------- #
# two-level (DCN × ICI) composition pricing (adapcc_tpu/strategy/hierarchy):
# RS-within-pod → AR-across-leaders → AG-within-pod vs the flat ring
# --------------------------------------------------------------------------- #

#: the composed plan's per-level schedule vocabularies; mirror
#: ``adapcc_tpu.strategy.hierarchy.POD_ALGOS`` / ``LEADER_ALGOS`` (drift
#: pinned by a test — the pricing must speak the synthesizer's vocabulary)
TWO_LEVEL_POD_ALGOS = ("rs-ag", "replicate")
TWO_LEVEL_LEADER_ALGOS = ("tree", "rs-ag")


def two_level_leader_time(
    num_pods: int, nbytes: float, dcn: LinkCoeffs, algo: str = "tree"
) -> float:
    """One cross-pod-leader allreduce of ``nbytes`` per leader, on the DCN
    class coefficients — the DCN-level solve's candidate pricing
    (:func:`adapcc_tpu.strategy.hierarchy.solve_leader_level`).

    - ``"tree"`` — binomial over the leaders: ``2·ceil(log2 P)`` rounds,
      each moving the full payload (reduce up + broadcast down).  DCN is a
      switched fabric, so unlike :func:`binomial_tree_time` there is no
      ring-embedding hop serialization.
    - ``"rs-ag"`` — segmented leader ring (reduce-scatter + all-gather):
      ``2·(P−1)`` rounds of ``nbytes/P`` each — the bandwidth-optimal
      schedule, paying ``2(P−1)`` α instead of ``2·log2 P``.

    The α/β trade is the point: a latency-degraded DCN (congestion raising
    α) flips the winner to "tree", which is exactly the leader-level
    re-solve the drift localization executes (docs/HIERARCHY.md §5).
    """
    P = int(num_pods)
    if P < 2:
        return 0.0
    if algo == "tree":
        rounds = (P - 1).bit_length()  # ceil(log2 P)
        return 2.0 * rounds * dcn.time(nbytes)
    if algo == "rs-ag":
        return 2.0 * (P - 1) * (dcn.alpha + dcn.beta * float(nbytes) / P)
    raise ValueError(
        f"unknown leader algo {algo!r}; expected one of "
        f"{TWO_LEVEL_LEADER_ALGOS}"
    )


def two_level_allreduce_time(
    num_pods: int,
    pod_size: int,
    nbytes: float,
    ici: LinkCoeffs,
    dcn: LinkCoeffs,
    pod_algo: str = "rs-ag",
    leader_algo: str = "tree",
) -> float:
    """Analytical latency of the composed two-level allreduce
    (docs/HIERARCHY.md): the ICI phases plus the leader-level allreduce of
    whatever payload the pod algorithm leaves on DCN.

    - ``pod_algo="rs-ag"`` — reduce-scatter within the pod ((I−1) ring
      hops of ``n/I``), leader level carries ``n/I``, all-gather within
      the pod after ((I−1) hops of ``n/I``): DCN traffic shrinks by the
      pod size — the wire-time half of the hierarchy win.
    - ``pod_algo="replicate"`` — the fixed schedule ``comm/two_level.py``
      shipped before the sketch existed: slice-local psum (priced as the
      same bandwidth-optimal 2(I−1)·t(n/I) ICI work), but the leader
      level carries the FULL payload and the broadcast down the leader
      tree lands on every lane (no AG phase).

    Strictly below the flat ring (``quantized_ring_allreduce_time`` on the
    DCN bottleneck — a flat lockstep ring advances at its slowest link) on
    every multi-pod topology where DCN is the slow class; the regression
    tests pin the ≥4-pod gap and :func:`two_level_crossover_pods` records
    where it opens.
    """
    P, I = int(num_pods), int(pod_size)
    if P < 1 or I < 1:
        raise ValueError(f"need num_pods/pod_size >= 1, got {P}x{I}")
    if P * I < 2:
        return 0.0
    if pod_algo not in TWO_LEVEL_POD_ALGOS:
        raise ValueError(
            f"unknown pod algo {pod_algo!r}; expected one of "
            f"{TWO_LEVEL_POD_ALGOS}"
        )
    n = float(nbytes)
    ici_phases = 2.0 * (I - 1) * ici.time(n / I) if I > 1 else 0.0
    if pod_algo == "rs-ag":
        leader_payload = n / I
    else:
        leader_payload = n
    return ici_phases + two_level_leader_time(
        P, leader_payload, dcn, leader_algo
    )


def choose_two_level(
    num_pods: int,
    pod_size: int,
    nbytes: float,
    ici: LinkCoeffs,
    dcn: LinkCoeffs,
) -> Tuple[str, Dict[str, float]]:
    """Two-level vs flat for one topology and payload — the pod-count-aware
    decision the hierarchical sweep stamps per row.  Returns ``(winner,
    {"two_level": s, "flat": s})``: the two-level arm is the best composed
    configuration (both pod algorithms × their best leader schedule), the
    flat arm is the lockstep flat ring paced by the DCN bottleneck (the
    schedule a hierarchy-blind synthesizer would run).  ``num_pods < 2``
    is flat by construction (a single pod has no DCN level; the flat arm
    prices on ICI there)."""
    P, I = int(num_pods), int(pod_size)
    if P < 2:
        flat = quantized_ring_allreduce_time(max(P * I, 1), nbytes, ici, "off")
        return "flat", {"two_level": flat, "flat": flat}
    two = min(
        two_level_allreduce_time(
            P, I, nbytes, ici, dcn, pod_algo=pa, leader_algo=la
        )
        for pa in TWO_LEVEL_POD_ALGOS
        for la in TWO_LEVEL_LEADER_ALGOS
    )
    flat = quantized_ring_allreduce_time(P * I, nbytes, dcn, "off")
    times = {"two_level": two, "flat": flat}
    # ties keep flat: no hierarchy churn for a prediction-identical plan
    return ("two_level" if two < flat else "flat"), times


def two_level_crossover_pods(
    pod_size: int,
    nbytes: float,
    ici: LinkCoeffs,
    dcn: LinkCoeffs,
    max_pods: int = 4096,
) -> Optional[int]:
    """The smallest pod count at which the composed two-level plan beats
    the flat ring for this payload (None when it never does within
    ``max_pods``) — the pod-count-aware crossover the hierarchical sweep
    records.  On healthy ICI-fast/DCN-slow coefficients this is 2: the
    flat ring pays ``2(P·I−1)`` DCN-paced rounds the moment one pod
    boundary exists."""
    P = 2
    while P <= max_pods:
        winner, _ = choose_two_level(P, pod_size, nbytes, ici, dcn)
        if winner == "two_level":
            return P
        P *= 2
    return None


def ring_allreduce_time(
    world: int, nbytes: float, coeffs: LinkCoeffs, chunks: int = 1
) -> float:
    """Analytical latency of the chain-tree ("ring"-schedule) allreduce.

    ``Strategy.ring`` lowers to a depth-(w−1) reduce chain plus a
    depth-(w−1) broadcast chain; with ``chunks`` pipelined chunks of
    ``nbytes / chunks`` each, the steady-state makespan is

        (2·(w−1) + chunks − 1) · (α + β·nbytes/chunks)

    — the oracle the simulator's event replay is tested against.  Exact at
    ``chunks=1``; for ``chunks>1`` it is the multi-port lower bound — the
    replay's single-port model (a rank receives one transfer at a time, the
    SCCL/TACCL assumption) adds a bounded constant of port-conflict hops
    where the reduce tail overlaps the broadcast head.
    """
    if world < 2:
        return 0.0
    per_hop = coeffs.time(nbytes / chunks)
    return (2 * (world - 1) + chunks - 1) * per_hop


def schedule_program_time(
    program, nbytes: float, coeffs: LinkCoeffs, per_dispatch_s: float = 0.0
) -> float:
    """Analytical latency of a ``compiler.ScheduleProgram``.

    The IR's rounds are barriers, so the program's makespan is the sum over
    rounds of the slowest link in that round.  Within a round, sends on the
    same directed (src, dst) link serialize — their bytes coalesce onto one
    α + β·bytes transfer — while distinct links run concurrently
    (full-duplex, fully-connected: the same abstraction
    :func:`ring_allreduce_time` and the recursive-doubling/tree terms price
    against, so cross-plane rankings compare like with like).  Each send
    carries ``span`` chunks of ``nbytes / program.chunks`` each, so an
    optimized program and its naive source price IDENTICALLY by default —
    same bytes on the same links — which is the invariant that lets one
    pricing serve both.

    ``per_dispatch_s`` opts into the launch-overhead term the default
    model coalesces away: each collective dispatch the lowering would
    issue (``compiler.lower.round_dispatch_counts`` — one ppermute per
    color per wire array) costs this many seconds on top of the transfer
    time.  With it set, the coalesced program's strictly-lower dispatch
    count becomes a strictly-lower price — the gap ``make compiler-bench``
    reports.  The default 0.0 keeps every pre-existing pin byte-exact.

    For the builders this reproduces the closed forms exactly: the
    segmented ring prices at ``2(w−1)·(α + β·n/w)``, and the bidirectional
    pipelined program at ``2(w−1)·(α + β·n/(2w))`` — half the β term, the
    novel schedule's whole point (docs/COMPILER.md §5).
    """
    if program.world < 2:
        return 0.0
    seg = float(nbytes) / max(1, program.chunks)
    total = 0.0
    for round_steps in program.rounds:
        link_bytes: Dict[Tuple[int, int], float] = {}
        for step in round_steps:
            if step.kind == "send":
                link = (step.rank, step.peer)
                link_bytes[link] = link_bytes.get(link, 0.0) + seg * step.span
        if link_bytes:
            total += max(coeffs.time(b) for b in link_bytes.values())
    if per_dispatch_s:
        from adapcc_tpu.compiler.lower import round_dispatch_counts

        total += per_dispatch_s * float(sum(round_dispatch_counts(program)))
    return total


# --------------------------------------------------------------------------- #
# pipeline-parallel pricing (adapcc_tpu/pipe): bubble, step time, stash
# --------------------------------------------------------------------------- #


def pipeline_bubble_fraction(stages: int, microbatches: int) -> float:
    """Idle fraction of a GPipe/1F1B pipeline step: ``(s−1)/(m+s−1)``.

    Both schedules run the same ``2·(m+s−1)`` ticks over ``2·m`` useful
    tasks per stage, so the bubble is schedule-independent — the schedules
    differ in *memory* (:func:`pipeline_stash_bytes`), not in ticks.
    """
    if stages < 1:
        raise ValueError(f"stages must be >= 1, got {stages}")
    if microbatches < 1:
        raise ValueError(f"microbatches must be >= 1, got {microbatches}")
    return (stages - 1) / (microbatches + stages - 1)


def pipeline_step_time(
    stages: int,
    microbatches: int,
    fwd_time_s: float,
    hop_bytes: float,
    coeffs: LinkCoeffs,
    bwd_ratio: float = 2.0,
) -> float:
    """Analytical latency of one pipelined forward/backward step.

    ``2·(m+s−1)`` ticks (fill + steady + drain, forward and backward);
    each tick costs one stage task — ``fwd_time_s`` per-stage forward
    compute, ``bwd_ratio``× that on the backward half — plus one α+β hop
    of ``hop_bytes`` activation (or activation-gradient) bytes on the
    calibrated link class.  GPipe and 1F1B price identically here: same
    tick count, same hop count per tick; the tuner cell between them is
    decided by measured step times and the stash bound, not this form.
    """
    if stages < 1:
        raise ValueError(f"stages must be >= 1, got {stages}")
    if microbatches < 1:
        raise ValueError(f"microbatches must be >= 1, got {microbatches}")
    if fwd_time_s < 0 or hop_bytes < 0 or bwd_ratio < 0:
        raise ValueError(
            "fwd_time_s, hop_bytes, and bwd_ratio must be non-negative"
        )
    if stages == 1:
        # no hops: m forwards + m backwards, back to back
        return microbatches * fwd_time_s * (1.0 + bwd_ratio)
    ticks = microbatches + stages - 1
    hop = coeffs.time(hop_bytes)
    fwd_half = ticks * (fwd_time_s + hop)
    bwd_half = ticks * (fwd_time_s * bwd_ratio + hop)
    return fwd_half + bwd_half


def pipeline_stash_bytes(
    stages: int,
    microbatches: int,
    schedule: str,
    stage: int,
    act_bytes: float,
) -> float:
    """Peak stashed-activation bytes at ``stage`` — the closed form of the
    executor's measured high-water mark (``PipelineReport.stash_peak``).

    GPipe stashes every microbatch before draining: ``m·act_bytes`` at
    every stage.  1F1B bounds the window to the in-flight depth
    ``min(m, stages − stage)`` — the whole reason to prefer it at large
    ``m``.
    """
    if stages < 1:
        raise ValueError(f"stages must be >= 1, got {stages}")
    if microbatches < 1:
        raise ValueError(f"microbatches must be >= 1, got {microbatches}")
    if not 0 <= stage < stages:
        raise ValueError(
            f"stage must be in [0, {stages}), got {stage}"
        )
    from adapcc_tpu.pipe.schedule import PIPE_SCHEDULES  # deferred: pipe prices via us

    if schedule not in PIPE_SCHEDULES:
        raise ValueError(
            f"unknown pipeline schedule {schedule!r}: expected one of "
            f"{PIPE_SCHEDULES}"
        )
    if schedule == "gpipe":
        return microbatches * float(act_bytes)
    return min(microbatches, stages - stage) * float(act_bytes)


# --------------------------------------------------------------------------- #
# durable-recovery pricing (adapcc_tpu/elastic/redundancy): replicated
# ZeRO-1 shards vs a checkpoint reload — the recovery sweep's rows
# --------------------------------------------------------------------------- #

#: shared-filesystem read bandwidth a checkpoint reload pays (a round
#: number of the right order for NFS/GCS-fuse on a pod host; replaced by
#: any measured figure) — deliberately far below ICI so the sweep shows
#: WHY the in-fabric repair wins the hot path
DEFAULT_CKPT_BYTES_PER_S = 1e9


def replication_overhead_time(
    world: int,
    state_bytes: float,
    coeffs: LinkCoeffs,
    replicas: int = 1,
) -> float:
    """Per-step wire cost of k-replicated ZeRO-1 shard placement
    (:func:`adapcc_tpu.elastic.redundancy.replica_placement`).

    Each rank owns ``state_bytes / world`` of optimizer state (flat fp32
    master + moments) and sends the rows its ``replicas`` holders keep —
    one shard copy per holder — inside the post-step all-gather window.
    The sends run concurrently across ranks, each over its own outbound
    hop, so the bottleneck link carries ``replicas · state_bytes/world``
    replica bytes per step: that single-hop transfer is the overhead the
    piggyback adds to the window.  ``replicas=0`` (replication off) is
    exactly free.
    """
    if world < 1:
        raise ValueError(f"world must be >= 1, got {world}")
    if state_bytes < 0:
        raise ValueError(f"state_bytes must be >= 0, got {state_bytes}")
    if replicas < 0:
        raise ValueError(f"replicas must be >= 0, got {replicas}")
    if replicas == 0:
        return 0.0
    if replicas >= world:
        raise ValueError(
            f"replicas={replicas} needs world > replicas (got {world})"
        )
    return coeffs.time(replicas * state_bytes / world)


def replica_repair_time(
    world: int,
    state_bytes: float,
    coeffs: LinkCoeffs,
    standby_cached: bool = True,
) -> float:
    """Time to repair one dead rank's shard from its in-fabric replica
    (docs/RECOVERY.md §1): the holder sends the lost ``state_bytes/world``
    rows back over one hop, plus the plan-swap stall of stepping onto the
    re-balanced layout — **no checkpoint reload and zero lost steps** on
    this path."""
    if world < 2:
        raise ValueError(f"repair pricing needs world >= 2, got {world}")
    if state_bytes < 0:
        raise ValueError(f"state_bytes must be >= 0, got {state_bytes}")
    return coeffs.time(state_bytes / world) + plan_swap_stall_s(standby_cached)


def checkpoint_reload_time(
    state_bytes: float,
    lost_steps: float,
    step_time_s: float,
    ckpt_bytes_per_s: float = DEFAULT_CKPT_BYTES_PER_S,
) -> float:
    """Time the checkpoint-reload arm pays for the same death: read the
    full ``state_bytes`` back from shared storage, then replay every step
    since the last save (``lost_steps × step_time_s`` of re-done work —
    the term the replica path never pays)."""
    if state_bytes < 0 or lost_steps < 0 or step_time_s < 0:
        raise ValueError("state_bytes / lost_steps / step_time_s must be >= 0")
    if ckpt_bytes_per_s <= 0:
        raise ValueError(
            f"ckpt_bytes_per_s must be > 0, got {ckpt_bytes_per_s}"
        )
    return state_bytes / ckpt_bytes_per_s + lost_steps * step_time_s


def recovery_cost(
    world: int,
    nbytes: float,
    coeffs: LinkCoeffs,
    state_bytes: Optional[float] = None,
    replicas: int = 1,
    save_interval_steps: int = 100,
    step_time_s: Optional[float] = None,
    wire_dtype: str = "off",
    standby_cached: bool = True,
    ckpt_bytes_per_s: float = DEFAULT_CKPT_BYTES_PER_S,
) -> Dict[str, float]:
    """Price one rank death both ways (docs/RECOVERY.md) — the rows
    ``sim_collectives --recovery-sweep`` emits:

    - ``baseline_step_comm_s`` — the healthy per-step ring collective;
    - ``replication_overhead_s`` / ``replication_overhead_ratio`` — the
      per-step price of keeping the replicas warm (the acceptance pin:
      < 5 % of step comm at the default config);
    - ``replica_repair_s`` — in-fabric repair: one shard over one hop +
      the warm plan swap, zero lost steps;
    - ``ckpt_reload_s`` — the alternative: full-state read from storage
      plus the expected ``save_interval/2`` steps of re-done work;
    - ``repair_speedup`` — reload / repair (> 1 everywhere the replica
      path earns its overhead);
    - ``overhead_break_even_steps`` — steps between failures above which
      the cumulative replication overhead exceeds what one repair saves
      (failures rarer than this favor plain checkpointing).

    ``state_bytes`` defaults to ``3 · nbytes`` — fp32 Adam's flat master
    + two moment banks for an ``nbytes`` gradient; ``step_time_s``
    defaults to the comm time itself (a fully comm-bound step, the
    conservative floor for the lost-work term).  Deterministic, analytic.
    """
    if world < 2:
        raise ValueError(f"recovery pricing needs world >= 2, got {world}")
    if save_interval_steps < 1:
        raise ValueError(
            f"save_interval_steps must be >= 1, got {save_interval_steps}"
        )
    if state_bytes is None:
        state_bytes = 3.0 * float(nbytes)
    baseline = quantized_ring_allreduce_time(world, nbytes, coeffs, wire_dtype)
    if step_time_s is None:
        step_time_s = baseline
    overhead = replication_overhead_time(world, state_bytes, coeffs, replicas)
    repair = replica_repair_time(world, state_bytes, coeffs, standby_cached)
    lost_steps = save_interval_steps / 2.0
    reload = checkpoint_reload_time(
        state_bytes, lost_steps, step_time_s, ckpt_bytes_per_s
    )
    saved = reload - repair
    return {
        "baseline_step_comm_s": baseline,
        "replication_overhead_s": overhead,
        "replication_overhead_ratio": (
            overhead / baseline if baseline > 0 else 0.0
        ),
        "replica_repair_s": repair,
        "ckpt_reload_s": reload,
        "repair_speedup": reload / repair if repair > 0 else float("inf"),
        "overhead_break_even_steps": (
            saved / overhead if overhead > 0 and saved > 0 else float("inf")
        ),
    }


# --------------------------------------------------------------------------- #
# serving-plane queueing (adapcc_tpu/serve): arrival rate × decode slots ×
# per-token step time → the latency/throughput frontier — the serve sweep's
# rows (docs/SERVING.md §5)
# --------------------------------------------------------------------------- #

#: per-layer on-chip compute of one decode step (qkv + attention over the
#: cached pages + MLP for a handful of slots) when no measured figure
#: exists — a deliberately round number of the right order for a small TP
#: shard on a v5e-class core, replaced by any calibration the operator
#: provides.  It exists so the frontier prices a *step*, not a bare
#: collective: at serving sizes the per-layer allreduce and the per-layer
#: compute are the same order, which is why the small-message plane
#: matters at all
DEFAULT_DECODE_COMPUTE_S_PER_LAYER = 5e-6


def decode_step_time(
    world: int,
    slots: int,
    n_layer: int,
    d_model: int,
    coeffs: LinkCoeffs,
    itemsize: int = 4,
    algo: Optional[str] = None,
    compute_s_per_layer: float = DEFAULT_DECODE_COMPUTE_S_PER_LAYER,
) -> Dict[str, object]:
    """Price ONE continuous-batching decode step (docs/SERVING.md §3): per
    layer, the head-sharded attention's compute plus the per-token combine
    — a ``slots × d_model`` allreduce whose payload sits far below the
    ring ↔ recursive-doubling crossover, so under ``algo=None`` ("auto")
    the selector's own :func:`choose_allreduce_algo` prices the algorithm
    the engine would execute.

    Returns the step ledger: ``step_time_s``, the per-dispatch
    ``collective_bytes`` (``slots · d_model · itemsize`` — the number the
    tuner's size bucket sees; ``itemsize`` defaults to 4 because the
    shipped decode plane is fp32 — exactness is what buys bit parity —
    so a sim row and a live dispatch land in the same bucket), the
    chosen/priced ``algo``, and the comm/compute split.  ``world < 2``
    serves without a fabric: the collective term is zero and ``algo`` is
    ``"none"``.
    """
    if slots < 1:
        raise ValueError(f"slots must be >= 1, got {slots}")
    if n_layer < 1 or d_model < 1:
        raise ValueError(
            f"n_layer={n_layer} / d_model={d_model} must be >= 1"
        )
    if itemsize < 1:
        raise ValueError(f"itemsize must be >= 1, got {itemsize}")
    if compute_s_per_layer < 0:
        raise ValueError(
            f"compute_s_per_layer must be >= 0, got {compute_s_per_layer}"
        )
    nbytes = float(slots * d_model * itemsize)
    if int(world) < 2:
        chosen, coll = "none", 0.0
    elif algo is None:
        chosen, times = choose_allreduce_algo(world, nbytes, coeffs)
        coll = times[chosen]
    else:
        chosen = algo
        _, times = choose_allreduce_algo(world, nbytes, coeffs, (algo,))
        coll = times[algo]
    comm_s = n_layer * coll
    compute_s = n_layer * compute_s_per_layer
    return {
        "step_time_s": comm_s + compute_s,
        "collective_bytes": int(nbytes),
        "algo": chosen,
        "comm_s": comm_s,
        "compute_s": compute_s,
    }


def simulate_serve_queue(
    arrival_steps: Sequence[int],
    service_steps: Sequence[int],
    slots: int,
) -> list:
    """Replay the continuous batcher's admission discipline on the integer
    step clock — the queueing twin of
    :meth:`adapcc_tpu.serve.scheduler.GPT2Server.step`:

    - FIFO admission at step start: a request is admitted at
      ``max(arrival, earliest slot-free step)``;
    - a lane occupies its slot for ``service_steps`` engine steps (the
      equivalent ``generate`` scan length, ``total − 1``) and completes at
      ``admitted + service``;
    - a completed lane's slot admits new traffic from the completion step
      itself (completion is end-of-step, admission start-of-next — the
      same step index).

    Returns one ``(arrival, admitted, completed)`` triple per request, in
    input order.  EOS eviction is not modeled: the triples price the
    no-early-exit worst case, an upper bound on every sojourn.
    Deterministic, analytic — no RNG, no wall clock.
    """
    import heapq

    if slots < 1:
        raise ValueError(f"slots must be >= 1, got {slots}")
    if len(arrival_steps) != len(service_steps):
        raise ValueError(
            f"{len(arrival_steps)} arrivals vs {len(service_steps)} service "
            "times: every request needs exactly one of each"
        )
    if any(a < 0 for a in arrival_steps):
        raise ValueError("arrival steps must be >= 0")
    if any(s < 1 for s in service_steps):
        raise ValueError(
            "service steps must be >= 1 (a request that decodes nothing is "
            "not serving traffic)"
        )
    if list(arrival_steps) != sorted(arrival_steps):
        raise ValueError(
            "arrival steps must be sorted (the batcher admits FIFO)"
        )
    free = [0] * int(slots)
    heapq.heapify(free)
    out = []
    for arrival, service in zip(arrival_steps, service_steps):
        admitted = max(int(arrival), heapq.heappop(free))
        completed = admitted + int(service)
        heapq.heappush(free, completed)
        out.append((int(arrival), admitted, completed))
    return out


def serve_queue_metrics(
    arrival_steps: Sequence[int],
    service_steps: Sequence[int],
    slots: int,
    step_time_s: float,
    slo_ms: Optional[float] = None,
    generated_steps: Optional[Sequence[int]] = None,
) -> Dict[str, float]:
    """The latency/throughput ledger of one (trace × slots × step-time)
    cell — the row body ``sim_collectives --serve-sweep`` emits:

    - ``p50_sojourn_steps`` / ``p99_sojourn_steps`` — arrival → completion
      on the deterministic step clock (queue wait included), nearest-rank;
    - ``p50_sojourn_ms`` / ``p99_sojourn_ms`` — the same scaled by the
      priced decode step time;
    - ``p99_queue_steps`` — arrival → admission: the congestion-collapse
      signal (it explodes first when the arrival rate crosses the service
      capacity ``slots / mean_service``);
    - ``throughput_tok_s`` — GENERATED tokens per second of makespan when
      ``generated_steps`` (per-request decode budgets) is given; without
      it, engine token-steps per second (prefill force-feeds included —
      an upper bound on the generated rate);
    - ``utilization`` — occupied-lane steps over ``slots × makespan``;
    - ``slo_attainment`` (with ``slo_ms``) — fraction of requests whose
      priced sojourn meets the SLO, the number the frontier trades
      against throughput.

    Deterministic: same trace, same slots, same step time → the same
    bytes.
    """
    from adapcc_tpu.utils.observability import nearest_rank_percentile

    if step_time_s <= 0:
        raise ValueError(f"step_time_s must be > 0, got {step_time_s}")
    if generated_steps is not None:
        if len(generated_steps) != len(service_steps):
            raise ValueError(
                f"{len(generated_steps)} generated budgets vs "
                f"{len(service_steps)} service times"
            )
        if any(g < 1 or g > s for g, s in
               zip(generated_steps, service_steps)):
            raise ValueError(
                "each generated budget must be in [1, service_steps]"
            )
    triples = simulate_serve_queue(arrival_steps, service_steps, slots)
    sojourns = sorted(c - a for a, _, c in triples)
    queues = sorted(adm - a for a, adm, _ in triples)

    def pct(xs, q: float) -> int:
        # nearest-rank, the shared convention (one spelling repo-wide)
        return int(nearest_rank_percentile(xs, q))

    makespan = max(c for _, _, c in triples)
    busy = sum(service_steps)
    tokens = sum(generated_steps) if generated_steps is not None else busy
    out: Dict[str, float] = {
        "requests": float(len(triples)),
        "makespan_steps": float(makespan),
        "p50_sojourn_steps": float(pct(sojourns, 0.50)),
        "p99_sojourn_steps": float(pct(sojourns, 0.99)),
        "p50_sojourn_ms": pct(sojourns, 0.50) * step_time_s * 1e3,
        "p99_sojourn_ms": pct(sojourns, 0.99) * step_time_s * 1e3,
        "p99_queue_steps": float(pct(queues, 0.99)),
        "throughput_tok_s": tokens / (makespan * step_time_s),
        "utilization": busy / float(makespan * slots),
    }
    if slo_ms is not None:
        if slo_ms <= 0:
            raise ValueError(f"slo_ms must be > 0, got {slo_ms}")
        within = sum(
            1 for s in sojourns if s * step_time_s * 1e3 <= slo_ms
        )
        out["slo_ms"] = float(slo_ms)
        out["slo_attainment"] = within / len(sojourns)
    return out


def simulate_disagg_queue(
    arrival_steps: Sequence[int],
    prefill_steps: Sequence[int],
    decode_steps: Sequence[int],
    prefill_slots: int,
    decode_slots: int,
    transfer_steps: int = 0,
) -> list:
    """Replay the :class:`~adapcc_tpu.serve.disagg.ClusterRouter`'s
    admission discipline on the integer step clock — the tandem-queue twin
    of the disaggregated cluster (docs/SERVING.md §7):

    - FIFO admission into a prefill slot at ``max(arrival, earliest
      prefill-slot-free step)``;
    - the **first token** lands ``prefill_steps`` later (the step that
      feeds the last prompt position samples it) — TTFT never waits on
      the decode pool's backlog, which is the disaggregation win;
    - migration claims a decode slot at ``max(first_token, earliest
      decode-slot-free step)`` — a finished prefill with no free decode
      slot **stays resident in its prefill slot** (the slot frees only at
      migration, exactly the router's never-drop discipline), then pays
      ``transfer_steps`` of DCN wire (priced off calibrated α-β by the
      caller) before decoding;
    - ``decode_steps`` may be 0 (``max_new_tokens == 1`` / early EOS
      completes inside the prefill pod: no migration, no transfer).

    Returns one ``(arrival, admitted_prefill, first_token,
    admitted_decode, completed)`` 5-tuple per request, in input order
    (``admitted_decode`` is the decode pod's first compute step,
    transfer included; for an unmigrated request it equals
    ``first_token``).  Deterministic, analytic — no RNG, no wall clock.
    """
    import heapq

    if prefill_slots < 1 or decode_slots < 1:
        raise ValueError(
            f"prefill_slots={prefill_slots} / decode_slots={decode_slots} "
            "must be >= 1"
        )
    if transfer_steps < 0:
        raise ValueError(
            f"transfer_steps must be >= 0, got {transfer_steps}"
        )
    if not (len(arrival_steps) == len(prefill_steps) == len(decode_steps)):
        raise ValueError(
            f"{len(arrival_steps)} arrivals vs {len(prefill_steps)} prefill "
            f"vs {len(decode_steps)} decode budgets: every request needs "
            "exactly one of each"
        )
    if any(a < 0 for a in arrival_steps):
        raise ValueError("arrival steps must be >= 0")
    if any(p < 1 for p in prefill_steps):
        raise ValueError(
            "prefill steps must be >= 1 (every prompt feeds at least one "
            "token)"
        )
    if any(d < 0 for d in decode_steps):
        raise ValueError("decode steps must be >= 0")
    if list(arrival_steps) != sorted(arrival_steps):
        raise ValueError(
            "arrival steps must be sorted (the router admits FIFO)"
        )
    prefill_free = [0] * int(prefill_slots)
    decode_free = [0] * int(decode_slots)
    heapq.heapify(prefill_free)
    heapq.heapify(decode_free)
    out = []
    for arrival, prefill, decode in zip(
        arrival_steps, prefill_steps, decode_steps
    ):
        admitted = max(int(arrival), heapq.heappop(prefill_free))
        first_token = admitted + int(prefill)
        if int(decode) < 1:
            # completes inside the prefill pod — the slot frees at once
            heapq.heappush(prefill_free, first_token)
            out.append((int(arrival), admitted, first_token, first_token,
                        first_token))
            continue
        migrated = max(first_token, heapq.heappop(decode_free))
        heapq.heappush(prefill_free, migrated)  # resident until migration
        admitted_decode = migrated + int(transfer_steps)
        completed = admitted_decode + int(decode)
        heapq.heappush(decode_free, completed)
        out.append((int(arrival), admitted, first_token, admitted_decode,
                    completed))
    return out


def disagg_queue_metrics(
    arrival_steps: Sequence[int],
    prefill_steps: Sequence[int],
    decode_steps: Sequence[int],
    prefill_slots: int,
    decode_slots: int,
    transfer_steps: int,
    prefill_step_time_s: float,
    decode_step_time_s: float,
    slo_ms: Optional[float] = None,
) -> Dict[str, float]:
    """The disaggregated latency/throughput ledger — the row body
    ``sim_collectives --disagg-sweep`` prices each frontier cell with.
    The cluster's pods step in lockstep per router tick, so the wall cost
    of one step is ``max(prefill_step_time_s, decode_step_time_s)``
    (reported as ``step_time_s``); TTFT is arrival → first token —
    **queue wait plus prefill service only**, the tail the two-pool
    split exists to protect — and ``p99_decode_wait_steps`` (first token
    → decode admission, transfer included) is the migration-stall signal
    that explodes first when the decode pool undersizes.  Generated
    tokens per request are ``1 + decode_steps`` (the prefill pod samples
    the first).  Deterministic: same inputs → the same bytes.
    """
    from adapcc_tpu.utils.observability import nearest_rank_percentile

    if prefill_step_time_s <= 0 or decode_step_time_s <= 0:
        raise ValueError(
            f"step times must be > 0, got prefill={prefill_step_time_s} / "
            f"decode={decode_step_time_s}"
        )
    rows = simulate_disagg_queue(
        arrival_steps, prefill_steps, decode_steps,
        prefill_slots, decode_slots, transfer_steps,
    )
    tick_s = max(float(prefill_step_time_s), float(decode_step_time_s))
    ttfts = sorted(f - a for a, _, f, _, _ in rows)
    sojourns = sorted(c - a for a, _, _, _, c in rows)
    queues = sorted(adm - a for a, adm, _, _, _ in rows)
    decode_waits = sorted(ad - f for _, _, f, ad, _ in rows)

    def pct(xs, q: float) -> int:
        # nearest-rank, the shared convention (one spelling repo-wide)
        return int(nearest_rank_percentile(xs, q))

    makespan = max(c for _, _, _, _, c in rows)
    # prefill residency runs admission → migration (decode-wait included:
    # the waiting lane blocks its prefill slot, the never-drop cost)
    prefill_busy = sum(
        (ad - int(transfer_steps) if d >= 1 else f) - adm
        for (_, adm, f, ad, _), d in zip(rows, decode_steps)
    )
    decode_busy = sum(int(d) for d in decode_steps)
    tokens = sum(1 + int(d) for d in decode_steps)
    out: Dict[str, float] = {
        "requests": float(len(rows)),
        "makespan_steps": float(makespan),
        "step_time_s": tick_s,
        "transfer_steps": float(transfer_steps),
        "p50_ttft_steps": float(pct(ttfts, 0.50)),
        "p99_ttft_steps": float(pct(ttfts, 0.99)),
        "p50_ttft_ms": pct(ttfts, 0.50) * tick_s * 1e3,
        "p99_ttft_ms": pct(ttfts, 0.99) * tick_s * 1e3,
        "p50_sojourn_steps": float(pct(sojourns, 0.50)),
        "p99_sojourn_steps": float(pct(sojourns, 0.99)),
        "p50_sojourn_ms": pct(sojourns, 0.50) * tick_s * 1e3,
        "p99_sojourn_ms": pct(sojourns, 0.99) * tick_s * 1e3,
        "p99_queue_steps": float(pct(queues, 0.99)),
        "p99_decode_wait_steps": float(pct(decode_waits, 0.99)),
        "throughput_tok_s": tokens / (makespan * tick_s),
        "prefill_utilization": prefill_busy / float(
            makespan * prefill_slots
        ),
        "decode_utilization": decode_busy / float(makespan * decode_slots),
    }
    if slo_ms is not None:
        if slo_ms <= 0:
            raise ValueError(f"slo_ms must be > 0, got {slo_ms}")
        within = sum(
            1 for s in sojourns if s * tick_s * 1e3 <= slo_ms
        )
        out["slo_ms"] = float(slo_ms)
        out["slo_attainment"] = within / len(sojourns)
    return out
