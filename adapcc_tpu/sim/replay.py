"""Lower real engine inputs into simulated timelines.

The adapters here consume exactly what the execution engine consumes — a
:class:`~adapcc_tpu.strategy.ir.Strategy` (from ParTrees, the MILP solver,
or a parsed ``strategy.xml``), an active-rank set (relay masks from
:mod:`adapcc_tpu.comm.relay`), or a :class:`~adapcc_tpu.strategy.flow_lp.
FlowSolution` — and return predicted collective latency plus per-link
utilization instead of running hardware.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, FrozenSet, Iterable, List, Optional, Sequence, Tuple

from adapcc_tpu.comm.relay import prune_broadcast_rounds, prune_reduce_rounds
from adapcc_tpu.sim.cost_model import Link, LinkCostModel
from adapcc_tpu.sim.events import EventSimulator, SimReport, Transfer, TreeSchedule
from adapcc_tpu.sim.vector import (
    SIM_ENGINE_ENV,
    SIM_ENGINES,
    VECTOR_MIN_WORLD,
    lowered_columns,
    resolve_sim_engine,
    vector_run,
)
from adapcc_tpu.strategy.ir import CommRound, Strategy, Tree

#: collectives the replay layer knows how to lower from a tree strategy
COLLECTIVES = ("allreduce", "reduce", "broadcast")


@dataclass
class SimTimeline:
    """Predicted execution of one collective under one cost model."""

    seconds: float
    collective: str
    nbytes: float
    world: int
    report: SimReport
    strategy_label: str = ""
    #: stamped into every simulated artifact row so a reader can never
    #: mistake a model prediction for a measured number
    mode: str = "simulated"

    def per_link_utilization(self) -> Dict[Link, float]:
        return self.report.utilization()

    def algbw_gbps(self) -> float:
        """nccl-tests-style algorithm bandwidth for the simulated latency."""
        if self.seconds <= 0:
            return 0.0
        return self.nbytes / self.seconds / 1e9

    def to_row(self) -> dict:
        """One artifact row (the simulated analog of a busbw sweep row)."""
        return {
            "mode": self.mode,
            "collective": self.collective,
            "size_bytes": int(self.nbytes),
            "world": self.world,
            "pred_time_us": round(self.seconds * 1e6, 3),
            "algbw_gbps": round(self.algbw_gbps(), 6),
            "strategy": self.strategy_label,
        }


def _tree_rounds(
    tree: Tree, collective: str, active: Optional[FrozenSet[int]]
) -> List[CommRound]:
    """The same round lists the engine compiles, relay-pruned when a subset
    is active (dead edges carry nothing and are dropped pre-compilation)."""
    if collective == "allreduce":
        if active is None:
            return tree.reduce_rounds() + tree.broadcast_rounds()
        return prune_reduce_rounds(tree, active) + prune_broadcast_rounds(tree, active)
    if collective == "reduce":
        if active is None:
            return tree.reduce_rounds()
        return prune_reduce_rounds(tree, active)
    if collective == "broadcast":
        if active is None:
            return tree.broadcast_rounds()
        return prune_broadcast_rounds(tree, active)
    raise ValueError(
        f"unknown collective {collective!r}; expected one of {COLLECTIVES}"
    )


def lower_strategy(
    strategy: Strategy,
    nbytes: float,
    collective: str = "allreduce",
    active: Optional[Iterable[int]] = None,
) -> List[TreeSchedule]:
    """Strategy → per-tree schedules: payload split by tree shares
    (``1/num_trans`` unless the MILP optimized unequal shares), each tree
    chunked at its own granularity — the solver's per-tree c_m when the
    strategy carries one (``Strategy.chunk_bytes_for_tree``), else the
    global ``chunk_bytes`` — so a skewed share pipelines at a comparable
    depth instead of one oversized chunk."""
    act = frozenset(active) if active is not None else None
    schedules = []
    for i, (tree, share) in enumerate(
        zip(strategy.trees, strategy.tree_shares())
    ):
        schedules.append(
            TreeSchedule(
                rounds=_tree_rounds(tree, collective, act),
                nbytes=nbytes * share,
                chunk_bytes=strategy.chunk_bytes_for_tree(i),
                label=f"tree@{tree.root}",
            )
        )
    return schedules


def simulate_strategy(
    strategy: Strategy,
    cost_model: LinkCostModel,
    nbytes: float,
    collective: str = "allreduce",
    active: Optional[Iterable[int]] = None,
    keep_transfers: bool = True,
    engine: Optional[str] = None,
    keep_links: Optional[bool] = None,
) -> SimTimeline:
    """Predict one collective's latency under the cost model.

    ``active`` prices the relay scenario: inactive ranks stay on the data
    path as forwarders, edges whose source subtree holds no active rank are
    pruned — the same algebra the engine applies before compiling.

    THE replay chokepoint: every pricing path (ranking, fault/congestion
    replays, standby scenarios, benches) funnels through here, and the
    ``engine`` funnel (arg > ``ADAPCC_SIM_ENGINE`` > ``auto``) picks the
    per-transfer event oracle below :data:`~adapcc_tpu.sim.vector.
    VECTOR_MIN_WORLD` ranks and the vectorized column replay above it —
    one pricing engine, parity-pinned, no second implementation to drift.
    ``keep_links`` opts the O(world) per-link busy map in or out (defaults:
    on for the event oracle, off for pod-scale vector replays); the
    vector path never keeps the per-transfer log.
    """
    resolved = resolve_sim_engine(engine, strategy.world_size)
    if resolved == "vector":
        report = vector_run(
            lowered_columns(strategy, collective, active),
            cost_model,
            nbytes,
            keep_links=bool(keep_links),
        )
    else:
        report = EventSimulator(
            cost_model,
            keep_transfers=keep_transfers,
            keep_links=True if keep_links is None else keep_links,
        ).run(lower_strategy(strategy, nbytes, collective, active))
    return SimTimeline(
        seconds=report.makespan,
        collective=collective,
        nbytes=nbytes,
        world=strategy.world_size,
        report=report,
        strategy_label=f"{strategy.synthesis or 'unnamed'} x{strategy.num_trans}",
    )


def simulate_program(
    program,
    cost_model: LinkCostModel,
    nbytes: float,
    keep_transfers: bool = True,
    engine: Optional[str] = None,
    keep_links: Optional[bool] = None,
) -> SimTimeline:
    """Replay a ``compiler.ScheduleProgram`` — the SAME object the engine's
    ``algo="ir"`` dispatch lowers and ``engine.schedule_program()`` returns,
    not a parallel description that can drift from it.

    The IR's rounds are barriers, so the replay is exact, not heuristic:
    per round, sends sharing a directed link serialize (their chunk bytes
    coalesce onto one transfer priced by ``cost_model.time_for``), distinct
    links run concurrently, and the round completes at its slowest link.
    Under a uniform cost model this reproduces
    :func:`~adapcc_tpu.sim.cost_model.schedule_program_time` to the float —
    the cross-check ``tests/test_compiler.py`` pins — while a heterogeneous
    model (degraded links, two-level classes) prices each link at its own
    α/β.

    The same ``engine`` funnel as :func:`simulate_strategy` (arg >
    ``ADAPCC_SIM_ENGINE`` > ``auto``) applies: below
    :data:`~adapcc_tpu.sim.vector.VECTOR_MIN_WORLD` ranks the per-round
    event loop below runs with its per-transfer log; above it the cached
    column replay (``vector.vector_program_run``) prices the program as
    numpy algebra, parity-pinned on the makespan, per-transfer log never
    kept.  ``keep_links`` defaults on for the event path and off for the
    vector path, like ``simulate_strategy``.
    """
    # optimized programs (span steps, fused codecs) replay through their
    # unit-step expansion — same bytes on the same links, and the
    # per-chunk transfer log keeps one chunk per row; the label below
    # stays the ORIGINAL program's name@fingerprint, because that is the
    # object the caller handed in and the engine lowers
    from adapcc_tpu.compiler.verify import normalize_program

    label = f"program:{program.name}@{program.fingerprint()}"
    resolved = resolve_sim_engine(engine, program.world)
    program = normalize_program(program)
    if resolved == "vector":
        from adapcc_tpu.sim.vector import program_columns, vector_program_run

        report = vector_program_run(
            program_columns(program),
            cost_model,
            nbytes,
            keep_links=bool(keep_links),
        )
        return SimTimeline(
            seconds=report.makespan,
            collective=program.collective,
            nbytes=nbytes,
            world=program.world,
            report=report,
            strategy_label=label,
        )
    seg = float(nbytes) / max(1, program.chunks)
    keep_link_busy = True if keep_links is None else bool(keep_links)
    transfers: List[Transfer] = []
    link_busy: Dict[Link, float] = {}
    clock = 0.0
    for round_idx, round_steps in enumerate(program.rounds):
        link_chunks: Dict[Link, List[int]] = {}
        for step in round_steps:
            if step.kind == "send":
                link_chunks.setdefault((step.rank, step.peer), []).append(step.chunk)
        if not link_chunks:
            continue
        round_end = clock
        for (src, dst), chunks in link_chunks.items():
            dur = cost_model.time_for(src, dst, seg * len(chunks))
            if keep_link_busy:
                link_busy[(src, dst)] = link_busy.get((src, dst), 0.0) + dur
            round_end = max(round_end, clock + dur)
            if keep_transfers:
                for chunk in chunks:
                    transfers.append(
                        Transfer(
                            tree=0,
                            round_idx=round_idx,
                            chunk=chunk,
                            src=src,
                            dst=dst,
                            nbytes=seg,
                            start=clock,
                            finish=clock + dur,
                        )
                    )
        clock = round_end
    report = SimReport(makespan=clock, transfers=transfers, link_busy=link_busy)
    return SimTimeline(
        seconds=clock,
        collective=program.collective,
        nbytes=nbytes,
        world=program.world,
        report=report,
        strategy_label=label,
    )


def simulate_reduce(strategy, cost_model, nbytes, **kwargs) -> SimTimeline:
    return simulate_strategy(strategy, cost_model, nbytes, "reduce", **kwargs)


def simulate_broadcast(strategy, cost_model, nbytes, **kwargs) -> SimTimeline:
    return simulate_strategy(strategy, cost_model, nbytes, "broadcast", **kwargs)


def simulate_xml(
    text_or_path: str,
    cost_model: LinkCostModel,
    nbytes: float,
    collective: str = "allreduce",
    **kwargs,
) -> SimTimeline:
    """Simulate a persisted ``strategy.xml`` — the artifact the reference's
    tinyxml2 reader and this repo's engine both consume."""
    from adapcc_tpu.strategy.xml_io import parse_strategy_xml

    return simulate_strategy(
        parse_strategy_xml(text_or_path), cost_model, nbytes, collective, **kwargs
    )


@dataclass
class FaultStepRow:
    """One step of a fault-plan replay: the collective's predicted cost
    under that step's fault state, plus the transition costs stamped on
    the step where the world actually changed."""

    step: int
    epoch: int
    alive: Tuple[int, ...]
    relays: Tuple[int, ...]
    seconds: float
    #: world changed at this step (detection + swap were paid here)
    swapped: bool = False
    detection_s: float = 0.0
    swap_s: float = 0.0
    mode: str = "simulated"

    def to_row(self) -> dict:
        return {
            "mode": self.mode,
            "step": self.step,
            "epoch": self.epoch,
            "alive": list(self.alive),
            "relays": list(self.relays),
            "pred_time_us": round(self.seconds * 1e6, 3),
            "swapped": self.swapped,
            "detection_us": round(self.detection_s * 1e6, 3),
            "swap_us": round(self.swap_s * 1e6, 3),
        }


def simulate_fault_plan(
    strategy: Strategy,
    cost_model: LinkCostModel,
    nbytes: float,
    plan,
    steps: Optional[int] = None,
    collective: str = "allreduce",
    heartbeat_timeout_s: float = 1.0,
    standby_cached: bool = True,
    engine: Optional[str] = None,
) -> List[FaultStepRow]:
    """Replay a :class:`~adapcc_tpu.elastic.faults.FaultPlan` through the
    event simulator: every step's collective is priced under that step's
    fault state — down ranks excluded and their edges relay-pruned, slow
    ranks demoted to relays on a degraded (slowed-link) cost model — and
    each world *transition* is stamped with the detection latency and the
    plan-swap stall from the failover cost terms.

    This is the CPU-exercisable twin of the live failover loop: the same
    plan injected at the coordinator funnel produces the same epochs, and
    these rows price what each epoch costs.  Deterministic — same plan,
    same calibration → byte-identical rows.
    """
    from adapcc_tpu.sim.cost_model import (
        detection_latency_s,
        plan_swap_stall_s,
    )

    if plan.world != strategy.world_size:
        raise ValueError(
            f"fault plan world {plan.world} != strategy world "
            f"{strategy.world_size}"
        )
    n_steps = steps if steps is not None else plan.last_step() + 2
    rows: List[FaultStepRow] = []
    prev_state = None
    epoch = 0
    healthy_s: Optional[float] = None
    for step in range(n_steps):
        state = plan.state_at(step)
        slow = state.slow_map
        model = cost_model
        for rank, slowdown in sorted(slow.items()):
            model = model.degraded([rank], slowdown)
        contributing = sorted(
            set(range(plan.world)) - state.down - set(slow)
        ) or sorted(set(range(plan.world)) - state.down)
        active = None if state.healthy else contributing
        seconds = simulate_strategy(
            strategy, model, nbytes, collective, active=active,
            keep_transfers=False, engine=engine,
        ).seconds
        if healthy_s is None and state.healthy:
            healthy_s = seconds
        # a plan whose FIRST event lands at step 0 is still a transition
        # (from the implicit healthy world before training): its detection
        # + swap costs must be stamped, not silently dropped
        swapped = (
            state != prev_state
            if prev_state is not None
            else not state.healthy
        )
        rows.append(
            FaultStepRow(
                step=step,
                epoch=(epoch := epoch + 1) if swapped else epoch,
                alive=tuple(sorted(set(range(plan.world)) - state.down)),
                relays=tuple(sorted(slow)),
                seconds=seconds,
                swapped=swapped,
                detection_s=(
                    detection_latency_s(heartbeat_timeout_s, healthy_s or 0.0)
                    if swapped else 0.0
                ),
                swap_s=plan_swap_stall_s(standby_cached) if swapped else 0.0,
            )
        )
        prev_state = state
    return rows


@dataclass
class CongestionStepRow:
    """One step of a congestion-profile replay: the collective's predicted
    cost under that step's contended link classes, next to the healthy
    price — so the per-step contention tax is a printed number."""

    step: int
    congested: bool
    factors: Tuple[Tuple[str, float], ...]  # sorted (class, factor) pairs
    seconds: float
    healthy_s: float
    mode: str = "simulated"

    @property
    def contention_ratio(self) -> float:
        return self.seconds / self.healthy_s if self.healthy_s > 0 else 1.0

    def to_row(self) -> dict:
        return {
            "mode": self.mode,
            "step": self.step,
            "congested": self.congested,
            "factors": {cls: f for cls, f in self.factors},
            "pred_time_us": round(self.seconds * 1e6, 3),
            "healthy_us": round(self.healthy_s * 1e6, 3),
            "contention_ratio": round(self.contention_ratio, 6),
        }


def simulate_congestion_profile(
    strategy: Strategy,
    cost_model: LinkCostModel,
    nbytes: float,
    profile,
    steps: Optional[int] = None,
    collective: str = "allreduce",
    engine: Optional[str] = None,
) -> List[CongestionStepRow]:
    """Replay a :class:`~adapcc_tpu.sim.congestion.CongestionProfile`
    through the event simulator: every step's collective is priced under
    that step's contended model (each active window's link class gets its
    effective bandwidth cut — β scaled, α intact, the congestion
    signature), next to the healthy price.

    This is the CPU-exercisable twin of a live run under neighbor
    traffic: the same profile injected at the adaptation controller's
    observation funnel produces the same windows, and these rows price
    what each window costs the strategy that did NOT re-route.
    Deterministic — same profile, same calibration → byte-identical rows.
    """
    if profile.world != strategy.world_size:
        raise ValueError(
            f"congestion profile world {profile.world} != strategy world "
            f"{strategy.world_size}"
        )
    n_steps = steps if steps is not None else profile.last_step() + 1
    healthy_s = simulate_strategy(
        strategy, cost_model, nbytes, collective, keep_transfers=False,
        engine=engine,
    ).seconds
    rows: List[CongestionStepRow] = []
    # every step inside one window prices identically — simulate once per
    # distinct factors tuple, not once per step
    priced: Dict[Tuple[Tuple[str, float], ...], float] = {(): healthy_s}
    for step in range(n_steps):
        factors = profile.factors_at(step)
        fkey = tuple(sorted(factors.items()))
        seconds = priced.get(fkey)
        if seconds is None:
            seconds = simulate_strategy(
                strategy,
                cost_model.contended(factors),
                nbytes,
                collective,
                keep_transfers=False,
                engine=engine,
            ).seconds
            priced[fkey] = seconds
        rows.append(
            CongestionStepRow(
                step=step,
                congested=bool(factors),
                factors=fkey,
                seconds=seconds,
                healthy_s=healthy_s,
            )
        )
    return rows


def simulate_flow_broadcast(
    flow, cost_model: LinkCostModel, nbytes: float
) -> SimTimeline:
    """Replay a :class:`~adapcc_tpu.strategy.flow_lp.FlowSolution`.

    The LP owns its own chunking (fractional per-round flows), so each LP
    round's edge carries ``fraction × nbytes`` and store-and-forward
    readiness replaces the tree dependency order: a node may forward in
    round ``r`` only what earlier rounds delivered to it.
    """
    from adapcc_tpu.sim.events import SimReport, Transfer

    ready: Dict[int, float] = {flow.source: 0.0}
    link_free: Dict[Link, float] = {}
    egress_free: Dict[int, float] = {}
    ingress_free: Dict[int, float] = {}
    link_busy: Dict[Link, float] = {}
    transfers: List[Transfer] = []
    makespan = 0.0
    recv_frac: Dict[int, float] = {}   # cumulative payload fraction received
    recv_last: Dict[int, float] = {}   # latest counted arrival per node
    for r, flows in enumerate(flow.rounds):
        # within one LP round, heavier flows schedule first (they dominate
        # the round's duration, mirroring FlowSolution.comm_rounds)
        landed: List[Tuple[int, float, float]] = []
        for (src, dst), frac in sorted(
            flows.items(), key=lambda kv: -kv[1]
        ):
            if src not in ready:
                # alternate optima can park flow on edges whose source never
                # received data; the broadcast semantics carry nothing there
                continue
            start = max(
                ready[src],
                link_free.get((src, dst), 0.0),
                egress_free.get(src, 0.0),
                ingress_free.get(dst, 0.0),
            )
            dur = cost_model.time_for(src, dst, frac * nbytes)
            finish = start + dur
            link_free[(src, dst)] = finish
            egress_free[src] = finish
            ingress_free[dst] = finish
            link_busy[(src, dst)] = link_busy.get((src, dst), 0.0) + dur
            landed.append((dst, frac, finish))
            makespan = max(makespan, finish)
            transfers.append(
                Transfer(
                    tree=0, round_idx=r, chunk=0, src=src, dst=dst,
                    nbytes=frac * nbytes, start=start, finish=finish,
                )
            )
        # deliveries land for the *next* round (store-and-forward: sends
        # through round r are bounded by receipts before round r).  A node
        # is ready only once its CUMULATIVE receipts cover the payload —
        # a partial fraction must not grant early readiness — and a node
        # that already holds it (the source, or a completed receiver) is
        # never delayed by a redundant delivery an alternate LP optimum
        # parked on it
        for dst, frac, t in landed:
            if dst in ready:
                continue
            recv_frac[dst] = recv_frac.get(dst, 0.0) + frac
            recv_last[dst] = max(recv_last.get(dst, 0.0), t)
            if recv_frac[dst] >= 1.0 - 1e-9:
                ready[dst] = recv_last[dst]
    report = SimReport(makespan=makespan, transfers=transfers, link_busy=link_busy)
    return SimTimeline(
        seconds=report.makespan,
        collective="broadcast",
        nbytes=nbytes,
        world=flow.num_nodes,
        report=report,
        strategy_label="flow-lp",
    )
