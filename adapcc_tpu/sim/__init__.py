"""Trace-driven collective simulator and calibrated α-β cost model.

Round 5 landed every performance lever with the TPU tunnel dead: nothing
could be ranked or regressed because every number needed live hardware.
This package is the hardware-free half of the profile → synthesize → execute
loop: an analytical per-link α-β (latency + inverse-bandwidth) cost model
calibrated from the profiler's probe CSVs or committed hardware-battery
traces, a discrete-event engine that replays schedule-IR rounds with chunk
pipelining and link contention, and a ranking API the synthesizer and the
bench harness use when the backend is unreachable.

The same modeling family TACCL and SCCL (PAPERS.md) use to rank candidate
schedules offline — here wired to this repo's strategy IR, relay masks, and
artifact formats.

Layers:

- :mod:`adapcc_tpu.sim.cost_model` — per-link α-β coefficients with ICI/DCN
  link classes, least-squares fit from probe points;
- :mod:`adapcc_tpu.sim.events` — discrete-event replay of communication
  rounds (chunk pipelining, merged-tree round coloring, link/port
  contention);
- :mod:`adapcc_tpu.sim.replay` — lower strategies / XML schedules / flow-LP
  solutions into simulated timelines;
- :mod:`adapcc_tpu.sim.rank` — strategy ranking + straggler/relay
  degradation prediction;
- :mod:`adapcc_tpu.sim.calibrate` — fit + persist calibration artifacts so
  simulated numbers stay anchored to the last good hardware round.
"""

from adapcc_tpu.sim.congestion import (
    CONGESTION_PROFILE_ENV,
    CongestionProfile,
    CongestionWindow,
    load_congestion_profile,
)
from adapcc_tpu.sim.cost_model import (
    DCN,
    ICI,
    LinkCoeffs,
    LinkCostModel,
    bandwidth_lower_bound,
    choose_wire_dtype,
    collective_lower_bound,
    congested_ring_allreduce_time,
    congested_two_level_allreduce_time,
    contended_coeffs,
    contended_lower_bound,
    disagg_queue_metrics,
    fastest_coeffs,
    fit_alpha_beta,
    latency_lower_bound,
    optimality_gap,
    quantized_ring_allreduce_time,
    simulate_disagg_queue,
    wire_bytes_per_element,
)
from adapcc_tpu.sim.events import EventSimulator, SimReport, Transfer, TreeSchedule
from adapcc_tpu.sim.vector import (
    SIM_ENGINE_ENV,
    SIM_ENGINES,
    VECTOR_MIN_WORLD,
    LoweredColumns,
    ProgramColumns,
    clear_lowering_cache,
    clear_program_cache,
    lowered_columns,
    lowering_cache_info,
    program_cache_info,
    program_columns,
    resolve_sim_engine,
    vector_program_run,
    vector_run,
)
from adapcc_tpu.sim.replay import (
    CongestionStepRow,
    SimTimeline,
    simulate_broadcast,
    simulate_congestion_profile,
    simulate_flow_broadcast,
    simulate_reduce,
    simulate_strategy,
    simulate_xml,
)
from adapcc_tpu.sim.rank import (
    RankedCandidate,
    predict_degradation,
    rank_candidates,
    relay_latency,
)
from adapcc_tpu.sim.calibrate import (
    Calibration,
    calibrate_from_battery,
    calibrate_from_matrices,
    calibrate_from_profile_dir,
    load_calibration,
)

__all__ = [
    "CONGESTION_PROFILE_ENV",
    "CongestionProfile",
    "CongestionStepRow",
    "CongestionWindow",
    "DCN",
    "ICI",
    "SIM_ENGINE_ENV",
    "SIM_ENGINES",
    "VECTOR_MIN_WORLD",
    "LoweredColumns",
    "ProgramColumns",
    "bandwidth_lower_bound",
    "clear_lowering_cache",
    "clear_program_cache",
    "collective_lower_bound",
    "fastest_coeffs",
    "latency_lower_bound",
    "lowered_columns",
    "lowering_cache_info",
    "optimality_gap",
    "program_cache_info",
    "program_columns",
    "resolve_sim_engine",
    "vector_program_run",
    "vector_run",
    "LinkCoeffs",
    "LinkCostModel",
    "choose_wire_dtype",
    "congested_ring_allreduce_time",
    "congested_two_level_allreduce_time",
    "contended_coeffs",
    "contended_lower_bound",
    "disagg_queue_metrics",
    "simulate_disagg_queue",
    "fit_alpha_beta",
    "load_congestion_profile",
    "simulate_congestion_profile",
    "quantized_ring_allreduce_time",
    "wire_bytes_per_element",
    "EventSimulator",
    "SimReport",
    "Transfer",
    "TreeSchedule",
    "SimTimeline",
    "simulate_broadcast",
    "simulate_flow_broadcast",
    "simulate_reduce",
    "simulate_strategy",
    "simulate_xml",
    "RankedCandidate",
    "predict_degradation",
    "rank_candidates",
    "relay_latency",
    "Calibration",
    "calibrate_from_battery",
    "calibrate_from_matrices",
    "calibrate_from_profile_dir",
    "load_calibration",
]
