"""Deterministic background-traffic model: congestion windows on shared
link classes.

Production pods run many jobs over shared DCN, and The Big Send-off
(PAPERS.md) shows collectives must be designed to *survive* datacenter-
scale contention, not just win clean-network benchmarks.  A congested
link is not a degraded link: a neighbor's traffic steals **bandwidth
share** for a bounded window and then gives it back, while the wire's
propagation latency is mostly untouched — so the right model is a
time-windowed *effective-bandwidth* scaling (β × factor, α intact:
:func:`adapcc_tpu.sim.cost_model.contended_coeffs`), and the right
response is a re-route, never a re-calibration (docs/FABRIC.md).

A :class:`CongestionProfile` is the congestion twin of
:class:`~adapcc_tpu.elastic.faults.FaultPlan`: a deterministic,
serializable schedule of :class:`CongestionWindow` entries — each naming
a shared link class (``ici`` | ``dcn``), a step range, and the bandwidth
contention factor — replayed by ``state-at-step`` folding so two runs of
the same profile see byte-identical contention timelines on any backend.

Injection points:

- the simulated replay (:func:`adapcc_tpu.sim.replay.
  simulate_congestion_profile`) prices every step's collective under that
  step's contended model — through the one ``simulate_strategy`` engine
  funnel, so at pod scale each distinct window re-prices the strategy's
  cached lowered columns (one β-vector swap per contended class) instead
  of re-lowering it (docs/SIMULATION.md §7);
- the adaptation controller's observation funnel
  (:meth:`adapcc_tpu.adapt.AdaptationController.tick`) feeds the drift
  detector contention-scaled priced samples, so the congestion-vs-
  degradation triage fires *deterministically* — the observation-funnel
  twin of the coordinator's fault-plan injection.

``ADAPCC_CONGESTION_PROFILE`` points at a JSON artifact through the SAME
shared funnel as ``ADAPCC_FAULT_PLAN``
(:func:`adapcc_tpu.utils.artifacts.load_env_json_artifact`): unset →
None, set-but-broken → loud, world mismatch → loud.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from adapcc_tpu.sim.cost_model import DCN, ICI

#: env var pointing at a congestion-profile JSON artifact
CONGESTION_PROFILE_ENV = "ADAPCC_CONGESTION_PROFILE"

#: link classes background traffic can contend; anything else is a loud
#: error, never a silent no-op
CONGESTION_CLASSES = (ICI, DCN)

#: default bandwidth-contention factor for seeded profiles: a neighbor
#: job taking 3/4 of the shared links' bandwidth (effective β × 4)
DEFAULT_CONGESTION_FACTOR = 4.0


@dataclass(frozen=True)
class CongestionWindow:
    """One bounded burst of background traffic: steps in
    ``[start, until)`` see the named link class's effective bandwidth cut
    by ``factor`` (β × factor — α is untouched, the congestion-vs-
    degradation signature the triage keys on)."""

    start: int
    until: int
    link_class: str
    factor: float = DEFAULT_CONGESTION_FACTOR

    def __post_init__(self) -> None:
        if self.link_class not in CONGESTION_CLASSES:
            raise ValueError(
                f"unknown congestion link class {self.link_class!r}; "
                f"expected one of {CONGESTION_CLASSES}"
            )
        if self.start < 0:
            raise ValueError(f"window start must be >= 0, got {self.start}")
        if self.until <= self.start:
            raise ValueError(
                f"window [{self.start}, {self.until}) is empty: 'until' "
                "must exceed 'start'"
            )
        if self.factor < 1.0:
            raise ValueError(
                f"congestion factor must be >= 1 (1 = no contention), got "
                f"{self.factor}"
            )

    def active_at(self, step: int) -> bool:
        return self.start <= step < self.until

    def to_dict(self) -> dict:
        return {
            "start": self.start,
            "until": self.until,
            "link_class": self.link_class,
            "factor": self.factor,
        }

    @classmethod
    def from_dict(cls, obj: Mapping) -> "CongestionWindow":
        return cls(
            start=int(obj["start"]),
            until=int(obj["until"]),
            link_class=str(obj["link_class"]),
            factor=float(obj.get("factor", DEFAULT_CONGESTION_FACTOR)),
        )


class CongestionProfile:
    """A deterministic, serializable schedule of congestion windows.

    ``world`` is the world size the profile was authored for; every
    consumer validates it against the runtime world (a profile's windows
    are priced against that world's topology — injecting one authored for
    another pod would contend the wrong links).
    """

    def __init__(
        self,
        windows: Sequence[CongestionWindow],
        world: int,
        label: str = "congestion-profile",
    ) -> None:
        if world < 1:
            raise ValueError(f"world must be >= 1, got {world}")
        self.world = world
        self.label = label
        self.windows: Tuple[CongestionWindow, ...] = tuple(
            sorted(windows, key=lambda w: (w.start, w.until, w.link_class))
        )

    # -- replay ----------------------------------------------------------------

    def active_at(self, step: int) -> List[CongestionWindow]:
        return [w for w in self.windows if w.active_at(step)]

    def factors_at(self, step: int) -> Dict[str, float]:
        """Per-class contention factor at one step.  Overlapping windows
        on the same class take the MAX factor (the hottest neighbor sets
        the share; stacking products would price phantom traffic) —
        deterministic either way."""
        factors: Dict[str, float] = {}
        for w in self.active_at(step):
            factors[w.link_class] = max(
                factors.get(w.link_class, 1.0), w.factor
            )
        return factors

    def healthy_at(self, step: int) -> bool:
        return not self.active_at(step)

    def contended_model(self, model, step: int):
        """The cost model this step's traffic actually offers: the given
        model with every active window's class contended
        (:meth:`LinkCostModel.contended` — β scaled, α intact)."""
        factors = self.factors_at(step)
        return model.contended(factors) if factors else model

    def last_step(self) -> int:
        return max((w.until for w in self.windows), default=0)

    def classes(self) -> Tuple[str, ...]:
        return tuple(sorted({w.link_class for w in self.windows}))

    # -- serialization ---------------------------------------------------------

    def to_dict(self) -> dict:
        return {
            "world": self.world,
            "label": self.label,
            "windows": [w.to_dict() for w in self.windows],
        }

    @classmethod
    def from_dict(cls, obj: Mapping) -> "CongestionProfile":
        return cls(
            windows=[
                CongestionWindow.from_dict(w) for w in obj.get("windows", ())
            ],
            world=int(obj["world"]),
            label=str(obj.get("label", "congestion-profile")),
        )

    def save(self, path: str) -> str:
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(self.to_dict(), f, indent=2, sort_keys=True)
        return path

    @classmethod
    def load(cls, path: str) -> "CongestionProfile":
        with open(path) as f:
            return cls.from_dict(json.load(f))

    # -- canned profiles -------------------------------------------------------

    @classmethod
    def seeded(
        cls,
        world: int,
        steps: int,
        seed: int = 0,
        n_windows: int = 2,
        classes: Sequence[str] = (DCN,),
        factor: float = DEFAULT_CONGESTION_FACTOR,
    ) -> "CongestionProfile":
        """Deterministic pseudo-random profile: ``n_windows`` bounded
        bursts at distinct steps, each a few steps long, cycling over
        ``classes``.  Same (world, steps, seed) → the same profile, byte
        for byte — the property every fabric-sweep row rides on."""
        if steps < 2:
            raise ValueError("a seeded congestion profile needs steps >= 2")
        bad = [c for c in classes if c not in CONGESTION_CLASSES]
        if bad:
            raise ValueError(
                f"unknown congestion classes {bad}; expected a subset of "
                f"{CONGESTION_CLASSES}"
            )
        rng = np.random.default_rng(seed)
        n_windows = max(1, min(n_windows, steps // 2))
        starts = sorted(
            int(s)
            for s in rng.choice(max(1, steps - 1), size=n_windows, replace=False)
        )
        windows = [
            CongestionWindow(
                start=start,
                until=min(steps, start + 2 + int(rng.integers(0, 3))),
                link_class=classes[i % len(classes)],
                factor=factor,
            )
            for i, start in enumerate(starts)
        ]
        return cls(windows, world, label=f"seeded:{seed}")

    def __repr__(self) -> str:
        return (
            f"CongestionProfile(world={self.world}, "
            f"windows={len(self.windows)}, label={self.label!r})"
        )


def load_congestion_profile(
    world: Optional[int] = None, env: Optional[Mapping[str, str]] = None
) -> Optional[CongestionProfile]:
    """The ``ADAPCC_CONGESTION_PROFILE`` funnel — the SAME shared loader
    as ``ADAPCC_FAULT_PLAN`` (:mod:`adapcc_tpu.utils.artifacts`): None
    when the env is unset; a set-but-broken value (missing file, garbage
    JSON, world mismatch) raises loudly, never a silently uncontended
    run."""
    from adapcc_tpu.utils.artifacts import load_env_json_artifact

    return load_env_json_artifact(
        CONGESTION_PROFILE_ENV,
        CongestionProfile.from_dict,
        kind="congestion-profile",
        world=world,
        env=env,
        mismatch_hint=(
            "injecting it as-is would contend another pod's link layout"
        ),
    )
