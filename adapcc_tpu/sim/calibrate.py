"""Fit the α-β model from real traces and persist it as a JSON artifact.

Two calibration sources, in preference order:

1. **Probe CSVs** — the profiler's ``topo_profile_*`` shards
   (``src,dst,type,value`` rows): two points per directed link give exact
   per-link (α, β).
2. **Hardware-battery JSONL** — ``benchmarks/results/hw_<tag>.jsonl`` rows
   from :mod:`benchmarks.hw_session`: busbw sweep rows carry
   ``(collective, world, size_bytes, time_us)``, and each collective's
   round/byte structure (ring algebra: allreduce = 2(w−1) serial hops
   carrying ``2(w−1)/w`` of the payload per link, …) turns the sweep into a
   linear system in (α, β).

The fitted coefficients persist to a versioned JSON artifact so later
hardware-free sessions stay anchored to the last good hardware round: a
dead tunnel changes *how* numbers are produced, not *what* they are
calibrated to.
"""

from __future__ import annotations

import json
import os
import sys
from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Optional, Tuple

import numpy as np

from adapcc_tpu.sim.cost_model import (
    DCN,
    ICI,
    LinkCoeffs,
    LinkCostModel,
    fit_alpha_beta,
)

CALIBRATION_VERSION = 1

#: where the bootstrap persists the artifact, beside the other topology
#: artifacts (ip_table, strategy.xml — docs/OPERATIONS.md §2)
DEFAULT_CALIBRATION_PATH = os.path.join("topology", "calibration.json")

#: serial round count and per-link byte fraction for the ring realization of
#: each collective: time ≈ rounds(w)·α + byte_factor(w)·size·β.  The byte
#: factors match the nccl-tests busbw corrections (benchmarks/collectives.py
#: BUS_FACTORS); the round counts are the matching ring-schedule depths.
_RING_STRUCTURE = {
    "allreduce": (lambda w: 2 * (w - 1), lambda w: 2 * (w - 1) / w),
    "reduce_scatter": (lambda w: w - 1, lambda w: (w - 1) / w),
    "all_gather": (lambda w: w - 1, lambda w: (w - 1) / w),
    "all_to_all": (lambda w: w - 1, lambda w: (w - 1) / w),
    "broadcast": (lambda w: w - 1, lambda w: 1.0),
    "reduce": (lambda w: w - 1, lambda w: 1.0),
}


@dataclass
class Calibration:
    """Serializable α-β calibration: class coefficients + optional per-link
    table, stamped with provenance.

    Hygiene stamps (docs/ADAPT.md §3): ``fingerprint`` is the topology
    fingerprint the coefficients were fitted on (a calibration from one
    fabric must not silently price another — :func:`load_or_default` warns
    loudly on a mismatch), ``samples`` counts the measurements behind the
    fit (the decay weight :func:`merge_calibration` blends by), and
    ``provenance`` chains the merge history so an artifact always says how
    it came to hold its numbers.  All three default empty, so pre-stamp
    artifacts load unchanged.
    """

    world: int
    classes: Dict[str, LinkCoeffs]
    links: Dict[Tuple[int, int], LinkCoeffs] = field(default_factory=dict)
    ips: Optional[Dict[int, str]] = None
    source: str = "unspecified"
    version: int = CALIBRATION_VERSION
    #: topology fingerprint (adapcc_tpu.tuner.db.topology_fingerprint) the
    #: fit was taken on; None = unstamped (legacy artifact)
    fingerprint: Optional[str] = None
    #: measurements behind the fit — the weight re-calibration merges by
    samples: int = 0
    #: bounded merge-history chain, newest last
    provenance: Optional[List[str]] = None

    # -- model -----------------------------------------------------------------

    def cost_model(self) -> LinkCostModel:
        return LinkCostModel(
            self.world,
            links=self.links,
            classes=self.classes,
            ips=self.ips,
            source=self.source,
        )

    # -- (de)serialization -----------------------------------------------------

    def to_dict(self) -> dict:
        return {
            "version": self.version,
            "source": self.source,
            "world": self.world,
            "classes": {
                cls: {"alpha": c.alpha, "beta": c.beta}
                for cls, c in self.classes.items()
            },
            "links": [
                {"src": s, "dst": d, "alpha": c.alpha, "beta": c.beta}
                for (s, d), c in sorted(self.links.items())
            ],
            "ips": {str(r): ip for r, ip in (self.ips or {}).items()} or None,
            "fingerprint": self.fingerprint,
            "samples": int(self.samples),
            "provenance": list(self.provenance) if self.provenance else None,
        }

    @classmethod
    def from_dict(cls, obj: Mapping) -> "Calibration":
        version = int(obj.get("version", 0))
        if version != CALIBRATION_VERSION:
            raise ValueError(
                f"calibration artifact version {version} != supported "
                f"{CALIBRATION_VERSION}; re-calibrate from traces"
            )
        classes = {
            name: LinkCoeffs(float(c["alpha"]), float(c["beta"]))
            for name, c in (obj.get("classes") or {}).items()
        }
        links = {
            (int(l["src"]), int(l["dst"])): LinkCoeffs(
                float(l["alpha"]), float(l["beta"])
            )
            for l in (obj.get("links") or [])
        }
        ips_raw = obj.get("ips")
        ips = {int(r): ip for r, ip in ips_raw.items()} if ips_raw else None
        prov = obj.get("provenance")
        return cls(
            world=int(obj["world"]),
            classes=classes,
            links=links,
            ips=ips,
            source=str(obj.get("source", "unspecified")),
            version=version,
            fingerprint=(
                str(obj["fingerprint"]) if obj.get("fingerprint") else None
            ),
            samples=int(obj.get("samples") or 0),
            provenance=[str(p) for p in prov] if prov else None,
        )

    def save(self, path: str) -> str:
        """Atomic write (tmp + rename), the checkpoint.py artifact rule."""
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(self.to_dict(), f, indent=1, sort_keys=True)
        os.rename(tmp, path)
        return path

    @classmethod
    def load(cls, path: str) -> "Calibration":
        with open(path) as f:
            return cls.from_dict(json.load(f))


def _from_model(model: LinkCostModel) -> Calibration:
    return Calibration(
        world=model.world,
        classes=dict(model.classes),
        links=dict(model.links),
        ips=model.ips,
        source=model.source,
    )


def calibrate_from_matrices(
    lat: np.ndarray,
    bw: np.ndarray,
    ips: Optional[Mapping[int, str]] = None,
    source: str = "matrices",
) -> Calibration:
    """Per-link fit from the profiler's latency [s] / bandwidth [GB/s]
    matrices (in-memory variant of the CSV path)."""
    return _from_model(LinkCostModel.from_matrices(lat, bw, ips, source=source))


def calibrate_from_profile_dir(
    topology_dir: str, world: int, ips: Optional[Mapping[int, str]] = None
) -> Calibration:
    """Per-link fit from on-disk ``topo_profile_*`` CSV shards."""
    return _from_model(
        LinkCostModel.from_topo_profile_dir(topology_dir, world, ips)
    )


def _battery_rows(jsonl_path: str) -> List[dict]:
    """Collective-sweep rows inside a battery artifact: rows lists from
    sweep phases, plus any single parsed row shaped like a BenchResult."""
    rows: List[dict] = []
    with open(jsonl_path) as f:
        for line in f:
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            candidates = list(rec.get("rows") or [])
            if not candidates and isinstance(rec.get("parsed"), dict):
                # "parsed" duplicates rows[-1] when a rows list exists
                # (hw_session._run keeps both) — counting it again would
                # double-weight the largest sweep size in the lstsq fit
                candidates.append(rec["parsed"])
            for row in candidates:
                if (
                    isinstance(row, dict)
                    and row.get("collective") in _RING_STRUCTURE
                    and row.get("time_us")
                    and row.get("size_bytes")
                    and int(row.get("world", 0)) >= 2
                ):
                    rows.append(row)
    return rows


def calibrate_from_battery(
    jsonl_path: str, impls: Tuple[str, ...] = ("xla", "pallas_ring")
) -> Optional[Calibration]:
    """Fit one (α, β) pair from a committed hardware-battery artifact.

    Only baseline impls are used by default — strategy-schedule rows measure
    the *schedule under test*, not the wire, and folding them in would
    calibrate the model to its own prediction target.  Returns ``None`` when
    the artifact holds no usable sweep rows (e.g. the busbw phase timed out),
    so callers fall through to the next calibration source.
    """
    rows = [r for r in _battery_rows(jsonl_path) if r.get("impl") in impls]
    if len(rows) < 2:
        return None
    a = []
    y = []
    for r in rows:
        w = int(r["world"])
        rounds_fn, byte_fn = _RING_STRUCTURE[r["collective"]]
        a.append([float(rounds_fn(w)), byte_fn(w) * float(r["size_bytes"])])
        y.append(float(r["time_us"]) * 1e-6)
    if np.linalg.matrix_rank(np.array(a)) < 2:
        # a rank-deficient design (e.g. every row proportional) cannot
        # separate α from β — lstsq would return a minimum-norm fantasy
        return None
    (alpha, beta), *_ = np.linalg.lstsq(np.array(a), np.array(y), rcond=None)
    coeffs = LinkCoeffs(alpha=max(0.0, float(alpha)), beta=max(0.0, float(beta)))
    world = max(int(r["world"]) for r in rows)
    return Calibration(
        world=world,
        classes={ICI: coeffs, DCN: LinkCoeffs(*_dcn_guess(coeffs))},
        source=f"battery:{os.path.basename(jsonl_path)}",
    )


def _dcn_guess(ici: LinkCoeffs) -> Tuple[float, float]:
    """A battery sweep on one slice says nothing about DCN; scale the ICI
    fit by the default class ratio so cross-host edges stay priced worse."""
    from adapcc_tpu.sim.cost_model import DEFAULT_COEFFS

    a_ratio = DEFAULT_COEFFS[DCN][0] / DEFAULT_COEFFS[ICI][0]
    b_ratio = DEFAULT_COEFFS[DCN][1] / DEFAULT_COEFFS[ICI][1]
    return ici.alpha * a_ratio, ici.beta * b_ratio


#: merge-history entries retained on a calibration artifact — enough to
#: audit a long re-calibration chain without growing the file unboundedly
MAX_PROVENANCE = 8


def merge_calibration(
    base: Calibration, update: Calibration, decay: float = 0.5
) -> Calibration:
    """Fold a re-calibration into an existing artifact WITH decay — the
    fix for last-writer-wins (docs/ADAPT.md §3).

    Coefficients blend per class (and per link) by sample-count weight:
    the update enters at its own ``samples``, the base is discounted by
    ``decay`` (an unstamped base borrows the update's weight, so a legacy
    artifact still decays instead of being overwritten).  Classes/links
    only one side knows survive unchanged — a correction that localized to
    one link class must not reset the others.  The merged artifact keeps
    the sample accounting and a bounded provenance chain, so the next
    merge decays THIS merge in turn.
    """
    if base.world != update.world:
        raise ValueError(
            f"cannot merge calibrations across worlds "
            f"({base.world} vs {update.world}); re-calibrate for this world"
        )
    if (
        base.fingerprint is not None
        and update.fingerprint is not None
        and base.fingerprint != update.fingerprint
    ):
        # blending two fabrics' fits and stamping the chimera with one
        # fingerprint would make every FUTURE load trust it silently —
        # the exact hygiene hole the stamps exist to close.  Callers with
        # a stale artifact start a fresh base instead.
        raise ValueError(
            f"cannot merge calibrations across fabrics (base fitted on "
            f"{base.fingerprint!r}, update on {update.fingerprint!r}); "
            "seed a fresh artifact for this fabric instead"
        )
    if not 0.0 <= decay <= 1.0:
        raise ValueError(f"decay must be in [0, 1], got {decay}")
    w_new = float(max(1, update.samples))
    w_old = decay * float(base.samples if base.samples > 0 else w_new)

    def blend(old: LinkCoeffs, new: LinkCoeffs) -> LinkCoeffs:
        if w_old + w_new <= 0:
            return new
        return LinkCoeffs(
            alpha=(w_old * old.alpha + w_new * new.alpha) / (w_old + w_new),
            beta=(w_old * old.beta + w_new * new.beta) / (w_old + w_new),
        )

    classes = dict(base.classes)
    for cls_name, c in update.classes.items():
        classes[cls_name] = (
            blend(base.classes[cls_name], c) if cls_name in base.classes else c
        )
    links = dict(base.links)
    for link, c in update.links.items():
        links[link] = blend(base.links[link], c) if link in base.links else c
    provenance = list(base.provenance or [])
    if not provenance and base.source:
        provenance.append(base.source)
    provenance.append(update.source)
    return Calibration(
        world=base.world,
        classes=classes,
        links=links,
        ips=update.ips if update.ips is not None else base.ips,
        source=f"merged:{update.source}",
        fingerprint=update.fingerprint or base.fingerprint,
        samples=int(round(w_old + w_new)),
        provenance=provenance[-MAX_PROVENANCE:],
    )


def load_calibration(path: str = DEFAULT_CALIBRATION_PATH) -> LinkCostModel:
    """Artifact → ready-to-use cost model (raises if absent/incompatible)."""
    return Calibration.load(path).cost_model()


def _stamp_warning(what: str) -> None:
    print(f"[sim] calibration WARNING: {what}", file=sys.stderr, flush=True)


def load_or_default(
    path: str = DEFAULT_CALIBRATION_PATH,
    world: Optional[int] = None,
    fingerprint: Optional[str] = None,
) -> LinkCostModel:
    """Artifact if present, else the synthetic defaults — the simulated
    bench's entry point, which must produce numbers either way.

    ``fingerprint`` (when given) is checked against the artifact's stamp:
    a calibration fitted on another fabric still *loads* — class-level
    coefficients transfer better than nothing — but the mismatch is
    reported LOUDLY, as is a world-size resize, so a stale artifact can
    never silently price a different pod (docs/ADAPT.md §3)."""
    try:
        cal = Calibration.load(path)
        # build the model INSIDE the fallback guard: an artifact that
        # parses but carries unusable values (world: 0, ...) must fall
        # back too — this entry point produces numbers either way
        model = cal.cost_model()
    except (OSError, ValueError, KeyError, TypeError) as e:
        # unreadable OR structurally malformed (hand-edited / partial tool /
        # version-gated) artifacts all fall back — this entry point must
        # produce numbers.  But an artifact that EXISTS and still failed is
        # a silently-discarded calibration: say so, or sim-rank quietly
        # commits to strategies priced on synthetic defaults
        if os.path.exists(path):
            print(
                f"[sim] calibration artifact {path} unusable "
                f"({type(e).__name__}: {e}); pricing with synthetic defaults",
                file=sys.stderr,
                flush=True,
            )
        return LinkCostModel.uniform(world or 8, source="defaults")
    if (
        fingerprint is not None
        and cal.fingerprint is not None
        and cal.fingerprint != fingerprint
    ):
        _stamp_warning(
            f"{path} was fitted on fabric {cal.fingerprint!r} but this "
            f"world's fingerprint is {fingerprint!r}; class coefficients "
            "still price the sweep, but re-calibrate before trusting a "
            "ranking on them"
        )
    if world is not None and world != model.world:
        _stamp_warning(
            f"{path} was fitted at world={model.world}, loading for "
            f"world={world}; per-link fits outside the new range fall back "
            "to class means"
        )
        # a calibration from another world still prices links by class —
        # keeping the recorded host layout when it covers the new rank
        # range, so cross-host edges stay classed DCN after the resize
        ips = None
        if model.ips and all(r in model.ips for r in range(world)):
            ips = {r: model.ips[r] for r in range(world)}
        return LinkCostModel(
            world,
            # in-range per-link fits survive the shrink; out-of-range links
            # (and a grown world's new links) fall back to class means
            links={
                (s, d): c
                for (s, d), c in model.links.items()
                if s < world and d < world
            },
            classes=model.classes,
            ips=ips,
            source=model.source + f"@world{world}",
        )
    return model
