"""Discrete-event replay of schedule-IR communication rounds.

Replays the same round lists the compiled engine executes
(:meth:`Tree.reduce_rounds` / :meth:`Tree.broadcast_rounds`, relay-pruned
variants from :mod:`adapcc_tpu.comm.relay`, flow-LP lowerings) against a
:class:`~adapcc_tpu.sim.cost_model.LinkCostModel`, producing a predicted
timeline instead of moving bytes.

Modeled resources and constraints:

- **data dependencies** — an edge ``(s → d)`` in round ``r`` starts only
  once ``s`` holds that chunk's data (delivered by earlier rounds; round
  lists are dependency-ordered by construction, ``ir._pack_rounds``);
- **link contention** — transfers sharing a directed link serialize (the
  physical wire is busy);
- **port contention** — a rank sends at most one transfer at a time and
  receives at most one at a time (each ``CommRound`` is a partial
  permutation, so contention arises only *across* rounds, chunks, and
  trees — exactly where the engine's merged-round coloring overlaps work);
- **chunk pipelining** — each tree's payload splits into ``chunk_bytes``
  chunks that flow through the rounds independently (the reference's
  per-chunk recv→reduce→send pipeline, allreduce.cu:628-646), so chunk
  ``c+1`` rides round ``r`` while chunk ``c`` is in round ``r+1``;
- **merged-tree round coloring** — round ``r`` of every tree shares one
  color, mirroring the engine's merged multi-tree executor: parallel trees
  progress in lockstep colors and contend for shared links.

Events are processed color-major / chunk-minor, which is a valid
topological order of the dependency DAG: every transfer's inputs are
already placed when it is priced, so greedy resource assignment yields
consistent (if FIFO-tie-broken) timestamps without a full event queue.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from adapcc_tpu.strategy.ir import CommRound
from adapcc_tpu.sim.cost_model import Link, LinkCostModel


@dataclass(frozen=True)
class Transfer:
    """One simulated point-to-point send."""

    tree: int
    round_idx: int
    chunk: int
    src: int
    dst: int
    nbytes: float
    start: float
    finish: float


@dataclass
class TreeSchedule:
    """One tree's dependency-ordered rounds plus the payload they carry."""

    rounds: List[CommRound]
    nbytes: float
    chunk_bytes: float = 4 * 1024 * 1024
    label: str = ""

    def num_chunks(self) -> int:
        if self.nbytes <= 0 or self.chunk_bytes <= 0:
            return 1
        return max(1, int(-(-self.nbytes // self.chunk_bytes)))


@dataclass
class SimReport:
    """Replay output: makespan + the full transfer timeline.

    At pod scale the per-link map (``link_busy``, O(world) entries per
    timeline) and the per-transfer log are opt-in; ``class_busy`` — busy
    seconds aggregated per ICI/DCN link class, O(#classes) — is the
    always-on accounting surface a 100k-rank ranking can afford to hold
    per candidate.
    """

    makespan: float
    transfers: List[Transfer] = field(default_factory=list)
    link_busy: Dict[Link, float] = field(default_factory=dict)
    #: busy seconds aggregated per link class (always bounded: one entry
    #: per class in use, never per link)
    class_busy: Dict[str, float] = field(default_factory=dict)

    def utilization(self) -> Dict[Link, float]:
        """Busy fraction per directed link over the makespan (empty when
        the replay ran with the per-link map opted out)."""
        if self.makespan <= 0:
            return {link: 0.0 for link in self.link_busy}
        return {
            link: busy / self.makespan for link, busy in self.link_busy.items()
        }

    def class_utilization(self) -> Dict[str, float]:
        """Aggregate busy seconds per link class over the makespan — the
        world-size-independent utilization surface.  Note this sums busy
        time across every link of the class, so values exceed 1.0 as soon
        as the class has concurrent links (it is a parallelism measure,
        not a single-wire fraction)."""
        if self.makespan <= 0:
            return {cls: 0.0 for cls in self.class_busy}
        return {
            cls: busy / self.makespan for cls, busy in self.class_busy.items()
        }

    def bytes_moved(self) -> float:
        return sum(t.nbytes for t in self.transfers)


class EventSimulator:
    """Replays :class:`TreeSchedule` lists against a link cost model."""

    def __init__(
        self,
        cost_model: LinkCostModel,
        keep_transfers: bool = True,
        keep_links: bool = True,
    ):
        self.cost_model = cost_model
        #: pod-scale rankings don't need the per-transfer log; dropping it
        #: keeps a 1000-tree × 1000-chunk replay in constant memory
        self.keep_transfers = keep_transfers
        #: the per-link busy map is O(world) per report; opting out leaves
        #: only the per-class aggregation in the returned SimReport
        self.keep_links = keep_links

    def run(self, schedules: Sequence[TreeSchedule]) -> SimReport:
        link_free: Dict[Link, float] = {}
        egress_free: Dict[int, float] = {}
        ingress_free: Dict[int, float] = {}
        link_busy: Dict[Link, float] = {}
        transfers: List[Transfer] = []
        makespan = 0.0

        # per (tree, chunk): rank → time at which the rank holds this
        # chunk's current partial value
        ready: List[List[Dict[int, float]]] = [
            [dict() for _ in range(s.num_chunks())] for s in schedules
        ]
        chunk_sizes = [
            s.nbytes / s.num_chunks() if s.num_chunks() else 0.0
            for s in schedules
        ]

        colors = max((len(s.rounds) for s in schedules), default=0)
        for color in range(colors):
            for t, sched in enumerate(schedules):
                if color >= len(sched.rounds):
                    continue
                rnd = sched.rounds[color]
                for chunk in range(sched.num_chunks()):
                    chunk_ready = ready[t][chunk]
                    for src, dst in rnd.edges:
                        start = max(
                            chunk_ready.get(src, 0.0),
                            link_free.get((src, dst), 0.0),
                            egress_free.get(src, 0.0),
                            ingress_free.get(dst, 0.0),
                        )
                        dur = self.cost_model.time_for(
                            src, dst, chunk_sizes[t]
                        )
                        finish = start + dur
                        link_free[(src, dst)] = finish
                        egress_free[src] = finish
                        ingress_free[dst] = finish
                        link_busy[(src, dst)] = (
                            link_busy.get((src, dst), 0.0) + dur
                        )
                        chunk_ready[dst] = max(chunk_ready.get(dst, 0.0), finish)
                        makespan = max(makespan, finish)
                        if self.keep_transfers:
                            transfers.append(
                                Transfer(
                                    tree=t,
                                    round_idx=color,
                                    chunk=chunk,
                                    src=src,
                                    dst=dst,
                                    nbytes=chunk_sizes[t],
                                    start=start,
                                    finish=finish,
                                )
                            )
        class_busy: Dict[str, float] = {}
        for (src, dst), busy in link_busy.items():
            cls = self.cost_model.link_class_of(src, dst)
            class_busy[cls] = class_busy.get(cls, 0.0) + busy
        return SimReport(
            makespan=makespan,
            transfers=transfers,
            link_busy=link_busy if self.keep_links else {},
            class_busy=class_busy,
        )
