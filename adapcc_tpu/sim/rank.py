"""Strategy ranking and degradation prediction on the simulated timeline.

The synthesizer's job is to pick a schedule *before* committing compiled
programs to it; with the hardware tunnel dead there is nothing to measure,
so candidates are ranked on the calibrated α-β replay instead — the TACCL /
SCCL offline-ranking move, wired to this repo's strategy IR.

Two prediction surfaces ride along:

- :func:`relay_latency` — the collective's cost under a relay mask (inactive
  ranks demoted to forwarders, dead edges pruned).  Shrinking the active set
  prunes a *subset* of edges, so predicted latency is monotonically
  non-increasing in mask size — the property the relay controller relies on
  when it decides that demoting a straggler can only help the collective.
- :func:`predict_degradation` — the straggler scenario: links touching slow
  ranks stretched by a slowdown factor, reported as a ratio to the healthy
  baseline.  The rent-or-buy coordinator compares this against the relay
  speed-up to choose demote-vs-wait.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterable, List, Optional, Sequence, Tuple, Union

from adapcc_tpu.sim.cost_model import (
    LinkCostModel,
    collective_lower_bound,
    optimality_gap,
)
from adapcc_tpu.sim.replay import SimTimeline, simulate_strategy
from adapcc_tpu.strategy.ir import Strategy

#: a candidate is a Strategy, a (label, Strategy) pair, or a (label,
#: SimTimeline) pair for schedules simulated through another adapter
#: (e.g. a flow-LP lowering)
Candidate = Union[Strategy, Tuple[str, Strategy], Tuple[str, SimTimeline]]


@dataclass
class RankedCandidate:
    label: str
    seconds: float
    strategy: Optional[Strategy]
    timeline: SimTimeline
    #: certified topology floor for this (collective, payload, participant
    #: set) and the candidate's distance above it — ``seconds/LB − 1``,
    #: non-negative whenever the bound holds (regression-pinned)
    lower_bound_s: Optional[float] = None
    optimality_gap: Optional[float] = None

    def to_row(self) -> dict:
        row = self.timeline.to_row()
        row["label"] = self.label
        if self.optimality_gap is not None:
            row["optimality_gap"] = round(self.optimality_gap, 6)
            row["lower_bound_us"] = round((self.lower_bound_s or 0.0) * 1e6, 3)
        return row


def _as_labeled(item: Candidate, index: int) -> Tuple[str, object]:
    if isinstance(item, Strategy):
        return f"{item.synthesis or 'candidate'}#{index}", item
    label, obj = item
    return label, obj


def rank_candidates(
    candidates: Sequence[Candidate],
    cost_model: LinkCostModel,
    nbytes: float,
    collective: str = "allreduce",
    active: Optional[Iterable[int]] = None,
    engine: Optional[str] = None,
) -> List[RankedCandidate]:
    """Simulate every candidate and return them fastest-first, each
    stamped with its certified ``optimality_gap`` against the topology's
    latency+bandwidth lower bound (SCCL's certification move: the ranking
    says how far from *optimal* the winner is, not just that it beat the
    pool).

    Ties break by input order (stable sort), so a caller listing its
    incumbent first keeps it on a tie — re-synthesis must not churn the
    compiled-program cache for a prediction-identical alternative.
    """
    if not candidates:
        raise ValueError("need at least one candidate to rank")
    active_list = list(active) if active is not None else None
    lower_cache: dict = {}
    out: List[RankedCandidate] = []
    for i, item in enumerate(candidates):
        label, obj = _as_labeled(item, i)
        if isinstance(obj, SimTimeline):
            timeline, strategy = obj, None
        else:
            timeline = simulate_strategy(
                obj, cost_model, nbytes, collective, active=active_list,
                keep_transfers=False, engine=engine,
            )
            strategy = obj
        # relay masks shrink the participant set: the floor certifies the
        # collective actually priced (p = |active|), not the full world
        p_eff = len(active_list) if active_list is not None else timeline.world
        lower = lower_cache.get(p_eff)
        if lower is None:
            lower = collective_lower_bound(
                cost_model, nbytes, collective, world=p_eff
            )
            lower_cache[p_eff] = lower
        out.append(
            RankedCandidate(
                label=label,
                seconds=timeline.seconds,
                strategy=strategy,
                timeline=timeline,
                lower_bound_s=lower,
                optimality_gap=optimality_gap(timeline.seconds, lower),
            )
        )
    out.sort(key=lambda c: c.seconds)
    return out


def relay_latency(
    strategy: Strategy,
    cost_model: LinkCostModel,
    nbytes: float,
    active: Iterable[int],
    collective: str = "allreduce",
    engine: Optional[str] = None,
) -> float:
    """Predicted latency with only ``active`` ranks contributing (everyone
    else a forwarding relay; dead edges pruned as the engine prunes them)."""
    return simulate_strategy(
        strategy, cost_model, nbytes, collective, active=active,
        keep_transfers=False, engine=engine,
    ).seconds


@dataclass
class DegradationReport:
    """Healthy vs degraded prediction for one straggler scenario."""

    healthy_seconds: float
    degraded_seconds: float
    #: latency with the slow ranks demoted to relays under the SAME degraded
    #: links — what the relay controller would actually run
    relay_seconds: float
    slow_ranks: Tuple[int, ...]
    slowdown: float

    @property
    def ratio(self) -> float:
        """Degraded / healthy; ≥ 1 by construction (slowdown ≥ 1)."""
        if self.healthy_seconds <= 0:
            return 1.0
        return self.degraded_seconds / self.healthy_seconds

    @property
    def relay_gain(self) -> float:
        """Degraded / relay-masked: >1 means demoting the stragglers is
        predicted to pay."""
        if self.relay_seconds <= 0:
            return 1.0
        return self.degraded_seconds / self.relay_seconds


def predict_degradation(
    strategy: Strategy,
    cost_model: LinkCostModel,
    nbytes: float,
    slow_ranks: Sequence[int],
    slowdown: float = 4.0,
    collective: str = "allreduce",
    engine: Optional[str] = None,
) -> DegradationReport:
    """Price a straggler scenario: every link touching a slow rank is
    ``slowdown``× more expensive.  Returns healthy, degraded, and
    degraded-with-relay-mask predictions — the three numbers the rent-or-buy
    decision needs."""
    degraded_model = cost_model.degraded(slow_ranks, slowdown)
    healthy = simulate_strategy(
        strategy, cost_model, nbytes, collective, keep_transfers=False,
        engine=engine,
    ).seconds
    degraded = simulate_strategy(
        strategy, degraded_model, nbytes, collective, keep_transfers=False,
        engine=engine,
    ).seconds
    active = sorted(set(range(strategy.world_size)) - set(slow_ranks))
    relay = simulate_strategy(
        strategy, degraded_model, nbytes, collective, active=active,
        keep_transfers=False, engine=engine,
    ).seconds
    return DegradationReport(
        healthy_seconds=healthy,
        degraded_seconds=degraded,
        relay_seconds=relay,
        slow_ranks=tuple(slow_ranks),
        slowdown=slowdown,
    )
