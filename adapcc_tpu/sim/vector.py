"""Vectorized round-synchronous replay: the pod-scale fast path.

The discrete-event oracle (:class:`adapcc_tpu.sim.events.EventSimulator`)
places one transfer per Python loop iteration — exact, but O(colors ×
trees × chunks × edges) interpreter work, which caps it at worlds of a
few hundred.  This module replays the SAME greedy placement as numpy
array algebra over per-round (src, dst, link, link-class) columns, so a
world=131072 strategy prices in seconds instead of hours.

Why the algebra is exact (not an approximation): within one lowered
round the edges form a *matching* — ``ir._pack_rounds`` packs
dependency-ordered edges so that per round, sources are distinct,
destinations are distinct, and no rank both sends and receives (an edge
out of a rank is always packed strictly after every edge into it).
Under the event simulator's resource model (per-link, per-egress-port,
per-ingress-port free times plus per-(tree, chunk) readiness), matched
edges never read a resource another edge in the same batch wrote, so a
whole round column places in one ``np.maximum`` chain — bitwise equal
to the sequential loop, because ``max`` is order-independent and the
single ``start + dur`` addition is the same operation.  Rounds that are
NOT matchings (hand-built ``CommRound``s, foreign lowerings) fall back
to exact sequential *waves* within the same engine — never a silent
approximation.

Two caches make re-pricing incremental (the hot loop of
``adapt/controller.py`` re-ranks, ``sim/congestion.py`` window replays,
and ``StandbyPlanCache`` scenario sweeps):

- **structure** — the lowered columns are cached per (strategy
  fingerprint, chunking spec, collective, relay mask), so pricing a
  strategy under a drifted/contended/degraded model never re-lowers
  trees or re-prunes relay masks;
- **class membership** — each column's ICI/DCN split is a cached host-id
  comparison, so a correction that touches one link class re-prices as
  one ``np.where`` over the affected columns (β vector swap), not a
  per-edge Python walk.  Per-link overrides (degraded links, per-link
  calibration fits) patch the class vectors sparsely.

Engine selection is funneled through ``ADAPCC_SIM_ENGINE``
(``auto`` | ``event`` | ``vector``; malformed values are a loud error,
docs/OPERATIONS.md §1).  ``auto`` — the default — keeps small worlds on
the event oracle and switches to this path at
:data:`VECTOR_MIN_WORLD` ranks.
"""

from __future__ import annotations

import os
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, Iterable, List, Optional, Tuple

import numpy as np

from adapcc_tpu.sim.cost_model import DCN, ICI, Link, LinkCostModel
from adapcc_tpu.sim.events import SimReport
from adapcc_tpu.strategy.ir import Strategy

#: env knob selecting the replay engine; malformed → loud ValueError
SIM_ENGINE_ENV = "ADAPCC_SIM_ENGINE"

#: the engines ``ADAPCC_SIM_ENGINE`` (and the ``engine=`` kwargs) accept
SIM_ENGINES = ("auto", "event", "vector")

#: ``auto`` switches from the event oracle to the vectorized path at this
#: world size — below it the per-transfer loop is already sub-millisecond
#: and keeps its per-transfer log; above it interpreter overhead dominates
VECTOR_MIN_WORLD = 256


def resolve_sim_engine(engine: Optional[str], world: int) -> str:
    """``engine`` arg > ``ADAPCC_SIM_ENGINE`` env > ``auto``; returns the
    concrete engine (``"event"`` or ``"vector"``), never ``"auto"``."""
    raw = engine
    if raw is None:
        raw = os.environ.get(SIM_ENGINE_ENV, "").strip() or "auto"
    choice = str(raw).strip().lower()
    if choice not in SIM_ENGINES:
        raise ValueError(
            f"the {SIM_ENGINE_ENV} replay engine must be one of "
            f"{SIM_ENGINES}, got {raw!r}"
        )
    if choice == "auto":
        return "vector" if world >= VECTOR_MIN_WORLD else "event"
    return choice


# --------------------------------------------------------------------------- #
# lowered column structure
# --------------------------------------------------------------------------- #


@dataclass
class RoundCols:
    """One lowered round as columns: parallel (src, dst, link-id) arrays."""

    srcs: np.ndarray  # int64 (E,)
    dsts: np.ndarray  # int64 (E,)
    eidx: np.ndarray  # int64 (E,) — indices into the structure's link table
    #: True when the round is a matching (distinct srcs, distinct dsts,
    #: no rank both sends and receives) — the batched placement is exact
    matching: bool
    #: exact sequential fallback for non-matching rounds: index arrays
    #: into the round's columns, each wave internally conflict-free
    waves: Optional[List[np.ndarray]] = None


@dataclass
class TreeCols:
    """One tree's lowered rounds plus its share of the payload."""

    rounds: List[RoundCols]
    share: float
    chunk_bytes: float
    label: str = ""


class _LinkTable:
    """Shared link-table behavior for column structures: the directed-link
    vocabulary plus the cached rank → host-id vectors the class-membership
    pricing uses.  Subclasses provide ``world``, ``link_srcs``,
    ``link_dsts``, ``link_pos`` and a ``_host_ids`` OrderedDict field."""

    @property
    def num_links(self) -> int:
        return len(self.link_srcs)

    def host_ids(self, ips: Optional[Dict[int, str]]) -> Optional[np.ndarray]:
        """Rank → integer host id under ``ips`` (None → one flat domain),
        cached per ip-table object: the class-membership half of a pricing
        never recomputes across re-prices under the same layout."""
        if ips is None:
            return None
        key = id(ips)
        hit = self._host_ids.get(key)
        if hit is not None and hit[0] is ips:
            self._host_ids.move_to_end(key)
            return hit[1]
        token: Dict[object, int] = {}
        out = np.empty(self.world, dtype=np.int64)
        for r in range(self.world):
            ip = ips.get(r)
            out[r] = token.setdefault(ip, len(token))
        self._host_ids[key] = (ips, out)
        while len(self._host_ids) > 8:
            self._host_ids.popitem(last=False)
        return out


@dataclass
class LoweredColumns(_LinkTable):
    """A strategy lowered once into numpy columns, re-priced many times."""

    world: int
    trees: List[TreeCols]
    #: global directed-link table: link ``i`` is (link_srcs[i], link_dsts[i])
    link_srcs: np.ndarray
    link_dsts: np.ndarray
    link_pos: Dict[Link, int]
    strategy_label: str = ""
    #: per-ips-table host-id vectors, keyed by ``id(ips)`` with a strong
    #: reference to the keyed object so the id can never be recycled
    _host_ids: "OrderedDict[int, Tuple[object, np.ndarray]]" = field(
        default_factory=OrderedDict, repr=False
    )


def _split_waves(
    srcs: np.ndarray, dsts: np.ndarray
) -> List[np.ndarray]:
    """Split a non-matching round into sequential, internally conflict-free
    waves, preserving edge order.  Edge ``j`` must start a new wave when it
    READS state an earlier edge in the wave WROTE: its src in the wave's
    srcs (egress) or dsts (readiness/ingress chains), or its dst in the
    wave's dsts (ingress)."""
    waves: List[List[int]] = []
    wave_srcs: set = set()
    wave_dsts: set = set()
    for j, (s, d) in enumerate(zip(srcs.tolist(), dsts.tolist())):
        if not waves or s in wave_srcs or s in wave_dsts or d in wave_dsts:
            waves.append([])
            wave_srcs, wave_dsts = set(), set()
        waves[-1].append(j)
        wave_srcs.add(s)
        wave_dsts.add(d)
    return [np.asarray(w, dtype=np.int64) for w in waves]


def lower_columns(
    strategy: Strategy,
    collective: str = "allreduce",
    active: Optional[Iterable[int]] = None,
) -> LoweredColumns:
    """Lower a strategy (relay-pruned under ``active``) into column arrays.

    Uncached — callers on a re-pricing loop want :func:`lowered_columns`.
    """
    from adapcc_tpu.sim.replay import _tree_rounds  # deferred: replay imports us

    act = frozenset(active) if active is not None else None
    link_pos: Dict[Link, int] = {}
    trees: List[TreeCols] = []
    for i, (tree, share) in enumerate(
        zip(strategy.trees, strategy.tree_shares())
    ):
        rounds: List[RoundCols] = []
        for rnd in _tree_rounds(tree, collective, act):
            if not rnd.edges:
                continue
            srcs = np.fromiter((e[0] for e in rnd.edges), dtype=np.int64)
            dsts = np.fromiter((e[1] for e in rnd.edges), dtype=np.int64)
            eidx = np.fromiter(
                (
                    link_pos.setdefault((int(s), int(d)), len(link_pos))
                    for s, d in rnd.edges
                ),
                dtype=np.int64,
            )
            sset, dset = set(srcs.tolist()), set(dsts.tolist())
            matching = (
                len(sset) == len(srcs)
                and len(dset) == len(dsts)
                and not (sset & dset)
            )
            rounds.append(
                RoundCols(
                    srcs=srcs,
                    dsts=dsts,
                    eidx=eidx,
                    matching=matching,
                    waves=None if matching else _split_waves(srcs, dsts),
                )
            )
        trees.append(
            TreeCols(
                rounds=rounds,
                share=share,
                chunk_bytes=float(strategy.chunk_bytes_for_tree(i)),
                label=f"tree@{tree.root}",
            )
        )
    link_srcs = np.fromiter((s for s, _ in link_pos), dtype=np.int64)
    link_dsts = np.fromiter((d for _, d in link_pos), dtype=np.int64)
    return LoweredColumns(
        world=strategy.world_size,
        trees=trees,
        link_srcs=link_srcs,
        link_dsts=link_dsts,
        link_pos=link_pos,
        strategy_label=(
            f"{strategy.synthesis or 'unnamed'} x{strategy.num_trans}"
        ),
    )


#: (fingerprint, chunking spec, collective, mask) → LoweredColumns.
#: fingerprint covers world + tree structure; the chunking spec rides in
#: the key because two strategies can share trees but pipeline differently.
_LOWERING_CACHE: "OrderedDict[tuple, LoweredColumns]" = OrderedDict()
_LOWERING_CACHE_MAX = 64
_LOWERING_CACHE_STATS = {"hits": 0, "misses": 0}


def _lowering_key(
    strategy: Strategy, collective: str, act: Optional[FrozenSet[int]]
) -> tuple:
    return (
        strategy.fingerprint(),
        strategy.chunk_bytes,
        tuple(strategy.tree_chunk_bytes or ()),
        tuple(strategy.shares or ()),
        collective,
        act,
    )


def lowered_columns(
    strategy: Strategy,
    collective: str = "allreduce",
    active: Optional[Iterable[int]] = None,
) -> LoweredColumns:
    """:func:`lower_columns` behind the module LRU — the incremental
    re-pricing entry point: a controller correction, congestion window, or
    standby scenario that re-prices an already-seen (strategy, collective,
    mask) pays only the column algebra, never the lowering."""
    act = frozenset(active) if active is not None else None
    key = _lowering_key(strategy, collective, act)
    hit = _LOWERING_CACHE.get(key)
    if hit is not None:
        _LOWERING_CACHE_STATS["hits"] += 1
        _LOWERING_CACHE.move_to_end(key)
        return hit
    _LOWERING_CACHE_STATS["misses"] += 1
    cols = lower_columns(strategy, collective, act)
    _LOWERING_CACHE[key] = cols
    while len(_LOWERING_CACHE) > _LOWERING_CACHE_MAX:
        _LOWERING_CACHE.popitem(last=False)
    return cols


def clear_lowering_cache() -> None:
    """Drop cached lowered columns (tests pin cold-vs-warm equivalence)."""
    _LOWERING_CACHE.clear()
    _LOWERING_CACHE_STATS["hits"] = _LOWERING_CACHE_STATS["misses"] = 0


def lowering_cache_info() -> Dict[str, int]:
    return {
        "entries": len(_LOWERING_CACHE),
        "max": _LOWERING_CACHE_MAX,
        "hits": _LOWERING_CACHE_STATS["hits"],
        "misses": _LOWERING_CACHE_STATS["misses"],
    }


# --------------------------------------------------------------------------- #
# ScheduleProgram columns: the IR-replay twin of the strategy lowering
# --------------------------------------------------------------------------- #


@dataclass
class ProgramRoundCols:
    """One IR round as columns: one entry per *distinct directed link*,
    with the number of chunks that coalesce onto it (the event loop's
    ``seg * len(chunks)`` serialization rule, pre-grouped)."""

    srcs: np.ndarray    # int64 (E,)
    dsts: np.ndarray    # int64 (E,)
    eidx: np.ndarray    # int64 (E,) — indices into the link table
    counts: np.ndarray  # float64 (E,) — chunks coalesced per link


@dataclass
class ProgramColumns(_LinkTable):
    """A ``compiler.ScheduleProgram`` lowered once into per-round link
    columns, re-priced many times (the pipeline-sweep / large-stage-count
    workload).  Rounds with no sends are dropped — they cost nothing in
    the event loop too."""

    world: int
    chunks: int
    rounds: List[ProgramRoundCols]
    link_srcs: np.ndarray
    link_dsts: np.ndarray
    link_pos: Dict[Link, int]
    label: str = ""
    _host_ids: "OrderedDict[int, Tuple[object, np.ndarray]]" = field(
        default_factory=OrderedDict, repr=False
    )


def lower_program_columns(program) -> ProgramColumns:
    """Group each round's sends by directed link into numpy columns."""
    link_pos: Dict[Link, int] = {}
    rounds: List[ProgramRoundCols] = []
    for rnd in program.rounds:
        per_link: "OrderedDict[Link, int]" = OrderedDict()
        for step in rnd:
            if step.kind == "send":
                link = (step.rank, step.peer)
                per_link[link] = per_link.get(link, 0) + 1
        if not per_link:
            continue
        E = len(per_link)
        srcs = np.empty(E, dtype=np.int64)
        dsts = np.empty(E, dtype=np.int64)
        eidx = np.empty(E, dtype=np.int64)
        counts = np.empty(E, dtype=np.float64)
        for j, (link, count) in enumerate(per_link.items()):
            pos = link_pos.get(link)
            if pos is None:
                pos = link_pos[link] = len(link_pos)
            srcs[j], dsts[j] = link
            eidx[j] = pos
            counts[j] = float(count)
        rounds.append(ProgramRoundCols(srcs, dsts, eidx, counts))
    link_srcs = np.array([l[0] for l in link_pos], dtype=np.int64)
    link_dsts = np.array([l[1] for l in link_pos], dtype=np.int64)
    return ProgramColumns(
        world=program.world,
        chunks=program.chunks,
        rounds=rounds,
        link_srcs=link_srcs,
        link_dsts=link_dsts,
        link_pos=link_pos,
        label=f"program:{program.name}@{program.fingerprint()}",
    )


#: program fingerprint → ProgramColumns (the program is immutable, so the
#: fingerprint alone keys the structure — no chunking spec or mask axis)
_PROGRAM_CACHE: "OrderedDict[str, ProgramColumns]" = OrderedDict()
_PROGRAM_CACHE_MAX = 64
_PROGRAM_CACHE_STATS = {"hits": 0, "misses": 0}


def program_columns(program) -> ProgramColumns:
    """:func:`lower_program_columns` behind the module LRU — re-pricing a
    pipeline program across a (stages × microbatches) sweep pays the
    grouping walk once per program."""
    key = program.fingerprint()
    hit = _PROGRAM_CACHE.get(key)
    if hit is not None:
        _PROGRAM_CACHE_STATS["hits"] += 1
        _PROGRAM_CACHE.move_to_end(key)
        return hit
    _PROGRAM_CACHE_STATS["misses"] += 1
    cols = lower_program_columns(program)
    _PROGRAM_CACHE[key] = cols
    while len(_PROGRAM_CACHE) > _PROGRAM_CACHE_MAX:
        _PROGRAM_CACHE.popitem(last=False)
    return cols


def clear_program_cache() -> None:
    """Drop cached program columns (tests pin cold-vs-warm equivalence)."""
    _PROGRAM_CACHE.clear()
    _PROGRAM_CACHE_STATS["hits"] = _PROGRAM_CACHE_STATS["misses"] = 0


def program_cache_info() -> Dict[str, int]:
    return {
        "entries": len(_PROGRAM_CACHE),
        "max": _PROGRAM_CACHE_MAX,
        "hits": _PROGRAM_CACHE_STATS["hits"],
        "misses": _PROGRAM_CACHE_STATS["misses"],
    }


def vector_program_run(
    cols: ProgramColumns,
    model: LinkCostModel,
    nbytes: float,
    keep_links: bool = False,
) -> SimReport:
    """Replay program columns under ``model`` — the numpy twin of
    ``replay.simulate_program``'s event loop, bitwise equal on the
    makespan: per round each link's coalesced transfer costs
    ``α + β·(seg·count)`` (the identical float expression), distinct
    links run concurrently, and the round-barrier advance
    ``clock + max(durs)`` is the same operation as the event loop's
    ``max(clock + dur_i)`` because addition is monotone.  The
    per-transfer log is never kept on this path (that is what the event
    oracle is for); per-link busy is opt-in via ``keep_links``.
    """
    alpha, beta, cls_vec = _link_coeff_vectors(cols, model)
    seg = float(nbytes) / max(1, cols.chunks)
    busy = np.zeros(cols.num_links)
    clock = 0.0
    for rc in cols.rounds:
        durs = alpha[rc.eidx] + beta[rc.eidx] * (seg * rc.counts)
        busy[rc.eidx] += durs
        clock = clock + float(durs.max())

    class_busy: Dict[str, float] = {}
    if cols.num_links:
        class_busy[ICI] = float(busy[~cls_vec].sum())
        if bool(cls_vec.any()):
            class_busy[DCN] = float(busy[cls_vec].sum())
    link_busy: Dict[Link, float] = {}
    if keep_links:
        link_busy = {
            (int(s), int(d)): float(b)
            for s, d, b in zip(cols.link_srcs, cols.link_dsts, busy)
        }
    return SimReport(
        makespan=clock,
        transfers=[],
        link_busy=link_busy,
        class_busy=class_busy,
    )


# --------------------------------------------------------------------------- #
# pricing: per-link α/β vectors under one cost model
# --------------------------------------------------------------------------- #


def _link_coeff_vectors(
    cols: LoweredColumns, model: LinkCostModel
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(α, β, class-id) vectors over the structure's link table.

    Class coefficients broadcast over the cached host-id comparison (the
    one-``np.where`` re-price); per-link overrides — degraded links,
    per-link calibration fits — patch sparsely, O(#overrides)."""
    host = cols.host_ids(model.ips)
    n = cols.num_links
    ici, dcn = model.classes[ICI], model.classes[DCN]
    if host is None:
        cls = np.zeros(n, dtype=bool)  # everything ICI: one flat domain
        alpha = np.full(n, ici.alpha)
        beta = np.full(n, ici.beta)
    else:
        cls = host[cols.link_srcs] != host[cols.link_dsts]
        alpha = np.where(cls, dcn.alpha, ici.alpha)
        beta = np.where(cls, dcn.beta, ici.beta)
    if model.links:
        pos = cols.link_pos
        for link, c in model.links.items():
            p = pos.get(link)
            if p is not None:
                alpha[p] = c.alpha
                beta[p] = c.beta
    return alpha, beta, cls


# --------------------------------------------------------------------------- #
# the replay itself
# --------------------------------------------------------------------------- #


def vector_run(
    cols: LoweredColumns,
    model: LinkCostModel,
    nbytes: float,
    keep_links: bool = False,
) -> SimReport:
    """Replay lowered columns under ``model`` — the numpy twin of
    :meth:`EventSimulator.run`, same greedy placement, same timestamps.

    Returns a :class:`SimReport` with per-link-class busy aggregation
    (O(#classes), world-size-independent); the full per-link busy map is
    opt-in via ``keep_links`` — a 100k-rank replay must not hold a
    world-sized dict per candidate.  The per-transfer log is never kept
    on this path (that is what the event oracle is for).
    """
    alpha, beta, cls_vec = _link_coeff_vectors(cols, model)

    # per-tree chunking, exactly TreeSchedule.num_chunks's rule
    num_chunks: List[int] = []
    chunk_size: List[float] = []
    for tc in cols.trees:
        tb = nbytes * tc.share
        if tb <= 0 or tc.chunk_bytes <= 0:
            c = 1
        else:
            c = max(1, int(-(-tb // tc.chunk_bytes)))
        num_chunks.append(c)
        chunk_size.append(tb / c if c else 0.0)

    link_free = np.zeros(cols.num_links)
    busy = np.zeros(cols.num_links)
    egress = np.zeros(cols.world)
    ingress = np.zeros(cols.world)
    ready = [
        np.zeros((num_chunks[t], cols.world)) for t in range(len(cols.trees))
    ]
    makespan = 0.0

    colors = max((len(tc.rounds) for tc in cols.trees), default=0)
    for color in range(colors):
        for t, tc in enumerate(cols.trees):
            if color >= len(tc.rounds):
                continue
            rc = tc.rounds[color]
            csize = chunk_size[t]
            C = num_chunks[t]
            if rc.matching and len(rc.srcs) == 1:
                # chains produce single-edge rounds; scalar placement
                # avoids per-call numpy overhead on 1-element arrays
                s = int(rc.srcs[0])
                d = int(rc.dsts[0])
                e = int(rc.eidx[0])
                dur = float(alpha[e]) + float(beta[e]) * csize
                fprev = max(
                    float(link_free[e]), float(egress[s]), float(ingress[d])
                )
                rt = ready[t]
                for c in range(C):
                    fprev = max(float(rt[c, s]), fprev) + dur
                    if fprev > rt[c, d]:
                        rt[c, d] = fprev
                link_free[e] = fprev
                egress[s] = fprev
                ingress[d] = fprev
                busy[e] += dur * C
                if fprev > makespan:
                    makespan = fprev
            elif rc.matching:
                durs = alpha[rc.eidx] + beta[rc.eidx] * csize
                fprev = np.maximum(
                    np.maximum(link_free[rc.eidx], egress[rc.srcs]),
                    ingress[rc.dsts],
                )
                rt = ready[t]
                block = rt[:, rc.srcs]  # (C, E) gather — a copy
                if C == 1:
                    fprev = np.maximum(block[0], fprev) + durs
                    rt[0, rc.dsts] = np.maximum(rt[0, rc.dsts], fprev)
                else:
                    out = np.empty((C, len(durs)))
                    for c in range(C):
                        fprev = np.maximum(block[c], fprev) + durs
                        out[c] = fprev
                    rt[:, rc.dsts] = np.maximum(rt[:, rc.dsts], out)
                link_free[rc.eidx] = fprev
                egress[rc.srcs] = fprev
                ingress[rc.dsts] = fprev
                busy[rc.eidx] += durs * C
                m = float(fprev.max())
                if m > makespan:
                    makespan = m
            else:
                # exact sequential waves, chunk-major like the event loop
                rt = ready[t]
                for c in range(C):
                    row = rt[c]
                    for widx in rc.waves:
                        ws = rc.srcs[widx]
                        wd = rc.dsts[widx]
                        we = rc.eidx[widx]
                        wdur = alpha[we] + beta[we] * csize
                        fin = (
                            np.maximum(
                                np.maximum(row[ws], link_free[we]),
                                np.maximum(egress[ws], ingress[wd]),
                            )
                            + wdur
                        )
                        row[wd] = np.maximum(row[wd], fin)
                        link_free[we] = fin
                        egress[ws] = fin
                        ingress[wd] = fin
                        busy[we] += wdur
                        m = float(fin.max())
                        if m > makespan:
                            makespan = m

    class_busy: Dict[str, float] = {}
    if cols.num_links:
        ici_busy = float(busy[~cls_vec].sum())
        dcn_busy = float(busy[cls_vec].sum())
        class_busy[ICI] = ici_busy
        if bool(cls_vec.any()):
            class_busy[DCN] = dcn_busy
    link_busy: Dict[Link, float] = {}
    if keep_links:
        link_busy = {
            (int(s), int(d)): float(b)
            for s, d, b in zip(cols.link_srcs, cols.link_dsts, busy)
        }
    return SimReport(
        makespan=makespan,
        transfers=[],
        link_busy=link_busy,
        class_busy=class_busy,
    )
