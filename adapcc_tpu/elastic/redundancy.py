"""Redundant ZeRO-1 shard placement: k-replicated optimizer shards.

Shrink-and-continue (PR 7/10) keeps the *collective* alive through a rank
death, but a ZeRO-1 optimizer shard is single-owner state: in a real
multi-process deployment the dead rank's flat master slice and moment
buffers live only in its HBM, and without redundancy the only recovery is
a checkpoint reload — losing every step since the last save.  This module
closes that hole the way production collective stacks do (The Big
Send-off, PAPERS.md): each rank's shard is replicated to ``k``
ring-neighbor holders, piggybacked on the post-step all-gather window the
ZeRO-1 cycle already opens (the shard's bytes ride to a neighbor while the
params broadcast anyway), and a death is repaired by pulling the lost
shard from its in-fabric replica — no checkpoint reload on the hot path.

Placement rule (:func:`replica_placement`): walk the ring from ``r+1``,
preferring holders on a **different host** than the primary (a host loss
must never take a shard and all its replicas together); a single-host
world (or one with no ip table — the CPU test rig) falls back to plain
ring neighbors, which is the best a one-host fabric can do.  The rule is
pure and deterministic: every process derives the identical placement from
the strategy's host layout, no negotiation.

:class:`ShardReplicaStore` is the in-fabric replica set's process-local
twin: on a real pod each holder keeps its primaries' rows in device/host
memory; on the single-process test rig the store materializes the rows a
holder *would* hold, stamped with the step they were captured at, so
reconstruction (and its freshness guard) is exercisable on CPU.  The wire
cost of the replication itself is priced by
:func:`adapcc_tpu.sim.cost_model.replication_overhead_time` and swept by
``make recovery-bench`` (docs/RECOVERY.md).
"""

from __future__ import annotations

import os
from typing import Any, Dict, Iterable, Mapping, Optional, Sequence, Tuple

import jax
import numpy as np

#: replica count for ZeRO-1 shards (``0`` disables replication entirely);
#: malformed → loud error, never a silent default (the ADAPCC_MERGE_ROUNDS
#: policy)
SHARD_REPLICAS_ENV = "ADAPCC_SHARD_REPLICAS"

#: one replica survives any single failure unit (rank or — with a
#: multi-host placement — host), at one shard-send per step of overhead
DEFAULT_SHARD_REPLICAS = 1


def shard_replicas(default: int = DEFAULT_SHARD_REPLICAS) -> int:
    """The ``ADAPCC_SHARD_REPLICAS`` funnel: env > ``default``."""
    raw = os.environ.get(SHARD_REPLICAS_ENV, "").strip()
    if not raw:
        return int(default)
    try:
        value = int(raw)
    except ValueError as e:
        raise ValueError(
            f"{SHARD_REPLICAS_ENV}={raw!r}: expected an integer"
        ) from e
    if value < 0:
        raise ValueError(f"{SHARD_REPLICAS_ENV}={raw!r}: must be >= 0")
    return value


def replica_placement(
    world: int,
    ips: Optional[Mapping[int, str]] = None,
    replicas: int = DEFAULT_SHARD_REPLICAS,
) -> Dict[int, Tuple[int, ...]]:
    """Primary rank → its ``replicas`` holder ranks.

    Deterministic walk of the ring from ``r+1``: ranks on a *different
    host* than ``r`` are preferred holders (a host loss must never take a
    shard and all its replicas together), rotated by the primary's index
    within its own host group so holder load stays balanced (two
    same-host primaries never pile onto the same neighbor); if fewer than
    ``replicas`` off-host ranks exist (single-host world, no ip table),
    the remaining slots fill with the nearest on-host ring neighbors — a
    rank never holds its own shard, and holders are distinct.  Every
    process computes the identical placement from the same host layout.
    """
    if world < 1:
        raise ValueError(f"world must be >= 1, got {world}")
    if replicas < 0:
        raise ValueError(f"replicas must be >= 0, got {replicas}")
    if replicas >= world:
        raise ValueError(
            f"replicas={replicas} needs at least replicas+1={replicas + 1} "
            f"ranks (world={world}): a shard cannot be replicated onto "
            "more distinct holders than there are other ranks"
        )
    ips = dict(ips or {})
    out: Dict[int, Tuple[int, ...]] = {}
    for r in range(world):
        ring = [(r + i) % world for i in range(1, world)]
        my_host = ips.get(r)
        off_host = [h for h in ring if ips.get(h) != my_host] if ips else []
        off_set = frozenset(off_host)
        on_host = [h for h in ring if h not in off_set]
        if off_host:
            # balance: the g-th primary of a host starts g holders into
            # the off-host walk, so a whole host's shards spread over the
            # other hosts' ranks instead of piling onto one neighbor
            g = sum(1 for q in range(r) if ips.get(q) == my_host)
            g %= len(off_host)
            off_host = off_host[g:] + off_host[:g]
        holders = (off_host + on_host)[:replicas]
        out[r] = tuple(holders)
    return out


def _rows_of(opt_pair: Tuple[Any, Any], world: int):
    """Validate a ZeRO-1 ``(master [world, L], opt-state shards)`` pair and
    return it as host arrays (the shape every store operation speaks)."""
    master, opt_state = opt_pair
    master = np.asarray(jax.device_get(master))
    if master.ndim != 2 or master.shape[0] != world:
        raise ValueError(
            f"expected a [world={world}, shard] master, got shape "
            f"{master.shape}"
        )
    opt_state = jax.device_get(opt_state)
    return master, opt_state


class ShardReplicaStore:
    """The in-fabric replica set for one world's ZeRO-1 shards.

    ``capture(opt_pair, step)`` records, for every primary rank, the rows
    its holders keep — stamped with ``step`` so a reconstruction against a
    *newer* training state refuses loudly (a stale replica silently
    rewinding one shard's adam moments is exactly the corruption this
    store exists to prevent; the caller falls back to the checkpoint
    path).  ``reconstruct(opt_pair, dead, step)`` returns the pair with
    every dead rank's rows replaced from its replica — the repair
    :func:`adapcc_tpu.elastic.rebalance.recover_zero1_pair` routes through
    the checkpoint layout-guard funnel.

    On a real pod the capture is the piggyback transfer this store's
    pricing term models (each rank sends its ``state_bytes/world`` rows to
    ``k`` neighbors inside the post-step all-gather window); the
    process-local twin materializes the same rows to host memory so the
    protocol — placement, freshness, repair — runs unchanged on CPU.
    """

    def __init__(
        self,
        world: int,
        ips: Optional[Mapping[int, str]] = None,
        replicas: Optional[int] = None,
    ) -> None:
        self.world = int(world)
        self.replicas = shard_replicas() if replicas is None else int(replicas)
        if self.replicas < 1:
            raise ValueError(
                f"a replica store needs replicas >= 1, got {self.replicas} "
                f"(replicas=0 means replication is off — build no store)"
            )
        self.placement = replica_placement(self.world, ips, self.replicas)
        #: primary rank → (master row, opt-state rows, step captured at)
        self._held: Dict[int, Tuple[np.ndarray, Any, int]] = {}
        self.captures = 0

    def holders_of(self, rank: int) -> Tuple[int, ...]:
        if not 0 <= rank < self.world:
            raise ValueError(f"rank {rank} outside world [0, {self.world})")
        return self.placement[rank]

    # -- the piggyback window --------------------------------------------------

    def capture(self, opt_pair: Tuple[Any, Any], step: int) -> None:
        """Record every rank's replica rows as of ``step`` (the post-step
        all-gather window: the shard every holder receives is the one just
        written by this step's optimizer update).

        One flatten + one host materialization for the whole state, then
        per-rank row slices — the copied bytes total ONE extra state copy
        per step (the twin of the ``k·state_bytes/world``-per-rank wire
        piggyback the cost model prices), not world× tree traversals.
        """
        master, opt_state = _rows_of(opt_pair, self.world)
        leaves, treedef = jax.tree_util.tree_flatten(opt_state)
        arrs = [np.asarray(leaf) for leaf in leaves]
        step = int(step)
        for r in range(self.world):
            rows = [
                a[r].copy()
                if a.ndim >= 1 and a.shape[0] == self.world
                else a.copy()
                for a in arrs
            ]
            self._held[r] = (
                master[r].copy(),
                jax.tree_util.tree_unflatten(treedef, rows),
                step,
            )
        self.captures += 1

    def replica_step(self, rank: int) -> Optional[int]:
        held = self._held.get(rank)
        return held[2] if held is not None else None

    # -- repair ----------------------------------------------------------------

    def payload_for(self, rank: int, expect_step: Optional[int] = None):
        """The replica rows for ``rank`` — the bytes its holder would send
        back.  ``expect_step`` is the freshness guard: a replica older
        than the state being repaired refuses loudly."""
        held = self._held.get(rank)
        if held is None:
            raise KeyError(
                f"no replica held for rank {rank}: the store never "
                "captured a step (replication must run before the first "
                "failure it is supposed to survive)"
            )
        master_row, opt_rows, step = held
        if expect_step is not None and step != int(expect_step):
            raise ValueError(
                f"replica for rank {rank} is stamped step {step} but the "
                f"repair expects step {expect_step}; restoring it would "
                "rewind one shard's optimizer state relative to its peers "
                "— fall back to the checkpoint path"
            )
        return master_row, opt_rows, step

    def reconstruct(
        self,
        opt_pair: Tuple[Any, Any],
        dead: Iterable[int],
        step: Optional[int] = None,
    ) -> Tuple[np.ndarray, Any]:
        """Return ``opt_pair`` with every ``dead`` rank's rows replaced by
        its replica — the in-fabric repair.  Surviving rows pass through
        untouched; the result is host-resident (the caller re-places it on
        the mesh through the rebalance funnel)."""
        dead = sorted({int(r) for r in dead})
        bad = [r for r in dead if not 0 <= r < self.world]
        if bad:
            raise ValueError(f"dead ranks {bad} outside world [0, {self.world})")
        master, opt_state = _rows_of(opt_pair, self.world)
        master = master.copy()
        payloads = {r: self.payload_for(r, expect_step=step) for r in dead}
        for r, (master_row, _, _) in payloads.items():
            if master_row.shape != master[r].shape:
                raise ValueError(
                    f"replica master row for rank {r} has shape "
                    f"{master_row.shape}, state expects {master[r].shape}; "
                    "the replica belongs to a different layout"
                )
            master[r] = master_row

        # flatten each dead rank's replica rows ONCE (leaf order is
        # deterministic — the held rows were captured from this exact
        # opt_state structure), not once per state leaf
        row_leaves = {
            r: jax.tree_util.tree_leaves(opt_rows)
            for r, (_, opt_rows, _) in payloads.items()
        }
        leaf_idx = [0]

        def repair(leaf):
            arr = np.asarray(leaf)
            i = leaf_idx[0]
            leaf_idx[0] += 1
            if arr.ndim >= 1 and arr.shape[0] == self.world:
                arr = arr.copy()
                for r, rows in row_leaves.items():
                    arr[r] = rows[i]
                return arr
            return arr

        new_opt = jax.tree_util.tree_map(repair, opt_state)
        return master, new_opt


__all__ = [
    "DEFAULT_SHARD_REPLICAS",
    "SHARD_REPLICAS_ENV",
    "ShardReplicaStore",
    "replica_placement",
    "shard_replicas",
]
