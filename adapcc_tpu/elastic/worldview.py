"""WorldView: the coordinator's explicit picture of the world.

The reference coordinator hands out bare active *lists* per step
(rpc_server.py:48-96); everything downstream then re-derives who is dead,
who is merely slow, and whether anything changed since the last step.  The
elastic loop needs those distinctions first-class:

- **alive** — ranks still answering heartbeats; the set collectives
  continue with instead of hanging;
- **relays** — alive-but-slow ranks demoted to pure forwarders (the
  paper's straggler demotion): they stay on the data path, contribute the
  reduction identity, and keep receiving results;
- **epoch** — a monotone counter bumped on every membership change.  The
  epoch is the hot-swap token: compiled plans are installed per epoch, and
  a collective issued against a dead epoch raises a retryable
  :class:`~adapcc_tpu.comm.engine.EpochMismatch` instead of running a
  stale schedule.

The slow-rank rule (:func:`slow_ranks_from_medians`) feeds on the per-rank
step medians the :class:`~adapcc_tpu.tuner.measure.DispatchTimer` pipeline
already collects — detection costs no new measurement.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, replace
from typing import FrozenSet, Iterable, List, Mapping, Optional

import numpy as np

#: heartbeat timeout override for fault detection (seconds); default is the
#: coordinator's fault timeout (primitives.FAULT_TOLERANT_TIME_S)
HEARTBEAT_TIMEOUT_ENV = "ADAPCC_HEARTBEAT_TIMEOUT_S"

#: slow-rank demotion threshold: a rank whose step median exceeds
#: ``factor x`` the median of its peers' medians is demoted to a relay
SLOW_RANK_FACTOR_ENV = "ADAPCC_SLOW_RANK_FACTOR"

#: default demotion factor — 2x its peers is decisively a straggler, not
#: measurement noise (the tuner's hysteresis uses the same order of margin)
DEFAULT_SLOW_RANK_FACTOR = 2.0


def _env_float(name: str, default: float) -> float:
    """Loud parse of a float knob: a malformed value raises instead of
    silently running the default (the ADAPCC_MERGE_ROUNDS policy)."""
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        value = float(raw)
    except ValueError as e:
        raise ValueError(f"{name}={raw!r}: expected a number") from e
    if value <= 0:
        raise ValueError(f"{name}={raw!r}: must be > 0")
    return value


def heartbeat_timeout_s(default: float) -> float:
    return _env_float(HEARTBEAT_TIMEOUT_ENV, default)


def slow_rank_factor(default: float = DEFAULT_SLOW_RANK_FACTOR) -> float:
    return _env_float(SLOW_RANK_FACTOR_ENV, default)


@dataclass(frozen=True)
class WorldView:
    """Immutable snapshot of the coordinator's world picture.

    Transitions return a NEW view with the epoch bumped when (and only
    when) membership actually changed — a no-op transition keeps the same
    epoch, so compiled plans are never invalidated for nothing.
    """

    world_size: int
    alive: FrozenSet[int]
    relays: FrozenSet[int]
    epoch: int = 0

    def __post_init__(self) -> None:
        bad = [r for r in self.alive | self.relays if not 0 <= r < self.world_size]
        if bad:
            raise ValueError(
                f"ranks {sorted(bad)} outside world [0, {self.world_size})"
            )
        if not self.relays <= self.alive:
            raise ValueError(
                f"relays {sorted(self.relays - self.alive)} are not alive; a "
                "dead rank cannot forward"
            )

    @classmethod
    def full(cls, world_size: int) -> "WorldView":
        return cls(
            world_size=world_size,
            alive=frozenset(range(world_size)),
            relays=frozenset(),
            epoch=0,
        )

    # -- queries ---------------------------------------------------------------

    @property
    def contributing(self) -> FrozenSet[int]:
        """Ranks whose data enters the reduction: alive and not demoted."""
        return self.alive - self.relays

    @property
    def dead(self) -> FrozenSet[int]:
        return frozenset(range(self.world_size)) - self.alive

    @property
    def degraded(self) -> bool:
        return bool(self.dead or self.relays)

    def active_list(self) -> List[int]:
        """The bare list legacy consumers (hook responses, engine
        ``active_gpus``) expect."""
        return sorted(self.contributing)

    def mask(self) -> np.ndarray:
        m = np.zeros((self.world_size,), dtype=bool)
        m[self.active_list()] = True
        return m

    def key(self):
        """Standby-plan cache key: membership without the epoch (the same
        degraded shape recurring at a later epoch reuses the same plan)."""
        return (self.alive, self.relays)

    # -- transitions -----------------------------------------------------------

    def _bump(self, alive: FrozenSet[int], relays: FrozenSet[int]) -> "WorldView":
        relays = relays & alive
        if alive == self.alive and relays == self.relays:
            return self
        return replace(self, alive=alive, relays=relays, epoch=self.epoch + 1)

    def with_down(self, ranks: Iterable[int]) -> "WorldView":
        down = frozenset(ranks)
        return self._bump(self.alive - down, self.relays - down)

    def with_alive(self, ranks: Iterable[int]) -> "WorldView":
        """Replace the alive set wholesale (the controller's status-0
        output: exactly the ranks that reported)."""
        alive = frozenset(ranks)
        return self._bump(alive, self.relays & alive)

    def with_relays(self, ranks: Iterable[int]) -> "WorldView":
        """Replace the relay set (the slow-rank rule's output)."""
        return self._bump(self.alive, frozenset(ranks) & self.alive)

    def with_recovered(self, ranks: Iterable[int]) -> "WorldView":
        up = frozenset(ranks)
        return self._bump(self.alive | up, self.relays - up)


def slow_ranks_from_medians(
    medians: Mapping[int, float],
    factor: Optional[float] = None,
    min_peers: int = 2,
) -> FrozenSet[int]:
    """The slow-rank demotion rule over per-rank step medians.

    A rank is slow when its median step time exceeds ``factor ×`` the
    median of the *other* ranks' medians — each rank is judged against its
    peers, so a uniformly slow world demotes nobody (there is no relay to
    forward through) and one straggler stands out immediately.  Fewer than
    ``min_peers`` peers means no judgement: a 1–2 rank sample cannot
    distinguish a straggler from noise.
    """
    if factor is None:
        factor = slow_rank_factor()
    if factor <= 1.0:
        raise ValueError(f"slow-rank factor must be > 1, got {factor}")
    items = {int(r): float(s) for r, s in medians.items() if s > 0}
    if len(items) <= min_peers:
        return frozenset()
    slow = set()
    for rank, median in items.items():
        peers = [s for r, s in items.items() if r != rank]
        if median > factor * float(np.median(peers)):
            slow.add(rank)
    return frozenset(slow)
