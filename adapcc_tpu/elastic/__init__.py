"""Elastic fault tolerance: the detect → re-plan → hot-swap loop.

The subsystem closes ROADMAP open item 1 — the paper's signature
robustness behaviors on the *compiled* data plane:

- :mod:`~adapcc_tpu.elastic.faults` — deterministic fault injection
  (``FaultPlan``; ``ADAPCC_FAULT_PLAN`` env artifact) so every failover
  path is exercisable on CPU and priced by the cost model;
- :mod:`~adapcc_tpu.elastic.worldview` — the coordinator's explicit
  ``WorldView`` (alive set, relay set, epoch counter) plus the slow-rank
  demotion rule over DispatchTimer step medians;
- :mod:`~adapcc_tpu.elastic.standby` — sim-ranked degraded plans
  (one-rank-down, one-host-down) AOT-compiled at setup, so a world shrink
  is a dispatch-time cache-key switch, not a cold recompile stall;
- :mod:`~adapcc_tpu.elastic.rebalance` — ZeRO-1 shard re-balance on a
  world change (shrink, grow-back, replica repair), validated through the
  checkpoint layout-tag funnel;
- :mod:`~adapcc_tpu.elastic.redundancy` — k-replicated ZeRO-1 shard
  placement (``ADAPCC_SHARD_REPLICAS``): ring-neighbor, host-disjoint
  replicas piggybacked on the post-step all-gather window, so a dead
  rank's optimizer shard is repaired from the fabric instead of a
  checkpoint reload (docs/RECOVERY.md).

See docs/ELASTIC.md for the lifecycle and the failover cost rows, and
docs/RECOVERY.md for the durable-recovery layer on top of it.
"""

from adapcc_tpu.elastic.faults import (
    DEFAULT_SLOWDOWN,
    FAULT_PLAN_ENV,
    FaultEvent,
    FaultPlan,
    FaultState,
    load_fault_plan,
)
from adapcc_tpu.elastic.rebalance import (
    grow_zero1_trainer_state,
    rebalance_zero1_pair,
    recover_zero1_trainer_state,
    reshard_zero1_snapshot,
    shrink_zero1_trainer_state,
)
from adapcc_tpu.elastic.redundancy import (
    DEFAULT_SHARD_REPLICAS,
    SHARD_REPLICAS_ENV,
    ShardReplicaStore,
    replica_placement,
    shard_replicas,
)
from adapcc_tpu.elastic.standby import (
    StandbyPlan,
    StandbyPlanCache,
    degraded_scenarios,
    reemit_for_active,
)
from adapcc_tpu.elastic.worldview import (
    HEARTBEAT_TIMEOUT_ENV,
    SLOW_RANK_FACTOR_ENV,
    WorldView,
    slow_ranks_from_medians,
)

__all__ = [
    "DEFAULT_SHARD_REPLICAS",
    "DEFAULT_SLOWDOWN",
    "FAULT_PLAN_ENV",
    "FaultEvent",
    "FaultPlan",
    "FaultState",
    "HEARTBEAT_TIMEOUT_ENV",
    "SHARD_REPLICAS_ENV",
    "SLOW_RANK_FACTOR_ENV",
    "ShardReplicaStore",
    "StandbyPlan",
    "StandbyPlanCache",
    "WorldView",
    "degraded_scenarios",
    "grow_zero1_trainer_state",
    "load_fault_plan",
    "rebalance_zero1_pair",
    "recover_zero1_trainer_state",
    "reemit_for_active",
    "replica_placement",
    "reshard_zero1_snapshot",
    "shard_replicas",
    "shrink_zero1_trainer_state",
    "slow_ranks_from_medians",
]
