"""Elastic fault tolerance: the detect → re-plan → hot-swap loop.

The subsystem closes ROADMAP open item 1 — the paper's signature
robustness behaviors on the *compiled* data plane:

- :mod:`~adapcc_tpu.elastic.faults` — deterministic fault injection
  (``FaultPlan``; ``ADAPCC_FAULT_PLAN`` env artifact) so every failover
  path is exercisable on CPU and priced by the cost model;
- :mod:`~adapcc_tpu.elastic.worldview` — the coordinator's explicit
  ``WorldView`` (alive set, relay set, epoch counter) plus the slow-rank
  demotion rule over DispatchTimer step medians;
- :mod:`~adapcc_tpu.elastic.standby` — sim-ranked degraded plans
  (one-rank-down, one-host-down) AOT-compiled at setup, so a world shrink
  is a dispatch-time cache-key switch, not a cold recompile stall;
- :mod:`~adapcc_tpu.elastic.rebalance` — ZeRO-1 shard re-balance on a
  world change, validated through the checkpoint layout-tag funnel.

See docs/ELASTIC.md for the lifecycle and the failover cost rows.
"""

from adapcc_tpu.elastic.faults import (
    DEFAULT_SLOWDOWN,
    FAULT_PLAN_ENV,
    FaultEvent,
    FaultPlan,
    FaultState,
    load_fault_plan,
)
from adapcc_tpu.elastic.rebalance import (
    rebalance_zero1_pair,
    reshard_zero1_snapshot,
    shrink_zero1_trainer_state,
)
from adapcc_tpu.elastic.standby import (
    StandbyPlan,
    StandbyPlanCache,
    degraded_scenarios,
    reemit_for_active,
)
from adapcc_tpu.elastic.worldview import (
    HEARTBEAT_TIMEOUT_ENV,
    SLOW_RANK_FACTOR_ENV,
    WorldView,
    slow_ranks_from_medians,
)

__all__ = [
    "DEFAULT_SLOWDOWN",
    "FAULT_PLAN_ENV",
    "FaultEvent",
    "FaultPlan",
    "FaultState",
    "HEARTBEAT_TIMEOUT_ENV",
    "SLOW_RANK_FACTOR_ENV",
    "StandbyPlan",
    "StandbyPlanCache",
    "WorldView",
    "degraded_scenarios",
    "load_fault_plan",
    "rebalance_zero1_pair",
    "reemit_for_active",
    "reshard_zero1_snapshot",
    "shrink_zero1_trainer_state",
    "slow_ranks_from_medians",
]
