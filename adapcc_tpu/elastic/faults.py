"""Deterministic fault model: a seeded schedule of rank-level failures.

The paper's robustness claims (stragglers demoted to forwarding relays;
collectives that continue with the alive subset) are only testable if the
failures themselves are reproducible.  A :class:`FaultPlan` is a list of
``(step, kind, rank)`` events — ``down`` / ``slow`` / ``recover`` — replayed
deterministically: ``state_at(step)`` folds every event up to and including
``step`` into the down-set and the slow-map, so two runs of the same plan
see byte-identical fault timelines on any backend, hardware or CPU.

Injection points (the two funnels every failover path flows through):

- the coordinator's ``hook_arrive``/``controller_arrive`` funnel
  (:class:`adapcc_tpu.coordinator.logic.CoordinatorLogic` takes a
  ``fault_plan``): a down rank's arrival is dropped at the funnel and the
  barrier's expected count shrinks, so fault detection fires
  *deterministically* instead of waiting out a wall-clock timeout;
- the simulated replay (:func:`adapcc_tpu.sim.replay.simulate_fault_plan`):
  the same plan prices detection → swap → degraded steady state on the
  calibrated α-β model.

``ADAPCC_FAULT_PLAN`` points at a JSON artifact (see :func:`load_fault_plan`)
so a battery entry or a workload run can inject the identical schedule from
the environment with zero wiring at the call site.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from typing import Dict, FrozenSet, List, Mapping, Optional, Sequence, Tuple

import numpy as np

#: env var pointing at a fault-plan JSON artifact
FAULT_PLAN_ENV = "ADAPCC_FAULT_PLAN"

#: the event vocabulary; anything else is a loud error, never a silent no-op
FAULT_KINDS = ("down", "slow", "recover")

#: default straggler slowdown factor for ``slow`` events (the sim's
#: ``predict_degradation`` default — one number across injection and pricing)
DEFAULT_SLOWDOWN = 4.0


@dataclass(frozen=True)
class FaultEvent:
    """One scheduled fault transition at a training step."""

    step: int
    kind: str
    rank: int
    slowdown: float = DEFAULT_SLOWDOWN

    def __post_init__(self) -> None:
        if self.kind not in FAULT_KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r}; expected one of {FAULT_KINDS}"
            )
        if self.step < 0:
            raise ValueError(f"fault step must be >= 0, got {self.step}")
        if self.kind == "slow" and self.slowdown < 1.0:
            raise ValueError(
                f"slow-event slowdown must be >= 1, got {self.slowdown}"
            )

    def to_dict(self) -> dict:
        out = {"step": self.step, "kind": self.kind, "rank": self.rank}
        if self.kind == "slow":
            out["slowdown"] = self.slowdown
        return out

    @classmethod
    def from_dict(cls, obj: Mapping) -> "FaultEvent":
        return cls(
            step=int(obj["step"]),
            kind=str(obj["kind"]),
            rank=int(obj["rank"]),
            slowdown=float(obj.get("slowdown", DEFAULT_SLOWDOWN)),
        )


@dataclass(frozen=True)
class FaultState:
    """The fault picture at one step: who is dead, who is slow (and by
    how much).  Slow ranks are candidates for relay demotion; down ranks
    are out of the collective entirely."""

    down: FrozenSet[int]
    slow: Tuple[Tuple[int, float], ...]  # sorted (rank, slowdown) pairs

    @property
    def slow_map(self) -> Dict[int, float]:
        return dict(self.slow)

    @property
    def faulty(self) -> FrozenSet[int]:
        return self.down | frozenset(r for r, _ in self.slow)

    @property
    def healthy(self) -> bool:
        return not self.down and not self.slow


class FaultPlan:
    """A deterministic, serializable schedule of fault events.

    ``world`` is the world size the plan was authored for; every consumer
    validates it against the runtime world (injecting a plan for the wrong
    world would silently shift which ranks die).
    """

    def __init__(
        self,
        events: Sequence[FaultEvent],
        world: int,
        label: str = "fault-plan",
    ) -> None:
        if world < 1:
            raise ValueError(f"world must be >= 1, got {world}")
        bad = [e for e in events if not 0 <= e.rank < world]
        if bad:
            raise ValueError(
                f"fault events {bad} name ranks outside world [0, {world})"
            )
        self.world = world
        self.label = label
        self.events: Tuple[FaultEvent, ...] = tuple(
            sorted(events, key=lambda e: (e.step, e.rank, e.kind))
        )
        # the plan must never kill the whole world: a step where every rank
        # is down has no leader to freeze an active list and no alive subset
        # for the collectives to continue with
        for step in sorted({e.step for e in self.events}):
            st = self.state_at(step)
            if len(st.down) >= world:
                raise ValueError(
                    f"fault plan kills the entire world at step {step}; at "
                    "least one rank must stay alive"
                )

    # -- replay ----------------------------------------------------------------

    def events_at(self, step: int) -> List[FaultEvent]:
        return [e for e in self.events if e.step == step]

    def state_at(self, step: int) -> FaultState:
        """Fold every event with ``event.step <= step`` into one state."""
        down: set = set()
        slow: Dict[int, float] = {}
        for e in self.events:
            if e.step > step:
                break
            if e.kind == "down":
                down.add(e.rank)
                slow.pop(e.rank, None)
            elif e.kind == "slow":
                if e.rank not in down:
                    slow[e.rank] = e.slowdown
            else:  # recover
                down.discard(e.rank)
                slow.pop(e.rank, None)
        return FaultState(
            down=frozenset(down), slow=tuple(sorted(slow.items()))
        )

    def down_at(self, step: int) -> FrozenSet[int]:
        return self.state_at(step).down

    def alive_at(self, step: int) -> FrozenSet[int]:
        return frozenset(range(self.world)) - self.state_at(step).down

    def contributing_at(self, step: int) -> FrozenSet[int]:
        """Ranks that contribute to step ``step``'s collectives: alive and
        not demoted to a forwarding relay (slow ranks are demoted)."""
        st = self.state_at(step)
        return (
            frozenset(range(self.world))
            - st.down
            - frozenset(r for r, _ in st.slow)
        )

    def mask_at(self, step: int) -> np.ndarray:
        """``[world]`` bool contribution mask for step ``step`` — the shape
        the engine/trainer data plane consumes."""
        m = np.zeros((self.world,), dtype=bool)
        m[sorted(self.contributing_at(step))] = True
        if not m.any():
            # every rank demoted/down would zero the collective's divisor;
            # the plan constructor forbids all-down, so this can only be
            # "everyone slow" — keep the alive ranks contributing instead
            m[sorted(self.alive_at(step))] = True
        return m

    def last_step(self) -> int:
        return max((e.step for e in self.events), default=0)

    def chaos_schedule(self, step_period_s: float, **kwargs):
        """The plan's **cross-process spelling** (docs/SUPERVISOR.md §5):
        compile the step-indexed events into a wall-clock signal schedule
        for real worker processes — ``down`` → SIGKILL, ``slow`` → a
        SIGSTOP/SIGCONT duty cycle stretching wall time by the event's
        ``slowdown`` (so the slow-rank demotion rule is exercised by a
        genuinely straggling process), ``recover`` → SIGCONT.  Delegates
        to :func:`adapcc_tpu.supervisor.chaos.wall_schedule`; pure and
        deterministic like every other replay of this plan."""
        from adapcc_tpu.supervisor.chaos import wall_schedule

        return wall_schedule(self, step_period_s, **kwargs)

    # -- serialization ---------------------------------------------------------

    def to_dict(self) -> dict:
        return {
            "world": self.world,
            "label": self.label,
            "events": [e.to_dict() for e in self.events],
        }

    @classmethod
    def from_dict(cls, obj: Mapping) -> "FaultPlan":
        return cls(
            events=[FaultEvent.from_dict(e) for e in obj.get("events", ())],
            world=int(obj["world"]),
            label=str(obj.get("label", "fault-plan")),
        )

    def save(self, path: str) -> str:
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(path, "w") as f:
            json.dump(self.to_dict(), f, indent=2, sort_keys=True)
        return path

    @classmethod
    def load(cls, path: str) -> "FaultPlan":
        with open(path) as f:
            return cls.from_dict(json.load(f))

    # -- canned plans ----------------------------------------------------------

    @classmethod
    def seeded(
        cls,
        world: int,
        steps: int,
        seed: int = 0,
        n_faults: int = 2,
        recover: bool = True,
        slowdown: float = DEFAULT_SLOWDOWN,
    ) -> "FaultPlan":
        """Deterministic pseudo-random plan: ``n_faults`` events (alternating
        down/slow) at distinct steps on distinct ranks, each recovered a few
        steps later when ``recover``.  Same (world, steps, seed) → the same
        plan, byte for byte — the property every fault-sweep row rides on."""
        if world < 2:
            raise ValueError("a seeded fault plan needs world >= 2")
        rng = np.random.default_rng(seed)
        n_faults = min(n_faults, world - 1, max(1, steps // 2))
        ranks = rng.choice(world, size=n_faults, replace=False)
        fault_steps = sorted(
            int(s) for s in rng.choice(max(1, steps - 2), size=n_faults, replace=False)
        )
        events: List[FaultEvent] = []
        for i, (rank, step) in enumerate(zip(ranks, fault_steps)):
            kind = "down" if i % 2 == 0 else "slow"
            events.append(
                FaultEvent(step=step, kind=kind, rank=int(rank), slowdown=slowdown)
            )
            if recover:
                events.append(
                    FaultEvent(
                        step=min(steps - 1, step + 2), kind="recover", rank=int(rank)
                    )
                )
        return cls(events, world, label=f"seeded:{seed}")

    def __repr__(self) -> str:
        return (
            f"FaultPlan(world={self.world}, events={len(self.events)}, "
            f"label={self.label!r})"
        )


def load_fault_plan(
    world: Optional[int] = None, env: Optional[Mapping[str, str]] = None
) -> Optional[FaultPlan]:
    """The ``ADAPCC_FAULT_PLAN`` funnel: None when the env is unset, the
    parsed plan otherwise.  A set-but-broken value (missing file, malformed
    JSON, world mismatch) raises loudly — a typo'd injection artifact must
    never silently run a healthy world (the ADAPCC_MERGE_ROUNDS policy).
    One shared funnel with ``ADAPCC_CONGESTION_PROFILE``
    (:func:`adapcc_tpu.utils.artifacts.load_env_json_artifact`)."""
    from adapcc_tpu.utils.artifacts import load_env_json_artifact

    return load_env_json_artifact(
        FAULT_PLAN_ENV,
        FaultPlan.from_dict,
        kind="fault-plan",
        world=world,
        env=env,
        mismatch_hint="injecting it as-is would shift which ranks die",
    )
