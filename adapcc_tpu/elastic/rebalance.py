"""Elastic ZeRO-1 re-balance: reshard the flat optimizer state on a world
change, validated through the checkpoint layout-tag funnel.

PR 1's checkpoint layout tags (``extra["zero1_layout"] = {ring, align,
world}``) exist precisely so a ZeRO-1 master restored under the wrong
geometry fails loudly.  Elastic shrink/grow is the one *legitimate* layout
change: the flat ``[world, N/world]`` master and its mirrored optimizer
shards are gathered back to the canonical flat vector (undoing any ring
chunk ownership), re-padded and re-split for the new world, and re-tagged.
The result then flows through the EXISTING ``apply_snapshot`` load funnel,
whose layout guard verifies the re-tagged snapshot against the resuming
optimizer's declared geometry — so a reshard is exactly as validated as a
resume, and an un-resharded snapshot still refuses to load.
"""

from __future__ import annotations

from typing import Any, Mapping, Optional, Tuple

import jax
import numpy as np

from adapcc_tpu.checkpoint import TrainCheckpointState
from adapcc_tpu.parallel.fsdp import Zero1Optimizer, _flatten_meta


def _require_layout(extra: Optional[Mapping[str, Any]]) -> Mapping[str, Any]:
    layout = (extra or {}).get(Zero1Optimizer.LAYOUT_KEY)
    if layout is None:
        raise ValueError(
            "snapshot carries no zero1 layout tag (extra["
            f"{Zero1Optimizer.LAYOUT_KEY!r}]); cannot re-balance a master "
            "of unknown chunk geometry — save with "
            "Zero1Optimizer.checkpoint_extra() first"
        )
    return layout


def _to_canonical_flat(
    rows: np.ndarray, layout: Mapping[str, Any], total: int
) -> np.ndarray:
    """``[old_world, L_old]`` shard rows → the canonical flat ``[total]``
    vector (ring ownership unrolled, padding dropped)."""
    world = int(layout["world"])
    if rows.ndim != 2 or rows.shape[0] != world:
        raise ValueError(
            f"expected a [world={world}, shard] array, got shape {rows.shape}"
        )
    if layout.get("ring"):
        # init() assigned row r ← chunk (r+1) % world (jnp.roll(..., -1));
        # rolling +1 restores chunk order
        rows = np.roll(rows, 1, axis=0)
    flat = rows.reshape(-1)
    if flat.size < total:
        raise ValueError(
            f"flat master holds {flat.size} elements but the param tree "
            f"needs {total}; the snapshot belongs to a different model"
        )
    return flat[:total]


def _to_layout_rows(
    flat: np.ndarray, meta, world: int, ring: bool
) -> np.ndarray:
    """Canonical flat ``[total]`` vector → ``[new_world, L_new]`` rows in
    the target layout (padded, ring-rolled when the target rides the ring)."""
    padded = np.pad(flat, (0, meta.padded - flat.size))
    rows = padded.reshape(world, meta.padded // world)
    if ring:
        rows = np.roll(rows, -1, axis=0)
    return rows


def rebalance_zero1_pair(
    opt_pair: Tuple[Any, Any],
    params: Any,
    old_layout: Mapping[str, Any],
    new_opt: Zero1Optimizer,
) -> Tuple[np.ndarray, Any]:
    """Reshard a ``(master [old_world, L], opt-state shards)`` pair onto
    ``new_opt``'s geometry.

    The optimizer shards mirror the master's layout leaf-by-leaf
    (``vmap(tx.init)`` over the master rows): per-element moment buffers
    ``[old_world, L]`` reshard exactly like the master; per-shard scalars
    (adam's ``count``, shape ``[old_world]``) are world-replicated by
    construction, so the first row's value fans out to the new world.
    Padding regions hold zeros on both sides of the move (gradients never
    land there), so truncate-and-repad is lossless.
    """
    master, opt_state = opt_pair
    old_world = int(old_layout["world"])
    old_align = int(old_layout.get("align", 1))
    new_layout = new_opt.layout_metadata()
    meta_old = _flatten_meta(params, old_world, old_align)
    meta_new = _flatten_meta(params, new_opt.world, new_opt._align())
    total = meta_old.total
    if meta_new.total != total:
        raise ValueError(
            f"param tree sizes disagree: {total} vs {meta_new.total}"
        )

    def reshard_rows(leaf: np.ndarray) -> np.ndarray:
        flat = _to_canonical_flat(np.asarray(leaf), old_layout, total)
        return _to_layout_rows(
            flat, meta_new, new_opt.world, bool(new_layout["ring"])
        ).astype(np.asarray(leaf).dtype)

    new_master = reshard_rows(np.asarray(master))

    def one(leaf):
        arr = np.asarray(leaf)
        if arr.shape == (old_world, meta_old.padded // old_world):
            return reshard_rows(arr)
        if arr.shape == (old_world,):
            # per-shard scalar (e.g. adam count): replicated by construction
            return np.full((new_opt.world,), arr[0], arr.dtype)
        if arr.shape == ():
            return arr
        raise ValueError(
            f"cannot re-balance optimizer leaf of shape {arr.shape}; "
            f"expected [{old_world}, shard], [{old_world}] or scalar"
        )

    new_opt_state = jax.tree_util.tree_map(one, opt_state)
    # record the target meta so the resharded pair is immediately usable
    # by new_opt.apply() without an init() that would reset the master
    new_opt._meta = meta_new
    new_opt._compiled = None
    return new_master, new_opt_state


def reshard_zero1_snapshot(
    snapshot: TrainCheckpointState,
    params: Any,
    new_opt: Zero1Optimizer,
) -> TrainCheckpointState:
    """Re-balance a tagged ZeRO-1 snapshot onto ``new_opt``'s world and
    validate the result at the EXISTING ``apply_snapshot`` load funnel.

    The returned state was produced by applying the re-tagged snapshot to a
    receiving state that *declares* the new layout — so the same guard that
    blocks a mis-matched resume has positively verified this reshard, and
    ``new_opt.restore(returned_state)`` places the pair on the new mesh.
    """
    old_layout = _require_layout(snapshot.extra)
    new_pair = rebalance_zero1_pair(
        snapshot.opt_state, params, old_layout, new_opt
    )
    resharded = TrainCheckpointState(
        params=snapshot.params,
        opt_state=new_pair,
        epoch=snapshot.epoch,
        step=snapshot.step,
        best_metric=snapshot.best_metric,
        extra=new_opt.checkpoint_extra(
            {k: v for k, v in (snapshot.extra or {}).items()
             if k != Zero1Optimizer.LAYOUT_KEY}
        ),
    )
    # the load funnel: a receiver declaring the NEW layout applies the
    # re-tagged snapshot; the layout guard runs on this exact path
    receiver = TrainCheckpointState(
        params=params,
        opt_state=new_pair,  # template with the target structure
        extra=new_opt.checkpoint_extra(),
    )
    receiver.apply_snapshot(resharded.capture_snapshot())
    return receiver


def shrink_zero1_trainer_state(
    trainer,
    state,
    old_world: Optional[int] = None,
):
    """Re-balance a ZeRO-1 :class:`~adapcc_tpu.ddp.trainer.TrainState`
    produced under a LARGER world onto ``trainer``'s (already smaller)
    mesh — the mid-run shrink path.

    ``trainer`` must be a ``zero1=True`` DDPTrainer whose ``init_state``
    has been called once (so its optimizer geometry exists); ``state`` is
    the old-world TrainState.  Returns a TrainState on the new world with
    identical canonical master/opt content, validated through the
    checkpoint funnel.
    """
    return _rebalance_zero1_trainer_state(
        trainer, state, old_world, direction="shrink"
    )


def grow_zero1_trainer_state(
    trainer,
    state,
    old_world: Optional[int] = None,
):
    """The grow-back twin of :func:`shrink_zero1_trainer_state`: re-balance
    a ZeRO-1 TrainState produced under a SMALLER world onto ``trainer``'s
    (already larger) mesh — the rejoin path (docs/RECOVERY.md §3).  Same
    gather → re-split → re-tag cycle through the same ``apply_snapshot``
    layout-guard funnel; only the direction check differs, so a rejoin is
    exactly as validated as a shrink or a resume.
    """
    return _rebalance_zero1_trainer_state(
        trainer, state, old_world, direction="grow"
    )


def _rebalance_zero1_trainer_state(
    trainer,
    state,
    old_world: Optional[int],
    direction: str,
):
    from adapcc_tpu.ddp.trainer import TrainState

    opt = trainer._zero1_opt
    if opt is None:
        raise ValueError(
            "call trainer.init_state(params) once before re-balancing into "
            "it: the target optimizer geometry comes from the constructed "
            "Zero1Optimizer"
        )
    master, opt_state = state.opt_state
    if old_world is None:
        old_world = int(np.asarray(master).shape[0])
    if direction == "shrink" and old_world < opt.world:
        raise ValueError(
            f"shrink_zero1_trainer_state: old world {old_world} is smaller "
            f"than the target world {opt.world}; a rejoin that grows the "
            "shard layout goes through grow_zero1_trainer_state"
        )
    if direction == "grow" and old_world > opt.world:
        raise ValueError(
            f"grow_zero1_trainer_state: old world {old_world} is larger "
            f"than the target world {opt.world}; a world loss goes through "
            "shrink_zero1_trainer_state"
        )
    # the OLD layout: same ring/align discipline as the target (one trainer
    # configuration, two worlds) — only the world differs
    old_layout = dict(opt.layout_metadata())
    old_layout["world"] = int(old_world)
    snap = TrainCheckpointState(
        params=state.params,
        opt_state=(np.asarray(master), jax.device_get(opt_state)),
        step=int(state.step),
        extra={Zero1Optimizer.LAYOUT_KEY: old_layout},
    )
    restored = reshard_zero1_snapshot(snap, state.params, opt)
    new_master, new_opt_state = opt.restore(restored)
    # replicated leaves (params, step, model collections) were committed to
    # the OLD mesh's devices; re-place them on the new mesh or the first
    # step dies on a device mismatch between params and the resharded pair
    from jax.sharding import NamedSharding, PartitionSpec as P

    replicated = NamedSharding(opt.mesh, P())

    def replace(tree):
        return jax.tree_util.tree_map(
            lambda leaf: jax.device_put(jax.device_get(leaf), replicated)
            if isinstance(leaf, jax.Array) else leaf,
            tree,
        )

    return TrainState(
        params=replace(state.params),
        opt_state=(new_master, new_opt_state),
        step=replace(state.step),
        model_state=replace(state.model_state),
    )


def recover_zero1_trainer_state(
    trainer,
    state,
    dead,
    store,
    expect_step: Optional[int] = None,
):
    """Repair a ZeRO-1 TrainState whose ``dead`` ranks' shards are lost,
    from their in-fabric replicas (docs/RECOVERY.md §1) — **no checkpoint
    reload on the hot path**.

    ``store`` is the :class:`~adapcc_tpu.elastic.redundancy.
    ShardReplicaStore` that captured the post-step replica rows;
    ``expect_step`` (default: the state's own step counter) is the
    freshness guard — a replica stamped with a different step refuses
    loudly rather than silently rewinding one shard's optimizer state
    relative to its peers.  The repaired pair flows through the SAME
    ``reshard_zero1_snapshot`` → ``apply_snapshot`` layout-guard funnel as
    a shrink or a resume (a same-world reshard is the identity move, so
    the funnel purely validates), and the result is re-placed on the
    trainer's mesh.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    from adapcc_tpu.ddp.trainer import TrainState

    opt = trainer._zero1_opt
    if opt is None:
        raise ValueError(
            "call trainer.init_state(params) once before recovering into "
            "it: the target optimizer geometry comes from the constructed "
            "Zero1Optimizer"
        )
    if expect_step is None:
        expect_step = int(np.asarray(jax.device_get(state.step)))
    master, opt_state = store.reconstruct(
        state.opt_state, dead, step=expect_step
    )
    snap = TrainCheckpointState(
        params=state.params,
        opt_state=(master, opt_state),
        step=int(expect_step),
        extra=opt.checkpoint_extra(),
    )
    restored = reshard_zero1_snapshot(snap, state.params, opt)
    new_master, new_opt_state = opt.restore(restored)
    replicated = NamedSharding(opt.mesh, P())

    def replace(tree):
        return jax.tree_util.tree_map(
            lambda leaf: jax.device_put(jax.device_get(leaf), replicated)
            if isinstance(leaf, jax.Array) else leaf,
            tree,
        )

    return TrainState(
        params=replace(state.params),
        opt_state=(new_master, new_opt_state),
        step=replace(state.step),
        model_state=replace(state.model_state),
    )
