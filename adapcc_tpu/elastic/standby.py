"""Standby plan cache: pre-compiled degraded plans for hot failover.

GC3 treats communication schedules as compiled programs that must be
*swapped*, not patched; TACCL shows degraded strategies are cheap to
re-synthesize when the topology sketch changes (PAPERS.md).  This module
does both ahead of time: for every plausible world shrink (each one-rank-
down, each one-host-down), a strategy is re-emitted over the alive subset
(dead ranks pushed to prunable leaf tails — relay masks are already in the
IR), the candidates are sim-ranked on the calibrated α-β replay, and the
top-k winners are AOT-compiled against the live engine — so when the
coordinator's WorldView actually shrinks, the swap is a dispatch-time
cache-key switch, not a cold recompile stall.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, FrozenSet, Iterable, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from adapcc_tpu.strategy.ir import Strategy, Tree


def degraded_scenarios(
    world: int,
    ips: Optional[Mapping[int, str]] = None,
    include_hosts: bool = True,
) -> List[Tuple[str, FrozenSet[int]]]:
    """The shrink shapes worth pre-compiling: every one-rank-down subset,
    plus (multi-host worlds) every one-host-down subset — the preemptible-
    pod failure units.  Labels are stable and deterministic."""
    if world < 2:
        return []
    everyone = frozenset(range(world))
    out: List[Tuple[str, FrozenSet[int]]] = [
        (f"rank{r}-down", everyone - {r}) for r in range(world)
    ]
    if include_hosts and ips:
        hosts: Dict[str, set] = {}
        for r in range(world):
            hosts.setdefault(ips.get(r, ""), set()).add(r)
        if len(hosts) > 1:
            for host, ranks in sorted(hosts.items()):
                if len(ranks) < world:  # never the whole world
                    out.append((f"host[{host}]-down", everyone - ranks))
    # the one-rank scenarios subsume single-rank hosts; dedup by subset
    seen: Dict[FrozenSet[int], str] = {}
    deduped = []
    for label, active in out:
        if active not in seen:
            seen[active] = label
            deduped.append((label, active))
    return deduped


def reemit_for_active(
    world: int,
    active: Iterable[int],
    ips: Optional[Mapping[int, str]] = None,
    num_trans: int = 1,
    shape: str = "ring",
    like: Optional[Strategy] = None,
) -> Strategy:
    """Re-emit a strategy over the alive subset.

    The IR requires trees to span the full world (relay masks are runtime
    state), so "over the alive subset" means: alive ranks form the working
    chain/heap, dead ranks hang off the tail as prunable leaf subtrees —
    :func:`adapcc_tpu.comm.relay.prune_reduce_rounds` then drops every
    dead edge, and the simulated replay prices exactly the alive-only
    schedule.  Roots rotate over ALIVE ranks only: a dead root could never
    source a broadcast (the engine rejects that loudly).

    ``like`` carries the incumbent strategy's data-plane settings —
    synthesized ``chunk_bytes`` and ``wire_dtype`` — into the degraded
    plan: a failover must not silently downgrade the wire format or reset
    the ring granularity during exactly the window the fabric is already
    degraded.
    """
    act = sorted(set(int(r) for r in active))
    if not act:
        raise ValueError("cannot re-emit a strategy for an empty active set")
    bad = [r for r in act if not 0 <= r < world]
    if bad:
        raise ValueError(f"active ranks {bad} outside world [0, {world})")
    dead = [r for r in range(world) if r not in act]
    ips = dict(ips or {})
    trees: List[Tree] = []
    n = len(act)
    for t in range(max(1, num_trans)):
        order = [act[(t + i) % n] for i in range(n)] + dead
        children: Dict[int, List[int]] = {}
        if shape == "ring":
            for i in range(len(order) - 1):
                children[order[i]] = [order[i + 1]]
        elif shape == "binary":
            for i in range(len(order)):
                kids = [order[j] for j in (2 * i + 1, 2 * i + 2) if j < len(order)]
                if kids:
                    children[order[i]] = kids
        else:
            raise ValueError(f"unknown degraded shape {shape!r}")
        trees.append(Tree(order[0], children, ips))
    out = Strategy(
        trees, world, synthesis=f"degraded-{shape}", shares=None
    )
    if like is not None:
        out.chunk_bytes = like.chunk_bytes
        out.wire_dtype = like.wire_dtype
    return out


@dataclass
class StandbyPlan:
    """One pre-ranked degraded plan: the strategy to swap to when the
    world shrinks to ``active``."""

    label: str
    active: FrozenSet[int]
    strategy: Strategy
    predicted_s: float
    #: whether the engine's compiled-program cache was pre-populated
    warmed: bool = False

    def to_row(self) -> dict:
        return {
            "label": self.label,
            "active": sorted(self.active),
            "strategy": self.strategy.synthesis,
            "pred_time_us": round(self.predicted_s * 1e6, 3),
            "warmed": self.warmed,
        }


class StandbyPlanCache:
    """Epoch-keyed standby plans over one :class:`CollectiveEngine`.

    Lifecycle::

        cache = StandbyPlanCache(engine, nbytes=grad_bytes)
        cache.build()                      # sim-rank every shrink scenario
        cache.warm(shape, dtype)           # AOT-compile the top-k plans
        ...
        plan, epoch = cache.activate(worldview.alive)   # dispatch-time swap
        ...
        epoch = cache.restore_full()       # recovery: back to the base plan

    ``activate`` looks the alive set up in the cache; a hit swaps the
    engine's strategy under a fresh epoch with the compiled programs
    already warm.  A miss (an unanticipated multi-failure shape) re-emits
    on the spot — correct, but a cold compile at the first dispatch, which
    the plan row records as ``warmed=False`` so the stall is attributable.
    """

    def __init__(
        self,
        engine,
        nbytes: float = 16 * 1024 * 1024,
        top_k: int = 4,
        cost_model=None,
        num_trans: Optional[int] = None,
        shapes: Sequence[str] = ("ring", "binary"),
        include_hosts: bool = True,
        sim_engine: Optional[str] = None,
    ) -> None:
        if top_k < 1:
            raise ValueError(f"top_k must be >= 1, got {top_k}")
        self.engine = engine
        self.nbytes = float(nbytes)
        self.top_k = top_k
        self.shapes = tuple(shapes)
        self.include_hosts = include_hosts
        #: replay engine for the scenario sweep (None → arg/env/auto funnel,
        #: docs/SIMULATION.md §7).  ``build()`` prices O(world) scenarios ×
        #: shapes; at pod scale the vectorized path's fingerprint-keyed
        #: column cache turns the sweep's repeated masks into re-prices
        self.sim_engine = sim_engine
        self.num_trans = (
            num_trans if num_trans is not None else engine.strategy.num_trans
        )
        if cost_model is None:
            from adapcc_tpu.sim.calibrate import load_or_default

            cost_model = load_or_default(world=engine.world_size)
        self._ips = dict(engine.strategy.trees[0].ips or {})
        if cost_model.ips is None and self._ips:
            cost_model = cost_model.with_ips(self._ips)
        self.cost_model = cost_model
        #: base (full-world) strategy to restore on recovery
        self.base_strategy = engine.strategy
        self.plans: Dict[FrozenSet[int], StandbyPlan] = {}
        #: full-world CANDIDATE strategies warmed for the online
        #: re-adaptation loop (docs/ADAPT.md), keyed by strategy
        #: fingerprint — the shrink plans above key by alive subset, but a
        #: re-ranked challenger keeps the whole world and only changes shape
        self.adaptive: Dict[str, StandbyPlan] = {}

    # -- construction ----------------------------------------------------------

    def _best_for(self, label: str, active: FrozenSet[int]) -> StandbyPlan:
        """Sim-rank the re-emitted candidate shapes for one shrink scenario
        on the degraded replay (dead edges pruned) and keep the fastest;
        ties break by shape order so "ring" survives a prediction-identical
        alternative (no plan churn for nothing)."""
        from adapcc_tpu.sim.rank import relay_latency

        world = self.engine.world_size
        best: Optional[StandbyPlan] = None
        for shape in self.shapes:
            strategy = reemit_for_active(
                world, active, self._ips, self.num_trans, shape,
                like=self.base_strategy,
            )
            seconds = relay_latency(
                strategy, self.cost_model, self.nbytes, sorted(active),
                engine=self.sim_engine,
            )
            if best is None or seconds < best.predicted_s:
                best = StandbyPlan(label, active, strategy, seconds)
        assert best is not None  # self.shapes is never empty
        return best

    def build(self) -> List[StandbyPlan]:
        """Re-emit + sim-rank every shrink scenario; returns the plans
        fastest-first (the warm order)."""
        self.plans = {}
        for label, active in degraded_scenarios(
            self.engine.world_size, self._ips, self.include_hosts
        ):
            self.plans[active] = self._best_for(label, active)
        return self.ranked()

    def ranked(self) -> List[StandbyPlan]:
        return sorted(
            self.plans.values(), key=lambda p: (p.predicted_s, p.label)
        )

    # -- AOT compile -----------------------------------------------------------

    def warm(
        self,
        shape: Tuple[int, ...],
        dtype=np.float32,
        primitives: Sequence[str] = ("all_reduce",),
        top_k: Optional[int] = None,
    ) -> List[StandbyPlan]:
        """AOT-compile the top-k plans' programs for a ``[world, *shape]``
        payload: one throwaway zeros dispatch per (plan, primitive) under
        the temporarily-swapped strategy populates the engine's compiled-
        program cache, keyed by the standby fingerprint.  After this, a
        real failover's first dispatch is a cache hit (`cache_hit: true`
        in the dispatch trace) — the no-recompile property the elastic
        acceptance test pins."""
        import jax.numpy as jnp

        if not self.plans:
            self.build()
        k = top_k if top_k is not None else self.top_k
        warmed = []
        engine = self.engine
        zeros = jnp.zeros((engine.world_size,) + tuple(shape), dtype)
        for plan in self.ranked()[:k]:
            saved = engine.strategy
            engine.strategy = plan.strategy
            try:
                for prim in primitives:
                    getattr(engine, prim)(
                        zeros, active_gpus=sorted(plan.active)
                    )
            finally:
                engine.strategy = saved
            plan.warmed = True
            warmed.append(plan)
        return warmed

    def warm_strategy(
        self,
        strategy: Strategy,
        shape: Tuple[int, ...],
        dtype=np.float32,
        primitives: Sequence[str] = ("all_reduce",),
        label: Optional[str] = None,
        predicted_s: float = 0.0,
    ) -> StandbyPlan:
        """AOT-compile a full-world CANDIDATE strategy — the online
        re-adaptation half of this cache (docs/ADAPT.md §4).

        Same temporary-swap warm as :meth:`warm`, but for an arbitrary
        re-ranked strategy instead of a shrink scenario: one throwaway
        zeros dispatch per primitive populates the engine's compiled-
        program cache under the candidate's fingerprint, so a later
        :meth:`adopt` is a dispatch-time cache-key switch (``cache_hit:
        true`` on the first post-swap dispatch — the same no-recompile
        property the elastic failover pins).  ``predicted_s`` records the
        sim-ranked steady state that nominated the candidate.
        """
        import jax.numpy as jnp

        engine = self.engine
        if strategy.world_size != engine.world_size:
            raise ValueError(
                f"candidate strategy world {strategy.world_size} != engine "
                f"world {engine.world_size}"
            )
        active = frozenset(range(engine.world_size))
        plan = StandbyPlan(
            label or f"adapt-{strategy.fingerprint()[:8]}",
            active,
            strategy,
            float(predicted_s),
        )
        zeros = jnp.zeros((engine.world_size,) + tuple(shape), dtype)
        saved = engine.strategy
        engine.strategy = strategy
        try:
            for prim in primitives:
                getattr(engine, prim)(zeros, active_gpus=sorted(active))
        finally:
            engine.strategy = saved
        plan.warmed = True
        self.adaptive[strategy.fingerprint()] = plan
        return plan

    def warm_leader_alternatives(
        self,
        shape: Tuple[int, ...],
        dtype=np.float32,
        primitives: Sequence[str] = ("all_reduce",),
    ) -> List[StandbyPlan]:
        """Per-LEVEL standby plans (docs/HIERARCHY.md §5): when the
        engine's strategy is a composed two-level plan, pre-compile the
        composed program for every leader schedule the DCN level could
        re-solve to — so a drift-localized leader swap
        (:func:`adapcc_tpu.strategy.hierarchy.resolve_leader_level`) is a
        dispatch-time cache hit even when it lands on the schedule the
        healthy solve did NOT pick.  The pod level is shared by
        construction (the variants differ only across leaders).  No-op on
        engines without a composed plan."""
        from adapcc_tpu.strategy.hierarchy import (
            LEADER_ALGOS,
            leader_variant,
            plan_of,
        )

        plan = plan_of(self.engine.strategy)
        if plan is None:
            return []
        warmed: List[StandbyPlan] = []
        for algo in LEADER_ALGOS:
            if algo == plan.leader_algo:
                continue  # the incumbent's own program is already live
            variant = leader_variant(plan, algo)
            warmed.append(
                self.warm_strategy(
                    variant.strategy,
                    shape,
                    dtype,
                    primitives,
                    label=f"leader-{algo}",
                )
            )
        return warmed

    def adopt(self, strategy: Strategy) -> int:
        """Hot-swap the engine onto a candidate strategy under a fresh
        epoch (the adoption half of :meth:`warm_strategy`): one
        ``advance_epoch`` call — compiled programs stay cached under their
        fingerprints, so a warmed candidate's first dispatch replays warm.
        Returns the new epoch."""
        return self.engine.advance_epoch(strategy)

    # -- failover --------------------------------------------------------------

    def plan_for(self, active: Iterable[int]) -> StandbyPlan:
        key = frozenset(int(r) for r in active)
        hit = self.plans.get(key)
        if hit is not None:
            return hit
        # unanticipated shrink shape (multi-failure): re-emit on the spot —
        # correct but cold; the plan row says so
        plan = self._best_for(f"adhoc-{sorted(key)}", key)
        self.plans[key] = plan
        return plan

    def activate(self, active: Iterable[int]) -> Tuple[StandbyPlan, int]:
        """Swap the engine to the plan for ``active`` under a fresh epoch.
        Returns ``(plan, epoch)``; collectives in flight against the old
        epoch raise :class:`~adapcc_tpu.comm.engine.EpochMismatch` and
        retry at the Communicator layer."""
        plan = self.plan_for(active)
        epoch = self.engine.advance_epoch(plan.strategy)
        return plan, epoch

    def restore_full(self) -> int:
        """Recovery: swap back to the base full-world strategy (its
        programs never left the cache) under a fresh epoch."""
        return self.engine.advance_epoch(self.base_strategy)
