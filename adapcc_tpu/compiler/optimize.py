"""Optimizer pass pipeline over :class:`~adapcc_tpu.compiler.ir.ScheduleProgram`.

PR 15 made the IR the one program form every plane shares; this module is
the pass pipeline between a verified schedule and the wire (the GC3
optimizing-compiler gap, PAPERS.md), sitting where ``engine.all_reduce
(algo="ir")`` resolves its program:

- ``dce`` — dead-copy/identity-relay elimination: a ``copy`` delivered to
  a relay rank whose value is never read again (no later send, no later
  reduce at that (rank, chunk)) is wire traffic with no observer — relays
  have no delivery obligation, so the whole message group goes.  Runs to
  fixpoint: removing one dead delivery can orphan the one feeding it.
- ``fuse_codec`` — encode→send and recv→decode step groups rewrite into
  fused wire ops: the ``codec`` moves onto the ``send``/``recv`` pair and
  the separate encode/decode steps disappear, so the lowering ships the
  codec's REAL transport arrays (``quant/codec.py`` block math) instead
  of locally round-tripping and shipping fp32 — wire bytes in the
  dispatch trace then reflect the executed codec.
- ``coalesce`` — superstep coalescing: unit message groups in one round
  with the same (src, dst, consumer kind, codec) and contiguous chunks
  merge into single ``span`` steps, so the lowering issues one ppermute
  over a concatenated chunk buffer where the naive program issued one per
  chunk — a w-chunk recursive-doubling round drops from O(chunks) to one
  dispatch.

Passes apply in that canonical order (``PASS_NAMES``), each one verified
pass-in/pass-out through ``compiler/verify.py`` — an optimizer bug dies
at the rewrite, naming the offending (rank, round, chunk), never at a
traced collective.  ``ADAPCC_IR_OPT`` (off | on | comma list of pass
names, default on) gates the pipeline for A/B runs; a malformed value is
a loud error.  Passes that change nothing return the input object, so an
already-optimal program (the segmented ring) keeps its identity and its
fingerprint — only real rewrites stamp ``applied_passes``.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union

from adapcc_tpu.compiler.ir import ScheduleProgram, Step

#: the env knob gating the pipeline: "off" | "on" | comma list of passes
IR_OPT_ENV = "ADAPCC_IR_OPT"

#: registered passes in canonical application order
PASS_NAMES = ("dce", "fuse_codec", "coalesce")


def resolve_ir_opt(value: Optional[str] = None) -> Tuple[str, ...]:
    """The optimizer passes in force: ``ADAPCC_IR_OPT`` env > the explicit
    argument > the default (``on`` = every pass).  Returns pass names in
    canonical order; a malformed value raises — a typo'd
    ``ADAPCC_IR_OPT=coalesse`` silently running naive lowering would
    invalidate the A/B it was meant to drive (the ADAPCC_COLL_ALGO
    policy)."""
    env = os.environ.get(IR_OPT_ENV)
    raw = env if env is not None and env.strip() else value
    if raw is None:
        raw = "on"
    v = str(raw).strip().lower()
    if v == "off":
        return ()
    if v == "on":
        return PASS_NAMES
    names = [p.strip() for p in v.split(",") if p.strip()]
    bad = [p for p in names if p not in PASS_NAMES]
    if bad or not names:
        raise ValueError(
            f"{IR_OPT_ENV}/ir_opt={raw!r}: expected off|on or a comma list "
            f"drawn from {'|'.join(PASS_NAMES)}"
        )
    return tuple(p for p in PASS_NAMES if p in names)


# --------------------------------------------------------------------------- #
# round parsing shared by the passes: unit message groups
# --------------------------------------------------------------------------- #


@dataclasses.dataclass
class _Message:
    """One unit message group of one round: send/recv plus consumer, with
    either the legacy encode/decode pair or a fused wire codec."""

    src: int
    dst: int
    chunk: int
    action: str                      # "reduce" | "copy"
    fused_codec: Optional[str]       # codec on the send/recv steps
    legacy_codec: Optional[str]      # codec on encode/decode steps

    def steps(self, span: int = 1) -> List[Step]:
        out: List[Step] = []
        if self.legacy_codec is not None:
            out.append(Step("encode", self.src, self.chunk,
                            codec=self.legacy_codec, span=span))
        out.append(Step("send", self.src, self.chunk, peer=self.dst,
                        codec=self.fused_codec, span=span))
        out.append(Step("recv", self.dst, self.chunk, peer=self.src,
                        codec=self.fused_codec, span=span))
        if self.legacy_codec is not None:
            out.append(Step("decode", self.dst, self.chunk,
                            codec=self.legacy_codec, span=span))
        out.append(Step(self.action, self.dst, self.chunk, span=span))
        return out


def _parse_round(rnd: Sequence[Step]) -> Optional[List[_Message]]:
    """Parse a round into unit message groups, or ``None`` when the round
    does not decompose cleanly (span steps already present, orphan steps):
    passes skip what they cannot prove, they never guess."""
    sends: Dict[Tuple[int, int], Step] = {}
    recvs: Dict[Tuple[int, int], Step] = {}
    consumers: Dict[Tuple[int, int], Step] = {}
    encodes: Dict[Tuple[int, int], Step] = {}
    decodes: Dict[Tuple[int, int], Step] = {}
    order: List[Tuple[int, int, int]] = []  # (src, dst, chunk) in send order
    for step in rnd:
        if step.span != 1:
            return None
        key = (step.rank, step.chunk)
        if step.kind == "send":
            if key in sends:
                return None
            sends[key] = step
            order.append((step.rank, step.peer, step.chunk))
        elif step.kind == "recv":
            if key in recvs:
                return None
            recvs[key] = step
        elif step.kind in ("reduce", "copy"):
            if key in consumers:
                return None
            consumers[key] = step
        elif step.kind == "encode":
            encodes[key] = step
        elif step.kind == "decode":
            decodes[key] = step
    messages: List[_Message] = []
    used = 0
    for src, dst, chunk in order:
        send = sends[(src, chunk)]
        recv = recvs.get((dst, chunk))
        consumer = consumers.get((dst, chunk))
        if recv is None or recv.peer != src or consumer is None:
            return None
        enc = encodes.get((src, chunk))
        dec = decodes.get((dst, chunk))
        if (enc is None) != (dec is None):
            return None
        if send.codec != recv.codec:
            return None
        messages.append(_Message(
            src=src, dst=dst, chunk=chunk, action=consumer.kind,
            fused_codec=send.codec,
            legacy_codec=enc.codec if enc is not None else None,
        ))
        used += 3 + (2 if enc is not None else 0)
    if used != len(rnd):
        return None  # orphan steps: leave the round untouched
    return messages


def _rebuild(program: ScheduleProgram, rounds: List[Tuple[Step, ...]],
             **overrides) -> ScheduleProgram:
    return dataclasses.replace(
        program, rounds=tuple(rounds), **overrides
    )


# --------------------------------------------------------------------------- #
# the passes
# --------------------------------------------------------------------------- #


def dce_pass(program: ScheduleProgram) -> ScheduleProgram:
    """Dead-copy elimination under relay masks (module doc).  Identity on
    programs without relays."""
    if not program.relays:
        return program
    relays = set(program.relays)
    parsed = [_parse_round(rnd) for rnd in program.rounds]
    changed = False
    while True:
        # reads of (rank, chunk) per round: any send from it, any reduce
        # into it (the local operand feeds the combine)
        dead: List[Tuple[int, _Message]] = []
        for i, messages in enumerate(parsed):
            if messages is None:
                continue
            for m in messages:
                if m.action != "copy" or m.dst not in relays:
                    continue
                read_later = False
                for j in range(i + 1, len(parsed)):
                    later = parsed[j]
                    if later is None:
                        read_later = True  # unparseable round: assume read
                        break
                    for n in later:
                        if (n.src == m.dst and n.chunk == m.chunk) or (
                            n.dst == m.dst and n.chunk == m.chunk
                            and n.action == "reduce"
                        ):
                            read_later = True
                            break
                    if read_later:
                        break
                if not read_later:
                    dead.append((i, m))
        if not dead:
            break
        changed = True
        for i, m in dead:
            parsed[i].remove(m)
    if not changed:
        return program
    rounds: List[Tuple[Step, ...]] = []
    for i, messages in enumerate(parsed):
        if messages is None:
            rounds.append(program.rounds[i])
        else:
            steps: List[Step] = []
            for m in messages:
                steps.extend(m.steps())
            if steps:
                rounds.append(tuple(steps))
    return _rebuild(program, rounds)


def fuse_codec_pass(program: ScheduleProgram) -> ScheduleProgram:
    """Fuse encode→send / recv→decode groups into codec-carrying wire ops
    (module doc).  Identity on programs with no encode/decode steps."""
    if not any(
        s.kind in ("encode", "decode") for _, s in program.steps()
    ):
        return program
    changed = False
    rounds: List[Tuple[Step, ...]] = []
    for rnd in program.rounds:
        messages = _parse_round(rnd)
        if messages is None or not any(m.legacy_codec for m in messages):
            rounds.append(rnd)
            continue
        steps: List[Step] = []
        for m in messages:
            if m.legacy_codec is not None:
                m = dataclasses.replace(
                    m, fused_codec=m.legacy_codec, legacy_codec=None
                )
                changed = True
            steps.extend(m.steps())
        rounds.append(tuple(steps))
    if not changed:
        return program
    from adapcc_tpu.quant.codec import DEFAULT_BLOCK_SIZE

    # the fused wire executes the codec's block math on the transport
    # path, so the block size becomes a program property (and a
    # fingerprint component): two fusions with different block geometry
    # are different programs
    return _rebuild(program, rounds, block_size=DEFAULT_BLOCK_SIZE)


def coalesce_pass(program: ScheduleProgram) -> ScheduleProgram:
    """Superstep coalescing: contiguous same-(src, dst, action, codec)
    unit messages in one round merge into single span steps (module doc).
    Identity when no round carries a mergeable run."""
    changed = False
    rounds: List[Tuple[Step, ...]] = []
    for rnd in program.rounds:
        messages = _parse_round(rnd)
        if messages is None:
            rounds.append(rnd)
            continue
        groups: Dict[Tuple, List[_Message]] = {}
        order: List[Tuple] = []
        for m in messages:
            key = (m.src, m.dst, m.action, m.fused_codec, m.legacy_codec)
            if key not in groups:
                groups[key] = []
                order.append(key)
            groups[key].append(m)
        steps: List[Step] = []
        round_changed = False
        for key in order:
            run = sorted(groups[key], key=lambda m: m.chunk)
            i = 0
            while i < len(run):
                j = i
                while (
                    j + 1 < len(run)
                    and run[j + 1].chunk == run[j].chunk + 1
                ):
                    j += 1
                span = j - i + 1
                steps.extend(run[i].steps(span=span))
                if span > 1:
                    round_changed = True
                i = j + 1
        if round_changed:
            changed = True
            rounds.append(tuple(steps))
        else:
            rounds.append(rnd)
    if not changed:
        return program
    return _rebuild(program, rounds)


#: the pass registry: name -> program-to-program rewrite
PASSES: Dict[str, Callable[[ScheduleProgram], ScheduleProgram]] = {
    "dce": dce_pass,
    "fuse_codec": fuse_codec_pass,
    "coalesce": coalesce_pass,
}

_PassSpec = Union[str, Tuple[str, Callable[[ScheduleProgram], ScheduleProgram]]]


def optimize_program(
    program: ScheduleProgram,
    passes: Optional[Sequence[_PassSpec]] = None,
) -> ScheduleProgram:
    """Run the pass pipeline over ``program``: verify pass-in, apply each
    pass, verify pass-out, stamping ``applied_passes`` with the passes
    that actually rewrote the program.

    ``passes=None`` resolves the set from ``ADAPCC_IR_OPT`` (default: all
    of ``PASS_NAMES``); an explicit sequence may name registered passes or
    carry ``(name, callable)`` pairs — the hook the verifier property
    battery uses to prove a broken pass is rejected loudly with the
    offending (rank, round, chunk) named, before anything lowers.
    Returns the input object unchanged when nothing rewrites.
    """
    from adapcc_tpu.compiler.verify import verify_program

    resolved: List[Tuple[str, Callable]] = []
    for p in (resolve_ir_opt() if passes is None else passes):
        if isinstance(p, str):
            if p not in PASSES:
                raise ValueError(
                    f"unknown optimizer pass {p!r}; registered passes: "
                    f"{'|'.join(PASS_NAMES)}"
                )
            resolved.append((p, PASSES[p]))
        else:
            name, fn = p
            resolved.append((str(name), fn))
    verify_program(program)  # pass-in: never rewrite an invalid program
    out = program
    for name, fn in resolved:
        nxt = fn(out)
        if nxt is out or nxt == out:
            continue
        nxt = dataclasses.replace(
            nxt, applied_passes=out.applied_passes + (name,)
        )
        verify_program(nxt)  # pass-out: a broken rewrite dies here, loudly
        out = nxt
    return out
