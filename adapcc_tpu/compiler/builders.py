"""Builders re-emitting today's hand-written planes as ScheduleProgram IR.

Each builder mirrors the **exact combine structure** of the plane it
replaces, so the parity tests can pin IR-lowered execution against the
legacy executor at the tightest tolerance the plane admits:

- :func:`program_from_strategy` — the generic strategy-tree lowering
  behind ``Strategy.schedule_program()``: one chunk per tree, reduce
  rounds aligned by index across trees, then broadcast rounds.  For
  ``Strategy.ring(w, num_trans=w)`` this *is* the segmented
  bandwidth-optimal ring.
- :func:`ring_allreduce_program` — that segmented ring by name.
- :func:`rd_allreduce_program` — recursive halving/doubling at
  world-chunk granularity, mirroring ``comm/latency.py``'s
  ``_halving_rounds``/``_doubling_rounds`` bit arithmetic (same keep-half
  convention, same ``combine(keep, recvd)`` operand order).
- :func:`tree_allreduce_program` — the binomial tree, edges taken from
  the same ``_binomial_rounds`` tables ``binomial_reduce_shard`` runs.
- :func:`two_level_allreduce_program` — the composed hierarchical plan:
  ring reduce-scatter inside each pod, a per-chunk cross-pod binomial
  allreduce on the DCN axis, ring all-gather back inside the pod
  (``comm/two_level.allreduce_two_level_composed_shard``'s phase
  structure; parity is ulp-bounded because that plane's pod phase is an
  XLA ``psum_scatter`` with its own reduction order).

Programs with ``wire_dtype != "off"`` carry explicit encode/decode steps
on every reduce-phase message (broadcast-phase copies ship the already
combined value; quantizing them would double-apply the codec error
relative to the engine's ring plane, which encodes contributions once).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from adapcc_tpu.compiler.ir import ScheduleProgram, Step


def _message(
    src: int,
    dst: int,
    chunk: int,
    action: str,
    codec: Optional[str] = None,
) -> Tuple[Step, ...]:
    """The step group for one message: send/recv plus the consumer, with
    the encode/decode pair when a codec rides the wire."""
    steps: List[Step] = []
    if codec is not None:
        steps.append(Step("encode", src, chunk, codec=codec))
    steps.append(Step("send", src, chunk, peer=dst))
    steps.append(Step("recv", dst, chunk, peer=src))
    if codec is not None:
        steps.append(Step("decode", dst, chunk, codec=codec))
    steps.append(Step(action, dst, chunk))
    return tuple(steps)


def program_from_strategy(strategy, name: Optional[str] = None) -> ScheduleProgram:
    """Lower a ``strategy.ir.Strategy`` to the chunk-granular program form.

    Chunk ``t`` is tree ``t``'s segment (the strategy's parallel-
    transmission sharding, one chunk per tree).  Reduce rounds of all
    trees are aligned by round index — the merged-executor alignment the
    schedule plane already runs — followed by the broadcast rounds.  The
    IR round has no partial-permutation constraint, so the alignment is
    always legal; the lowering re-colors as needed.
    """
    wire = strategy.wire_dtype if strategy.wire_dtype != "off" else None
    reduce_rounds = [t.reduce_rounds() for t in strategy.trees]
    broadcast_rounds = [t.broadcast_rounds() for t in strategy.trees]
    rounds: List[Tuple[Step, ...]] = []
    for per_tree, action, codec in (
        (reduce_rounds, "reduce", wire),
        (broadcast_rounds, "copy", None),
    ):
        depth = max((len(r) for r in per_tree), default=0)
        for i in range(depth):
            steps: List[Step] = []
            for t, tree_rounds in enumerate(per_tree):
                if i < len(tree_rounds):
                    for src, dst in tree_rounds[i].edges:
                        steps.extend(_message(src, dst, t, action, codec))
            if steps:
                rounds.append(tuple(steps))
    return ScheduleProgram(
        name=name or f"strategy-{strategy.synthesis or 'custom'}-w{strategy.world_size}",
        world=strategy.world_size,
        chunks=len(strategy.trees),
        rounds=tuple(rounds),
        wire_dtype=strategy.wire_dtype,
    )


def ring_allreduce_program(world: int, wire_dtype: str = "off") -> ScheduleProgram:
    """The segmented ring: ``Strategy.ring(world, num_trans=world)``
    through the generic lowering — w rotated chains, one chunk each, so
    every round is a full ring permutation and the program prices at the
    bandwidth-optimal ``2(w−1)·(α + β·n/w)``."""
    from adapcc_tpu.strategy.ir import Strategy

    strategy = Strategy.ring(world, num_trans=max(1, world))
    strategy.wire_dtype = wire_dtype
    prog = program_from_strategy(strategy, name=f"ring-seg-w{world}")
    return prog


def rd_allreduce_program(world: int, wire_dtype: str = "off") -> ScheduleProgram:
    """Recursive halving/doubling at world-chunk granularity.

    Power-of-two worlds only, like the plane it mirrors.  Chunk ``c`` is
    the c-th of ``world`` equal segments; at distance ``d`` rank ``me``
    (bit ``(me//d) % 2``) keeps its bit-half of its active range and
    ships the other half to ``me ^ d`` — exactly
    ``comm/latency.py:_halving_rounds``'s convention, so the receiver's
    ``reduce`` lands combine(keep, recvd) in the same operand order and
    the parity is bit-identical.  Doubling reverses the walk with copies.
    """
    if world < 1 or world & (world - 1):
        raise ValueError(f"rd program needs a power-of-two world, got {world}")
    codec = wire_dtype if wire_dtype != "off" else None
    rounds: List[Tuple[Step, ...]] = []
    # active chunk range per rank, narrowed by the rank's own bits
    ranges = [(0, world) for _ in range(world)]
    d = world // 2
    while d >= 1:
        steps: List[Step] = []
        new_ranges = list(ranges)
        for me in range(world):
            lo, hi = ranges[me]
            mid = (lo + hi) // 2
            partner = me ^ d
            if (me // d) % 2 == 0:
                keep, ship = (lo, mid), (mid, hi)
            else:
                keep, ship = (mid, hi), (lo, mid)
            for c in range(*ship):
                steps.extend(_message(me, partner, c, "reduce", codec))
            new_ranges[me] = keep
        ranges = new_ranges
        rounds.append(tuple(steps))
        d //= 2
    d = 1
    while d < world:
        steps = []
        new_ranges = list(ranges)
        for me in range(world):
            lo, hi = ranges[me]
            partner = me ^ d
            for c in range(lo, hi):
                steps.extend(_message(me, partner, c, "copy"))
            plo, phi = ranges[partner]
            new_ranges[me] = (min(lo, plo), max(hi, phi))
        ranges = new_ranges
        rounds.append(tuple(steps))
        d *= 2
    return ScheduleProgram(
        name=f"rd-w{world}",
        world=world,
        chunks=max(1, world),
        rounds=tuple(rounds),
        wire_dtype=wire_dtype,
    )


def tree_allreduce_program(world: int, wire_dtype: str = "off") -> ScheduleProgram:
    """The binomial tree rooted at 0: one chunk, reduce up then broadcast
    down, edges from the same ``_binomial_rounds`` tables the legacy
    ``binomial_reduce_shard``/``binomial_broadcast_shard`` pair executes
    (same edge order ⇒ same combine order ⇒ bit-identical parity)."""
    from adapcc_tpu.comm.latency import _binomial_rounds, _tree_round_tables

    codec = wire_dtype if wire_dtype != "off" else None
    rounds: List[Tuple[Step, ...]] = []
    distances = _binomial_rounds(world)
    for d in distances:
        perm, _ = _tree_round_tables(world, d, 0, up=True)
        steps: List[Step] = []
        for src, dst in perm:
            steps.extend(_message(src, dst, 0, "reduce", codec))
        if steps:
            rounds.append(tuple(steps))
    for d in reversed(distances):
        perm, _ = _tree_round_tables(world, d, 0, up=False)
        steps = []
        for src, dst in perm:
            steps.extend(_message(src, dst, 0, "copy"))
        if steps:
            rounds.append(tuple(steps))
    return ScheduleProgram(
        name=f"tree-binomial-w{world}",
        world=world,
        chunks=1,
        rounds=tuple(rounds),
        wire_dtype=wire_dtype,
    )


def two_level_allreduce_program(
    pods: int, pod_size: int, wire_dtype: str = "off"
) -> ScheduleProgram:
    """The composed hierarchical plan as one flat-world program.

    Rank ``p·S + i`` is member ``i`` of pod ``p``; the payload splits
    into ``S = pod_size`` chunks.  Three phases, matching
    ``allreduce_two_level_composed_shard``'s structure:

    1. ring reduce-scatter inside each pod (S−1 rounds) — member ``i``
       ends holding the pod-partial chunk ``i``;
    2. per-chunk cross-pod allreduce among the member-``i`` ranks
       (binomial reduce to pod 0's member, then broadcast back — the
       ``leader_algo="tree"`` spelling, general in ``pods``);
    3. ring all-gather inside each pod (S−1 rounds).

    DCN-phase volume is 1/S of the payload per member — the composed
    plane's whole point — and the program prices that way through
    ``schedule_program_time``.
    """
    from adapcc_tpu.comm.latency import _binomial_rounds, _tree_round_tables

    if pods < 1 or pod_size < 1:
        raise ValueError(f"need pods >= 1 and pod_size >= 1, got {pods}x{pod_size}")
    world = pods * pod_size
    S = pod_size
    codec = wire_dtype if wire_dtype != "off" else None
    rounds: List[Tuple[Step, ...]] = []

    def member(p: int, i: int) -> int:
        return p * S + i

    # phase 1: ring reduce-scatter within each pod over member index.
    # Round r: member i ships chunk (i - r) mod S to member (i+1) mod S;
    # chunk c travels i = c+r → c+r+1, so after S-1 rounds it sits fully
    # pod-reduced at member (c-1) mod S — member i owns chunk (i+1) mod S
    for r in range(S - 1):
        steps: List[Step] = []
        for p in range(pods):
            for i in range(S):
                c = (i - r) % S
                steps.extend(
                    _message(member(p, i), member(p, (i + 1) % S), c, "reduce", codec)
                )
        if steps:
            rounds.append(tuple(steps))
    # after the RS walk, chunk c sits fully pod-reduced at member (c-1)%S
    owner = {c: (c - 1) % S for c in range(S)}
    # phase 2: cross-pod binomial allreduce per chunk among its owners
    distances = _binomial_rounds(pods)
    for d in distances:
        perm, _ = _tree_round_tables(pods, d, 0, up=True)
        steps = []
        for src_pod, dst_pod in perm:
            for c in range(S):
                steps.extend(
                    _message(
                        member(src_pod, owner[c]), member(dst_pod, owner[c]),
                        c, "reduce", codec,
                    )
                )
        if steps:
            rounds.append(tuple(steps))
    for d in reversed(distances):
        perm, _ = _tree_round_tables(pods, d, 0, up=False)
        steps = []
        for src_pod, dst_pod in perm:
            for c in range(S):
                steps.extend(
                    _message(
                        member(src_pod, owner[c]), member(dst_pod, owner[c]),
                        c, "copy",
                    )
                )
        if steps:
            rounds.append(tuple(steps))
    # phase 3: ring all-gather within each pod.  Member i owns chunk
    # (i+1) mod S; at round r it forwards the newest chunk it holds,
    # (i + 1 - r) mod S, to member (i+1) mod S, who copies it in
    for r in range(S - 1):
        steps = []
        for p in range(pods):
            for i in range(S):
                c = (i + 1 - r) % S
                steps.extend(
                    _message(member(p, i), member(p, (i + 1) % S), c, "copy")
                )
        if steps:
            rounds.append(tuple(steps))
    return ScheduleProgram(
        name=f"two-level-{pods}x{S}",
        world=world,
        chunks=max(1, S),
        rounds=tuple(rounds),
        wire_dtype=wire_dtype,
    )
