"""Collective schedule compiler: one chunk-granular IR, verified and
lowered to every data plane (docs/COMPILER.md).

- :mod:`adapcc_tpu.compiler.ir` — ``ScheduleProgram``/``Step``, the one
  program form synthesizer, simulator, verifier and executor share;
- :mod:`adapcc_tpu.compiler.builders` — today's ring / recursive-doubling
  / binomial-tree / two-level planes re-emitted as IR programs;
- :mod:`adapcc_tpu.compiler.synthesize` — schedules only the IR can
  express (the bidirectional pipelined ring);
- :mod:`adapcc_tpu.compiler.verify` — static certification before
  lowering, loud rejection with the offending (rank, round, chunk);
- :mod:`adapcc_tpu.compiler.optimize` — the pass pipeline between a
  verified schedule and the wire (dce / fuse_codec / coalesce, gated by
  ``ADAPCC_IR_OPT``, every pass verified pass-in/pass-out);
- :mod:`adapcc_tpu.compiler.lower` — the ONE shard_map/ppermute lowering
  behind ``engine.all_reduce(algo="ir")``, flat-mesh and two-level
  ``(dcn, ici)`` alike, with a static per-program dispatch count.
"""

from adapcc_tpu.compiler.builders import (
    program_from_strategy,
    rd_allreduce_program,
    ring_allreduce_program,
    tree_allreduce_program,
    two_level_allreduce_program,
)
from adapcc_tpu.compiler.ir import (
    PROGRAM_COLLECTIVES,
    STEP_KINDS,
    ScheduleProgram,
    Step,
)
from adapcc_tpu.compiler.lower import (
    allreduce_per_shard,
    allreduce_per_shard_two_level,
    dispatch_count,
    execute_program_shard,
    execute_program_two_level_shard,
    round_dispatch_counts,
    two_level_color_axes,
)
from adapcc_tpu.compiler.optimize import (
    IR_OPT_ENV,
    PASS_NAMES,
    PASSES,
    optimize_program,
    resolve_ir_opt,
)
from adapcc_tpu.compiler.synthesize import pipelined_allreduce_program
from adapcc_tpu.compiler.verify import (
    ScheduleVerificationError,
    normalize_program,
    verify_program,
)

__all__ = [
    "IR_OPT_ENV",
    "PASSES",
    "PASS_NAMES",
    "PROGRAM_COLLECTIVES",
    "STEP_KINDS",
    "ScheduleProgram",
    "ScheduleVerificationError",
    "Step",
    "allreduce_per_shard",
    "allreduce_per_shard_two_level",
    "dispatch_count",
    "execute_program_shard",
    "execute_program_two_level_shard",
    "normalize_program",
    "optimize_program",
    "pipelined_allreduce_program",
    "program_from_strategy",
    "rd_allreduce_program",
    "resolve_ir_opt",
    "ring_allreduce_program",
    "round_dispatch_counts",
    "tree_allreduce_program",
    "two_level_allreduce_program",
    "two_level_color_axes",
    "verify_program",
]
