"""Collective schedule compiler: one chunk-granular IR, verified and
lowered to every data plane (docs/COMPILER.md).

- :mod:`adapcc_tpu.compiler.ir` — ``ScheduleProgram``/``Step``, the one
  program form synthesizer, simulator, verifier and executor share;
- :mod:`adapcc_tpu.compiler.builders` — today's ring / recursive-doubling
  / binomial-tree / two-level planes re-emitted as IR programs;
- :mod:`adapcc_tpu.compiler.synthesize` — schedules only the IR can
  express (the bidirectional pipelined ring);
- :mod:`adapcc_tpu.compiler.verify` — static certification before
  lowering, loud rejection with the offending (rank, round, chunk);
- :mod:`adapcc_tpu.compiler.lower` — the ONE shard_map/ppermute lowering
  behind ``engine.all_reduce(algo="ir")``.
"""

from adapcc_tpu.compiler.builders import (
    program_from_strategy,
    rd_allreduce_program,
    ring_allreduce_program,
    tree_allreduce_program,
    two_level_allreduce_program,
)
from adapcc_tpu.compiler.ir import (
    PROGRAM_COLLECTIVES,
    STEP_KINDS,
    ScheduleProgram,
    Step,
)
from adapcc_tpu.compiler.lower import allreduce_per_shard, execute_program_shard
from adapcc_tpu.compiler.synthesize import pipelined_allreduce_program
from adapcc_tpu.compiler.verify import ScheduleVerificationError, verify_program

__all__ = [
    "PROGRAM_COLLECTIVES",
    "STEP_KINDS",
    "ScheduleProgram",
    "ScheduleVerificationError",
    "Step",
    "allreduce_per_shard",
    "execute_program_shard",
    "pipelined_allreduce_program",
    "program_from_strategy",
    "rd_allreduce_program",
    "ring_allreduce_program",
    "tree_allreduce_program",
    "two_level_allreduce_program",
    "verify_program",
]
