"""Chunk-granular collective schedule IR.

The repo grew six hand-written execution planes (XLA psum, the quantized
ppermute ring, two Pallas ring kernels ± fused codec, recursive-doubling
and binomial-tree in ``comm/latency.py``, the hierarchical composed plane
in ``comm/two_level.py``) — each re-implementing chunking, masking and
codec plumbing, so only strategies with a hand-written twin were
executable.  Following GC3/MSCCLang's chunk-oriented DSL and SCCL/TACCL's
synthesized-algorithm model (PAPERS.md), this module is the one program
form all of them share:

- a :class:`ScheduleProgram` is a list of **rounds**; a round is a list of
  typed :class:`Step`\\ s (``send``/``recv``/``reduce``/``copy``/``encode``/
  ``decode``) over ``chunks`` named chunk buffers replicated on every rank;
- rounds are barriers: every ``send`` reads its rank's *round-entry* buffer
  state, and its matching ``recv`` must sit in the same round (a recv whose
  send lands later is a deadlock — the verifier rejects it);
- each ``recv`` is consumed by exactly one same-round ``reduce`` (combine
  into the local chunk) or ``copy`` (overwrite the local chunk);
- ``encode``/``decode`` mark a send/recv pair whose wire value takes the
  named codec's quantize→dequantize round trip (``quant/codec.py``) — the
  wire-dtype annotation is first-class, not an engine-side reroute;
- ``relays`` names ranks that forward traffic without contributing input
  or requiring delivery (the AdapCC relay mask, here a program property).

Unlike :class:`adapcc_tpu.strategy.ir.CommRound`, a round is **not**
constrained to a partial permutation — a rank may send several chunks to
several peers in one round.  The lowering (``compiler/lower.py``) colors a
round's messages into ppermute-able partial permutations; the IR itself
stays at the algorithm's natural granularity, which is what lets it
express schedules (e.g. the bidirectional pipelined ring in
``compiler/synthesize.py``) that no ``CommRound``-shaped plane can.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Iterator, Optional, Tuple

#: the closed set of step kinds; anything else is a construction error
STEP_KINDS = ("send", "recv", "reduce", "copy", "encode", "decode")

#: collectives a program may declare.  ``allreduce`` has a lowering to
#: every data plane; ``pipeline`` names point-to-point stage-hop programs
#: (GC3-style: each chunk is one payload routed from a source rank to a
#: sink rank) — verified and priced through the same object, executed by
#: the pipeline engine rather than ``compiler/lower.py``.
PROGRAM_COLLECTIVES = ("allreduce", "pipeline")


@dataclass(frozen=True)
class Step:
    """One typed step of one rank in one round.

    ``peer`` is the destination rank for ``send`` and the source rank for
    ``recv`` (required for both, meaningless elsewhere); ``codec`` names
    the registered wire codec for ``encode``/``decode`` steps — and, after
    the ``fuse_codec`` optimizer pass (``compiler/optimize.py``), on the
    ``send``/``recv`` pair itself, meaning the codec's transport arrays
    (not the decoded value) cross the wire.  ``span`` widens the step to
    the contiguous chunk range ``[chunk, chunk + span)`` — the coalesced
    form the ``coalesce`` pass emits; the verifier checks span steps by
    expanding them back to unit steps, so a span is an execution-shape
    annotation, never a semantic change.
    """

    kind: str
    rank: int
    chunk: int
    peer: Optional[int] = None
    codec: Optional[str] = None
    span: int = 1

    def __post_init__(self) -> None:
        if self.kind not in STEP_KINDS:
            raise ValueError(
                f"unknown step kind {self.kind!r}; expected one of {STEP_KINDS}"
            )
        if self.kind in ("send", "recv") and self.peer is None:
            raise ValueError(f"{self.kind} step at rank {self.rank} needs a peer")
        if self.kind in ("encode", "decode") and not self.codec:
            raise ValueError(f"{self.kind} step at rank {self.rank} needs a codec")
        if self.span < 1:
            raise ValueError(
                f"{self.kind} step at rank {self.rank}: span must be >= 1, "
                f"got {self.span}"
            )

    def describe(self) -> str:
        """Human-readable spelling used by verifier rejections."""
        bits = f"{self.kind}(rank={self.rank}, chunk={self.chunk}"
        if self.span != 1:
            bits += f", span={self.span}"
        if self.peer is not None:
            bits += f", peer={self.peer}"
        if self.codec is not None:
            bits += f", codec={self.codec}"
        return bits + ")"


@dataclass(frozen=True)
class ScheduleProgram:
    """One verified-lowerable collective schedule.

    ``rounds`` is a tuple of rounds, each a tuple of :class:`Step`.  The
    program is the single object the builders emit, the verifier certifies
    (``compiler/verify.py``), the cost model prices
    (``sim/cost_model.schedule_program_time``), the replay layer simulates
    (``sim/replay.simulate_program``) and the lowering executes
    (``compiler/lower.py``) — pricing and execution share the schedule by
    construction because they share this object.
    """

    name: str
    world: int
    chunks: int
    rounds: Tuple[Tuple[Step, ...], ...]
    collective: str = "allreduce"
    #: wire codec annotation; "off" = payload dtype end to end.  Programs
    #: carrying encode/decode steps name their codec here so dispatch-time
    #: pin-conflict checks and tuner keys see it without walking steps.
    wire_dtype: str = "off"
    #: ranks that forward without contributing input or needing delivery
    relays: Tuple[int, ...] = ()
    #: ``pipeline`` programs only: per-chunk origin and destination rank.
    #: Chunk ``c`` starts as rank ``chunk_sources[c]``'s private payload and
    #: must end up delivered (unmodified contribution set) at rank
    #: ``chunk_sinks[c]``.  Empty for collective programs, where every
    #: non-relay rank both contributes and requires delivery.
    chunk_sources: Tuple[int, ...] = ()
    chunk_sinks: Tuple[int, ...] = ()
    #: block size the fused block codec executes with (``fuse_codec`` sets
    #: it for block-scaled wires like int8); ``None`` = no fused block math
    block_size: Optional[int] = None
    #: optimizer passes that actually rewrote this program, in application
    #: order (``compiler/optimize.py``).  Empty for naive/builder programs.
    applied_passes: Tuple[str, ...] = ()

    def __post_init__(self) -> None:
        if self.world < 1:
            raise ValueError(f"world must be >= 1, got {self.world}")
        if self.chunks < 1:
            raise ValueError(f"chunks must be >= 1, got {self.chunks}")
        if self.collective not in PROGRAM_COLLECTIVES:
            raise ValueError(
                f"unknown collective {self.collective!r}; expected one of "
                f"{PROGRAM_COLLECTIVES}"
            )
        object.__setattr__(
            self, "rounds", tuple(tuple(rnd) for rnd in self.rounds)
        )
        object.__setattr__(self, "relays", tuple(sorted(set(self.relays))))
        for r in self.relays:
            if not (0 <= r < self.world):
                raise ValueError(f"relay rank {r} out of range [0, {self.world})")
        if len(self.relays) >= self.world:
            raise ValueError("every rank is a relay: nothing contributes")
        object.__setattr__(self, "chunk_sources", tuple(self.chunk_sources))
        object.__setattr__(self, "chunk_sinks", tuple(self.chunk_sinks))
        if self.collective == "pipeline":
            if len(self.chunk_sources) != self.chunks or (
                len(self.chunk_sinks) != self.chunks
            ):
                raise ValueError(
                    "pipeline programs route each chunk point-to-point: "
                    f"need chunk_sources/chunk_sinks of length {self.chunks}, "
                    f"got {len(self.chunk_sources)}/{len(self.chunk_sinks)}"
                )
            if self.relays:
                raise ValueError(
                    "pipeline programs have no relays: intermediate stages "
                    "are named by the per-chunk hop steps themselves"
                )
            for label, ranks in (
                ("chunk_sources", self.chunk_sources),
                ("chunk_sinks", self.chunk_sinks),
            ):
                for c, r in enumerate(ranks):
                    if not (0 <= r < self.world):
                        raise ValueError(
                            f"{label}[{c}] = {r} out of range [0, {self.world})"
                        )
        elif self.chunk_sources or self.chunk_sinks:
            raise ValueError(
                "chunk_sources/chunk_sinks are pipeline-program routing "
                f"metadata; collective {self.collective!r} does not take them"
            )
        object.__setattr__(self, "applied_passes", tuple(self.applied_passes))
        if self.block_size is not None and self.block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {self.block_size}")
        for i, rnd in enumerate(self.rounds):
            for step in rnd:
                if not (0 <= step.rank < self.world):
                    raise ValueError(
                        f"round {i}: {step.describe()} rank out of range "
                        f"[0, {self.world})"
                    )
                if step.peer is not None and not (0 <= step.peer < self.world):
                    raise ValueError(
                        f"round {i}: {step.describe()} peer out of range "
                        f"[0, {self.world})"
                    )
                if not (0 <= step.chunk < self.chunks):
                    raise ValueError(
                        f"round {i}: {step.describe()} chunk out of range "
                        f"[0, {self.chunks})"
                    )
                if step.chunk + step.span > self.chunks:
                    raise ValueError(
                        f"round {i}: {step.describe()} span reaches past the "
                        f"last chunk (chunks={self.chunks})"
                    )

    # -- queries ---------------------------------------------------------------

    @property
    def num_rounds(self) -> int:
        return len(self.rounds)

    def contributors(self) -> Tuple[int, ...]:
        """Ranks that contribute input and require delivery (non-relays)."""
        relay = set(self.relays)
        return tuple(r for r in range(self.world) if r not in relay)

    def steps(self) -> Iterator[Tuple[int, Step]]:
        for i, rnd in enumerate(self.rounds):
            for step in rnd:
                yield i, step

    def total_sends(self) -> int:
        return sum(1 for _, s in self.steps() if s.kind == "send")

    def fingerprint(self) -> str:
        """Stable structural hash — the compiled-executor cache key
        component and the dispatch-trace provenance stamp.  Memoized:
        the program is immutable and hot dispatch paths consult this per
        collective call (the ``Strategy.fingerprint`` pattern)."""
        cached = self.__dict__.get("_fingerprint")
        if cached is not None:
            return cached
        h = hashlib.sha256()
        h.update(
            f"{self.name}|{self.world}|{self.chunks}|{self.collective}|"
            f"{self.wire_dtype}|{self.relays}".encode()
        )
        if self.chunk_sources or self.chunk_sinks:
            # folded in only when present so collective-program fingerprints
            # predating the pipeline family are unchanged
            h.update(f"|{self.chunk_sources}|{self.chunk_sinks}".encode())
        if self.block_size is not None or self.applied_passes:
            # optimizer provenance (same only-when-present rule): an
            # optimized program and its naive source must never collide in
            # the standby cache or the tuner's key space — the pass list
            # and the fused block size are part of WHAT executes
            h.update(f"|b{self.block_size}|{self.applied_passes}".encode())
        for i, rnd in enumerate(self.rounds):
            h.update(f"r{i}".encode())
            for s in rnd:
                h.update(
                    f"{s.kind},{s.rank},{s.chunk},{s.peer},{s.codec}".encode()
                )
                if s.span != 1:
                    h.update(f",x{s.span}".encode())
                h.update(b";")
        fp = h.hexdigest()[:16]
        self.__dict__["_fingerprint"] = fp
        return fp

    def __repr__(self) -> str:
        return (
            f"ScheduleProgram(name={self.name!r}, world={self.world}, "
            f"chunks={self.chunks}, rounds={self.num_rounds}, "
            f"wire_dtype={self.wire_dtype!r}, fingerprint={self.fingerprint()})"
        )
