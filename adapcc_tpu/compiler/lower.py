"""ONE lowering: ScheduleProgram → compiled shard_map/ppermute executor.

Every program that passes ``compiler/verify.py`` executes through this
module — ring, recursive doubling, binomial tree, the composed two-level
plan and any synthesized schedule alike.  The engine dispatches it via
``engine.all_reduce(algo="ir")`` and stamps the executed program's
fingerprint into the dispatch trace.

Execution model (mirrors the IR's barrier-round semantics exactly):

- the payload flattens and zero-pads to ``chunks × seg`` rows, one row
  per named chunk buffer, identically on every rank;
- each round snapshots its entry state; all sends read the snapshot, so
  a chunk that is both shipped and overwritten in one round behaves as
  the verifier's abstract interpretation says it does;
- a round's messages are **colored** into partial permutations (distinct
  sources, distinct destinations per color) — each color is one
  ``lax.ppermute``.  The IR places no per-round fan-out limit; the
  coloring is where the free-form schedule meets the ppermute contract,
  which is exactly what lets one executor run schedules (two sends per
  rank per round, say) that the CommRound-shaped planes cannot;
- ``reduce`` consumers combine ``(local, received)`` in that operand
  order — the same order ``comm/latency.py`` uses, which is what makes
  the rd/tree parity bit-identical; ``copy`` consumers overwrite;
- ``encode``/``decode`` pairs execute as the named codec's jittable
  quantize→dequantize round trip (``WireCodec.apply``) on the wire value
  — numerically identical to encode/ship/decode, with XLA free to fuse;
- relays enter with the reduction identity and are excluded from the
  ``AVG`` normalization count.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np
from jax import lax

from adapcc_tpu.compiler.ir import ScheduleProgram
from adapcc_tpu.primitives import ReduceOp


def _combine(a: jnp.ndarray, b: jnp.ndarray, op: ReduceOp) -> jnp.ndarray:
    if op is ReduceOp.MAX:
        return jnp.maximum(a, b)
    return a + b  # SUM; AVG normalizes once at the end


def _identity_value(op: ReduceOp, dtype) -> float:
    if op is ReduceOp.MAX:
        if jnp.issubdtype(dtype, jnp.floating):
            return float("-inf")
        return int(jnp.iinfo(dtype).min)
    return 0


class _Color:
    """One partial permutation of one round: the per-rank constant tables
    a single ppermute + masked commit needs."""

    __slots__ = (
        "perm", "send_chunk", "is_src", "dst_chunk", "is_dst", "is_copy",
        "encoded", "any_encoded",
    )

    def __init__(self, world: int) -> None:
        self.perm: List[Tuple[int, int]] = []
        self.send_chunk = np.zeros(world, dtype=np.int32)
        self.is_src = np.zeros(world, dtype=bool)
        self.dst_chunk = np.zeros(world, dtype=np.int32)
        self.is_dst = np.zeros(world, dtype=bool)
        self.is_copy = np.zeros(world, dtype=bool)
        self.encoded = np.zeros(world, dtype=bool)
        self.any_encoded = False

    def can_take(self, src: int, dst: int) -> bool:
        return not self.is_src[src] and not self.is_dst[dst]

    def take(
        self, src: int, dst: int, chunk: int, copy: bool, encoded: bool
    ) -> None:
        self.perm.append((src, dst))
        self.send_chunk[src] = chunk
        self.is_src[src] = True
        self.dst_chunk[dst] = chunk
        self.is_dst[dst] = True
        self.is_copy[dst] = copy
        self.encoded[src] = encoded
        self.any_encoded = self.any_encoded or encoded


def _color_rounds(program: ScheduleProgram) -> List[List[_Color]]:
    """Greedy-color every round's messages into ppermute-able partial
    permutations, in deterministic step order.  Memoized on the program —
    it is immutable and the executor cache may rebuild per shape."""
    cached = program.__dict__.get("_lowering_colors")
    if cached is not None:
        return cached
    plan: List[List[_Color]] = []
    for rnd in program.rounds:
        sends = []
        consumers = {}
        encodes = set()
        for step in rnd:
            if step.kind == "send":
                sends.append((step.rank, step.peer, step.chunk))
            elif step.kind in ("reduce", "copy"):
                consumers[(step.rank, step.chunk)] = step.kind
            elif step.kind == "encode":
                encodes.add((step.rank, step.chunk))
        colors: List[_Color] = []
        for src, dst, chunk in sends:
            copy = consumers.get((dst, chunk)) == "copy"
            encoded = (src, chunk) in encodes
            for col in colors:
                if col.can_take(src, dst):
                    col.take(src, dst, chunk, copy, encoded)
                    break
            else:
                col = _Color(program.world)
                col.take(src, dst, chunk, copy, encoded)
                colors.append(col)
        plan.append(colors)
    program.__dict__["_lowering_colors"] = plan
    return plan


def execute_program_shard(
    x: jnp.ndarray,
    program: ScheduleProgram,
    axis_name: str,
    op: ReduceOp = ReduceOp.SUM,
) -> jnp.ndarray:
    """Run ``program`` on this rank's payload inside a shard_map body.

    ``x`` is the rank's full (replicated-shape) contribution; the result
    is the completed collective in ``x``'s shape.  Callers are expected
    to have verified the program (the engine verifies once per
    fingerprint before compiling).
    """
    k = program.chunks
    flat = x.reshape(-1)
    n = flat.size
    seg = -(-n // k)
    pad = k * seg - n
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    acc = flat.reshape(k, seg)
    me = lax.axis_index(axis_name)
    if program.relays:
        relay = np.zeros(program.world, dtype=bool)
        relay[list(program.relays)] = True
        ident = jnp.full_like(acc, _identity_value(op, acc.dtype))
        acc = jnp.where(jnp.asarray(relay)[me], ident, acc)
    codec = None
    if program.wire_dtype != "off":
        from adapcc_tpu.quant.codec import get_codec

        codec = get_codec(program.wire_dtype)
    for colors in _color_rounds(program):
        entry = acc
        for col in colors:
            wire = entry[jnp.asarray(col.send_chunk)[me]]
            if col.any_encoded and codec is not None:
                wire = jnp.where(
                    jnp.asarray(col.encoded)[me], codec.apply(wire), wire
                )
            recvd = lax.ppermute(wire, axis_name, col.perm)
            dst_chunk = jnp.asarray(col.dst_chunk)[me]
            cur = acc[dst_chunk]
            new = jnp.where(
                jnp.asarray(col.is_copy)[me], recvd, _combine(cur, recvd, op)
            )
            acc = acc.at[dst_chunk].set(
                jnp.where(jnp.asarray(col.is_dst)[me], new, cur)
            )
    if op is ReduceOp.AVG:
        acc = acc / len(program.contributors())
    return acc.reshape(-1)[:n].reshape(x.shape)


def allreduce_per_shard(
    program: ScheduleProgram, axis_name: str, op: ReduceOp = ReduceOp.SUM
):
    """The engine-facing per-shard callable (stacked ``[1, *payload]``
    convention, matching ``CollectiveEngine._shard_mapped``)."""

    def per_shard(x: jnp.ndarray) -> jnp.ndarray:
        return execute_program_shard(x[0], program, axis_name, op)[None]

    return per_shard
