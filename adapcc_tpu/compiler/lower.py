"""ONE lowering: ScheduleProgram → compiled shard_map/ppermute executor.

Every program that passes ``compiler/verify.py`` executes through this
module — ring, recursive doubling, binomial tree, the composed two-level
plan and any synthesized schedule alike.  The engine dispatches it via
``engine.all_reduce(algo="ir")`` and stamps the executed program's
fingerprint into the dispatch trace.

Execution model (mirrors the IR's barrier-round semantics exactly):

- the payload flattens and zero-pads to ``chunks × seg`` rows, one row
  per named chunk buffer, identically on every rank;
- each round snapshots its entry state; all sends read the snapshot, so
  a chunk that is both shipped and overwritten in one round behaves as
  the verifier's abstract interpretation says it does;
- a round's messages are **colored** into partial permutations (distinct
  sources, distinct destinations per color) — each color is one
  ``lax.ppermute``.  A message covers the ``span`` chunk rows its step
  names (one row naive, several after the ``coalesce`` optimizer pass),
  and every message in a color ships the same row count, so one ppermute
  moves every pair's concatenated chunk buffer at once: the optimized
  recursive-doubling round that naively issued one dispatch per chunk
  issues exactly one.  The collective **dispatch count** — the number of
  ppermutes the compiled program issues — is a static property of this
  color plan (:func:`dispatch_count`), reported in the dispatch trace;
- ``reduce`` consumers combine ``(local, received)`` in that operand
  order — the same order ``comm/latency.py`` uses, which is what makes
  the rd/tree parity bit-identical; ``copy`` consumers overwrite;
- legacy ``encode``/``decode`` pairs execute as the named codec's
  jittable quantize→dequantize round trip (``WireCodec.apply``) on the
  wire value; **fused** codec steps (``fuse_codec`` pass: the codec on
  the send/recv pair itself) ship the codec's real transport arrays —
  quantize on the sender, ppermute each wire array, dequantize on the
  receiver.  Both are applied per chunk row, so the fused wire VALUE is
  bit-identical to the unfused apply-then-ship form (same block math on
  the same rows — pinned by test), while the bytes that cross the fabric
  are the codec's.  One caveat, stated rather than hidden: a ``reduce``
  consuming a fused block-scaled wire may land within one ulp of the
  unfused plane, because XLA contracts the receiver-side dequantize
  multiply into the combine (a single-rounding FMA) — fp32 payloads,
  where the optimizer's bit-identity guarantee lives, have no such
  multiply and stay exact;
- relays enter with the reduction identity and are excluded from the
  ``AVG`` normalization count;
- on a two-level ``(dcn, ici)`` mesh, :func:`execute_program_two_level_shard`
  classifies every color as intra-pod (one member-level permutation,
  shipped over the ICI axis in every pod at once) or cross-pod (one
  slice-level permutation over the DCN axis) — the composed two-level
  program runs natively on the hierarchy, DCN carrying 1/pod_size of the
  payload, with no flat-mesh detour.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from adapcc_tpu.compiler.ir import ScheduleProgram, Step

from adapcc_tpu.primitives import ReduceOp

#: ppermutes per color and fused wire codec: each of the codec's
#: transport arrays (``WireCodec.encode``'s tuple) is one ppermute.
#: Codecs not named ship one array (bf16's cast, or the payload itself).
_WIRE_ARRAYS = {"int8": 2}


def _combine(a: jnp.ndarray, b: jnp.ndarray, op: ReduceOp) -> jnp.ndarray:
    if op is ReduceOp.MAX:
        return jnp.maximum(a, b)
    return a + b  # SUM; AVG normalizes once at the end


def _identity_value(op: ReduceOp, dtype) -> float:
    if op is ReduceOp.MAX:
        if jnp.issubdtype(dtype, jnp.floating):
            return float("-inf")
        return int(jnp.iinfo(dtype).min)
    return 0


class _Color:
    """One partial permutation of one round: the per-rank constant tables
    a single ppermute + masked commit needs.  ``k`` chunk rows ride per
    pair; every pair in a color ships the same ``k`` and the same fused
    wire codec, so the concatenated buffer is one homogeneous transfer."""

    __slots__ = (
        "world", "k", "codec", "perm", "send_rows", "dst_rows", "copy_row",
        "is_src", "is_dst", "encoded", "any_encoded",
    )

    def __init__(self, world: int, k: int, codec: Optional[str]) -> None:
        self.world = world
        self.k = k
        self.codec = codec
        self.perm: List[Tuple[int, int]] = []
        self.send_rows = np.zeros((world, k), dtype=np.int32)
        self.dst_rows = np.zeros((world, k), dtype=np.int32)
        self.copy_row = np.zeros((world, k), dtype=bool)
        self.is_src = np.zeros(world, dtype=bool)
        self.is_dst = np.zeros(world, dtype=bool)
        self.encoded = np.zeros((world, k), dtype=bool)
        self.any_encoded = False

    def can_take(self, src: int, dst: int, k: int, codec: Optional[str]) -> bool:
        return (
            not self.is_src[src] and not self.is_dst[dst]
            and self.k == k and self.codec == codec
        )

    def take(
        self,
        src: int,
        dst: int,
        rows: Sequence[int],
        copy: Sequence[bool],
        encoded: Sequence[bool],
    ) -> None:
        self.perm.append((src, dst))
        self.send_rows[src] = rows
        self.is_src[src] = True
        self.dst_rows[dst] = rows
        self.is_dst[dst] = True
        self.copy_row[dst] = copy
        self.encoded[src] = encoded
        self.any_encoded = self.any_encoded or any(encoded)

    def dispatches(self) -> int:
        """ppermutes this color issues: one per wire array."""
        if self.codec is None:
            return 1
        return _WIRE_ARRAYS.get(self.codec, 1)


def _color_rounds(program: ScheduleProgram) -> List[List[_Color]]:
    """Greedy-color every round's messages into ppermute-able partial
    permutations, in deterministic step order.  Memoized on the program —
    it is immutable and the executor cache may rebuild per shape."""
    cached = program.__dict__.get("_lowering_colors")
    if cached is not None:
        return cached
    plan: List[List[_Color]] = []
    for rnd in program.rounds:
        sends = []
        consumers = {}
        encodes = set()
        for step in rnd:
            if step.kind == "send":
                sends.append(step)
            elif step.kind in ("reduce", "copy"):
                for i in range(step.span):
                    consumers[(step.rank, step.chunk + i)] = step.kind
            elif step.kind == "encode":
                for i in range(step.span):
                    encodes.add((step.rank, step.chunk + i))
        colors: List[_Color] = []
        for step in sends:
            src, dst = step.rank, step.peer
            rows = list(range(step.chunk, step.chunk + step.span))
            copy = [consumers.get((dst, c)) == "copy" for c in rows]
            encoded = [(src, c) in encodes for c in rows]
            k = len(rows)
            for col in colors:
                if col.can_take(src, dst, k, step.codec):
                    col.take(src, dst, rows, copy, encoded)
                    break
            else:
                col = _Color(program.world, k, step.codec)
                col.take(src, dst, rows, copy, encoded)
                colors.append(col)
        plan.append(colors)
    program.__dict__["_lowering_colors"] = plan
    return plan


def round_dispatch_counts(program: ScheduleProgram) -> List[int]:
    """Collective dispatches (ppermutes) per round of the compiled
    executor — static, from the color plan alone."""
    return [
        sum(col.dispatches() for col in colors)
        for colors in _color_rounds(program)
    ]


def dispatch_count(program: ScheduleProgram) -> int:
    """Total collective dispatches the compiled program issues — the
    number the optimizer exists to shrink, stamped in the dispatch trace
    and priced by ``schedule_program_time(..., per_dispatch_s=...)``."""
    return sum(round_dispatch_counts(program))


def _ship_flat(axis_name: str) -> Callable:
    def ship(col: _Color, wire: jnp.ndarray) -> jnp.ndarray:
        return lax.ppermute(wire, axis_name, col.perm)

    return ship


def _execute(
    x: jnp.ndarray,
    program: ScheduleProgram,
    op: ReduceOp,
    me: jnp.ndarray,
    ship_for: Callable[[int, int], Callable],
) -> jnp.ndarray:
    """The shared executor core: ``me`` is this rank's flat index and
    ``ship_for(round_idx, color_idx)`` returns the transfer callable for
    one color — a flat-axis ppermute, or the classified single-axis
    ppermute of the two-level lowering."""
    k = program.chunks
    flat = x.reshape(-1)
    n = flat.size
    seg = -(-n // k)
    pad = k * seg - n
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    acc = flat.reshape(k, seg)
    if program.relays:
        relay = np.zeros(program.world, dtype=bool)
        relay[list(program.relays)] = True
        ident = jnp.full_like(acc, _identity_value(op, acc.dtype))
        acc = jnp.where(jnp.asarray(relay)[me], ident, acc)
    codec = None
    if program.wire_dtype != "off":
        from adapcc_tpu.quant.codec import get_codec

        codec = get_codec(program.wire_dtype)
    from adapcc_tpu.quant.codec import DEFAULT_BLOCK_SIZE, get_codec

    block_size = program.block_size or DEFAULT_BLOCK_SIZE
    for ri, colors in enumerate(_color_rounds(program)):
        entry = acc
        for ci, col in enumerate(colors):
            ship = ship_for(ri, ci)
            wire = entry[jnp.asarray(col.send_rows)[me]]  # [k, seg]
            if col.any_encoded and codec is not None:
                # legacy unfused form: the wire VALUE takes the codec's
                # round trip per chunk row; fp32 still crosses the fabric
                applied = jax.vmap(lambda r: codec.apply(r, block_size))(wire)
                wire = jnp.where(
                    jnp.asarray(col.encoded)[me][:, None], applied, wire
                )
            if col.codec is not None:
                # fused form: the codec's transport arrays cross the
                # fabric, quantized per chunk row on the sender and
                # decoded on the receiver — same block math as the
                # unfused round trip, a fraction of the wire bytes
                fused = get_codec(col.codec)
                seg_n = wire.shape[-1]
                arrays = jax.vmap(lambda r: fused.encode(r, block_size))(
                    wire.astype(jnp.float32)
                    if col.codec == "int8" else wire
                )
                shipped = tuple(ship(col, a) for a in arrays)
                recvd = jax.vmap(
                    lambda *w: fused.decode(w, seg_n, block_size)
                )(*shipped).astype(acc.dtype)
            else:
                recvd = ship(col, wire)
            dst_rows = jnp.asarray(col.dst_rows)[me]
            cur = acc[dst_rows]
            new = jnp.where(
                jnp.asarray(col.copy_row)[me][:, None],
                recvd,
                _combine(cur, recvd, op),
            )
            acc = acc.at[dst_rows].set(
                jnp.where(jnp.asarray(col.is_dst)[me], new, cur)
            )
    if op is ReduceOp.AVG:
        acc = acc / len(program.contributors())
    return acc.reshape(-1)[:n].reshape(x.shape)


def execute_program_shard(
    x: jnp.ndarray,
    program: ScheduleProgram,
    axis_name: str,
    op: ReduceOp = ReduceOp.SUM,
) -> jnp.ndarray:
    """Run ``program`` on this rank's payload inside a shard_map body.

    ``x`` is the rank's full (replicated-shape) contribution; the result
    is the completed collective in ``x``'s shape.  Callers are expected
    to have verified the program (the engine verifies once per
    fingerprint before compiling).
    """
    me = lax.axis_index(axis_name)
    ship = _ship_flat(axis_name)
    return _execute(x, program, op, me, lambda ri, ci: ship)


def allreduce_per_shard(
    program: ScheduleProgram, axis_name: str, op: ReduceOp = ReduceOp.SUM
):
    """The engine-facing per-shard callable (stacked ``[1, *payload]``
    convention, matching ``CollectiveEngine._shard_mapped``)."""

    def per_shard(x: jnp.ndarray) -> jnp.ndarray:
        return execute_program_shard(x[0], program, axis_name, op)[None]

    return per_shard


# --------------------------------------------------------------------------- #
# two-level (dcn, ici) mesh execution
# --------------------------------------------------------------------------- #


def _partial_permutation(pairs: List[Tuple[int, int]]) -> Optional[List[Tuple[int, int]]]:
    """The deduplicated pair set as a partial permutation, or None when
    sources or destinations collide."""
    uniq = sorted(set(pairs))
    if len({s for s, _ in uniq}) != len(uniq):
        return None
    if len({d for _, d in uniq}) != len(uniq):
        return None
    return uniq


def two_level_color_axes(
    program: ScheduleProgram, num_slices: int, ici_size: int
) -> List[List[Tuple[str, List[Tuple[int, int]]]]]:
    """Classify every color of ``program`` onto the ``(dcn, ici)`` mesh:
    per round, per color, ``("ici", member_perm)`` when every pair stays
    inside its pod and the member-level projection is one partial
    permutation (shipped in every pod at once — pods missing a pair just
    mask the commit), or ``("dcn", slice_perm)`` when every pair connects
    the same member across pods.  A color that is neither rejects loudly
    naming the round — the program does not decompose onto the hierarchy
    and must run on a flat mesh instead.  Memoized per (program, shape).
    """
    key = ("_two_level_axes", num_slices, ici_size)
    cached = program.__dict__.get(key)
    if cached is not None:
        return cached
    if program.world != num_slices * ici_size:
        raise ValueError(
            f"program {program.name!r} is for world {program.world}, the "
            f"(dcn, ici) mesh is {num_slices}x{ici_size}"
        )
    plan: List[List[Tuple[str, List[Tuple[int, int]]]]] = []
    for ri, colors in enumerate(_color_rounds(program)):
        out: List[Tuple[str, List[Tuple[int, int]]]] = []
        for col in colors:
            intra = all(s // ici_size == d // ici_size for s, d in col.perm)
            cross = all(s % ici_size == d % ici_size for s, d in col.perm)
            axis_perm = None
            if intra:
                axis_perm = _partial_permutation(
                    [(s % ici_size, d % ici_size) for s, d in col.perm]
                )
                if axis_perm is not None:
                    out.append(("ici", axis_perm))
                    continue
            if cross:
                axis_perm = _partial_permutation(
                    [(s // ici_size, d // ici_size) for s, d in col.perm]
                )
                if axis_perm is not None:
                    out.append(("dcn", axis_perm))
                    continue
            raise ValueError(
                f"program {program.name!r} round {ri} has a transfer group "
                "that is neither intra-pod nor member-aligned cross-pod: "
                "it does not decompose onto the (dcn, ici) mesh — run it "
                "on a flat mesh, or build a two-level program "
                "(compiler.two_level_allreduce_program)"
            )
        plan.append(out)
    program.__dict__[key] = plan
    return plan


def execute_program_two_level_shard(
    x: jnp.ndarray,
    program: ScheduleProgram,
    num_slices: int,
    ici_size: int,
    dcn_axis: str = "dcn",
    ici_axis: str = "ici",
    op: ReduceOp = ReduceOp.SUM,
) -> jnp.ndarray:
    """Run ``program`` natively on a two-level ``(dcn, ici)`` mesh inside
    shard_map: flat rank ``slice · ici_size + lane`` (the
    ``comm/two_level.py`` layout), every color shipped over exactly the
    axis its classification names — intra-pod traffic never touches DCN,
    and the composed program's cross-pod phase moves 1/pod_size of the
    payload per member over the DCN axis, which is the hierarchy's whole
    point."""
    axes = two_level_color_axes(program, num_slices, ici_size)
    me = lax.axis_index(dcn_axis) * ici_size + lax.axis_index(ici_axis)

    def ship_for(ri: int, ci: int) -> Callable:
        axis_kind, perm = axes[ri][ci]
        axis = ici_axis if axis_kind == "ici" else dcn_axis

        def ship(col: _Color, wire: jnp.ndarray) -> jnp.ndarray:
            return lax.ppermute(wire, axis, perm)

        return ship

    return _execute(x, program, op, me, ship_for)


def allreduce_per_shard_two_level(
    program: ScheduleProgram,
    num_slices: int,
    ici_size: int,
    dcn_axis: str = "dcn",
    ici_axis: str = "ici",
    op: ReduceOp = ReduceOp.SUM,
):
    """The engine-facing two-level per-shard callable (stacked
    ``[1, *payload]`` convention)."""

    def per_shard(x: jnp.ndarray) -> jnp.ndarray:
        return execute_program_two_level_shard(
            x[0], program, num_slices, ici_size, dcn_axis, ici_axis, op
        )[None]

    return per_shard
