"""Synthesized schedules only the IR can express.

The flagship is :func:`pipelined_allreduce_program` — a TACCL/SCCL-style
bidirectional pipelined ring.  The payload splits into ``2·world`` chunks;
the first ``world`` travel clockwise (rank → rank+1), the other ``world``
counter-clockwise (rank → rank−1), each direction running its own
segmented reduce-scatter + all-gather walk.  Every rank sends **two**
chunks per round — one per direction — which no existing plane can run:

- ``strategy.ir.CommRound`` is a partial permutation (one send per rank
  per round), so the schedule plane cannot hold both directions in one
  round — a Strategy spelling would serialize them and double the
  round count;
- the ring/rd/tree planes hard-code their own walks.

On a full-duplex fabric the two directions occupy disjoint directed
links, so each of the ``2(w−1)`` rounds moves ``n/(2w)`` bytes per link:

    T_pipelined = 2(w−1) · (α + β·n/(2w))

vs the lockstep chain ring's ``2(w−1)·(α + β·n)`` and the segmented
ring's ``2(w−1)·(α + β·n/w)`` — a ~2× bandwidth-bound win over the best
single-direction ring, priced by ``sim/cost_model.schedule_program_time``
and pinned in the schedule sweep (``make compiler-bench``).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from adapcc_tpu.compiler.builders import _message
from adapcc_tpu.compiler.ir import ScheduleProgram, Step


def _ring_direction_rounds(
    world: int,
    direction: int,
    chunk_base: int,
    codec: Optional[str],
) -> List[List[Step]]:
    """One direction's segmented ring walk over its ``world`` chunks.

    ``direction=+1``: RS round ``r`` has rank ``s`` shipping local chunk
    ``(s − r) mod w`` to ``s+1`` (reduce); AG round ``r`` ships
    ``(s + 1 − r) mod w`` (copy).  ``direction=−1`` mirrors both walks.
    Chunk indices are offset by ``chunk_base`` into the program's global
    chunk namespace.
    """
    w = world
    rounds: List[List[Step]] = []
    for r in range(w - 1):
        steps: List[Step] = []
        for s in range(w):
            local = (s - r) % w if direction > 0 else (s + r) % w
            dst = (s + direction) % w
            steps.extend(_message(s, dst, chunk_base + local, "reduce", codec))
        rounds.append(steps)
    for r in range(w - 1):
        steps = []
        for s in range(w):
            local = (s + 1 - r) % w if direction > 0 else (s - 1 + r) % w
            dst = (s + direction) % w
            steps.extend(_message(s, dst, chunk_base + local, "copy"))
        rounds.append(steps)
    return rounds


def pipelined_allreduce_program(
    world: int, wire_dtype: str = "off"
) -> ScheduleProgram:
    """The bidirectional 2w-chunk pipelined ring allreduce (module doc)."""
    if world < 2:
        raise ValueError(
            f"the pipelined ring needs world >= 2, got {world} (at world=1 "
            "there is nothing to pipeline — use any builder program)"
        )
    codec = wire_dtype if wire_dtype != "off" else None
    cw = _ring_direction_rounds(world, +1, 0, codec)
    ccw = _ring_direction_rounds(world, -1, world, codec)
    rounds: Tuple[Tuple[Step, ...], ...] = tuple(
        tuple(a + b) for a, b in zip(cw, ccw)
    )
    return ScheduleProgram(
        name=f"pipelined-bidir-w{world}",
        world=world,
        chunks=2 * world,
        rounds=rounds,
        wire_dtype=wire_dtype,
    )
