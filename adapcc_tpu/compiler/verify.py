"""Static verifier for :class:`~adapcc_tpu.compiler.ir.ScheduleProgram`.

Every program is certified **before** lowering (the engine verifies once
per fingerprint).  Verification is an abstract interpretation over
contribution sets: chunk ``c`` on rank ``r`` carries the frozenset of
ranks whose input has been folded into it.  The checks, each rejecting
loudly with the offending ``(rank, round, chunk)`` named:

1. **Matching** — every ``recv`` has exactly one same-round ``send`` with
   mirrored endpoints (rounds are barriers, so a send in a later round
   could never satisfy it: that is a deadlock, and the rejection says so);
   every ``send`` has a matching ``recv`` (an unreceived send is lost
   contribution); duplicate messages on one (src, dst, chunk) edge in one
   round are ambiguous and rejected.
2. **Consumption** — each recv is consumed by exactly one same-round
   ``reduce`` or ``copy`` on its (rank, chunk); a reduce/copy with no recv
   feeding it has nothing to combine; at most one recv lands per
   (rank, chunk) per round so the combine order is well-defined.
3. **No double-reduce** — a ``reduce`` whose incoming contribution set
   intersects the local one would fold some rank's input in twice; the
   duplicated contributors are named.
4. **Codec pairing** — an ``encode`` must wrap a same-round send whose
   receiver ``decode``\\ s with the same codec (an orphaned encode means
   the receiver would combine quantized wire values as if exact); a
   ``decode`` with no encoded incoming message decodes nothing.
5. **Delivery** — after the last round every non-relay rank holds, for
   every chunk, exactly the full contributor set (all non-relay ranks).

Point-to-point (``collective="pipeline"``) programs run the same abstract
interpretation with routed initial/final states: chunk ``c`` starts as the
private payload of ``chunk_sources[c]`` only, every hop must forward a
chunk its sender actually holds at round entry (an unheld send is a
use-before-receive ordering bug), and delivery means ``chunk_sinks[c]``
ends holding exactly the source's contribution — intermediate stages may
hold stale copies, sinks may not hold a wrong or empty one.
"""

from __future__ import annotations

from typing import Dict, FrozenSet, List, Tuple

from adapcc_tpu.compiler.ir import ScheduleProgram, Step


class ScheduleVerificationError(ValueError):
    """A program failed static verification; the message names the
    offending step as ``(rank=…, round=…, chunk=…)``."""


def _fail(round_idx: int, step: Step, why: str) -> None:
    raise ScheduleVerificationError(
        f"invalid schedule step at (rank={step.rank}, round={round_idx}, "
        f"chunk={step.chunk}): {step.describe()}: {why}"
    )


def normalize_program(program: ScheduleProgram) -> ScheduleProgram:
    """The unit-step, unfused view of an optimized program.

    The optimizer (``compiler/optimize.py``) emits two execution-shape
    annotations with no semantic content of their own: ``span`` steps
    (one step over a contiguous chunk range) and fused ``send``/``recv``
    steps carrying a ``codec`` (the encode/decode moved into the wire op).
    This expands both back to the legacy one-chunk encode/send/recv/decode
    form, so the abstract interpretation below — and the replay layer's
    per-chunk transfer log — check and price exactly what executes, with
    no optimizer-aware second implementation of either.  Programs already
    in normal form are returned unchanged (same object).
    """
    changed = False
    rounds: List[tuple] = []
    for rnd in program.rounds:
        steps: List[Step] = []
        for step in rnd:
            units = (
                [step] if step.span == 1 else [
                    Step(step.kind, step.rank, step.chunk + i,
                         peer=step.peer, codec=step.codec)
                    for i in range(step.span)
                ]
            )
            for unit in units:
                if unit.kind == "send" and unit.codec is not None:
                    steps.append(
                        Step("encode", unit.rank, unit.chunk, codec=unit.codec)
                    )
                    steps.append(Step("send", unit.rank, unit.chunk, peer=unit.peer))
                    changed = True
                elif unit.kind == "recv" and unit.codec is not None:
                    steps.append(Step("recv", unit.rank, unit.chunk, peer=unit.peer))
                    steps.append(
                        Step("decode", unit.rank, unit.chunk, codec=unit.codec)
                    )
                    changed = True
                else:
                    steps.append(unit)
            changed = changed or len(units) > 1
        rounds.append(tuple(steps))
    if not changed:
        return program
    import dataclasses

    return dataclasses.replace(
        program, rounds=tuple(rounds), applied_passes=(), block_size=None
    )


def verify_program(program: ScheduleProgram) -> None:
    """Certify ``program`` or raise :class:`ScheduleVerificationError`."""
    program = normalize_program(program)
    contributors = frozenset(program.contributors())
    pipeline = program.collective == "pipeline"
    # contribution state: state[rank][chunk] -> frozenset of folded ranks;
    # relays start empty (they forward, they do not contribute)
    if pipeline:
        # routed payloads: chunk c exists only at its source rank
        state: List[List[FrozenSet[int]]] = [
            [frozenset((r,)) if program.chunk_sources[c] == r else frozenset()
             for c in range(program.chunks)]
            for r in range(program.world)
        ]
    else:
        state = [
            [frozenset((r,)) if r in contributors else frozenset()
             for _ in range(program.chunks)]
            for r in range(program.world)
        ]

    for i, rnd in enumerate(program.rounds):
        sends: Dict[Tuple[int, int, int], Step] = {}  # (src, dst, chunk)
        recvs: Dict[Tuple[int, int, int], Step] = {}
        consumers: Dict[Tuple[int, int], List[Step]] = {}  # (rank, chunk)
        encodes: Dict[Tuple[int, int], Step] = {}  # (rank, chunk)
        decodes: Dict[Tuple[int, int], Step] = {}
        for step in rnd:
            if step.kind == "send":
                edge = (step.rank, step.peer, step.chunk)
                if edge in sends:
                    _fail(i, step, "duplicate send on this (src, dst, chunk) edge")
                sends[edge] = step
            elif step.kind == "recv":
                edge = (step.peer, step.rank, step.chunk)
                if edge in recvs:
                    _fail(i, step, "duplicate recv on this (src, dst, chunk) edge")
                recvs[edge] = step
            elif step.kind in ("reduce", "copy"):
                consumers.setdefault((step.rank, step.chunk), []).append(step)
            elif step.kind == "encode":
                if (step.rank, step.chunk) in encodes:
                    _fail(i, step, "duplicate encode for this (rank, chunk)")
                encodes[(step.rank, step.chunk)] = step
            elif step.kind == "decode":
                if (step.rank, step.chunk) in decodes:
                    _fail(i, step, "duplicate decode for this (rank, chunk)")
                decodes[(step.rank, step.chunk)] = step

        # 1. send <-> recv bijection inside the barrier round
        for edge, step in recvs.items():
            if edge not in sends:
                _fail(
                    i, step,
                    f"no matching send from rank {step.peer} in round {i} — "
                    "rounds are barriers, so this recv can never be "
                    "satisfied (deadlock)",
                )
        for edge, step in sends.items():
            if edge not in recvs:
                _fail(
                    i, step,
                    f"no matching recv at rank {step.peer} in round {i} — "
                    "the sent contribution would be dropped",
                )

        # 2. one recv per (rank, chunk), consumed exactly once
        landing: Dict[Tuple[int, int], Tuple[int, Step]] = {}
        for (src, dst, chunk), step in recvs.items():
            if (dst, chunk) in landing:
                _fail(
                    i, step,
                    "a second recv lands on this (rank, chunk) in one round; "
                    "the combine order would be ambiguous",
                )
            landing[(dst, chunk)] = (src, step)
        for key, steps in consumers.items():
            if len(steps) > 1:
                _fail(
                    i, steps[1],
                    "chunk consumed twice in one round (double-reduce)",
                )
            if key not in landing:
                _fail(i, steps[0], "consumes no received value (no recv feeds it)")
        for key, (src, step) in landing.items():
            if key not in consumers:
                _fail(
                    i, step,
                    "received value is never consumed (missing reduce/copy)",
                )

        # 4. codec pairing rides the matched messages
        for (rank, chunk), step in encodes.items():
            edge = next(
                (e for e in sends if e[0] == rank and e[2] == chunk), None
            )
            if edge is None:
                _fail(i, step, "encode wraps no same-round send")
            send = sends[edge]
            dec = decodes.get((send.peer, chunk))
            if dec is None:
                _fail(
                    i, step,
                    f"orphaned encode: receiver rank {send.peer} has no "
                    f"matching decode in round {i}",
                )
            if dec.codec != step.codec:
                _fail(
                    i, dec,
                    f"decode codec {dec.codec!r} does not match encode "
                    f"codec {step.codec!r}",
                )
        for (rank, chunk), step in decodes.items():
            if (rank, chunk) not in landing:
                _fail(i, step, "decode with no incoming message")
            src, _ = landing[(rank, chunk)]
            if (src, chunk) not in encodes:
                _fail(
                    i, step,
                    f"decode of an unencoded message from rank {src}",
                )

        # 3. dataflow: sends read round-entry state; reduce unions
        # disjoint contribution sets; copy overwrites
        entry = [list(row) for row in state]
        if pipeline:
            # a hop may only forward a payload its sender holds at round
            # entry — an empty send is a use-before-receive ordering bug
            for (src, _dst, chunk), step in sends.items():
                if not entry[src][chunk]:
                    _fail(
                        i, step,
                        f"sends chunk {chunk} before holding it — the "
                        f"payload (source rank "
                        f"{program.chunk_sources[chunk]}) has not reached "
                        f"rank {src} by round {i}",
                    )
        for (dst, chunk), (src, _step) in landing.items():
            incoming = entry[src][chunk]
            consumer = consumers[(dst, chunk)][0]
            if consumer.kind == "copy":
                state[dst][chunk] = incoming
            else:  # reduce
                dup = state[dst][chunk] & incoming
                if dup:
                    _fail(
                        i, consumer,
                        f"double-reduce: contributions {sorted(dup)} are "
                        "already folded into this chunk",
                    )
                state[dst][chunk] = state[dst][chunk] | incoming

    # 5. delivery
    if pipeline:
        # routed delivery: each chunk's sink holds exactly its source's
        # contribution (nothing lost, nothing folded in along the way)
        for c in range(program.chunks):
            src, sink = program.chunk_sources[c], program.chunk_sinks[c]
            want = frozenset((src,))
            if state[sink][c] != want:
                raise ScheduleVerificationError(
                    f"undelivered chunk at (rank={sink}, "
                    f"round={program.num_rounds - 1}, chunk={c}): sink holds "
                    f"{sorted(state[sink][c])}, expected the source payload "
                    f"from rank {src}"
                )
        return
    # collective delivery: every non-relay rank holds the full contributor set
    for r in program.contributors():
        for c in range(program.chunks):
            if state[r][c] != contributors:
                missing = sorted(contributors - state[r][c])
                raise ScheduleVerificationError(
                    f"undelivered chunk at (rank={r}, round={program.num_rounds - 1}, "
                    f"chunk={c}): final contributions {sorted(state[r][c])} "
                    f"are missing ranks {missing}"
                )
