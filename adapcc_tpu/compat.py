"""Version compatibility shims for the jax API surface this repo targets.

The codebase is written against the modern ``jax.shard_map(f, mesh=...,
in_specs=..., out_specs=..., check_vma=...)`` entry point.  Older jax
releases (< 0.6) only ship ``jax.experimental.shard_map.shard_map`` with the
``check_rep`` spelling of the replication-check knob.  Installing the alias
here — imported from ``adapcc_tpu/__init__`` — keeps every call site on the
one modern spelling instead of sprinkling try/except at 20+ call sites.
"""

from __future__ import annotations

import functools


def ensure_shard_map() -> None:
    """Install ``jax.shard_map`` on jax builds that predate it."""
    import jax

    if hasattr(jax, "shard_map"):
        return
    from jax.experimental.shard_map import shard_map as _legacy

    @functools.wraps(_legacy)
    def shard_map(f, mesh, in_specs, out_specs, check_vma=None, **kwargs):
        if check_vma is not None and "check_rep" not in kwargs:
            kwargs["check_rep"] = check_vma
        return _legacy(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
        )

    jax.shard_map = shard_map


def ensure_pallas_tpu_params() -> None:
    """Alias ``pltpu.CompilerParams`` on jax builds that still call it
    ``TPUCompilerParams`` (renamed upstream around jax 0.5)."""
    try:
        import jax.experimental.pallas.tpu as pltpu
    except ImportError:  # pallas not available on this build at all
        return
    if not hasattr(pltpu, "CompilerParams") and hasattr(pltpu, "TPUCompilerParams"):
        import dataclasses

        legacy_fields = {f.name for f in dataclasses.fields(pltpu.TPUCompilerParams)}

        def _compiler_params(**kwargs):
            # drop knobs the legacy dataclass doesn't know (has_side_effects
            # moved into CompilerParams upstream; legacy pallas_call keeps
            # the kernel alive through its data dependency instead)
            return pltpu.TPUCompilerParams(
                **{k: v for k, v in kwargs.items() if k in legacy_fields}
            )

        pltpu.CompilerParams = _compiler_params
    if not hasattr(pltpu, "InterpretParams"):
        class _InterpretParams:
            """Stand-in for the Mosaic TPU interpret-mode params (jax >= 0.5).

            Legacy pallas_call only understands ``interpret: bool``; kernels
            that need the TPU interpreter's cross-device semantics
            (semaphores, remote DMA) cannot run on this build and surface
            their own errors.  Truthiness routes the generic interpreter.
            """

            _adapcc_shim = True

            def __init__(self, **kwargs):
                self.kwargs = kwargs

            def __bool__(self):
                return True

        pltpu.InterpretParams = _InterpretParams


def tpu_interpret_mode_available() -> bool:
    """Whether this jax build ships the Mosaic TPU interpreter (semaphores,
    remote DMA) rather than the shimmed stand-in above."""
    try:
        import jax.experimental.pallas.tpu as pltpu
    except ImportError:
        return False
    return not getattr(
        getattr(pltpu, "InterpretParams", None), "_adapcc_shim", False
    )


def ring_kernels_supported() -> bool:
    """Whether the Pallas ICI ring kernels can execute here: a real TPU runs
    them through Mosaic; anywhere else they need the TPU interpret mode
    (cross-device semaphore/remote-DMA emulation, jax >= 0.5)."""
    import jax

    if jax.devices()[0].platform == "tpu":
        return True
    return tpu_interpret_mode_available()


def install() -> None:
    ensure_shard_map()
    ensure_pallas_tpu_params()
