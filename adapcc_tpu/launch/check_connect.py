"""Bring-up smoke checks — units-test/{check_mpi_connect,check-p2p} analogs.

The reference ships minimal scripts to validate a cluster before real runs:
an mpirun echo sanity check and a CUDA-aware MPI point-to-point test
(SURVEY §4.2).  The TPU analogs, runnable standalone or via launcher
``--exec-file "-m adapcc_tpu.launch.check_connect"``:

1. **world check**: the process joins the jax.distributed world (or the
   local/virtual device set) and reports device count + process indices —
   the ``echo HELLO`` analog.
2. **p2p check**: a one-hop ``ppermute`` ring pass with per-rank payloads
   verifying every neighbor link delivers intact data — the
   ``check_mpi_p2p.cu`` analog.
3. **collective check**: the ``ones*i → i*w`` allreduce oracle.

Exit code 0 iff every check passes.
"""

from __future__ import annotations

import argparse
from typing import Optional, Sequence


def check_world(world: Optional[int] = None):
    """Join the world; return (mesh, report string)."""
    from adapcc_tpu.launch import maybe_initialize_distributed

    distributed = maybe_initialize_distributed()

    import jax

    from adapcc_tpu.comm.mesh import build_world_mesh

    mesh = build_world_mesh(world)
    report = (
        f"world: {int(mesh.devices.size)} devices over "
        f"{jax.process_count()} process(es), platform "
        f"{jax.devices()[0].platform}, distributed={distributed}"
    )
    return mesh, report


def check_p2p(mesh) -> bool:
    """Every rank sends its rank-stamped payload one hop; each must receive
    exactly its left neighbor's."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax
    from jax.sharding import PartitionSpec as P

    world = int(mesh.devices.size)
    perm = [(i, (i + 1) % world) for i in range(world)]

    def shard(x):
        return lax.ppermute(x, "ranks", perm)

    fn = jax.jit(
        jax.shard_map(shard, mesh=mesh, in_specs=P("ranks"), out_specs=P("ranks"))
    )
    payload = jnp.stack([jnp.full((8,), r, jnp.float32) for r in range(world)])
    out = np.asarray(fn(payload))
    expect = np.stack([np.full((8,), (r - 1) % world) for r in range(world)])
    return bool((out == expect).all())


def check_allreduce(mesh) -> bool:
    """ones*i over w ranks must equal i*w everywhere (adapcc.py oracle)."""
    import jax.numpy as jnp
    import numpy as np

    from adapcc_tpu.comm.engine import CollectiveEngine
    from adapcc_tpu.strategy.ir import Strategy

    world = int(mesh.devices.size)
    engine = CollectiveEngine(mesh, Strategy.ring(world))
    for i in (1.0, 3.0):
        out = np.asarray(engine.all_reduce(jnp.ones((world, 8)) * i))
        if not np.allclose(out, i * world):
            return False
    return True


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--world", type=int, default=None)
    # accepted for launcher flag-contract compat; unused by the checks
    for flag in ("--port", "--entry_point", "--strategy_file", "--logical_graph",
                 "--parallel_degree", "--profile_freq"):
        ap.add_argument(flag, default=None)
    args = ap.parse_args(argv)

    mesh, report = check_world(int(args.world) if args.world else None)
    print(report)
    ok = True
    for name, check in (("p2p", check_p2p), ("allreduce", check_allreduce)):
        passed = check(mesh)
        print(f"{name} check: {'OK' if passed else 'FAILED'}")
        ok = ok and passed
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
