"""Artifact dissemination — the reference's scp fan-out, TPU-shaped.

The reference Dispatcher scp's four artifact classes between nodes
(dispatcher.py:23-54): the ip table to every rank's node, detected topology
to each node's local-rank-0, profiled topology to the master, and the
strategy to every node.  On TPU pods processes usually share a filesystem
(GCS fuse / NFS) or can exchange bytes through the ``jax.distributed`` KV
store, so the transport is pluggable:

- ``local``  — plain file copy (single host, virtual pods, shared fs).
- ``ssh``    — scp, byte-compatible with the reference for bare clusters.
- ``kvstore``— publish/fetch file bytes through the jax.distributed
  coordinator client.  Only valid *inside* a running job (the coordinator
  must exist), so the launcher CLI never uses it; the Communicator does,
  to keep the synthesized strategy byte-identical across processes.

Method names and call sites match the reference so the control plane reads
the same either way.
"""

from __future__ import annotations

import base64
import os
import shlex
import shutil
import subprocess
from typing import Dict, List, Optional, Sequence


class Dispatcher:
    """Fan artifact files out across the hosts of the job.

    ``ip_table`` is the per-rank host list (one entry per rank, duplicates
    meaning multiple ranks per host), exactly the reference's constructor
    contract (dispatcher.py:8-17).
    """

    def __init__(self, ip_table: Sequence[str], transport: str = "local"):
        if transport not in ("local", "ssh", "kvstore"):
            raise ValueError(f"unknown transport {transport!r}")
        self.transport = transport
        self.ip_dict: Dict[str, bool] = {}
        self.ip_table: List[str] = []
        self.renew_ip_table(ip_table)
        #: record of (src, host, dst) sends — the test/observability surface
        self.log: List[tuple] = []

    def init_ip_dict(self) -> None:
        for ip in self.ip_table:
            self.ip_dict.setdefault(ip, True)

    def renew_ip_table(self, ip_table: Sequence[str]) -> None:
        self.ip_table = list(ip_table)
        self.ip_dict = {}
        self.init_ip_dict()

    # --- transport ------------------------------------------------------------

    def _send(self, src_file: str, host: str, dst_path: str) -> None:
        self.log.append((src_file, host, dst_path))
        if self.transport == "local":
            dst = os.path.join(dst_path, os.path.basename(src_file))
            os.makedirs(dst_path, exist_ok=True)
            if os.path.abspath(src_file) != os.path.abspath(dst):
                shutil.copy2(src_file, dst)
        else:  # ssh; remote dst anchored to this cwd (workers `cd` here too)
            dst = dst_path if os.path.isabs(dst_path) else os.path.join(os.getcwd(), dst_path)
            mk = subprocess.run(["ssh", host, f"mkdir -p {shlex.quote(dst)}"])
            if mk.returncode != 0:
                raise RuntimeError(f"ssh {host} mkdir -p {dst} failed (rc={mk.returncode})")
            proc = subprocess.run(["scp", "-q", src_file, f"{host}:{shlex.quote(dst)}"])
            if proc.returncode != 0:
                raise RuntimeError(
                    f"scp {src_file} -> {host}:{dst} failed (rc={proc.returncode})"
                )

    def _fanout(self, src_file: str, hosts: Sequence[str], dst_path: str) -> None:
        if self.transport == "kvstore":
            # one publish covers every receiver; republishing a regenerated
            # artifact under the same key is allowed (overwrite)
            self.log.append((src_file, "kvstore", dst_path))
            publish_file(src_file)
            return
        for ip in hosts:
            self._send(src_file, ip, dst_path)

    # --- reference call sites (dispatcher.py:23-54) ---------------------------

    def dispatch_ip_table(self, src_file: str, dst_path: str) -> None:
        """Master sends the ip table to every node."""
        self._fanout(src_file, list(self.ip_dict), dst_path)

    def dispatch_detected_topo(self, src_file: str, dst_path: str) -> None:
        """Each local-rank-0 shares its detected topology with every node."""
        self._fanout(src_file, list(self.ip_dict), dst_path)

    def send_profiled_topo(self, src_file: str, dst_path: str) -> None:
        """Each local-rank-0 sends its profile matrix to the master."""
        self._fanout(src_file, [self.ip_table[0]], dst_path)

    def dispatch_strategy(self, src_file: str, dst_path: str) -> None:
        """Master sends the synthesized strategy to every node."""
        self._fanout(src_file, list(self.ip_dict), dst_path)


# --- jax.distributed KV-store transport ---------------------------------------


def _kv_client():
    from jax._src import distributed

    state = distributed.global_state
    if state.client is None:
        raise RuntimeError(
            "kvstore transport needs jax.distributed.initialize() first"
        )
    return state.client


def file_key(path: str) -> str:
    """Deterministic KV key for an artifact file name."""
    return f"adapcc/file/{os.path.basename(path)}"


def _kv_set(key: str, value: str) -> None:
    """Set-with-overwrite: regenerated artifacts republish under their key."""
    client = _kv_client()
    try:
        client.key_value_set(key, value, allow_overwrite=True)
    except TypeError:  # older jaxlib without the kwarg
        try:
            client.key_value_delete(key)
        except Exception:
            pass
        client.key_value_set(key, value)


def publish_file(path: str, key: Optional[str] = None) -> str:
    """Put a file's bytes into the coordinator KV store; returns the key."""
    key = key or file_key(path)
    with open(path, "rb") as f:
        _kv_set(key, base64.b64encode(f.read()).decode())
    return key


def fetch_file(key: str, dst_path: str, timeout_ms: int = 60_000, file_name: Optional[str] = None) -> str:
    """Blocking fetch of a published file into ``dst_path``.

    ``file_name`` overrides the on-disk name (keys may carry version
    suffixes that are not part of the artifact's file name).
    """
    data = _kv_client().blocking_key_value_get(key, timeout_ms)
    dst = os.path.join(dst_path, file_name or os.path.basename(key))
    os.makedirs(dst_path, exist_ok=True)
    with open(dst, "wb") as f:
        f.write(base64.b64decode(data))
    return dst


def publish_value(key: str, value: str) -> None:
    """Put a small string value into the coordinator KV store (overwrite ok)."""
    _kv_set(key, value)


def fetch_value(key: str, timeout_ms: int = 60_000) -> str:
    """Blocking fetch of a small string value."""
    return _kv_client().blocking_key_value_get(key, timeout_ms)
