"""Job launcher — the reference launcher.py, with jax.distributed as the world.

The reference packs an ``mpirun -np N -H host:slots,...`` command line, writes
``topology/ip_table.txt`` (one host line per rank), scp-disseminates it, and
execs the training script with the required flag contract forwarded
(launcher.py:34-86).  The TPU analog keeps steps 2-4 byte-compatible and
replaces mpirun with per-host process launch wired to the
``jax.distributed`` coordinator: one process per host (each process owns all
its local chips), with ``JAX_COORDINATOR_ADDRESS`` plus
``ADAPCC_NUM_PROCESSES`` / ``ADAPCC_PROCESS_ID`` replacing ``MASTER_ADDR`` /
world size / rank.  Workloads call :func:`maybe_initialize_distributed` to
consume that contract (the analog of reading ``OMPI_COMM_WORLD_*``,
reference commu.py:446-448).

Single-host virtual pods (the test rig) get
``XLA_FLAGS=--xla_force_host_platform_device_count=N JAX_PLATFORMS=cpu``
instead — the analog of the reference's fake multi-node localhost launches
(units-test/launch_get_wait_time.sh ``-H 127.0.0.1:4,127.0.0.1:4``).
"""

from __future__ import annotations

import argparse
import functools
import os
import shlex
import subprocess
import sys
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

from adapcc_tpu.launch.dispatcher import Dispatcher


@dataclass(frozen=True)
class HostSpec:
    ip: str
    num_chips: int


def parse_ips(spec: str) -> List[HostSpec]:
    """Parse ``host:chips,host:chips,...`` (reference ``--ips`` format)."""
    hosts = []
    for item in spec.split(","):
        item = item.strip()
        if not item:
            continue
        ip, _, n = item.partition(":")
        hosts.append(HostSpec(ip=ip, num_chips=int(n) if n else 1))
    if not hosts:
        raise ValueError(f"empty --ips spec: {spec!r}")
    return hosts


def order_hosts(hosts: Sequence[HostSpec], master: Optional[str]) -> List[HostSpec]:
    """Master's host first — rank 0 lives on the master node (launcher.py:8-9)."""
    hosts = list(hosts)
    if master is None:
        return hosts
    for i, h in enumerate(hosts):
        if h.ip == master:
            return [hosts[i], *hosts[:i], *hosts[i + 1 :]]
    raise ValueError(f"--master {master!r} is not one of the --ips hosts")


def write_ip_table(hosts: Sequence[HostSpec], path: str) -> List[str]:
    """One line per rank, in host order (launcher.py:64-79); callers pass
    the master-first ordering from :func:`order_hosts`."""
    from adapcc_tpu.strategy.xml_io import write_ip_table as write_lines

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    lines = [h.ip for h in hosts for _ in range(h.num_chips)]
    write_lines(lines, path)
    return lines


def forwarded_flags(args: argparse.Namespace) -> List[str]:
    """The required flag contract every exec-file accepts (launcher.py:53-62)."""
    return [
        f"--port={args.socket_port}",
        f"--entry_point={args.entry_point}",
        f"--strategy_file={args.strategy_file}",
        f"--logical_graph={args.logical_graph}",
        f"--parallel_degree={args.parallel_degree}",
        f"--profile_freq={args.profile_freq}",
    ]


def _exec_argv(exec_file: str, flags: Sequence[str]) -> List[str]:
    """``python script.py`` or ``python -m pkg.mod`` + forwarded flags."""
    if exec_file.startswith("-m "):
        return [sys.executable, "-m", exec_file[3:].strip(), *flags]
    return [sys.executable, exec_file, *flags]


@functools.lru_cache(maxsize=1)
def _local_identities() -> frozenset:
    """Every name/address this machine answers to, computed once per process.

    DNS of the hostname alone is unreliable (Debian maps the hostname to
    127.0.1.1; interface IPs often have no PTR/A records), so also discover
    the primary interface addresses via the UDP connect trick — no packets
    are sent, the kernel just picks the source address it would route with.
    """
    import socket

    ids = set()
    try:
        ids.add(socket.gethostname())
        ids.add(socket.getfqdn())
        for name in list(ids):
            try:
                ids.update(socket.gethostbyname_ex(name)[2])
            except OSError:
                pass
    except OSError:
        pass
    for probe in ("8.8.8.8", "2001:4860:4860::8888"):
        fam = socket.AF_INET6 if ":" in probe else socket.AF_INET
        try:
            with socket.socket(fam, socket.SOCK_DGRAM) as s:
                s.connect((probe, 80))
                ids.add(s.getsockname()[0])
        except OSError:
            pass
    return frozenset(ids)


def _is_local_host(ip: str) -> bool:
    """Does ``ip`` name the machine the launcher runs on?"""
    if ip in ("127.0.0.1", "::1", "localhost"):
        return True
    return ip in _local_identities()


def _virtual_env(num_chips: int) -> Dict[str, str]:
    """Forced-CPU virtual-pod env for one process."""
    return {
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={num_chips}"
        ).strip(),
    }


def build_launch_plan(
    args: argparse.Namespace, hosts: Optional[List[HostSpec]] = None
) -> List[Dict]:
    """One launch record per process: command + env.

    Multi-host: one process per host (master first), ssh-wrapped for remote
    hosts, with the jax.distributed coordinator env.  Single host: one local
    process exposing all chips (virtual CPU devices when ``--virtual``).
    """
    if hosts is None:
        hosts = order_hosts(parse_ips(args.ips), args.master)
    master = args.master or hosts[0].ip
    coordinator = f"{master}:{args.coordinator_port}"
    argv = _exec_argv(args.exec_file, forwarded_flags(args))

    plan: List[Dict] = []
    if len(hosts) == 1:
        env = _virtual_env(hosts[0].num_chips) if args.virtual else {}
        plan.append({"host": hosts[0].ip, "cmd": argv, "env": env})
        return plan

    for idx, h in enumerate(hosts):
        env = {
            "JAX_COORDINATOR_ADDRESS": coordinator,
            "ADAPCC_NUM_PROCESSES": str(len(hosts)),
            "ADAPCC_PROCESS_ID": str(idx),
        }
        if args.virtual:
            # fake multi-node on localhost: every process gets its own
            # forced-CPU device set, joined through the coordinator (the
            # reference's -H 127.0.0.1:4,127.0.0.1:4 localhost launches)
            env.update(_virtual_env(h.num_chips))
        if args.virtual or _is_local_host(h.ip):
            cmd = argv  # local process; env rides the Popen env dict
        else:
            exports = " ".join(f"{k}={shlex.quote(v)}" for k, v in env.items())
            remote = " ".join(shlex.quote(a) for a in argv)
            cmd = [
                "ssh", h.ip,
                f"cd {shlex.quote(os.getcwd())} && {exports} {remote}",
            ]
        plan.append({"host": h.ip, "cmd": cmd, "env": env})
    return plan


def apply_platform_env() -> None:
    """Re-pin ``jax_platforms`` from the env var.

    Site customizations may force-select a platform list at interpreter
    startup, overriding ``JAX_PLATFORMS`` from the launcher's ``--virtual``
    env; re-applying it through the config restores the requested backend.
    Safe no-op once a backend is already initialized with the same platform.
    """
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)


def maybe_initialize_distributed() -> bool:
    """Join the multi-host world described by the launcher env contract.

    Applies the platform env pin, then reads ``JAX_COORDINATOR_ADDRESS`` +
    ``ADAPCC_NUM_PROCESSES`` / ``ADAPCC_PROCESS_ID`` and calls
    ``jax.distributed.initialize``; returns False (after the platform pin)
    when launched single-host.  Call before first device use.
    """
    apply_platform_env()
    addr = os.environ.get("JAX_COORDINATOR_ADDRESS")
    num = os.environ.get("ADAPCC_NUM_PROCESSES")
    if not addr or not num or int(num) <= 1:
        return False
    import jax

    jax.distributed.initialize(
        coordinator_address=addr,
        num_processes=int(num),
        process_id=int(os.environ.get("ADAPCC_PROCESS_ID", "0")),
    )
    return True


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    # reference launcher flag contract (launcher.py:19-32); mpi-path/net-device
    # have no TPU meaning and are accepted-but-ignored for script compat
    p.add_argument("--num-process", type=int, default=None, help="ignored; derived from --ips")
    p.add_argument("--ips", type=str, default="127.0.0.1:8")
    p.add_argument("--master", type=str, default=None)
    p.add_argument("--mpi-path", type=str, default=None, help="ignored (no MPI on TPU)")
    p.add_argument("--net-device", type=str, default=None, help="ignored (ICI/DCN is implicit)")
    p.add_argument("--exec-file", type=str, default="-m adapcc_tpu.workloads.train_ddp")
    p.add_argument("--socket_port", type=str, default="5000")
    p.add_argument("--entry_point", type=int, default=-1, help="6:detect, 7:profile, -1:skip")
    p.add_argument("--strategy_file", type=str, default="topology/strategy.xml")
    p.add_argument("--logical_graph", type=str, default="topology/logical_graph.xml")
    p.add_argument("--parallel_degree", type=int, default=4)
    p.add_argument("--profile_freq", type=int, default=500)
    # TPU-native knobs
    p.add_argument("--coordinator_port", type=int, default=8476)
    p.add_argument("--ip_table", type=str, default="topology/ip_table.txt")
    # kvstore transport is runtime-only (needs a live coordinator) — not here
    p.add_argument("--transport", choices=["local", "ssh"], default="local")
    p.add_argument("--virtual", action="store_true", help="virtual CPU pod on one host")
    p.add_argument("--dry-run", action="store_true", help="print the plan, launch nothing")
    return p


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    hosts = order_hosts(parse_ips(args.ips), args.master)

    lines = write_ip_table(hosts, args.ip_table)
    dispatcher = Dispatcher(lines, transport=args.transport)
    dispatcher.dispatch_ip_table(args.ip_table, os.path.dirname(args.ip_table) or ".")

    plan = build_launch_plan(args, hosts)

    if args.dry_run:
        for rec in plan:
            print(rec["host"], " ".join(rec["cmd"]), rec["env"])
        return 0

    procs = []
    for rec in plan:
        env = {**os.environ, **rec["env"]}
        procs.append(subprocess.Popen(rec["cmd"], env=env))
    rcs = [p.wait() for p in procs]
    return next((rc for rc in rcs if rc != 0), 0)


if __name__ == "__main__":
    raise SystemExit(main())
