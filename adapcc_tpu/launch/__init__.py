"""Process launch + artifact dissemination (reference launcher.py/dispatcher.py).

The reference launches ranks with ``mpirun -H host:slots,...`` and fans
topology/strategy files out with ``scp`` (launcher.py:34-62,
dispatcher.py:23-54).  The TPU-native equivalents: processes are started per
*host* (one JAX process per host controls all local chips) with the
``jax.distributed`` coordinator env replacing the MPI world, and artifacts
travel over a pluggable transport — local copy (single host / shared fs),
ssh/scp (bare multi-host), or the jax.distributed KV store (TPU pods).
"""

from adapcc_tpu.launch.dispatcher import Dispatcher
from adapcc_tpu.launch.launcher import (
    HostSpec,
    build_launch_plan,
    main,
    maybe_initialize_distributed,
    order_hosts,
    parse_ips,
    write_ip_table,
)

__all__ = [
    "Dispatcher",
    "HostSpec",
    "build_launch_plan",
    "main",
    "maybe_initialize_distributed",
    "order_hosts",
    "parse_ips",
    "write_ip_table",
]
