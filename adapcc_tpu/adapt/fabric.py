"""Multi-tenant QoS: prioritized jobs sharing one fabric, yielding links.

The Big Send-off (PAPERS.md) frames datacenter collectives as tenants of
a shared fabric: the schedule that wins a clean-network benchmark can be
exactly the one that starves a neighbor on a shared DCN link.  This
module is the two-job harness that makes the trade a printed number:

- every :class:`FabricJob` carries a priority (``ADAPCC_JOB_PRIORITY``:
  ``high`` | ``low``, malformed → loud), its OWN
  :class:`~adapcc_tpu.elastic.worldview.WorldView` and
  :class:`~adapcc_tpu.coordinator.logic.CoordinatorLogic` (per-job
  worldviews, one fabric — supervisor isolation is per tenant), and its
  own :class:`~adapcc_tpu.strategy.synthesizer.Synthesizer` over the
  SHARED ip table;
- :meth:`SharedFabric.plan` assigns strategies in priority order: each
  job's candidates are ranked under a model where every link a
  higher-priority job's strategy occupies is CONTENDED by the share
  penalty (β × penalty — :func:`~adapcc_tpu.sim.cost_model.
  contended_coeffs`), so the low-priority job's winning tree *avoids*
  the high-priority job's hot links instead of fighting for them —
  graceful yielding, synthesized rather than policed;
- the resulting :class:`FabricPlan` prices the fairness/throughput
  frontier: each job's steady state under coordinated sharing vs the
  uncoordinated baseline (every job greedily picks the clean-network
  winner, maximally overlapping), with Jain's fairness index and
  aggregate throughput stamped per row.  Deterministic — same model →
  byte-identical frontier rows (the ``--fabric-sweep`` property).

The acceptance shape (docs/FABRIC.md §5): under coordination the
high-priority job's links stay uncontended, so its steady state is
STRICTLY better than under the uncoordinated pile-up — priority costs
the low job bounded slowdown instead of costing both jobs the fabric.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Sequence, Tuple

from adapcc_tpu.sim.cost_model import (
    Link,
    LinkCostModel,
    contended_coeffs,
)
from adapcc_tpu.strategy.ir import Strategy, Tree

#: per-job priority env (docs/OPERATIONS.md): which tenant yields when
#: strategies would collide on a shared link
JOB_PRIORITY_ENV = "ADAPCC_JOB_PRIORITY"

JOB_PRIORITIES = ("high", "low")

#: bandwidth penalty a contended shared link costs each of its users:
#: two lockstep collectives on one wire each see half the bandwidth
DEFAULT_SHARE_PENALTY = 2.0


def job_priority(explicit: Optional[str] = None) -> str:
    """The job priority in force: ``ADAPCC_JOB_PRIORITY`` env > the
    explicit argument > "high" (a job that never declared a priority must
    not silently yield).  Malformed → loud error, never a silent default
    (the ADAPCC_RING_CHUNK_BYTES policy)."""
    env = os.environ.get(JOB_PRIORITY_ENV)
    value = env if env is not None and env.strip() else explicit
    if value is None:
        return "high"
    prio = str(value).strip().lower()
    if prio not in JOB_PRIORITIES:
        raise ValueError(
            f"{JOB_PRIORITY_ENV}={value!r}: expected one of "
            f"{'|'.join(JOB_PRIORITIES)}"
        )
    return prio


# --------------------------------------------------------------------------- #
# link occupancy
# --------------------------------------------------------------------------- #

def strategy_links(strategy: Strategy) -> FrozenSet[Link]:
    """Every directed link a strategy's trees occupy: reduce traverses
    child → parent, broadcast parent → child, so each tree edge claims
    BOTH directions — the occupancy set the yielding model contends."""
    links: set = set()
    for tree in strategy.trees:
        for child, parent in tree.parent.items():
            links.add((parent, child))
            links.add((child, parent))
    return frozenset(links)


def hot_links(
    strategy: Strategy, model: LinkCostModel, band: float = 0.5
) -> FrozenSet[Link]:
    """The strategy's BOTTLENECK links: occupied links whose per-1MB cost
    sits within ``band`` of the most expensive occupied link (a lockstep
    schedule is paced there — on a pod fabric this is the strategy's DCN
    edge set).  The avoidance drill pins disjointness of these sets, not
    of full occupancy: two spanning trees on one pod necessarily share
    some fast ICI wire, but they need never collide on the slow links
    that pace them."""
    if not 0.0 < band <= 1.0:
        raise ValueError(f"band must be in (0, 1], got {band}")
    links = strategy_links(strategy)
    if not links:
        return frozenset()
    probe = 1 << 20
    cost = {l: model.coeffs(*l).time(probe) for l in links}
    top = max(cost.values())
    return frozenset(l for l, c in cost.items() if c >= band * top)


def contend_links(
    model: LinkCostModel, links: Sequence[Link], factor: float
) -> LinkCostModel:
    """A copy of the model with the NAMED directed links contended by
    ``factor`` (β scaled, α intact — per-link congestion, the yielding
    price).  Per-link entries win over class means in ``coeffs``, so the
    contention is visible to every pricing pass."""
    if factor < 1.0:
        raise ValueError(f"share factor must be >= 1, got {factor}")
    contended = dict(model.links)
    for l in links:
        contended[l] = contended_coeffs(model.coeffs(*l), factor)
    return LinkCostModel(
        model.world,
        links=contended,
        classes=model.classes,
        ips=model.ips,
        source=f"{model.source}+shared[{len(set(links))}links]",
    )


# --------------------------------------------------------------------------- #
# jobs
# --------------------------------------------------------------------------- #

@dataclass
class FabricJob:
    """One tenant: a named job with a priority, its own worldview and
    coordinator logic over the SHARED topology, and its own synthesizer.
    Per-job state is deliberately isolated — one tenant's failover or
    adaptation must never mutate another's world picture."""

    name: str
    priority: str = "high"
    nbytes: int = 16 << 20
    degree: int = 1
    worldview: object = None
    coordinator: object = None
    synthesizer: object = None

    def __post_init__(self) -> None:
        if self.priority not in JOB_PRIORITIES:
            raise ValueError(
                f"job {self.name!r}: unknown priority {self.priority!r}; "
                f"expected one of {JOB_PRIORITIES}"
            )
        if self.nbytes < 1:
            raise ValueError(f"job {self.name!r}: nbytes must be >= 1")


@dataclass
class JobAssignment:
    """One job's planned strategy plus its priced steady states."""

    job: FabricJob
    label: str
    strategy: Strategy
    #: predicted steady state on the model this job was ranked under
    #: (higher-priority occupancy already contended)
    ranked_s: float
    #: steady state under the final shared fabric (every co-tenant link
    #: contended by its user count)
    shared_s: float = 0.0
    #: steady state this job would see alone on the clean fabric
    alone_s: float = 0.0
    yielded_links: int = 0

    def to_row(self) -> dict:
        return {
            "job": self.job.name,
            "priority": self.job.priority,
            "strategy": self.label,
            "pred_us": round(self.shared_s * 1e6, 3),
            "alone_us": round(self.alone_s * 1e6, 3),
            "slowdown": round(
                self.shared_s / self.alone_s if self.alone_s > 0 else 1.0, 6
            ),
            "yielded_links": self.yielded_links,
        }


@dataclass
class FabricPlan:
    """The planned fabric: per-job assignments plus the frontier row."""

    assignments: List[JobAssignment]
    share_penalty: float
    coordinated: bool
    #: directed links used by more than one job's strategy
    shared_links: FrozenSet[Link] = frozenset()

    def job(self, name: str) -> JobAssignment:
        for a in self.assignments:
            if a.job.name == name:
                return a
        raise KeyError(f"no job {name!r} in this fabric plan")

    def fairness(self) -> float:
        """Jain's index over per-job sharing efficiencies (alone ÷
        shared, each in (0, 1]): 1.0 = every tenant keeps the same
        fraction of its clean-fabric throughput, i.e. pays the same
        contention tax.  (Jain is not inversion-invariant — the index
        over slowdowns would be a different number.)"""
        xs = [
            a.alone_s / a.shared_s if a.shared_s > 0 else 1.0
            for a in self.assignments
        ]
        n = len(xs)
        if n == 0:
            return 1.0
        s = sum(xs)
        sq = sum(x * x for x in xs)
        return (s * s) / (n * sq) if sq > 0 else 1.0

    def throughput_gbps(self) -> float:
        """Aggregate fabric throughput in **gigabits/s** (the unit link
        specs quote — the 12.5 GB/s DCN class is 100 Gbps):
        Σ job payload ÷ job steady state, × 8."""
        return sum(
            a.job.nbytes * 8.0 / a.shared_s / 1e9
            for a in self.assignments
            if a.shared_s > 0
        )

    def to_row(self) -> dict:
        return {
            "coordinated": self.coordinated,
            "share_penalty": self.share_penalty,
            "shared_links": len(self.shared_links),
            "fairness": round(self.fairness(), 6),
            "throughput_gbps": round(self.throughput_gbps(), 6),
            "jobs": [a.to_row() for a in self.assignments],
        }


# --------------------------------------------------------------------------- #
# the shared fabric
# --------------------------------------------------------------------------- #

def _priority_order(jobs: Sequence[FabricJob]) -> List[FabricJob]:
    """High first; ties keep registration order (stable sort)."""
    return sorted(jobs, key=lambda j: JOB_PRIORITIES.index(j.priority))


def _rotated_chain(world: int, start: int, ips: Dict[int, str]) -> Strategy:
    """A chain strategy rotated to start at ``start`` — the rotation
    moves WHICH pod boundary the chain crosses, which is exactly the
    degree of freedom a yielding job needs to route around an occupied
    cross-pod link."""
    order = [(start + i) % world for i in range(world)]
    children: Dict[int, List[int]] = {
        order[i]: [order[i + 1]] for i in range(world - 1)
    }
    s = Strategy([Tree(order[0], children, ips)], world)
    s.synthesis = f"ring@{start}"
    return s


class SharedFabric:
    """One simulated topology, many prioritized tenants (module doc)."""

    def __init__(
        self,
        model: LinkCostModel,
        ip_table: Sequence[str],
        share_penalty: float = DEFAULT_SHARE_PENALTY,
    ) -> None:
        if len(ip_table) != model.world:
            raise ValueError(
                f"ip table has {len(ip_table)} entries for a world-"
                f"{model.world} model"
            )
        if share_penalty < 1.0:
            raise ValueError(
                f"share_penalty must be >= 1, got {share_penalty}"
            )
        self.ip_table = list(ip_table)
        self.ips = {r: ip for r, ip in enumerate(self.ip_table)}
        self.model = (
            model if model.ips is not None else model.with_ips(self.ips)
        )
        self.share_penalty = float(share_penalty)
        self.jobs: List[FabricJob] = []

    @property
    def world(self) -> int:
        return self.model.world

    def add_job(
        self,
        name: str,
        priority: Optional[str] = None,
        nbytes: int = 16 << 20,
        degree: int = 1,
    ) -> FabricJob:
        """Register a tenant with its own worldview + coordinator logic
        (isolation) and its own synthesizer over the shared ip table.
        An EXPLICIT ``priority`` wins here; only an unset one resolves
        through :func:`job_priority` (env).  ``ADAPCC_JOB_PRIORITY`` is a
        per-process knob — a harness registering both tenants in one
        process must not have the env clobber both to the same class
        (the "high-low" plan would silently measure low-low)."""
        from adapcc_tpu.coordinator.logic import CoordinatorLogic
        from adapcc_tpu.elastic.worldview import WorldView
        from adapcc_tpu.strategy.synthesizer import Synthesizer

        if any(j.name == name for j in self.jobs):
            raise ValueError(f"job {name!r} already registered")
        job = FabricJob(
            name=name,
            priority=(
                str(priority).strip().lower()
                if priority is not None
                else job_priority()
            ),
            nbytes=int(nbytes),
            degree=max(1, int(degree)),
            worldview=WorldView.full(self.world),
            coordinator=CoordinatorLogic(self.world),
            synthesizer=Synthesizer(None, self.ip_table),
        )
        self.jobs.append(job)
        return job

    # -- candidates ------------------------------------------------------------

    def _candidates(self, job: FabricJob) -> List[Tuple[str, Strategy]]:
        """The job's candidate pool: its synthesizer's own shapes plus a
        rotated chain per pod boundary — the rotations give a yielding
        job cross-pod edges the incumbent tenants do NOT occupy, so
        avoidance is expressible, not just priced."""
        bw, lat = self.model.to_graphs()
        cands = list(job.synthesizer.candidates(job.degree, bw, lat))
        starts = sorted(
            {
                r
                for r in range(self.world)
                if r == 0 or self.ip_table[r - 1] != self.ip_table[r]
            }
        )
        for start in starts:
            s = _rotated_chain(self.world, start, self.ips)
            cands.append((s.synthesis, s))
        return cands

    # -- planning --------------------------------------------------------------

    def plan(self, coordinated: bool = True) -> FabricPlan:
        """Assign every registered job a strategy (module doc).

        ``coordinated=True`` ranks each job under the occupancy of every
        higher-priority tenant (contended by the share penalty), so lower
        priorities yield.  ``coordinated=False`` is the baseline: every
        job greedily ranks on the clean model — what an uncoordinated
        fabric does, and what the frontier row prices it against.
        Deterministic: no RNG, no wall clock.
        """
        if not self.jobs:
            raise ValueError("no jobs registered on this fabric")
        from adapcc_tpu import sim

        assignments: List[JobAssignment] = []
        occupied: set = set()
        for job in _priority_order(self.jobs):
            if coordinated and occupied:
                ranked_model = contend_links(
                    self.model, sorted(occupied), self.share_penalty
                )
            else:
                ranked_model = self.model
            ranked = sim.rank_candidates(
                self._candidates(job), ranked_model, job.nbytes, "allreduce"
            )
            winner = ranked[0]
            assignments.append(
                JobAssignment(
                    job=job,
                    label=winner.label,
                    strategy=winner.strategy,
                    ranked_s=winner.seconds,
                    yielded_links=len(occupied) if coordinated else 0,
                )
            )
            occupied |= strategy_links(winner.strategy)
        # -- price the final shared fabric: each link contended by its
        # user count (two tenants on one wire each see half of it)
        use_count: Dict[Link, int] = {}
        for a in assignments:
            for l in strategy_links(a.strategy):
                use_count[l] = use_count.get(l, 0) + 1
        shared = frozenset(l for l, n in use_count.items() if n > 1)
        shared_model = self.model
        for n_users in sorted({n for n in use_count.values() if n > 1}):
            links = [l for l, n in use_count.items() if n == n_users]
            shared_model = contend_links(
                shared_model, sorted(links),
                1.0 + (self.share_penalty - 1.0) * (n_users - 1),
            )
        for a in assignments:
            a.alone_s = sim.simulate_strategy(
                a.strategy, self.model, a.job.nbytes, "allreduce",
                keep_transfers=False,
            ).seconds
            a.shared_s = sim.simulate_strategy(
                a.strategy, shared_model, a.job.nbytes, "allreduce",
                keep_transfers=False,
            ).seconds
        return FabricPlan(
            assignments=assignments,
            share_penalty=self.share_penalty,
            coordinated=coordinated,
            shared_links=shared,
        )

    def frontier(self) -> dict:
        """The fairness/throughput frontier row: the coordinated plan
        priced against the uncoordinated pile-up — one deterministic
        artifact row (the ``--fabric-sweep`` unit)."""
        coord = self.plan(coordinated=True)
        unco = self.plan(coordinated=False)
        row = {
            "mode": "simulated",
            "world": self.world,
            "share_penalty": self.share_penalty,
            "coordinated": coord.to_row(),
            "uncoordinated": unco.to_row(),
        }
        highs_c = [
            a for a in coord.assignments if a.job.priority == "high"
        ]
        highs_u = {a.job.name: a for a in unco.assignments}
        # bool(highs_c): a fabric with no high-priority tenant has no
        # acceptance claim to make — all([]) must not stamp a vacuous True
        row["high_priority_wins"] = bool(highs_c) and all(
            a.shared_s < highs_u[a.job.name].shared_s or
            a.shared_s == a.alone_s
            for a in highs_c
        )
        return row
