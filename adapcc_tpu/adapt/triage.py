"""Congestion-vs-degradation triage over a fired drift report.

PR 9's closed loop treats every fired window as *degradation*: invert,
decay-merge into ``topology/calibration.json``, re-rank, swap.  That is
the wrong robustness behavior for a congested link — transient neighbor
traffic would be "fixed" by permanently corrupting the α-β calibration,
and when the window clears the artifact remembers a fabric that no longer
exists.  This module is the missing classification step:

- **congestion** — the regression is localized to a shared link class
  with the *bandwidth share* signature: the fitted β blew past the drift
  factor while α stayed mostly intact
  (:func:`~adapcc_tpu.sim.cost_model.contended_coeffs` is exactly this
  shape).  The right response is a transient re-route off the hot class
  (:meth:`AdaptationController.maybe_adapt` →
  ``outcome="congestion-reroute"``) with the calibration artifact
  **byte-untouched** and the incumbent restored when the window clears.
- **degradation** — anything else: both terms stretched (a genuinely
  slow wire prices like :meth:`LinkCoeffs.scaled`), α-dominated drift,
  or evidence at a single payload size (one size cannot separate α from
  β, so the conservative call keeps PR 9's re-calibrate path — a real
  degradation mis-read as congestion would re-route forever and never
  fix the model, the worse failure).

The α/β separation needs fired windows at **two or more distinct payload
sizes** — the same requirement the PR-11 leader-level re-fit drill
established; the controller's congestion-profile injection funnel feeds
two payload decades for exactly this reason.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

from typing import Optional

from adapcc_tpu.adapt.detector import DriftReport
from adapcc_tpu.adapt.recalibrate import _hop_points
from adapcc_tpu.sim.cost_model import (
    LinkCostModel,
    bottleneck_ring_coeffs,
    bottleneck_ring_link,
    fit_alpha_beta,
)

#: fitted-α tolerance: congestion leaves α within this factor of the
#: calibrated value (bandwidth share is stolen; propagation is not)
CONGESTION_ALPHA_BAND = 1.5

#: fitted-β threshold: the effective-bandwidth cut must at least match
#: the default drift factor, or the evidence is noise the window absorbed
CONGESTION_BETA_SEPARATION = 2.0

TRIAGE_KINDS = ("congestion", "degradation")


@dataclass(frozen=True)
class TriageVerdict:
    """One fired drift report's classification."""

    kind: str                 #: "congestion" | "degradation"
    link_class: str           #: the link class the fitted evidence names
    alpha_ratio: float        #: fitted α ÷ calibrated α
    beta_ratio: float         #: fitted β ÷ calibrated β
    #: whether the evidence spanned >= 2 payload sizes (α/β separable);
    #: False forces the conservative "degradation" call
    separable: bool

    @property
    def factor(self) -> float:
        """The effective contention factor a congestion verdict carries —
        the β inflation (the bandwidth share the neighbor took)."""
        return self.beta_ratio

    def to_row(self) -> dict:
        return {
            "kind": self.kind,
            "link_class": self.link_class,
            "alpha_ratio": round(self.alpha_ratio, 6),
            "beta_ratio": round(self.beta_ratio, 6),
            "separable": self.separable,
        }


def classify_drift(
    report: DriftReport,
    model: LinkCostModel,
    alpha_band: float = CONGESTION_ALPHA_BAND,
    separation: float = CONGESTION_BETA_SEPARATION,
) -> Optional[TriageVerdict]:
    """Classify a fired drift report (module doc), or None when no fired
    signal carries link algebra (baseline-referenced cells only — the
    ``uninvertible`` outcome the controller already stops on).

    Deterministic, analytic: the SAME per-hop inversion the
    re-calibration uses (:mod:`adapcc_tpu.adapt.recalibrate`), so triage
    and re-calibration can never disagree about what the evidence says.
    """
    if alpha_band < 1.0:
        raise ValueError(f"alpha_band must be >= 1, got {alpha_band}")
    if separation <= 1.0:
        raise ValueError(
            f"separation must be > 1, got {separation}: at <= 1 healthy "
            "noise would classify as congestion"
        )
    fired_points, _samples = _hop_points(report.fired, model.world)
    if not fired_points:
        return None
    # the FIT spans every full priced window, fired or not: a small-
    # payload window that stayed healthy while the large one blew past
    # the factor is not absence of evidence — it IS the α-intact half of
    # the congestion signature (an α-degraded wire would have fired the
    # small window too)
    points, _ = _hop_points(
        [s for s in report.signals if s.reference == "calibration"],
        model.world,
    )
    link = bottleneck_ring_link(model, model.world)
    cls = model.link_class_of(*link)
    current = bottleneck_ring_coeffs(model, model.world)
    distinct_sizes = {round(b, 3) for b, _ in points}
    if len(distinct_sizes) < 2:
        # one payload size cannot separate α from β: the conservative
        # call is degradation (PR 9's re-calibrate path), never a
        # re-route on inseparable evidence
        nbytes, seconds = fired_points[0]
        predicted = current.time(nbytes)
        ratio = seconds / predicted if predicted > 0 else 1.0
        return TriageVerdict(
            kind="degradation",
            link_class=cls,
            alpha_ratio=ratio,
            beta_ratio=ratio,
            separable=False,
        )
    fitted = fit_alpha_beta(points)
    # attribute the evidence to a link class by the α signature: the
    # priced ring is paced by the CONTENDED fabric's bottleneck hop, and
    # congestion leaves that hop's α intact — so the class whose healthy
    # α the fit REPRODUCES (two-sided: within the band either way) is
    # the class the fit measured.  A contended ICI that overtook the
    # healthy DCN bottleneck fits ICI's µs-scale α, not DCN's; pinning
    # the healthy bottleneck's class would re-route off the wrong
    # (still-healthy) class.  The band is deliberately two-sided and
    # exclusive: a fit whose α lands BETWEEN classes (e.g. an ICI wire
    # degraded far enough that its stretched α drifts toward DCN's)
    # matches nothing and keeps the healthy-bottleneck anchor, where the
    # two-sided α test below reads it as degradation — a degradation
    # misread as congestion would re-route forever and never fix the
    # model, the worse failure.  (A degradation whose stretched α lands
    # EXACTLY on another class's α is observationally equivalent to that
    # class's congestion through a scalar probe; no triage can split it.)
    if fitted.alpha > 0:
        candidates = [
            (c, co)
            for c, co in model.classes.items()
            if co.alpha > 0
            and max(fitted.alpha / co.alpha, co.alpha / fitted.alpha)
            <= alpha_band
        ]
        if len(candidates) == 1:
            cls, current = candidates[0]
        elif len(candidates) > 1 and not any(c == cls for c, _ in candidates):
            cls, current = min(
                candidates,
                key=lambda item: abs(math.log(fitted.alpha / item[1].alpha)),
            )
    alpha_ratio = fitted.alpha / current.alpha if current.alpha > 0 else 1.0
    beta_ratio = fitted.beta / current.beta if current.beta > 0 else 1.0
    # α must be INTACT both ways: a fitted α well below the anchor class
    # is not "intact", it is evidence the anchor is wrong (some other
    # stretched wire overtook it) — degradation, never a re-route
    alpha_intact = (
        max(alpha_ratio, 1.0 / alpha_ratio) <= alpha_band
        if alpha_ratio > 0
        else False
    )
    congestion = (
        alpha_intact
        and beta_ratio >= separation
        and beta_ratio > alpha_ratio
    )
    return TriageVerdict(
        kind="congestion" if congestion else "degradation",
        link_class=cls,
        alpha_ratio=alpha_ratio,
        beta_ratio=beta_ratio,
        separable=True,
    )


def contended_view(
    model: LinkCostModel, verdict: TriageVerdict
) -> LinkCostModel:
    """The TRANSIENT cost model a congestion verdict implies: the live
    model with the named class contended by the fitted β inflation —
    never merged, never persisted (the calibration artifact stays
    byte-unchanged; reversibility is the point)."""
    if verdict.kind != "congestion":
        raise ValueError(
            f"contended_view needs a congestion verdict, got {verdict.kind!r}"
        )
    return model.contended({verdict.link_class: max(1.0, verdict.factor)})
