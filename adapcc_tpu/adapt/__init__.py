"""Closed-loop online adaptation (docs/ADAPT.md).

The paper's core promise is *adaptive* collective communication: re-profile
every ``profile_freq`` steps, re-synthesize when link conditions drift
(PAPER.md:61).  Before this package the only re-adaptation path was
``AdapCC.reconstruct_topology`` — a full teardown + active re-profile +
re-synthesis + engine rebuild, paying probe traffic and recompiles the
whole time.  This package closes the loop from data that already flows,
with **zero probe traffic on the hot path**:

- :mod:`adapcc_tpu.adapt.detector` — passive drift detection over rolling
  per-plan-cell windows of the measurements the tuner already records
  (``ADAPCC_DRIFT_FACTOR`` / ``ADAPCC_DRIFT_WINDOW``);
- :mod:`adapcc_tpu.adapt.recalibrate` — observed collective timings
  inverted back into per-link-class α-β corrections through the existing
  ``fit_alpha_beta`` + ``calibrate.py`` funnel, decay-merged into
  ``topology/calibration.json`` (never last-writer-wins);
- :mod:`adapcc_tpu.adapt.controller` — sim re-rank over candidate
  strategies under the corrected costs, top-k AOT-compiled through the
  PR-7 :class:`StandbyPlanCache`, adoption a hysteresis-gated
  ``advance_epoch`` cache-key switch (``ADAPCC_ADAPT=off|detect|swap``);
- :mod:`adapcc_tpu.adapt.triage` — congestion-vs-degradation triage over
  a fired drift report (docs/FABRIC.md): congestion re-routes under a
  TRANSIENT contended model and restores the incumbent when the window
  clears; only degradation takes the re-calibrate path above;
- :mod:`adapcc_tpu.adapt.fabric` — the multi-tenant QoS harness: two
  prioritized jobs on one simulated topology, the low-priority job's
  synthesizer constrained off the links the high-priority job occupies
  (``ADAPCC_JOB_PRIORITY``), the fairness/throughput frontier priced.
"""

from adapcc_tpu.adapt.controller import (
    ADAPT_MODE_ENV,
    ADAPT_MODES,
    AdaptationController,
    AdaptationReport,
    adapt_mode,
)
from adapcc_tpu.adapt.fabric import (
    JOB_PRIORITIES,
    JOB_PRIORITY_ENV,
    FabricJob,
    FabricPlan,
    SharedFabric,
    job_priority,
)
from adapcc_tpu.adapt.triage import (
    TriageVerdict,
    classify_drift,
    contended_view,
)
from adapcc_tpu.adapt.detector import (
    DEFAULT_DRIFT_FACTOR,
    DEFAULT_DRIFT_WINDOW,
    DRIFT_FACTOR_ENV,
    DRIFT_WINDOW_ENV,
    DriftDetector,
    DriftReport,
    DriftSignal,
    resolve_drift_factor,
    resolve_drift_window,
)
from adapcc_tpu.adapt.recalibrate import (
    calibration_of,
    corrected_model,
    drift_correction,
)

__all__ = [
    "ADAPT_MODE_ENV",
    "ADAPT_MODES",
    "AdaptationController",
    "AdaptationReport",
    "DEFAULT_DRIFT_FACTOR",
    "DEFAULT_DRIFT_WINDOW",
    "DRIFT_FACTOR_ENV",
    "DRIFT_WINDOW_ENV",
    "DriftDetector",
    "DriftReport",
    "DriftSignal",
    "FabricJob",
    "FabricPlan",
    "JOB_PRIORITIES",
    "JOB_PRIORITY_ENV",
    "SharedFabric",
    "TriageVerdict",
    "adapt_mode",
    "calibration_of",
    "classify_drift",
    "contended_view",
    "corrected_model",
    "drift_correction",
    "job_priority",
    "resolve_drift_factor",
    "resolve_drift_window",
]
