"""Closed-loop online adaptation (docs/ADAPT.md).

The paper's core promise is *adaptive* collective communication: re-profile
every ``profile_freq`` steps, re-synthesize when link conditions drift
(PAPER.md:61).  Before this package the only re-adaptation path was
``AdapCC.reconstruct_topology`` — a full teardown + active re-profile +
re-synthesis + engine rebuild, paying probe traffic and recompiles the
whole time.  This package closes the loop from data that already flows,
with **zero probe traffic on the hot path**:

- :mod:`adapcc_tpu.adapt.detector` — passive drift detection over rolling
  per-plan-cell windows of the measurements the tuner already records
  (``ADAPCC_DRIFT_FACTOR`` / ``ADAPCC_DRIFT_WINDOW``);
- :mod:`adapcc_tpu.adapt.recalibrate` — observed collective timings
  inverted back into per-link-class α-β corrections through the existing
  ``fit_alpha_beta`` + ``calibrate.py`` funnel, decay-merged into
  ``topology/calibration.json`` (never last-writer-wins);
- :mod:`adapcc_tpu.adapt.controller` — sim re-rank over candidate
  strategies under the corrected costs, top-k AOT-compiled through the
  PR-7 :class:`StandbyPlanCache`, adoption a hysteresis-gated
  ``advance_epoch`` cache-key switch (``ADAPCC_ADAPT=off|detect|swap``).
"""

from adapcc_tpu.adapt.controller import (
    ADAPT_MODE_ENV,
    ADAPT_MODES,
    AdaptationController,
    AdaptationReport,
    adapt_mode,
)
from adapcc_tpu.adapt.detector import (
    DEFAULT_DRIFT_FACTOR,
    DEFAULT_DRIFT_WINDOW,
    DRIFT_FACTOR_ENV,
    DRIFT_WINDOW_ENV,
    DriftDetector,
    DriftReport,
    DriftSignal,
    resolve_drift_factor,
    resolve_drift_window,
)
from adapcc_tpu.adapt.recalibrate import (
    calibration_of,
    corrected_model,
    drift_correction,
)

__all__ = [
    "ADAPT_MODE_ENV",
    "ADAPT_MODES",
    "AdaptationController",
    "AdaptationReport",
    "DEFAULT_DRIFT_FACTOR",
    "DEFAULT_DRIFT_WINDOW",
    "DRIFT_FACTOR_ENV",
    "DRIFT_WINDOW_ENV",
    "DriftDetector",
    "DriftReport",
    "DriftSignal",
    "adapt_mode",
    "calibration_of",
    "corrected_model",
    "drift_correction",
    "resolve_drift_factor",
    "resolve_drift_window",
]
