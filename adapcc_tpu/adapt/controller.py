"""The closed loop: drift detection → re-calibration → re-rank → hot swap.

``AdaptationController`` ties the passive halves together over one live
:class:`CollectiveEngine` (and optionally a :class:`DDPTrainer`):

1. **Detect** — a :class:`DriftDetector` consumes the measurements already
   flowing (no probe traffic on the hot path, ever).
2. **Re-calibrate** — fired windows invert into per-link-class α-β
   corrections, decay-merged into ``topology/calibration.json``
   (:mod:`adapcc_tpu.adapt.recalibrate`).
3. **Re-rank** — :meth:`Synthesizer.resynthesize` re-runs the sim-rank
   pass under the corrected costs, incumbent listed first.
4. **Swap** — under the hysteresis gate (challenger's predicted steady
   state must beat the incumbent's by ``hysteresis_margin``, drift backed
   by at least a full window of samples), the top-k candidates are
   AOT-compiled through the PR-7 :class:`StandbyPlanCache` and adoption is
   one ``advance_epoch`` — a dispatch-time cache-key switch (``cache_hit``
   pinned), with ``DDPTrainer.adopt_strategy`` swapping the training step
   the same way.

``ADAPCC_ADAPT=off|detect|swap`` gates the plane (env > explicit mode >
off; malformed → loud): ``detect`` runs steps 1–3 and *reports* the swap
it would make, ``swap`` executes it.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from adapcc_tpu.adapt.detector import DriftDetector, DriftReport
from adapcc_tpu.adapt.recalibrate import calibration_of, drift_correction

#: global adaptation-plane mode env: off (default) | detect | swap
ADAPT_MODE_ENV = "ADAPCC_ADAPT"

ADAPT_MODES = ("off", "detect", "swap")


def adapt_mode(explicit: Optional[str] = None) -> str:
    """The adaptation mode in force: ``ADAPCC_ADAPT`` env > the caller's
    explicit mode > "off".  A malformed value raises — a typo'd
    ``ADAPCC_ADAPT=swapp`` silently running un-adapted would invalidate
    the drill it was meant to drive (the ADAPCC_TUNER policy)."""
    env = os.environ.get(ADAPT_MODE_ENV)
    value = env if env is not None and env.strip() else explicit
    if value is None:
        return "off"
    mode = str(value).strip().lower()
    if mode not in ADAPT_MODES:
        raise ValueError(
            f"{ADAPT_MODE_ENV}={value!r}: expected one of "
            f"{'|'.join(ADAPT_MODES)}"
        )
    return mode


@dataclass
class AdaptationReport:
    """What one :meth:`AdaptationController.maybe_adapt` pass did — every
    stage's outcome, artifact-shaped."""

    mode: str
    #: "off" | "no-drift" | "uninvertible" | "incumbent-wins" |
    #: "hysteresis" | "would-swap" (detect mode) | "swapped" |
    #: "congestion-would-reroute" (detect mode) | "congestion-reroute" |
    #: "congestion-hysteresis" | "congestion-active" |
    #: "congestion-sustained" | "congestion-cleared" (docs/FABRIC.md)
    outcome: str
    #: the triage verdict behind a fired pass: "congestion" |
    #: "degradation" | None (no drift, or uninvertible evidence)
    triage: Optional[str] = None
    drift: Optional[DriftReport] = None
    recalibrated: bool = False
    calibration_source: Optional[str] = None
    ranked: List[dict] = field(default_factory=list)
    incumbent_fingerprint: Optional[str] = None
    incumbent_pred_s: Optional[float] = None
    winner_label: Optional[str] = None
    winner_fingerprint: Optional[str] = None
    winner_pred_s: Optional[float] = None
    swapped: bool = False
    epoch: Optional[int] = None
    #: drift localization (docs/HIERARCHY.md §5): "dcn" when the incumbent
    #: is a composed two-level plan and the correction named only the DCN
    #: class, so ONLY the leader level was re-solved (pod level kept warm)
    resolved_level: Optional[str] = None
    #: AOT warm walltime (off the swap's critical path)
    aot_warm_s: Optional[float] = None
    #: the swap stall itself: advance_epoch + trainer adoption walltime
    stall_s: Optional[float] = None
    trainer_adopt_hit: Optional[bool] = None

    @property
    def fired(self) -> bool:
        return self.drift is not None and self.drift.drifted

    def to_row(self) -> dict:
        return {
            "mode": self.mode,
            "outcome": self.outcome,
            "triage": self.triage,
            "fired": self.fired,
            "recalibrated": self.recalibrated,
            "calibration": self.calibration_source,
            "incumbent": self.incumbent_fingerprint,
            "incumbent_pred_us": (
                round(self.incumbent_pred_s * 1e6, 3)
                if self.incumbent_pred_s is not None else None
            ),
            "winner": self.winner_fingerprint,
            "winner_label": self.winner_label,
            "winner_pred_us": (
                round(self.winner_pred_s * 1e6, 3)
                if self.winner_pred_s is not None else None
            ),
            "swapped": self.swapped,
            "epoch": self.epoch,
            "resolved_level": self.resolved_level,
            "aot_warm_s": self.aot_warm_s,
            "stall_s": self.stall_s,
            "trainer_adopt_hit": self.trainer_adopt_hit,
        }


class AdaptationController:
    """One engine's closed adaptation loop (module doc).

    Pure host work until a swap: detection, re-calibration, and re-ranking
    never dispatch a collective; only the ``swap``-mode AOT warm compiles
    (off the critical path — the adoption itself is a cache-key switch).
    """

    def __init__(
        self,
        engine,
        synthesizer,
        detector: Optional[DriftDetector] = None,
        trainer: Optional[Any] = None,
        trainer_prewarm: Optional[Callable[[Any], Any]] = None,
        mode: Optional[str] = None,
        calibration_path: Optional[str] = None,
        cost_model=None,
        db=None,
        fingerprint: Optional[str] = None,
        nbytes: int = 16 << 20,
        parallel_degree: int = 1,
        top_k: int = 2,
        hysteresis_margin: float = 0.1,
        min_samples: Optional[int] = None,
        warm_shape: Tuple[int, ...] = (1024,),
        warm_dtype=np.float32,
        decay: float = 0.5,
        congestion_profile=None,
        sim_engine: Optional[str] = None,
    ) -> None:
        adapt_mode(mode)  # validate BOTH the env and the explicit mode now
        if top_k < 1:
            raise ValueError(f"top_k must be >= 1, got {top_k}")
        if hysteresis_margin < 0:
            raise ValueError(
                f"hysteresis_margin must be >= 0, got {hysteresis_margin}"
            )
        self.engine = engine
        self.synthesizer = synthesizer
        self.trainer = trainer
        self.trainer_prewarm = trainer_prewarm
        self.explicit_mode = mode
        self.calibration_path = calibration_path
        self.db = db
        self.nbytes = int(nbytes)
        self.parallel_degree = max(1, int(parallel_degree))
        self.top_k = int(top_k)
        self.hysteresis_margin = float(hysteresis_margin)
        self.warm_shape = tuple(warm_shape)
        self.warm_dtype = warm_dtype
        self.decay = float(decay)
        #: replay engine for re-rank pricing (None → arg/env/auto funnel).
        #: Every correction re-prices the SAME candidate structures, so the
        #: vectorized path's fingerprint-keyed lowering cache turns the
        #: adapt loop's hottest cost — re-lowering per tick — into a
        #: per-link-class column re-price (docs/SIMULATION.md §7)
        self.sim_engine = sim_engine
        world = engine.world_size
        ips = dict(engine.strategy.trees[0].ips or {})
        if fingerprint is None:
            from adapcc_tpu.tuner.db import topology_fingerprint

            fingerprint = topology_fingerprint(world, ips or None)
        self.fingerprint = fingerprint
        if cost_model is None:
            from adapcc_tpu.sim.calibrate import (
                DEFAULT_CALIBRATION_PATH,
                load_or_default,
            )

            cost_model = load_or_default(
                calibration_path or DEFAULT_CALIBRATION_PATH,
                world=world,
                fingerprint=fingerprint,
            )
        if cost_model.ips is None and ips:
            cost_model = cost_model.with_ips(ips)
        self._model = cost_model
        self.detector = (
            detector
            if detector is not None
            else DriftDetector(world, fingerprint, cost_model=cost_model)
        )
        self.min_samples = (
            int(min_samples) if min_samples is not None else self.detector.window
        )
        # PR-7's standby machinery carries the AOT warm + epoch swap
        from adapcc_tpu.elastic.standby import StandbyPlanCache

        self.cache = StandbyPlanCache(
            engine, nbytes=float(self.nbytes), cost_model=cost_model
        )
        self.swaps = 0
        self.reports: List[AdaptationReport] = []
        #: the congestion-reroute state: set exactly while a transient
        #: re-route is live, carrying the pre-congestion incumbent so the
        #: clear restores it (reversibility is the acceptance property)
        self._congestion: Optional[Tuple[Any, Any]] = None  # (strategy, verdict)
        #: deterministic congestion-injection funnel (docs/FABRIC.md §4)
        self._profile = None
        #: per-(factors, model) pricing-policy cache for the tick funnel
        self._tick_policies: Dict[Any, Any] = {}
        if congestion_profile is not None:
            self.attach_congestion_profile(congestion_profile)
        # two payload decades for the priced probe cells: the α-β triage
        # needs >= 2 distinct sizes to separate bandwidth contention from
        # degradation (adapcc_tpu/adapt/triage.py module doc).  A payload
        # whose bucket is already at the 4 KiB floor would collapse both
        # probes into ONE cell — single-size evidence is never separable,
        # so every congestion window would be mis-triaged as degradation;
        # stretch the top probe to the 16 MiB decade instead (β-dominated
        # on every calibrated fabric here — a 1 MiB probe can sit under
        # the drift threshold on α-heavy classes; the probe cells price
        # the fabric, they need not equal the job payload).
        from adapcc_tpu.tuner.db import size_bucket

        top = size_bucket(self.nbytes)
        lo = max(4096, top >> 8)
        self._probe_sizes: Tuple[int, ...] = (lo, top if top > lo else lo << 12)

    # -- mode ------------------------------------------------------------------

    @property
    def mode(self) -> str:
        return adapt_mode(self.explicit_mode)

    # -- feeds (delegation) ----------------------------------------------------

    def observe(
        self,
        key,
        seconds: float,
        ts: Optional[float] = None,
        nbytes: Optional[int] = None,
    ) -> None:
        self.detector.observe(key, seconds, ts=ts, nbytes=nbytes)

    def observe_step(self, seconds: float, nbytes: int) -> None:
        self.detector.observe_step(seconds, nbytes)

    def ingest_trace(self, trace) -> Tuple[int, int]:
        return self.detector.ingest_trace(trace)

    def refresh(self) -> None:
        """Re-sync the detector from the attached tuning database (the
        ``tuning.jsonl`` / DispatchTimer history feed), when one exists."""
        if self.db is not None:
            self.detector.ingest_db(self.db)

    def check(self) -> DriftReport:
        self.refresh()
        return self.detector.check()

    # -- congestion injection funnel (docs/FABRIC.md §4) -----------------------

    @property
    def rerouted(self) -> bool:
        """True exactly while a transient congestion re-route is live."""
        return self._congestion is not None

    def attach_congestion_profile(self, profile) -> None:
        """Arm the deterministic congestion-injection funnel
        (``ADAPCC_CONGESTION_PROFILE``): :meth:`tick` will feed the drift
        detector contention-scaled priced samples per step — the
        observation-funnel twin of the coordinator's fault-plan
        injection, so the triage drill fires deterministically instead of
        waiting for a real neighbor."""
        if profile.world != self.engine.world_size:
            raise ValueError(
                f"congestion profile world {profile.world} != engine world "
                f"{self.engine.world_size}"
            )
        self._profile = profile

    def _tick_policy(self, factors):
        """The pricing policy for one step's contention factors, cached:
        tick() runs on the training hot path (once per step), and the
        policy only changes when the window factors or the live model do
        — never rebuild it per probe per step."""
        from adapcc_tpu.tuner.db import TuningDatabase
        from adapcc_tpu.tuner.policy import TuningPolicy

        fkey = tuple(sorted(factors.items()))
        cached = self._tick_policies.get(fkey)
        if cached is not None and cached[0] is self._model:
            return cached[1]
        model = self._model.contended(factors) if factors else self._model
        policy = TuningPolicy(
            TuningDatabase(persist=False),
            self.engine.world_size,
            self.detector.topology,
            cost_model=model,
        )
        self._tick_policies[fkey] = (self._model, policy)
        return policy

    def _priced(self, policy, key, nbytes: int) -> Optional[float]:
        try:
            pred = policy.prior_time(key, int(nbytes))
        except (KeyError, ValueError):
            return None
        return pred if pred > 0 else None

    def tick(self, step: int) -> None:
        """Feed one step of the attached congestion profile: each probe
        cell (two payload decades, :meth:`DriftDetector.probe_key`)
        observes the calibration price under that step's CONTENDED model
        — the class's β scaled by the window factor, α intact — so a
        window fires the detector with the congestion signature and a
        healthy step feeds reversal evidence.  No-op without a profile;
        deterministic (no RNG, no wall clock)."""
        if self._profile is None:
            return
        factors = self._profile.factors_at(int(step))
        policy = self._tick_policy(factors)
        for nbytes in self._probe_sizes:
            key = self.detector.probe_key(nbytes)
            pred = self._priced(policy, key, nbytes)
            if pred is not None:
                self.detector.observe(key, pred, nbytes=nbytes)

    # -- the loop --------------------------------------------------------------

    def _base_calibration(self):
        """The merge base: the persisted artifact when it exists AND was
        fitted on this fabric, else the live model wrapped as a calibration
        (first re-calibration seeds — or re-seeds — the artifact).  An
        artifact stamped with another fabric's fingerprint is never merged
        into (``merge_calibration`` would refuse anyway): corrections from
        this pod must not launder another pod's fit under our stamp."""
        from adapcc_tpu.sim.calibrate import Calibration

        if self.calibration_path and os.path.exists(self.calibration_path):
            try:
                base = Calibration.load(self.calibration_path)
            except (OSError, ValueError, KeyError, TypeError):
                base = None  # unusable artifact: fall through
            if base is not None and (
                base.world == self.engine.world_size
                and (
                    base.fingerprint is None
                    or base.fingerprint == self.fingerprint
                )
            ):
                return base
        return calibration_of(
            self._model,
            fingerprint=self.fingerprint,
            samples=0,
        )

    def _done(self, report: AdaptationReport) -> AdaptationReport:
        self.reports.append(report)
        return report

    def _adapt_leader_level(
        self, report: AdaptationReport, plan, incumbent, drift, mode: str
    ) -> AdaptationReport:
        """The localized half of the loop: re-solve ONLY the DCN leader
        level under the corrected model, hysteresis-gate, and hot-swap
        through the standby cache.  The pod level is never re-solved —
        ``resolve_leader_level`` carries the pod solve over by identity —
        and the warmed composed program makes the first post-swap dispatch
        a ``cache_hit`` (the same no-recompile property the elastic
        failover pins)."""
        from adapcc_tpu.sim.cost_model import DCN, ICI, two_level_allreduce_time
        from adapcc_tpu.strategy.hierarchy import resolve_leader_level

        model = self._model
        new = resolve_leader_level(plan, model, nbytes=self.nbytes)
        ici, dcn = model.classes[ICI], model.classes[DCN]
        inc_s = two_level_allreduce_time(
            plan.sketch.num_pods, plan.sketch.pod_size, self.nbytes,
            ici, dcn, pod_algo=plan.pod_algo, leader_algo=plan.leader_algo,
        )
        report.resolved_level = "dcn"
        report.incumbent_pred_s = inc_s
        report.winner_label = f"two-level[{new.leader_algo}]"
        report.winner_pred_s = new.predicted_s
        report.ranked = [
            {"label": report.winner_label,
             "pred_us": round(new.predicted_s * 1e6, 3)},
            {"label": "incumbent", "pred_us": round(inc_s * 1e6, 3)},
        ]
        if new.strategy.fingerprint() == incumbent.fingerprint():
            report.outcome = "incumbent-wins"
            report.winner_fingerprint = incumbent.fingerprint()
            return self._done(report)
        report.winner_fingerprint = new.strategy.fingerprint()
        evidence = max((s.count for s in drift.fired), default=0)
        if (
            new.predicted_s >= inc_s * (1.0 - self.hysteresis_margin)
            or evidence < self.min_samples
        ):
            report.outcome = "hysteresis"
            return self._done(report)
        if mode == "detect":
            report.outcome = "would-swap"
            return self._done(report)
        t0 = time.perf_counter()
        self.cache.warm_strategy(
            new.strategy,
            self.warm_shape,
            self.warm_dtype,
            label=report.winner_label,
            predicted_s=new.predicted_s,
        )
        if self.trainer_prewarm is not None:
            self.trainer_prewarm(new.strategy)
        report.aot_warm_s = time.perf_counter() - t0
        t1 = time.perf_counter()
        report.epoch = self.cache.adopt(new.strategy)
        if self.trainer is not None:
            report.trainer_adopt_hit = self.trainer.adopt_strategy(
                new.strategy
            )
        report.stall_s = time.perf_counter() - t1
        report.swapped = True
        report.outcome = "swapped"
        self.swaps += 1
        self.detector.reset(watermark=time.time())
        return self._done(report)

    def _swap_stages(self, report: AdaptationReport, winner_strategy,
                     label: str, predicted_s: float, warm_extra=()) -> None:
        """The shared swap tail: AOT warm (winner + any extra candidates)
        → trainer prewarm → one ``advance_epoch`` adoption → trainer
        adoption, with the warm/stall walltimes stamped on the report."""
        t0 = time.perf_counter()
        self.cache.warm_strategy(
            winner_strategy,
            self.warm_shape,
            self.warm_dtype,
            label=label,
            predicted_s=predicted_s,
        )
        for cand in warm_extra:
            self.cache.warm_strategy(
                cand.strategy,
                self.warm_shape,
                self.warm_dtype,
                label=cand.label,
                predicted_s=cand.seconds,
            )
        if self.trainer_prewarm is not None:
            self.trainer_prewarm(winner_strategy)
        report.aot_warm_s = time.perf_counter() - t0
        t1 = time.perf_counter()
        report.epoch = self.cache.adopt(winner_strategy)
        if self.trainer is not None:
            report.trainer_adopt_hit = self.trainer.adopt_strategy(
                winner_strategy
            )
        report.stall_s = time.perf_counter() - t1
        report.swapped = True
        self.swaps += 1
        self.detector.reset(watermark=time.time())

    def _congestion_pass(self, mode: str) -> AdaptationReport:
        """One pass while a transient re-route is live: the feeds keep
        monitoring the fabric against the UNCHANGED calibration, and the
        incumbent is restored the moment a full window reads healthy —
        the reversibility half of the triage (docs/FABRIC.md §3).  The
        full window IS the restore hysteresis: one healthy dispatch never
        flaps the plan back."""
        incumbent, verdict = self._congestion
        drift = self.check()
        report = AdaptationReport(
            mode=mode,
            outcome="congestion-active",
            triage="congestion",
            drift=drift,
            incumbent_fingerprint=self.engine.strategy.fingerprint(),
            winner_fingerprint=incumbent.fingerprint(),
        )
        if not drift.signals:
            return self._done(report)  # no full window yet: keep riding
        if drift.drifted:
            report.outcome = "congestion-sustained"
            return self._done(report)
        # cleared: restore the pre-congestion incumbent — its compiled
        # programs never left the engine cache, so the restore's first
        # dispatch replays warm (the same no-recompile property the
        # grow-back drill pins on StandbyPlanCache.restore_full)
        t1 = time.perf_counter()
        report.epoch = self.cache.adopt(incumbent)
        if self.trainer is not None:
            report.trainer_adopt_hit = self.trainer.adopt_strategy(incumbent)
        report.stall_s = time.perf_counter() - t1
        report.swapped = True
        report.outcome = "congestion-cleared"
        report.winner_label = "incumbent-restored"
        self.swaps += 1
        self._congestion = None
        self.detector.reset(watermark=time.time())
        return self._done(report)

    def _reroute_congestion(
        self, report: AdaptationReport, verdict, drift, mode: str, incumbent
    ) -> AdaptationReport:
        """The congestion half of the triage: re-route off the contended
        class under a TRANSIENT contended model — ``topology/
        calibration.json`` stays byte-unchanged, the detector keeps its
        healthy reference (a congested fabric SHOULD keep reading as
        contended), and the incumbent is remembered for the restore.  A
        composed two-level incumbent with DCN-class congestion re-solves
        only the leader level (PR 11's ``resolve_leader_level`` seam);
        everything else re-ranks the synthesizer's candidate pool under
        the contended costs, so trees that avoid the hot class win."""
        from adapcc_tpu.adapt.triage import contended_view
        from adapcc_tpu.sim.cost_model import DCN, ICI, two_level_allreduce_time
        from adapcc_tpu.strategy.hierarchy import plan_of, resolve_leader_level

        contended = contended_view(self._model, verdict)
        evidence = max((s.count for s in drift.fired), default=0)
        plan = plan_of(incumbent)
        sketch = None
        if plan is None and verdict.link_class == DCN:
            from adapcc_tpu.strategy.hierarchy import resolve_sketch

            try:
                sketch = resolve_sketch(
                    self.engine.world_size, self.synthesizer.ip_table
                )
            except ValueError:
                sketch = None  # ragged/flat layout: no hierarchy to escape to
        if plan is not None and verdict.link_class == DCN:
            # leader-level localization: the pod level never re-solves
            new = resolve_leader_level(plan, contended, nbytes=self.nbytes)
            ici, dcn = contended.classes[ICI], contended.classes[DCN]
            inc_s = two_level_allreduce_time(
                plan.sketch.num_pods, plan.sketch.pod_size, self.nbytes,
                ici, dcn, pod_algo=plan.pod_algo,
                leader_algo=plan.leader_algo,
            )
            report.resolved_level = "dcn"
            winner_strategy = new.strategy
            winner_label = f"two-level[{new.leader_algo}]+congestion"
            winner_s = new.predicted_s
            report.ranked = [
                {"label": winner_label, "pred_us": round(winner_s * 1e6, 3)},
                {"label": "incumbent", "pred_us": round(inc_s * 1e6, 3)},
            ]
            warm_extra = ()
        elif sketch is not None:
            # a FLAT incumbent under DCN congestion: the principled escape
            # off the contended class is the two-level hierarchy — the
            # composed plan ships 1/pod_size of the payload over DCN
            # (docs/HIERARCHY.md), which no flat re-shape can match.  Both
            # arms price in the same analytic family: the solver's own
            # predicted_s vs its flat DCN-paced comparator, both under the
            # contended coefficients.
            from adapcc_tpu.strategy.hierarchy import synthesize_two_level

            tl = synthesize_two_level(
                sketch, contended, nbytes=self.nbytes,
                num_trans=self.parallel_degree,
            )
            inc_s = tl.flat_pred_s
            winner_strategy = tl.strategy
            winner_label = (
                f"two-level[{tl.pod_algo}/{tl.leader_algo}]+congestion"
            )
            winner_s = tl.predicted_s
            report.ranked = [
                {"label": winner_label, "pred_us": round(winner_s * 1e6, 3)},
                {"label": "incumbent", "pred_us": round(inc_s * 1e6, 3)},
            ]
            warm_extra = ()
        else:
            ranked = self.synthesizer.resynthesize(
                contended,
                self.nbytes,
                parallel_degree=self.parallel_degree,
                incumbent=incumbent,
                provenance="congestion-reroute",
                engine=self.sim_engine,
            )
            report.ranked = [
                {"label": r.label, "pred_us": round(r.seconds * 1e6, 3)}
                for r in ranked
            ]
            winner = ranked[0]
            inc_s = next(
                (r.seconds for r in ranked if r.label == "incumbent"), None
            )
            winner_strategy = winner.strategy
            winner_label = winner.label
            winner_s = winner.seconds
            warm_extra = [
                r for r in ranked[1: self.top_k]
                if r.strategy is not None
                and r.strategy is not incumbent
                and r.strategy is not winner_strategy
            ]
        report.incumbent_pred_s = inc_s
        report.winner_label = winner_label
        report.winner_pred_s = winner_s
        if (
            winner_strategy is None
            or winner_strategy.fingerprint() == incumbent.fingerprint()
        ):
            report.outcome = "incumbent-wins"
            report.winner_fingerprint = incumbent.fingerprint()
            return self._done(report)
        report.winner_fingerprint = winner_strategy.fingerprint()
        if (
            inc_s is None
            or winner_s >= inc_s * (1.0 - self.hysteresis_margin)
            or evidence < self.min_samples
        ):
            report.outcome = "congestion-hysteresis"
            return self._done(report)
        if mode == "detect":
            report.outcome = "congestion-would-reroute"
            return self._done(report)
        self._swap_stages(
            report, winner_strategy, winner_label, winner_s, warm_extra
        )
        report.outcome = "congestion-reroute"
        self._congestion = (incumbent, verdict)
        return self._done(report)

    def maybe_adapt(self) -> AdaptationReport:
        """Run one pass of the loop (module doc).  Deterministic given the
        fed samples; returns a stage-by-stage report either way."""
        mode = self.mode
        if mode == "off":
            return self._done(AdaptationReport(mode=mode, outcome="off"))
        if self._congestion is not None:
            return self._congestion_pass(mode)
        drift = self.check()
        incumbent = self.engine.strategy
        report = AdaptationReport(
            mode=mode,
            outcome="no-drift",
            drift=drift,
            incumbent_fingerprint=incumbent.fingerprint(),
        )
        if not drift.drifted:
            return self._done(report)
        # -- triage (docs/FABRIC.md §2): congestion re-routes, degradation
        # re-calibrates — a transient neighbor must never corrupt the
        # persistent α-β artifact
        from adapcc_tpu.adapt.triage import classify_drift

        verdict = classify_drift(drift, self._model)
        if verdict is not None:
            report.triage = verdict.kind
            if verdict.kind == "congestion":
                return self._reroute_congestion(
                    report, verdict, drift, mode, incumbent
                )
        # -- re-calibrate ------------------------------------------------------
        from adapcc_tpu.sim.calibrate import merge_calibration

        correction = drift_correction(
            drift, self._model, fingerprint=self.fingerprint
        )
        if correction is None:
            # drift without link algebra (baseline-referenced cells only —
            # e.g. a ddp_step compute slowdown): nothing to re-calibrate,
            # and re-ranking under the UNCHANGED model would let a compute
            # regression hot-swap the comm strategy on evidence that says
            # nothing about links.  Report it; the operator (or a priced
            # feed) decides.
            report.outcome = "uninvertible"
            return self._done(report)
        merged = merge_calibration(
            self._base_calibration(), correction, decay=self.decay
        )
        if self.calibration_path:
            merged.save(self.calibration_path)
        model = merged.cost_model()
        ips = dict(incumbent.trees[0].ips or {})
        if model.ips is None and ips:
            model = model.with_ips(ips)
        self._model = model
        # the corrected model becomes the detector's reference: windows
        # consistent with it stop firing (the loop converges)
        self.detector.set_cost_model(model)
        self.cache.cost_model = model
        report.recalibrated = True
        report.calibration_source = merged.source
        # -- drift localization (docs/HIERARCHY.md §5) -------------------------
        # a DCN-class correction on a composed two-level incumbent says
        # nothing about the ICI level: re-solve ONLY the leader schedule
        # and keep every pod-level decision (and its compiled programs)
        # warm, instead of re-ranking the whole candidate pool
        from adapcc_tpu.sim.cost_model import DCN
        from adapcc_tpu.strategy.hierarchy import plan_of

        plan = plan_of(incumbent)
        if plan is not None and set(correction.classes) == {DCN}:
            return self._adapt_leader_level(report, plan, incumbent, drift, mode)
        # -- re-rank -----------------------------------------------------------
        ranked = self.synthesizer.resynthesize(
            self._model,
            self.nbytes,
            parallel_degree=self.parallel_degree,
            incumbent=incumbent,
            engine=self.sim_engine,
        )
        report.ranked = [
            {"label": r.label, "pred_us": round(r.seconds * 1e6, 3)}
            for r in ranked
        ]
        winner = ranked[0]
        inc_s = next(
            (r.seconds for r in ranked if r.label == "incumbent"), None
        )
        report.incumbent_pred_s = inc_s
        report.winner_label = winner.label
        report.winner_pred_s = winner.seconds
        if (
            winner.strategy is None
            or winner.strategy.fingerprint() == incumbent.fingerprint()
        ):
            report.outcome = "incumbent-wins"
            report.winner_fingerprint = incumbent.fingerprint()
            return self._done(report)
        report.winner_fingerprint = winner.strategy.fingerprint()
        # -- hysteresis gate ---------------------------------------------------
        # the challenger's predicted steady state must beat the incumbent's
        # by the margin, and the drift evidence must be a full window deep —
        # one lucky (or unlucky) dispatch must not flap the executing plan
        evidence = max((s.count for s in drift.fired), default=0)
        if (
            inc_s is None
            or winner.seconds >= inc_s * (1.0 - self.hysteresis_margin)
            or evidence < self.min_samples
        ):
            report.outcome = "hysteresis"
            return self._done(report)
        if mode == "detect":
            report.outcome = "would-swap"
            return self._done(report)
        # -- swap --------------------------------------------------------------
        # _swap_stages resets the detector with a wall-clock watermark:
        # stale windows measured the OLD plan and would immediately
        # re-fire against the new one, and the attached tuning database
        # still HOLDS the old plan's samples — the next refresh() would
        # otherwise re-ingest exactly what was cleared.
        challengers = [
            r for r in ranked
            if r.strategy is not None
            and r.strategy is not incumbent
            and r.strategy is not winner.strategy
        ]
        self._swap_stages(
            report, winner.strategy, winner.label, winner.seconds,
            warm_extra=challengers[: max(0, self.top_k - 1)],
        )
        report.outcome = "swapped"
        return self._done(report)
