"""Passive drift detection from measurements that already flow.

The paper re-profiles every ``profile_freq`` steps with *active* probe
rounds (PAPER.md:61); this module gets the same signal for free.  Three
feeds already carry per-dispatch walltimes:

- the engine's :class:`~adapcc_tpu.tuner.measure.DispatchTimer` samples
  (live, warmup-discarded),
- dispatch-trace events whose extras carry ``duration_s``
  (``ADAPCC_TUNER=record|choose`` runs),
- the persisted ``tuning.jsonl`` history (:class:`TuningDatabase`).

The detector keeps one bounded rolling window per plan cell — the tuner's
``(primitive, size bucket, world, topology, path, chunk, codec)`` key — and
compares each full window's **median** against the
``topology/calibration.json``-priced prediction for that cell (the SAME
pricing the tuner's prior uses, via :class:`TuningPolicy.prior_time`, so
the detector and every sweep judge a cell identically).  Each sample is
normalized at feed time by the calibration price at its TRUE payload when
the feed knows it (live observes carry ``nbytes=``), or at the bucket
otherwise (database history only keeps the bucket — a payload just above
a power of two then reads up to the bucket width *conservative*, never
trigger-happy).  A window whose median ratio exceeds
``ADAPCC_DRIFT_FACTOR`` fires; anything less — healthy noise, a single
straggler-polluted dispatch — must not (the false-positive guard is a
pinned test).

Cells the calibration cannot price (``ddp_step`` walltimes carry the
step's *compute*, which no link model prices) fall back to a frozen
self-baseline: the first full window's median becomes the reference, and
later windows fire on the same factor against it — drift is still a
sustained departure from what this fabric measured when healthy.

Zero probe traffic, zero RNG, zero wall-clock reads in the decision: the
whole trajectory is a deterministic function of the fed samples.
"""

from __future__ import annotations

import os
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional, Tuple

from adapcc_tpu.tuner.db import TuningDatabase, TuningKey

#: measured-median ÷ prediction ratio at which a full window fires
DRIFT_FACTOR_ENV = "ADAPCC_DRIFT_FACTOR"
DEFAULT_DRIFT_FACTOR = 2.0

#: samples per rolling window (per plan cell) — detection needs a full one
DRIFT_WINDOW_ENV = "ADAPCC_DRIFT_WINDOW"
DEFAULT_DRIFT_WINDOW = 8

#: primitives whose cells the calibration prices (the tuner-prior terms);
#: everything else (ddp_step, zero1_ring, …) detects against a frozen
#: self-baseline instead
PRICED_PRIMITIVES = (
    "allreduce", "reduce_scatter", "all_gather", "all_to_all",
)


def resolve_drift_factor(explicit: Optional[float] = None) -> float:
    """The drift threshold in force: ``ADAPCC_DRIFT_FACTOR`` env > the
    explicit argument > the default.  Must be > 1 (a factor ≤ 1 would fire
    on every healthy window); malformed → loud error, never a silent
    default (the ADAPCC_RING_CHUNK_BYTES policy)."""
    env = os.environ.get(DRIFT_FACTOR_ENV)
    value = env if env is not None and env.strip() else explicit
    if value is None:
        return DEFAULT_DRIFT_FACTOR
    try:
        factor = float(value)
    except (TypeError, ValueError):
        raise ValueError(
            f"{DRIFT_FACTOR_ENV}/factor={value!r}: expected a number > 1"
        ) from None
    if factor <= 1.0:
        raise ValueError(
            f"{DRIFT_FACTOR_ENV}/factor={factor} must be > 1: at <= 1 every "
            "healthy window would read as drift"
        )
    return factor


def resolve_drift_window(explicit: Optional[int] = None) -> int:
    """The window length in force: ``ADAPCC_DRIFT_WINDOW`` env > the
    explicit argument > the default.  Must be >= 2 (a one-sample median is
    exactly the single noisy dispatch the window exists to absorb);
    malformed → loud error."""
    env = os.environ.get(DRIFT_WINDOW_ENV)
    value = env if env is not None and env.strip() else explicit
    if value is None:
        return DEFAULT_DRIFT_WINDOW
    try:
        window = int(str(value).strip())
    except (TypeError, ValueError):
        raise ValueError(
            f"{DRIFT_WINDOW_ENV}/window={value!r}: expected an integer >= 2"
        ) from None
    if window < 2:
        raise ValueError(
            f"{DRIFT_WINDOW_ENV}/window={window} must be >= 2: a one-sample "
            "median is the single noisy dispatch the window exists to absorb"
        )
    return window


def _median(xs: List[float]) -> float:
    ys = sorted(xs)
    n = len(ys)
    mid = n // 2
    if n % 2:
        return ys[mid]
    return 0.5 * (ys[mid - 1] + ys[mid])


@dataclass(frozen=True)
class DriftSignal:
    """One cell's verdict at check time."""

    key: TuningKey
    median_s: float
    reference_s: float
    #: "calibration" = priced prediction; "baseline" = frozen first window
    reference: str
    ratio: float
    count: int
    fired: bool

    def to_row(self) -> dict:
        return {
            **self.key.to_dict(),
            "median_us": round(self.median_s * 1e6, 3),
            "reference_us": round(self.reference_s * 1e6, 3),
            "reference": self.reference,
            "ratio": round(self.ratio, 6),
            "count": self.count,
            "fired": self.fired,
        }


@dataclass
class DriftReport:
    """Everything one :meth:`DriftDetector.check` saw: every full-window
    cell's ratio, fired or not — a detection artifact, not just a bit."""

    factor: float
    window: int
    signals: List[DriftSignal] = field(default_factory=list)

    @property
    def fired(self) -> List[DriftSignal]:
        return [s for s in self.signals if s.fired]

    @property
    def drifted(self) -> bool:
        return any(s.fired for s in self.signals)

    def to_rows(self) -> List[dict]:
        return [s.to_row() for s in self.signals]


class DriftDetector:
    """Rolling-window drift detector over tuner plan cells (module doc).

    ``cost_model`` anchors the predictions (default: the persisted
    calibration artifact via ``load_or_default``); after a re-calibration
    the controller swaps the corrected model in with
    :meth:`set_cost_model`, so a model that has caught up with reality
    stops firing — the closed loop converges instead of oscillating.
    """

    def __init__(
        self,
        world: int,
        topology: str = "adapt",
        cost_model=None,
        factor: Optional[float] = None,
        window: Optional[int] = None,
    ) -> None:
        self.world = int(world)
        self.topology = topology
        self.factor = resolve_drift_factor(factor)
        self.window = resolve_drift_window(window)
        self._cost_model = cost_model
        self._policy = None  # lazily built pricing view (TuningPolicy)
        #: priced cells hold seconds ÷ reference RATIOS, unpriced cells raw
        #: seconds (the baseline path); one kind per key, decided by
        #: whether the calibration prices it
        self._windows: Dict[TuningKey, Deque[float]] = {}
        self._baseline: Dict[TuningKey, float] = {}
        #: per-key bucket-price cache (None = unpriced); dropped on
        #: set_cost_model so a re-calibration re-anchors every reference
        self._ref: Dict[TuningKey, Optional[float]] = {}
        #: timestamp floor for timestamped feeds (db/trace history): set by
        #: :meth:`reset` after a strategy swap so evidence recorded under
        #: the retired plan can never re-enter and re-fire against its
        #: successor — without it, the next ingest would simply replace the
        #: just-cleared windows with the same stale samples
        self._watermark = float("-inf")
        #: feed accounting (diagnosable ingestion, the replay_trace rule)
        self.ingested = 0
        self.skipped = 0

    # -- pricing ---------------------------------------------------------------

    def _pricing(self):
        """One pricing definition with the tuner: a throwaway in-memory
        :class:`TuningPolicy` whose ``prior_time`` routes every cell to the
        same cost-model term the prior and the benches use."""
        if self._policy is None:
            from adapcc_tpu.tuner.policy import TuningPolicy

            self._policy = TuningPolicy(
                TuningDatabase(persist=False),
                self.world,
                self.topology,
                cost_model=self._cost_model,
            )
        return self._policy

    def set_cost_model(self, cost_model) -> None:
        """Re-anchor predictions (post-re-calibration): the corrected model
        becomes the reference.  Priced windows are DROPPED — their stored
        ratios were normalized under the retired reference, and reading
        them against the new one would reconstruct seconds that were never
        measured (and re-fire forever on evidence the correction already
        absorbed).  Fresh samples normalize under the corrected price, so
        a model that has caught up with the fabric stops firing — the
        closed loop converges.  Baseline windows keep their (model-free)
        frozen reference."""
        priced = [k for k in self._windows if self.predicted_s(k) is not None]
        for k in priced:
            del self._windows[k]
        self._cost_model = cost_model
        self._policy = None
        self._ref.clear()

    def _price_at(self, key: TuningKey, nbytes: int) -> Optional[float]:
        if key.primitive not in PRICED_PRIMITIVES:
            return None
        try:
            pred = self._pricing().prior_time(key, int(nbytes))
        except (KeyError, ValueError):
            return None
        return pred if pred > 0 else None

    def predicted_s(self, key: TuningKey) -> Optional[float]:
        """Calibration-priced seconds for one cell at its bucket size, or
        None where no link model prices it (self-baseline cells).  Cached
        per key; dropped on :meth:`set_cost_model`."""
        if key in self._ref:
            return self._ref[key]
        pred = self._price_at(key, key.size_bucket)
        self._ref[key] = pred
        return pred

    # -- feeds -----------------------------------------------------------------

    def _freeze_baseline(self, key: TuningKey) -> None:
        win = self._windows.get(key)
        if (
            win is not None
            and len(win) >= self.window
            and key not in self._baseline
            and self.predicted_s(key) is None
        ):
            self._baseline[key] = _median(list(win))

    def observe(
        self,
        key: TuningKey,
        seconds: float,
        ts: Optional[float] = None,
        nbytes: Optional[int] = None,
    ) -> None:
        """Feed one measured dispatch (live DispatchTimer-style samples).

        ``nbytes`` is the dispatch's TRUE per-rank payload when the feed
        knows it: priced cells normalize each sample by the calibration
        price at that size (the bucket spans a 2× payload range, so
        bucket-priced references would read a just-above-a-power-of-two
        payload up to 2× too healthy).  ``ts`` (when known) is checked
        against the post-swap watermark — a timestamped sample from before
        the last swap is counted as skipped, never windowed;
        untimestamped samples are live by definition and always enter."""
        s = float(seconds)
        if s < 0:
            raise ValueError(f"negative duration {s}")
        if ts is not None and float(ts) < self._watermark:
            self.skipped += 1
            return
        ref = self.predicted_s(key)
        if ref is not None:
            per = ref
            if nbytes is not None and int(nbytes) != key.size_bucket:
                per = self._price_at(key, int(nbytes)) or ref
            value = s / per
        else:
            value = s
        win = self._windows.get(key)
        if win is None:
            win = self._windows[key] = deque(maxlen=self.window)
        win.append(value)
        self.ingested += 1
        self._freeze_baseline(key)

    def probe_key(self, nbytes: int, path: str = "xla") -> TuningKey:
        """The canonical PRICED cell for one payload size: the plain
        allreduce on the given data-plane path — the cell the calibration
        prices with the classic ring term.  One spelling shared by the
        congestion-profile injection funnel
        (:meth:`AdaptationController.tick`), the triage drills, and the
        fabric sweep, so an injected observation and a live dispatch can
        never land in different cells for the same payload."""
        from adapcc_tpu.tuner.db import size_bucket

        return TuningKey(
            primitive="allreduce",
            size_bucket=size_bucket(max(1, int(nbytes))),
            world=self.world,
            topology=self.topology,
            path=path,
            chunk_bytes=0,
            wire_dtype="off",
        )

    def observe_step(
        self, seconds: float, nbytes: int, label: str = "ddp_step"
    ) -> TuningKey:
        """Feed one training-step walltime (the DispatchTimer step-median
        feed): keyed as an unpriced ``ddp_step``-family cell, so detection
        runs against the frozen healthy baseline."""
        from adapcc_tpu.tuner.db import size_bucket

        key = TuningKey(
            primitive=label,
            size_bucket=size_bucket(max(1, int(nbytes))),
            world=self.world,
            topology=self.topology,
            path="step",
            chunk_bytes=0,
            wire_dtype="off",
        )
        self.observe(key, seconds)
        return key

    def ingest_db(self, db: TuningDatabase) -> Tuple[int, int]:
        """Re-sync windows from a tuning database (the ``tuning.jsonl``
        history feed): each matching key's window is REPLACED by its newest
        ``window`` samples, so repeated ingestion of the same database is
        idempotent.  Samples older than the post-swap watermark are
        excluded — the database keeps the retired plan's history, and
        replaying it into a freshly reset detector would re-fire on
        evidence the adopted strategy never produced.  Keys from other
        worlds are counted, never silently dropped.  Returns
        ``(ingested_keys, skipped_keys)``."""
        ingested = skipped = 0
        for key in db.keys():
            if key.world != self.world:
                skipped += 1
                self.skipped += len(db.timed_samples(key))
                continue
            timed = db.timed_samples(key)
            samples = [s for ts, s in timed if ts >= self._watermark]
            self.skipped += len(timed) - len(samples)  # pre-watermark
            samples = samples[-self.window:]
            if not samples:
                skipped += 1
                continue
            ref = self.predicted_s(key)
            win = self._windows[key] = deque(maxlen=self.window)
            for s in samples:
                # the db only keeps the bucket, not the true payload:
                # bucket-priced normalization (conservative — see observe)
                win.append(float(s) / ref if ref is not None else float(s))
            self.ingested += len(samples)
            self._freeze_baseline(key)
            ingested += 1
        return ingested, skipped

    def ingest_trace(self, trace) -> Tuple[int, int]:
        """Feed a recorded :class:`CollectiveTrace` (or TraceEvent
        iterable): events carrying ``duration_s`` land in their cells via
        the SAME key vocabulary as the tuner replay
        (:func:`adapcc_tpu.tuner.measure.replay_trace` — one spelling, so a
        trace and a live run can never disagree about which cell a dispatch
        belongs to).  Returns ``(ingested_events, skipped_events)``."""
        from adapcc_tpu.tuner.measure import replay_trace

        tmp = TuningDatabase(persist=False)
        ingested, skipped = replay_trace(trace, tmp, self.world, self.topology)
        # self.skipped is sample-granular and ingest_db already counts what
        # IT drops (watermark, empty keys); add only the events the replay
        # itself could not key — counting them twice would inflate the
        # diagnostic past the number of events fed
        self.skipped += skipped
        self.ingest_db(tmp)
        return ingested, skipped

    # -- decision --------------------------------------------------------------

    def check(self) -> DriftReport:
        """Evaluate every full window (side-effect-free beyond baseline
        freezing, which feeds already did): deterministic, analytic."""
        report = DriftReport(factor=self.factor, window=self.window)
        for key in sorted(self._windows):
            win = self._windows[key]
            if len(win) < self.window:
                continue
            med = _median(list(win))
            pred = self.predicted_s(key)
            if pred is not None:
                # priced cells window normalized RATIOS; report seconds at
                # the bucket reference so downstream algebra (the α-β
                # inversion) stays bucket-consistent
                reference, ref_s = "calibration", pred
                ratio, median_s = med, med * pred
            else:
                base = self._baseline.get(key)
                if base is None or base <= 0:
                    continue
                reference, ref_s = "baseline", base
                ratio, median_s = med / base, med
            report.signals.append(
                DriftSignal(
                    key=key,
                    median_s=median_s,
                    reference_s=ref_s,
                    reference=reference,
                    ratio=ratio,
                    count=len(win),
                    fired=ratio >= self.factor,
                )
            )
        return report

    def reset(self, watermark: Optional[float] = None) -> None:
        """Drop every window and baseline (post-swap: the new strategy's
        dispatches must build fresh evidence before the next adaptation).
        ``watermark`` additionally floors the timestamped feeds: history
        recorded before it (the retired plan's samples still sitting in
        the tuning database) can never re-enter the windows."""
        self._windows.clear()
        self._baseline.clear()
        if watermark is not None:
            self._watermark = float(watermark)

    def __repr__(self) -> str:
        return (
            f"DriftDetector(world={self.world}, factor={self.factor}, "
            f"window={self.window}, cells={len(self._windows)})"
        )
