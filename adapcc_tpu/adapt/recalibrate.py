"""α-β re-calibration from passively observed collective timings.

The drift detector says *that* measured medians departed from the priced
prediction; this module turns the same medians into *corrected* link
coefficients, through the existing calibration funnel:

1. **Invert** — each fired ring-structured cell contributes per-hop
   ``(bytes, seconds)`` points via the same round/byte algebra the battery
   calibration uses (``calibrate._RING_STRUCTURE``: an allreduce is
   ``2(w−1)`` serial hops of ``n/w`` bytes, …).  With two or more distinct
   payload sizes the points go through
   :func:`adapcc_tpu.sim.cost_model.fit_alpha_beta` — a real least-squares
   (α, β) fit; a single size cannot separate α from β, so the correction
   falls back to scaling the current coefficients by the observed ratio
   (both terms stretch — the degraded-link shape
   :meth:`LinkCoeffs.scaled` already models).
2. **Localize** — a lockstep collective is paced by its bottleneck ring
   hop, so the correction lands on that hop's link *class*
   (:func:`bottleneck_ring_link`): passive timings cannot name one wire,
   but they do name the class that paced them.
3. **Merge** — the correction becomes a :class:`Calibration` stamped with
   topology fingerprint + sample count + provenance, folded into the
   existing artifact with decay by
   :func:`adapcc_tpu.sim.calibrate.merge_calibration` — never
   last-writer-wins.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from adapcc_tpu.adapt.detector import DriftReport, DriftSignal
from adapcc_tpu.sim.calibrate import _RING_STRUCTURE, Calibration
from adapcc_tpu.sim.cost_model import (
    LinkCoeffs,
    LinkCostModel,
    bottleneck_ring_coeffs,
    bottleneck_ring_link,
    fit_alpha_beta,
)


def _hop_points(
    signals: List[DriftSignal], world: int
) -> Tuple[List[Tuple[float, float]], int]:
    """Fired ring-structured signals → per-hop (bytes, seconds) points +
    the total sample count behind them."""
    points: List[Tuple[float, float]] = []
    total = 0
    for sig in signals:
        structure = _RING_STRUCTURE.get(sig.key.primitive)
        if structure is None or sig.reference != "calibration":
            continue
        rounds_fn, byte_fn = structure
        rounds = float(rounds_fn(world))
        if rounds <= 0:
            continue
        per_hop_bytes = byte_fn(world) * float(sig.key.size_bucket) / rounds
        points.append((per_hop_bytes, sig.median_s / rounds))
        total += sig.count
    return points, total


def drift_correction(
    report: DriftReport,
    model: LinkCostModel,
    fingerprint: Optional[str] = None,
    source: str = "drift-recal",
) -> Optional[Calibration]:
    """One drift report → a correction :class:`Calibration` for the
    bottleneck link class (module doc), or None when no fired signal is
    invertible (baseline-referenced cells carry no link algebra).

    The returned artifact holds ONLY the corrected class — merging keeps
    every other class/link untouched, which is the point: a DCN
    degradation must not rewrite the ICI fit.  Per-link fits OF the
    corrected class ride along, each stretched by the same correction
    (``LinkCostModel.coeffs`` prefers per-link entries over class means,
    so a class-only correction under a per-link-fitted artifact — the
    normal profiler/battery output — would be silently masked and the
    loop could never converge); their relative structure survives.
    """
    world = model.world
    points, samples = _hop_points(report.fired, world)
    if not points:
        return None
    link = bottleneck_ring_link(model, world)
    cls = model.link_class_of(*link)
    current = bottleneck_ring_coeffs(model, world)
    distinct_sizes = {round(b, 3) for b, _ in points}
    if len(distinct_sizes) >= 2:
        corrected = fit_alpha_beta(points)
    else:
        # one payload size cannot separate α from β: stretch the current
        # coefficients by the observed per-hop ratio instead (exactly the
        # degraded-link shape the relay pricing models)
        nbytes, seconds = points[0]
        predicted = current.time(nbytes)
        ratio = seconds / predicted if predicted > 0 else 1.0
        corrected = current.scaled(max(1e-9, ratio))

    def _ratio(new: float, old: float) -> float:
        return new / old if old > 0 else 1.0

    ra = _ratio(corrected.alpha, current.alpha)
    rb = _ratio(corrected.beta, current.beta)
    links = {
        l: LinkCoeffs(c.alpha * ra, c.beta * rb)
        for l, c in model.links.items()
        if model.link_class_of(*l) == cls
    }
    return Calibration(
        world=world,
        classes={cls: corrected},
        links=links,
        ips=model.ips,
        source=source,
        fingerprint=fingerprint,
        samples=max(1, samples),
    )


def corrected_model(
    report: DriftReport,
    base: Calibration,
    decay: float = 0.5,
    fingerprint: Optional[str] = None,
    source: str = "drift-recal",
) -> Tuple[Optional[Calibration], LinkCostModel]:
    """Convenience funnel: invert ``report`` against ``base``'s model and
    decay-merge the correction in.  Returns ``(merged_or_None, model)`` —
    the model is the merged one when a correction existed, else ``base``'s
    unchanged model (callers re-rank on whatever comes back)."""
    from adapcc_tpu.sim.calibrate import merge_calibration

    base_model = base.cost_model()
    correction = drift_correction(
        report, base_model, fingerprint=fingerprint, source=source
    )
    if correction is None:
        return None, base_model
    merged = merge_calibration(base, correction, decay=decay)
    return merged, merged.cost_model()


def calibration_of(model: LinkCostModel, **stamps) -> Calibration:
    """Wrap a live cost model as a :class:`Calibration` (the merge base
    when no artifact exists yet): same classes/links/ips, stamped with
    whatever hygiene fields the caller knows (``fingerprint=``,
    ``samples=``, ``source=``)."""
    return Calibration(
        world=model.world,
        classes=dict(model.classes),
        links=dict(model.links),
        ips=dict(model.ips) if model.ips else None,
        source=stamps.pop("source", model.source),
        **stamps,
    )
