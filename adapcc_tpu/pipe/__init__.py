"""Pipeline-parallel training plane (docs/PIPELINE.md).

``partition`` splits GPT-2 across stages, ``schedule`` lays GPipe/1F1B
tick tables and re-emits them as verifiable ``compiler/`` programs,
``executor`` interprets the table with real backward through the traced
engine, and ``forward`` is the fused forward-only building block."""

from adapcc_tpu.pipe.forward import pipeline_apply
from adapcc_tpu.pipe.partition import (
    StagePartition,
    composed_loss,
    merge_params,
    partition_gpt2,
    split_params,
    stage_forward,
)
from adapcc_tpu.pipe.schedule import (
    DEFAULT_PIPE_SCHEDULE,
    PIPE_SCHEDULE_ENV,
    PIPE_SCHEDULES,
    PipelineSchedule,
    PipeTask,
    pipeline_program,
    pipeline_schedule,
    resolve_pipe_schedule,
)
from adapcc_tpu.pipe.executor import (
    PipelineExecutor,
    PipelineReport,
    sync_tied_embedding,
)

__all__ = [
    "DEFAULT_PIPE_SCHEDULE",
    "PIPE_SCHEDULE_ENV",
    "PIPE_SCHEDULES",
    "PipeTask",
    "PipelineExecutor",
    "PipelineReport",
    "PipelineSchedule",
    "StagePartition",
    "composed_loss",
    "merge_params",
    "partition_gpt2",
    "pipeline_apply",
    "pipeline_program",
    "pipeline_schedule",
    "resolve_pipe_schedule",
    "split_params",
    "stage_forward",
    "sync_tied_embedding",
]
