"""Forward-only pipeline building block: one compiled GPipe fill/drain.

This is the pipeline plane's *inference* primitive — a shape-static,
branch-free microbatch pipeline compiled into ONE XLA program, with
activations hopping stage→stage via ``lax.ppermute`` inside a single
``lax.scan``.  The training executor (:mod:`adapcc_tpu.pipe.executor`)
deliberately does NOT use it: training needs per-stage ``jax.vjp``
stashes, a 1F1B-bounded memory window, and per-hop trace events, all of
which live outside one fused scan.  What this block is for is cheap
forward sweeps (evaluation, pipelined inference over a block stack)
where one compiled program beats a host-driven tick loop.

Formerly ``adapcc_tpu.parallel.pipeline`` (still importable there via a
warn-once deprecation shim).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map


def _pipeline_shard(
    stage_params: Any,
    x: jnp.ndarray,
    *,
    stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    axis_name: str,
):
    """Per-shard pipeline body.

    ``stage_params``: this rank's stage slice (leading stage axis stripped to
    size 1 by shard_map; squeezed here).  ``x``: the full microbatched input
    ``[M, mb, ...]``, replicated across the stage axis.  Returns ``[M, mb, ...]``
    outputs, valid on every rank.  Output gather design: the last stage could
    broadcast each microbatch result back through the drain ticks of the same
    ppermute ring (zero extra collectives, but it couples the scan carry to
    the emit schedule and costs ``stages − 1`` extra ticks of latency);
    instead every non-last stage contributes zeros and ONE ``lax.psum`` over
    the stage axis at the end replicates the last stage's buffer — one extra
    collective, no extra ticks, and the scan body stays oblivious to
    draining.
    """
    params = jax.tree_util.tree_map(lambda a: a[0], stage_params)
    stages = lax.psum(1, axis_name)
    stage = lax.axis_index(axis_name)
    M = x.shape[0]
    ticks = M + stages - 1

    # send stage i -> i+1 (the last stage's send wraps to 0 and is ignored)
    fwd = [(i, (i + 1) % stages) for i in range(stages)]

    out0 = jnp.zeros(x.shape, jax.eval_shape(lambda p, b: stage_fn(p, b), params, x[0]).dtype)
    carry0 = jnp.zeros_like(x[0])

    def tick(carry, t):
        incoming, outputs = carry
        # stage 0 ingests microbatch t while filling; afterwards it computes
        # on zeros whose results are never collected
        feed_idx = jnp.clip(t, 0, M - 1)
        inp = jnp.where(stage == 0, x[feed_idx], incoming)
        out = stage_fn(params, inp)
        # the last stage owns microbatch t-(stages-1) at tick t
        emit_idx = jnp.clip(t - (stages - 1), 0, M - 1)
        is_emit = jnp.logical_and(stage == stages - 1, t >= stages - 1)
        outputs = lax.dynamic_update_index_in_dim(
            outputs,
            jnp.where(is_emit, out, lax.dynamic_index_in_dim(outputs, emit_idx, 0, False)),
            emit_idx,
            0,
        )
        incoming = lax.ppermute(out, axis_name, fwd)
        return (incoming, outputs), None

    (_, outputs), _ = lax.scan(tick, (carry0, out0), jnp.arange(ticks))

    # only the last stage holds real outputs; replicate them to every stage
    # so the caller sees a replicated result (one psum over the stage axis)
    outputs = jnp.where(stage == stages - 1, outputs, jnp.zeros_like(outputs))
    return lax.psum(outputs, axis_name)


def pipeline_apply(
    stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    stacked_params: Any,
    batch: jnp.ndarray,
    mesh: Mesh,
    axis_name: str = "stages",
    num_microbatches: int = 4,
) -> jnp.ndarray:
    """Run ``stage_fn`` as a forward pipeline over ``mesh[axis_name]``.

    ``stacked_params``: pytree whose leaves have a leading ``num_stages`` axis
    (stage s uses ``leaf[s]``).  ``batch [B, ...]`` with ``B`` divisible by
    ``num_microbatches``; microbatch size ``B // num_microbatches`` must keep
    ``stage_fn`` shape-preserving (same in/out shape), as in a transformer
    block stack.  Returns ``[B, ...]`` outputs, replicated.
    """
    B = batch.shape[0]
    if B % num_microbatches:
        raise ValueError(f"batch {B} not divisible by microbatches {num_microbatches}")
    x = batch.reshape(num_microbatches, B // num_microbatches, *batch.shape[1:])

    fn = shard_map(
        partial(_pipeline_shard, stage_fn=stage_fn, axis_name=axis_name),
        mesh=mesh,
        in_specs=(P(axis_name), P()),
        out_specs=P(),
        check_vma=False,
    )
    out = fn(stacked_params, x)
    return out.reshape(B, *out.shape[2:])
