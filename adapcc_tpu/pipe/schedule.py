"""Static pipeline schedules: GPipe and 1F1B tick tables + IR emission.

A pipeline schedule here is a **static structure**, not a runtime policy:
:func:`pipeline_schedule` lays every forward/backward microbatch task of
every stage onto a global tick grid (one task per stage per tick, rounds
aligned so a hop produced at tick ``t`` is consumed no earlier than tick
``t+1``), and everything downstream reads that one table —

- the executor (:mod:`adapcc_tpu.pipe.executor`) interprets it tick by
  tick, so what runs is exactly what was priced;
- :func:`pipeline_program` re-emits the per-tick stage hops as a
  ``collective="pipeline"`` :class:`~adapcc_tpu.compiler.ir.ScheduleProgram`
  so ``compiler/verify.py`` certifies delivery/matching/deadlock-freedom
  and ``sim/replay.simulate_program`` replays the same object;
- the measured properties (:attr:`PipelineSchedule.bubble_fraction`,
  :attr:`PipelineSchedule.stash_high_water`) are derived from the table,
  and the closed forms in ``sim/cost_model`` are pinned against them.

Both schedules run the same ``2·(m + s − 1)`` ticks (fill/drain bubble
``(s−1)/(m+s−1)``); they differ in *memory*: GPipe runs all forwards
before any backward, so every stage stashes ``m`` in-flight activations,
while 1F1B caps stage ``s`` at ``min(m, stages − s)`` by draining one
backward per steady-state forward (the Megatron-LM non-interleaved
schedule, PAPERS.md).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import List, Optional, Tuple

from adapcc_tpu.compiler.ir import ScheduleProgram, Step

#: the closed set of schedules; anything else is a construction error
PIPE_SCHEDULES = ("gpipe", "1f1b")

#: env override for the schedule axis (docs/PIPELINE.md, docs/OPERATIONS.md)
PIPE_SCHEDULE_ENV = "ADAPCC_PIPE_SCHEDULE"

DEFAULT_PIPE_SCHEDULE = "1f1b"

#: tuner key vocabulary for pipeline step cells (mirrors
#: ``tuner/policy.pipe_path`` — drift pinned by a test)
PIPE_PRIMITIVE = "pipe_step"


@dataclass(frozen=True)
class PipeTask:
    """One unit of stage work: ``kind`` is ``"fwd"`` or ``"bwd"``, ``mb``
    the microbatch index."""

    kind: str
    mb: int

    def __post_init__(self) -> None:
        if self.kind not in ("fwd", "bwd"):
            raise ValueError(f"unknown task kind {self.kind!r}")


@dataclass(frozen=True)
class PipelineSchedule:
    """One tick table: ``ticks[t][s]`` is stage ``s``'s task at tick ``t``
    (or ``None`` — a bubble slot)."""

    kind: str
    stages: int
    microbatches: int
    ticks: Tuple[Tuple[Optional[PipeTask], ...], ...]

    @property
    def num_ticks(self) -> int:
        return len(self.ticks)

    @property
    def bubble_fraction(self) -> float:
        """Measured idle fraction of the tick grid: each stage does
        ``2·m`` tasks over ``num_ticks`` slots.  Equals the closed form
        ``(s−1)/(m+s−1)`` for both schedules (pinned in tests)."""
        return 1.0 - (2.0 * self.microbatches) / float(self.num_ticks)

    @property
    def stash_high_water(self) -> Tuple[int, ...]:
        """Per-stage peak count of in-flight activations (forwards run
        minus backwards run, maximized over ticks) — the memory axis that
        separates 1F1B from GPipe."""
        peaks = []
        for s in range(self.stages):
            live = peak = 0
            for row in self.ticks:
                task = row[s]
                if task is None:
                    continue
                live += 1 if task.kind == "fwd" else -1
                peak = max(peak, live)
            peaks.append(peak)
        return tuple(peaks)

    def tasks_for_stage(self, s: int) -> List[Tuple[int, PipeTask]]:
        """``(tick, task)`` pairs for stage ``s`` in execution order."""
        return [(t, row[s]) for t, row in enumerate(self.ticks) if row[s]]


def _stage_order(kind: str, stages: int, microbatches: int, s: int) -> List[PipeTask]:
    """Stage ``s``'s local task order (deps are enforced by the tick sim)."""
    fwd = [PipeTask("fwd", m) for m in range(microbatches)]
    bwd = [PipeTask("bwd", m) for m in range(microbatches)]
    if kind == "gpipe":
        return fwd + bwd
    # 1f1b: warmup forwards, steady one-forward-one-backward, cooldown
    warmup = min(microbatches, stages - 1 - s)
    order: List[PipeTask] = fwd[:warmup]
    steady = microbatches - warmup
    for i in range(steady):
        order.append(fwd[warmup + i])
        order.append(bwd[i])
    order.extend(bwd[steady:])
    return order


def pipeline_schedule(
    stages: int, microbatches: int, kind: str = DEFAULT_PIPE_SCHEDULE
) -> PipelineSchedule:
    """Lay ``kind``'s per-stage task orders onto the global tick grid.

    Greedy list scheduling under the dependency rules — ``fwd(s, m)``
    needs ``fwd(s−1, m)`` from a strictly earlier tick, ``bwd(s, m)``
    needs ``fwd(s, m)`` and (for non-last stages) ``bwd(s+1, m)`` from
    strictly earlier ticks, one task per stage per tick.  Deterministic;
    loud on malformed shape or an (impossible) stall.
    """
    if kind not in PIPE_SCHEDULES:
        raise ValueError(
            f"unknown pipeline schedule {kind!r}; expected one of "
            f"{PIPE_SCHEDULES}"
        )
    if stages < 1:
        raise ValueError(f"stages must be >= 1, got {stages}")
    if microbatches < 1:
        raise ValueError(f"microbatches must be >= 1, got {microbatches}")

    orders = [
        _stage_order(kind, stages, microbatches, s) for s in range(stages)
    ]
    cursor = [0] * stages
    done_fwd: set = set()  # (stage, mb) completed in an earlier tick
    done_bwd: set = set()
    ticks: List[Tuple[Optional[PipeTask], ...]] = []
    while any(cursor[s] < len(orders[s]) for s in range(stages)):
        row: List[Optional[PipeTask]] = [None] * stages
        for s in range(stages):
            if cursor[s] >= len(orders[s]):
                continue
            task = orders[s][cursor[s]]
            if task.kind == "fwd":
                ready = s == 0 or (s - 1, task.mb) in done_fwd
            else:
                ready = (s, task.mb) in done_fwd and (
                    s == stages - 1 or (s + 1, task.mb) in done_bwd
                )
            if ready:
                row[s] = task
        if not any(row):
            raise RuntimeError(
                f"pipeline schedule {kind!r} stalled at tick {len(ticks)} "
                f"(stages={stages}, microbatches={microbatches}) — "
                "dependency cycle in the stage orders"
            )
        for s, task in enumerate(row):
            if task is None:
                continue
            cursor[s] += 1
            (done_fwd if task.kind == "fwd" else done_bwd).add((s, task.mb))
        ticks.append(tuple(row))
    return PipelineSchedule(
        kind=kind, stages=stages, microbatches=microbatches, ticks=tuple(ticks)
    )


def pipeline_program(
    schedule: PipelineSchedule,
    *,
    world: Optional[int] = None,
    tied_embedding: bool = False,
    name: Optional[str] = None,
) -> ScheduleProgram:
    """Re-emit ``schedule``'s stage hops as a verifiable ``pipeline``
    :class:`~adapcc_tpu.compiler.ir.ScheduleProgram`.

    Chunk ``m`` is microbatch ``m``'s forward activation (source stage 0,
    sink the last stage); chunk ``microbatches + m`` its backward
    gradient (routed the other way); with ``tied_embedding`` one extra
    chunk carries the Megatron-style head-embedding gradient from the
    last stage back to stage 0 after the drain.  One IR round per tick
    that moves data — a task at tick ``t`` sends in round ``t``'s
    barrier, and its consumer computes at a later tick, so matching holds
    by construction and ``verify_program`` certifies deadlock-freedom of
    the emitted table.
    """
    s_count, m_count = schedule.stages, schedule.microbatches
    if s_count < 2:
        raise ValueError(
            "a single-stage pipeline has no hops to compile into a program"
        )
    w = s_count if world is None else int(world)
    if w < s_count:
        raise ValueError(
            f"world {w} cannot host {s_count} stages (one rank per stage)"
        )
    chunks = 2 * m_count + (1 if tied_embedding else 0)
    sources = [0] * m_count + [s_count - 1] * m_count
    sinks = [s_count - 1] * m_count + [0] * m_count
    if tied_embedding:
        sources.append(s_count - 1)
        sinks.append(0)

    rounds: List[Tuple[Step, ...]] = []
    for row in schedule.ticks:
        msgs: List[Step] = []
        for s, task in enumerate(row):
            if task is None:
                continue
            if task.kind == "fwd" and s < s_count - 1:
                src, dst, chunk = s, s + 1, task.mb
            elif task.kind == "bwd" and s > 0:
                src, dst, chunk = s, s - 1, m_count + task.mb
            else:
                continue  # last-stage fwd / stage-0 bwd produce no hop
            msgs.extend(
                (
                    Step("send", rank=src, chunk=chunk, peer=dst),
                    Step("recv", rank=dst, chunk=chunk, peer=src),
                    Step("copy", rank=dst, chunk=chunk),
                )
            )
        if msgs:
            rounds.append(tuple(msgs))
    if tied_embedding:
        tie = chunks - 1
        rounds.append(
            (
                Step("send", rank=s_count - 1, chunk=tie, peer=0),
                Step("recv", rank=0, chunk=tie, peer=s_count - 1),
                Step("copy", rank=0, chunk=tie),
            )
        )
    return ScheduleProgram(
        name=name or f"pipe_{schedule.kind}_s{s_count}m{m_count}",
        world=w,
        chunks=chunks,
        rounds=tuple(rounds),
        collective="pipeline",
        chunk_sources=tuple(sources),
        chunk_sinks=tuple(sinks),
    )


def resolve_pipe_schedule(
    explicit: Optional[str] = None,
    *,
    tuner_db=None,
    world: int = 0,
    microbatches: int = 0,
    hop_bytes: int = 0,
    topology: str = "",
) -> str:
    """Resolve the schedule axis: env > arg > tuner > default.

    ``ADAPCC_PIPE_SCHEDULE`` wins outright (malformed → loud, the repo-wide
    env contract); then an explicit argument; then — when a
    :class:`~adapcc_tpu.tuner.db.TuningDatabase` and the cell coordinates
    are given — the measured ``pipe_step`` cell with the best median;
    finally :data:`DEFAULT_PIPE_SCHEDULE`.
    """
    env = os.environ.get(PIPE_SCHEDULE_ENV)
    if env is not None:
        val = env.strip().lower()
        if val not in PIPE_SCHEDULES:
            raise ValueError(
                f"{PIPE_SCHEDULE_ENV}={env!r}: expected one of "
                f"{PIPE_SCHEDULES} (docs/PIPELINE.md)"
            )
        return val
    if explicit is not None:
        if explicit not in PIPE_SCHEDULES:
            raise ValueError(
                f"pipe schedule {explicit!r}: expected one of {PIPE_SCHEDULES}"
            )
        return explicit
    if tuner_db is not None and world > 0 and microbatches > 0:
        from adapcc_tpu.tuner.db import TuningKey, size_bucket
        from adapcc_tpu.tuner.policy import pipe_path

        best, best_t = None, float("inf")
        for sched in PIPE_SCHEDULES:
            key = TuningKey(
                primitive=PIPE_PRIMITIVE,
                size_bucket=size_bucket(int(hop_bytes)),
                world=int(world),
                topology=topology,
                path=pipe_path(sched),
                chunk_bytes=int(microbatches),
                wire_dtype="off",
            )
            st = tuner_db.stats(key)
            if st is not None and st.median_s < best_t:
                best, best_t = sched, st.median_s
        if best is not None:
            return best
    return DEFAULT_PIPE_SCHEDULE
