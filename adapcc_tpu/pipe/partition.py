"""Stage partitioner: split GPT-2 across a pipeline ``stages`` axis.

The partitioner owns the **model side** of the pipeline plane: which
transformer blocks (plus the embedding front and the tied-head back) live
on which stage, how a full ``model.init`` param tree splits into
per-stage subtrees, and the pure per-stage forward functions the executor
differentiates with ``jax.vjp``.  The stage functions re-apply the *same
flax modules* ``models/gpt2.py`` builds inline (``nn.Embed``/``Block``/
``nn.LayerNorm`` with identical construction), so the staged composition
is the single-stage model's math by construction — the parity tests pin
the composed forward against ``GPT2.apply`` to the bit.

Weight tying across the cut: stage 0 owns ``wte``/``wpe``; the last stage
holds a ``head_wte`` *copy* of the token embedding for the tied LM head.
After the backward drain the executor routes the head copy's gradient
back to stage 0 (the Megatron-LM embedding-grad exchange) and folds it
into stage 0's ``wte`` gradient, so merged gradients match the
single-stage model where the head and the lookup share one tensor.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from adapcc_tpu.models.gpt2 import GPT2, GPT2Config, Block, lm_loss


@dataclass(frozen=True)
class StagePartition:
    """A contiguous block split: stage ``s`` runs blocks
    ``[block_ranges[s][0], block_ranges[s][1])``; ``param_counts`` is the
    per-stage parameter count including the embedding/head residents."""

    num_stages: int
    n_layer: int
    block_ranges: Tuple[Tuple[int, int], ...]
    param_counts: Tuple[int, ...]

    def blocks_of(self, stage: int) -> range:
        lo, hi = self.block_ranges[stage]
        return range(lo, hi)


def _param_count(tree: Any) -> int:
    return sum(
        int(jnp.size(x)) if hasattr(x, "size") else 0
        for x in jax.tree_util.tree_leaves(tree)
    )


def _module_sizes(cfg: GPT2Config) -> Dict[str, int]:
    """Per-top-module parameter counts from an abstract ``model.init`` —
    shapes only, nothing materialized."""
    model = GPT2(cfg)
    sample = jnp.zeros((1, min(2, cfg.max_seq)), dtype=jnp.int32)
    shapes = jax.eval_shape(lambda r: model.init(r, sample), jax.random.PRNGKey(0))
    return {
        name: sum(int(jnp.prod(jnp.array(l.shape))) for l in
                  jax.tree_util.tree_leaves(sub))
        for name, sub in shapes["params"].items()
    }


def partition_gpt2(cfg: GPT2Config, num_stages: int) -> StagePartition:
    """Split ``cfg.n_layer`` blocks over ``num_stages`` contiguous stages
    with balanced parameter counts.

    Every stage gets ``n_layer // num_stages`` blocks; the remainder
    blocks go one at a time to the lightest stages (the embedding makes
    stage 0 and the tied head makes the last stage heavier, so middle
    stages absorb the extras first).  Loud reject on un-splittable
    layouts: more stages than blocks, or a degenerate stage count.
    """
    if num_stages < 1:
        raise ValueError(f"num_stages must be >= 1, got {num_stages}")
    if num_stages > cfg.n_layer:
        raise ValueError(
            f"un-splittable layout: {cfg.n_layer} transformer blocks cannot "
            f"feed {num_stages} pipeline stages (each stage needs >= 1 block)"
        )
    if cfg.dropout != 0.0:
        raise ValueError("pipeline parallelism requires dropout == 0")
    if cfg.sp_axis is not None:
        raise ValueError(
            "pipeline parallelism does not compose with sequence "
            "parallelism (cfg.sp_axis must be None)"
        )

    sizes = _module_sizes(cfg)
    block_size = sizes["h0"]
    embed_size = sizes["wte"] + sizes["wpe"]
    head_size = sizes["wte"] + sizes["ln_f"]  # head_wte copy + final norm

    counts = [cfg.n_layer // num_stages] * num_stages
    extra = cfg.n_layer - sum(counts)
    overhead = [0.0] * num_stages
    overhead[0] += embed_size
    if num_stages > 1:
        overhead[-1] += head_size
    else:
        overhead[0] += sizes["ln_f"]
    for _ in range(extra):
        # lightest stage first; ties break toward the earlier stage
        load = [overhead[s] + counts[s] * block_size for s in range(num_stages)]
        s = min(range(num_stages), key=lambda i: (load[i], i))
        counts[s] += 1

    ranges: List[Tuple[int, int]] = []
    lo = 0
    for s in range(num_stages):
        ranges.append((lo, lo + counts[s]))
        lo += counts[s]
    param_counts = tuple(
        int(overhead[s]) + counts[s] * block_size for s in range(num_stages)
    )
    return StagePartition(
        num_stages=num_stages,
        n_layer=cfg.n_layer,
        block_ranges=tuple(ranges),
        param_counts=param_counts,
    )


# -- param tree surgery --------------------------------------------------------


def split_params(params: Any, partition: StagePartition) -> List[Dict[str, Any]]:
    """Split a full ``model.init`` tree into per-stage subtrees (each a
    plain ``{module_name: leaves}`` dict).  The last stage's ``head_wte``
    starts as a copy of ``wte`` — the executor keeps them in sync via the
    tied-embedding gradient exchange."""
    p = params["params"] if "params" in params else params
    out: List[Dict[str, Any]] = []
    S = partition.num_stages
    for s in range(S):
        sub: Dict[str, Any] = {}
        if s == 0:
            sub["wte"] = p["wte"]
            sub["wpe"] = p["wpe"]
        for i in partition.blocks_of(s):
            sub[f"h{i}"] = p[f"h{i}"]
        if s == S - 1:
            sub["ln_f"] = p["ln_f"]
            if S > 1:
                sub["head_wte"] = {"embedding": p["wte"]["embedding"]}
        out.append(sub)
    return out


def merge_params(stage_params: List[Dict[str, Any]], partition: StagePartition) -> Dict[str, Any]:
    """Inverse of :func:`split_params`: rebuild the flat ``{"params": …}``
    tree (dropping the derived ``head_wte`` copy — stage 0's ``wte`` is
    authoritative)."""
    flat: Dict[str, Any] = {}
    for sub in stage_params:
        for name, leaves in sub.items():
            if name != "head_wte":
                flat[name] = leaves
    return {"params": flat}


# -- per-stage forward functions ----------------------------------------------


def _wte(cfg: GPT2Config) -> nn.Embed:
    return nn.Embed(
        cfg.vocab_size,
        cfg.d_model,
        embedding_init=nn.initializers.normal(0.02),
        dtype=cfg.dtype,
    )


def _wpe(cfg: GPT2Config) -> nn.Embed:
    return nn.Embed(
        cfg.max_seq,
        cfg.d_model,
        embedding_init=nn.initializers.normal(0.01),
        dtype=cfg.dtype,
    )


def stage_forward(
    cfg: GPT2Config,
    partition: StagePartition,
    stage: int,
    stage_params: Dict[str, Any],
    x: Optional[jnp.ndarray],
    tokens: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Pure forward of one stage: embeds on stage 0 (``tokens`` required,
    ``x`` ignored), runs the stage's blocks, and on the last stage applies
    the final norm + tied head and returns the LM loss against
    ``tokens``.  Middle stages map activations to activations."""
    S = partition.num_stages
    if stage == 0:
        if tokens is None:
            raise ValueError("stage 0 embeds: tokens is required")
        T = tokens.shape[1]
        x = (
            _wte(cfg).apply({"params": stage_params["wte"]}, tokens)
            + _wpe(cfg).apply({"params": stage_params["wpe"]}, jnp.arange(T))[None]
        )
    for i in partition.blocks_of(stage):
        x = Block(cfg).apply(
            {"params": stage_params[f"h{i}"]}, x, True, False
        )
    if stage == S - 1:
        if tokens is None:
            raise ValueError("last stage computes the loss: tokens is required")
        x = nn.LayerNorm(dtype=jnp.float32).apply(
            {"params": stage_params["ln_f"]}, x
        )
        head = (
            stage_params["head_wte"]["embedding"]
            if S > 1
            else stage_params["wte"]["embedding"]
        )
        logits = (
            x.astype(cfg.dtype) @ head.T.astype(cfg.dtype)
        ).astype(jnp.float32)
        return lm_loss(logits, tokens)
    return x


def composed_loss(
    cfg: GPT2Config,
    partition: StagePartition,
    stage_params: List[Dict[str, Any]],
    tokens: jnp.ndarray,
) -> jnp.ndarray:
    """Sequential composition of every stage — the single-process baseline
    the pipeline executor is parity-pinned against (same stage functions,
    same order, no pipeline)."""
    x: Optional[jnp.ndarray] = None
    for s in range(partition.num_stages):
        x = stage_forward(cfg, partition, s, stage_params[s], x, tokens)
    return x  # the last stage returned the scalar loss
