"""Pipeline executor: interpret a tick table with real backward.

The executor runs the SAME static :class:`~adapcc_tpu.pipe.schedule
.PipelineSchedule` the verifier certified and the simulator priced —
tick by tick, one task per stage per tick.  Forward tasks run the pure
stage functions from :mod:`adapcc_tpu.pipe.partition` under ``jax.vjp``
and stash the pullback; backward tasks pop the stash, pull the upstream
gradient through, and accumulate per-stage parameter gradients in
microbatch order (identical order under GPipe and 1F1B, which is what
makes the two schedules' gradients bit-comparable).  Every stage-to-stage
hop — forward activations, backward activation gradients, and the final
Megatron-style tied-embedding gradient exchange — is dispatched through
the traced :meth:`~adapcc_tpu.comm.engine.CollectiveEngine.pipe_send`,
so the dispatch trace holds one event per hop with executed bytes and
route, and the hop count equals ``program.total_sends()`` of the emitted
IR program by construction.

The activation stash is the memory story: its per-stage high-water mark
is measured (count and bytes) and reported, so the 1F1B-vs-GPipe window
``min(m, stages − s)`` vs ``m`` is an observable, not a claim.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from adapcc_tpu.compiler.verify import verify_program
from adapcc_tpu.models.gpt2 import GPT2Config
from adapcc_tpu.pipe.partition import StagePartition, stage_forward
from adapcc_tpu.pipe.schedule import (
    PipelineSchedule,
    pipeline_program,
    pipeline_schedule,
    resolve_pipe_schedule,
)


@dataclass(frozen=True)
class PipelineReport:
    """What one pipelined step actually did."""

    schedule: str
    stages: int
    microbatches: int
    ticks: int
    hops: int
    stash_peak: Tuple[int, ...]        #: per-stage peak in-flight stash count
    stash_peak_bytes: Tuple[int, ...]  #: per-stage peak stashed activation bytes
    bubble_fraction: float
    step_time_s: float


class PipelineExecutor:
    """Drive GPT-2 stages over a pipeline schedule through a traced engine.

    ``engine`` is a :class:`~adapcc_tpu.comm.engine.CollectiveEngine`
    whose world hosts the stages (rank ``s`` is stage ``s``; extra ranks
    idle).  ``schedule`` resolves env > arg > tuner > default via
    :func:`~adapcc_tpu.pipe.schedule.resolve_pipe_schedule`.
    """

    def __init__(
        self,
        cfg: GPT2Config,
        partition: StagePartition,
        engine: Any,
        *,
        num_microbatches: int = 4,
        schedule: Optional[str] = None,
        tuner_db: Optional[Any] = None,
    ) -> None:
        if num_microbatches < 1:
            raise ValueError(
                f"num_microbatches must be >= 1, got {num_microbatches}"
            )
        S = partition.num_stages
        if engine.world_size < S:
            raise ValueError(
                f"engine world {engine.world_size} cannot host {S} stages"
            )
        self.cfg = cfg
        self.partition = partition
        self.engine = engine
        self.num_microbatches = int(num_microbatches)
        self.tuner_db = tuner_db
        topology = ""
        if tuner_db is not None:
            # the tuner cell lookup must spell the same topology slot the
            # recorder stamps, or measured cells can never win
            from adapcc_tpu.tuner.db import mesh_fingerprint

            topology = mesh_fingerprint(engine.mesh)
        self.schedule_kind = resolve_pipe_schedule(
            schedule,
            tuner_db=tuner_db,
            world=engine.world_size,
            microbatches=num_microbatches,
            topology=topology,
        )
        self.schedule: PipelineSchedule = pipeline_schedule(
            S, self.num_microbatches, self.schedule_kind
        )
        if S > 1:
            # the executor runs the verified object: emit the hop program
            # from the same tick table and certify it up front
            self.program = pipeline_program(
                self.schedule, world=engine.world_size, tied_embedding=True
            )
            verify_program(self.program)
        else:
            self.program = None

    # -- one pipelined step ----------------------------------------------------

    def _hop(
        self,
        value: jnp.ndarray,
        src: int,
        dst: int,
        kind: str,
        mb: Optional[int],
        tick: Optional[int],
    ) -> jnp.ndarray:
        """Route one payload src→dst through the traced engine primitive:
        stack it into the [world, ...] buffer layout, move the row, and
        read it back at the destination."""
        w = self.engine.world_size
        buf = jnp.zeros((w,) + value.shape, value.dtype).at[src].set(value)
        moved = self.engine.pipe_send(
            buf, src=src, dst=dst, kind=kind, mb=mb, tick=tick
        )
        return moved[dst]

    def forward_backward(
        self,
        stage_params: List[Dict[str, Any]],
        tokens: jnp.ndarray,
        *,
        grad_sync: Optional[Callable[[Any], Any]] = None,
    ) -> Tuple[jnp.ndarray, List[Any], PipelineReport]:
        """One pipelined forward/backward over ``tokens`` ``[B, T]``.

        Returns ``(loss, stage_grads, report)``: the mean microbatch loss,
        per-stage gradient pytrees already scaled to the full-batch mean
        (stage 0's ``wte`` gradient includes the tied-head contribution
        routed back from the last stage; the last stage's ``head_wte``
        slot is zeroed — stage 0 owns the shared tensor), and the step
        report.  ``grad_sync``, when given, is applied to each stage's
        accumulated gradients before return — the DP×PP attach point for
        the DDP grad-sync hook (docs/PIPELINE.md §DP×PP).
        """
        t0 = time.perf_counter()
        S = self.partition.num_stages
        M = self.num_microbatches
        B = tokens.shape[0]
        if B % M != 0:
            raise ValueError(
                f"batch {B} is not divisible into {M} microbatches"
            )
        mb_tokens = tokens.reshape(M, B // M, *tokens.shape[1:])
        cfg, part = self.cfg, self.partition

        fwd_inbox: Dict[Tuple[int, int], jnp.ndarray] = {}
        bwd_inbox: Dict[Tuple[int, int], jnp.ndarray] = {}
        stash: List[Dict[int, Any]] = [dict() for _ in range(S)]
        stash_bytes: List[Dict[int, int]] = [dict() for _ in range(S)]
        peak = [0] * S
        peak_bytes = [0] * S
        losses: List[Optional[jnp.ndarray]] = [None] * M
        grads: List[Any] = [None] * S
        hops = 0

        def accumulate(s: int, g: Any) -> None:
            grads[s] = (
                g
                if grads[s] is None
                else jax.tree_util.tree_map(jnp.add, grads[s], g)
            )

        for t, row in enumerate(self.schedule.ticks):
            for s, task in enumerate(row):
                if task is None:
                    continue
                m = task.mb
                if task.kind == "fwd":
                    if s == 0:
                        out, vjp = jax.vjp(
                            lambda p: stage_forward(
                                cfg, part, 0, p, None, mb_tokens[m]
                            ),
                            stage_params[0],
                        )
                        in_bytes = int(out.nbytes)
                    else:
                        x = fwd_inbox.pop((s, m))
                        toks = mb_tokens[m] if s == S - 1 else None
                        out, vjp = jax.vjp(
                            lambda p, xx: stage_forward(
                                cfg, part, s, p, xx, toks
                            ),
                            stage_params[s],
                            x,
                        )
                        in_bytes = int(x.nbytes)
                    stash[s][m] = vjp
                    stash_bytes[s][m] = in_bytes
                    peak[s] = max(peak[s], len(stash[s]))
                    peak_bytes[s] = max(
                        peak_bytes[s], sum(stash_bytes[s].values())
                    )
                    if s == S - 1:
                        losses[m] = out
                    else:
                        fwd_inbox[(s + 1, m)] = self._hop(
                            out, s, s + 1, "activation", m, t
                        )
                        hops += 1
                else:  # bwd
                    vjp = stash[s].pop(m)
                    stash_bytes[s].pop(m)
                    if s == S - 1:
                        seed = jnp.ones((), dtype=losses[m].dtype)
                        pulled = vjp(seed)
                    else:
                        pulled = vjp(bwd_inbox.pop((s, m)))
                    accumulate(s, pulled[0])
                    if s > 0:
                        bwd_inbox[(s - 1, m)] = self._hop(
                            pulled[1], s, s - 1, "grad", m, t
                        )
                        hops += 1

        assert not fwd_inbox and not bwd_inbox and all(
            not st for st in stash
        ), "pipeline drain left in-flight state (schedule/executor drift)"

        # microbatch-mean loss and grads (each microbatch loss is already a
        # mean over its tokens; equal sizes make sum/M the full-batch mean)
        loss = sum(losses[1:], losses[0]) / M
        grads = [
            jax.tree_util.tree_map(lambda g: g / M, gs) for gs in grads
        ]

        if S > 1:
            # Megatron-style tied-embedding exchange: the head copy's
            # gradient rides one traced hop back to the owner of wte
            head_g = grads[S - 1]["head_wte"]["embedding"]
            arrived = self._hop(head_g, S - 1, 0, "tied_embed", None, None)
            hops += 1
            grads[0]["wte"]["embedding"] = (
                grads[0]["wte"]["embedding"] + arrived
            )
            grads[S - 1]["head_wte"]["embedding"] = jnp.zeros_like(head_g)
            assert self.program is not None
            assert hops == self.program.total_sends(), (
                f"executor ran {hops} hops but the verified program has "
                f"{self.program.total_sends()} sends"
            )

        if grad_sync is not None:
            grads = [grad_sync(gs) for gs in grads]

        step_time = time.perf_counter() - t0
        if self.tuner_db is not None:
            self._record_tuner_sample(step_time)
        report = PipelineReport(
            schedule=self.schedule_kind,
            stages=S,
            microbatches=M,
            ticks=self.schedule.num_ticks,
            hops=hops,
            stash_peak=tuple(peak),
            stash_peak_bytes=tuple(peak_bytes),
            bubble_fraction=self.schedule.bubble_fraction,
            step_time_s=step_time,
        )
        return loss, grads, report

    def _record_tuner_sample(self, seconds: float) -> None:
        from adapcc_tpu.pipe.schedule import PIPE_PRIMITIVE
        from adapcc_tpu.tuner.db import (
            TuningKey,
            mesh_fingerprint,
            size_bucket,
        )
        from adapcc_tpu.tuner.policy import pipe_path

        key = TuningKey(
            primitive=PIPE_PRIMITIVE,
            size_bucket=size_bucket(0),
            world=self.engine.world_size,
            topology=mesh_fingerprint(self.engine.mesh),
            path=pipe_path(self.schedule_kind),
            chunk_bytes=self.num_microbatches,
            wire_dtype="off",
        )
        self.tuner_db.record(key, seconds)


def sync_tied_embedding(stage_params: List[Dict[str, Any]]) -> None:
    """Refresh the last stage's ``head_wte`` copy from stage 0's ``wte``
    after an optimizer update — the other half of the weight tie (the
    gradient half lives in :meth:`PipelineExecutor.forward_backward`).
    Mutates the per-stage dicts in place."""
    if len(stage_params) > 1 and "head_wte" in stage_params[-1]:
        stage_params[-1]["head_wte"]["embedding"] = (
            stage_params[0]["wte"]["embedding"]
        )
