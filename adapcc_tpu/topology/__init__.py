"""Topology detection and online network profiling for TPU meshes."""

from adapcc_tpu.topology.detect import detect_topology, dump_detected_topology, gather_detect_graph
from adapcc_tpu.topology.profile import NetworkProfiler
from adapcc_tpu.topology.variability import VariabilityMonitor, detect_drift, load_trace

__all__ = [
    "detect_topology",
    "dump_detected_topology",
    "gather_detect_graph",
    "NetworkProfiler",
    "VariabilityMonitor",
    "detect_drift",
    "load_trace",
]
