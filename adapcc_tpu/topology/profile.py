"""Online network profiling: latency/bandwidth matrices from probe transfers.

The reference's profile context times `cudaMemcpyPeerAsync` per intra-node
GPU pair and runs N−1 rounds of paired MPI probes inter-node
(csrc/profile.cu:163-334), dumping ``topo_profile_<rank>`` CSVs that the
master merges into lat/bw matrices (commu.py:246-270).  The TPU equivalent
probes *links of the device mesh* with timed one-hop ``ppermute`` programs —
small payload for latency, large payload for bandwidth — executed offset by
offset around the mesh axis (the same ring-offset pattern as the reference's
rounds, profile.cu:220-334).  The CSV artifact format (``src,dst,type,value``)
is kept.
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from adapcc_tpu.comm.mesh import RANKS_AXIS

#: probe payloads, mirroring the reference's probe sizes: 64 floats for
#: latency, 1M floats for inter-node bandwidth (profile.cu:120-158)
LATENCY_PROBE_FLOATS = 64
BANDWIDTH_PROBE_FLOATS = 1 << 20

_LAT, _BW = "lat", "bw"


def bandwidth_gbps(nbytes: int, seconds: float) -> float:
    """Transfer rate in GB/s (decimal), guarded against zero timings."""
    return nbytes / max(seconds, 1e-9) / 1e9


class NetworkProfiler:
    """Measures per-link latency (s) and bandwidth (GB/s) over a world mesh."""

    def __init__(self, mesh: Mesh, axis_name: str = RANKS_AXIS, warmup: int = 1, iters: int = 3):
        if len(mesh.axis_names) > 1:
            # multi-axis (e.g. two-level dcn×ici) world: probe over a flat
            # alias mesh on the same devices in the same order — the probes
            # measure physical links between flat ranks, and the flat rank r
            # sits at mesh position (r // ici, r % ici) by construction
            # (comm/two_level.py build_two_level_mesh), so the matrices line
            # up with the strategy/ip-table world
            mesh = Mesh(mesh.devices.reshape(-1), (RANKS_AXIS,))
            axis_name = RANKS_AXIS
        self.mesh = mesh
        self.axis_name = axis_name
        self.warmup = warmup
        self.iters = iters
        self.world = mesh.devices.size

    # -- probe programs --------------------------------------------------------

    def _offset_shift_fn(self, offset: int, n_floats: int):
        """Jitted program: every rank sends its buffer one hop to
        ``(rank + offset) % world`` — a full ring-offset round, so one timing
        exercises every link of that offset class simultaneously."""
        world = self.world
        perm = [(i, (i + offset) % world) for i in range(world)]

        def shard_fn(x):
            return lax.ppermute(x, self.axis_name, perm)

        fn = jax.jit(
            jax.shard_map(shard_fn, mesh=self.mesh, in_specs=P(self.axis_name), out_specs=P(self.axis_name))
        )
        x = jnp.zeros((world, n_floats), dtype=jnp.float32)
        return fn, x

    def _time(self, fn, x) -> float:
        for _ in range(self.warmup):
            jax.block_until_ready(fn(x))
        t0 = time.perf_counter()
        for _ in range(self.iters):
            jax.block_until_ready(fn(x))
        return (time.perf_counter() - t0) / self.iters

    def make_probe(self, offset: int, n_floats: int):
        """A reusable zero-arg probe: each call times one ring-offset round
        and returns seconds.  Build once, call many — the compiled program is
        captured (no re-tracing), and the compile/cache warmup runs only on
        the first call, so steady-state sampling injects exactly ``iters``
        probe rounds into the live network per reading."""
        fn, x = self._offset_shift_fn(offset, n_floats)
        warmed = False

        def probe() -> float:
            nonlocal warmed
            if not warmed:
                for _ in range(self.warmup):
                    jax.block_until_ready(fn(x))
                warmed = True
            t0 = time.perf_counter()
            for _ in range(self.iters):
                jax.block_until_ready(fn(x))
            return (time.perf_counter() - t0) / self.iters

        return probe

    # -- matrix profiling ------------------------------------------------------

    def profile(self) -> Tuple[np.ndarray, np.ndarray]:
        """Return (latency_matrix [s], bandwidth_matrix [GB/s]), world×world.

        Every off-diagonal (src, dst) pair is covered: the offset-``o`` round
        fills all pairs with ``dst − src ≡ o (mod world)``.
        """
        world = self.world
        lat = np.zeros((world, world))
        bw = np.zeros((world, world))
        if world == 1:
            return lat, bw
        for offset in range(1, world):
            t_lat = self.make_probe(offset, LATENCY_PROBE_FLOATS)()
            t_bw = self.make_probe(offset, BANDWIDTH_PROBE_FLOATS)()
            gbps = bandwidth_gbps(BANDWIDTH_PROBE_FLOATS * 4, t_bw)
            for src in range(world):
                dst = (src + offset) % world
                lat[src][dst] = t_lat
                bw[src][dst] = gbps
        return lat, bw

    # -- artifacts -------------------------------------------------------------

    def dump(self, out_dir: str, rank: int = 0) -> str:
        """Write ``topo_profile_<rank>`` CSV rows ``src,dst,type,value``
        (artifact contract of profile.cu:336-357)."""
        os.makedirs(out_dir, exist_ok=True)
        lat, bw = self.profile()
        path = os.path.join(out_dir, f"topo_profile_{rank}")
        with open(path, "w") as f:
            for src in range(self.world):
                for dst in range(self.world):
                    if src == dst:
                        continue
                    f.write(f"{src},{dst},{_LAT},{lat[src][dst]:.9f}\n")
                    f.write(f"{src},{dst},{_BW},{bw[src][dst]:.6f}\n")
        return path


def gather_topo_profile(topology_dir: str, world: int) -> Tuple[np.ndarray, np.ndarray]:
    """Merge ``topo_profile_*`` CSVs into lat/bw matrices (analog of
    ``_gather_topo_profile``, commu.py:246-270)."""
    import glob

    lat = np.zeros((world, world))
    bw = np.zeros((world, world))
    for path in sorted(glob.glob(os.path.join(topology_dir, "topo_profile_*"))):
        with open(path) as f:
            for line in f:
                parts = line.strip().split(",")
                if len(parts) != 4:
                    continue
                src, dst, typ, val = int(parts[0]), int(parts[1]), parts[2], float(parts[3])
                if not (0 <= src < world and 0 <= dst < world):
                    continue  # stale artifact from a different world size
                if typ == _LAT:
                    lat[src][dst] = val
                elif typ == _BW:
                    bw[src][dst] = val
    return lat, bw
