"""Long-horizon network-variability probes (the reference's cloud/ study).

The reference characterizes cloud interconnect variability with iperf probes
fired every 5 s for hours, logging timestamped bandwidth/latency readings to
trace files (cloud/band_profile.py:16-30, traces under cloud/trace/) — the
evidence that motivates periodic re-adaptation (``profile_freq``).  The TPU
analog samples the mesh's links with the same one-hop ``ppermute`` probes the
online profiler uses, on a background thread, appending to trace files of the
same shape; drift detection over the trace decides when a re-profile +
re-synthesis (``reconstruct_topology``) is worth its cost.
"""

from __future__ import annotations

import os
import statistics
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from jax.sharding import Mesh

from adapcc_tpu.comm.mesh import RANKS_AXIS
from adapcc_tpu.topology.profile import (
    LATENCY_PROBE_FLOATS,
    NetworkProfiler,
    bandwidth_gbps,
)


#: trailing-median window for drift detection (samples)
_DRIFT_WINDOW = 12


def detect_drift(
    history: Sequence[float],
    threshold: float = 0.3,
    window: int = _DRIFT_WINDOW,
    consecutive: int = 1,
    direction: str = "both",
) -> bool:
    """Have the newest ``consecutive`` readings EACH drifted > ``threshold``
    (relative) from the median of the trailing ``window`` before them?  The
    trigger condition for re-adaptation: a sustained bandwidth dip like the
    reference's observed 14.7 → 1.7 GB-scale drops
    (cloud/trace/bandwidth-hw.txt).

    ``consecutive > 1`` makes the trigger *sustained* — a single noisy probe
    (scheduler jitter on a loaded host) cannot fire a re-synthesis.
    ``direction`` limits which deviations count: ``"down"`` (a degraded
    link — the case re-adaptation exists for), ``"up"``, or ``"both"``.
    """
    if direction not in ("down", "up", "both"):
        raise ValueError(f"direction must be down/up/both, got {direction!r}")
    if consecutive < 1:
        raise ValueError(f"consecutive must be >= 1, got {consecutive}")
    if len(history) < consecutive + 1:
        return False
    base = statistics.median(history[-window - consecutive : -consecutive])
    if base <= 0:
        return False
    for v in history[-consecutive:]:
        rel = (v - base) / base
        if direction == "down":
            hit = rel < -threshold
        elif direction == "up":
            hit = rel > threshold
        else:
            hit = abs(rel) > threshold
        if not hit:
            return False
    return True


class VariabilityMonitor:
    """Periodic link sampling with timestamped traces.

    One sample = one ring-offset-1 probe round (every neighbor link at once,
    the cheapest full-coverage probe): a small payload timing → latency, a
    large one → aggregate bandwidth.  ``on_drift`` (if given) is invoked from
    the monitor thread when :func:`detect_drift` fires on the bandwidth
    trace — the hook where a training loop schedules reconstruct_topology.
    """

    def __init__(
        self,
        mesh: Mesh,
        axis_name: str = RANKS_AXIS,
        interval_s: float = 5.0,
        out_dir: Optional[str] = None,
        probe_floats: int = 1 << 18,
        drift_threshold: float = 0.3,
        on_drift: Optional[Callable[[float], None]] = None,
        max_samples: int = 100_000,
        drift_consecutive: int = 1,
        drift_direction: str = "both",
    ) -> None:
        # fail bad drift config at construction — detect_drift's own checks
        # would otherwise first fire on the monitor's daemon thread, killing
        # monitoring with nothing but a stderr traceback
        if drift_direction not in ("down", "up", "both"):
            raise ValueError(
                f"drift_direction must be down/up/both, got {drift_direction!r}"
            )
        if drift_consecutive < 1:
            raise ValueError(
                f"drift_consecutive must be >= 1, got {drift_consecutive}"
            )
        self.interval_s = interval_s
        self.out_dir = out_dir
        self.drift_threshold = drift_threshold
        self.drift_consecutive = drift_consecutive
        self.drift_direction = drift_direction
        self.on_drift = on_drift
        # in-memory traces are bounded (oldest trimmed) — day-scale runs keep
        # their full history in the trace *files*, not in RAM
        self.max_samples = max_samples
        self.bandwidth_trace: List[Tuple[float, float]] = []  # (ts, GB/s)
        self.latency_trace: List[Tuple[float, float]] = []  # (ts, s)
        profiler = NetworkProfiler(mesh, axis_name, warmup=1, iters=1)
        self._bw_probe = profiler.make_probe(1, probe_floats)
        self._lat_probe = profiler.make_probe(1, LATENCY_PROBE_FLOATS)
        self._probe_bytes = probe_floats * 4
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if out_dir:
            os.makedirs(out_dir, exist_ok=True)

    # -- sampling --------------------------------------------------------------

    def sample(self) -> Tuple[float, float]:
        """One (bandwidth GB/s, latency s) reading across neighbor links."""
        t_lat = self._lat_probe()
        gbps = bandwidth_gbps(self._probe_bytes, self._bw_probe())
        ts = time.time()
        self.bandwidth_trace.append((ts, gbps))
        self.latency_trace.append((ts, t_lat))
        for trace in (self.bandwidth_trace, self.latency_trace):
            if len(trace) > self.max_samples:
                del trace[: -self.max_samples]
        if self.out_dir:
            self._append(os.path.join(self.out_dir, "bandwidth.txt"), ts, gbps)
            self._append(os.path.join(self.out_dir, "latency.txt"), ts, t_lat)
        if self.on_drift is not None and detect_drift(
            # drift only reads the trailing window; don't copy full history
            [
                v
                for _, v in self.bandwidth_trace[
                    -_DRIFT_WINDOW - self.drift_consecutive :
                ]
            ],
            self.drift_threshold,
            consecutive=self.drift_consecutive,
            direction=self.drift_direction,
        ):
            self.on_drift(gbps)
        return gbps, t_lat

    @staticmethod
    def _append(path: str, ts: float, value: float) -> None:
        # %g keeps significant digits for µs-scale latencies, where fixed
        # 6-decimal formatting would round everything to zero
        with open(path, "a") as f:
            f.write(f"{ts:.3f} {value:.9g}\n")

    # -- background loop -------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            raise RuntimeError("monitor already running")
        self._stop.clear()

        def loop() -> None:
            while not self._stop.is_set():
                self.sample()
                self._stop.wait(self.interval_s)

        self._thread = threading.Thread(target=loop, daemon=True, name="adapcc-varmon")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join()
            self._thread = None

    # -- analysis --------------------------------------------------------------

    def summary(self) -> Dict[str, float]:
        """min/median/max over the bandwidth trace (the reference's study
        reports exactly this spread per instance pair)."""
        values = [v for _, v in self.bandwidth_trace]
        if not values:
            return {"samples": 0.0}
        return {
            "samples": float(len(values)),
            "bw_min_gbps": min(values),
            "bw_median_gbps": statistics.median(values),
            "bw_max_gbps": max(values),
        }


def load_trace(path: str) -> List[Tuple[float, float]]:
    """Read a ``ts value`` trace file back into memory."""
    out = []
    with open(path) as f:
        for line in f:
            parts = line.split()
            if len(parts) == 2:
                out.append((float(parts[0]), float(parts[1])))
    return out
