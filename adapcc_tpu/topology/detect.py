"""Topology detection: build the logical cluster graph from the device mesh.

The reference burns a whole native context on this — NUMA-pinned loopback
timing, pairwise PCIe-contention probes, NIC-affinity bandwidth tests
(csrc/detect.cu:70-361) — because GPU servers hide their topology.  TPU
runtimes don't: every `jax.Device` carries its owning process, slice, and
torus coordinates, so "detection" is reading metadata instead of racing DMA
engines.  What survives from the reference design is the *artifact contract*:
a per-host detected-topology XML (analog of ``topology/topo_detect_<rank>.xml``,
detect.cu:367-424) and a merge step producing the logical graph XML that
drives profiling and synthesis (analog of ``_gather_detect_graph``,
commu.py:207-244).
"""

from __future__ import annotations

import glob
import os
from typing import Dict, List, Optional

from jax.sharding import Mesh

from adapcc_tpu.strategy.xml_io import (
    LogicalGraph,
    ServerEntry,
    emit_logical_graph_xml,
    parse_logical_graph_xml,
)


def _device_slice(device) -> int:
    """ICI domain id: devices in one slice talk over ICI, across slices over
    DCN (the TPU analog of the reference's NIC grouping)."""
    for attr in ("slice_index", "slice"):
        v = getattr(device, attr, None)
        if isinstance(v, int):
            return v
    return getattr(device, "process_index", 0)


def detect_topology(mesh: Mesh, version: str = "tpu-detected") -> LogicalGraph:
    """Logical graph of the world mesh: one server entry per host analog.

    The host analog is the mesh's ip-table label (``mesh_ip_table``): the
    process on a flat mesh, the *slice row* on a two-level ``(dcn, ici)``
    mesh — so the logical graph's server grouping (which feeds the
    synthesizer's master/chain hierarchy) always matches the execution
    split.  Rank numbering is mesh order (flattened), matching how the
    collective engine assigns schedule ranks to mesh positions.
    """
    from adapcc_tpu.comm.mesh import mesh_ip_table

    devices = list(mesh.devices.flat)
    table = mesh_ip_table(mesh)
    buckets: Dict[tuple, List[int]] = {}
    for rank, dev in enumerate(devices):
        key = (table[rank], _device_slice(dev))
        buckets.setdefault(key, []).append(rank)

    graph = LogicalGraph(version=version)
    ordered = sorted(buckets.items(), key=lambda kv: min(kv[1]))
    for sid, ((ip, sl), ranks) in enumerate(ordered):
        graph.servers.append(
            ServerEntry(
                server_id=sid,
                ip=ip,
                nic_id=sl,
                gpus=sorted(ranks),
            )
        )
    return graph


def dump_detected_topology(mesh: Mesh, out_dir: str, process_index: Optional[int] = None) -> List[str]:
    """Write per-host detected-topology XML files.

    Single-controller JAX sees every process's devices, so this writes the
    shard of the graph owned by each process (or just ``process_index`` if
    given) — the analog of each node's local-rank-0 dumping
    ``topo_detect_<rank>.xml``.
    """
    os.makedirs(out_dir, exist_ok=True)
    graph = detect_topology(mesh)
    devices = list(mesh.devices.flat)
    written = []
    for s in graph.servers:
        # the owning process comes from device metadata, not from parsing the
        # ip label (two-level labels are "slice-N", not "process-N"); a
        # slice spanning processes is dumped by its first-rank owner
        proc = getattr(devices[min(s.gpus)], "process_index", 0)
        if process_index is not None and proc != process_index:
            continue
        shard = LogicalGraph(servers=[s], version=graph.version)
        path = os.path.join(out_dir, f"topo_detect_{min(s.gpus)}.xml")
        emit_logical_graph_xml(shard, path)
        written.append(path)
    return written


def gather_detect_graph(topology_dir: str, out_path: Optional[str] = None) -> LogicalGraph:
    """Merge per-host ``topo_detect_*.xml`` shards into one logical graph
    (analog of the reference's xmltodict merge, commu.py:207-244)."""
    servers: List[ServerEntry] = []
    for path in sorted(glob.glob(os.path.join(topology_dir, "topo_detect_*.xml"))):
        shard = parse_logical_graph_xml(path)
        servers.extend(shard.servers)
    servers.sort(key=lambda s: min(s.gpus) if s.gpus else 0)
    for sid, s in enumerate(servers):
        s.server_id = sid
    graph = LogicalGraph(servers=servers, version="tpu-gathered")
    if out_path:
        emit_logical_graph_xml(graph, out_path)
    return graph
