"""Public AdapCC façade (reference adapcc.py API surface).

Fleshed out together with the collective engine; see SURVEY.md §7 step 2.
"""

from __future__ import annotations


class AdapCC:
    """Classmethod façade over one communicator instance (reference
    adapcc.py:6-77).  Populated as the engine lands."""

    communicator = None
    local_rank = None
    world_rank = None
    world_size = None
    profile_freq = None
