"""Public AdapCC façade — same surface as the reference's adapcc.py.

The reference exposes a classmethod façade over one ``CudaCommu``
(adapcc.py:6-77): ``init`` runs the detect/profile bootstrap chosen by
``entry_point``, ``setup`` creates a transmission context, the collective
methods forward to the communicator, and ``reconstruct_topology`` tears
everything down and re-adapts.  This is the same façade over the TPU
:class:`~adapcc_tpu.communicator.Communicator`.

Entry-point contract (adapcc.py:30-41): ``DETECT`` (6) runs detect → profile
→ synthesize; ``PROFILE`` (7) assumes a logical graph exists and runs profile
→ synthesize; ``-1`` skips the bootstrap (use a pre-written strategy file).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax.numpy as jnp
from jax.sharding import Mesh

from adapcc_tpu.communicator import Communicator
from adapcc_tpu.config import CommArgs
from adapcc_tpu.primitives import DETECT, PROFILE, SKIP_BOOTSTRAP, ReduceOp


class AdapCC:
    """Classmethod façade; state mirrors the reference's class attributes."""

    communicator: Optional[Communicator] = None
    local_rank: Optional[int] = None
    world_rank: Optional[int] = None
    world_size: Optional[int] = None
    profile_freq: Optional[int] = None

    @classmethod
    def init(
        cls,
        args: Any,
        local_rank: int = 0,
        world_rank: int = 0,
        world_size: Optional[int] = None,
        mesh: Optional[Mesh] = None,
    ) -> None:
        """Create the communicator and run the adaptive bootstrap.

        ``local_rank``/``world_rank`` are accepted for signature parity with
        the reference (adapcc.py:16); under single-controller JAX the mesh
        carries the whole world, so they only label this process.
        """
        comm_args = args if isinstance(args, CommArgs) else CommArgs.from_namespace(args)
        cls.communicator = Communicator(comm_args, mesh=mesh, world_size=world_size)
        cls.local_rank = local_rank
        cls.world_rank = world_rank
        cls.world_size = cls.communicator.world_size
        cls.profile_freq = comm_args.profile_freq

        entry = comm_args.entry_point
        if entry == DETECT:
            cls.communicator.init_threads(DETECT)
            cls.communicator.exit_threads(DETECT)
            cls.communicator.init_threads(PROFILE)
            cls.communicator.exit_threads(PROFILE)
        elif entry == PROFILE:
            cls.communicator.init_threads(PROFILE)
            cls.communicator.exit_threads(PROFILE)
        elif entry == SKIP_BOOTSTRAP:
            pass
        else:
            raise ValueError(f"no supported entry point for init: {entry}")

    @classmethod
    def setup(cls, prim: int) -> None:
        cls.communicator.init_threads(prim)

    @classmethod
    def allreduce(
        cls,
        tensor: jnp.ndarray,
        size: Optional[int] = None,
        chunk_bytes: Optional[int] = None,
        active_gpus: Optional[Sequence[int]] = None,
        op: ReduceOp = ReduceOp.SUM,
    ) -> jnp.ndarray:
        return cls.communicator.all_reduce(tensor, size, chunk_bytes, active_gpus, op=op)

    @classmethod
    def reduce(
        cls,
        tensor: jnp.ndarray,
        size: Optional[int] = None,
        chunk_bytes: Optional[int] = None,
        active_gpus: Optional[Sequence[int]] = None,
        op: ReduceOp = ReduceOp.SUM,
    ) -> jnp.ndarray:
        return cls.communicator.reduce(tensor, size, chunk_bytes, active_gpus, op=op)

    @classmethod
    def boardcast(
        cls,
        tensor: jnp.ndarray,
        size: Optional[int] = None,
        chunk_bytes: Optional[int] = None,
        active_gpus: Optional[Sequence[int]] = None,
    ) -> jnp.ndarray:
        return cls.communicator.boardcast(tensor, size, chunk_bytes, active_gpus)

    @classmethod
    def alltoall(
        cls,
        tensor: jnp.ndarray,
        size: Optional[int] = None,
        chunk_bytes: Optional[int] = None,
        active_gpus: Optional[Sequence[int]] = None,
    ) -> jnp.ndarray:
        return cls.communicator.alltoall(tensor, size, chunk_bytes, active_gpus)

    @classmethod
    def reconstruct_topology(cls, args: Any, prim: int) -> None:
        """Clear contexts, re-run the adaptive bootstrap, rebuild the context
        (adapcc.py:63-67) — the periodic re-adaptation driven by
        ``profile_freq`` in training loops."""
        cls.clear(prim)
        cls.init(
            args,
            cls.local_rank,
            cls.world_rank,
            cls.world_size,
            mesh=cls.communicator.mesh if cls.communicator else None,
        )
        cls.setup(prim)

    @classmethod
    def set_profile_freq(cls, freq: int) -> None:
        cls.profile_freq = freq

    @classmethod
    def clear(cls, prim: int) -> None:
        cls.communicator.exit_threads(prim)
        cls.communicator.clear()


def smoke_benchmark(world: int = 4) -> None:
    """The reference's ``__main__`` smoke benchmark (adapcc.py:81-117): full
    adaptive bootstrap, then 16-float allreduces of ``ones*i`` over ``world``
    ranks — every rank must print ``i*world`` — plus a subset (relay)
    allreduce.  Output is deterministic; ``log/primitive`` holds the golden
    copy (README.md:104 analog), asserted by the test suite.
    """
    import tempfile

    from adapcc_tpu.launch import maybe_initialize_distributed

    # re-pin jax_platforms from the env before any device use (site
    # customizations override the env var at interpreter startup)
    maybe_initialize_distributed()

    import jax
    import numpy as np

    from adapcc_tpu.comm.mesh import build_world_mesh
    from adapcc_tpu.primitives import ALLREDUCE

    mesh = build_world_mesh(min(world, len(jax.devices())))
    w = int(mesh.devices.size)
    with tempfile.TemporaryDirectory(prefix="adapcc_smoke_") as workdir:
        args = CommArgs(
            strategy_file=f"{workdir}/strategy.xml",
            logical_graph=f"{workdir}/logical_graph.xml",
            topology_dir=workdir,
            entry_point=DETECT,
            parallel_degree=2,
        )
        AdapCC.init(args, mesh=mesh)
        AdapCC.setup(ALLREDUCE)

        for i in (1, 2, 3):
            x = jnp.stack([jnp.ones(16) * i for _ in range(w)])
            out = np.asarray(AdapCC.allreduce(x, size=16, chunk_bytes=8))
            for r in range(w):
                vals = out[r].astype(int).tolist()
                print(f"rank {r} allreduce(ones*{i}) -> {vals}")

        # subset collective: the last rank is a relay; active ranks still sum
        x = jnp.stack([jnp.ones(16) * (r + 1) for r in range(w)])
        active = list(range(w - 1))
        out = np.asarray(AdapCC.allreduce(x, active_gpus=active))
        print(f"partial allreduce over active {active} -> {int(out[0][0])}")

        AdapCC.clear(ALLREDUCE)
    print("smoke benchmark complete")


if __name__ == "__main__":
    smoke_benchmark()
