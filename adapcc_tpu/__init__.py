"""adapcc-tpu: TPU-native adaptive collective-communication framework.

A from-scratch JAX/XLA/Pallas re-design of the capabilities of JoeyYoung/adapcc
(reference layer map in SURVEY.md §1): topology detection, online network
profiling, communication-strategy synthesis (parallel spanning trees), chunked
pipelined tree/ring collectives, relay control (subset collectives with
straggler ranks demoted to forwarding relays), and heartbeat-based fault
tolerance — built on `jax.sharding.Mesh` + `shard_map` + XLA collectives +
Pallas ICI kernels instead of CUDA IPC / MPI / NCCL.

Public surface mirrors the reference's `adapcc.py` (reference adapcc.py:6-77):
``AdapCC.init / setup / allreduce / reduce / boardcast / alltoall /
reconstruct_topology / set_profile_freq / clear``.
"""

from adapcc_tpu import compat as _compat

_compat.install()

from adapcc_tpu.primitives import (
    ALLREDUCE,
    REDUCE,
    BOARDCAST,
    ALLGATHER,
    ALLTOALL,
    REDUCESCATTER,
    DETECT,
    PROFILE,
)
from adapcc_tpu.api import AdapCC

__version__ = "0.1.0"

__all__ = [
    "AdapCC",
    "ALLREDUCE",
    "REDUCE",
    "BOARDCAST",
    "ALLGATHER",
    "ALLTOALL",
    "REDUCESCATTER",
    "DETECT",
    "PROFILE",
    "__version__",
]
