"""gRPC transport for the coordinator (wire-compatible with the reference).

Service/message names, field numbers, and RPC semantics match the reference's
``coordinator.proto`` (proto/protobuf/coordinator.proto:20-43) so a reference
client could talk to this server.  The Python gRPC *stubs* are hand-written
over the protoc-generated message classes because the image ships protoc but
not the grpc codegen plugin.

Client classes mirror the reference's (proto/rpc_client.py): ``Controller``
sends per-step relay/heartbeat requests, ``Hooker`` sends bucket-ready
requests.  Beyond the reference, the service carries a third, additive RPC —
``heartbeat`` — the liveness lease the supervisor daemon
(docs/SUPERVISOR.md) detects real cross-process silence from; it reuses the
reference's ``cont_request``/``cont_response`` message shapes so the wire
vocabulary stays the reference's.

Every client call runs under a deadline (``ADAPCC_RPC_TIMEOUT_S``) with
bounded exponential backoff + jitter on transport-level UNAVAILABLE errors:
a dead coordinator surfaces a loud :class:`CoordinatorUnavailable` within
the budget, never an indefinite block.  (Server-side, ``stop()`` drains
blocked waiters with an explicit sentinel — the two halves of the same
no-hang contract.)
"""

from __future__ import annotations

import os
import random
import time
from concurrent import futures
from typing import Callable, List, Optional, Tuple

import grpc

from adapcc_tpu.coordinator.logic import CoordinatorLogic, CoordinatorShutdown
from adapcc_tpu.coordinator.protocol import coordinator_pb2 as pb

_SERVICE = "coordinator.Coordinator"

#: client-side deadline budget for every coordinator RPC (seconds).  The
#: default clears the coordinator's own longest legitimate wait (the 10 s
#: fault timeout a blocked barrier can ride) with headroom; deployments
#: with tighter heartbeat knobs shrink it to match.  Malformed → loud.
RPC_TIMEOUT_ENV = "ADAPCC_RPC_TIMEOUT_S"
DEFAULT_RPC_TIMEOUT_S = 30.0

#: backoff for transport-level retries: bounded, exponential, jittered
RPC_BACKOFF_INITIAL_S = 0.05
RPC_BACKOFF_MAX_S = 1.0


def rpc_timeout_s(default: float = DEFAULT_RPC_TIMEOUT_S) -> float:
    """The ``ADAPCC_RPC_TIMEOUT_S`` funnel (malformed → loud, the
    ADAPCC_MERGE_ROUNDS policy)."""
    raw = os.environ.get(RPC_TIMEOUT_ENV, "").strip()
    if not raw:
        return default
    try:
        value = float(raw)
    except ValueError as e:
        raise ValueError(f"{RPC_TIMEOUT_ENV}={raw!r}: expected a number") from e
    if value <= 0:
        raise ValueError(f"{RPC_TIMEOUT_ENV}={raw!r}: must be > 0")
    return value


class CoordinatorUnavailable(grpc.RpcError):
    """The coordinator did not answer within the RPC deadline budget.

    A :class:`grpc.RpcError` subclass so every existing handler that
    catches transport errors keeps working, but *named*: "the control
    plane is gone" must read differently from a generic RPC hiccup.
    Raised client-side after the bounded backoff budget is exhausted (or
    immediately on a deadline the server let expire) — the loud surface
    the fault machinery needs, never an indefinite block.
    """

    def __init__(self, message: str) -> None:
        super().__init__(message)
        self.message = message

    def code(self) -> grpc.StatusCode:
        return grpc.StatusCode.UNAVAILABLE

    def details(self) -> str:
        return self.message


def _call_with_deadline(
    call: Callable,
    request,
    what: str,
    timeout_s: Optional[float] = None,
    rng: Optional[random.Random] = None,
):
    """Run one unary RPC under the deadline budget (module doc).

    Retries ONLY transport-level UNAVAILABLE (connection refused / reset
    — and since gRPC can surface that even after the server processed the
    call, the arrival funnels dedupe per (step, rank) server-side, so a
    re-send is idempotent); an explicit server abort (the shutdown
    sentinel's "coordinator stopped") re-raises as-is, and a
    DEADLINE_EXCEEDED converts straight to :class:`CoordinatorUnavailable`
    — the server held the call past the whole budget, so retrying would
    just double the hang.
    """
    budget = rpc_timeout_s() if timeout_s is None else float(timeout_s)
    rng = rng if rng is not None else random.Random(0xBEA7)
    deadline = time.monotonic() + budget
    backoff = RPC_BACKOFF_INITIAL_S
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            raise CoordinatorUnavailable(
                f"coordinator unreachable: {what} got no answer within "
                f"{budget:.3f}s ({RPC_TIMEOUT_ENV} budget)"
            )
        try:
            return call(request, timeout=remaining)
        except grpc.RpcError as e:
            code = e.code() if callable(getattr(e, "code", None)) else None
            if code is grpc.StatusCode.DEADLINE_EXCEEDED:
                raise CoordinatorUnavailable(
                    f"coordinator unresponsive: {what} deadline "
                    f"({budget:.3f}s, {RPC_TIMEOUT_ENV}) expired"
                ) from e
            if code is not grpc.StatusCode.UNAVAILABLE:
                raise
            details = e.details() if callable(getattr(e, "details", None)) else ""
            if details and "coordinator stopped" in details:
                # the server's own drain sentinel: an explicit answer,
                # not silence — surface it unchanged
                raise
            sleep = min(
                backoff * (1.0 + rng.random()),  # full jitter in [b, 2b)
                RPC_BACKOFF_MAX_S,
                max(0.0, deadline - time.monotonic()),
            )
            if sleep > 0:
                time.sleep(sleep)
            backoff = min(backoff * 2, RPC_BACKOFF_MAX_S)


class CoordinatorServer:
    """Hosts the decision logic on ``ip:port`` (rank 0 in the reference,
    commu.py:136-141)."""

    def __init__(
        self,
        world_size: int,
        ip: str = "127.0.0.1",
        port: int = 50051,
        logic: Optional[CoordinatorLogic] = None,
        max_workers: int = 16,
    ) -> None:
        self.logic = logic if logic is not None else CoordinatorLogic(world_size)
        self.address = f"{ip}:{port}"
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))
        handlers = {
            "controller_fetch": grpc.unary_unary_rpc_method_handler(
                self._controller_fetch,
                request_deserializer=pb.cont_request.FromString,
                response_serializer=pb.cont_response.SerializeToString,
            ),
            "hook_fetch": grpc.unary_unary_rpc_method_handler(
                self._hook_fetch,
                request_deserializer=pb.hook_request.FromString,
                response_serializer=pb.hook_response.SerializeToString,
            ),
            # additive liveness-lease RPC (docs/SUPERVISOR.md): reuses the
            # cont_request/cont_response shapes — step carries the rank's
            # self-reported recent step walltime in MICROSECONDS (0 =
            # none), the response's status carries the worldview epoch
            "heartbeat": grpc.unary_unary_rpc_method_handler(
                self._heartbeat,
                request_deserializer=pb.cont_request.FromString,
                response_serializer=pb.cont_response.SerializeToString,
            ),
        }
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(_SERVICE, handlers),)
        )
        self._port = self._server.add_insecure_port(self.address)

    @property
    def port(self) -> int:
        return self._port

    def start(self) -> "CoordinatorServer":
        self._server.start()
        return self

    def stop(self, grace: Optional[float] = 0.5) -> None:
        """Drain in-flight waiters, then stop the transport.

        ``logic.shutdown()`` wakes every RPC handler blocked on the
        condition variable with an explicit sentinel (turned into an
        UNAVAILABLE abort below), so a worker parked in
        ``send_ready_request`` unblocks with a clean error instead of
        hanging until its channel times out long after the server is gone.
        """
        self.logic.shutdown()
        self._server.stop(grace)

    # -- rpc handlers ----------------------------------------------------------

    def _controller_fetch(self, request, context):
        try:
            active, status = self.logic.controller_arrive(request.step, request.world_rank)
        except CoordinatorShutdown:
            context.abort(grpc.StatusCode.UNAVAILABLE, "coordinator stopped")
        return pb.cont_response(active_list=active, status=status)

    def _hook_fetch(self, request, context):
        try:
            active = self.logic.hook_arrive(request.step, request.world_rank)
        except CoordinatorShutdown:
            context.abort(grpc.StatusCode.UNAVAILABLE, "coordinator stopped")
        return pb.hook_response(active_list=active)

    def _heartbeat(self, request, context):
        try:
            alive, epoch = self.logic.heartbeat_arrive(
                request.world_rank,
                median_s=(request.step / 1e6) if request.step > 0 else None,
            )
        except CoordinatorShutdown:
            context.abort(grpc.StatusCode.UNAVAILABLE, "coordinator stopped")
        return pb.cont_response(active_list=alive, status=epoch)


class _Stub:
    def __init__(self, channel: grpc.Channel):
        self.controller_fetch = channel.unary_unary(
            f"/{_SERVICE}/controller_fetch",
            request_serializer=pb.cont_request.SerializeToString,
            response_deserializer=pb.cont_response.FromString,
        )
        self.hook_fetch = channel.unary_unary(
            f"/{_SERVICE}/hook_fetch",
            request_serializer=pb.hook_request.SerializeToString,
            response_deserializer=pb.hook_response.FromString,
        )
        self.heartbeat = channel.unary_unary(
            f"/{_SERVICE}/heartbeat",
            request_serializer=pb.cont_request.SerializeToString,
            response_deserializer=pb.cont_response.FromString,
        )


class Controller:
    """Per-rank relay/heartbeat client (reference rpc_client.py Controller)."""

    def __init__(self, ip: str, port: int, timeout_s: Optional[float] = None):
        self._channel = grpc.insecure_channel(f"{ip}:{port}")
        self._stub = _Stub(self._channel)
        self._timeout_s = timeout_s
        self._rng = random.Random(0xC0)

    def send_relay_request(self, step: int, world_rank: int) -> Tuple[List[int], int]:
        resp = _call_with_deadline(
            self._stub.controller_fetch,
            pb.cont_request(step=step, world_rank=world_rank),
            f"controller_fetch(step={step}, rank={world_rank})",
            timeout_s=self._timeout_s,
            rng=self._rng,
        )
        return list(resp.active_list), resp.status

    def close(self) -> None:
        self._channel.close()


class Hooker:
    """Per-rank bucket-ready client (reference rpc_client.py Hooker)."""

    def __init__(self, ip: str, port: int, timeout_s: Optional[float] = None):
        self._channel = grpc.insecure_channel(f"{ip}:{port}")
        self._stub = _Stub(self._channel)
        self._timeout_s = timeout_s
        self._rng = random.Random(0x400C)

    def send_ready_request(self, step: int, world_rank: int) -> List[int]:
        resp = _call_with_deadline(
            self._stub.hook_fetch,
            pb.hook_request(step=step, world_rank=world_rank),
            f"hook_fetch(step={step}, rank={world_rank})",
            timeout_s=self._timeout_s,
            rng=self._rng,
        )
        return list(resp.active_list)

    def close(self) -> None:
        self._channel.close()


class HeartbeatClient:
    """Per-rank liveness lease (docs/SUPERVISOR.md).

    ``beat`` sends one heartbeat — optionally carrying the rank's recent
    step walltime, the slow-rank rule's evidence — and returns the
    coordinator's ``(alive_list, worldview_epoch)``, which is how a
    training process *observes* epoch bumps without owning any decision.
    ``run`` loops at ``period_s`` until stopped; an optional ``gate``
    (e.g. :class:`adapcc_tpu.supervisor.chaos.BeatChaos`) drops or delays
    individual beats at this exact seam, deterministically.
    """

    def __init__(
        self,
        ip: str,
        port: int,
        rank: int,
        timeout_s: Optional[float] = None,
    ):
        self._channel = grpc.insecure_channel(f"{ip}:{port}")
        self._stub = _Stub(self._channel)
        self.rank = int(rank)
        self._timeout_s = timeout_s
        self._rng = random.Random(0xBEA7 ^ self.rank)
        self.seq = 0

    def beat(self, median_s: Optional[float] = None) -> Tuple[List[int], int]:
        self.seq += 1
        median_us = 0
        if median_s is not None:
            if median_s <= 0:
                raise ValueError(f"median_s must be > 0, got {median_s}")
            median_us = max(1, int(round(median_s * 1e6)))
        resp = _call_with_deadline(
            self._stub.heartbeat,
            pb.cont_request(step=median_us, world_rank=self.rank),
            f"heartbeat(rank={self.rank}, seq={self.seq})",
            timeout_s=self._timeout_s,
            rng=self._rng,
        )
        return list(resp.active_list), resp.status

    def run(
        self,
        period_s: float,
        stop_event,
        median_source: Optional[Callable[[], Optional[float]]] = None,
        gate=None,
    ) -> None:
        """Beat every ``period_s`` until ``stop_event`` is set.  A beat
        the coordinator cannot take (unavailable within the deadline) is
        dropped and the loop continues — a rank must keep *trying* to
        lease through a control-plane blip, not die of one."""
        if period_s <= 0:
            raise ValueError(f"period_s must be > 0, got {period_s}")
        while not stop_event.is_set():
            send, delay = (True, 0.0)
            if gate is not None:
                send, delay = gate.gate(self.rank, self.seq + 1)
            if delay > 0 and stop_event.wait(delay):
                return
            if send:
                try:
                    self.beat(
                        median_source() if median_source is not None else None
                    )
                except grpc.RpcError:
                    pass  # keep leasing; silence is the supervisor's signal
            else:
                self.seq += 1  # a dropped beat still consumes its slot
            if stop_event.wait(period_s):
                return

    def close(self) -> None:
        self._channel.close()
