"""gRPC transport for the coordinator (wire-compatible with the reference).

Service/message names, field numbers, and RPC semantics match the reference's
``coordinator.proto`` (proto/protobuf/coordinator.proto:20-43) so a reference
client could talk to this server.  The Python gRPC *stubs* are hand-written
over the protoc-generated message classes because the image ships protoc but
not the grpc codegen plugin.

Client classes mirror the reference's (proto/rpc_client.py): ``Controller``
sends per-step relay/heartbeat requests, ``Hooker`` sends bucket-ready
requests.
"""

from __future__ import annotations

from concurrent import futures
from typing import List, Optional, Tuple

import grpc

from adapcc_tpu.coordinator.logic import CoordinatorLogic, CoordinatorShutdown
from adapcc_tpu.coordinator.protocol import coordinator_pb2 as pb

_SERVICE = "coordinator.Coordinator"


class CoordinatorServer:
    """Hosts the decision logic on ``ip:port`` (rank 0 in the reference,
    commu.py:136-141)."""

    def __init__(
        self,
        world_size: int,
        ip: str = "127.0.0.1",
        port: int = 50051,
        logic: Optional[CoordinatorLogic] = None,
        max_workers: int = 16,
    ) -> None:
        self.logic = logic if logic is not None else CoordinatorLogic(world_size)
        self.address = f"{ip}:{port}"
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))
        handlers = {
            "controller_fetch": grpc.unary_unary_rpc_method_handler(
                self._controller_fetch,
                request_deserializer=pb.cont_request.FromString,
                response_serializer=pb.cont_response.SerializeToString,
            ),
            "hook_fetch": grpc.unary_unary_rpc_method_handler(
                self._hook_fetch,
                request_deserializer=pb.hook_request.FromString,
                response_serializer=pb.hook_response.SerializeToString,
            ),
        }
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(_SERVICE, handlers),)
        )
        self._port = self._server.add_insecure_port(self.address)

    @property
    def port(self) -> int:
        return self._port

    def start(self) -> "CoordinatorServer":
        self._server.start()
        return self

    def stop(self, grace: Optional[float] = 0.5) -> None:
        """Drain in-flight waiters, then stop the transport.

        ``logic.shutdown()`` wakes every RPC handler blocked on the
        condition variable with an explicit sentinel (turned into an
        UNAVAILABLE abort below), so a worker parked in
        ``send_ready_request`` unblocks with a clean error instead of
        hanging until its channel times out long after the server is gone.
        """
        self.logic.shutdown()
        self._server.stop(grace)

    # -- rpc handlers ----------------------------------------------------------

    def _controller_fetch(self, request, context):
        try:
            active, status = self.logic.controller_arrive(request.step, request.world_rank)
        except CoordinatorShutdown:
            context.abort(grpc.StatusCode.UNAVAILABLE, "coordinator stopped")
        return pb.cont_response(active_list=active, status=status)

    def _hook_fetch(self, request, context):
        try:
            active = self.logic.hook_arrive(request.step, request.world_rank)
        except CoordinatorShutdown:
            context.abort(grpc.StatusCode.UNAVAILABLE, "coordinator stopped")
        return pb.hook_response(active_list=active)


class _Stub:
    def __init__(self, channel: grpc.Channel):
        self.controller_fetch = channel.unary_unary(
            f"/{_SERVICE}/controller_fetch",
            request_serializer=pb.cont_request.SerializeToString,
            response_deserializer=pb.cont_response.FromString,
        )
        self.hook_fetch = channel.unary_unary(
            f"/{_SERVICE}/hook_fetch",
            request_serializer=pb.hook_request.SerializeToString,
            response_deserializer=pb.hook_response.FromString,
        )


class Controller:
    """Per-rank relay/heartbeat client (reference rpc_client.py Controller)."""

    def __init__(self, ip: str, port: int):
        self._channel = grpc.insecure_channel(f"{ip}:{port}")
        self._stub = _Stub(self._channel)

    def send_relay_request(self, step: int, world_rank: int) -> Tuple[List[int], int]:
        resp = self._stub.controller_fetch(pb.cont_request(step=step, world_rank=world_rank))
        return list(resp.active_list), resp.status

    def close(self) -> None:
        self._channel.close()


class Hooker:
    """Per-rank bucket-ready client (reference rpc_client.py Hooker)."""

    def __init__(self, ip: str, port: int):
        self._channel = grpc.insecure_channel(f"{ip}:{port}")
        self._stub = _Stub(self._channel)

    def send_ready_request(self, step: int, world_rank: int) -> List[int]:
        resp = self._stub.hook_fetch(pb.hook_request(step=step, world_rank=world_rank))
        return list(resp.active_list)

    def close(self) -> None:
        self._channel.close()
