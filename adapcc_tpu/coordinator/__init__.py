"""Coordinator plane: relay negotiation + fault detection.

The centralized brain the reference runs as a gRPC service on world rank 0
(proto/rpc_server.py): per-step it decides which ranks participate in the
collective (rent-or-buy straggler waiting) and which ranks are considered
dead (heartbeat timeout).  The decision logic lives in
:mod:`adapcc_tpu.coordinator.logic`, transport-free and deterministic to
test; :mod:`adapcc_tpu.coordinator.service` wraps it in a gRPC service that
is wire-compatible with the reference's ``coordinator.proto``.
"""

from adapcc_tpu.coordinator.logic import CoordinatorLogic
from adapcc_tpu.coordinator.service import (
    CoordinatorServer,
    CoordinatorUnavailable,
    Controller,
    HeartbeatClient,
    Hooker,
    rpc_timeout_s,
)

__all__ = [
    "CoordinatorLogic",
    "CoordinatorServer",
    "CoordinatorUnavailable",
    "Controller",
    "HeartbeatClient",
    "Hooker",
    "rpc_timeout_s",
]
