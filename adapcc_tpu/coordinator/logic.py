"""Relay/fault decision logic (transport-free).

Implements the two decisions of the reference coordinator
(proto/rpc_server.py:48-108) as a plain thread-safe object:

- **hook phase** (``hook_arrive``): the first rank to finish its backward
  pass for a step becomes the *leader* and runs a rent-or-buy (ski-rental)
  wait: each 5 ms time slot spent waiting for more ranks accrues "rent";
  committing to a partial collective with the ``m`` ranks present costs
  "buy" = the m-rank collective scaled by ``((m-1)/m) / ((n-1)/n)`` plus the
  deferred full-world cost.  The leader stops waiting when renting longer
  than buying, when the hard relay threshold (0.1 s) is exceeded, or when
  everyone arrived — then freezes the step's **active list**
  (rpc_server.py:69-96).  Ranks arriving before the freeze join it; ranks
  arriving after are relays and just learn the frozen list.

- **controller phase** (``controller_arrive``): a per-step heartbeat
  barrier.  If not all ranks report within the fault timeout (10 s), the
  caller gets the list of ranks that *did* report with ``status=0`` — the
  alive set the collectives continue with instead of hanging
  (rpc_server.py:48-62, README "fault tolerance").

The reference implements both with spin-polling and queues; this uses one
condition variable so waits wake on arrival instead of on a poll tick.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict
from typing import Dict, List, Mapping, Optional, Tuple

from adapcc_tpu.primitives import (
    FAULT_TOLERANT_TIME_S,
    RELAY_THRESHOLD_S,
    TIME_SLOT_DURATION_S,
)


class CoordinatorShutdown(RuntimeError):
    """The coordinator is stopping: blocked waiters are drained with this
    instead of being left parked on the condition variable forever.  The
    gRPC layer turns it into an UNAVAILABLE abort, so a worker blocked on
    ``send_ready_request`` unblocks with a clean error when the
    coordinator dies (instead of hanging past the server's teardown)."""


class CoordinatorLogic:
    def __init__(
        self,
        world_size: int,
        relay_threshold: float = RELAY_THRESHOLD_S,
        time_slot: float = TIME_SLOT_DURATION_S,
        fault_timeout: float = FAULT_TOLERANT_TIME_S,
        accumulated_size: float = 100 * 8 / 1024,
        accumulated_bandwidth: Optional[float] = None,
        fault_plan: Optional[object] = None,
        heartbeat_timeout: Optional[float] = None,
        slow_factor: Optional[float] = None,
    ) -> None:
        from adapcc_tpu.elastic.worldview import (
            WorldView,
            heartbeat_timeout_s,
            slow_rank_factor,
        )

        self.world_size = world_size
        self.relay_threshold = relay_threshold
        self.time_slot = time_slot
        self.fault_timeout = fault_timeout
        #: heartbeat deadline for the controller barrier; defaults to the
        #: fault timeout, overridable per-deploy via
        #: ``ADAPCC_HEARTBEAT_TIMEOUT_S`` (docs/ELASTIC.md)
        self.heartbeat_timeout = heartbeat_timeout_s(
            heartbeat_timeout if heartbeat_timeout is not None else fault_timeout
        )
        #: slow-rank demotion threshold for :meth:`observe_step_medians`
        #: (``ADAPCC_SLOW_RANK_FACTOR`` overrides)
        self.slow_factor = slow_rank_factor(
            slow_factor if slow_factor is not None else 2.0
        )
        #: deterministic fault injection (adapcc_tpu.elastic.faults): down
        #: ranks' arrivals are dropped at this funnel and the barriers'
        #: expected counts shrink, so every failover path is exercisable on
        #: CPU with no hardware and no wall-clock timeout
        self.fault_plan = fault_plan
        # cost-model constants mirroring the reference's defaults
        # (rpc_server.py:41-46): a nominal accumulated gradient size and an
        # aggregate bandwidth proportional to the world size
        self.accumulated_size = accumulated_size
        self.accumulated_bandwidth = (
            accumulated_bandwidth if accumulated_bandwidth is not None else 50.0 * world_size
        )

        self._cond = threading.Condition()
        self._ready: Dict[int, List[int]] = defaultdict(list)
        self._frozen: Dict[int, List[int]] = {}
        self._heartbeats: Dict[int, List[int]] = defaultdict(list)
        # liveness-lease funnel (docs/SUPERVISOR.md): per-rank beat count,
        # last-beat monotonic timestamp, and the rank's self-reported
        # recent step walltime — the raw inputs the supervisor's liveness
        # state machine and slow-rank rule run over
        self._beat_counts: Dict[int, int] = {}
        self._beat_times: Dict[int, float] = {}
        self._beat_medians: Dict[int, float] = {}
        self._shutdown = False
        self._worldview = WorldView.full(world_size)
        # rejoin bookkeeping (docs/RECOVERY.md §3): bumped whenever a
        # previously-DEAD rank is re-admitted, so a replacement worker's
        # rendezvous (restore_newest_across_processes) keys its KV
        # namespace by the admit generation and never reads the keys of
        # the world that died
        self._restart_gen = 0
        # plan-fold bookkeeping: the newest step whose fault state has been
        # applied (late arrivals for older steps must not regress the view)
        # and the relay set the PLAN installed (so plan updates never
        # clobber relays the slow-rank rule demoted independently)
        self._plan_step = -1
        self._plan_relays: frozenset = frozenset()

    def calibrate(self, total_grad_bytes: float, link_bandwidth_gbps: float) -> None:
        """Replace the reference's hardcoded cost constants
        (rpc_server.py:41-46) with measured quantities: the gradient volume a
        step actually allreduces and the profiled per-link bandwidth.

        Sets the units so ``_initial_rent_cost()`` equals the ring-allreduce
        estimate ``2(n-1)/n · bytes / bw`` in SECONDS — the same clock the
        leader's wall-time rent accrues on, so the rent-or-buy comparison
        becomes dimensionally honest instead of heuristically scaled.
        Thread-safe; takes effect for the next freeze decision.
        """
        if total_grad_bytes <= 0 or link_bandwidth_gbps <= 0:
            raise ValueError(
                f"calibrate needs positive bytes/bandwidth, got "
                f"{total_grad_bytes}/{link_bandwidth_gbps}"
            )
        with self._cond:
            self.accumulated_size = total_grad_bytes / 1e9  # GB
            self.accumulated_bandwidth = self.world_size * link_bandwidth_gbps

    # -- hook phase ------------------------------------------------------------

    def _initial_rent_cost(self) -> float:
        n = self.world_size
        return 2 * (n - 1) * self.accumulated_size / self.accumulated_bandwidth

    def _buy_cost(self, num_ready: int) -> float:
        n, m = self.world_size, num_ready
        ratio = ((m - 1) / m) / ((n - 1) / n)
        return self._initial_rent_cost() * ratio + n * self.accumulated_size / self.accumulated_bandwidth

    def _check_shutdown_locked(self) -> None:
        if self._shutdown:
            raise CoordinatorShutdown("coordinator stopped")

    def _plan_down_locked(self, step: int) -> frozenset:
        """Injected-dead ranks at ``step`` (empty without a fault plan).
        Folding the plan into the world picture happens here — the single
        funnel every arrival passes through — so detection is deterministic
        and the WorldView epoch advances exactly when membership changes.

        The fold is MONOTONE in step: a relay worker landing its arrival
        for an older step (the rent-or-buy design explicitly allows that)
        replays that step's barrier but must not regress the world picture
        to the older fault state.  Plan-installed relays are tracked
        separately so applying the plan never clobbers demotions the
        slow-rank rule (:meth:`observe_step_medians`) installed on its own.
        """
        if self.fault_plan is None:
            return frozenset()
        state = self.fault_plan.state_at(step)
        if step >= self._plan_step:
            self._plan_step = step
            plan_slow = frozenset(state.slow_map)
            kept = (self._worldview.relays - self._plan_relays) | plan_slow
            self._plan_relays = plan_slow
            self._worldview = self._worldview.with_alive(
                frozenset(range(self.world_size)) - state.down
            ).with_relays(kept)
        return state.down

    def hook_arrive(self, step: int, rank: int) -> List[int]:
        """Register ``rank`` as ready for ``step``; block until the active
        list is frozen; return it.  Thread-safe, reentrant across steps.

        With a fault plan attached, a rank the plan marks down at this step
        is dropped at the funnel: its arrival never joins the ready list
        (the injected analog of the dead worker whose RPC never lands) and
        it learns the frozen list like a late relay.  The freeze barrier
        shrinks to the injected-alive count so the decision is reached
        deterministically, with no wall-clock timeout in the loop.
        """
        with self._cond:
            self._check_shutdown_locked()
            down = self._plan_down_locked(step)
            expected = self.world_size - len(down)
            if rank in down:
                # injected-dead: the arrival is dropped; wait out the freeze
                # like a relay so the caller still unblocks deterministically
                while step not in self._frozen:
                    self._check_shutdown_locked()
                    self._cond.wait(timeout=self.time_slot)
                return list(self._frozen[step])
            if step in self._frozen:
                # relay worker: the train has left, learn who's on it
                return list(self._frozen[step])

            if rank not in self._ready[step]:
                # idempotent arrival: the client retries a transport-level
                # UNAVAILABLE (service.py _call_with_deadline), and gRPC can
                # surface that AFTER the server processed the call (response
                # lost to a reset) — a duplicate must not inflate the barrier
                # count and freeze the step with a live rank missing
                self._ready[step].append(rank)
            self._cond.notify_all()

            if len(self._ready[step]) > 1:
                # active waiting worker: sleep until the leader freezes
                while step not in self._frozen:
                    self._check_shutdown_locked()
                    self._cond.wait(timeout=self.time_slot)
                return list(self._frozen[step])

            # leader: rent-or-buy wait loop.  Unlike the reference
            # (rpc_server.py:69-96, which can wait forever when no peer ever
            # arrives), a sole leader escapes after the fault timeout and
            # freezes its singleton list — dead peers are the controller
            # phase's problem, not a reason to hang the hook phase.  Rent is
            # wall time actually waited (a condition variable wakes early on
            # any notify — heartbeats, other steps' arrivals — so counting a
            # full slot per wakeup would inflate rent arbitrarily).
            # snapshot the cost constants once: calibrate() may retune them
            # mid-wait (trainer's first step races the same step's freeze),
            # and one decision must not mix two scales — the new constants
            # take effect at the NEXT step's freeze
            size, bandwidth = self.accumulated_size, self.accumulated_bandwidth
            n = self.world_size
            initial_rent = 2 * (n - 1) * size / bandwidth

            def buy_cost(m: int) -> float:
                ratio = ((m - 1) / m) / ((n - 1) / n)
                return initial_rent * ratio + n * size / bandwidth

            t0 = time.monotonic()
            while True:
                self._check_shutdown_locked()
                accumulated_rent = time.monotonic() - t0
                num_ready = len(self._ready[step])
                # the freeze barrier is the *injected-alive* count: a plan's
                # dead ranks can never arrive, so waiting for the full world
                # would always ride the rent clock to the relay threshold
                if num_ready == expected:
                    break
                if num_ready > 1:
                    if (
                        accumulated_rent + initial_rent >= buy_cost(num_ready)
                        or accumulated_rent > self.relay_threshold
                    ):
                        break
                elif accumulated_rent > self.fault_timeout:
                    break
                self._cond.wait(timeout=self.time_slot)

            self._frozen[step] = list(self._ready[step])
            self._cond.notify_all()
            return list(self._frozen[step])

    # -- controller phase ------------------------------------------------------

    def controller_arrive(self, step: int, rank: int) -> Tuple[List[int], int]:
        """Heartbeat for ``step``; block until all ranks heartbeat (then
        return the frozen active list, status 1) or the heartbeat timeout
        expires (then return the alive list, status 0).

        With a fault plan, injected-dead ranks never count toward the
        barrier and their own heartbeats are dropped, so the alive subset
        surfaces with status 0 *deterministically* — the CPU-testable twin
        of the wall-clock timeout path.  Either status-0 exit also records
        the detection in the :class:`WorldView` (alive set shrunk, epoch
        bumped), which is what downstream plan failover keys on.
        """
        with self._cond:
            self._check_shutdown_locked()
            down = self._plan_down_locked(step)
            if rank in down:
                # injected-dead rank: its heartbeat is dropped at the funnel;
                # it learns the alive picture like everyone else
                return sorted(set(range(self.world_size)) - down), 0
            if rank not in self._heartbeats[step]:
                # idempotent like hook_arrive: a retried arrival whose first
                # attempt's response was lost must not count twice toward
                # the barrier
                self._heartbeats[step].append(rank)
            self._cond.notify_all()

            expected = self.world_size - len(down)
            deadline = time.monotonic() + self.heartbeat_timeout
            while len(self._heartbeats[step]) < expected:
                self._check_shutdown_locked()
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    alive = list(self._heartbeats[step])
                    self._worldview = self._worldview.with_alive(alive)
                    return alive, 0
                self._cond.wait(timeout=remaining)

            if down:
                # every injected-alive rank reported; surface the alive
                # subset with status 0 without waiting out any clock
                alive = sorted(self._heartbeats[step])
                self._worldview = self._worldview.with_alive(alive)
                return alive, 0

            # everyone is alive; hand out the hook phase's decision
            while step not in self._frozen:
                self._check_shutdown_locked()
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    alive = list(self._heartbeats[step])
                    self._worldview = self._worldview.with_alive(alive)
                    return alive, 0
                self._cond.wait(timeout=remaining)
            # bounded history (the reference preallocates 1M steps instead,
            # rpc_server.py:29-34); participants are never 1000 steps apart
            if step % 100 == 0:
                self._forget_locked(step - 1000)
            return list(self._frozen[step]), 1

    # -- world view / elastic surface ------------------------------------------

    def worldview(self):
        """The coordinator's current :class:`~adapcc_tpu.elastic.worldview.
        WorldView` — alive set, relay set, epoch counter — the explicit
        output plan failover consumes (a bare active list cannot say
        *why* a rank is absent or whether anything changed)."""
        with self._cond:
            return self._worldview

    def observe_step_medians(self, medians: Mapping[int, float]):
        """Feed per-rank step medians (the DispatchTimer data already
        flowing through the tuner) into the slow-rank rule: ranks slower
        than ``slow_factor ×`` their peers' median are demoted to
        forwarding relays; ranks that caught back up are promoted.
        Returns the (possibly epoch-bumped) WorldView."""
        from adapcc_tpu.elastic.worldview import slow_ranks_from_medians

        slow = slow_ranks_from_medians(medians, factor=self.slow_factor)
        with self._cond:
            self._worldview = self._worldview.with_relays(slow)
            return self._worldview

    def heartbeat_arrive(
        self,
        rank: int,
        median_s: Optional[float] = None,
        now: Optional[float] = None,
    ) -> Tuple[List[int], int]:
        """The liveness-lease funnel (docs/SUPERVISOR.md): record that
        ``rank`` is alive *now* (and, optionally, its recent step
        walltime — the slow-rank rule's evidence, reported by the
        straggling process itself).  Returns ``(alive_list, epoch)`` so
        the beating process observes membership changes passively.

        Unlike the per-step barriers above, heartbeats never block: the
        call is a timestamp write plus a worldview read.  Detection —
        deciding that silence means death — is the supervisor's job
        (:mod:`adapcc_tpu.supervisor.liveness`), not this funnel's; both
        this funnel and the fault-plan injection feed the same
        :meth:`worldview`.
        """
        if not 0 <= rank < self.world_size:
            raise ValueError(
                f"rank {rank} outside world [0, {self.world_size})"
            )
        with self._cond:
            self._check_shutdown_locked()
            self._beat_counts[rank] = self._beat_counts.get(rank, 0) + 1
            self._beat_times[rank] = (
                time.monotonic() if now is None else float(now)
            )
            if median_s is not None and median_s > 0:
                self._beat_medians[rank] = float(median_s)
            wv = self._worldview
            return sorted(wv.alive), wv.epoch

    def heartbeat_snapshot(self) -> Dict[int, dict]:
        """Per-rank beat bookkeeping for the supervisor's sweep:
        ``{rank: {"beats", "ts", "median_s"}}`` — only ranks that ever
        beat appear (a rank silent since boot is the liveness table's
        initial-lease case, not this snapshot's)."""
        with self._cond:
            return {
                r: {
                    "beats": self._beat_counts[r],
                    "ts": self._beat_times[r],
                    "median_s": self._beat_medians.get(r),
                }
                for r in self._beat_counts
            }

    def mark_down(self, ranks) -> None:
        with self._cond:
            self._worldview = self._worldview.with_down(ranks)

    def mark_recovered(self, ranks) -> int:
        """Re-admit ``ranks``; returns the (possibly bumped) restart
        generation — bumped only when a genuinely DEAD rank came back, so
        a relay promotion never invalidates rendezvous keys.  The
        supervisor journals this generation in its ``admit`` decision and
        the replacement worker passes it to
        :func:`adapcc_tpu.checkpoint.restore_newest_across_processes`
        (``gen=``) for its catch-up restore."""
        with self._cond:
            was_dead = frozenset(int(r) for r in ranks) & self._worldview.dead
            self._worldview = self._worldview.with_recovered(ranks)
            if was_dead:
                self._restart_gen += 1
            return self._restart_gen

    @property
    def restart_generation(self) -> int:
        with self._cond:
            return self._restart_gen

    def seed_restart_generation(self, gen: int) -> None:
        """Fast-forward the admit counter to at least ``gen`` — the
        supervisor's journal replay calls this with the highest journaled
        ``admit`` generation, so a restarted supervisor can never hand a
        new rejoin a generation (and thus a rendezvous namespace) an
        earlier rejoin already used."""
        with self._cond:
            self._restart_gen = max(self._restart_gen, int(gen))

    def set_relays(self, ranks) -> None:
        """Replace the relay set wholesale — the supervisor's demotion
        actuator, merging its two slow-rank evidence streams (reported
        step medians, injected ``slow`` events) into one target."""
        with self._cond:
            self._worldview = self._worldview.with_relays(ranks)

    def restore_worldview(self, alive, relays, epoch: int):
        """Impose a journald world picture (supervisor restart replay,
        docs/SUPERVISOR.md §4).  Refuses to regress: a live view that
        moved past the journal's epoch while the supervisor was down
        stays — replay must reconstruct history, never rewrite it."""
        from adapcc_tpu.elastic.worldview import WorldView

        with self._cond:
            if int(epoch) >= self._worldview.epoch:
                self._worldview = WorldView(
                    world_size=self.world_size,
                    alive=frozenset(int(r) for r in alive),
                    relays=frozenset(int(r) for r in relays),
                    epoch=int(epoch),
                )
            return self._worldview

    def shutdown(self) -> None:
        """Drain every blocked waiter with :class:`CoordinatorShutdown`
        (the explicit sentinel ``CoordinatorServer.stop`` fires before
        tearing the gRPC server down)."""
        with self._cond:
            self._shutdown = True
            self._cond.notify_all()

    # -- introspection / GC ----------------------------------------------------

    def active_list(self, step: int) -> Optional[List[int]]:
        with self._cond:
            frozen = self._frozen.get(step)
            return list(frozen) if frozen is not None else None

    def forget_steps_before(self, step: int) -> None:
        """Drop per-step state older than ``step`` (the reference
        preallocates a dict of 1M steps instead, rpc_server.py:29-34)."""
        with self._cond:
            self._forget_locked(step)

    def _forget_locked(self, step: int) -> None:
        for d in (self._ready, self._frozen, self._heartbeats):
            for s in [s for s in d if s < step]:
                del d[s]
