"""Generated protobuf messages for the coordinator protocol.

``coordinator_pb2.py`` is generated from ``coordinator.proto``; regenerate
with ``protoc --python_out=. coordinator.proto`` in this directory.
"""
