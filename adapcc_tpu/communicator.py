"""Communicator: the control-plane orchestrator (reference CudaCommu analog).

Owns the detect → profile → synthesize → execute workflow that the reference
spreads across ctypes calls into ``communicator.so`` plus scp file fan-out
(commu.py:301-352).  Here every stage is in-process: detection reads device
metadata, profiling runs timed probe collectives, synthesis emits the
strategy XML, and "transmission contexts" are compiled collective programs
held by a :class:`CollectiveEngine`.

Lifecycle parity (reference commu.py / run.cu):

- ``init_threads(DETECT)`` / ``exit_threads(DETECT)`` — detect topology, dump
  per-host XML shards, merge into the logical graph.
- ``init_threads(PROFILE)`` / ``exit_threads(PROFILE)`` — probe the mesh,
  dump/gather lat+bw matrices, synthesize + persist the strategy
  (``_synthesis_strategy``, commu.py:272-278).
- ``init_threads(<collective>)`` — build the engine from the strategy file
  (the analog of ``bootstrapFromXMl`` spawning tree threads).
- ``exit_threads(<collective>)`` / ``clear()`` — drop compiled programs.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from adapcc_tpu.comm.engine import CollectiveEngine
from adapcc_tpu.comm.mesh import build_world_mesh, mesh_ip_table
from adapcc_tpu.config import CommArgs
from adapcc_tpu.primitives import (
    ALLGATHER,
    ALLREDUCE,
    ALLTOALL,
    BOARDCAST,
    DETECT,
    PROFILE,
    REDUCE,
    REDUCESCATTER,
    ReduceOp,
)
from adapcc_tpu.strategy.ir import Strategy
from adapcc_tpu.strategy.synthesizer import Synthesizer
from adapcc_tpu.strategy.xml_io import parse_strategy_xml, read_ip_table, write_ip_table
from adapcc_tpu.topology.detect import (
    dump_detected_topology,
    gather_detect_graph,
)
from adapcc_tpu.topology.profile import NetworkProfiler, gather_topo_profile

# Profile-round counter for KV-store strategy dissemination keys.  Process-wide
# (not per-Communicator): reconstruct_topology builds a fresh Communicator each
# cycle, and a per-instance counter would reuse round keys, handing workers the
# stale previous-round strategy.  Every process executes the same number of
# PROFILE exits, so the counter stays in lockstep across the job; elastic
# restarts (which relaunch the whole world and reset the counter) are isolated
# by the supervisor's ADAPCC_RESTART_GEN in the key prefix.
_profile_round_counter = iter(range(1 << 62))


def _strategy_round_key() -> str:
    gen = os.environ.get("ADAPCC_RESTART_GEN", "0")
    return f"adapcc/strategy/g{gen}@r{next(_profile_round_counter)}"

_COLLECTIVE_PRIMS = (ALLREDUCE, REDUCE, BOARDCAST, ALLGATHER, ALLTOALL, REDUCESCATTER)

#: bounded retry for collectives that race a plan failover: a dispatch
#: issued against a dead epoch (the coordinator advanced the WorldView and
#: the engine hot-swapped plans) raises EpochMismatch; the Communicator
#: adopts the engine's current epoch and re-issues after an exponential
#: backoff.  Exhausting the budget re-raises — a world churning faster
#: than the retry budget is an operator problem, not something to spin on.
EPOCH_RETRY_MAX = 3
EPOCH_RETRY_BACKOFF_S = 0.02


class Communicator:
    """One communication world: mesh + artifacts + compiled engines."""

    def __init__(self, args: CommArgs, mesh: Optional[Mesh] = None, world_size: Optional[int] = None):
        self.args = args
        self.mesh = mesh if mesh is not None else build_world_mesh(world_size)
        self.world_size = int(self.mesh.devices.size)
        self.axis_name = self.mesh.axis_names[0]
        self.chunk_bytes = args.default_chunk_bytes

        from adapcc_tpu.comm.two_level import is_two_level

        os.makedirs(args.topology_dir, exist_ok=True)
        ip_table_path = os.path.join(args.topology_dir, "ip_table.txt")
        self.ip_table = None
        # a two-level mesh's host analog IS the slice row — a pre-existing
        # table (launcher-written real IPs, or a prior flat-mesh run in the
        # same dir) would misalign the synthesizer's host groups with the
        # DCN×ICI execution split, so the mesh always wins there
        if os.path.exists(ip_table_path) and not is_two_level(self.mesh):
            table = read_ip_table(ip_table_path)
            if len(table) == self.world_size:
                self.ip_table = table
        if self.ip_table is None:
            # missing/stale (wrong world size) or two-level: derive from mesh.
            # Persist only when no artifact exists — a two-level run must not
            # clobber a launcher-written real-IP table that a later flat run
            # in the same dir would then mistake for host identities
            self.ip_table = mesh_ip_table(self.mesh)
            if not os.path.exists(ip_table_path):
                write_ip_table(self.ip_table, ip_table_path)

        self.synthesizer = Synthesizer(args.strategy_file, self.ip_table, policy=args.policy)
        # measurement-driven plan autotuner (adapcc_tpu/tuner): owned here so
        # every engine this communicator builds shares one database view and
        # one hysteresis state.  Fingerprinted with the ip table — a tuning
        # median from one host layout must not rank plans for another.  The
        # database lands next to the other topology artifacts unless
        # ADAPCC_TUNER_DB points elsewhere; ADAPCC_TUNER gates whether any
        # dispatch consults or feeds it (off = this is inert state).
        from adapcc_tpu.tuner import TUNER_DB_ENV, CollectiveTuner
        from adapcc_tpu.tuner.db import topology_fingerprint

        dev = next(iter(self.mesh.devices.flat))
        self.tuner = CollectiveTuner(
            world=self.world_size,
            topology=topology_fingerprint(
                self.world_size,
                {r: ip for r, ip in enumerate(self.ip_table)},
                platform=f"{getattr(dev, 'platform', '?')}:"
                f"{getattr(dev, 'device_kind', '?')}",
            ),
            db_path=(
                None  # let ADAPCC_TUNER_DB win
                if os.environ.get(TUNER_DB_ENV)
                else os.path.join(args.topology_dir, "tuning.jsonl")
            ),
        )
        self._engines: Dict[int, CollectiveEngine] = {}
        self._strategy: Optional[Strategy] = None
        self._profiler: Optional[NetworkProfiler] = None

        # coordinator plane (reference commu.py:81-94,143-170)
        self.fault_worker_list: List[int] = []
        self.coordinator_unreachable = False
        self.process_rank = 0
        self.num_processes = 1
        self._coordinator_server = None
        self._coordinator_addr = None
        self._controller = None
        self._hooker = None
        self._controller_thread = None
        self._heartbeat_client = None
        self._heartbeat_thread = None
        self._heartbeat_stop = None
        self._step_queue = None
        self._active_by_step: Dict[int, List[int]] = {}
        # per-step negotiate() round-trip cost (reference instruments its
        # hook with rpc latency prints + latency_0.0.txt, commu.py:37,387-394)
        self.rpc_latencies: List[tuple] = []  # (step, seconds)
        self.metrics = None  # optional MetricsRegistry; timings under "negotiate"

    # -- lifecycle -------------------------------------------------------------

    def init_threads(self, prim: int) -> None:
        if prim == DETECT:
            dump_detected_topology(self.mesh, self.args.topology_dir)
        elif prim == PROFILE:
            self._profiler = NetworkProfiler(self.mesh, self.axis_name)
            self._profiler.dump(self.args.topology_dir, rank=0)
        elif prim in _COLLECTIVE_PRIMS:
            self._engines[prim] = CollectiveEngine(
                self.mesh,
                self._load_strategy(),
                axis_name=self.axis_name,
                use_xla_fastpath=self.args.use_xla_fastpath,
                tuner=self.tuner,
            )
        else:
            raise ValueError(f"unknown primitive {prim}")

    def exit_threads(self, prim: int) -> None:
        if prim == DETECT:
            gather_detect_graph(self.args.topology_dir, self.args.logical_graph)
        elif prim == PROFILE:
            # Profile timings are host-measured and diverge across processes;
            # only process 0 synthesizes, and the strategy + chunk size travel
            # through the coordinator KV store so every process runs the
            # identical schedule (the analog of the reference's
            # master-synthesize + scp fan-out, commu.py:345-351).  Keys are
            # versioned per profile round: re-profiling republished under the
            # same key would hand workers the stale previous-round bytes.
            import jax

            round_key = _strategy_round_key()
            if jax.process_count() > 1 and jax.process_index() != 0:
                import base64

                # empty payload = master's synthesis was skipped (no profile
                # data); mirror the master and keep the current strategy
                payload = self._fetch_synthesis_value(round_key)
                if payload:
                    os.makedirs(
                        os.path.dirname(self.args.strategy_file) or ".", exist_ok=True
                    )
                    with open(self.args.strategy_file, "wb") as f:
                        f.write(base64.b64decode(payload))
                    self._strategy = None  # force reload from the fetched XML
                self.chunk_bytes = int(
                    self._fetch_synthesis_value(round_key + "/chunk_bytes")
                )
            else:
                self._synthesis_strategy()
                if jax.process_count() > 1:
                    from adapcc_tpu.launch.dispatcher import publish_file, publish_value

                    if os.path.exists(self.args.strategy_file):
                        publish_file(self.args.strategy_file, key=round_key)
                    else:
                        publish_value(round_key, "")
                    publish_value(round_key + "/chunk_bytes", str(self.chunk_bytes))
        elif prim in _COLLECTIVE_PRIMS:
            eng = self._engines.pop(prim, None)
            if eng is not None:
                eng.clear()

    def _fetch_synthesis_value(self, key: str) -> str:
        """KV fetch with a diagnosable failure: the master can die *between*
        its strategy and chunk_bytes publishes, in which case the worker's
        blocking get times out (or hands back nothing) — exactly the window
        the fault machinery exists for, so name it instead of surfacing an
        opaque timeout/``int(None)`` TypeError."""
        from adapcc_tpu.launch.dispatcher import fetch_value

        try:
            value = fetch_value(key, timeout_ms=self.args.kv_timeout_ms)
        except Exception as e:  # noqa: BLE001 — KV backend errors vary
            raise RuntimeError(
                f"master died during strategy synthesis (or is still "
                f"synthesizing — raise kv_timeout_ms — or the coordinator is "
                f"unreachable): no value published under {key!r} within "
                f"{self.args.kv_timeout_ms} ms"
            ) from e
        if value is None:
            raise RuntimeError(
                f"master died during strategy synthesis: KV store returned "
                f"nothing for {key!r}"
            )
        return value

    def clear(self) -> None:
        """Tear down contexts and the coordinator plane (reference clear
        stops the controller thread and the grpc server, commu.py:285-291)."""
        for eng in self._engines.values():
            eng.clear()
        self._engines.clear()
        self._strategy = None
        # re-synthesis follows: plans should be re-decided from the
        # database, not inherited from the torn-down world's incumbency
        self.tuner.reset()
        self.stop_coordinator()

    def _load_strategy(self) -> Strategy:
        if self._strategy is not None:
            return self._strategy
        if self.args.strategy_file and os.path.exists(self.args.strategy_file):
            self._strategy = parse_strategy_xml(self.args.strategy_file, self.chunk_bytes)
            # a persisted strategy fully determines ring execution: when the
            # XML carries its own chunk_bytes (emitted since the staged
            # pipeline landed), it overrides this communicator's default and
            # becomes the granularity every engine built from this strategy
            # hands to the ring kernels
            self.chunk_bytes = self._strategy.chunk_bytes
        else:
            # no strategy artifact: default ring over the mesh (TPU-idiomatic)
            ips = {r: ip for r, ip in enumerate(self.ip_table)}
            self._strategy = Strategy.ring(
                self.world_size, max(1, self.args.parallel_degree), ips
            )
        return self._strategy

    def _synthesis_strategy(self) -> None:
        """Profile artifacts → strategy XML + chunk size
        (reference ``_synthesis_strategy``, commu.py:272-278)."""
        lat, bw = gather_topo_profile(self.args.topology_dir, self.world_size)
        if not bw.any():  # profiling produced nothing (single device)
            return
        graph_local_rank0s = None
        if os.path.exists(self.args.logical_graph):
            from adapcc_tpu.strategy.xml_io import parse_logical_graph_xml

            graph_local_rank0s = parse_logical_graph_xml(self.args.logical_graph).local_rank0_list()
        self.chunk_bytes = self.synthesizer.generate_strategy(
            ALLREDUCE,
            self.args.parallel_degree,
            transmission_size=self.chunk_bytes,
            bandwidth_graph=bw,
            latency_graph=lat,
            local_rank0_list=graph_local_rank0s,
        )
        self._strategy = None  # force reload from the fresh XML

    # -- collectives (stacked [world, ...] single-controller view) -------------

    def _engine(self, prim: int) -> CollectiveEngine:
        if prim not in self._engines:
            raise RuntimeError(
                f"no context for primitive {prim}; call setup/init_threads first "
                "(reference requires initThreads before collectives, run.cu:103-127)"
            )
        return self._engines[prim]

    def _dispatch_with_epoch_retry(self, dispatch, epoch: Optional[int]):
        """Run ``dispatch(epoch)`` with bounded EpochMismatch retry.

        ``epoch=None`` (the default on every collective) skips the check —
        legacy callers never see a behavior change.  An elastic caller
        passes the epoch token it planned against; if the world moved on
        mid-flight, the mismatch is caught here, the engine's current
        epoch adopted, and the call re-issued after an exponential backoff
        — the collective continues with the swapped plan instead of
        hanging (or silently running the dead schedule).
        """
        import time as _time

        from adapcc_tpu.comm.engine import EpochMismatch

        attempt = 0
        while True:
            try:
                return dispatch(epoch)
            except EpochMismatch as e:
                if attempt >= EPOCH_RETRY_MAX:
                    raise
                if attempt > 0:
                    # the first retry goes immediately: the exception
                    # already carries the refreshed epoch, so it succeeds
                    # unless a SECOND swap raced in — only then back off
                    _time.sleep(EPOCH_RETRY_BACKOFF_S * (2 ** (attempt - 1)))
                attempt += 1
                epoch = e.current

    def all_reduce(
        self,
        tensor: jnp.ndarray,
        size: Optional[int] = None,
        chunk_bytes: Optional[int] = None,
        active_gpus: Optional[Sequence[int]] = None,
        op: ReduceOp = ReduceOp.SUM,
        epoch: Optional[int] = None,
    ) -> jnp.ndarray:
        """Reference signature ``all_reduce(tensor, size, chunk_bytes,
        active_gpus)`` (commu.py:360-365); size/chunk_bytes are accepted for
        parity only — shapes are static under jit, and chunking belongs to
        the compiled program (synthesis-time ``self.chunk_bytes``), so a
        per-call value is ignored rather than mutating communicator state.

        ``epoch`` is the elastic plan token (docs/ELASTIC.md): when given,
        a dispatch racing a plan failover retries against the refreshed
        epoch with bounded backoff instead of hanging."""
        if isinstance(size, ReduceOp) or isinstance(chunk_bytes, ReduceOp):
            raise TypeError(
                "pass op= by keyword: the reference-parity positional slots "
                "are (tensor, size, chunk_bytes, active_gpus), so a "
                "positional ReduceOp would silently land in one of them and "
                "the reduction would run as SUM"
            )
        return self._dispatch_with_epoch_retry(
            lambda ep: self._engine(ALLREDUCE).all_reduce(
                tensor, active_gpus=active_gpus, op=op, epoch=ep
            ),
            epoch,
        )

    def reduce(
        self,
        tensor: jnp.ndarray,
        size: Optional[int] = None,
        chunk_bytes: Optional[int] = None,
        active_gpus: Optional[Sequence[int]] = None,
        op: ReduceOp = ReduceOp.SUM,
        epoch: Optional[int] = None,
    ) -> jnp.ndarray:
        if isinstance(size, ReduceOp) or isinstance(chunk_bytes, ReduceOp):
            raise TypeError(
                "pass op= by keyword: a positional ReduceOp would silently "
                "land in 'size'/'chunk_bytes' and the reduction would run "
                "as SUM"
            )
        return self._dispatch_with_epoch_retry(
            lambda ep: self._engine(REDUCE).reduce(
                tensor, active_gpus=active_gpus, op=op, epoch=ep
            ),
            epoch,
        )

    def broadcast(
        self,
        tensor: jnp.ndarray,
        size: Optional[int] = None,
        chunk_bytes: Optional[int] = None,
        active_gpus: Optional[Sequence[int]] = None,
        epoch: Optional[int] = None,
    ) -> jnp.ndarray:
        return self._dispatch_with_epoch_retry(
            lambda ep: self._engine(BOARDCAST).broadcast(
                tensor, active_gpus=active_gpus, epoch=ep
            ),
            epoch,
        )

    #: reference C-ABI spelling (commu.py boardcast); the engine-level
    #: alias carries the one deprecation warning, this facade stays silent
    #: for AdapCC API parity (PARITY.md P1)
    boardcast = broadcast

    def alltoall(
        self,
        tensor: jnp.ndarray,
        size: Optional[int] = None,
        chunk_bytes: Optional[int] = None,
        active_gpus: Optional[Sequence[int]] = None,
        epoch: Optional[int] = None,
    ) -> jnp.ndarray:
        return self._dispatch_with_epoch_retry(
            lambda ep: self._engine(ALLTOALL).all_to_all(
                tensor, active_gpus=active_gpus, epoch=ep
            ),
            epoch,
        )

    def all_gather(
        self,
        tensor: jnp.ndarray,
        active_gpus: Optional[Sequence[int]] = None,
        epoch: Optional[int] = None,
    ) -> jnp.ndarray:
        return self._dispatch_with_epoch_retry(
            lambda ep: self._engine(ALLGATHER).all_gather(
                tensor, active_gpus=active_gpus, epoch=ep
            ),
            epoch,
        )

    def reduce_scatter(
        self,
        tensor: jnp.ndarray,
        *,
        active_gpus: Optional[Sequence[int]] = None,
        op: ReduceOp = ReduceOp.SUM,
        epoch: Optional[int] = None,
    ) -> jnp.ndarray:
        # keyword-only: ``active_gpus`` was inserted before the pre-existing
        # ``op`` parameter, so a legacy positional ``reduce_scatter(t,
        # ReduceOp.AVG)`` would silently bind the enum to active_gpus; now it
        # fails at the call site instead (ADVICE r5)
        return self._dispatch_with_epoch_retry(
            lambda ep: self._engine(REDUCESCATTER).reduce_scatter(
                tensor, active_gpus=active_gpus, op=op, epoch=ep
            ),
            epoch,
        )

    # -- coordinator plane -----------------------------------------------------

    def enable_coordinator(
        self,
        is_master: bool = True,
        process_rank: int = 0,
        num_processes: Optional[int] = None,
        ip: str = "127.0.0.1",
        port: Optional[int] = None,
    ) -> None:
        """Start the relay/fault coordinator plane.

        In the reference, world rank 0 hosts the gRPC Coordinator and every
        rank runs a controller thread plus Controller/Hooker stubs
        (commu.py:81-94,136-141).  Here the participants are *processes*
        (hosts), since one JAX process drives all its local chips.
        """
        import queue as _queue
        import threading

        from adapcc_tpu.coordinator import Controller, CoordinatorServer, Hooker

        port = port if port is not None else self.args.port
        self.num_processes = num_processes if num_processes is not None else 1
        self.process_rank = process_rank
        if is_master:
            self._coordinator_server = CoordinatorServer(self.num_processes, ip=ip, port=port).start()
            port = self._coordinator_server.port  # resolves port=0 to the bound one
        self._coordinator_addr = (ip, port)
        self._controller = Controller(ip, port)
        self._hooker = Hooker(ip, port)
        self._step_queue = _queue.Queue()
        self._controller_thread = threading.Thread(target=self._controller_loop, daemon=True)
        self._controller_thread.start()

    def start_heartbeat(
        self,
        period_s: float = 1.0,
        median_source=None,
        gate=None,
    ) -> None:
        """Lease liveness to the supervisor daemon (docs/SUPERVISOR.md):
        a background thread beats this process's rank through the
        coordinator's heartbeat RPC every ``period_s``, optionally
        carrying the recent step walltime ``median_source`` reports (the
        slow-rank rule's evidence).  Requires :meth:`enable_coordinator`
        first; idempotent per enable cycle."""
        from adapcc_tpu.coordinator import HeartbeatClient

        if getattr(self, "_coordinator_addr", None) is None:
            raise RuntimeError(
                "start_heartbeat needs enable_coordinator first (the "
                "heartbeat leases through the coordinator channel)"
            )
        if getattr(self, "_heartbeat_thread", None) is not None:
            return
        import threading

        ip, port = self._coordinator_addr
        self._heartbeat_client = HeartbeatClient(ip, port, self.process_rank)
        self._heartbeat_stop = threading.Event()
        self._heartbeat_thread = threading.Thread(
            target=self._heartbeat_client.run,
            args=(period_s, self._heartbeat_stop),
            kwargs={"median_source": median_source, "gate": gate},
            name="adapcc-heartbeat",
            daemon=True,
        )
        self._heartbeat_thread.start()

    def supervisor(self, prim: int = ALLREDUCE, **kwargs):
        """An autonomous :class:`~adapcc_tpu.supervisor.Supervisor` over
        this world's seams: the ``prim`` engine, a chip-granular
        coordinator logic, and a journal beside the other topology
        artifacts unless overridden (docs/SUPERVISOR.md).

        The supervisor's world is the CHIP world (the engine's): when the
        in-process coordinator server runs at the same granularity (one
        process per chip — the chaos-drill shape), its logic is shared so
        real heartbeats feed the daemon; a process-granular server (one
        process driving many chips) keeps its own world and the daemon
        gets a standalone chip-world logic — its detection then rides the
        fault-plan feed and any chip-granular heartbeats wired directly.
        """
        from adapcc_tpu.supervisor import Supervisor

        engine = kwargs.pop("engine", None) or self._engine(prim)
        logic = kwargs.pop("logic", None)
        if logic is None:
            if (
                self._coordinator_server is not None
                and self._coordinator_server.logic.world_size
                == engine.world_size
            ):
                logic = self._coordinator_server.logic
            else:
                from adapcc_tpu.coordinator import CoordinatorLogic

                logic = CoordinatorLogic(engine.world_size)
        kwargs.setdefault("metrics", self.metrics)
        kwargs.setdefault(
            "journal_path",
            os.path.join(self.args.topology_dir, "supervisor.journal"),
        )
        if "cache" not in kwargs:
            # pre-rank every plausible shrink so the daemon's failover is
            # a dispatch-time cache-key switch, not a cold re-plan
            from adapcc_tpu.elastic import StandbyPlanCache

            cache = StandbyPlanCache(engine)
            cache.build()
            kwargs["cache"] = cache
        return Supervisor(logic, engine=engine, **kwargs)

    def calibrate_coordinator(self, total_grad_bytes: float) -> bool:
        """Feed measured quantities into the rent-or-buy cost model: the
        caller's gradient volume plus this world's *profiled* mean link
        bandwidth (the matrices gathered during the bootstrap).  Replaces
        the reference coordinator's hardcoded constants
        (rpc_server.py:41-46).  The logic's world is the PROCESS count —
        the rent-or-buy warps the inter-process collective, so the cost
        model is scaled to that world.  Master-process only (the decision
        logic lives with the server); returns False when there is no
        in-process server or no usable profile — callers treat that as
        "stay on the defaults", not an error.
        """
        if self._coordinator_server is None:
            return False
        lat, bw = gather_topo_profile(self.args.topology_dir, self.world_size)
        # the rent-or-buy prices the INTER-process collective: averaging in
        # fast intra-process ICI links would inflate the estimate ~(ici/dcn)x
        # and make the leader commit to partial sets almost immediately
        ips = np.asarray(self.ip_table)
        inter = ips[:, None] != ips[None, :]
        links = bw[(bw > 0) & inter]
        if links.size == 0:
            # single-process world: no inter-process links exist; fall back
            # to the overall off-diagonal mean (the model is near-degenerate
            # at n=1 processes anyway — sole-leader path)
            links = bw[(bw > 0) & ~np.eye(self.world_size, dtype=bool)]
        if links.size == 0:
            return False
        self._coordinator_server.logic.calibrate(
            total_grad_bytes, float(links.mean())
        )
        return True

    @property
    def _controller_alive(self) -> bool:
        return self._controller_thread is not None and self._controller_thread.is_alive()

    def _controller_loop(self) -> None:
        """Background heartbeat consumer (reference controller thread,
        commu.py:143-170): one relay request per training step; a status-0
        response records the dead ranks and stops the thread.  RPC failures
        (master gone, channel closed during shutdown) also stop the thread —
        silently losing fault detection would be worse than reporting the
        master unreachable."""
        import grpc as _grpc

        while True:
            step = self._step_queue.get()
            if step is None:
                return
            try:
                active, status = self._controller.send_relay_request(step, self.process_rank)
            except _grpc.RpcError as e:  # noqa: PERF203
                if e.code() is not _grpc.StatusCode.CANCELLED:
                    print(f"[adapcc] controller RPC failed ({e.code()}); fault detection stopped")
                    self.coordinator_unreachable = True
                return
            if status == 0:
                self.fault_worker_list = sorted(set(range(self.num_processes)) - set(active))
                return
            self._active_by_step[step] = active
            # bounded history: long runs must not accumulate per-step state
            for old in [s for s in self._active_by_step if s < step - 100]:
                del self._active_by_step[old]

    def update_relay(self, step: int) -> None:
        """Kick the controller heartbeat for this step (reference
        commu.py:293-299; called once per training iteration).  A dead
        controller thread (fault detected / master unreachable) makes this a
        no-op instead of filling an unconsumed queue."""
        if self._step_queue is not None and self._controller_alive:
            self._step_queue.put(step)

    def hook_ready(self, step: int) -> List[int]:
        """First-bucket-ready negotiation: returns the frozen active list for
        this step (reference cuda_allreduce_hook → hook_fetch,
        commu.py:385-399).  If the coordinator is unreachable, training
        proceeds with every local participant active — the reference's
        continue-with-alive-subset stance (README "fault tolerance").

        The client call runs under the ``ADAPCC_RPC_TIMEOUT_S`` deadline
        with bounded jittered backoff; a dead coordinator surfaces as a
        :class:`~adapcc_tpu.coordinator.CoordinatorUnavailable` (a
        ``grpc.RpcError`` subclass, so it lands in the same handler)
        within the budget instead of blocking indefinitely."""
        if self._hooker is None:
            return list(range(self.world_size))
        import grpc as _grpc

        try:
            import time as _time

            t0 = _time.perf_counter()
            active = self._hooker.send_ready_request(step, self.process_rank)
            dt = _time.perf_counter() - t0
            self.rpc_latencies.append((step, dt))
            if len(self.rpc_latencies) > 100_000:  # bound long-run memory
                del self.rpc_latencies[: 50_000]
            if self.metrics is not None:
                self.metrics.observe("negotiate", dt)
            return active
        except _grpc.RpcError as e:
            if self.num_processes <= 1:
                # sole participant: falling back to "just me" cannot diverge
                if not self.coordinator_unreachable:
                    print(f"[adapcc] hook RPC failed ({e.code()}); proceeding without coordinator")
                    self.coordinator_unreachable = True
                return [self.process_rank]
            # multi-process: inventing an active set here would differ from
            # what peers got from the coordinator and silently diverge the
            # SPMD program (different masks/divisors per process) — surface it
            raise RuntimeError(
                "coordinator unreachable during hook negotiation; cannot pick an "
                "active set unilaterally in a multi-process world"
            ) from e

    def write_rpc_latency(self, path: Optional[str] = None) -> str:
        """Dump per-step negotiate() round-trip latencies, one float per
        line — the reference's ``proto/latency_0.0.txt`` artifact
        (commu.py:37,387-394 wrote ``format(rpc_end - rpc_start, 'f')``)."""
        if path is None:
            path = os.path.join(
                self.args.topology_dir, f"latency_{self.process_rank}.0.txt"
            )
        with open(path, "w") as f:
            for _, dt in self.rpc_latencies:
                f.write(format(dt, "f") + "\n")
        return path

    def relay_active_list(self, step: int) -> Optional[List[int]]:
        return self._active_by_step.get(step)

    def chips_of_processes(self, active_processes: Sequence[int]) -> List[int]:
        """Expand coordinator *process* ranks to the chip ranks they drive.

        The coordinator's participants are processes (one JAX process per
        host), while collectives run over chips; a straggling process demotes
        all of its chips to relays.
        """
        procs = set(active_processes)
        return [
            r
            for r, dev in enumerate(self.mesh.devices.flat)
            if getattr(dev, "process_index", 0) in procs
        ]

    def stop_coordinator(self) -> None:
        if self._step_queue is not None:
            self._step_queue.put(None)
        if self._controller_thread is not None:
            self._controller_thread.join(timeout=2)
            self._controller_thread = None
        if self._heartbeat_stop is not None:
            self._heartbeat_stop.set()
        if self._heartbeat_thread is not None:
            self._heartbeat_thread.join(timeout=2)
            self._heartbeat_thread = None
        for client in (self._controller, self._hooker, self._heartbeat_client):
            if client is not None:
                client.close()
        self._controller = self._hooker = self._heartbeat_client = None
        self._heartbeat_stop = None
        self._coordinator_addr = None
        if self._coordinator_server is not None:
            self._coordinator_server.stop()
            self._coordinator_server = None
        self._step_queue = None

    # -- online adaptation (docs/ADAPT.md) -------------------------------------

    def adaptation_controller(
        self, prim: int = ALLREDUCE, trainer=None, mode: Optional[str] = None,
        **kwargs,
    ):
        """Closed-loop online adaptation over this world's engine: an
        :class:`~adapcc_tpu.adapt.AdaptationController` wired to the
        communicator's own seams — the ``prim`` engine, the synthesizer
        (so re-ranked candidates come from the same policy pool the
        bootstrap used), the tuner's database (the passive measurement
        feed) and topology fingerprint, and the calibration artifact
        beside the other topology products.  ``ADAPCC_ADAPT`` gates the
        plane; ``mode`` is the env-unset default (the tuner's contract)."""
        from adapcc_tpu.adapt import AdaptationController

        engine = self._engine(prim)
        kwargs.setdefault("db", self.tuner.db)
        kwargs.setdefault("fingerprint", self.tuner.topology)
        kwargs.setdefault(
            "calibration_path",
            os.path.join(self.args.topology_dir, "calibration.json"),
        )
        kwargs.setdefault("parallel_degree", max(1, self.args.parallel_degree))
        return AdaptationController(
            engine, self.synthesizer, trainer=trainer, mode=mode, **kwargs
        )

    # -- introspection ---------------------------------------------------------

    @property
    def strategy(self) -> Strategy:
        return self._load_strategy()

    def active_contexts(self) -> List[int]:
        return sorted(self._engines)
