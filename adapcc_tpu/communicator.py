"""Communicator: the control-plane orchestrator (reference CudaCommu analog).

Owns the detect → profile → synthesize → execute workflow that the reference
spreads across ctypes calls into ``communicator.so`` plus scp file fan-out
(commu.py:301-352).  Here every stage is in-process: detection reads device
metadata, profiling runs timed probe collectives, synthesis emits the
strategy XML, and "transmission contexts" are compiled collective programs
held by a :class:`CollectiveEngine`.

Lifecycle parity (reference commu.py / run.cu):

- ``init_threads(DETECT)`` / ``exit_threads(DETECT)`` — detect topology, dump
  per-host XML shards, merge into the logical graph.
- ``init_threads(PROFILE)`` / ``exit_threads(PROFILE)`` — probe the mesh,
  dump/gather lat+bw matrices, synthesize + persist the strategy
  (``_synthesis_strategy``, commu.py:272-278).
- ``init_threads(<collective>)`` — build the engine from the strategy file
  (the analog of ``bootstrapFromXMl`` spawning tree threads).
- ``exit_threads(<collective>)`` / ``clear()`` — drop compiled programs.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from adapcc_tpu.comm.engine import CollectiveEngine
from adapcc_tpu.comm.mesh import RANKS_AXIS, build_world_mesh, mesh_ip_table
from adapcc_tpu.config import CommArgs
from adapcc_tpu.primitives import (
    ALLGATHER,
    ALLREDUCE,
    ALLTOALL,
    BOARDCAST,
    DETECT,
    PROFILE,
    REDUCE,
    REDUCESCATTER,
    ReduceOp,
)
from adapcc_tpu.strategy.ir import Strategy
from adapcc_tpu.strategy.synthesizer import Synthesizer
from adapcc_tpu.strategy.xml_io import parse_strategy_xml, read_ip_table, write_ip_table
from adapcc_tpu.topology.detect import (
    detect_topology,
    dump_detected_topology,
    gather_detect_graph,
)
from adapcc_tpu.topology.profile import NetworkProfiler, gather_topo_profile

_COLLECTIVE_PRIMS = (ALLREDUCE, REDUCE, BOARDCAST, ALLGATHER, ALLTOALL, REDUCESCATTER)


class Communicator:
    """One communication world: mesh + artifacts + compiled engines."""

    def __init__(self, args: CommArgs, mesh: Optional[Mesh] = None, world_size: Optional[int] = None):
        self.args = args
        self.mesh = mesh if mesh is not None else build_world_mesh(world_size)
        self.world_size = int(self.mesh.devices.size)
        self.axis_name = self.mesh.axis_names[0]
        self.chunk_bytes = args.default_chunk_bytes

        os.makedirs(args.topology_dir, exist_ok=True)
        ip_table_path = os.path.join(args.topology_dir, "ip_table.txt")
        self.ip_table = None
        if os.path.exists(ip_table_path):
            table = read_ip_table(ip_table_path)
            if len(table) == self.world_size:
                self.ip_table = table
        if self.ip_table is None:
            # missing or stale (wrong world size) artifact: re-derive from mesh
            self.ip_table = mesh_ip_table(self.mesh)
            write_ip_table(self.ip_table, ip_table_path)

        self.synthesizer = Synthesizer(args.strategy_file, self.ip_table, policy=args.policy)
        self._engines: Dict[int, CollectiveEngine] = {}
        self._strategy: Optional[Strategy] = None
        self._profiler: Optional[NetworkProfiler] = None
        self.fault_worker_list: List[int] = []

    # -- lifecycle -------------------------------------------------------------

    def init_threads(self, prim: int) -> None:
        if prim == DETECT:
            dump_detected_topology(self.mesh, self.args.topology_dir)
        elif prim == PROFILE:
            self._profiler = NetworkProfiler(self.mesh, self.axis_name)
            self._profiler.dump(self.args.topology_dir, rank=0)
        elif prim in _COLLECTIVE_PRIMS:
            self._engines[prim] = CollectiveEngine(
                self.mesh,
                self._load_strategy(),
                axis_name=self.axis_name,
                use_xla_fastpath=self.args.use_xla_fastpath,
            )
        else:
            raise ValueError(f"unknown primitive {prim}")

    def exit_threads(self, prim: int) -> None:
        if prim == DETECT:
            gather_detect_graph(self.args.topology_dir, self.args.logical_graph)
        elif prim == PROFILE:
            self._synthesis_strategy()
        elif prim in _COLLECTIVE_PRIMS:
            eng = self._engines.pop(prim, None)
            if eng is not None:
                eng.clear()

    def clear(self) -> None:
        for eng in self._engines.values():
            eng.clear()
        self._engines.clear()
        self._strategy = None

    def _load_strategy(self) -> Strategy:
        if self._strategy is not None:
            return self._strategy
        if self.args.strategy_file and os.path.exists(self.args.strategy_file):
            self._strategy = parse_strategy_xml(self.args.strategy_file, self.chunk_bytes)
        else:
            # no strategy artifact: default ring over the mesh (TPU-idiomatic)
            ips = {r: ip for r, ip in enumerate(self.ip_table)}
            self._strategy = Strategy.ring(
                self.world_size, max(1, self.args.parallel_degree), ips
            )
        return self._strategy

    def _synthesis_strategy(self) -> None:
        """Profile artifacts → strategy XML + chunk size
        (reference ``_synthesis_strategy``, commu.py:272-278)."""
        lat, bw = gather_topo_profile(self.args.topology_dir, self.world_size)
        if not bw.any():  # profiling produced nothing (single device)
            return
        graph_local_rank0s = None
        if os.path.exists(self.args.logical_graph):
            from adapcc_tpu.strategy.xml_io import parse_logical_graph_xml

            graph_local_rank0s = parse_logical_graph_xml(self.args.logical_graph).local_rank0_list()
        self.chunk_bytes = self.synthesizer.generate_strategy(
            ALLREDUCE,
            self.args.parallel_degree,
            transmission_size=self.chunk_bytes,
            bandwidth_graph=bw,
            latency_graph=lat,
            local_rank0_list=graph_local_rank0s,
        )
        self._strategy = None  # force reload from the fresh XML

    # -- collectives (stacked [world, ...] single-controller view) -------------

    def _engine(self, prim: int) -> CollectiveEngine:
        if prim not in self._engines:
            raise RuntimeError(
                f"no context for primitive {prim}; call setup/init_threads first "
                "(reference requires initThreads before collectives, run.cu:103-127)"
            )
        return self._engines[prim]

    def all_reduce(
        self,
        tensor: jnp.ndarray,
        size: Optional[int] = None,
        chunk_bytes: Optional[int] = None,
        active_gpus: Optional[Sequence[int]] = None,
        op: ReduceOp = ReduceOp.SUM,
    ) -> jnp.ndarray:
        """Reference signature ``all_reduce(tensor, size, chunk_bytes,
        active_gpus)`` (commu.py:360-365); size/chunk_bytes are accepted for
        parity only — shapes are static under jit, and chunking belongs to
        the compiled program (synthesis-time ``self.chunk_bytes``), so a
        per-call value is ignored rather than mutating communicator state."""
        return self._engine(ALLREDUCE).all_reduce(tensor, active_gpus=active_gpus, op=op)

    def reduce(
        self,
        tensor: jnp.ndarray,
        size: Optional[int] = None,
        chunk_bytes: Optional[int] = None,
        active_gpus: Optional[Sequence[int]] = None,
        op: ReduceOp = ReduceOp.SUM,
    ) -> jnp.ndarray:
        return self._engine(REDUCE).reduce(tensor, active_gpus=active_gpus, op=op)

    def boardcast(
        self, tensor: jnp.ndarray, size: Optional[int] = None, chunk_bytes: Optional[int] = None
    ) -> jnp.ndarray:
        return self._engine(BOARDCAST).boardcast(tensor)

    def alltoall(
        self, tensor: jnp.ndarray, size: Optional[int] = None, chunk_bytes: Optional[int] = None
    ) -> jnp.ndarray:
        return self._engine(ALLTOALL).all_to_all(tensor)

    def all_gather(self, tensor: jnp.ndarray) -> jnp.ndarray:
        return self._engine(ALLGATHER).all_gather(tensor)

    def reduce_scatter(self, tensor: jnp.ndarray, op: ReduceOp = ReduceOp.SUM) -> jnp.ndarray:
        return self._engine(REDUCESCATTER).reduce_scatter(tensor, op=op)

    # -- introspection ---------------------------------------------------------

    @property
    def strategy(self) -> Strategy:
        return self._load_strategy()

    def active_contexts(self) -> List[int]:
        return sorted(self._engines)
