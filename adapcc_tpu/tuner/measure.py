"""Timing harness: engine dispatches → tuning-database samples.

Two feeds produce samples:

- **live** — the engine calls :meth:`DispatchTimer.observe` with the
  walltime of each dispatch (``block_until_ready`` inclusive) whenever a
  tuner is attached and ``ADAPCC_TUNER`` is ``record`` or ``choose``.  The
  first observation per compiled-program cache key is discarded as warmup:
  it includes tracing + XLA compilation, which would poison the cell's
  median for every later steady-state dispatch.
- **offline** — :func:`replay_trace` re-reads a :class:`CollectiveTrace`
  (or a parsed ``track.txt``) whose events carry ``duration_s`` and turns
  them into database samples, so a run that only *recorded* can still seed
  the database for the next run's ``choose`` mode.

:func:`timed_call` is the standalone probe used by benchmarks: median-free
raw samples, warmup discarded, one ``block_until_ready`` per iteration.
"""

from __future__ import annotations

import time
from typing import Any, Hashable, Iterable, List, Optional, Set, Tuple, Union

from adapcc_tpu.tuner.db import (
    TuningDatabase,
    TuningKey,
    size_bucket,
)


def timed_call(fn, *args, warmup: int = 1, iters: int = 3) -> List[float]:
    """Walltime samples for ``fn(*args)``: ``warmup`` calls discarded (the
    compile), then ``iters`` timed calls, each blocked to completion —
    async dispatch must not let a measurement finish before the work does.
    """
    import jax

    if warmup < 0 or iters < 1:
        raise ValueError(f"need warmup >= 0 and iters >= 1, got {warmup}/{iters}")
    for _ in range(warmup):
        jax.block_until_ready(fn(*args))
    out: List[float] = []
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        out.append(time.perf_counter() - t0)
    return out


class DispatchTimer:
    """Warmup-aware funnel from live dispatches into the database.

    The engine hands it ``(key, cache_token, seconds)`` per dispatch; the
    first observation for each ``cache_token`` (the engine's compiled-
    program cache key) is dropped — that dispatch paid tracing + XLA
    compile, not the plan's steady-state cost.
    """

    def __init__(self, db: TuningDatabase) -> None:
        self.db = db
        self._warmed: Set[Hashable] = set()
        #: observations discarded as compile warmup (introspection/tests)
        self.discarded = 0
        #: observations recorded
        self.recorded = 0

    def observe(
        self, key: TuningKey, cache_token: Hashable, seconds: float
    ) -> bool:
        """Record one dispatch walltime; returns False when the sample was
        discarded as that program's compile warmup."""
        if cache_token not in self._warmed:
            self._warmed.add(cache_token)
            self.discarded += 1
            return False
        self.db.record(key, seconds)
        self.recorded += 1
        return True

    def reset(self) -> None:
        """Forget warmup state (engine ``clear()``: recompilation follows)."""
        self._warmed.clear()


# --------------------------------------------------------------------------- #
# offline feed: CollectiveTrace replay
# --------------------------------------------------------------------------- #

def _key_from_event(
    event: Any, world: int, topology: str
) -> Optional[TuningKey]:
    """TraceEvent → TuningKey, or None when the event carries no timing or
    is not a tunable dispatch (strategy/xla impls have no plan cell)."""
    extra = getattr(event, "extra", None) or {}
    if "duration_s" not in extra:
        return None
    impl = getattr(event, "impl", "")
    per_rank = int(extra.get("per_rank_bytes", 0))
    if per_rank <= 0:
        # stacked nbytes = world × per-rank payload
        per_rank = max(1, int(event.nbytes) // max(1, world))
    from adapcc_tpu.tuner.policy import NO_CHUNK, QUANT_PATH

    if impl.startswith("pallas_ring["):
        path = impl[len("pallas_ring["):-1]
        # fused codec dispatches spell the codec into the impl
        # ("pallas_ring[hbm-stream+int8]"); the extras carry it too
        wire = "off"
        if "+" in path:
            path, wire = path.split("+", 1)
        wire = str(extra.get("wire_dtype", wire))
        return TuningKey(
            primitive=event.primitive,
            size_bucket=size_bucket(per_rank),
            world=world,
            topology=topology,
            path=path,
            # vmem is one cell regardless of budget (the key vocabulary the
            # engine and the candidate grid share)
            chunk_bytes=(
                NO_CHUNK if path == "vmem"
                else int(extra.get("chunk_bytes", 0))
            ),
            wire_dtype=wire,
        )
    if impl.startswith("quant_ring["):
        return TuningKey(
            primitive=event.primitive,
            size_bucket=size_bucket(per_rank),
            world=world,
            topology=topology,
            path=QUANT_PATH,
            chunk_bytes=NO_CHUNK,
            wire_dtype=str(extra.get("wire_dtype", impl[len("quant_ring["):-1])),
        )
    from adapcc_tpu.tuner.policy import A2A_XLA_PATH, ALGO_PATHS, XLA_PATH

    if impl in ALGO_PATHS:
        # latency-plane dispatches (docs/LATENCY.md): the impl IS the
        # algorithm path — no chunk knob, fp32 wire
        return TuningKey(
            primitive=event.primitive,
            size_bucket=size_bucket(per_rank),
            world=world,
            topology=topology,
            path=impl,
            chunk_bytes=NO_CHUNK,
            wire_dtype="off",
        )
    if event.primitive == "allreduce" and impl == XLA_PATH:
        # the psum fastpath — the xla baseline cell all_reduce's
        # algorithm arbitration reads (only timed dispatches land here;
        # untimed xla events fall through to the caller's skip count)
        return TuningKey(
            primitive="allreduce",
            size_bucket=size_bucket(per_rank),
            world=world,
            topology=topology,
            path=XLA_PATH,
            chunk_bytes=NO_CHUNK,
            wire_dtype="off",
        )
    if event.primitive == "all_to_all" and impl in (A2A_XLA_PATH, "two_level"):
        return TuningKey(
            primitive="all_to_all",
            size_bucket=size_bucket(per_rank),
            world=world,
            topology=topology,
            path=impl,
            chunk_bytes=NO_CHUNK,
            wire_dtype="off",
        )
    return None


def replay_trace(
    trace: Union[Any, Iterable[Any]],
    db: TuningDatabase,
    world: int,
    topology: str,
) -> Tuple[int, int]:
    """Feed a recorded :class:`CollectiveTrace` (or an iterable of
    :class:`TraceEvent`, e.g. from ``parse_track_log``) into ``db``.

    Returns ``(ingested, skipped)``.  Skipped events are the ones with no
    ``duration_s`` (recorded under ``ADAPCC_TUNER=off``) or with an impl
    that has no plan cell (strategy/schedule dispatches; timed allreduce
    ``xla`` and latency-plane ``rd``/``tree`` events DO have cells) —
    counted, never silently vanished, so a replay that ingests nothing is
    diagnosable.
    """
    events = trace.events() if hasattr(trace, "events") else list(trace)
    ingested = skipped = 0
    for ev in events:
        key = _key_from_event(ev, world, topology)
        if key is None:
            skipped += 1
            continue
        db.record(key, float(ev.extra["duration_s"]), ts=float(ev.ts))
        ingested += 1
    return ingested, skipped
