"""Persistent tuning database: measured collective costs, keyed by plan cell.

The reference adapts from *measurements* taken on the live fabric (PAPER.md
step 2-3: profile, then choose), but its profile artifacts are link
matrices — they say what a wire costs, not what a *plan* costs.  This module
stores the missing layer: robust walltime statistics per executed plan cell

    (primitive, payload-size bucket, world, topology fingerprint,
     ring path, chunk_bytes, wire_dtype)

so the policy (:mod:`adapcc_tpu.tuner.policy`) can rank candidate plans by
what dispatches actually cost on *this* pod, not by the α-β prior alone.

Storage is append-only JSONL — one sample per line — because the writers
are concurrent: every process of a multi-host job appends to the same file
(or its own copy of it) without coordination, and a deterministic group-by
on load merges whatever interleaving the filesystem produced.  Corrupt
lines and records from other schema versions are *skipped with a loud
warning*, never silently dropped: a tuning database that quietly loses its
history would re-explore cells the pod already paid to measure.
"""

from __future__ import annotations

import hashlib
import json
import os
import sys
import time
from dataclasses import asdict, dataclass, field
from typing import Any, Dict, Iterable, List, Mapping, Optional, Tuple

#: bump when the record layout changes; mismatched records are skipped
#: loudly on load (an old database stays readable as "nothing measured")
SCHEMA_VERSION = 1

#: env override for the database path (default ``topology/tuning.jsonl``)
TUNER_DB_ENV = "ADAPCC_TUNER_DB"

DEFAULT_DB_PATH = os.path.join("topology", "tuning.jsonl")

#: samples retained per key after a load/merge — newest win, so a drifting
#: fabric (thermal, degraded link) ages out stale measurements
MAX_SAMPLES_PER_KEY = 128


def resolve_db_path(path: Optional[str] = None) -> str:
    """The database path in force: explicit argument > ``ADAPCC_TUNER_DB``
    env > the default artifact next to the other topology products."""
    if path is not None:
        return path
    env = os.environ.get(TUNER_DB_ENV)
    if env is not None and env.strip():
        return env.strip()
    return DEFAULT_DB_PATH


def size_bucket(nbytes: int) -> int:
    """Payload-size bucket: bytes rounded up to the next power of two.

    Measurements generalize across nearby payloads (a 12 MB and a 14 MB
    allreduce cost the same plan the same), but not across decades — so
    samples pool per power-of-two bucket, the granularity nccl-tests
    sweeps use.
    """
    n = max(1, int(nbytes))
    return 1 << (n - 1).bit_length()


def topology_fingerprint(
    world: int,
    ips: Optional[Mapping[int, str]] = None,
    platform: Optional[str] = None,
) -> str:
    """Stable fabric identity for tuning keys: world size + host layout +
    device platform/kind.  Measurements taken on one fabric must never rank
    plans for another (a v5e ICI median says nothing about a CPU interpret
    run), so the fingerprint is part of every key."""
    h = hashlib.sha256()
    h.update(str(int(world)).encode())
    if ips:
        h.update(repr(sorted((int(r), str(ip)) for r, ip in ips.items())).encode())
    if platform:
        h.update(str(platform).encode())
    return h.hexdigest()[:12]


def mesh_fingerprint(mesh: Any) -> str:
    """Fingerprint a live ``jax.sharding.Mesh``: device kind + platform +
    world (the engine-side analog of :func:`topology_fingerprint`)."""
    devs = list(mesh.devices.flat)
    first = devs[0]
    kind = f"{getattr(first, 'platform', '?')}:{getattr(first, 'device_kind', '?')}"
    return topology_fingerprint(len(devs), platform=kind)


@dataclass(frozen=True, order=True)
class TuningKey:
    """One plan cell: what ran, on what fabric, at what size."""

    primitive: str      #: "allreduce" | "reduce_scatter" | "ddp_step" | ...
    size_bucket: int    #: power-of-two per-rank payload bucket (bytes)
    world: int
    topology: str       #: fabric fingerprint (:func:`topology_fingerprint`)
    path: str           #: "vmem" | "hbm-stream" | "quant-ring" | "hook" | ...
    chunk_bytes: int    #: staging granularity; 0 where the path has none
    wire_dtype: str     #: codec registry name ("off" = payload dtype)

    def to_dict(self) -> Dict[str, Any]:
        return asdict(self)

    @classmethod
    def from_dict(cls, obj: Mapping[str, Any]) -> "TuningKey":
        return cls(
            primitive=str(obj["primitive"]),
            size_bucket=int(obj["size_bucket"]),
            world=int(obj["world"]),
            topology=str(obj["topology"]),
            path=str(obj["path"]),
            chunk_bytes=int(obj["chunk_bytes"]),
            wire_dtype=str(obj["wire_dtype"]),
        )


@dataclass(frozen=True)
class TuningStats:
    """Robust summary of one cell's samples: median + IQR, not mean + max —
    a single straggler-polluted dispatch must not poison the cell.

    ``p99_s`` is the nearest-rank 99th percentile over the cell's bounded
    sample window (the tuner-side reservoir, newest
    :data:`MAX_SAMPLES_PER_KEY`): the number the tail-aware objective
    (``ADAPCC_TUNER_OBJECTIVE=p99``, docs/TUNER.md §6) ranks cells by —
    a plan that wins the median but fattens the tail must lose there.
    """

    count: int
    median_s: float
    iqr_s: float
    min_s: float
    max_s: float
    p99_s: float

    def to_dict(self) -> Dict[str, Any]:
        return asdict(self)


def _robust_stats(samples: List[float]) -> TuningStats:
    from adapcc_tpu.utils.observability import nearest_rank_percentile

    xs = sorted(samples)

    def q(frac: float) -> float:
        return nearest_rank_percentile(xs, frac)

    return TuningStats(
        count=len(xs),
        median_s=q(0.5),
        iqr_s=q(0.75) - q(0.25),
        min_s=xs[0],
        max_s=xs[-1],
        p99_s=q(0.99),
    )


@dataclass
class _Cell:
    #: (ts, seconds) pairs; kept sorted on read, bounded to newest
    samples: List[Tuple[float, float]] = field(default_factory=list)


class TuningDatabase:
    """Schema-versioned JSONL store of per-plan-cell timing samples.

    - ``record()`` appends one line to the file immediately (append mode:
      concurrent processes interleave whole lines, which the deterministic
      merge on load handles), and updates the in-memory view.
    - ``load()`` re-reads the file, skipping corrupt / version-mismatched
      lines with a loud stderr warning and counting them in
      ``skipped_records``.
    - Per key, only the newest :data:`MAX_SAMPLES_PER_KEY` samples are
      retained, ordered by ``(ts, seconds)`` — a total order independent of
      append interleaving, so every process that loads the same lines sees
      the same statistics.
    """

    def __init__(self, path: Optional[str] = None, persist: bool = True) -> None:
        #: resolved artifact path (still meaningful when persist=False: it
        #: names where a later ``save()`` would land)
        self.path = resolve_db_path(path)
        #: persist=False keeps the db purely in-memory — the sim replay and
        #: unit tests must not write into the repo's topology/ artifacts
        self.persist = persist
        self._cells: Dict[TuningKey, _Cell] = {}
        self.skipped_records = 0
        # the on-disk history is parsed lazily, at the first query/record:
        # a Communicator always owns a tuner, but with ADAPCC_TUNER=off
        # nothing ever asks it anything — construction must not pay a full
        # JSONL parse of a long-lived pod's append-only history for that
        self._loaded = not (self.persist and os.path.exists(self.path))
        # one O_APPEND handle reused across records: record() sits on the
        # per-dispatch hot path, where per-sample makedirs+open+close would
        # be repeated filesystem syscalls for one JSONL line.  O_APPEND
        # writes of whole lines stay atomic for concurrent processes.
        self._append_fh = None

    def _ensure_loaded(self) -> None:
        if not self._loaded:
            self.load()

    # -- ingestion -------------------------------------------------------------

    def record(
        self, key: TuningKey, seconds: float, ts: Optional[float] = None
    ) -> None:
        """Add one timing sample and (when persisting) append it to disk."""
        self._ensure_loaded()
        s = float(seconds)
        if s < 0:
            raise ValueError(f"negative duration {s}; clocks do not run backwards")
        t = time.time() if ts is None else float(ts)
        self._insert(key, t, s)
        if self.persist:
            line = json.dumps(
                {"v": SCHEMA_VERSION, "key": key.to_dict(), "t_s": s, "ts": t},
                sort_keys=True,
            )
            if self._append_fh is None:
                d = os.path.dirname(self.path)
                if d:
                    os.makedirs(d, exist_ok=True)
                self._append_fh = open(self.path, "a")
            self._append_fh.write(line + "\n")
            self._append_fh.flush()  # other processes merge on their load

    def _insert(self, key: TuningKey, ts: float, seconds: float) -> None:
        cell = self._cells.get(key)
        if cell is None:
            cell = self._cells[key] = _Cell()
        cell.samples.append((ts, seconds))
        if len(cell.samples) > 2 * MAX_SAMPLES_PER_KEY:
            self._trim(cell)

    @staticmethod
    def _trim(cell: _Cell) -> None:
        cell.samples.sort()
        del cell.samples[:-MAX_SAMPLES_PER_KEY]

    # -- load / merge ----------------------------------------------------------

    def load(self, path: Optional[str] = None) -> int:
        """(Re)load from disk, merging concurrent appends deterministically.

        Returns the number of samples ingested.  Lines that fail to parse,
        lack required fields, or carry a different schema version are
        counted in ``skipped_records`` and reported ONCE per load with a
        loud stderr warning — never silently.
        """
        path = path if path is not None else self.path
        self._loaded = True
        self._cells.clear()
        self.skipped_records = 0
        loaded = 0
        bad: List[str] = []
        try:
            f = open(path)
        except FileNotFoundError:
            return 0
        with f:
            for lineno, line in enumerate(f, 1):
                line = line.strip()
                if not line:
                    continue
                try:
                    obj = json.loads(line)
                    version = int(obj["v"])
                    if version != SCHEMA_VERSION:
                        raise ValueError(
                            f"schema v{version} != v{SCHEMA_VERSION}"
                        )
                    key = TuningKey.from_dict(obj["key"])
                    self._insert(key, float(obj["ts"]), float(obj["t_s"]))
                    loaded += 1
                except (KeyError, TypeError, ValueError) as e:
                    self.skipped_records += 1
                    if len(bad) < 3:
                        bad.append(f"line {lineno}: {type(e).__name__}: {e}")
        if self.skipped_records:
            print(
                f"[adapcc.tuner] WARNING: skipped {self.skipped_records} "
                f"corrupt/version-mismatched record(s) in {path} "
                f"(first: {'; '.join(bad)})",
                file=sys.stderr,
                flush=True,
            )
        # deterministic merge: per key, sort by (ts, seconds) and keep the
        # newest window — any interleaving of the same appended lines
        # reaches the same state
        for cell in self._cells.values():
            self._trim(cell)
        return loaded

    def merge_from(self, other: "TuningDatabase") -> None:
        """Fold another database's samples in (e.g. per-process shards
        gathered to one artifact); same deterministic bound per key."""
        self._ensure_loaded()
        other._ensure_loaded()
        for key, cell in other._cells.items():
            for ts, s in cell.samples:
                self._insert(key, ts, s)
        for cell in self._cells.values():
            self._trim(cell)

    def save(self, path: Optional[str] = None) -> str:
        """Compact rewrite: one line per retained sample, sorted — the
        maintenance valve for databases grown by long append-only runs."""
        self._ensure_loaded()
        path = path if path is not None else self.path
        if path == self.path and self._append_fh is not None:
            # the compaction rewrite replaces the file the append handle
            # points at; drop it so the next record() reopens the new one
            self._append_fh.close()
            self._append_fh = None
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            for key in sorted(self._cells):
                cell = self._cells[key]
                for ts, s in sorted(cell.samples):
                    f.write(
                        json.dumps(
                            {
                                "v": SCHEMA_VERSION,
                                "key": key.to_dict(),
                                "t_s": s,
                                "ts": ts,
                            },
                            sort_keys=True,
                        )
                        + "\n"
                    )
        return path

    # -- queries ---------------------------------------------------------------

    def keys(self) -> List[TuningKey]:
        self._ensure_loaded()
        return sorted(self._cells)

    def count(self, key: TuningKey) -> int:
        self._ensure_loaded()
        cell = self._cells.get(key)
        return len(cell.samples) if cell else 0

    def samples(self, key: TuningKey) -> List[float]:
        self._ensure_loaded()
        cell = self._cells.get(key)
        if not cell:
            return []
        return [s for _, s in sorted(cell.samples)[-MAX_SAMPLES_PER_KEY:]]

    def timed_samples(self, key: TuningKey) -> List[Tuple[float, float]]:
        """``(ts, seconds)`` pairs in the same deterministic order/bound as
        :meth:`samples` — for consumers that must tell WHEN a sample was
        taken (the drift detector's post-swap watermark: evidence recorded
        under a retired plan must not re-fire against its successor)."""
        self._ensure_loaded()
        cell = self._cells.get(key)
        if not cell:
            return []
        return sorted(cell.samples)[-MAX_SAMPLES_PER_KEY:]

    def stats(self, key: TuningKey) -> Optional[TuningStats]:
        xs = self.samples(key)
        if not xs:
            return None
        return _robust_stats(xs)

    def snapshot(self) -> List[Dict[str, Any]]:
        """Artifact rows: one summary dict per key (benchmarks, docs)."""
        out = []
        for key in self.keys():
            stats = self.stats(key)
            assert stats is not None
            out.append({**key.to_dict(), **stats.to_dict()})
        return out

    def __len__(self) -> int:
        self._ensure_loaded()
        return len(self._cells)

    def __repr__(self) -> str:
        n = sum(len(c.samples) for c in self._cells.values())
        return (
            f"TuningDatabase(path={self.path!r}, keys={len(self._cells)}, "
            f"samples={n})"
        )


def ingest_iter(
    db: TuningDatabase, records: Iterable[Tuple[TuningKey, float, float]]
) -> int:
    """Bulk-insert ``(key, seconds, ts)`` tuples (offline replay helper)."""
    n = 0
    for key, seconds, ts in records:
        db.record(key, seconds, ts=ts)
        n += 1
    return n
