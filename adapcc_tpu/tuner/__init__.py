"""Measurement-driven collective plan autotuner.

Closes the adaptation loop PR 1-3 left open: the sim cost model ranks
candidate plans *a priori*, ``chunk_bytes`` and ``wire_dtype`` steer the
data plane — but nothing chose them from what dispatches actually cost on
this pod.  The tuner does:

- :mod:`adapcc_tpu.tuner.db` — persistent, schema-versioned JSONL database
  of robust per-plan-cell timing stats (``topology/tuning.jsonl``,
  ``ADAPCC_TUNER_DB`` overrides);
- :mod:`adapcc_tpu.tuner.measure` — walltime harness feeding it, live from
  engine dispatches or offline from a replayed :class:`CollectiveTrace`;
- :mod:`adapcc_tpu.tuner.policy` — epsilon-greedy selection with the sim
  model as prior, measured medians as posterior, and hysteresis so plans
  don't flap.

Global control: ``ADAPCC_TUNER=off|record|choose`` (malformed → loud
error).  ``record`` times dispatches into the database without changing
them; ``choose`` additionally lets the policy pick ``chunk_bytes`` /
``wire_dtype`` for dispatches that didn't pin them — under the standing
precedence **env > explicit arg > tuner > strategy** (docs/TUNER.md).
"""

from __future__ import annotations

import os
from typing import Hashable, Optional, Sequence

from adapcc_tpu.tuner.db import (
    DEFAULT_DB_PATH,
    SCHEMA_VERSION,
    TUNER_DB_ENV,
    TuningDatabase,
    TuningKey,
    TuningStats,
    mesh_fingerprint,
    resolve_db_path,
    size_bucket,
    topology_fingerprint,
)
from adapcc_tpu.tuner.measure import DispatchTimer, replay_trace, timed_call
from adapcc_tpu.tuner.policy import (
    DEFAULT_CHUNK_GRID,
    TUNER_OBJECTIVE_ENV,
    TUNER_OBJECTIVES,
    TunedPlan,
    TuningPolicy,
    resolve_tuner_objective,
)

#: global tuner mode env: off (default) | record | choose
TUNER_MODE_ENV = "ADAPCC_TUNER"

TUNER_MODES = ("off", "record", "choose")


def tuner_mode(explicit: Optional[str] = None) -> str:
    """The tuner mode in force: ``ADAPCC_TUNER`` env > the caller's
    explicit mode > "off".  A malformed value raises — a typo'd
    ``ADAPCC_TUNER=chose`` silently running untuned would invalidate the
    convergence run it was meant to drive (the ADAPCC_MERGE_ROUNDS
    policy)."""
    env = os.environ.get(TUNER_MODE_ENV)
    value = env if env is not None and env.strip() else explicit
    if value is None:
        return "off"
    mode = value.strip().lower()
    if mode not in TUNER_MODES:
        raise ValueError(
            f"{TUNER_MODE_ENV}={value!r}: expected one of {'|'.join(TUNER_MODES)}"
        )
    return mode


class CollectiveTuner:
    """One fabric's tuner: database + policy + live-dispatch timer.

    ``mode`` here is the *construction-time* default; the env var wins at
    every query so an operator can flip a running job's next engine build
    without code changes.  All heavy state (db load) happens once at
    construction; per-dispatch work is a dict lookup and, in record mode,
    one ``block_until_ready`` the measurement semantics require anyway.
    """

    def __init__(
        self,
        world: int,
        topology: str,
        db: Optional[TuningDatabase] = None,
        db_path: Optional[str] = None,
        mode: Optional[str] = None,
        chunk_grid: Sequence[int] = DEFAULT_CHUNK_GRID,
        wire_dtypes: Optional[Sequence[str]] = None,
        cost_model=None,
        policy: Optional[TuningPolicy] = None,
        timer: Optional[DispatchTimer] = None,
        **policy_kwargs,
    ) -> None:
        tuner_mode(mode)  # validate BOTH the env and the explicit mode now
        #: the construction-time default mode (None = env-or-off); the env
        #: always wins at query time
        self.explicit_mode = mode
        self.world = int(world)
        self.topology = topology
        self.db = db if db is not None else TuningDatabase(db_path)
        # an injected policy/timer (the with_mode view path) takes the slot
        # as-is; the grid/codec/cost kwargs configure only a fresh build
        self.policy = (
            policy
            if policy is not None
            else TuningPolicy(
                self.db,
                self.world,
                topology,
                chunk_grid=chunk_grid,
                wire_dtypes=wire_dtypes,
                cost_model=cost_model,
                **policy_kwargs,
            )
        )
        self.timer = timer if timer is not None else DispatchTimer(self.db)

    # -- mode ------------------------------------------------------------------

    @property
    def mode(self) -> str:
        return tuner_mode(self.explicit_mode)

    def with_mode(self, mode: str) -> "CollectiveTuner":
        """A view of THIS tuner with a different default mode: same
        database, same policy (hysteresis), same warmup timer — only the
        env-unset fallback changes.  An explicit opt-in surface (e.g.
        ``DDPTrainer(tune=True)``) uses this so its promise holds without
        ``ADAPCC_TUNER`` being exported, while the env keeps global
        override either way."""
        return CollectiveTuner(
            world=self.world, topology=self.topology, db=self.db, mode=mode,
            policy=self.policy, timer=self.timer,
        )

    @property
    def recording(self) -> bool:
        return self.mode in ("record", "choose")

    @property
    def choosing(self) -> bool:
        return self.mode == "choose"

    # -- the two verbs ---------------------------------------------------------

    def choose(
        self,
        primitive: str,
        nbytes: int,
        dtype: str = "float32",
        wire_dtypes: Optional[Sequence[str]] = None,
        overlap_modes: Optional[Sequence[str]] = None,
        algos: Optional[Sequence[str]] = None,
    ) -> TunedPlan:
        """Commit a plan for one dispatch (policy rules; see
        :class:`adapcc_tpu.tuner.policy.TuningPolicy`).  ``wire_dtypes``
        narrows the codec axis for configurations that cannot legally run
        every codec; ``overlap_modes`` narrows the ddp_step overlap axis
        the same way; ``algos`` narrows the allreduce algorithm axis (an
        ``ADAPCC_COLL_ALGO`` pin at the engine collapses it)."""
        return self.policy.choose(
            primitive, max(1, int(nbytes)), dtype, wire_dtypes,
            overlap_modes, algos,
        )

    def rank_only(
        self,
        primitive: str,
        nbytes: int,
        dtype: str = "float32",
        wire_dtypes: Optional[Sequence[str]] = None,
        overlap_modes: Optional[Sequence[str]] = None,
        algos: Optional[Sequence[str]] = None,
    ) -> TunedPlan:
        """Side-effect-free exploitation view (no exploration, no
        incumbent mutation) — see :meth:`TuningPolicy.rank_only`."""
        return self.policy.rank_only(
            primitive, max(1, int(nbytes)), dtype, wire_dtypes,
            overlap_modes, algos,
        )

    def observe_dispatch(
        self, key: TuningKey, cache_token: Hashable, seconds: float
    ) -> bool:
        """Record one live dispatch walltime (warmup-discarding)."""
        return self.timer.observe(key, cache_token, seconds)

    def key_for(
        self,
        primitive: str,
        nbytes: int,
        path: str,
        chunk_bytes: int,
        wire_dtype: str,
    ) -> TuningKey:
        """The database key for an *executed* configuration — callers hand
        in what actually ran (post-precedence), not what was chosen."""
        return TuningKey(
            primitive=primitive,
            size_bucket=size_bucket(nbytes),
            world=self.world,
            topology=self.topology,
            path=path,
            chunk_bytes=int(chunk_bytes),
            wire_dtype=wire_dtype,
        )

    def reset(self) -> None:
        """Drop hysteresis + warmup state (engine rebuild / re-adaptation)."""
        self.policy.reset()
        self.timer.reset()

    # -- constructors ----------------------------------------------------------

    @classmethod
    def for_mesh(
        cls, mesh, db_path: Optional[str] = None, **kwargs
    ) -> "CollectiveTuner":
        """Tuner fingerprinted from a live mesh (the engine-side spelling)."""
        return cls(
            world=int(mesh.devices.size),
            topology=mesh_fingerprint(mesh),
            db_path=db_path,
            **kwargs,
        )

    def __repr__(self) -> str:
        return (
            f"CollectiveTuner(world={self.world}, topology={self.topology!r}, "
            f"mode={self.mode!r}, db={self.db!r})"
        )


__all__ = [
    "CollectiveTuner",
    "DEFAULT_CHUNK_GRID",
    "DEFAULT_DB_PATH",
    "DispatchTimer",
    "SCHEMA_VERSION",
    "TUNER_DB_ENV",
    "TUNER_MODE_ENV",
    "TUNER_MODES",
    "TUNER_OBJECTIVE_ENV",
    "TUNER_OBJECTIVES",
    "TunedPlan",
    "TuningDatabase",
    "TuningKey",
    "TuningPolicy",
    "TuningStats",
    "mesh_fingerprint",
    "replay_trace",
    "resolve_db_path",
    "resolve_tuner_objective",
    "size_bucket",
    "timed_call",
    "topology_fingerprint",
    "tuner_mode",
]
