"""Plan selection: α-β prior + measured posterior + bounded exploration.

The decision layer of the tuner.  For one ``(primitive, payload)`` request
it builds the candidate grid — ring staging granularities from
:func:`adapcc_tpu.comm.pallas_ring.plan_ring_schedule` crossed with the
wire-codec registry — and picks a cell by three rules, in order:

1. **Explore** (epsilon-greedy, bounded): while any cell has fewer than
   ``trial_budget`` samples, a coin flip with probability ``epsilon``
   returns the least-sampled cell so the database fills evenly.  Once every
   cell has met its budget, exploration stops for good — the tuner never
   burns steady-state steps re-proving a settled grid.
2. **Exploit**: cells with at least ``min_samples`` measurements rank by
   their database median (the posterior); when nothing is measured yet the
   PR-1 sim cost model prices the grid (the prior).  The posterior
   *replaces* the prior wholesale rather than blending: measured medians of
   different cells are mutually comparable, model-vs-measurement deltas are
   not.
3. **Hysteresis**: the previous winner (the incumbent) keeps the slot
   unless a challenger beats its median by ``hysteresis_margin`` over at
   least ``hysteresis_min_samples`` samples — one lucky dispatch must not
   flap the executed plan step to step (TACCL's stability argument;
   PAPERS.md 2111.04867).
"""

from __future__ import annotations

import os
import random
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from adapcc_tpu.tuner.db import (
    TuningDatabase,
    TuningKey,
    TuningStats,
    size_bucket,
)

#: default ring staging grid: the spread `make ring-sweep` covers, from
#: latency-bound small tiles to near-whole-payload staging
DEFAULT_CHUNK_GRID = (256 << 10, 1 << 20, 4 << 20, 16 << 20)

#: tail-aware scoring (docs/TUNER.md §6): "median" ranks measured cells by
#: their robust median (the historical default), "p99" by the nearest-rank
#: 99th percentile over the cell's bounded sample window — the objective
#: the serving plane keys on, where a strategy that wins the median but
#: fattens the tail loses (The Big Send-off, PAPERS.md)
TUNER_OBJECTIVE_ENV = "ADAPCC_TUNER_OBJECTIVE"

TUNER_OBJECTIVES = ("median", "p99")


def resolve_tuner_objective(explicit: Optional[str] = None) -> str:
    """The scoring objective in force: ``ADAPCC_TUNER_OBJECTIVE`` env >
    the caller's explicit value > "median".  Malformed values raise — a
    typo'd ``p95`` silently ranking by medians would invalidate the tail
    claim the run was meant to make (the ADAPCC_MERGE_ROUNDS policy)."""
    env = os.environ.get(TUNER_OBJECTIVE_ENV)
    value = env if env is not None and env.strip() else explicit
    if value is None:
        return "median"
    objective = value.strip().lower()
    if objective not in TUNER_OBJECTIVES:
        raise ValueError(
            f"{TUNER_OBJECTIVE_ENV}={value!r}: expected one of "
            f"{'|'.join(TUNER_OBJECTIVES)}"
        )
    return objective

#: cells with fewer samples than this rank by the prior, not their median
DEFAULT_MIN_SAMPLES = 2

#: per-cell sample budget the explorer fills before going quiet
DEFAULT_TRIAL_BUDGET = 8

#: probability one choose() call explores while the budget is unfilled
DEFAULT_EPSILON = 0.25

#: a challenger must beat the incumbent median by this fraction
DEFAULT_HYSTERESIS_MARGIN = 0.05

#: ... over at least this many samples
DEFAULT_HYSTERESIS_MIN_SAMPLES = 3

#: paths with no chunk knob store 0 in the key's chunk_bytes slot
NO_CHUNK = 0

#: the quantized ppermute ring (wire_dtype != "off") — one cell per codec
QUANT_PATH = "quant-ring"

#: latency-plane algorithm cells (adapcc_tpu/comm/latency): the recursive
#: halving/doubling allreduce and the binomial-tree allreduce, keyed in the
#: path slot like the ring paths — the persistent schema stays untouched
RD_PATH = "rd"
TREE_PATH = "tree"
ALGO_PATHS = (RD_PATH, TREE_PATH)

#: selector spelling of a path slot: rd/tree cells name their algorithm,
#: every other path (vmem/hbm-stream/quant-ring) is the ring plane
ALGO_OF_PATH = {RD_PATH: "rd", TREE_PATH: "tree"}

#: the engine's flat XLA all-to-all — the one cell of the (new) tuned
#: ``all_to_all`` primitive on a flat mesh ("two_level" on a (dcn, ici)
#: mesh, recorded by the engine and folded in via the known-keys rule)
A2A_XLA_PATH = "xla"

#: the composed two-level allreduce (adapcc_tpu/strategy/hierarchy: the
#: RS-within-pod → AR-across-leaders → AG-within-pod plan executed by
#: comm/two_level.py) as a key-vocabulary path: record-mode engines on a
#: (dcn, ici) mesh time composed dispatches into this cell, and a pre-PR
#: tuning.jsonl loads byte-identical next to it (a vocabulary extension,
#: not a schema change — same rule as the rd/tree cells)
TWO_LEVEL_PATH = "two-level"

#: the compiled ScheduleProgram executor (``adapcc_tpu/compiler``,
#: ``engine.all_reduce(algo="ir")``, docs/COMPILER.md) as a key-vocabulary
#: path: record-mode engines time IR dispatches into this cell (the key's
#: wire_dtype slot carries the program's codec annotation), and a pre-PR
#: tuning.jsonl loads byte-identical next to it (a vocabulary extension,
#: not a schema change — the rd/tree/two-level rule).  IR cells join a
#: candidate grid only when the caller's ``algos`` names "ir" explicitly
#: or a recorded cell exists — the default grids stay byte-stable.
IR_PATH = "ir"

#: the optimizer axis of the IR plane (``compiler/optimize.py``,
#: ``ADAPCC_IR_OPT``): dispatches whose executed program was actually
#: rewritten by the pass pipeline time into this cell, naive/identity
#: ones stay in ``IR_PATH`` — two different executables, two cells, so
#: measured medians arbitrate the A/B instead of averaging it away.
#: Same vocabulary-extension rule as IR_PATH: pre-PR tuning.jsonl loads
#: byte-identical next to it, and the cell joins no default grid.
IR_OPT_PATH = "ir-opt"

#: the fused XLA collective plane (``engine.all_reduce``'s psum fastpath)
#: as an allreduce cell: the baseline the algorithm cells compete against
#: from THAT entry point — it can neither execute nor time the Pallas
#: chunk/codec grid, so without its own measurable cell a measured rd
#: sample would beat every unmeasurable alternative forever.  Joins the
#: grid only on request (``algos`` containing "xla"); ring_allreduce
#: never offers it (that plane cannot run a psum).
XLA_PATH = "xla"

#: gradient-hook dispatches (DDPTrainer --tune): knobs are the wire codec
#: and the overlap schedule (encoded in the key's path slot, see
#: :func:`hook_path` — the persistent schema stays untouched)
HOOK_PATH = "hook"

#: overlap schedules a ddp_step cell can carry; mirrors
#: ``adapcc_tpu.ddp.overlap.OVERLAP_MODES`` (drift pinned by a test — a
#: module-level import would couple the tuner's import graph to the DDP
#: package for three strings)
HOOK_OVERLAP_MODES = ("off", "bucket", "microbatch")


def hook_path(overlap: str = "off") -> str:
    """The ``TuningKey.path`` spelling of a ddp_step cell's overlap
    schedule: ``"hook"`` for the baseline (unchanged from the pre-overlap
    schema, so existing databases keep their samples), ``"hook-<mode>"``
    for an overlapped schedule."""
    if overlap not in HOOK_OVERLAP_MODES:
        raise ValueError(
            f"overlap={overlap!r}: expected one of {HOOK_OVERLAP_MODES}"
        )
    return HOOK_PATH if overlap == "off" else f"{HOOK_PATH}-{overlap}"


def hook_overlap_of(path: str) -> str:
    """Inverse of :func:`hook_path`; loud on a non-hook path."""
    if path == HOOK_PATH:
        return "off"
    prefix = HOOK_PATH + "-"
    if path.startswith(prefix) and path[len(prefix):] in HOOK_OVERLAP_MODES:
        return path[len(prefix):]
    raise ValueError(
        f"path={path!r} is not a ddp_step hook cell (expected "
        f"{HOOK_PATH!r} or {prefix}<{'|'.join(HOOK_OVERLAP_MODES[1:])}>)"
    )


def _is_hook_path(path: str) -> bool:
    return path == HOOK_PATH or path.startswith(HOOK_PATH + "-")


#: pipelined-step dispatches (``pipe_step`` cells recorded by
#: ``PipelineExecutor``): the knob is the tick schedule, encoded in the
#: key's path slot like the hook overlap modes — the persistent schema
#: stays untouched (the key's chunk_bytes slot carries the microbatch
#: count; there is no chunk knob)
PIPE_PATH = "pipe"

#: schedules a pipe_step cell can carry; mirrors
#: ``adapcc_tpu.pipe.schedule.PIPE_SCHEDULES`` (drift pinned by a test —
#: a module-level import would couple the tuner's import graph to the
#: pipeline package for two strings)
PIPE_SCHEDULE_MODES = ("gpipe", "1f1b")


def pipe_path(schedule: str) -> str:
    """The ``TuningKey.path`` spelling of a pipe_step cell's schedule:
    always ``"pipe-<schedule>"`` — unlike :func:`hook_path` there is no
    pre-existing bare cell to stay compatible with, so both schedules
    spell themselves explicitly."""
    if schedule not in PIPE_SCHEDULE_MODES:
        raise ValueError(
            f"schedule={schedule!r}: expected one of {PIPE_SCHEDULE_MODES}"
        )
    return f"{PIPE_PATH}-{schedule}"


def pipe_schedule_of(path: str) -> str:
    """Inverse of :func:`pipe_path`; loud on a non-pipe path."""
    prefix = PIPE_PATH + "-"
    if path.startswith(prefix) and path[len(prefix):] in PIPE_SCHEDULE_MODES:
        return path[len(prefix):]
    raise ValueError(
        f"path={path!r} is not a pipe_step cell (expected "
        f"{prefix}<{'|'.join(PIPE_SCHEDULE_MODES)}>)"
    )


@dataclass(frozen=True)
class TunedPlan:
    """What the policy committed for one dispatch.

    ``source`` is part of the observable contract (the engine records it in
    the dispatch trace): ``measured`` = the database median picked it,
    ``prior`` = the sim cost model picked it (nothing measured yet),
    ``explore`` = an under-sampled cell is being filled.
    """

    key: TuningKey
    source: str                    #: "measured" | "prior" | "explore"
    expected_s: float              #: the score that won (objective or prior)
    #: scoring objective the decision ranked measured cells by
    #: (:data:`TUNER_OBJECTIVES`) — part of the trace payload so a tail
    #: claim can be audited against the mode that actually decided
    objective: str = "median"
    #: execution hint for cells whose persistent key carries no chunk: a
    #: vmem cell is keyed chunk_bytes=0 (the knob is inert there — every
    #: budget ≥ the payload runs the identical program), but the engine
    #: still needs a concrete budget that RESOLVES to the vmem path
    exec_chunk_bytes: Optional[int] = None

    @property
    def chunk_bytes(self) -> Optional[int]:
        """Staging granularity to pass down, or None when the chosen path
        has no chunk knob (quantized ring / hook)."""
        if self.key.chunk_bytes > 0:
            return self.key.chunk_bytes
        return self.exec_chunk_bytes

    @property
    def wire_dtype(self) -> str:
        return self.key.wire_dtype

    def trace_extra(self, applied: bool = True) -> Dict[str, object]:
        """The ``tuner=`` payload for the dispatch trace: what was chosen,
        why, and whether precedence let it run (``applied=False`` = an env
        var or explicit argument overrode the tuner)."""
        return {
            "chosen": {
                "chunk_bytes": self.key.chunk_bytes,
                "wire_dtype": self.key.wire_dtype,
                "path": self.key.path,
            },
            "source": self.source,
            "objective": self.objective,
            "applied": bool(applied),
        }


class TuningPolicy:
    """Ranks candidate plan cells for one fabric (world + topology)."""

    def __init__(
        self,
        db: TuningDatabase,
        world: int,
        topology: str,
        chunk_grid: Sequence[int] = DEFAULT_CHUNK_GRID,
        wire_dtypes: Optional[Sequence[str]] = None,
        epsilon: float = DEFAULT_EPSILON,
        trial_budget: int = DEFAULT_TRIAL_BUDGET,
        min_samples: int = DEFAULT_MIN_SAMPLES,
        hysteresis_margin: float = DEFAULT_HYSTERESIS_MARGIN,
        hysteresis_min_samples: int = DEFAULT_HYSTERESIS_MIN_SAMPLES,
        cost_model=None,
        seed: int = 0,
        fused_paths: Optional[bool] = None,
        objective: Optional[str] = None,
    ) -> None:
        if not 0.0 <= epsilon <= 1.0:
            raise ValueError(f"epsilon must be in [0, 1], got {epsilon}")
        if trial_budget < 1:
            raise ValueError(f"trial_budget must be >= 1, got {trial_budget}")
        if hysteresis_margin < 0:
            raise ValueError(
                f"hysteresis_margin must be >= 0, got {hysteresis_margin}"
            )
        self.db = db
        self.world = int(world)
        self.topology = topology
        self.chunk_grid = tuple(sorted({int(c) for c in chunk_grid}))
        if any(c <= 0 for c in self.chunk_grid):
            raise ValueError(f"chunk grid must be positive, got {chunk_grid}")
        if wire_dtypes is None:
            from adapcc_tpu.quant import codec_names

            wire_dtypes = codec_names()
        self.wire_dtypes = tuple(wire_dtypes)
        self.epsilon = float(epsilon)
        self.trial_budget = int(trial_budget)
        self.min_samples = int(min_samples)
        self.hysteresis_margin = float(hysteresis_margin)
        self.hysteresis_min_samples = int(hysteresis_min_samples)
        #: scoring objective for MEASURED cells (env > explicit > median,
        #: resolved once at construction — an engine rebuild picks up a
        #: changed env, a running policy never flips mid-decision).  The
        #: prior is untouched: the α-β model predicts one deterministic
        #: time, so objectives only diverge once samples exist.
        self.objective = resolve_tuner_objective(objective)
        self._cost_model = cost_model
        #: whether fused wire cells (codec inside the Pallas kernels) join
        #: the grid: None = probe the data plane (a cell must never claim a
        #: path the engine would not run, or the explorer pins on it
        #: forever); True/False force it — the tune-replay synthetic
        #: surface forces True so the artifact shows fused cells on any
        #: build
        self.fused_paths = fused_paths
        # deterministic exploration: a seeded PRNG, not wall-clock entropy —
        # two identical runs explore the same cells in the same order
        self._rng = random.Random(seed)
        #: hysteresis state: (primitive, size_bucket) → incumbent key
        self._incumbent: Dict[Tuple[str, int], TuningKey] = {}
        #: lazily computed sim crossover (ring vs recursive doubling) that
        #: gates the algorithm axis: None = not yet computed
        self._algo_crossover: Optional[float] = None

    # -- candidate grid --------------------------------------------------------

    def _pinned_wire_dtype(self) -> Optional[str]:
        """The ``ADAPCC_WIRE_DTYPE`` pin, or None when unset.  Under a pin
        every dispatch executes the pinned codec regardless of what the
        policy chooses, so cells of any other codec could never accrue
        samples — the grid must collapse to the pinned axis value (the
        ADAPCC_RING_CHUNK_BYTES collapse, codec flavor)."""
        from adapcc_tpu.quant.codec import WIRE_DTYPE_ENV, resolve_wire_dtype

        env = os.environ.get(WIRE_DTYPE_ENV)
        if env is None or not env.strip():
            return None
        return resolve_wire_dtype(None)  # validated; loud on a typo

    def _fused_paths_available(self, dtype, wire_dtype: str) -> bool:
        """Whether fused (chunk × codec) cells may join the grid for this
        payload: forced by :attr:`fused_paths` when set, otherwise probed
        against the data plane's own support funnel."""
        if self.fused_paths is not None:
            return bool(self.fused_paths)
        from adapcc_tpu.comm.pallas_ring import fused_ring_dispatch_reason

        try:
            return fused_ring_dispatch_reason(dtype, wire_dtype) is None
        except ValueError:
            # ADAPCC_FUSED_WIRE=on with an unsupportable combo: the
            # dispatch itself will fail loudly; no cell for it
            return False

    def algo_crossover_bytes(self) -> float:
        """The sim crossover (ring vs recursive doubling) on THIS policy's
        cost model, cached — the one number both the candidate-grid gate
        and the engine's ``auto`` selector consult, so an injected custom
        calibration can never make the tuner offer rd cells at sizes the
        engine's own crossover would refuse (or vice versa)."""
        if self._algo_crossover is None:
            from adapcc_tpu.sim.cost_model import (
                allreduce_crossover_bytes,
                bottleneck_ring_coeffs,
            )

            coeffs = bottleneck_ring_coeffs(self._model(), max(2, self.world))
            self._algo_crossover = allreduce_crossover_bytes(
                self.world, coeffs
            )
        return self._algo_crossover

    def _sub_crossover(self, nbytes: int) -> bool:
        """Whether this payload's size bucket sits at or below the sim
        crossover (ring vs recursive doubling) — the gate that admits the
        algorithm axis into the grid.  Bucket-granular on purpose: every
        payload in one bucket must see the same candidate set, or samples
        and choices within a bucket would rank different grids."""
        x = self.algo_crossover_bytes()
        if x <= 0.0:
            return False
        if x == float("inf"):
            return True
        return size_bucket(nbytes) <= size_bucket(max(1, int(x)))

    def candidates(
        self,
        primitive: str,
        nbytes: int,
        dtype: str = "float32",
        wire_dtypes: Optional[Sequence[str]] = None,
        overlap_modes: Optional[Sequence[str]] = None,
        algos: Optional[Sequence[str]] = None,
    ) -> List[TuningKey]:
        """The plan cells competing for this dispatch.

        Ring primitives cross the chunk grid (``wire_dtype="off"``, path
        from the kernel's own planner so a cell can never claim a path the
        data plane would not run) with, per non-"off" codec, one unfused
        quant-ring cell (no staging knob) plus — where the fused kernels
        can run — fused cells over the same chunk grid, so chunk_bytes ×
        wire_dtype × path compete on measured medians.  ``allreduce``
        additionally carries the **algorithm axis** for sub-crossover size
        buckets (docs/LATENCY.md): one recursive-doubling and one
        binomial-tree cell (:data:`RD_PATH`/:data:`TREE_PATH` in the path
        slot, no chunk knob, fp32 wire), gated on the latency plane's own
        support funnel.  ``ddp_step`` carries the codec axis crossed with
        the overlap-schedule axis (:data:`HOOK_OVERLAP_MODES`, encoded via
        :func:`hook_path`) — the hook's allreduce is not chunk-steered.
        ``all_to_all`` (the MoE dispatch/combine shuffle) has one flat XLA
        cell plus whatever the database already measured for the bucket —
        the engine's dispatches are timed and traced like every other
        collective even while the axis has a single knobless cell.

        ``wire_dtypes`` narrows the codec axis for this call (default: the
        policy's full registry) — a caller whose configuration cannot
        legally run a codec (error-feedback forbids "off") must exclude it
        here, or the explorer pins on a cell that can never accrue samples.
        An ``ADAPCC_WIRE_DTYPE`` pin collapses the codec axis outright
        (every dispatch executes the pin; other codecs' cells would
        starve).  ``overlap_modes`` narrows the ddp_step overlap axis the
        same way (a trainer without gradient accumulation cannot compile
        the microbatch pipeline).  ``algos`` narrows the algorithm axis:
        an ``ADAPCC_COLL_ALGO`` pin (or an explicit ``algo=`` argument at
        the engine) collapses it — a pinned ``rd`` dispatch can never
        execute a ring cell, so offering one would starve the explorer;
        under a single-algorithm pin the crossover gate stands down (the
        pinned cell must exist at every size the engine dispatches).
        """
        if wire_dtypes is None:
            wire_dtypes = self.wire_dtypes
        pin = self._pinned_wire_dtype()
        if pin is not None:
            wire_dtypes = (pin,)
        allowed_algos = (
            ("ring",) + ALGO_PATHS if algos is None else tuple(algos)
        )
        bucket = size_bucket(nbytes)
        cells: List[TuningKey] = []
        if (
            primitive == "allreduce"
            and "xla" in allowed_algos
            and "off" in wire_dtypes
        ):
            # the XLA-plane baseline cell, FIRST so a predicted tie keeps
            # the fused collective (see XLA_PATH)
            cells.append(
                TuningKey(
                    primitive, bucket, self.world, self.topology,
                    XLA_PATH, NO_CHUNK, "off",
                )
            )
        if primitive == "all_to_all":
            cells.append(
                TuningKey(
                    primitive, bucket, self.world, self.topology,
                    A2A_XLA_PATH, NO_CHUNK, "off",
                )
            )
            # measured cells beyond the static grid compete (e.g. the
            # two-level hierarchical exchange the engine records on a
            # (dcn, ici) mesh)
            for known in self.db.keys():
                if (
                    known.primitive == primitive
                    and known.size_bucket == bucket
                    and known.world == self.world
                    and known.topology == self.topology
                    and known not in cells
                ):
                    cells.append(known)
            return cells
        if primitive == "ddp_step":
            modes = (
                HOOK_OVERLAP_MODES if overlap_modes is None
                else tuple(overlap_modes)
            )
            for overlap in modes:
                for wd in wire_dtypes:
                    cells.append(
                        TuningKey(
                            primitive, bucket, self.world, self.topology,
                            hook_path(overlap), NO_CHUNK, wd,
                        )
                    )
            return cells
        from adapcc_tpu.comm.pallas_ring import plan_ring_schedule

        nelems = max(1, int(nbytes)) // max(
            1, _itemsize(dtype)
        )
        if "off" in wire_dtypes and "ring" in allowed_algos:
            seen_planned = set()
            for chunk in self.chunk_grid:
                plan = plan_ring_schedule(nelems, dtype, self.world, chunk)
                # several budgets can resolve to the identical executed plan
                # (every vmem-path budget does — and under an
                # ADAPCC_RING_CHUNK_BYTES pin, every budget does); duplicate
                # cells would split one physical configuration's samples
                # across keys.  Cells are keyed by the PLANNER-RESOLVED
                # budget (``plan.chunk_bytes``, exactly what the engine keys
                # live recordings with) — vmem by 0, the budget being inert
                # there — so a record-mode run's samples always land where
                # choose() looks, env pin or not
                planned = (plan.path, plan.stage_bytes)
                if planned in seen_planned:
                    continue
                seen_planned.add(planned)
                cells.append(
                    TuningKey(
                        primitive, bucket, self.world, self.topology,
                        plan.path,
                        NO_CHUNK if plan.path == "vmem" else int(plan.chunk_bytes),
                        "off",
                    )
                )
        if primitive == "allreduce":
            # the algorithm axis (docs/LATENCY.md): recursive doubling and
            # the binomial tree join the grid for sub-crossover buckets —
            # where the log2(p) α term can actually win — gated on the
            # latency plane's own support funnel (rd needs a power-of-two
            # world).  Under a single-algorithm pin (algos collapsed by
            # the engine) the crossover gate stands down: the pinned cell
            # must exist wherever the engine dispatches it.
            from adapcc_tpu.comm.latency import latency_algo_unsupported_reason

            for path in ALGO_PATHS:
                if path not in allowed_algos or "off" not in wire_dtypes:
                    continue
                if latency_algo_unsupported_reason(self.world, path) is not None:
                    continue
                if "ring" in allowed_algos and not self._sub_crossover(nbytes):
                    continue
                cells.append(
                    TuningKey(
                        primitive, bucket, self.world, self.topology,
                        path, NO_CHUNK, "off",
                    )
                )
        # measured cells OUTSIDE the grid still compete in exploitation: a
        # record-only run under a pinned or solver-assigned chunk (any
        # budget not in the grid) produced honest medians for a plan the
        # data plane actually ran — ignoring them would re-explore cells
        # the pod already paid to measure.  Fused off-grid cells compete
        # too, but only where the data plane can still run them (a cell
        # the dispatch would reroute around would starve forever); a cell
        # of a narrowed-out algorithm never re-enters (the pin the caller
        # declared means the engine would override it every time)
        for known in self.db.keys():
            if (
                known.primitive == primitive
                and known.size_bucket == bucket
                and known.world == self.world
                and known.topology == self.topology
                and known.wire_dtype in wire_dtypes
                and known.path != QUANT_PATH
                and known not in cells
                and (
                    known.path
                    if known.path in ALGO_PATHS or known.path == IR_PATH
                    # the opt cell is the same algo="ir" entry point —
                    # which executable runs is the engine's ADAPCC_IR_OPT
                    # resolution, not a selector choice
                    else (
                        "ir" if known.path == IR_OPT_PATH
                        else ("xla" if known.path == XLA_PATH else "ring")
                    )
                ) in allowed_algos
                and (
                    known.wire_dtype == "off"
                    or self._fused_paths_available(dtype, known.wire_dtype)
                )
            ):
                cells.append(known)
        if primitive == "allreduce" and "ring" in allowed_algos:
            # only allreduce has a quantized ring variant (PR-3); the fused
            # streaming cells (PR-6) speak every ring primitive but compete
            # on the tuner's one steered primitive.  ADAPCC_FUSED_WIRE=on
            # prunes the unfused cells outright — under "on" the engine
            # refuses to run them, so offering them would starve the
            # explorer (the mirror of "off" pruning the fused cells)
            from adapcc_tpu.comm.pallas_ring import resolve_fused_wire

            fused_only = resolve_fused_wire() == "on"
            for wd in wire_dtypes:
                if wd == "off":
                    continue
                if self._fused_paths_available(dtype, wd):
                    seen_planned = set()
                    for chunk in self.chunk_grid:
                        plan = plan_ring_schedule(
                            nelems, dtype, self.world, chunk, wire_dtype=wd
                        )
                        planned = (plan.path, plan.stage_bytes)
                        if planned in seen_planned:
                            continue
                        seen_planned.add(planned)
                        cells.append(
                            TuningKey(
                                primitive, bucket, self.world, self.topology,
                                plan.path,
                                NO_CHUNK if plan.path == "vmem"
                                else int(plan.chunk_bytes),
                                wd,
                            )
                        )
                if not fused_only:
                    cells.append(
                        TuningKey(
                            primitive, bucket, self.world, self.topology,
                            QUANT_PATH, NO_CHUNK, wd,
                        )
                    )
        return cells

    # -- prior -----------------------------------------------------------------

    def _model(self):
        if self._cost_model is None:
            from adapcc_tpu.sim.calibrate import load_or_default

            self._cost_model = load_or_default(world=self.world)
        return self._cost_model

    def prior_time(self, key: TuningKey, nbytes: int) -> float:
        """Model-predicted seconds for one cell — the PR-1/2/3/6 cost-model
        terms, so the tuner's prior and ``make ring-sweep`` /
        ``make quant-bench`` / ``make fused-bench`` can never disagree
        about a cell's ranking."""
        from adapcc_tpu.sim.cost_model import (
            DEFAULT_HBM_BYTES_PER_S,
            all_to_all_time,
            binomial_tree_time,
            bottleneck_ring_coeffs,
            fused_quantized_ring_allreduce_time,
            quantized_ring_allreduce_time,
            recursive_doubling_allreduce_time,
            ring_allreduce_time,
            staged_ring_allreduce_time,
        )

        model = self._model()
        world = max(2, self.world)
        coeffs = bottleneck_ring_coeffs(model, world)
        if key.primitive == "all_to_all":
            return all_to_all_time(world, float(nbytes), coeffs)
        if key.path == RD_PATH:
            return recursive_doubling_allreduce_time(
                world, float(nbytes), coeffs
            )
        if key.path == TREE_PATH:
            # a tree allreduce is two single-shot phases: reduce + broadcast
            return 2.0 * binomial_tree_time(world, float(nbytes), coeffs)
        if key.path in (IR_PATH, IR_OPT_PATH):
            # IR cells carry no program handle in the key, so the prior is
            # the segmented-ring floor every builder meets or beats (the
            # optimizer never raises a program's price — same floor for
            # the opt cell); the exact per-program price is
            # sim.cost_model.schedule_program_time and a recorded cell's
            # median supersedes this prior anyway
            return ring_allreduce_time(world, float(nbytes), coeffs, chunks=world)
        if key.primitive == "allreduce" and key.path == XLA_PATH:
            # the fused XLA collective is the bandwidth-optimal ring on a
            # healthy torus: price it with the classic ring term
            return quantized_ring_allreduce_time(
                world, float(nbytes), coeffs, "off"
            )
        if _is_hook_path(key.path):
            # hook cells: the comm term only (the step's compute is shared
            # across every cell, so it cancels in the ranking).  Overlap
            # variants price identically to their codec's baseline cell on
            # purpose: "off" wins the tie by candidate order, so an overlap
            # schedule is adopted ONLY when measured step medians beat the
            # incumbent — never from the model alone (docs/OVERLAP.md §4)
            return quantized_ring_allreduce_time(
                world, float(nbytes), coeffs, key.wire_dtype
            )
        if key.wire_dtype != "off" and key.path == QUANT_PATH:
            return quantized_ring_allreduce_time(
                world, float(nbytes), coeffs, key.wire_dtype
            )
        from adapcc_tpu.comm.pallas_ring import plan_ring_schedule

        nelems = max(1, int(nbytes)) // 4
        wire = key.wire_dtype
        plan = plan_ring_schedule(
            nelems, "float32", world,
            key.chunk_bytes if key.chunk_bytes > 0 else None,
            wire_dtype=wire,
        )
        if key.path == "vmem" and plan.path != "vmem":
            # a vmem cell is keyed chunk_bytes=0; realize it with a budget
            # covering the whole padded payload
            plan = plan_ring_schedule(
                nelems, "float32", world, plan.padded_bytes, wire_dtype=wire,
            )
        hbm = float("inf") if plan.path == "vmem" else DEFAULT_HBM_BYTES_PER_S
        if wire != "off":
            # fused cells: codec inside the staged kernels, priced by the
            # overlapped per-tile term
            return fused_quantized_ring_allreduce_time(
                world, float(nbytes), coeffs, plan.stage_bytes, wire,
                hbm_bytes_per_s=hbm,
            )
        return staged_ring_allreduce_time(
            world, float(nbytes), coeffs, plan.stage_bytes,
            hbm_bytes_per_s=hbm,
        )

    # -- selection -------------------------------------------------------------

    def _stat_score(self, stats: TuningStats) -> float:
        """The measured scalar the objective ranks cells by: the robust
        median (default) or the tail percentile (``p99``, docs/TUNER.md
        §6) — where a cell that wins the median but fattens the tail
        loses.  One spelling for exploit, hysteresis, and rank_only, so
        the adoption gate and the ranking can never judge by different
        numbers."""
        return stats.p99_s if self.objective == "p99" else stats.median_s

    def _score(self, key: TuningKey, nbytes: int) -> Tuple[float, bool]:
        """(seconds, measured?) — the objective score when the cell has
        enough samples, the model prior otherwise."""
        stats = self.db.stats(key)
        if stats is not None and stats.count >= self.min_samples:
            return self._stat_score(stats), True
        return self.prior_time(key, nbytes), False

    def _exec_chunk(self, key: TuningKey, nbytes: int, dtype: str) -> Optional[int]:
        """Execution budget for a vmem cell (keyed chunk_bytes=0, fused or
        not): the smallest grid budget the planner resolves to the vmem
        path, so applying the plan actually runs the cell that was
        ranked."""
        if key.path != "vmem" or key.chunk_bytes > 0:
            return None
        from adapcc_tpu.comm.pallas_ring import plan_ring_schedule

        nelems = max(1, int(nbytes)) // max(1, _itemsize(dtype))
        for chunk in self.chunk_grid:
            if plan_ring_schedule(nelems, dtype, self.world, chunk).path == "vmem":
                return int(chunk)
        return None

    def _plan(
        self, key: TuningKey, source: str, expected_s: float,
        nbytes: int, dtype: str,
    ) -> TunedPlan:
        return TunedPlan(
            key=key, source=source, expected_s=expected_s,
            objective=self.objective,
            exec_chunk_bytes=self._exec_chunk(key, nbytes, dtype),
        )

    def _best(
        self, cells: Sequence[TuningKey], nbytes: int
    ) -> Tuple[TuningKey, float, str]:
        """Exploitation ranking shared by :meth:`choose` and
        :meth:`rank_only`: measured cells by the objective score (median
        or p99); with nothing measured, the sim prior over the whole
        grid."""
        measured = {
            c: self.db.stats(c)
            for c in cells
            if self.db.count(c) >= self.min_samples
        }
        if measured:
            best = min(
                measured,
                key=lambda c: (self._stat_score(measured[c]), cells.index(c)),
            )
            return best, self._stat_score(measured[best]), "measured"
        priors = {c: self.prior_time(c, nbytes) for c in cells}
        best = min(cells, key=lambda c: (priors[c], cells.index(c)))
        return best, priors[best], "prior"

    def rank_only(
        self,
        primitive: str,
        nbytes: int,
        dtype: str = "float32",
        wire_dtypes: Optional[Sequence[str]] = None,
        overlap_modes: Optional[Sequence[str]] = None,
        algos: Optional[Sequence[str]] = None,
    ) -> TunedPlan:
        """Side-effect-free exploitation view of :meth:`choose`: rank the
        grid by measured median (prior fallback) WITHOUT exploration,
        incumbent mutation, or RNG advance.

        For callers that can only *honor* a decision, never realize
        arbitrary cells — ``engine.all_reduce``'s algorithm arbitration:
        the xla/schedule plane cannot execute or time a chunk/codec cell,
        so an exploring choose() there would return count-0 cells whose
        trial budget can never drain (explorer starvation), and its
        incumbent writes would flap the REAL dispatcher's hysteresis."""
        cells = self.candidates(
            primitive, nbytes, dtype, wire_dtypes, overlap_modes, algos
        )
        if not cells:
            raise ValueError(
                f"no candidate cells for primitive={primitive!r} "
                f"(chunk grid {self.chunk_grid})"
            )
        best, best_s, best_src = self._best(cells, nbytes)
        return self._plan(best, best_src, best_s, nbytes, dtype)

    def choose(
        self,
        primitive: str,
        nbytes: int,
        dtype: str = "float32",
        wire_dtypes: Optional[Sequence[str]] = None,
        overlap_modes: Optional[Sequence[str]] = None,
        algos: Optional[Sequence[str]] = None,
    ) -> TunedPlan:
        """Commit a plan cell for one dispatch (see module docstring).

        ``wire_dtypes`` narrows the codec axis, ``overlap_modes`` the
        ddp_step overlap axis, ``algos`` the allreduce algorithm axis (see
        :meth:`candidates`)."""
        cells = self.candidates(
            primitive, nbytes, dtype, wire_dtypes, overlap_modes, algos
        )
        if not cells:
            raise ValueError(
                f"no candidate cells for primitive={primitive!r} "
                f"(chunk grid {self.chunk_grid}, codecs "
                f"{wire_dtypes if wire_dtypes is not None else self.wire_dtypes})"
            )
        # 1. bounded exploration
        under = [c for c in cells if self.db.count(c) < self.trial_budget]
        if under and self._rng.random() < self.epsilon:
            cell = min(under, key=lambda c: (self.db.count(c), cells.index(c)))
            return self._plan(
                cell, "explore", self._score(cell, nbytes)[0], nbytes, dtype
            )
        # 2. posterior over prior
        best, best_s, best_src = self._best(cells, nbytes)
        # 3. hysteresis against the incumbent
        group = (primitive, size_bucket(nbytes))
        incumbent = self._incumbent.get(group)
        if incumbent is not None and incumbent in cells and incumbent != best:
            inc_s, inc_measured = self._score(incumbent, nbytes)
            challenger_stats = self.db.stats(best)
            promotes = (
                best_src == "measured"
                and challenger_stats is not None
                and challenger_stats.count >= self.hysteresis_min_samples
                and best_s < inc_s * (1.0 - self.hysteresis_margin)
            )
            if not promotes:
                return self._plan(
                    incumbent,
                    "measured" if inc_measured else "prior",
                    inc_s, nbytes, dtype,
                )
        self._incumbent[group] = best
        return self._plan(best, best_src, best_s, nbytes, dtype)

    def incumbent(self, primitive: str, nbytes: int) -> Optional[TuningKey]:
        return self._incumbent.get((primitive, size_bucket(nbytes)))

    def reset(self) -> None:
        """Drop hysteresis state (re-adaptation: a re-profiled fabric should
        re-decide from the database, not from the previous incumbency)."""
        self._incumbent.clear()


def _itemsize(dtype) -> int:
    import jax.numpy as jnp

    return jnp.dtype(dtype).itemsize
