"""Headline benchmark: GPT-2 DDP training throughput with the adaptive stack.

Prints ONE JSON line: ``{"metric": ..., "value": N, "unit": ..., "vs_baseline": N,
"mfu": ..., "step_ms": ..., ...}``.

The flagship workload (GPT-2 under data parallelism with the AdapCC gradient
hook — the reference's train_ddp GPT-2 configuration, BASELINE.md north star)
is timed against a plain-JAX DDP baseline (jit + psum gradient mean, no
framework) on the same devices.  ``vs_baseline`` = framework tokens/s ÷
plain-JAX tokens/s: ≥1.0 means the adaptive machinery costs nothing.

``mfu`` is analytic model FLOPs (matmuls + attention, ×3 for the backward)
per wall-second over the chip's advertised bf16 peak — the utilization
statement the raw tokens/s number lacks.  Timing is forced-sync: a scalar
``device_get`` closes every measured window, because on remote-tunnel
backends ``block_until_ready`` can return before execution completes
(PERFORMANCE.md "measurement methodology").

Size knobs via env (defaults target a single v5e chip):
    BENCH_LAYERS, BENCH_DMODEL, BENCH_HEADS, BENCH_SEQ, BENCH_BATCH,
    BENCH_STEPS, BENCH_WORLD, BENCH_PEAK_TFLOPS
"""

from __future__ import annotations

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax


def _env_int(name: str, default: int) -> int:
    return int(os.environ.get(name, default))


#: advertised bf16 peak TFLOP/s per chip, by device_kind substring
_PEAK_TFLOPS = (
    ("v5 lite", 197.0),  # v5e
    ("v5litepod", 197.0),
    ("v5e", 197.0),
    ("v5p", 459.0),
    ("v4", 275.0),
    ("v6", 918.0),  # trillium
)


def chip_peak_tflops() -> float:
    env = os.environ.get("BENCH_PEAK_TFLOPS")
    if env:
        return float(env)
    kind = getattr(jax.devices()[0], "device_kind", "").lower()
    for sub, peak in _PEAK_TFLOPS:
        if sub in kind:
            return peak
    return 197.0  # assume v5e when unrecognizable


def train_flops_per_token(cfg) -> float:
    """Analytic matmul+attention FLOPs per trained token (fwd + 2×bwd)."""
    d, L, T, V = cfg.d_model, cfg.n_layer, cfg.max_seq, cfg.vocab_size
    per_layer = (
        2 * d * 3 * d        # qkv projection
        + 2 * d * d          # output projection
        + 2 * 2 * d * 4 * d  # mlp up + down
        + 2 * 2 * T * d      # attention scores + values (2·T·d each per token)
    )
    fwd = L * per_layer + 2 * d * V  # + logits matmul
    return 3.0 * fwd


def main() -> None:
    from adapcc_tpu.comm.mesh import build_world_mesh
    from adapcc_tpu.ddp import DDPTrainer, TrainState
    from adapcc_tpu.models.gpt2 import GPT2, GPT2Config, lm_loss
    from adapcc_tpu.strategy.ir import Strategy

    world = _env_int("BENCH_WORLD", 0) or len(jax.devices())
    mesh = build_world_mesh(world)

    cfg = GPT2Config(
        vocab_size=16384,
        max_seq=_env_int("BENCH_SEQ", 512),
        n_layer=_env_int("BENCH_LAYERS", 12),
        n_head=_env_int("BENCH_HEADS", 16),
        d_model=_env_int("BENCH_DMODEL", 1024),
    )
    per_rank_batch = _env_int("BENCH_BATCH", 16)
    batch = per_rank_batch * world
    steps = _env_int("BENCH_STEPS", 10)

    model = GPT2(cfg)
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(batch, cfg.max_seq)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens[:1])

    def loss_fn(p, b):
        return lm_loss(model.apply(p, b), b)

    tx = optax.adamw(3e-4)

    def time_steps(step_fn, state):
        """Mean step seconds with a forced host sync closing the window."""
        state, loss = step_fn(state)  # compile + warmup
        _ = float(jax.device_get(jnp.mean(loss)))
        t0 = time.perf_counter()
        for _ in range(steps):
            state, loss = step_fn(state)
        # a scalar host read forces the whole dispatched chain to finish;
        # block_until_ready alone is not trustworthy through remote tunnels
        _ = float(jax.device_get(jnp.mean(loss)))
        return (time.perf_counter() - t0) / steps

    # --- framework path: DDPTrainer with the adaptive gradient hook -----------
    trainer = DDPTrainer(
        loss_fn, tx, mesh, Strategy.ring(world), donate_state=True, use_xla_fastpath=True
    )
    # both paths donate their state; give each its own param buffers
    fw_state = TrainState.create(jax.tree_util.tree_map(jnp.array, params), tx)
    fw_time = time_steps(lambda s: trainer.step(s, tokens), fw_state)

    # --- baseline: plain jit + psum DDP (no framework) -------------------------
    from jax.sharding import PartitionSpec as P

    def base_step_shard(state, b):
        loss, grads = jax.value_and_grad(loss_fn)(state.params, b)
        grads = jax.tree_util.tree_map(lambda g: jax.lax.pmean(g, "ranks"), grads)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params2 = optax.apply_updates(state.params, updates)
        return TrainState(params=params2, opt_state=opt_state, step=state.step + 1), loss[None]

    base_fn = jax.jit(
        jax.shard_map(
            base_step_shard,
            mesh=mesh,
            in_specs=(P(), P("ranks")),
            out_specs=(P(), P("ranks")),
            check_vma=False,
        ),
        donate_argnums=(0,),
    )
    base_state = TrainState.create(jax.tree_util.tree_map(jnp.array, params), tx)
    base_time = time_steps(lambda s: base_fn(s, tokens), base_state)

    tokens_per_step = batch * cfg.max_seq
    value = tokens_per_step / fw_time
    baseline = tokens_per_step / base_time
    flops_per_tok = train_flops_per_token(cfg)
    peak = chip_peak_tflops() * 1e12 * world
    mfu = value * flops_per_tok / peak

    print(
        json.dumps(
            {
                "metric": "gpt2_ddp_train_throughput",
                "value": round(value, 1),
                "unit": "tokens/s",
                "vs_baseline": round(value / baseline, 4),
                "mfu": round(mfu, 4),
                "step_ms": round(fw_time * 1e3, 2),
                "baseline_step_ms": round(base_time * 1e3, 2),
                "model_flops_per_token": round(flops_per_tok / 1e6, 1),
                "world": world,
            }
        )
    )


if __name__ == "__main__":
    main()
