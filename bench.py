"""Headline benchmark: GPT-2 DDP training throughput with the adaptive stack.

Prints ONE JSON line: ``{"metric": ..., "value": N, "unit": ..., "vs_baseline": N,
"mfu": ..., "step_ms": ..., ...}``.

The flagship workload (GPT-2 under data parallelism with the AdapCC gradient
hook — the reference's train_ddp GPT-2 configuration, BASELINE.md north star)
is timed against a plain-JAX DDP baseline (jit + psum gradient mean, no
framework) on the same devices.  ``vs_baseline`` = framework tokens/s ÷
plain-JAX tokens/s: ≥1.0 means the adaptive machinery costs nothing.

``mfu`` is analytic model FLOPs (matmuls + attention, ×3 for the backward)
per wall-second over the chip's advertised bf16 peak — the utilization
statement the raw tokens/s number lacks.  Timing is forced-sync: a scalar
``device_get`` closes every measured window, because on remote-tunnel
backends ``block_until_ready`` can return before execution completes
(PERFORMANCE.md "measurement methodology").

Robustness (the round-2 failure was one transient tunnel error zeroing the
whole round's evidence): a subprocess *preflight* proves the backend can
compile a tiny program within a hard deadline (bounded retries) before the
main process ever initializes it; a *watchdog* emits whatever was measured
plus an ``error`` field if a phase hangs past ``BENCH_DEADLINE``; each phase
records its partial results as soon as they exist, so a late failure (e.g.
in the baseline path) still leaves the framework numbers in the JSON with
``error`` naming the dead phase and a nonzero exit code.  On a dead
backend the artifact additionally carries ``last_live_bench`` — the
newest committed battery bench row — so the JSON alone still points at
the round's measured number.

Size knobs via env (defaults target a single v5e chip):
    BENCH_LAYERS, BENCH_DMODEL, BENCH_HEADS, BENCH_SEQ, BENCH_BATCH,
    BENCH_STEPS, BENCH_WORLD, BENCH_PEAK_TFLOPS, BENCH_HBM_GBPS,
    BENCH_ATTN (flash|xla),
    BENCH_PARAM_DTYPE (bf16|f32), BENCH_LOSS (dense|chunked),
    BENCH_REMAT (off|full|dots|dots_no_batch), BENCH_SCAN (1|0), BENCH_ACCUM,
    BENCH_FLASH_BLOCK (flash tile edge, default 256 — measured best on v5e;
    "auto" runs the measured tile sweep, ops/flash_autotune.py),
    BENCH_OPT_MOMENTS (f32|bf16 adam first-moment dtype),
    BENCH_GRAD_COMPRESS (off|bf16 gradient-sync wire dtype),
    BENCH_PREFLIGHT_S, BENCH_ATTEMPTS, BENCH_DEADLINE
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

_RESULT = {
    "metric": "gpt2_ddp_train_throughput",
    "value": None,
    "unit": "tokens/s",
    "vs_baseline": None,
}
_PHASE = {"name": "startup"}


def _env_int(name: str, default: int) -> int:
    return int(os.environ.get(name, default))


def _progress(msg: str) -> None:
    print(f"[bench] {msg}", file=sys.stderr, flush=True)


def _phase_begin(name: str) -> None:
    _PHASE["name"] = name
    _progress(f"phase: {name}")


def _emit(rc: int) -> None:
    print(json.dumps(_RESULT), flush=True)
    sys.exit(rc)


def _arm_watchdog() -> None:
    """Emit partial JSON and die if the bench hangs past its deadline —
    a hung phase must still leave an attributable artifact."""
    deadline = _env_int("BENCH_DEADLINE", 1500)

    def fire() -> None:
        _RESULT["error"] = f"watchdog: deadline {deadline}s exceeded in phase {_PHASE['name']}"
        print(json.dumps(_RESULT), flush=True)
        os._exit(3)

    t = threading.Timer(deadline, fire)
    t.daemon = True
    t.start()


def preflight() -> str:
    """Prove the backend compiles a tiny program, in a *subprocess* with a
    hard per-attempt deadline — backend init against a wedged tunnel can hang
    for minutes, and it must not take the main process down with it."""
    attempts = _env_int("BENCH_ATTEMPTS", 3)
    per_attempt = _env_int("BENCH_PREFLIGHT_S", 90)
    # the axon sitecustomize overrides JAX_PLATFORMS at interpreter startup,
    # so the env pin must be re-applied via jax.config before backend init
    code = (
        "import os, jax; p = os.environ.get('JAX_PLATFORMS'); "
        "p and jax.config.update('jax_platforms', p); "
        "import jax.numpy as jnp; d = jax.devices(); "
        "jax.jit(lambda a: a + 1)(jnp.ones(8)).block_until_ready(); "
        "print('PREFLIGHT_OK', d[0].platform, getattr(d[0], 'device_kind', '?'))"
    )
    last = ""
    for i in range(attempts):
        try:
            out = subprocess.run(
                [sys.executable, "-c", code],
                capture_output=True, text=True, timeout=per_attempt,
            )
            if out.returncode == 0 and "PREFLIGHT_OK" in out.stdout:
                line = [l for l in out.stdout.splitlines() if "PREFLIGHT_OK" in l][0]
                _progress(f"preflight: {line}")
                return line
            last = (out.stderr or out.stdout)[-300:].replace("\n", " | ")
        except subprocess.TimeoutExpired:
            last = f"no response within {per_attempt}s"
        _progress(f"preflight attempt {i + 1}/{attempts} failed: {last}")
        if i + 1 < attempts:
            time.sleep(5)
    raise RuntimeError(f"backend unreachable after {attempts} attempts: {last}")


def latest_committed_bench() -> "dict | None":
    """Newest committed hardware-battery bench row (TPU backend, non-null
    value) under benchmarks/results/hw_r*.jsonl — the round's standing
    evidence when the live tunnel is down at bench time.  Battery files
    only (hw_r<round>s<session>), natural-sorted so session 10 outranks
    session 2."""
    import glob
    import re

    def natural(path):
        name = os.path.basename(path)
        return [int(t) if t.isdigit() else t for t in re.split(r"(\d+)", name)]

    root = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "benchmarks", "results")
    best = None
    for path in sorted(glob.glob(os.path.join(root, "hw_r*.jsonl")), key=natural):
        try:
            for line in open(path):
                try:
                    r = json.loads(line)
                except ValueError:
                    continue
                p = r.get("parsed") or {}
                if (
                    r.get("phase") == "bench"
                    and p.get("value")
                    and "tpu" in str(p.get("backend", "")).lower()
                ):
                    best = {
                        "artifact": os.path.basename(path),
                        "value": p["value"],
                        "mfu": p.get("mfu"),
                        "step_ms": p.get("step_ms"),
                        "backend": p.get("backend"),
                    }
        except OSError:
            continue
    return best


def _attach_last_live_bench() -> None:
    """Best-effort: point the error artifact at the newest committed live
    bench row.  Runs in the dead-backend path right before ``_emit(2)``, so
    NO exception may escape — a surprise artifact shape must never replace
    the graceful error JSON with a traceback (ADVICE r4)."""
    try:
        last = latest_committed_bench()
        if last:
            _RESULT["last_live_bench"] = last
    except Exception as e:  # noqa: BLE001
        _RESULT["last_live_bench_error"] = f"{type(e).__name__}: {e}"


#: advertised bf16 peak TFLOP/s per chip, by device_kind substring
_PEAK_TFLOPS = (
    ("v5 lite", 197.0),  # v5e
    ("v5litepod", 197.0),
    ("v5e", 197.0),
    ("v5p", 459.0),
    ("v4", 275.0),
    ("v6", 918.0),  # trillium
)


#: advertised HBM bandwidth GB/s per chip, by device_kind substring
_HBM_GBPS = (
    ("v5 lite", 819.0),  # v5e
    ("v5litepod", 819.0),
    ("v5e", 819.0),
    ("v5p", 2765.0),
    ("v4", 1228.0),
    ("v6", 1640.0),  # trillium
)


def _chip_lookup(env_var: str, table, default: float) -> float:
    """Env override, else device_kind substring table, else the v5e value."""
    import jax

    env = os.environ.get(env_var)
    if env:
        return float(env)
    kind = getattr(jax.devices()[0], "device_kind", "").lower()
    for sub, value in table:
        if sub in kind:
            return value
    return default


def chip_peak_tflops() -> float:
    return _chip_lookup("BENCH_PEAK_TFLOPS", _PEAK_TFLOPS, 197.0)


def chip_hbm_gbps() -> float:
    return _chip_lookup("BENCH_HBM_GBPS", _HBM_GBPS, 819.0)


def train_flops_per_token(cfg) -> float:
    """Analytic matmul+attention FLOPs per trained token (fwd + 2×bwd)."""
    d, L, T, V = cfg.d_model, cfg.n_layer, cfg.max_seq, cfg.vocab_size
    per_layer = (
        2 * d * 3 * d        # qkv projection
        + 2 * d * d          # output projection
        + 2 * 2 * d * 4 * d  # mlp up + down
        + 2 * 2 * T * d      # attention scores + values (2·T·d each per token)
    )
    fwd = L * per_layer + 2 * d * V  # + logits matmul
    return 3.0 * fwd


#: measured best on v5e at T=512: 88,760 tok/s vs 79,751 at 128
#: (battery hw_r04s3.jsonl bench phases)
_DEFAULT_FLASH_BLOCK = 256


def flash_block_for(seq: int) -> int:
    """Largest 8-aligned tile <= BENCH_FLASH_BLOCK that divides ``seq`` —
    flash requires T %% block == 0, so an indivisible seq (384, 640, ...)
    clamps to a compatible tile instead of silently downgrading to xla
    attention.  When no aligned divisor exists (seq itself not a multiple
    of 8, or a pathological knob value), fall back to the full sequence as
    one block — always kernel-legal; the probe-compile guards VMEM.

    ``BENCH_FLASH_BLOCK=auto`` runs the measured tile sweep instead
    (ops/flash_autotune.py): each candidate is timed on the live backend
    (transient-aware warmup) and the per-candidate seconds land in the
    artifact under ``flash_autotune``."""
    raw = os.environ.get("BENCH_FLASH_BLOCK", "").strip().lower()
    if raw == "auto":
        import jax.numpy as jnp

        from adapcc_tpu.ops.flash_autotune import autotune_flash_block, last_timings

        d_head = _env_int("BENCH_DMODEL", 1024) // _env_int("BENCH_HEADS", 16)
        # sweep at the bench's REAL shape: per-rank batch, head count, and
        # the activation dtype (GPT2Config.dtype — bf16 regardless of the
        # BENCH_PARAM_DTYPE param cast), so the crowned tile's VMEM
        # footprint matches what the flagship step actually runs
        batch = _env_int("BENCH_BATCH", 16)
        heads = _env_int("BENCH_HEADS", 16)
        best = autotune_flash_block(
            seq, d_head=d_head, dtype=jnp.bfloat16, batch=batch, heads=heads
        )
        timings = last_timings(
            seq, d_head=d_head, dtype=jnp.bfloat16, batch=batch, heads=heads
        )
        _RESULT["flash_autotune"] = {
            "best": best,
            "timings_ms": {
                str(b): (round(t * 1e3, 3) if t != float("inf") else None)
                for b, t in (timings or {}).items()
            },
        }
        _progress(f"flash autotune: best block {best} of {timings}")
        return best
    want = _env_int("BENCH_FLASH_BLOCK", _DEFAULT_FLASH_BLOCK)
    b = min(max(8, want - want % 8), seq)
    while b >= 8 and seq % b:
        b -= 8
    return b if b >= 8 and seq % b == 0 else seq


def _pick_attention() -> str:
    """Probe-compile the flash path on the live backend; fall back to the XLA
    attention (recording why) rather than failing the whole bench."""
    import jax
    import jax.numpy as jnp

    want = os.environ.get("BENCH_ATTN", "flash")
    if want != "flash":
        return want
    try:
        from adapcc_tpu.ops import flash_attention

        # probe at the REAL seq and tile sizes: a VMEM overflow at
        # BENCH_FLASH_BLOCK=512 or a seq/block divisibility error must fall
        # back here, not burn the whole bench phase later
        seq = _env_int("BENCH_SEQ", 512)
        block = flash_block_for(seq)  # same resolution the bench cfg uses
        x = jnp.ones((1, seq, 2, 64), jnp.bfloat16)
        jax.block_until_ready(jax.jit(
            lambda q, k, v: flash_attention(
                q, k, v, causal=True, block_q=block, block_k=block
            )
        )(x, x, x))
        return "flash"
    except Exception as e:  # noqa: BLE001 — any lowering failure falls back
        _RESULT["flash_error"] = f"{type(e).__name__}: {e}"[:300]
        _progress(f"flash probe failed, falling back to xla attention: {e}")
        return "xla"


def _parse_remat_env() -> "str | None":
    """Validate BENCH_REMAT before any slow phase — a typo must fail fast,
    not after (or masked by) a multi-minute preflight."""
    remat_env = os.environ.get("BENCH_REMAT", "").strip().lower()
    if remat_env in ("", "0", "off", "false", "no", "none"):
        return None
    if remat_env in ("1", "on", "yes", "true", "full"):
        return "full"
    if remat_env in ("dots", "dots_no_batch"):
        return remat_env
    raise ValueError(
        f"BENCH_REMAT={remat_env!r}: expected off/full/dots/dots_no_batch"
    )


def main() -> None:
    _arm_watchdog()
    _phase_begin("config")
    try:
        remat_policy = _parse_remat_env()
        grad_compress = os.environ.get("BENCH_GRAD_COMPRESS", "off")
        if grad_compress not in ("off", "bf16"):
            raise ValueError(
                f"BENCH_GRAD_COMPRESS={grad_compress!r}: expected off/bf16"
            )
    except ValueError as e:
        _RESULT["error"] = str(e)
        _emit(2)
    _phase_begin("preflight")
    try:
        _RESULT["backend"] = preflight()
    except Exception as e:  # noqa: BLE001
        _RESULT["error"] = f"preflight: {e}"
        # a dead tunnel zeroes THIS run, not the round's evidence: point the
        # artifact at the newest committed live-battery bench row so a
        # reader of the JSON alone finds the measured number
        _attach_last_live_bench()
        _emit(2)

    _phase_begin("setup")
    try:
        import jax

        from adapcc_tpu.launch.launcher import apply_platform_env

        apply_platform_env()  # honor JAX_PLATFORMS despite site customizations
        import jax.numpy as jnp
        import numpy as np
        import optax

        from adapcc_tpu.comm.mesh import build_world_mesh
        from adapcc_tpu.ddp import DDPTrainer, TrainState
        from adapcc_tpu.models.gpt2 import GPT2, GPT2Config, lm_loss
        from adapcc_tpu.strategy.ir import Strategy

        world = _env_int("BENCH_WORLD", 0) or len(jax.devices())
        mesh = build_world_mesh(world)

        attention = _pick_attention()
        cfg = GPT2Config(
            vocab_size=16384,
            max_seq=_env_int("BENCH_SEQ", 512),
            n_layer=_env_int("BENCH_LAYERS", 12),
            n_head=_env_int("BENCH_HEADS", 16),
            d_model=_env_int("BENCH_DMODEL", 1024),
            attention=attention,
            # flash tile: largest seq-compatible tile <= BENCH_FLASH_BLOCK
            # (default 256, measured best on v5e; probe fallback guards the
            # rest — VMEM overflow etc.)
            flash_block=flash_block_for(_env_int("BENCH_SEQ", 512)),
            # BENCH_REMAT: unset/""/"0"/"off" = no remat; "dots" |
            # "dots_no_batch" pick a policy; any other truthy value = "full"
            remat=remat_policy is not None,
            remat_policy=remat_policy or "full",
        )
        _RESULT["remat"] = remat_policy or "off"
        _RESULT["flash_block"] = cfg.flash_block
        per_rank_batch = _env_int("BENCH_BATCH", 16)
        accum = _env_int("BENCH_ACCUM", 1)
        _RESULT["accum"] = accum
        batch = per_rank_batch * world
        steps = _env_int("BENCH_STEPS", 10)

        model = GPT2(cfg)
        rng = np.random.default_rng(0)
        tokens = jnp.asarray(
            rng.integers(0, cfg.vocab_size, size=(batch, cfg.max_seq)), jnp.int32
        )
        params = model.init(jax.random.PRNGKey(0), tokens[:1])
        param_dtype = os.environ.get("BENCH_PARAM_DTYPE", "bf16")
        if param_dtype == "bf16":
            params = jax.tree_util.tree_map(
                lambda p: p.astype(jnp.bfloat16)
                if jnp.issubdtype(p.dtype, jnp.floating) else p,
                params,
            )
        _RESULT["attention"] = attention
        _RESULT["param_dtype"] = param_dtype

        # BENCH_LOSS=chunked fuses the LM head into an online-softmax scan
        # (ops/chunked_ce.py): no [B,T,V] logits in HBM, one recompute in bwd
        loss_impl = os.environ.get("BENCH_LOSS", "dense")
        _RESULT["loss_impl"] = loss_impl
        if loss_impl == "chunked":
            from adapcc_tpu.models.gpt2 import lm_loss_chunked

            def loss_fn(p, b):
                return lm_loss_chunked(model, p, b, block=2048)
        else:

            def loss_fn(p, b):
                return lm_loss(model.apply(p, b), b)

        # BENCH_OPT_MOMENTS=bf16 stores adam's first moment in bf16 — a
        # third less optimizer HBM traffic per step for ~bf16-eps update
        # noise (the second moment stays fp32: optax's mu_dtype knob)
        opt_moments = os.environ.get("BENCH_OPT_MOMENTS", "f32")
        if opt_moments not in ("f32", "bf16"):
            raise ValueError(
                f"BENCH_OPT_MOMENTS={opt_moments!r}: expected f32/bf16"
            )
        _RESULT["opt_moments"] = opt_moments
        tx = optax.adamw(
            3e-4,
            mu_dtype=jnp.bfloat16 if opt_moments == "bf16" else None,
        )

        use_scan = _env_int("BENCH_SCAN", 1)
        _RESULT["dispatch"] = "scan" if use_scan else "loop"

        def time_steps(step_fn, state, label):
            """Mean steady-state step seconds, forced host sync closing the
            window.

            ``step_fn`` runs either one step per call (loop mode: every call
            pays the host→device dispatch round-trip — the remote-tunnel
            tax) or all ``steps`` in one scanned dispatch (BENCH_SCAN=1,
            default: the device-side throughput number).

            Warmup is multi-window on TPU: the tunneled runtime migrates the
            executable + buffer residency over a fresh program's first TWO
            executions (~30 s each measured on the r04 hardware session,
            PERF_NOTES), settling ~300x faster from the third — a single
            warmup call times the migration transient, not the device
            (exactly the round-1..4a 0.01-MFU artifact).  Up to
            ``BENCH_WARMUP_WINDOWS`` windows run (default 3 on tpu, 1
            elsewhere), exiting early once a window collapses to <1/4 of the
            previous (steady state proven); every warmup window time lands
            in the JSON for transparency."""
            default_w = 3 if jax.devices()[0].platform == "tpu" else 1
            # at least one warmup always: zero would time compile + the
            # migration transient — the exact artifact this loop eliminates
            max_w = max(1, _env_int("BENCH_WARMUP_WINDOWS", default_w))
            trail = []
            prev = None
            for _ in range(max_w):
                t0 = time.perf_counter()
                state, loss = step_fn(state)
                _ = float(jax.device_get(jnp.mean(loss)))
                w = time.perf_counter() - t0
                trail.append(round(w * 1e3, 1))
                if prev is not None and w < prev / 4:
                    break  # migration transient collapsed: steady state
                prev = w
            _RESULT[f"warmup_windows_ms_{label}"] = trail
            t0 = time.perf_counter()
            if use_scan:
                state, loss = step_fn(state)
            else:
                for _i in range(steps):
                    state, loss = step_fn(state)
            # a scalar host read forces the whole dispatched chain to finish;
            # block_until_ready alone is not trustworthy through remote tunnels
            _ = float(jax.device_get(jnp.mean(loss)))
            return (time.perf_counter() - t0) / steps

        tokens_per_step = batch * cfg.max_seq
        flops_per_tok = train_flops_per_token(cfg)
        _RESULT["model_flops_per_token"] = round(flops_per_tok / 1e6, 1)
        _RESULT["world"] = world
    except Exception as e:  # noqa: BLE001
        _RESULT["error"] = f"setup: {type(e).__name__}: {e}"[:500]
        _emit(1)

    # --- framework path: DDPTrainer with the adaptive gradient hook ---------
    _phase_begin("framework")
    try:
        trainer = DDPTrainer(
            loss_fn, tx, mesh, Strategy.ring(world),
            donate_state=True, use_xla_fastpath=True,
            # BENCH_ACCUM>1 scans microbatches inside the step: activation
            # memory / accum at unchanged math — the HBM headroom knob
            accum_steps=accum,
            # BENCH_GRAD_COMPRESS=bf16 halves gradient-sync wire bytes
            grad_compress=grad_compress,
        )
        _RESULT["grad_compress"] = grad_compress
        # both paths donate their state; give each its own param buffers
        fw_state = TrainState.create(jax.tree_util.tree_map(jnp.array, params), tx)
        if use_scan:
            fw_time = time_steps(
                lambda s: trainer.scan_steps(s, tokens, steps), fw_state, "framework"
            )
        else:
            fw_time = time_steps(
                lambda s: trainer.step(s, tokens), fw_state, "framework"
            )

        value = tokens_per_step / fw_time
        peak = chip_peak_tflops() * 1e12 * world
        _RESULT["value"] = round(value, 1)
        _RESULT["step_ms"] = round(fw_time * 1e3, 2)
        _RESULT["mfu"] = round(value * flops_per_tok / peak, 4)
        _progress(
            f"framework: {value:,.0f} tok/s, {fw_time * 1e3:.1f} ms/step, "
            f"mfu {_RESULT['mfu']:.3f}"
        )
    except Exception as e:  # noqa: BLE001
        _RESULT["error"] = f"framework: {type(e).__name__}: {e}"[:500]
        _emit(1)

    # --- baseline: plain jit + psum DDP (no framework) ----------------------
    _phase_begin("baseline")
    try:
        from jax.sharding import PartitionSpec as P

        def base_step_shard(state, b):
            loss, grads = jax.value_and_grad(loss_fn)(state.params, b)
            grads = jax.tree_util.tree_map(lambda g: jax.lax.pmean(g, "ranks"), grads)
            updates, opt_state = tx.update(grads, state.opt_state, state.params)
            params2 = optax.apply_updates(state.params, updates)
            return (
                TrainState(params=params2, opt_state=opt_state, step=state.step + 1),
                loss[None],
            )

        if use_scan:

            def base_scan_shard(state, b):
                def body(st, _):
                    st2, loss = base_step_shard(st, b)
                    return st2, loss[0]

                st, losses = jax.lax.scan(body, state, None, length=steps)
                return st, losses[None]

            base_inner = base_scan_shard
        else:
            base_inner = base_step_shard
        base_fn = jax.jit(
            jax.shard_map(
                base_inner,
                mesh=mesh,
                in_specs=(P(), P("ranks")),
                out_specs=(P(), P("ranks")),
                check_vma=False,
            ),
            donate_argnums=(0,),
        )
        base_state = TrainState.create(jax.tree_util.tree_map(jnp.array, params), tx)
        base_time = time_steps(lambda s: base_fn(s, tokens), base_state, "baseline")
        baseline = tokens_per_step / base_time
        _RESULT["baseline_step_ms"] = round(base_time * 1e3, 2)
        _RESULT["vs_baseline"] = round(_RESULT["value"] / baseline, 4)
        _progress(f"baseline: {baseline:,.0f} tok/s, {base_time * 1e3:.1f} ms/step")
    except Exception as e:  # noqa: BLE001
        # the framework numbers above are already recorded — keep them
        _RESULT["error"] = f"baseline: {type(e).__name__}: {e}"[:500]
        _emit(1)

    _emit(0)


if __name__ == "__main__":
    main()
