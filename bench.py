"""Headline benchmark: GPT-2 DDP training throughput with the adaptive stack.

Prints ONE JSON line: ``{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}``.

The flagship workload (GPT-2 under data parallelism with the AdapCC gradient
hook — the reference's train_ddp GPT-2 configuration, BASELINE.md north star)
is timed against a plain-JAX DDP baseline (jit + psum gradient mean, no
framework) on the same devices.  ``vs_baseline`` = framework tokens/s ÷
plain-JAX tokens/s: ≥1.0 means the adaptive machinery costs nothing.

Size knobs via env (defaults fit a single v5e chip and compile in ~1 min):
    BENCH_LAYERS, BENCH_DMODEL, BENCH_HEADS, BENCH_SEQ, BENCH_BATCH,
    BENCH_STEPS, BENCH_WORLD
"""

from __future__ import annotations

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax


def _env_int(name: str, default: int) -> int:
    return int(os.environ.get(name, default))


def main() -> None:
    from adapcc_tpu.comm.mesh import build_world_mesh
    from adapcc_tpu.ddp import DDPTrainer, TrainState
    from adapcc_tpu.models.gpt2 import GPT2, GPT2Config, lm_loss
    from adapcc_tpu.strategy.ir import Strategy

    world = _env_int("BENCH_WORLD", 0) or len(jax.devices())
    mesh = build_world_mesh(world)

    cfg = GPT2Config(
        vocab_size=16384,
        max_seq=_env_int("BENCH_SEQ", 512),
        n_layer=_env_int("BENCH_LAYERS", 8),
        n_head=_env_int("BENCH_HEADS", 8),
        d_model=_env_int("BENCH_DMODEL", 512),
    )
    per_rank_batch = _env_int("BENCH_BATCH", 8)
    batch = per_rank_batch * world
    steps = _env_int("BENCH_STEPS", 10)

    model = GPT2(cfg)
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(batch, cfg.max_seq)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens[:1])

    def loss_fn(p, b):
        return lm_loss(model.apply(p, b), b)

    tx = optax.adamw(3e-4)

    def time_steps(step_fn, state):
        state = step_fn(state)  # compile + warmup
        jax.block_until_ready(jax.tree_util.tree_leaves(state)[0])
        t0 = time.perf_counter()
        for _ in range(steps):
            state = step_fn(state)
        jax.block_until_ready(jax.tree_util.tree_leaves(state)[0])
        return (time.perf_counter() - t0) / steps

    # --- framework path: DDPTrainer with the adaptive gradient hook -----------
    trainer = DDPTrainer(
        loss_fn, tx, mesh, Strategy.ring(world), donate_state=True, use_xla_fastpath=True
    )
    # both paths donate their state; give each its own param buffers
    fw_state = TrainState.create(jax.tree_util.tree_map(jnp.array, params), tx)

    def fw_step(state):
        state, _ = trainer.step(state, tokens)  # host-side step counter, async dispatch
        return state

    fw_time = time_steps(fw_step, fw_state)

    # --- baseline: plain jit + psum DDP (no framework) -------------------------
    from jax.sharding import PartitionSpec as P

    def base_step_shard(state, b):
        loss, grads = jax.value_and_grad(loss_fn)(state.params, b)
        grads = jax.tree_util.tree_map(lambda g: jax.lax.pmean(g, "ranks"), grads)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params2 = optax.apply_updates(state.params, updates)
        return TrainState(params=params2, opt_state=opt_state, step=state.step + 1)

    base_fn = jax.jit(
        jax.shard_map(
            base_step_shard,
            mesh=mesh,
            in_specs=(P(), P("ranks")),
            out_specs=P(),
            check_vma=False,
        ),
        donate_argnums=(0,),
    )
    base_state = TrainState.create(jax.tree_util.tree_map(jnp.array, params), tx)
    base_time = time_steps(lambda s: base_fn(s, tokens), base_state)

    tokens_per_step = batch * cfg.max_seq
    value = tokens_per_step / fw_time
    baseline = tokens_per_step / base_time

    print(
        json.dumps(
            {
                "metric": "gpt2_ddp_train_throughput",
                "value": round(value, 1),
                "unit": "tokens/s",
                "vs_baseline": round(value / baseline, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
