"""Straggler-regime end-to-end benchmark: the experiment that justifies the
adaptive sync plane.

The reference ships evidence of the straggler *problem* (wait-time CSVs from
``units-test/get_wait_time.py``'s ``heter_alpha`` skew emulation,
units-test/wait_time_heter_bc128.csv) and the rent-or-buy policy that
monetizes it (proto/rpc_server.py:69-96) — but never a committed run showing
the adaptive path beating full-wait BSP.  This benchmark closes that loop on
the virtual pod, with the REAL machinery end to end: per-rank worker threads
sleep their emulated backward time and negotiate each step through
:class:`CoordinatorLogic` (actual rent-or-buy freeze, wall-clock rent), and
the frozen active list drives the REAL compiled
:class:`~adapcc_tpu.ddp.DDPTrainer` step with a runtime mask.

Three sync modes over identical skew and data:

* ``full_wait``   — plain BSP DDP: every step waits for the slowest rank
                    (static full-world program, the psum fastpath).
* ``rentbuy_bsp`` — coordinator rent-or-buy freeze + BSP relay skip: the
                    leader stops waiting when renting costs more than buying;
                    the straggler's gradients for that step are dropped
                    (reference is_bsp=True, commu.py:107).
* ``rentbuy_async`` — same freeze, async relay bank: the straggler banks its
                    gradients in the carried deferred buffer and contributes
                    the accumulated sum at its next active step
                    (commu.py:160-170,427-431).

Skew pattern (``--pattern``): ``persistent`` marks ``--slow-rank`` slow on
every step; ``bursty`` (default) on 1 of every 4 steps, leaving enough fast
steps for the rank's pipeline lag to drain so it rejoins — intermittent
stragglers are where the async bank differs from BSP drop (a permanently
excluded rank's bank never lands, and the reference's replay has the same
property: a relay that never rejoins never replays).

Reported per mode: steps/s, per-step wait stats (dispatch start minus
previous-step result, the analog of the reference's wait-time CSV columns),
active-count totals, landed-gradient fraction (what share of per-rank batch
shards made it into an update — the convergence-relevant quantity), and the
final full-data eval loss.

Usage (virtual 8-CPU pod or real hardware)::

    python -m benchmarks.straggler --world 8 --steps 40
"""

from __future__ import annotations

import argparse
import json
import statistics
import threading
import time
from typing import Dict, List, Optional, Sequence

MODES = ("full_wait", "rentbuy_bsp", "rentbuy_async")


def _slow_steps(pattern: str, steps: int) -> List[bool]:
    if pattern == "persistent":
        return [True] * steps
    if pattern == "bursty":
        # slow 1 of every 4 steps.  The straggler's pipeline lag after one
        # slow step is (alpha-1)*base - rent_window; each fast step shrinks
        # it by the fast ranks' rent window, so with the default cost
        # constants it catches back up on the 3rd fast step, rejoins the
        # active set, and its banked gradients land — the regime where the
        # async bank beats BSP drop.  (2-of-3 slow at alpha 6 accrues lag
        # faster than it can recover: effectively persistent.)
        return [s % 4 == 0 for s in range(steps)]
    raise ValueError(f"unknown --pattern {pattern!r}")


def run_mode(
    mode: str,
    *,
    trainer,
    state,
    batches: Sequence,
    world: int,
    base_s: float,
    alpha: float,
    slow_rank: int,
    slow: Sequence[bool],
    logic_factory,
) -> Dict:
    """Run ``len(batches)`` steps of ``mode``; returns the metrics dict.

    Worker thread ``r`` emulates rank r's backward pass for step ``s`` by
    sleeping its compute delay after the step ``s-1`` result lands, then
    negotiating (or barriering).  The dispatcher thread launches the real
    compiled train step the moment the step's active set is decided.
    """
    import jax
    import numpy as np

    steps = len(batches)
    delays = [
        [
            base_s * (alpha if (r == slow_rank and slow[s]) else 1.0)
            for r in range(world)
        ]
        for s in range(steps)
    ]
    result_done = [threading.Event() for _ in range(steps)]
    frozen_ready = [threading.Event() for _ in range(steps)]
    frozen_lists: List[Optional[List[int]]] = [None] * steps
    arrivals = [0] * steps
    lock = threading.Lock()
    logic = logic_factory() if mode != "full_wait" else None

    def worker(rank: int) -> None:
        for s in range(steps):
            if s:
                result_done[s - 1].wait()
            time.sleep(delays[s][rank])
            if logic is None:
                with lock:
                    arrivals[s] += 1
                    if arrivals[s] == world:
                        frozen_lists[s] = list(range(world))
                        frozen_ready[s].set()
            else:
                active = logic.hook_arrive(s, rank)
                with lock:
                    if frozen_lists[s] is None:
                        frozen_lists[s] = active
                        frozen_ready[s].set()

    threads = [
        threading.Thread(target=worker, args=(r,), daemon=True)
        for r in range(world)
    ]
    t_start = time.monotonic()
    last_result = t_start
    waits: List[float] = []
    active_counts: List[int] = []
    excluded_shards = 0
    # per-rank shards banked since the rank's last active step: they land in
    # full at the next active step (sync_deferred folds the accumulated sum
    # into the masked average); whatever is still pending at the end is lost
    banked_pending = [0] * world
    for t in threads:
        t.start()
    for s in range(steps):
        frozen_ready[s].wait()
        waits.append(time.monotonic() - last_result)
        active = sorted(frozen_lists[s])
        active_counts.append(len(active))
        excluded_shards += world - len(active)
        for r in range(world):
            if r in active:
                banked_pending[r] = 0
            else:
                banked_pending[r] += 1
        if mode == "full_wait":
            state, _ = trainer.step(state, batches[s])
        else:
            mask = np.zeros((world,), dtype=bool)
            mask[active] = True
            state, _ = trainer.step(state, batches[s], active_mask=mask)
        jax.block_until_ready(state.params)
        last_result = time.monotonic()
        result_done[s].set()
    wall = time.monotonic() - t_start
    for t in threads:
        t.join()

    # landed-gradient fraction: how much of the presented data contributed
    # to an update.  BSP drop loses excluded shards outright; the async bank
    # recovers every banked shard whose rank rejoined, losing only the
    # still-pending tail.
    total_shards = steps * world
    if mode == "rentbuy_async":
        unlanded_tail = sum(banked_pending)
        landed = (total_shards - unlanded_tail) / total_shards
    else:
        landed = (total_shards - excluded_shards) / total_shards

    return {
        "mode": mode,
        "steps": steps,
        "wall_s": round(wall, 4),
        "steps_per_s": round(steps / wall, 3),
        "wait_mean_ms": round(1e3 * statistics.fmean(waits), 2),
        "wait_p95_ms": round(1e3 * sorted(waits)[max(0, int(0.95 * steps) - 1)], 2),
        "active_mean": round(statistics.fmean(active_counts), 3),
        "active_counts": active_counts,
        "excluded_rank_steps": excluded_shards,
        "landed_fraction": round(landed, 4),
        "state": state,
    }


def main(argv: Optional[Sequence[str]] = None) -> List[Dict]:
    from adapcc_tpu.launch.launcher import apply_platform_env

    apply_platform_env()  # honor JAX_PLATFORMS despite the site customization

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--world", type=int, default=8)
    ap.add_argument("--steps", type=int, default=40)
    ap.add_argument("--base-ms", type=float, default=15.0,
                    help="emulated per-rank backward time")
    ap.add_argument("--alpha", type=float, default=6.0,
                    help="straggler slowdown factor (reference heter_alpha)")
    ap.add_argument("--slow-rank", type=int, default=0)
    ap.add_argument("--pattern", choices=("persistent", "bursty"),
                    default="bursty")
    ap.add_argument("--out", type=str, default=None,
                    help="append one JSON line per mode to this file")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--sync-mode", choices=("auto", "psum", "schedule"),
                    default="auto",
                    help="gradient-sync data plane; schedule = bucketed "
                    "strategy-tree allreduce (merged rounds on multi-tree)")
    ap.add_argument("--trans", type=int, default=1,
                    help="ring-strategy parallel trees (>1 engages the "
                    "merged-round executor on the schedule path)")
    args = ap.parse_args(argv)

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from adapcc_tpu.comm.mesh import build_world_mesh
    from adapcc_tpu.coordinator.logic import CoordinatorLogic
    from adapcc_tpu.ddp import DDPTrainer
    from adapcc_tpu.models.mlp import MLP
    from adapcc_tpu.strategy.ir import Strategy

    world, steps = args.world, args.steps
    mesh = build_world_mesh(world)
    slow = _slow_steps(args.pattern, steps)

    # fixed synthetic regression task; fresh batch per step (plain SGD)
    rng = np.random.default_rng(args.seed)
    d_in, d_out, per_rank = 16, 4, 8
    w_true = rng.normal(size=(d_in, d_out))
    model = MLP(features=(32, d_out))

    def make_batch():
        x = rng.normal(size=(world * per_rank, d_in)).astype(np.float32)
        y = np.tanh(x @ w_true).astype(np.float32)
        return jnp.asarray(x), jnp.asarray(y)

    batches = [make_batch() for _ in range(steps)]
    x_eval = jnp.concatenate([b[0] for b in batches[:8]])
    y_eval = jnp.concatenate([b[1] for b in batches[:8]])

    def loss_fn(params, batch):
        x, y = batch
        return jnp.mean((model.apply(params, x) - y) ** 2)

    params0 = model.init(jax.random.PRNGKey(args.seed), batches[0][0][:1])
    eval_loss = jax.jit(lambda p: loss_fn(p, (x_eval, y_eval)))

    def logic_factory():
        return CoordinatorLogic(world)

    records = []
    for mode in MODES:
        trainer = DDPTrainer(
            loss_fn,
            optax.sgd(0.05),
            mesh,
            Strategy.ring(world, args.trans),
            dynamic_mask=(mode != "full_wait"),
            bsp=(mode != "rentbuy_async"),
            sync_mode=args.sync_mode,
            use_xla_fastpath=(args.sync_mode != "schedule"),
        )
        state = trainer.init_state(jax.tree_util.tree_map(jnp.array, params0))
        # compile outside the measured window (full-world warmup plus, for
        # masked modes, one partial-mask step — masking is a runtime input,
        # so both share one program; the warmup state is discarded)
        warm = trainer.init_state(jax.tree_util.tree_map(jnp.array, params0))
        if mode == "full_wait":
            trainer.step(warm, batches[0])
        else:
            m = np.ones((world,), dtype=bool)
            trainer.step(warm, batches[0], active_mask=m)
        trainer.reset()  # drop warmup step count + any warmup bank
        rec = run_mode(
            mode,
            trainer=trainer,
            state=state,
            batches=batches,
            world=world,
            base_s=args.base_ms / 1e3,
            alpha=args.alpha,
            slow_rank=args.slow_rank,
            slow=slow,
            logic_factory=logic_factory,
        )
        state = rec.pop("state")
        rec["final_eval_loss"] = round(float(eval_loss(state.params)), 6)
        rec.update(
            world=world, base_ms=args.base_ms, alpha=args.alpha,
            pattern=args.pattern, slow_rank=args.slow_rank,
            sync_mode=args.sync_mode, trans=args.trans,
            backend=jax.devices()[0].platform,
        )
        records.append(rec)
        print(json.dumps(rec), flush=True)
        if args.out:
            with open(args.out, "a") as f:
                f.write(json.dumps(rec) + "\n")

    a, b, c = records
    summary = {
        "pattern": args.pattern,
        "speedup_rentbuy_bsp": round(b["steps_per_s"] / a["steps_per_s"], 3),
        "speedup_rentbuy_async": round(c["steps_per_s"] / a["steps_per_s"], 3),
        # the wait component alone: on tiny emulation models the async bank's
        # device-side O(params) overhead is visible in wall time; on real
        # models backward is O(params × batch) and the bank cost vanishes,
        # so the wait ratio is the transferable number
        "wait_speedup_bsp": round(a["wait_mean_ms"] / b["wait_mean_ms"], 3),
        "wait_speedup_async": round(a["wait_mean_ms"] / c["wait_mean_ms"], 3),
        "landed_bsp": b["landed_fraction"],
        "landed_async": c["landed_fraction"],
        "loss_full_wait": a["final_eval_loss"],
        "loss_rentbuy_bsp": b["final_eval_loss"],
        "loss_rentbuy_async": c["final_eval_loss"],
    }
    print(json.dumps({"summary": summary}), flush=True)
    if args.out:
        with open(args.out, "a") as f:
            f.write(json.dumps({"summary": summary}) + "\n")
    return records


if __name__ == "__main__":
    main()
