"""Hardware-free collective sweep on the calibrated α-β simulator.

The simulated twin of :mod:`benchmarks.collectives`: the same collectives ×
sizes × strategies grid, but every number is a model *prediction* from
:mod:`adapcc_tpu.sim` instead of a wall-clock measurement — so the sweep
runs (and ranks the schedule levers) even when the TPU tunnel is dead,
which is exactly the regime that nulled every round-5 number.

Rows carry ``"mode": "simulated"`` and ``pred_time_us`` (never ``time_us``)
so a reader — human or the battery post-processor — can never mistake a
prediction for a measurement.  Predictions are anchored to the last good
hardware round through the calibration artifact
(``topology/calibration.json``, see docs/SIMULATION.md); without one, the
deterministic synthetic defaults price the sweep.

The sweep is fully deterministic: the replay is analytic (no wall clock,
no RNG), and the ParTrees/flow-LP candidates are synthesized from the
calibrated link matrices, so two runs over the same calibration emit
byte-identical rows — the property the tier-1 rig asserts.

Usage (any backend, typically ``JAX_PLATFORMS=cpu``)::

    python -m benchmarks.sim_collectives --world 8 --sizes 4K,1M,16M --json
"""

from __future__ import annotations

import argparse
import json
import math
from typing import Dict, List, Optional, Sequence, Tuple

from adapcc_tpu.sim.calibrate import DEFAULT_CALIBRATION_PATH, load_or_default
from adapcc_tpu.sim.cost_model import (
    DCN,
    DEFAULT_HBM_BYTES_PER_S,
    LinkCostModel,
    collective_lower_bound,
    optimality_gap,
)
from adapcc_tpu.sim.replay import simulate_flow_broadcast, simulate_strategy
from adapcc_tpu.sim.vector import resolve_sim_engine
from adapcc_tpu.strategy.ir import Strategy

from benchmarks.collectives import BUS_FACTORS, parse_size

#: collectives the tree replay lowers (the engine's ppermute-schedule subset)
SIM_COLLECTIVES = ("allreduce", "reduce", "broadcast")

#: candidate schedules swept side by side, mirroring the measured sweep's
#: impl axis (xla/strategy/pallas_ring → here: schedule shapes); labels
#: match Synthesizer.candidates so artifact rows and sim-rank-stamped XML
#: group under one name ("partrees" is accepted as a CLI alias)
SIM_STRATEGIES = ("ring", "binary", "par-trees")

_STRATEGY_ALIASES = {"partrees": "par-trees"}


def _ip_table(world: int, hosts: int) -> List[str]:
    """Synthetic rank→ip table: ``world`` ranks over ``hosts`` hosts in
    contiguous runs (the launcher's placement)."""
    hosts = max(1, min(hosts, world))
    per = -(-world // hosts)
    return [f"10.0.0.{r // per}" for r in range(world)]


def _graphs_from_model(
    model: LinkCostModel,
) -> Tuple[List[List[float]], List[List[float]]]:
    """(bandwidth [GB/s], latency [s]) matrices for the synthesizers, read
    off the calibrated coefficients so candidate *shapes* see the same
    network the replay prices (one definition:
    :meth:`LinkCostModel.to_graphs`, shared with the online re-rank)."""
    return model.to_graphs()


def strategy_candidates(
    world: int,
    names: Sequence[str],
    model: LinkCostModel,
    ips: Optional[Dict[int, str]] = None,
    degree: int = 1,
) -> List[Tuple[str, Strategy]]:
    """Labeled candidate strategies for the sweep — the synthesizer's own
    candidate pool (so the sweep and the sim-rank policy can never drift),
    filtered to ``names``.  ParTrees is skipped (not fatal) when synthesis
    fails on a degenerate topology; Synthesizer.candidates handles that."""
    from adapcc_tpu.strategy.synthesizer import Synthesizer

    if ips is None:
        # a calibration artifact may carry its own ip table — candidate
        # shapes must be synthesized for the network the replay prices
        ips = model.ips
    table = (
        [ips[r] for r in range(world)] if ips else _ip_table(world, 1)
    )
    bw, lat = _graphs_from_model(model)
    pool = dict(Synthesizer(None, table).candidates(degree, bw, lat))
    out: List[Tuple[str, Strategy]] = []
    for name in names:
        label = _STRATEGY_ALIASES.get(name, name)
        if label not in SIM_STRATEGIES:
            raise ValueError(
                f"unknown strategy {name!r}; expected one of {SIM_STRATEGIES}"
            )
        if label in pool:
            out.append((label, pool[label]))
    return out


def _solve_flow(world: int, model: LinkCostModel):
    """Flow-LP broadcast solution on the calibrated complete graph; None
    when the LP backend (scipy) is unavailable.  The LP depends only on the
    topology, so callers solve once and re-simulate per message size."""
    try:
        from adapcc_tpu.strategy.flow_lp import solve_broadcast_lp
    except ImportError:
        return None
    edges = [(s, d) for s in range(world) for d in range(world) if s != d]
    bandwidth = [
        1.0 / max(model.coeffs(s, d).beta, 1e-15) for s, d in edges
    ]
    try:
        return solve_broadcast_lp(world, edges, bandwidth)
    except Exception:
        return None


def _finish_row(row: dict, collective: str, world: int) -> dict:
    row["impl"] = "sim"
    row["busbw_gbps"] = round(
        row["algbw_gbps"] * BUS_FACTORS[collective](world), 6
    )
    return row


def sweep(
    world: int,
    sizes: Sequence[int],
    collectives: Sequence[str] = SIM_COLLECTIVES,
    strategies: Sequence[str] = SIM_STRATEGIES,
    model: Optional[LinkCostModel] = None,
    hosts: int = 1,
    degree: int = 1,
    flow_lp: bool = True,
) -> List[dict]:
    """The full prediction grid as artifact rows (pure function — the CLI
    and the battery fallback both call this)."""
    ips = (
        {r: ip for r, ip in enumerate(_ip_table(world, hosts))}
        if hosts > 1
        else None
    )
    if model is None:
        model = load_or_default(world=world)
    elif model.world != world:
        raise ValueError(f"model world {model.world} != sweep world {world}")
    if ips is not None and model.ips is None:
        # the synthetic host split must actually price cross-host edges as
        # DCN; a calibration carrying its own ip table keeps it
        model = model.with_ips(ips)
    elif ips is not None and model.ips != ips:
        # candidate shapes and replay pricing must see the SAME host layout;
        # silently synthesizing for one network and pricing on another makes
        # the ranking meaningless
        raise ValueError(
            f"--hosts {hosts} conflicts with the host layout recorded in "
            f"the calibration ({model.source}); drop --hosts to sweep the "
            "calibrated layout"
        )
    candidates = strategy_candidates(world, strategies, model, ips, degree)
    flow = (
        _solve_flow(world, model)
        if flow_lp and "broadcast" in collectives
        else None
    )
    rows: List[dict] = []
    for collective in collectives:
        if collective not in SIM_COLLECTIVES:
            raise ValueError(
                f"unknown collective {collective!r}; "
                f"expected one of {SIM_COLLECTIVES}"
            )
        for nbytes in sizes:
            for label, strategy in candidates:
                timeline = simulate_strategy(
                    strategy, model, nbytes, collective, keep_transfers=False
                )
                row = _finish_row(timeline.to_row(), collective, world)
                row["strategy"] = label
                rows.append(row)
            if collective == "broadcast" and flow is not None:
                lp = _finish_row(
                    simulate_flow_broadcast(flow, model, nbytes).to_row(),
                    "broadcast", world,
                )
                lp["strategy"] = "flow-lp"
                rows.append(lp)
    if not rows:
        # an explicitly requested strategy that failed to synthesize (or an
        # empty grid) must not read as "ran fine, no data" — same
        # fail-loudly rule as collectives.py's --impls validation
        raise ValueError(
            f"sweep produced no rows: none of strategies={list(strategies)} "
            f"synthesized for world={world} and no flow-lp row applied"
        )
    for row in rows:
        row["calibration"] = model.source
    return rows


def ring_chunk_sweep(
    world: int,
    sizes: Sequence[int],
    chunk_sizes: Sequence[int],
    model: Optional[LinkCostModel] = None,
) -> List[dict]:
    """Predicted staged-ring rows over a chunk-size grid — the hardware-free
    regression artifact for ring chunk tuning (``make ring-sweep``).

    Each row prices the Pallas ring at one ``chunk_bytes`` staging
    granularity with :func:`adapcc_tpu.sim.cost_model.
    staged_ring_allreduce_time`, on the *bottleneck* ring link (a lockstep
    ring advances at its slowest hop).  The executed path and tile come from
    the kernel's own planner (:func:`adapcc_tpu.comm.pallas_ring.
    plan_ring_schedule` — pure planning, no kernel execution), so a sweep
    row can never disagree with what the data plane would actually run.
    Deterministic: same calibration → byte-identical rows.
    """
    from adapcc_tpu.comm.pallas_ring import plan_ring_schedule
    from adapcc_tpu.sim.cost_model import (
        bottleneck_ring_coeffs,
        staged_ring_allreduce_time,
    )

    if model is None:
        model = load_or_default(world=world)
    elif model.world != world:
        raise ValueError(f"model world {model.world} != sweep world {world}")
    # lockstep ring: the slowest (src → src+1) hop paces every step
    coeffs = bottleneck_ring_coeffs(model, world)
    rows: List[dict] = []
    for nbytes in sizes:
        for chunk in chunk_sizes:
            plan = plan_ring_schedule(
                -(-int(nbytes) // 4), "float32", world, int(chunk)
            )
            # both paths execute the same 2(w−1)-step ring walk, so both are
            # priced with the staged model; the vmem path just pays no HBM
            # staging (payload already VMEM-resident) — pricing them with
            # different schedule shapes would invert the vmem/stream knee
            seconds = staged_ring_allreduce_time(
                world, nbytes, coeffs, plan.stage_bytes,
                hbm_bytes_per_s=(
                    float("inf") if plan.path == "vmem" else
                    DEFAULT_HBM_BYTES_PER_S
                ),
            )
            algbw = nbytes / seconds / 1e9 if seconds > 0 else 0.0
            rows.append({
                "mode": "simulated",
                "collective": "allreduce",
                "impl": "pallas_ring",
                "strategy": "ring",
                "world": world,
                "size_bytes": int(nbytes),
                "chunk_bytes": int(chunk),
                "ring_path": plan.path,
                "stage_bytes": plan.stage_bytes,
                "n_tiles": plan.n_tiles,
                "vmem_bound_bytes": plan.vmem_bound_bytes,
                "pred_time_us": round(seconds * 1e6, 3),
                "algbw_gbps": round(algbw, 6),
                "busbw_gbps": round(algbw * BUS_FACTORS["allreduce"](world), 6),
                "calibration": model.source,
            })
    if not rows:
        raise ValueError(
            f"ring sweep produced no rows: sizes={list(sizes)} "
            f"chunks={list(chunk_sizes)}"
        )
    return rows


def wire_dtype_sweep(
    world: int,
    sizes: Sequence[int],
    wire_dtypes: Sequence[str],
    model: Optional[LinkCostModel] = None,
    block_size: Optional[int] = None,
) -> List[dict]:
    """Predicted wire-codec rows over the allreduce ring — the hardware-free
    regression artifact for codec selection (``make quant-bench``).

    Each row prices the quantized ppermute ring at one wire dtype with
    :func:`adapcc_tpu.sim.cost_model.quantized_ring_allreduce_time` — the
    exact term the sim-rank policy uses to set ``Strategy.wire_dtype`` — on
    the bottleneck ring link (a lockstep ring advances at its slowest hop).
    ``chosen`` marks the dtype :func:`choose_wire_dtype` would commit for
    that size, so the artifact shows not just the curve but the decision.
    Deterministic: same calibration → byte-identical rows.
    """
    from adapcc_tpu.quant import DEFAULT_BLOCK_SIZE, get_codec
    from adapcc_tpu.sim.cost_model import (
        bottleneck_ring_coeffs,
        choose_wire_dtype,
        quantized_ring_allreduce_time,
        wire_bytes_per_element,
    )

    if block_size is None:
        block_size = DEFAULT_BLOCK_SIZE
    for wd in wire_dtypes:
        get_codec(wd)  # loud on a typo'd codec, before any row is emitted
    if model is None:
        model = load_or_default(world=world)
    elif model.world != world:
        raise ValueError(f"model world {model.world} != sweep world {world}")
    coeffs = bottleneck_ring_coeffs(model, world)
    rows: List[dict] = []
    for nbytes in sizes:
        chosen, _ = choose_wire_dtype(
            world, nbytes, coeffs, block_size, candidates=tuple(wire_dtypes)
        )
        for wd in wire_dtypes:
            seconds = quantized_ring_allreduce_time(
                world, nbytes, coeffs, wd, block_size
            )
            algbw = nbytes / seconds / 1e9 if seconds > 0 else 0.0
            rows.append({
                "mode": "simulated",
                "collective": "allreduce",
                "impl": "quant_ring",
                "strategy": "ring",
                "world": world,
                "size_bytes": int(nbytes),
                "wire_dtype": wd,
                "block_size": int(block_size),
                "wire_bytes_per_elem": round(
                    wire_bytes_per_element(wd, block_size), 6
                ),
                "chosen": wd == chosen,
                "pred_time_us": round(seconds * 1e6, 3),
                "algbw_gbps": round(algbw, 6),
                "busbw_gbps": round(algbw * BUS_FACTORS["allreduce"](world), 6),
                "calibration": model.source,
            })
    if not rows:
        raise ValueError(
            f"wire-dtype sweep produced no rows: sizes={list(sizes)} "
            f"wire_dtypes={list(wire_dtypes)}"
        )
    return rows


def fused_wire_sweep(
    world: int,
    sizes: Sequence[int],
    chunk_sizes: Sequence[int],
    wire_dtypes: Sequence[str] = ("bf16", "int8"),
    model: Optional[LinkCostModel] = None,
    block_size: Optional[int] = None,
) -> List[dict]:
    """Predicted fused-vs-unfused codec rows over (size × wire_dtype ×
    chunk_bytes) — the hardware-free regression artifact for the fused
    quantized streaming ring (``make fused-bench``, docs/RING.md §5).

    Each row prices the SAME payload both ways on the bottleneck ring
    link: ``pred_fused_us`` with :func:`adapcc_tpu.sim.cost_model.
    fused_quantized_ring_allreduce_time` (codec inside the staged kernel,
    per-tile codec overlapped with RDMA) at the planner-resolved tile for
    that ``chunk_bytes``, and ``pred_unfused_us`` with
    :func:`quantized_ring_allreduce_time` (the ppermute reroute's serial
    codec passes).  ``fused_faster`` flags the winner per row and
    ``crossover_bytes`` stamps, per (wire_dtype, chunk) curve, the
    smallest swept size where the fused path wins (None when it never
    does — small payloads pay the per-tile α and the exposed codec
    fill/drain).  The executed path/tile come from
    :func:`adapcc_tpu.comm.pallas_ring.plan_ring_schedule`, so a row can
    never claim a geometry the data plane would not run.  Deterministic:
    same calibration → byte-identical rows.
    """
    from adapcc_tpu.comm.pallas_ring import (
        fused_wire_unsupported_reason,
        plan_ring_schedule,
    )
    from adapcc_tpu.quant import DEFAULT_BLOCK_SIZE
    from adapcc_tpu.sim.cost_model import (
        bottleneck_ring_coeffs,
        fused_quantized_ring_allreduce_time,
        quantized_ring_allreduce_time,
        wire_bytes_per_element,
    )

    if block_size is None:
        block_size = DEFAULT_BLOCK_SIZE
    for wd in wire_dtypes:
        reason = fused_wire_unsupported_reason("float32", wd, block_size)
        if reason is not None:
            # loud on off/unknown/ungeometric codecs before any row exists
            raise ValueError(f"fused sweep cannot price {wd!r}: {reason}")
    if model is None:
        model = load_or_default(world=world)
    elif model.world != world:
        raise ValueError(f"model world {model.world} != sweep world {world}")
    from adapcc_tpu.sim.cost_model import DEFAULT_HBM_BYTES_PER_S

    coeffs = bottleneck_ring_coeffs(model, world)
    sizes = [int(s) for s in sizes]

    def fused_pred(nbytes: int, wd: str, chunk: int):
        """(plan, fused seconds) with the tuner prior's exact pricing rule
        — vmem plans pay no HBM streaming (the payload is VMEM-resident),
        so the artifact and prior_time can never disagree on a ranking."""
        plan = plan_ring_schedule(
            nbytes // 4, "float32", world, int(chunk),
            wire_dtype=wd, block_size=block_size,
        )
        hbm = (
            float("inf") if plan.path == "vmem" else DEFAULT_HBM_BYTES_PER_S
        )
        return plan, fused_quantized_ring_allreduce_time(
            world, nbytes, coeffs, plan.stage_bytes, wd, block_size,
            hbm_bytes_per_s=hbm,
        )

    # price every cell exactly once; rows and crossovers read the dicts
    preds = {
        (s, wd, int(chunk)): fused_pred(s, wd, chunk)
        for s in sizes for wd in wire_dtypes for chunk in chunk_sizes
    }
    unfused = {
        (s, wd): quantized_ring_allreduce_time(world, s, coeffs, wd, block_size)
        for s in sizes for wd in wire_dtypes
    }
    rows: List[dict] = []
    crossover: Dict[Tuple[str, int], Optional[int]] = {
        (wd, int(chunk)): next(
            (
                s for s in sorted(sizes)
                if preds[(s, wd, int(chunk))][1] < unfused[(s, wd)]
            ),
            None,
        )
        for wd in wire_dtypes for chunk in chunk_sizes
    }
    for nbytes in sizes:
        for wd in wire_dtypes:
            unfused_s = unfused[(nbytes, wd)]
            for chunk in chunk_sizes:
                plan, fused_s = preds[(nbytes, wd, int(chunk))]
                algbw = nbytes / fused_s / 1e9 if fused_s > 0 else 0.0
                rows.append({
                    "mode": "simulated",
                    "collective": "allreduce",
                    "impl": "fused_ring",
                    "strategy": "ring",
                    "world": world,
                    "size_bytes": int(nbytes),
                    "wire_dtype": wd,
                    "block_size": int(block_size),
                    "chunk_bytes": int(chunk),
                    "ring_path": plan.path,
                    "stage_bytes": plan.stage_bytes,
                    "wire_stage_bytes": plan.wire_stage_bytes,
                    "scale_slot_bytes": plan.scale_slot_bytes,
                    "vmem_bound_bytes": plan.vmem_bound_bytes,
                    "wire_bytes_per_elem": round(
                        wire_bytes_per_element(wd, block_size), 6
                    ),
                    "pred_fused_us": round(fused_s * 1e6, 3),
                    "pred_unfused_us": round(unfused_s * 1e6, 3),
                    "fused_faster": fused_s < unfused_s,
                    "crossover_bytes": crossover[(wd, int(chunk))],
                    "algbw_gbps": round(algbw, 6),
                    "busbw_gbps": round(
                        algbw * BUS_FACTORS["allreduce"](world), 6
                    ),
                    "calibration": model.source,
                })
    if not rows:
        raise ValueError(
            f"fused sweep produced no rows: sizes={list(sizes)} "
            f"chunks={list(chunk_sizes)} wire_dtypes={list(wire_dtypes)}"
        )
    return rows


def latency_sweep(
    world: int,
    sizes: Sequence[int],
    algos: Sequence[str] = ("ring", "rd", "tree"),
    model: Optional[LinkCostModel] = None,
) -> List[dict]:
    """Predicted allreduce-algorithm rows over a size grid spanning the
    ring↔recursive-doubling crossover — the hardware-free regression
    artifact for the latency-bound regime (``make latency-bench``,
    docs/LATENCY.md).

    Each row prices one (size, algorithm) cell on the bottleneck ring link
    (the pacing rule every ring-shaped pricing shares): ``ring`` with the
    classic ``2·(p−1)·(α + β·n/p)`` term, ``rd`` with
    :func:`adapcc_tpu.sim.cost_model.recursive_doubling_allreduce_time`
    (hop-serialized recursive halving/doubling), ``tree`` as two
    single-shot binomial phases.  ``chosen`` marks the algorithm
    :func:`choose_allreduce_algo` would commit for that size — the sized
    decision ``ADAPCC_COLL_ALGO=auto`` executes — and every row stamps
    ``crossover_bytes`` (ring vs rd break-even; ``None`` when rd never
    loses, i.e. β = 0).  Deterministic: same calibration → byte-identical
    rows.
    """
    from adapcc_tpu.sim.cost_model import (
        COLL_ALGO_CANDIDATES,
        allreduce_crossover_bytes,
        bottleneck_ring_coeffs,
        choose_allreduce_algo,
    )

    algos = [a.strip() for a in algos if str(a).strip()]
    bad = [a for a in algos if a not in COLL_ALGO_CANDIDATES]
    if bad:
        raise ValueError(
            f"unknown algorithm(s) {bad}; expected a subset of "
            f"{COLL_ALGO_CANDIDATES}"
        )
    if model is None:
        model = load_or_default(world=world)
    elif model.world != world:
        raise ValueError(f"model world {model.world} != sweep world {world}")
    coeffs = bottleneck_ring_coeffs(model, world)
    crossover = allreduce_crossover_bytes(world, coeffs)
    crossover_field = (
        None if crossover == float("inf") else int(round(crossover))
    )
    rows: List[dict] = []
    for nbytes in sizes:
        chosen, times = choose_allreduce_algo(
            world, int(nbytes), coeffs, candidates=tuple(algos)
        )
        for algo in algos:
            seconds = times[algo]
            algbw = nbytes / seconds / 1e9 if seconds > 0 else 0.0
            rows.append({
                "mode": "simulated",
                "collective": "allreduce",
                "impl": "latency",
                "strategy": "ring",
                "world": world,
                "size_bytes": int(nbytes),
                "algo": algo,
                "chosen": algo == chosen,
                "sub_crossover": float(nbytes) < crossover,
                "crossover_bytes": crossover_field,
                "pred_time_us": round(seconds * 1e6, 3),
                "algbw_gbps": round(algbw, 6),
                "busbw_gbps": round(
                    algbw * BUS_FACTORS["allreduce"](world), 6
                ),
                "calibration": model.source,
            })
    if not rows:
        raise ValueError(
            f"latency sweep produced no rows: sizes={list(sizes)} "
            f"algos={list(algos)}"
        )
    return rows


#: schedule-sweep program grid: the three hand-written planes re-emitted
#: as compiler IR, plus the pipelined bidirectional schedule only the IR
#: can express (adapcc_tpu/compiler/synthesize.py)
SCHEDULE_PROGRAMS = ("ring", "rd", "tree", "pipelined")


def schedule_sweep(
    world: int,
    sizes: Sequence[int],
    programs: Sequence[str] = SCHEDULE_PROGRAMS,
    model: Optional[LinkCostModel] = None,
) -> List[dict]:
    """Predicted rows for IR-lowered schedule programs over a size grid —
    the hardware-free regression artifact for the schedule compiler
    (``make compiler-bench``, docs/COMPILER.md).

    Each row prices one (size, program) cell twice: ``pred_time_us`` is the
    verified :class:`~adapcc_tpu.compiler.ScheduleProgram` under
    :func:`~adapcc_tpu.sim.cost_model.schedule_program_time` (barrier
    rounds, coalesced per-link bytes, full-duplex fully-connected), and
    ``legacy_pred_time_us`` is the same algorithm's hand-written plane
    pricing (the classic ring term / ``recursive_doubling_allreduce_time``
    / ``2 × binomial_tree_time``), so drift between the IR pricing and the
    plane pricing is visible in one artifact.  The ``pipelined`` program
    has no legacy plane — that is the compiler's point — so its row stamps
    ``legacy_pred_time_us = None`` and ``lockstep_ring_us`` instead, with
    ``beats_lockstep_ring`` flagging the bandwidth-bound win.  Every
    program passes :func:`~adapcc_tpu.compiler.verify_program` before it is
    priced.  Deterministic: same calibration → byte-identical rows.

    Each row also carries the optimizer A/B (``compiler/optimize.py``):
    ``dispatches`` / ``opt_dispatches`` are the naive and optimized
    programs' static collective dispatch counts from the lowering's color
    plan, ``opt_pred_time_us`` prices the optimized program with the
    per-dispatch launch term set to the calibrated α (the overhead each
    coalesced ppermute saves), ``opt_speedup`` is naive-priced-with-α over
    that, and ``opt_faster`` flags a strict win.  ``passes`` and
    ``opt_fingerprint`` record what rewrote and what executes — empty /
    equal to ``program_fingerprint`` for programs the optimizer leaves
    alone (the segmented ring is already one dispatch per round).
    """
    from adapcc_tpu.compiler import (
        dispatch_count,
        optimize_program,
        pipelined_allreduce_program,
        rd_allreduce_program,
        ring_allreduce_program,
        tree_allreduce_program,
        verify_program,
    )
    from adapcc_tpu.sim.cost_model import (
        binomial_tree_time,
        bottleneck_ring_coeffs,
        quantized_ring_allreduce_time,
        recursive_doubling_allreduce_time,
        ring_allreduce_time,
        schedule_program_time,
    )

    programs = [p.strip() for p in programs if str(p).strip()]
    bad = [p for p in programs if p not in SCHEDULE_PROGRAMS]
    if bad:
        raise ValueError(
            f"unknown program(s) {bad}; expected a subset of "
            f"{SCHEDULE_PROGRAMS}"
        )
    if model is None:
        model = load_or_default(world=world)
    elif model.world != world:
        raise ValueError(f"model world {model.world} != sweep world {world}")
    coeffs = bottleneck_ring_coeffs(model, world)

    builders = {
        "ring": lambda: ring_allreduce_program(world),
        "rd": lambda: rd_allreduce_program(world),
        "tree": lambda: tree_allreduce_program(world),
        "pipelined": lambda: pipelined_allreduce_program(world),
    }
    legacy = {
        # the segmented-ring plane's own term, 2(w−1)·(α + β·n/w) — the IR
        # re-emission must reproduce it exactly, and the row shows it does
        "ring": lambda n: quantized_ring_allreduce_time(world, n, coeffs, "off"),
        "rd": lambda n: recursive_doubling_allreduce_time(world, n, coeffs),
        "tree": lambda n: 2.0 * binomial_tree_time(world, n, coeffs),
        "pipelined": None,
    }
    rows: List[dict] = []
    for name in programs:
        prog = builders[name]()
        verify_program(prog)
        fp = prog.fingerprint()
        # the full canonical pipeline, independent of the ambient
        # ADAPCC_IR_OPT, so the artifact is byte-deterministic
        opt = optimize_program(prog, passes=["dce", "fuse_codec", "coalesce"])
        naive_dispatches = dispatch_count(prog)
        opt_dispatches = dispatch_count(opt)
        for nbytes in sizes:
            seconds = schedule_program_time(prog, float(nbytes), coeffs)
            algbw = nbytes / seconds / 1e9 if seconds > 0 else 0.0
            legacy_fn = legacy[name]
            legacy_us = (
                round(legacy_fn(float(nbytes)) * 1e6, 3)
                if legacy_fn is not None else None
            )
            # the optimizer gap, priced with the launch-overhead term the
            # default model coalesces away: one α per collective dispatch
            naive_with_launch = schedule_program_time(
                prog, float(nbytes), coeffs, per_dispatch_s=coeffs.alpha
            )
            opt_with_launch = schedule_program_time(
                opt, float(nbytes), coeffs, per_dispatch_s=coeffs.alpha
            )
            row = {
                "mode": "simulated",
                "collective": "allreduce",
                "impl": "ir",
                "strategy": prog.name,
                "program_fingerprint": fp,
                "world": world,
                "size_bytes": int(nbytes),
                "chunks": prog.chunks,
                "rounds": prog.num_rounds,
                "pred_time_us": round(seconds * 1e6, 3),
                "legacy_pred_time_us": legacy_us,
                "algbw_gbps": round(algbw, 6),
                "busbw_gbps": round(
                    algbw * BUS_FACTORS["allreduce"](world), 6
                ),
                "dispatches": naive_dispatches,
                "opt_dispatches": opt_dispatches,
                "opt_fingerprint": opt.fingerprint(),
                "passes": list(opt.applied_passes),
                "opt_pred_time_us": round(opt_with_launch * 1e6, 3),
                "naive_launch_pred_time_us": round(naive_with_launch * 1e6, 3),
                "opt_speedup": round(
                    naive_with_launch / opt_with_launch, 6
                ) if opt_with_launch > 0 else None,
                "opt_faster": opt_with_launch < naive_with_launch,
                "calibration": model.source,
            }
            if name == "pipelined":
                lockstep = ring_allreduce_time(world, float(nbytes), coeffs)
                row["lockstep_ring_us"] = round(lockstep * 1e6, 3)
                row["beats_lockstep_ring"] = seconds < lockstep
            rows.append(row)
    if not rows:
        raise ValueError(
            f"schedule sweep produced no rows: sizes={list(sizes)} "
            f"programs={list(programs)}"
        )
    return rows


def pipe_sweep(
    sizes: Sequence[int],
    stages_grid: Sequence[int] = (2, 4),
    microbatch_grid: Sequence[int] = (2, 4, 8),
    fwd_us: float = 100.0,
    model: Optional[LinkCostModel] = None,
    engine: Optional[str] = None,
) -> List[dict]:
    """Predicted GPipe-vs-1F1B frontier over a (stages × microbatches ×
    hop-bytes) grid — the hardware-free regression artifact for the
    pipeline plane (``make pipe-bench``, docs/PIPELINE.md).

    Each cell builds the SAME objects the executor runs: the tick table
    (:func:`~adapcc_tpu.pipe.schedule.pipeline_schedule`), its emitted hop
    program (verified by :func:`~adapcc_tpu.compiler.verify_program`
    before pricing), and three prices per row — ``pred_step_us`` from the
    closed-form :func:`~adapcc_tpu.sim.cost_model.pipeline_step_time`
    (compute + hops over the calibrated link class), ``hop_program_us``
    from replaying the verified program through ``simulate_program``
    (engine funneled like every replay: ``ADAPCC_SIM_ENGINE``), and
    ``stash_bytes`` from the closed-form per-stage stash bound (max over
    stages).  The frontier's two invariants are visible per row:
    ``bubble_fraction`` depends only on (stages, microbatches) and
    shrinks as microbatches grow, and the 1F1B row at ``microbatches >
    stages − 1`` stamps ``memory_win_vs_gpipe`` — same ticks, smaller
    stash, the whole reason the schedule exists.  Deterministic: same
    calibration → byte-identical rows.
    """
    from adapcc_tpu.compiler import verify_program
    from adapcc_tpu.pipe.schedule import (
        PIPE_SCHEDULES,
        pipeline_program,
        pipeline_schedule,
    )
    from adapcc_tpu.sim.cost_model import (
        ICI,
        bottleneck_ring_coeffs,
        pipeline_bubble_fraction,
        pipeline_step_time,
        pipeline_stash_bytes,
    )
    from adapcc_tpu.sim.replay import simulate_program
    from adapcc_tpu.sim.vector import resolve_sim_engine
    from adapcc_tpu.tuner.policy import pipe_path

    stages_grid = [int(s) for s in stages_grid]
    microbatch_grid = [int(m) for m in microbatch_grid]
    bad = [s for s in stages_grid if s < 2]
    if bad:
        raise ValueError(
            f"pipe sweep stages must be >= 2 (a single stage has no "
            f"pipeline), got {bad}"
        )
    if any(m < 1 for m in microbatch_grid):
        raise ValueError(
            f"pipe sweep microbatches must be >= 1, got {microbatch_grid}"
        )
    if fwd_us < 0:
        raise ValueError(f"fwd_us must be >= 0, got {fwd_us}")
    if model is None:
        model = load_or_default(world=max(stages_grid))
    coeffs = bottleneck_ring_coeffs(model, model.world)

    rows: List[dict] = []
    for stages in stages_grid:
        # the hop fabric: one uniform class model at the calibration's
        # bottleneck coefficients, sized to the stage chain
        hop_model = LinkCostModel(
            stages, classes={ICI: coeffs}, source=model.source
        )
        for microbatches in microbatch_grid:
            gpipe_stash: Dict[int, int] = {}
            for schedule in PIPE_SCHEDULES:
                sched = pipeline_schedule(stages, microbatches, schedule)
                prog = pipeline_program(sched, tied_embedding=True)
                verify_program(prog)
                fp = prog.fingerprint()
                for nbytes in sizes:
                    step_s = pipeline_step_time(
                        stages, microbatches, fwd_us * 1e-6,
                        float(nbytes), coeffs,
                    )
                    # each program chunk carries one hop payload, so the
                    # replay's total is hop bytes × chunks
                    tl = simulate_program(
                        prog, hop_model, float(nbytes) * prog.chunks,
                        keep_transfers=False, engine=engine,
                        keep_links=False,
                    )
                    stash = max(
                        int(pipeline_stash_bytes(
                            stages, microbatches, schedule, s, nbytes
                        ))
                        for s in range(stages)
                    )
                    row = {
                        "mode": "simulated",
                        "collective": "pipeline",
                        "impl": pipe_path(schedule),
                        "schedule": schedule,
                        "stages": stages,
                        "microbatches": microbatches,
                        "size_bytes": int(nbytes),
                        "ticks": sched.num_ticks,
                        "rounds": prog.num_rounds,
                        "program_fingerprint": fp,
                        "bubble_fraction": round(
                            pipeline_bubble_fraction(stages, microbatches),
                            6,
                        ),
                        "pred_step_us": round(step_s * 1e6, 3),
                        "hop_program_us": round(tl.seconds * 1e6, 3),
                        "stash_bytes": stash,
                        "engine": resolve_sim_engine(engine, prog.world),
                        "calibration": model.source,
                    }
                    if schedule == "gpipe":
                        gpipe_stash[int(nbytes)] = stash
                    else:
                        row["memory_win_vs_gpipe"] = (
                            stash < gpipe_stash[int(nbytes)]
                        )
                    rows.append(row)
    if not rows:
        raise ValueError(
            f"pipe sweep produced no rows: sizes={list(sizes)} "
            f"stages={stages_grid} microbatches={microbatch_grid}"
        )
    return rows


def hier_sweep(
    sizes: Sequence[int],
    pods: Sequence[int] = (2, 4, 8),
    pod_sizes: Sequence[int] = (4, 8),
    model: Optional[LinkCostModel] = None,
) -> List[dict]:
    """Predicted two-level-vs-flat rows over the (pods × pod_size × size)
    grid — the hardware-free regression artifact for the hierarchical
    sketch synthesis (``make hier-bench``, docs/HIERARCHY.md §4).

    Each row prices the best composed two-level allreduce (both pod
    algorithms × their best leader schedule,
    :func:`adapcc_tpu.sim.cost_model.two_level_allreduce_time`) against
    the flat lockstep ring on the DCN bottleneck for one topology cell,
    stamping the winner in ``chosen`` and the pod count where the
    hierarchy starts paying in ``crossover_pods``
    (:func:`~adapcc_tpu.sim.cost_model.two_level_crossover_pods`).  Only
    the calibration's ICI/DCN *class* coefficients are read — the sweep
    grid names its own topologies, so the model's world is irrelevant
    (and world² state is never touched).  Deterministic: same calibration
    → byte-identical rows.
    """
    from adapcc_tpu.sim.cost_model import (
        DCN,
        ICI,
        choose_two_level,
        two_level_crossover_pods,
    )

    pods = [int(p) for p in pods]
    pod_sizes = [int(i) for i in pod_sizes]
    bad = [p for p in pods if p < 2] + [i for i in pod_sizes if i < 2]
    if bad:
        raise ValueError(
            f"hier sweep needs pods >= 2 and pod sizes >= 2, got pods="
            f"{pods} pod_sizes={pod_sizes}"
        )
    if model is None:
        model = load_or_default()
    ici, dcn = model.classes[ICI], model.classes[DCN]
    rows: List[dict] = []
    for num_pods in pods:
        for pod_size in pod_sizes:
            world = num_pods * pod_size
            for nbytes in sizes:
                chosen, times = choose_two_level(
                    num_pods, pod_size, int(nbytes), ici, dcn
                )
                two, flat = times["two_level"], times["flat"]
                algbw = (
                    int(nbytes) / two / 1e9 if two > 0 else 0.0
                )
                rows.append({
                    "mode": "simulated",
                    "collective": "allreduce",
                    "impl": "two_level",
                    "strategy": "two-level",
                    "world": world,
                    "pods": num_pods,
                    "pod_size": pod_size,
                    "size_bytes": int(nbytes),
                    "pred_two_level_us": round(two * 1e6, 3),
                    "pred_flat_us": round(flat * 1e6, 3),
                    "chosen": chosen,
                    "two_level_faster": chosen == "two_level",
                    "crossover_pods": two_level_crossover_pods(
                        pod_size, int(nbytes), ici, dcn
                    ),
                    "algbw_gbps": round(algbw, 6),
                    "busbw_gbps": round(
                        algbw * BUS_FACTORS["allreduce"](world), 6
                    ),
                    "calibration": model.source,
                })
    if not rows:
        raise ValueError(
            f"hier sweep produced no rows: sizes={list(sizes)} pods={pods} "
            f"pod_sizes={pod_sizes}"
        )
    return rows


def overlap_sweep(
    world: int,
    sizes: Sequence[int],
    accums: Sequence[int] = (1, 2, 4),
    bucket_caps_mb: Sequence[float] = (1.0, 4.0),
    compute_ratios: Sequence[float] = (0.25, 4.0),
    model: Optional[LinkCostModel] = None,
) -> List[dict]:
    """Predicted overlapped-step rows over (accum × bucket cap × overlap
    schedule) — the hardware-free regression artifact for the overlapped
    gradient sync (``make overlap-bench``, docs/OVERLAP.md §4).

    Each row prices one DDP step with :func:`adapcc_tpu.sim.cost_model.
    overlapped_step_time` on the bottleneck ring link (the pacing rule
    every other ring-shaped pricing shares).  The gradient is ``size``
    bytes split into equal buckets of at most ``bucket_cap_mb`` (the
    leaf-free proxy for ``build_bucket_plan``'s greedy fill); the step's
    compute is ``compute_ratio ×`` the baseline sync time, so the grid
    covers both the comm-bound (``ratio < 1``) and compute-bound regimes.
    For every comm-bound configuration the ``"bucket"`` schedule's
    ``exposed_comm_us`` is strictly below the ``"off"`` baseline's — the
    property the regression test pins.  Deterministic: same calibration →
    byte-identical rows.
    """
    from adapcc_tpu.sim.cost_model import (
        OVERLAP_MODE_CANDIDATES,
        bottleneck_ring_coeffs,
        overlapped_step_time,
    )

    if model is None:
        model = load_or_default(world=world)
    elif model.world != world:
        raise ValueError(f"model world {model.world} != sweep world {world}")
    coeffs = bottleneck_ring_coeffs(model, world)
    rows: List[dict] = []
    for nbytes in sizes:
        for cap_mb in bucket_caps_mb:
            cap = max(1, int(cap_mb * 1024 * 1024))
            n_buckets = max(1, -(-int(nbytes) // cap))
            bucket_bytes = [nbytes / n_buckets] * n_buckets
            baseline = overlapped_step_time(
                world, nbytes, coeffs, 0.0, overlap="off",
                bucket_bytes=bucket_bytes,
            )["comm_s"]
            for accum in accums:
                for ratio in compute_ratios:
                    compute_s = ratio * baseline
                    for mode in OVERLAP_MODE_CANDIDATES:
                        if mode == "microbatch" and accum < 2:
                            continue  # no pipeline with one microbatch
                        r = overlapped_step_time(
                            world, nbytes, coeffs, compute_s,
                            accum=accum, overlap=mode,
                            bucket_bytes=bucket_bytes,
                        )
                        rows.append({
                            "mode": "simulated",
                            "collective": "ddp_step",
                            "impl": "overlap",
                            "world": world,
                            "size_bytes": int(nbytes),
                            "accum": int(accum),
                            "bucket_cap_mb": float(cap_mb),
                            "n_buckets": n_buckets,
                            "compute_ratio": float(ratio),
                            "comm_bound": ratio < 1.0,
                            "overlap": mode,
                            "pred_step_us": round(r["step_time_s"] * 1e6, 3),
                            "compute_us": round(r["compute_s"] * 1e6, 3),
                            "comm_us": round(r["comm_s"] * 1e6, 3),
                            "exposed_comm_us": round(
                                r["exposed_comm_s"] * 1e6, 3
                            ),
                            "fill_us": round(r["fill_s"] * 1e6, 3),
                            "drain_us": round(r["drain_s"] * 1e6, 3),
                            "calibration": model.source,
                        })
    if not rows:
        raise ValueError(
            f"overlap sweep produced no rows: sizes={list(sizes)} "
            f"accums={list(accums)} caps={list(bucket_caps_mb)}"
        )
    return rows


def fault_sweep(
    world: int,
    sizes: Sequence[int],
    hosts: int = 1,
    model: Optional[LinkCostModel] = None,
    heartbeat_timeout_s: float = 1.0,
    slowdown: float = 4.0,
) -> List[dict]:
    """Deterministic simulated failover rows — the hardware-free regression
    artifact for elastic fault tolerance (``make elastic-bench``,
    docs/ELASTIC.md).

    Two row families per payload size:

    - **summary** rows (``phase: "failover"``) price each injected fault
      shape end to end with :func:`adapcc_tpu.sim.cost_model.failover_cost`:
      detection latency (heartbeat timeout + half a step), the plan-swap
      stall both ways (``swap_cached_us`` — the standby cache hit — vs
      ``swap_cold_us`` — the recompile the cache exists to avoid), and the
      healthy / undetected / degraded steady states.  Scenarios:
      ``rank-down``, ``rank-slow`` and, on multi-host layouts
      (``hosts > 1``), ``host-down``.
    - **timeline** rows (``phase: "timeline"``) replay one canonical
      :class:`~adapcc_tpu.elastic.faults.FaultPlan` (rank dies → another
      straggles → both recover) step by step through
      :func:`adapcc_tpu.sim.replay.simulate_fault_plan`: per-step predicted
      collective cost under that step's fault state, with detection + swap
      stamped on the transition steps — the detection → swap → steady-state
      shape of one failover, as data.

    Deterministic: same calibration → byte-identical rows.
    """
    from adapcc_tpu.elastic.faults import FaultEvent, FaultPlan
    from adapcc_tpu.sim.cost_model import bottleneck_ring_coeffs, failover_cost
    from adapcc_tpu.sim.replay import simulate_fault_plan

    if model is None:
        model = load_or_default(world=world)
    elif model.world != world:
        raise ValueError(f"model world {model.world} != sweep world {world}")
    ips = (
        {r: ip for r, ip in enumerate(_ip_table(world, hosts))}
        if hosts > 1 else None
    )
    if ips is not None and model.ips is None:
        model = model.with_ips(ips)
    coeffs = bottleneck_ring_coeffs(model, world)
    per_host = -(-world // max(1, hosts))
    scenarios = [("rank-down", 1, None), ("rank-slow", 1, slowdown)]
    if hosts > 1 and per_host < world:
        scenarios.append(("host-down", per_host, None))

    # one canonical plan: a rank dies, another straggles, both recover —
    # the storyline the elastic acceptance test drives live
    plan = FaultPlan(
        [
            FaultEvent(step=2, kind="down", rank=world - 1),
            FaultEvent(step=3, kind="slow", rank=1, slowdown=slowdown),
            FaultEvent(step=6, kind="recover", rank=world - 1),
            FaultEvent(step=7, kind="recover", rank=1),
        ],
        world=world,
        label="canonical-failover",
    )
    strategy = Strategy.ring(world, ips=ips)

    rows: List[dict] = []
    for nbytes in sizes:
        for label, n_down, slow in scenarios:
            cost = failover_cost(
                world, nbytes, coeffs, n_down=n_down, slowdown=slow,
                heartbeat_timeout_s=heartbeat_timeout_s,
                standby_cached=True,
            )
            cold = failover_cost(
                world, nbytes, coeffs, n_down=n_down, slowdown=slow,
                heartbeat_timeout_s=heartbeat_timeout_s,
                standby_cached=False,
            )
            rows.append({
                "mode": "simulated",
                "collective": "allreduce",
                "impl": "elastic",
                "phase": "failover",
                "scenario": label,
                "world": world,
                "size_bytes": int(nbytes),
                "n_down": n_down,
                "slowdown": slow,
                "heartbeat_timeout_s": heartbeat_timeout_s,
                "detection_us": round(cost["detection_s"] * 1e6, 3),
                "swap_cached_us": round(cost["swap_s"] * 1e6, 3),
                "swap_cold_us": round(cold["swap_s"] * 1e6, 3),
                "healthy_us": round(cost["healthy_s"] * 1e6, 3),
                "undetected_us": round(cost["undetected_s"] * 1e6, 3),
                "degraded_us": round(cost["degraded_s"] * 1e6, 3),
                "degraded_ratio": round(cost["degraded_ratio"], 6),
                "failover_total_us": round(cost["failover_total_s"] * 1e6, 3),
                "calibration": model.source,
            })
        for step_row in simulate_fault_plan(
            strategy, model, nbytes, plan,
            heartbeat_timeout_s=heartbeat_timeout_s,
        ):
            row = step_row.to_row()
            row.update({
                "collective": "allreduce",
                "impl": "elastic",
                "phase": "timeline",
                "scenario": plan.label,
                "world": world,
                "size_bytes": int(nbytes),
                "calibration": model.source,
            })
            rows.append(row)
    if not rows:
        raise ValueError(f"fault sweep produced no rows: sizes={list(sizes)}")
    return rows


def chaos_sweep(
    world: int,
    sizes: Sequence[int],
    model: Optional[LinkCostModel] = None,
    periods: Sequence[float] = (0.25, 0.5, 1.0, 2.0),
    graces: Sequence[int] = (1, 2, 4),
    timeout_periods: int = 3,
    sweep_period_s: float = 0.25,
) -> List[dict]:
    """Deterministic supervised-failover rows — the hardware-free
    regression artifact for the autonomous supervisor (``make
    chaos-bench``, docs/SUPERVISOR.md).

    Two row families per payload size:

    - **detection** rows (``phase: "detection"``) price the out-of-band
      liveness machine over the (heartbeat period × grace) grid with
      :func:`adapcc_tpu.sim.cost_model.supervised_detection_latency_s`
      (suspicion after ``timeout_periods`` missed beats, confirmation
      after ``grace`` further periods, half a supervisor sweep to
      observe), next to the swap stall both ways and the degraded steady
      state from :func:`failover_cost` — so the period/grace trade
      (detection latency vs false-positive headroom, printed as
      ``confirm_window_s``, the longest SIGSTOP pause a rank survives
      undemoted) is data, not folklore;
    - **schedule** rows (``phase: "schedule"``) compile the canonical
      fault plan (rank dies → another straggles → both recover) into its
      cross-process chaos spelling via
      :meth:`~adapcc_tpu.elastic.faults.FaultPlan.chaos_schedule` — the
      SIGKILL/SIGSTOP-duty-cycle action list the multi-process drill
      delivers — and pins its deterministic shape (action counts, first
      kill offset, stop/cont pairing).

    Deterministic: same calibration → byte-identical rows.
    """
    from adapcc_tpu.elastic.faults import FaultEvent, FaultPlan
    from adapcc_tpu.sim.cost_model import (
        bottleneck_ring_coeffs,
        failover_cost,
        supervised_detection_latency_s,
    )

    if model is None:
        model = load_or_default(world=world)
    elif model.world != world:
        raise ValueError(f"model world {model.world} != sweep world {world}")
    coeffs = bottleneck_ring_coeffs(model, world)
    slowdown = 4.0
    plan = FaultPlan(
        [
            FaultEvent(step=2, kind="down", rank=world - 1),
            FaultEvent(step=3, kind="slow", rank=1, slowdown=slowdown),
            FaultEvent(step=6, kind="recover", rank=world - 1),
            FaultEvent(step=7, kind="recover", rank=1),
        ],
        world=world,
        label="canonical-failover",
    )
    rows: List[dict] = []
    for nbytes in sizes:
        healthy = None
        for period in periods:
            timeout = timeout_periods * period
            for grace in graces:
                detect = supervised_detection_latency_s(
                    period, timeout, grace, sweep_period_s
                )
                cost = failover_cost(
                    world, nbytes, coeffs, n_down=1,
                    heartbeat_timeout_s=timeout, standby_cached=True,
                )
                cold = failover_cost(
                    world, nbytes, coeffs, n_down=1,
                    heartbeat_timeout_s=timeout, standby_cached=False,
                )
                healthy = cost["healthy_s"]
                rows.append({
                    "mode": "simulated",
                    "collective": "allreduce",
                    "impl": "supervisor",
                    "phase": "detection",
                    "world": world,
                    "size_bytes": int(nbytes),
                    "heartbeat_period_s": period,
                    "heartbeat_timeout_s": timeout,
                    "grace": int(grace),
                    "sweep_period_s": sweep_period_s,
                    "detection_us": round(detect * 1e6, 3),
                    # the false-positive headroom the grace window buys:
                    # a pause shorter than this never demotes the rank
                    "confirm_window_s": round(
                        timeout + grace * period, 9
                    ),
                    "swap_cached_us": round(cost["swap_s"] * 1e6, 3),
                    "swap_cold_us": round(cold["swap_s"] * 1e6, 3),
                    "degraded_ratio": round(cost["degraded_ratio"], 6),
                    # steady-state collectives burnt while undetected
                    "detection_steps_lost": round(detect / healthy, 1)
                    if healthy > 0 else None,
                    "calibration": model.source,
                })
        # the canonical plan's cross-process spelling at a step period of
        # one healthy collective (floored so the schedule stays sane on a
        # sub-microsecond sim step)
        step_period = max(float(healthy or 0.0), 0.05)
        schedule = plan.chaos_schedule(step_period)
        kills = [a for a in schedule if a.kind == "kill"]
        stops = [a for a in schedule if a.kind == "stop"]
        conts = [a for a in schedule if a.kind == "cont"]
        rows.append({
            "mode": "simulated",
            "collective": "allreduce",
            "impl": "supervisor",
            "phase": "schedule",
            "scenario": plan.label,
            "world": world,
            "size_bytes": int(nbytes),
            "step_period_s": round(step_period, 9),
            "actions": len(schedule),
            "kills": len(kills),
            "stops": len(stops),
            "conts": len(conts),
            "first_kill_s": round(kills[0].at_s, 9) if kills else None,
            "slowdown": slowdown,
            # the duty cycle's invariant: every stop has a cont after it
            "stop_cont_paired": len(stops) <= len(conts),
            "calibration": model.source,
        })
    if not rows:
        raise ValueError(f"chaos sweep produced no rows: sizes={list(sizes)}")
    return rows


def recovery_sweep(
    sizes: Sequence[int],
    worlds: Sequence[int] = (8, 32, 64),
    replicas: int = 1,
    save_interval_steps: int = 100,
    model: Optional[LinkCostModel] = None,
) -> List[dict]:
    """Deterministic durable-recovery rows — the hardware-free regression
    artifact for replicated ZeRO-1 shards vs a checkpoint reload (``make
    recovery-bench``, docs/RECOVERY.md §4).

    One row per (world × payload) cell, priced by
    :func:`adapcc_tpu.sim.cost_model.recovery_cost` on the calibration's
    ICI class coefficients (the replica piggyback rides ring-neighbor
    hops; the grid names its own worlds, so — like ``--hier-sweep`` — the
    model's world is irrelevant and no world² state is touched):

    - the per-step **replication overhead** next to the baseline step
      comm, with ``overhead_ok`` stamping the acceptance bound (< 5 % of
      step comm — holds from world=32 up at k=1, the default config: the
      shard shrinks as 1/world while step comm saturates at 2·nbytes);
    - the **repair** arm (one shard over one hop + warm plan swap, zero
      lost steps) against the **reload** arm (full state from shared
      storage + ``save_interval/2`` steps of re-done work), with
      ``repair_speedup`` and the failure-rate break-even.

    Deterministic: same calibration → byte-identical rows.
    """
    from adapcc_tpu.sim.cost_model import ICI, recovery_cost

    worlds = [int(w) for w in worlds]
    bad = [w for w in worlds if w < 2]
    if bad:
        raise ValueError(f"recovery sweep needs worlds >= 2, got {worlds}")
    if replicas < 1:
        raise ValueError(
            f"recovery sweep needs replicas >= 1, got {replicas} "
            "(replicas=0 prices nothing: replication is off)"
        )
    if model is None:
        model = load_or_default()
    coeffs = model.classes[ICI]
    rows: List[dict] = []
    for world in worlds:
        if replicas >= world:
            # an unreplicable cell (k >= world) is skipped LOUDLY in-band:
            # a silent drop would read as "priced that world" when nothing
            # was
            rows.append({
                "mode": "simulated",
                "collective": "allreduce",
                "impl": "recovery",
                "world": world,
                "replicas": replicas,
                "skipped": f"replicas={replicas} needs world > replicas",
                "calibration": model.source,
            })
            continue
        for nbytes in sizes:
            # fp32 Adam on an nbytes gradient: passed explicitly so the
            # emitted row and the priced times can never disagree about
            # what state size was modeled
            state_bytes = 3 * int(nbytes)
            cost = recovery_cost(
                world,
                int(nbytes),
                coeffs,
                state_bytes=float(state_bytes),
                replicas=replicas,
                save_interval_steps=save_interval_steps,
            )
            rows.append({
                "mode": "simulated",
                "collective": "allreduce",
                "impl": "recovery",
                "world": world,
                "size_bytes": int(nbytes),
                "state_bytes": state_bytes,
                "replicas": replicas,
                "save_interval_steps": int(save_interval_steps),
                "baseline_step_comm_us": round(
                    cost["baseline_step_comm_s"] * 1e6, 3
                ),
                "replication_overhead_us": round(
                    cost["replication_overhead_s"] * 1e6, 3
                ),
                "replication_overhead_ratio": round(
                    cost["replication_overhead_ratio"], 6
                ),
                # the acceptance bound: replica upkeep must stay in the
                # piggyback window's noise, not become a second collective
                "overhead_ok": cost["replication_overhead_ratio"] < 0.05,
                "replica_repair_us": round(cost["replica_repair_s"] * 1e6, 3),
                "ckpt_reload_us": round(cost["ckpt_reload_s"] * 1e6, 3),
                "repair_speedup": round(cost["repair_speedup"], 3),
                "overhead_break_even_steps": (
                    round(cost["overhead_break_even_steps"], 1)
                    if cost["overhead_break_even_steps"] != float("inf")
                    else None
                ),
                "calibration": model.source,
            })
    if not rows:
        raise ValueError(
            f"recovery sweep produced no rows: worlds={worlds} "
            f"sizes={list(sizes)}"
        )
    return rows


def adapt_sweep(
    world: int,
    sizes: Sequence[int],
    hosts: int = 2,
    model: Optional[LinkCostModel] = None,
    drift_factor: float = 2.0,
    drift_window: int = 4,
    drift_onset: int = 4,
    steps: int = 16,
    degrade: float = 8.0,
) -> List[dict]:
    """Deterministic closed-adaptation-loop rows — the hardware-free
    regression artifact for drift → re-calibration → re-rank → hot swap
    (``make adapt-bench``, docs/ADAPT.md).

    Two row families per payload size:

    - **timeline** rows replay one drift incident through the REAL
      :class:`~adapcc_tpu.adapt.DriftDetector`: per step, the observed
      dispatch time is the calibrated model's own prediction (healthy
      before ``drift_onset``, every DCN link ``degrade``× slower after —
      exactly what a live run's medians converge to), with the detector's
      ratio and fired bit stamped per step.  Detection lag (steps from
      onset to fire) falls out of the rows.
    - the **summary** row prices the incident end to end: the stale
      strategy's steady state under the degraded costs vs the re-ranked
      winner's (the sim-rank pass over the synthesizer's own candidate
      pool, flat-ring incumbent listed first), and the two one-time
      stalls — ``hot_swap_stall_us`` (the standby-cached epoch swap) vs
      ``full_rebuild_stall_us`` (probe traffic + re-synthesis + cold
      compile) via :func:`adapcc_tpu.sim.cost_model.adaptation_cost`, with
      each arm's break-even step count.  Hot-swap stall is strictly below
      the full rebuild's by construction — the acceptance property the
      regression test pins.

    Deterministic: no RNG, no wall clock — same calibration →
    byte-identical rows.
    """
    from adapcc_tpu import sim
    from adapcc_tpu.adapt import DriftDetector
    from adapcc_tpu.sim.cost_model import (
        DCN,
        LinkCostModel as _Model,
        adaptation_cost,
        bottleneck_ring_coeffs,
    )
    from adapcc_tpu.strategy.ir import Strategy
    from adapcc_tpu.tuner.db import TuningDatabase, TuningKey, size_bucket
    from adapcc_tpu.tuner.policy import TuningPolicy

    if drift_onset < drift_window:
        raise ValueError(
            f"drift_onset ({drift_onset}) must be >= drift_window "
            f"({drift_window}): the detector needs one healthy window "
            "before the incident or the control property is untestable"
        )
    if steps <= drift_onset:
        raise ValueError(f"steps ({steps}) must exceed onset ({drift_onset})")
    if degrade <= 1.0:
        raise ValueError(f"degrade must be > 1, got {degrade}")
    if model is None:
        model = load_or_default(world=world)
    elif model.world != world:
        raise ValueError(f"model world {model.world} != sweep world {world}")
    ips = {r: ip for r, ip in enumerate(_ip_table(world, max(2, hosts)))}
    if model.ips is None:
        model = model.with_ips(ips)
    else:
        ips = model.ips
    # the degraded network: every DCN link `degrade`x slower (class + any
    # per-link fits), ICI untouched — the inter-host drift the reference's
    # variability study measures
    classes = dict(model.classes)
    classes[DCN] = classes[DCN].scaled(degrade)
    links = {
        l: (c.scaled(degrade) if model.link_class_of(*l) == DCN else c)
        for l, c in model.links.items()
    }
    degraded_model = _Model(
        world, links=links, classes=classes, ips=ips,
        source=model.source + f"+dcn-x{degrade:g}",
    )

    def _pred(m: LinkCostModel, key: TuningKey, nbytes: int) -> float:
        return TuningPolicy(
            TuningDatabase(persist=False), world, "adapt-sweep", cost_model=m
        ).prior_time(key, nbytes)

    rows: List[dict] = []
    for nbytes in sizes:
        nbytes = int(nbytes)
        key = TuningKey(
            "allreduce", size_bucket(nbytes), world, "adapt-sweep",
            "xla", 0, "off",
        )
        detector = DriftDetector(
            world, "adapt-sweep", cost_model=model,
            factor=drift_factor, window=drift_window,
        )
        healthy_obs = _pred(model, key, nbytes)
        degraded_obs = _pred(degraded_model, key, nbytes)
        detection_step: Optional[int] = None
        for step in range(steps):
            obs = healthy_obs if step < drift_onset else degraded_obs
            detector.observe(key, obs, ts=float(step), nbytes=nbytes)
            report = detector.check()
            fired = report.drifted
            if fired and detection_step is None:
                detection_step = step
            signal = report.signals[0] if report.signals else None
            rows.append({
                "mode": "simulated",
                "collective": "allreduce",
                "impl": "adapt",
                "phase": "timeline",
                "world": world,
                "size_bytes": nbytes,
                "step": step,
                "degraded": step >= drift_onset,
                "observed_us": round(obs * 1e6, 3),
                "predicted_us": (
                    round(signal.reference_s * 1e6, 3) if signal else None
                ),
                "ratio": round(signal.ratio, 6) if signal else None,
                "fired": fired,
                "calibration": model.source,
            })
        # the re-rank: the synthesizer's own candidate pool under the
        # degraded costs, flat-ring incumbent (the stale strategy) first
        incumbent = Strategy.ring(world, 1, ips)
        candidates = [("incumbent", incumbent)] + strategy_candidates(
            world, SIM_STRATEGIES, degraded_model, ips, degree=1
        )
        ranked = sim.rank_candidates(
            candidates, degraded_model, nbytes, "allreduce"
        )
        stale = next(r.seconds for r in ranked if r.label == "incumbent")
        winner = ranked[0]
        cost = adaptation_cost(
            world, nbytes, bottleneck_ring_coeffs(model, world),
            stale_steady_s=stale, adapted_steady_s=winner.seconds,
        )
        rows.append({
            "mode": "simulated",
            "collective": "allreduce",
            "impl": "adapt",
            "phase": "summary",
            "world": world,
            "size_bytes": nbytes,
            "drift_factor": float(drift_factor),
            "drift_window": int(drift_window),
            "drift_onset_step": int(drift_onset),
            "detection_step": detection_step,
            "detection_lag_steps": (
                detection_step - drift_onset
                if detection_step is not None else None
            ),
            "degrade": float(degrade),
            "adapted_label": winner.label,
            "stale_steady_us": round(cost["stale_steady_s"] * 1e6, 3),
            "adapted_steady_us": round(cost["adapted_steady_s"] * 1e6, 3),
            "hot_swap_stall_us": round(cost["hot_swap_stall_s"] * 1e6, 3),
            "full_rebuild_stall_us": round(
                cost["full_rebuild_stall_s"] * 1e6, 3
            ),
            "hot_swap_break_even_steps": (
                round(cost["hot_swap_break_even_steps"], 3)
                if cost["hot_swap_break_even_steps"] != float("inf") else None
            ),
            "full_rebuild_break_even_steps": (
                round(cost["full_rebuild_break_even_steps"], 3)
                if cost["full_rebuild_break_even_steps"] != float("inf")
                else None
            ),
            "recovered": winner.seconds < stale,
            "calibration": model.source,
        })
    if not rows:
        raise ValueError(f"adapt sweep produced no rows: sizes={list(sizes)}")
    return rows


def fabric_sweep(
    world: int,
    sizes: Sequence[int],
    intensities: Sequence[float] = (1.0, 2.0, 4.0),
    mixes: Sequence[str] = ("high-low", "high-high"),
    share_penalty: float = 2.0,
    model: Optional[LinkCostModel] = None,
) -> List[dict]:
    """Deterministic multi-tenant fabric rows — the hardware-free
    regression artifact for priority-aware synthesis and graceful QoS
    yielding (``make fabric-bench``, docs/FABRIC.md).

    The grid is (payload size × background congestion intensity ×
    priority mix) on a fixed two-pod split of ``--world``:

    - **intensity** scales the shared DCN class's effective bandwidth
      (β × intensity, α intact — ambient neighbor traffic both tenants
      suffer, :meth:`LinkCostModel.contended`);
    - mix ``"high-low"`` is the coordinated fabric: the low-priority
      job's candidates are ranked under the high-priority job's link
      occupancy (contended by the share penalty), so its winning tree
      yields the high job's hot links;
    - mix ``"high-high"`` is the uncoordinated baseline: two equal
      tenants greedily pick the clean-network winner and pile onto the
      same links.

    Every row carries both jobs' priced steady states under the final
    shared fabric, Jain's fairness index, and aggregate throughput; the
    ``high-low`` rows additionally stamp ``high_beats_uncoordinated`` —
    the acceptance property that priority coordination makes the high
    job's sharing steady state strictly better than the pile-up.
    Deterministic: no RNG, no wall clock — same calibration →
    byte-identical rows.
    """
    from adapcc_tpu.adapt.fabric import SharedFabric

    if world < 4 or world % 2:
        raise ValueError(
            f"fabric sweep needs an even world >= 4 (two pods of world/2), "
            f"got {world}"
        )
    bad = [m for m in mixes if m not in ("high-low", "high-high")]
    if bad:
        raise ValueError(
            f"unknown priority mixes {bad}; expected a subset of "
            "['high-low', 'high-high']"
        )
    if any(i < 1.0 for i in intensities):
        raise ValueError(
            f"congestion intensities must be >= 1, got {list(intensities)}"
        )
    if model is None:
        model = load_or_default(world=world)
    elif model.world != world:
        raise ValueError(f"model world {model.world} != sweep world {world}")
    table = _ip_table(world, 2)
    ips = {r: ip for r, ip in enumerate(table)}
    base = model.with_ips(ips)

    def _plan(ambient, mix: str):
        fab = SharedFabric(ambient, table, share_penalty=share_penalty)
        if mix == "high-low":
            fab.add_job("job0", priority="high", nbytes=nbytes)
            fab.add_job("job1", priority="low", nbytes=nbytes)
            return fab.plan(coordinated=True)
        fab.add_job("job0", priority="high", nbytes=nbytes)
        fab.add_job("job1", priority="high", nbytes=nbytes)
        return fab.plan(coordinated=False)

    rows: List[dict] = []
    for nbytes in sizes:
        nbytes = int(nbytes)
        for intensity in intensities:
            intensity = float(intensity)
            ambient = (
                base.contended({DCN: intensity}) if intensity > 1.0 else base
            )
            plans = {mix: _plan(ambient, mix) for mix in mixes}
            baseline = plans.get("high-high") or _plan(ambient, "high-high")
            for mix in mixes:
                plan = plans[mix]
                j0, j1 = plan.job("job0"), plan.job("job1")
                row = {
                    "mode": "simulated",
                    "collective": "allreduce",
                    "impl": "fabric",
                    "world": world,
                    "size_bytes": nbytes,
                    "intensity": intensity,
                    "mix": mix,
                    "share_penalty": float(share_penalty),
                    "coordinated": plan.coordinated,
                    "job0_strategy": j0.label,
                    "job1_strategy": j1.label,
                    "job0_us": round(j0.shared_s * 1e6, 3),
                    "job1_us": round(j1.shared_s * 1e6, 3),
                    "job0_alone_us": round(j0.alone_s * 1e6, 3),
                    "job1_alone_us": round(j1.alone_s * 1e6, 3),
                    "shared_links": len(plan.shared_links),
                    "fairness": round(plan.fairness(), 6),
                    "throughput_gbps": round(plan.throughput_gbps(), 6),
                    "calibration": model.source,
                }
                if mix == "high-low":
                    row["high_beats_uncoordinated"] = (
                        j0.shared_s < baseline.job("job0").shared_s
                    )
                rows.append(row)
    if not rows:
        raise ValueError(
            f"fabric sweep produced no rows: sizes={list(sizes)} "
            f"intensities={list(intensities)} mixes={list(mixes)}"
        )
    return rows


def serve_sweep(
    world: int,
    rates: Sequence[float] = (0.05, 0.1, 0.25),
    slots_grid: Sequence[int] = (1, 2, 4, 8),
    num_requests: int = 64,
    n_layer: int = 2,
    d_model: int = 128,
    seed: int = 0,
    slo_ms: Optional[float] = None,
    model: Optional[LinkCostModel] = None,
) -> List[dict]:
    """Deterministic latency/throughput frontier rows for the serving
    plane — the hardware-free regression artifact for the continuous
    batcher (``make serve-bench``, docs/SERVING.md §5).

    The grid is (arrival rate × decode slots) on one seeded Poisson
    trace per rate (:func:`adapcc_tpu.serve.trace
    .synthesize_arrival_trace` — the SAME module the live server
    replays, so the sweep and the workload can never price different
    traffic).  Each cell:

    - prices the decode step with :func:`adapcc_tpu.sim.cost_model
      .decode_step_time` — per layer, a ``slots × d_model`` allreduce on
      the calibrated coefficients, the algorithm chosen by the selector's
      own crossover (at serving sizes: the small-message plane);
    - replays the trace through :func:`adapcc_tpu.sim.cost_model
      .simulate_serve_queue`, the queueing twin of the batcher's
      admission discipline, for p50/p99 sojourn on the step clock;
    - stamps throughput, utilization, and (with ``slo_ms``) SLO
      attainment — the frontier an admission policy trades along.

    Deterministic: the trace is seeded ``jax.random``, the replay is
    analytic — same calibration, same seed → byte-identical rows.
    """
    from adapcc_tpu.serve.trace import synthesize_arrival_trace
    from adapcc_tpu.sim.cost_model import (
        bottleneck_ring_coeffs,
        decode_step_time,
        serve_queue_metrics,
    )

    if world < 1:
        raise ValueError(f"world must be >= 1, got {world}")
    if num_requests < 1:
        raise ValueError(f"num_requests must be >= 1, got {num_requests}")
    if any(r <= 0 for r in rates):
        raise ValueError(
            f"arrival rates must be > 0 requests/step, got {list(rates)}"
        )
    if any(s < 1 for s in slots_grid):
        raise ValueError(f"slot counts must be >= 1, got {list(slots_grid)}")
    if model is None:
        model = load_or_default(world=world)
    elif model.world != world:
        raise ValueError(f"model world {model.world} != sweep world {world}")
    coeffs = bottleneck_ring_coeffs(model, max(2, world))
    rows: List[dict] = []
    for rate in rates:
        rate = float(rate)
        trace = synthesize_arrival_trace(
            world, num_requests, rate, seed=seed,
            label=f"serve-sweep-r{rate:g}",
        )
        arrivals = [r.arrival_step for r in trace.requests]
        services = [r.service_steps for r in trace.requests]
        generated = [r.max_new_tokens for r in trace.requests]
        for slots in slots_grid:
            slots = int(slots)
            step = decode_step_time(
                world, slots, n_layer, d_model, coeffs
            )
            metrics = serve_queue_metrics(
                arrivals, services, slots,
                float(step["step_time_s"]), slo_ms=slo_ms,
                generated_steps=generated,
            )
            row = {
                "mode": "simulated",
                "collective": "allreduce",
                "impl": "serve",
                "world": world,
                "slots": slots,
                "rate_req_per_step": rate,
                "requests": num_requests,
                "trace_seed": seed,
                "n_layer": n_layer,
                "d_model": d_model,
                "algo": step["algo"],
                "collective_bytes": step["collective_bytes"],
                "pred_step_us": round(float(step["step_time_s"]) * 1e6, 3),
                "pred_comm_us": round(float(step["comm_s"]) * 1e6, 3),
                "p50_sojourn_steps": int(metrics["p50_sojourn_steps"]),
                "p99_sojourn_steps": int(metrics["p99_sojourn_steps"]),
                "p50_sojourn_ms": round(metrics["p50_sojourn_ms"], 6),
                "p99_sojourn_ms": round(metrics["p99_sojourn_ms"], 6),
                "p99_queue_steps": int(metrics["p99_queue_steps"]),
                "throughput_tok_s": round(metrics["throughput_tok_s"], 3),
                "utilization": round(metrics["utilization"], 6),
                "calibration": model.source,
            }
            if slo_ms is not None:
                row["slo_ms"] = float(slo_ms)
                row["slo_attainment"] = round(metrics["slo_attainment"], 6)
            rows.append(row)
    if not rows:
        raise ValueError(
            f"serve sweep produced no rows: rates={list(rates)} "
            f"slots={list(slots_grid)}"
        )
    return rows


#: request mixes of the disaggregation frontier: (prompt range, max-new
#: range) — "prefill-heavy" is prompt-dominated traffic (long contexts,
#: short answers), "decode-heavy" the inverse (chat tails)
DISAGG_MIXES = {
    "prefill-heavy": ((24, 48), (4, 8)),
    "balanced": ((8, 16), (8, 16)),
    "decode-heavy": ((4, 8), (24, 48)),
}


def disagg_sweep(
    world: int,
    mixes: Sequence[str] = ("prefill-heavy", "balanced", "decode-heavy"),
    splits: Sequence[str] = ("1:1", "3:1"),
    dims: Sequence[int] = (128, 256),
    rate: float = 0.05,
    num_requests: int = 64,
    total_slots: int = 8,
    n_layer: int = 2,
    seed: int = 0,
    slo_ms: Optional[float] = None,
    model: Optional[LinkCostModel] = None,
) -> List[dict]:
    """The colocated-vs-disaggregated serving frontier (``make
    disagg-bench``, docs/SERVING.md §7): for each (request mix × pool
    split × d_model) cell, the SAME seeded arrival trace is priced both
    ways at **equal chip count and equal total KV-lane budget** (slots
    follow chips — lane count is bounded by per-chip KV HBM, so a pod
    with ``k`` of the chips gets ``k``'s share of the lanes):

    - **disaggregated**: a prefill pod and a decode pod splitting
      ``--world`` per ``split`` (``"3:1"`` = three quarters of the chips
      prefill), each pod's step priced by :func:`decode_step_time` at
      its own world and lane count, the KV handoff priced on the
      calibrated **DCN** α-β (mean-prompt page bytes, ceil'd to router
      ticks), the tandem queue replayed by
      :func:`~adapcc_tpu.sim.cost_model.disagg_queue_metrics`;
    - **colocated**: one ``--world``-wide batcher with all
      ``total_slots`` lanes, replayed by :func:`serve_queue_metrics`
      (TTFT recovered from the admission triples).

    Each row stamps ``disagg_beats_colocated_p99_ttft`` — the frontier
    claim the regression suite pins: half-world pods pay fewer α hops
    and smaller per-step payloads per token, so prefill-heavy traffic at
    moderate load beats the colocated tail on p99 TTFT **ms**, while the
    queueing twin prices exactly where the smaller prefill pool's queue
    eats the win (rate up → colocated's 2× lanes win back).
    Deterministic: seeded trace, analytic replay — byte-identical rows.
    """
    from adapcc_tpu.serve.trace import synthesize_arrival_trace
    from adapcc_tpu.sim.cost_model import (
        DCN,
        bottleneck_ring_coeffs,
        decode_step_time,
        disagg_queue_metrics,
        serve_queue_metrics,
        simulate_serve_queue,
    )
    from adapcc_tpu.utils.observability import nearest_rank_percentile

    if world < 2:
        raise ValueError(
            f"world must be >= 2 to split into two pods, got {world}"
        )
    if num_requests < 1:
        raise ValueError(f"num_requests must be >= 1, got {num_requests}")
    if rate <= 0:
        raise ValueError(f"arrival rate must be > 0, got {rate}")
    if total_slots < 2:
        raise ValueError(
            f"total_slots must be >= 2 (one lane per pool), got "
            f"{total_slots}"
        )
    unknown = [m for m in mixes if m not in DISAGG_MIXES]
    if unknown:
        raise ValueError(
            f"unknown request mix(es) {unknown}; expected "
            f"{sorted(DISAGG_MIXES)}"
        )
    if model is None:
        model = load_or_default(world=world)
    elif model.world != world:
        raise ValueError(f"model world {model.world} != sweep world {world}")
    dcn = model.classes[DCN]
    rows: List[dict] = []
    for mix in mixes:
        prompt_rng, new_rng = DISAGG_MIXES[mix]
        trace = synthesize_arrival_trace(
            world, num_requests, float(rate), seed=seed,
            prompt_len=prompt_rng, max_new_tokens=new_rng,
            label=f"disagg-sweep-{mix}",
        )
        arrivals = [r.arrival_step for r in trace.requests]
        prompts = [len(r.prompt) for r in trace.requests]
        prefills = prompts  # one forced step per prompt token
        decodes = [r.max_new_tokens - 1 for r in trace.requests]
        services = [p + d for p, d in zip(prefills, decodes)]  # total - 1
        generated = [r.max_new_tokens for r in trace.requests]
        mean_prompt = sum(prompts) / len(prompts)
        for split in splits:
            try:
                p_share, d_share = (int(x) for x in split.split(":"))
            except ValueError as e:
                raise ValueError(
                    f"pool split {split!r} is not 'P:D' integers"
                ) from e
            parts = p_share + d_share
            if p_share < 1 or d_share < 1:
                raise ValueError(
                    f"pool split {split!r}: both shares must be >= 1"
                )
            if world % parts or total_slots % parts:
                raise ValueError(
                    f"pool split {split!r} does not divide world={world} "
                    f"and total_slots={total_slots} into whole pods"
                )
            pw = world * p_share // parts
            dw = world - pw
            ps = total_slots * p_share // parts
            ds = total_slots - ps
            for d_model in dims:
                d_model = int(d_model)
                p_step = decode_step_time(
                    pw, ps, n_layer, d_model,
                    bottleneck_ring_coeffs(model, max(2, pw)),
                )
                d_step = decode_step_time(
                    dw, ds, n_layer, d_model,
                    bottleneck_ring_coeffs(model, max(2, dw)),
                )
                c_step = decode_step_time(
                    world, total_slots, n_layer, d_model,
                    bottleneck_ring_coeffs(model, max(2, world)),
                )
                tick_s = max(
                    float(p_step["step_time_s"]),
                    float(d_step["step_time_s"]),
                )
                # the migrated payload: the filled KV prefix of a mean
                # prompt (K and V, all layers, fp32), on the DCN wire
                kv_bytes = 2 * n_layer * mean_prompt * d_model * 4
                transfer_steps = int(math.ceil(dcn.time(kv_bytes) / tick_s))
                dm = disagg_queue_metrics(
                    arrivals, prefills, decodes, ps, ds, transfer_steps,
                    float(p_step["step_time_s"]),
                    float(d_step["step_time_s"]), slo_ms=slo_ms,
                )
                cm = serve_queue_metrics(
                    arrivals, services, total_slots,
                    float(c_step["step_time_s"]), slo_ms=slo_ms,
                    generated_steps=generated,
                )
                triples = simulate_serve_queue(
                    arrivals, services, total_slots
                )
                coloc_ttfts = sorted(
                    adm + p - a
                    for (a, adm, _), p in zip(triples, prefills)
                )
                coloc_p99_ttft = int(
                    nearest_rank_percentile(coloc_ttfts, 0.99)
                )
                coloc_step_s = float(c_step["step_time_s"])
                row = {
                    "mode": "simulated",
                    "collective": "allreduce",
                    "impl": "disagg",
                    "world": world,
                    "mix": mix,
                    "split": split,
                    "rate_req_per_step": float(rate),
                    "requests": num_requests,
                    "trace_seed": seed,
                    "n_layer": n_layer,
                    "d_model": d_model,
                    "prefill_world": pw,
                    "decode_world": dw,
                    "prefill_slots": ps,
                    "decode_slots": ds,
                    "coloc_slots": total_slots,
                    "transfer_steps": transfer_steps,
                    "kv_bytes_mean": int(kv_bytes),
                    "prefill_algo": p_step["algo"],
                    "decode_algo": d_step["algo"],
                    "coloc_algo": c_step["algo"],
                    "pred_prefill_step_us": round(
                        float(p_step["step_time_s"]) * 1e6, 3
                    ),
                    "pred_decode_step_us": round(
                        float(d_step["step_time_s"]) * 1e6, 3
                    ),
                    "pred_coloc_step_us": round(coloc_step_s * 1e6, 3),
                    "p50_ttft_ms": round(dm["p50_ttft_ms"], 6),
                    "p99_ttft_steps": int(dm["p99_ttft_steps"]),
                    "p99_ttft_ms": round(dm["p99_ttft_ms"], 6),
                    "p99_sojourn_ms": round(dm["p99_sojourn_ms"], 6),
                    "p99_queue_steps": int(dm["p99_queue_steps"]),
                    "p99_decode_wait_steps": int(
                        dm["p99_decode_wait_steps"]
                    ),
                    "throughput_tok_s": round(dm["throughput_tok_s"], 3),
                    "prefill_utilization": round(
                        dm["prefill_utilization"], 6
                    ),
                    "decode_utilization": round(
                        dm["decode_utilization"], 6
                    ),
                    "coloc_p99_ttft_steps": coloc_p99_ttft,
                    "coloc_p99_ttft_ms": round(
                        coloc_p99_ttft * coloc_step_s * 1e3, 6
                    ),
                    "coloc_p99_sojourn_ms": round(
                        cm["p99_sojourn_ms"], 6
                    ),
                    "coloc_throughput_tok_s": round(
                        cm["throughput_tok_s"], 3
                    ),
                    "disagg_beats_colocated_p99_ttft": bool(
                        dm["p99_ttft_ms"]
                        < coloc_p99_ttft * coloc_step_s * 1e3
                    ),
                    "calibration": model.source,
                }
                if slo_ms is not None:
                    row["slo_ms"] = float(slo_ms)
                    row["slo_attainment"] = round(
                        dm["slo_attainment"], 6
                    )
                    row["coloc_slo_attainment"] = round(
                        cm["slo_attainment"], 6
                    )
                rows.append(row)
    if not rows:
        raise ValueError(
            f"disagg sweep produced no rows: mixes={list(mixes)} "
            f"splits={list(splits)} dims={list(dims)}"
        )
    return rows


def tune_replay_sweep(
    world: int,
    sizes: Sequence[int],
    chunk_grid: Optional[Sequence[int]] = None,
    model: Optional[LinkCostModel] = None,
    trial_budget: int = 4,
    exploit_rounds: int = 8,
) -> List[dict]:
    """Deterministic tuner-convergence rows on a synthetic cost surface —
    the hardware-free regression artifact for the autotuner
    (``make tune-bench``).

    For each payload size the sweep builds a fresh in-memory tuning
    database and a :class:`adapcc_tpu.tuner.TuningPolicy`, then runs the
    policy against a synthetic "true" cost surface: the sim cost model's
    per-cell prediction warped by a deterministic per-cell factor (hash of
    the cell, ±25%) so the measured optimum *disagrees* with the prior
    somewhere — the exact situation the tuner exists for.  Exploration runs
    at epsilon=1 until every cell meets its trial budget, then
    ``exploit_rounds`` greedy rounds settle the incumbent.  One row per
    cell, ``chosen`` flagging the policy's final plan and ``surface_best``
    the true argmin, so the artifact shows both the decision and whether it
    converged.  Everything is seeded/hashed: same inputs → byte-identical
    rows.
    """
    import hashlib

    from adapcc_tpu.tuner import TuningDatabase
    from adapcc_tpu.tuner.policy import DEFAULT_CHUNK_GRID, TuningPolicy

    if chunk_grid is None:
        chunk_grid = DEFAULT_CHUNK_GRID
    if model is None:
        model = load_or_default(world=world)
    elif model.world != world:
        raise ValueError(f"model world {model.world} != sweep world {world}")

    def cell_factor(key) -> float:
        digest = hashlib.md5(repr(key).encode()).digest()
        return 0.75 + 0.5 * (digest[0] / 255.0)  # deterministic, in [0.75, 1.25]

    def sample_jitter(key, i: int) -> float:
        digest = hashlib.md5(f"{key!r}#{i}".encode()).digest()
        return 0.98 + 0.04 * (digest[0] / 255.0)  # ±2% around the cell truth

    rows: List[dict] = []
    for nbytes in sizes:
        db = TuningDatabase(persist=False)  # the replay must not write repo
        # artifacts; epsilon=1 fills the grid deterministically (seeded rng)
        policy = TuningPolicy(
            db, world, topology="tune-replay", chunk_grid=chunk_grid,
            epsilon=1.0, trial_budget=trial_budget, cost_model=model, seed=0,
            # the replay is a synthetic surface, not a data plane: force the
            # fused-path cells in so the artifact pins the full grid (chunk
            # × codec × path) on any build, TPU or not
            fused_paths=True,
        )
        cells = policy.candidates("allreduce", int(nbytes))
        surface = {
            c: policy.prior_time(c, int(nbytes)) * cell_factor(c) for c in cells
        }
        counts = {c: 0 for c in cells}
        for _ in range(trial_budget * len(cells) + exploit_rounds):
            plan = policy.choose("allreduce", int(nbytes))
            i = counts[plan.key] = counts[plan.key] + 1
            db.record(
                plan.key,
                surface[plan.key] * sample_jitter(plan.key, i),
                ts=float(i),
            )
        final = policy.choose("allreduce", int(nbytes))
        best_true = min(cells, key=lambda c: (surface[c], cells.index(c)))
        for cell in cells:
            stats = db.stats(cell)
            rows.append({
                "mode": "simulated",
                "collective": "allreduce",
                "impl": "tuner",
                "world": world,
                "size_bytes": int(nbytes),
                "path": cell.path,
                "chunk_bytes": cell.chunk_bytes,
                "wire_dtype": cell.wire_dtype,
                "samples": stats.count if stats else 0,
                "median_us": round(stats.median_s * 1e6, 3) if stats else None,
                "surface_us": round(surface[cell] * 1e6, 3),
                "prior_us": round(policy.prior_time(cell, int(nbytes)) * 1e6, 3),
                "chosen": cell == final.key,
                "choice_source": final.source if cell == final.key else None,
                "surface_best": cell == best_true,
                "converged": final.key == best_true,
                "calibration": model.source,
            })
    if not rows:
        raise ValueError(f"tune replay produced no rows: sizes={list(sizes)}")
    return rows


#: default --scale-worlds grid: pod scale, where only the vectorized
#: engine replays in seconds (docs/SIMULATION.md §7)
SCALE_WORLDS = (1024, 4096, 16384)

#: largest world the ring schedule is priced at in the scale sweep — a
#: ring is ``world`` rounds deep, so its replay cost grows linearly with
#: world even on the vectorized engine; past this the sweep emits an
#: explicit skip row instead of silently dropping the shape
RING_SCALE_MAX_WORLD = 16384


def scale_sweep(
    worlds: Sequence[int],
    sizes: Sequence[int],
    collective: str = "allreduce",
    degree: int = 1,
) -> List[dict]:
    """Replay-scaling grid: (world × size × strategy) priced on a uniform
    synthetic topology, every row stamped with its certified
    ``optimality_gap`` against the α-β collective lower bound
    (docs/SIMULATION.md §7).

    Strategies are constructed directly (``Strategy.ring`` /
    ``Strategy.binary``) — never via :func:`strategy_candidates`, whose
    ``to_graphs()`` materializes an O(world²) matrix that is exactly the
    scaling wall this sweep exists to demonstrate the engine clears.  Rows
    carry no wall-clock times, so two runs of the same grid are
    byte-identical (the measured replay-latency rows live in
    ``benchmarks.synthesis_scale``, which is allowed to be nondeterministic).
    """
    if collective not in SIM_COLLECTIVES:
        raise ValueError(
            f"unknown collective {collective!r}; "
            f"expected one of {SIM_COLLECTIVES}"
        )
    bad = [w for w in worlds if w < 2]
    if bad:
        raise ValueError(f"scale sweep worlds must be >= 2, got {bad}")
    rows: List[dict] = []
    for world in worlds:
        # per-world uniform model: O(#classes) memory, deterministic, and
        # source="synthetic" so the calibration column is honest about it
        model = LinkCostModel.uniform(world)
        engine = resolve_sim_engine(None, world)
        lower = {
            int(n): collective_lower_bound(model, n, collective, world)
            for n in sizes
        }
        candidates: List[Tuple[str, Optional[Strategy]]] = [
            ("binary", Strategy.binary(world, degree)),
            (
                "ring",
                Strategy.ring(world, degree)
                if world <= RING_SCALE_MAX_WORLD
                else None,
            ),
        ]
        for nbytes in sizes:
            for label, strategy in candidates:
                if strategy is None:
                    rows.append({
                        "mode": "simulated",
                        "collective": collective,
                        "world": world,
                        "size_bytes": int(nbytes),
                        "strategy": label,
                        "skipped": (
                            f"ring is {world} rounds deep; capped at "
                            f"--scale-worlds <= {RING_SCALE_MAX_WORLD}"
                        ),
                        "calibration": model.source,
                    })
                    continue
                timeline = simulate_strategy(
                    strategy, model, nbytes, collective, keep_transfers=False
                )
                row = _finish_row(timeline.to_row(), collective, world)
                row["strategy"] = label
                row["engine"] = engine
                lb = lower[int(nbytes)]
                row["lower_bound_us"] = round(lb * 1e6, 3)
                row["optimality_gap"] = round(
                    optimality_gap(timeline.seconds, lb), 6
                )
                row["calibration"] = model.source
                rows.append(row)
    if not rows:
        raise ValueError(
            f"scale sweep produced no rows: worlds={list(worlds)} "
            f"sizes={list(sizes)}"
        )
    return rows


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--world", type=int, default=8)
    ap.add_argument("--sizes", default="4K,1M,16M")
    ap.add_argument("--collectives", default=",".join(SIM_COLLECTIVES))
    ap.add_argument("--strategies", default=",".join(SIM_STRATEGIES))
    ap.add_argument(
        "--hosts", type=int, default=1,
        help="synthetic host count (>1 prices DCN edges between hosts)",
    )
    ap.add_argument(
        "--degree", type=int, default=1, help="parallel transmissions per strategy"
    )
    ap.add_argument(
        "--calibration", default=DEFAULT_CALIBRATION_PATH,
        help="calibration artifact path (synthetic defaults when absent)",
    )
    ap.add_argument("--no-flow-lp", action="store_true")
    ap.add_argument(
        "--ring-sweep", action="store_true",
        help="sweep the staged Pallas ring over --chunks instead of the "
        "strategy grid (chunk-size tuning rows, make ring-sweep)",
    )
    ap.add_argument(
        "--chunks", default="256K,1M,4M,16M",
        help="ring-sweep staging granularities (chunk_bytes grid)",
    )
    ap.add_argument(
        "--wire-dtype", default="",
        help="comma list of wire codecs (off,bf16,int8): sweep the "
        "quantized ring's codec A/B instead of the strategy grid, priced "
        "by the sim-rank cost-model term (make quant-bench)",
    )
    ap.add_argument(
        "--fused-sweep", action="store_true",
        help="price the FUSED quantized streaming ring against the unfused "
        "ppermute reroute over (size x wire_dtype x chunk_bytes), crossover "
        "size flagged per row (make fused-bench; docs/RING.md)",
    )
    ap.add_argument(
        "--fused-wire", default="bf16,int8",
        help="fused-sweep codec grid (codecs the fused kernels speak)",
    )
    ap.add_argument(
        "--tune-replay", action="store_true",
        help="replay the autotuner's policy against a deterministic "
        "synthetic cost surface over the (chunk x codec) grid instead of "
        "the strategy grid: one row per cell with the chosen plan flagged "
        "per size (make tune-bench; docs/TUNER.md)",
    )
    ap.add_argument(
        "--fault-sweep", action="store_true",
        help="price elastic failover instead of the strategy grid: per-fault "
        "detection/swap/degraded summary rows plus a canonical fault plan's "
        "step-by-step timeline (make elastic-bench; docs/ELASTIC.md)",
    )
    ap.add_argument(
        "--heartbeat-timeout-s", type=float, default=1.0,
        help="fault-sweep heartbeat timeout priced into detection latency",
    )
    ap.add_argument(
        "--chaos-sweep", action="store_true",
        help="price the autonomous supervisor's out-of-band detection "
        "over the (heartbeat period x grace) grid — detection latency vs "
        "false-positive headroom — plus the canonical fault plan's "
        "deterministic chaos (SIGKILL/SIGSTOP) schedule (make "
        "chaos-bench; docs/SUPERVISOR.md)",
    )
    ap.add_argument(
        "--hb-periods", default="0.25,0.5,1,2",
        help="chaos-sweep heartbeat period grid (seconds)",
    )
    ap.add_argument(
        "--hb-graces", default="1,2,4",
        help="chaos-sweep confirmation-count grid",
    )
    ap.add_argument(
        "--recovery-sweep", action="store_true",
        help="price durable elastic recovery instead of the strategy "
        "grid: per-(world x payload) replication wire overhead vs "
        "baseline step comm, and the in-fabric shard repair vs a "
        "checkpoint reload (make recovery-bench; docs/RECOVERY.md)",
    )
    ap.add_argument(
        "--rec-worlds", default="8,32,64",
        help="recovery-sweep world grid",
    )
    ap.add_argument(
        "--rec-replicas", type=int, default=1,
        help="recovery-sweep shard replica count (k)",
    )
    ap.add_argument(
        "--rec-save-interval", type=int, default=100,
        help="recovery-sweep checkpoint save interval (steps) priced "
        "into the reload arm's lost work",
    )
    ap.add_argument(
        "--hier-sweep", action="store_true",
        help="price the composed two-level allreduce against the flat "
        "ring over a (pods x pod_size x size) grid, with the per-row "
        "two-level-vs-flat decision and the pod-count crossover flagged "
        "(make hier-bench; docs/HIERARCHY.md)",
    )
    ap.add_argument(
        "--pods", default="2,4,8",
        help="hier-sweep pod-count grid",
    )
    ap.add_argument(
        "--pod-sizes", default="4,8",
        help="hier-sweep ranks-per-pod grid",
    )
    ap.add_argument(
        "--latency-sweep", action="store_true",
        help="price the latency-bound allreduce algorithms (ring vs "
        "recursive doubling vs binomial tree) over --sizes instead of the "
        "strategy grid, with the per-size chosen algorithm and the ring-rd "
        "crossover flagged per row (make latency-bench; docs/LATENCY.md)",
    )
    ap.add_argument(
        "--algos", default="ring,rd,tree",
        help="latency-sweep algorithm grid",
    )
    ap.add_argument(
        "--schedule-sweep", action="store_true",
        help="price IR-lowered schedule programs (compiler.ScheduleProgram: "
        "ring/rd/tree re-emitted as IR plus the pipelined bidirectional "
        "schedule no hand-written plane expresses) over --sizes instead of "
        "the strategy grid, each verified then priced by "
        "schedule_program_time next to its legacy plane's pricing (make "
        "compiler-bench; docs/COMPILER.md)",
    )
    ap.add_argument(
        "--programs", default=",".join(SCHEDULE_PROGRAMS),
        help="schedule-sweep program grid",
    )
    ap.add_argument(
        "--adapt-sweep", action="store_true",
        help="replay the closed adaptation loop instead of the strategy "
        "grid: per-step drift-detection timeline rows plus a summary row "
        "pricing stale-vs-adapted steady state and hot-swap vs "
        "full-rebuild stall (make adapt-bench; docs/ADAPT.md)",
    )
    ap.add_argument(
        "--degrade-factor", type=float, default=8.0,
        help="adapt-sweep DCN slowdown injected at the drift onset",
    )
    ap.add_argument(
        "--fabric-sweep", action="store_true",
        help="price the multi-tenant fabric instead of the strategy grid: "
        "two prioritized jobs on a two-pod split of --world, over "
        "(congestion intensity x priority mix), with the coordinated "
        "high-low yield priced against the uncoordinated high-high "
        "pile-up per row (make fabric-bench; docs/FABRIC.md)",
    )
    ap.add_argument(
        "--intensities", default="1,2,4",
        help="fabric-sweep background DCN congestion factor grid",
    )
    ap.add_argument(
        "--serve-sweep", action="store_true",
        help="price the serving plane's latency/throughput frontier "
        "instead of the strategy grid: a seeded Poisson arrival trace "
        "replayed through the continuous batcher's queueing twin over "
        "(--rates x --serve-slots), each cell priced by the decode-step "
        "service time on the calibrated coefficients, p50/p99 sojourn "
        "and SLO attainment stamped per row (make serve-bench; "
        "docs/SERVING.md)",
    )
    ap.add_argument(
        "--rates", default="0.05,0.1,0.25",
        help="serve-sweep Poisson arrival-rate grid (requests per decode "
        "step)",
    )
    ap.add_argument(
        "--serve-slots", default="1,2,4,8",
        help="serve-sweep decode-slot grid (the continuous batcher's "
        "fixed lane count)",
    )
    ap.add_argument(
        "--serve-requests", type=int, default=64,
        help="serve-sweep requests per synthesized trace",
    )
    ap.add_argument(
        "--slo-ms", type=float, default=0.0,
        help="serve-sweep per-request sojourn SLO in milliseconds "
        "(0 = no SLO-attainment column)",
    )
    ap.add_argument(
        "--disagg-sweep", action="store_true",
        help="price the colocated-vs-disaggregated serving frontier "
        "instead of the strategy grid: one seeded arrival trace per "
        "request mix, replayed through the two-pool tandem queue "
        "(prefill pod -> DCN KV transfer -> decode pod) AND the "
        "colocated batcher at equal chip count, p99 TTFT verdict "
        "stamped per row (make disagg-bench; docs/SERVING.md §7)",
    )
    ap.add_argument(
        "--disagg-mixes", default="prefill-heavy,balanced,decode-heavy",
        help="disagg-sweep request-mix grid (prompt-vs-decode balance)",
    )
    ap.add_argument(
        "--disagg-splits", default="1:1,3:1",
        help="disagg-sweep prefill:decode chip-split grid (slots follow "
        "chips — the per-chip KV HBM budget)",
    )
    ap.add_argument(
        "--disagg-dims", default="128,256",
        help="disagg-sweep d_model grid",
    )
    ap.add_argument(
        "--disagg-slots", type=int, default=8,
        help="disagg-sweep TOTAL cluster lane budget (the colocated arm "
        "runs all of them in one pool)",
    )
    ap.add_argument(
        "--disagg-rate", type=float, default=0.05,
        help="disagg-sweep Poisson arrival rate (requests per step)",
    )
    ap.add_argument(
        "--overlap-sweep", action="store_true",
        help="price the overlapped DDP gradient sync over (accum x "
        "bucket cap x overlap schedule) with overlapped_step_time instead "
        "of the strategy grid (make overlap-bench; docs/OVERLAP.md)",
    )
    ap.add_argument(
        "--accums", default="1,2,4",
        help="overlap-sweep gradient-accumulation grid",
    )
    ap.add_argument(
        "--bucket-caps-mb", default="1,4",
        help="overlap-sweep bucket cap grid (MB)",
    )
    ap.add_argument(
        "--scale-sweep", action="store_true",
        help="replay-scaling grid instead of the strategy grid: "
        "(--scale-worlds x --sizes) priced on per-world uniform synthetic "
        "topologies through the vectorized engine, each row stamped with "
        "its certified optimality_gap against the α-β collective lower "
        "bound (make simscale-bench; docs/SIMULATION.md §7)",
    )
    ap.add_argument(
        "--scale-worlds", default=",".join(str(w) for w in SCALE_WORLDS),
        help="scale-sweep world grid (pod scale; ring is skipped above "
        f"{RING_SCALE_MAX_WORLD})",
    )
    ap.add_argument(
        "--pipe-sweep", action="store_true",
        help="price the GPipe-vs-1F1B pipeline frontier instead of the "
        "strategy grid: (stages x microbatches x hop bytes), each cell's "
        "verified hop program replayed next to the closed-form step time "
        "and stash bound (make pipe-bench; docs/PIPELINE.md)",
    )
    ap.add_argument(
        "--pipe-stages", default="2,4",
        help="pipe-sweep stage-count grid",
    )
    ap.add_argument(
        "--pipe-microbatches", default="2,4,8",
        help="pipe-sweep microbatch grid",
    )
    ap.add_argument(
        "--pipe-fwd-us", type=float, default=100.0,
        help="pipe-sweep per-stage forward compute term (microseconds)",
    )
    ap.add_argument("--json", action="store_true", help="one JSON row per line")
    args = ap.parse_args(argv)

    exclusive = [
        name for name, on in (
            ("--wire-dtype", bool(args.wire_dtype)),
            ("--ring-sweep", args.ring_sweep),
            ("--fused-sweep", args.fused_sweep),
            ("--tune-replay", args.tune_replay),
            ("--overlap-sweep", args.overlap_sweep),
            ("--hier-sweep", args.hier_sweep),
            ("--latency-sweep", args.latency_sweep),
            ("--schedule-sweep", args.schedule_sweep),
            ("--fault-sweep", args.fault_sweep),
            ("--adapt-sweep", args.adapt_sweep),
            ("--chaos-sweep", args.chaos_sweep),
            ("--fabric-sweep", args.fabric_sweep),
            ("--recovery-sweep", args.recovery_sweep),
            ("--serve-sweep", args.serve_sweep),
            ("--disagg-sweep", args.disagg_sweep),
            ("--scale-sweep", args.scale_sweep),
            ("--pipe-sweep", args.pipe_sweep),
        ) if on
    ]
    if len(exclusive) > 1:
        # different sweep grids over one --sizes axis: silently running one
        # and dropping the others would read as "ran fine, no data"
        ap.error(f"{' and '.join(exclusive)} are mutually exclusive; "
                 "run one sweep per invocation")
    if args.scale_sweep:
        if args.hosts > 1:
            # the sweep prices per-world uniform synthetic topologies;
            # silently accepting --hosts would read as "priced that host
            # split" when nothing used it (the --hier-sweep precedent)
            ap.error("--hosts has no effect on --scale-sweep (each world "
                     "is priced on its own uniform synthetic topology)")
        rows = scale_sweep(
            worlds=[int(w) for w in args.scale_worlds.split(",") if w],
            sizes=[parse_size(s) for s in args.sizes.split(",") if s],
            degree=args.degree,
        )
        for row in rows:
            if args.json:
                print(json.dumps(row))
            elif "skipped" in row:
                print(
                    f"[sim] scale world={row['world']:>6} "
                    f"{row['strategy']:<6} skipped: {row['skipped']}"
                )
            else:
                print(
                    f"[sim] scale world={row['world']:>6} "
                    f"{row['strategy']:<6} {row['size_bytes']:>10}B  "
                    f"pred={row['pred_time_us']:>10.1f}us  "
                    f"lb={row['lower_bound_us']:>10.1f}us  "
                    f"gap={row['optimality_gap']:>8.4f}  "
                    f"engine={row['engine']}"
                )
        return 0
    model = load_or_default(args.calibration, world=args.world)
    if args.pipe_sweep:
        if args.hosts > 1:
            # the sweep prices stage chains on the calibration's bottleneck
            # class; silently accepting --hosts would read as "priced that
            # host split" when nothing used it (the --hier-sweep precedent)
            ap.error("--hosts has no effect on --pipe-sweep (each stage "
                     "chain prices on the calibration's bottleneck link "
                     "class)")
        rows = pipe_sweep(
            sizes=[parse_size(s) for s in args.sizes.split(",") if s],
            stages_grid=[int(s) for s in args.pipe_stages.split(",") if s],
            microbatch_grid=[
                int(m) for m in args.pipe_microbatches.split(",") if m
            ],
            fwd_us=args.pipe_fwd_us,
            model=model,
        )
        for row in rows:
            if args.json:
                print(json.dumps(row))
            else:
                win = row.get("memory_win_vs_gpipe")
                print(
                    f"[sim] pipe {row['schedule']:<5} "
                    f"s={row['stages']:>2} m={row['microbatches']:>2} "
                    f"{row['size_bytes']:>10}B  "
                    f"bubble={row['bubble_fraction']:.3f}  "
                    f"step={row['pred_step_us']:>10.1f}us  "
                    f"hops={row['hop_program_us']:>9.1f}us  "
                    f"stash={row['stash_bytes']:>10}B"
                    + ("  mem-win" if win else "")
                )
        return 0
    if args.serve_sweep:
        if args.hosts > 1:
            # the frontier prices the TP decode mesh of --world; silently
            # accepting --hosts would read as "priced that host split"
            # when nothing used it (the --hier-sweep precedent)
            ap.error("--hosts has no effect on --serve-sweep (the decode "
                     "mesh is --world)")
        if args.slo_ms < 0:
            ap.error(f"--slo-ms must be >= 0, got {args.slo_ms}")
        rows = serve_sweep(
            world=args.world,
            rates=[float(r) for r in args.rates.split(",") if r],
            slots_grid=[int(s) for s in args.serve_slots.split(",") if s],
            num_requests=args.serve_requests,
            slo_ms=args.slo_ms if args.slo_ms > 0 else None,
            model=model,
        )
        for row in rows:
            if args.json:
                print(json.dumps(row))
            else:
                att = row.get("slo_attainment")
                print(
                    f"[sim] serve rate={row['rate_req_per_step']:>5g} "
                    f"slots={row['slots']:>2} algo={row['algo']:<4} "
                    f"step={row['pred_step_us']:>8.1f}us  "
                    f"p50={row['p50_sojourn_ms']:>9.3f}ms "
                    f"p99={row['p99_sojourn_ms']:>9.3f}ms  "
                    f"tok/s={row['throughput_tok_s']:>11.1f}  "
                    f"util={row['utilization']:.3f}"
                    + (f"  slo={att:.3f}" if att is not None else "")
                )
        return 0
    if args.disagg_sweep:
        if args.hosts > 1:
            # the sweep fixes its own two-pod split of --world; silently
            # accepting --hosts would read as "priced that host split"
            # when nothing used it (the --hier-sweep precedent)
            ap.error("--hosts has no effect on --disagg-sweep (the sweep "
                     "splits --world into its own prefill/decode pods)")
        if args.slo_ms < 0:
            ap.error(f"--slo-ms must be >= 0, got {args.slo_ms}")
        rows = disagg_sweep(
            world=args.world,
            mixes=[m for m in args.disagg_mixes.split(",") if m],
            splits=[s for s in args.disagg_splits.split(",") if s],
            dims=[int(d) for d in args.disagg_dims.split(",") if d],
            rate=args.disagg_rate,
            num_requests=args.serve_requests,
            total_slots=args.disagg_slots,
            slo_ms=args.slo_ms if args.slo_ms > 0 else None,
            model=model,
        )
        for row in rows:
            if args.json:
                print(json.dumps(row))
            else:
                star = (
                    "*" if row["disagg_beats_colocated_p99_ttft"] else " "
                )
                print(
                    f"[sim] disagg {row['mix']:<13} {row['split']:<4} "
                    f"d={row['d_model']:>4}{star} "
                    f"ttft p99={row['p99_ttft_ms']:>9.3f}ms "
                    f"(coloc {row['coloc_p99_ttft_ms']:>9.3f}ms)  "
                    f"xfer={row['transfer_steps']:>2}st  "
                    f"tok/s={row['throughput_tok_s']:>10.1f} "
                    f"(coloc {row['coloc_throughput_tok_s']:>10.1f})"
                )
        return 0
    if args.fabric_sweep:
        if args.hosts > 1:
            # the sweep fixes its own two-pod split of --world; silently
            # accepting --hosts would read as "priced that host split"
            # when nothing used it (the --hier-sweep precedent)
            ap.error("--hosts has no effect on --fabric-sweep (the sweep "
                     "uses a fixed two-pod split of --world)")
        rows = fabric_sweep(
            world=args.world,
            sizes=[parse_size(s) for s in args.sizes.split(",")],
            intensities=[
                float(i) for i in args.intensities.split(",") if i
            ],
            model=model,
        )
        for row in rows:
            if args.json:
                print(json.dumps(row))
            else:
                star = (
                    "*" if row.get("high_beats_uncoordinated") else " "
                )
                print(
                    f"[sim] fabric {row['size_bytes']:>12}B "
                    f"x{row['intensity']:g} {row['mix']:<9}{star} "
                    f"high={row['job0_us']:>10.1f}us "
                    f"({row['job0_strategy']})  "
                    f"peer={row['job1_us']:>10.1f}us "
                    f"({row['job1_strategy']})  "
                    f"fair={row['fairness']:.4f}"
                )
        return 0
    if args.hier_sweep:
        if args.hosts > 1:
            # the sweep grid names its own topologies (pods x pod_size);
            # silently accepting --hosts would read as "priced that host
            # split" when nothing used it (the --chaos-sweep precedent)
            ap.error("--hosts has no effect on --hier-sweep (use --pods/"
                     "--pod-sizes)")
        rows = hier_sweep(
            sizes=[parse_size(s) for s in args.sizes.split(",")],
            pods=[int(p) for p in args.pods.split(",") if p],
            pod_sizes=[int(i) for i in args.pod_sizes.split(",") if i],
            model=model,
        )
        for row in rows:
            if args.json:
                print(json.dumps(row))
            else:
                star = "*" if row["two_level_faster"] else " "
                print(
                    f"[sim] hier {row['size_bytes']:>12}B "
                    f"pods={row['pods']:>3} pod_size={row['pod_size']:>2}{star} "
                    f"two_level={row['pred_two_level_us']:>10.1f}us  "
                    f"flat={row['pred_flat_us']:>10.1f}us  "
                    f"crossover_pods={row['crossover_pods']}"
                )
        return 0
    if args.recovery_sweep:
        if args.hosts > 1:
            # the grid names its own worlds and the replica piggyback is
            # priced on the ICI class alone; silently accepting --hosts
            # would read as "priced that host split" when nothing used it
            ap.error("--hosts has no effect on --recovery-sweep (use "
                     "--rec-worlds)")
        rows = recovery_sweep(
            sizes=[parse_size(s) for s in args.sizes.split(",")],
            worlds=[int(w) for w in args.rec_worlds.split(",") if w],
            replicas=args.rec_replicas,
            save_interval_steps=args.rec_save_interval,
            model=model,
        )
        for row in rows:
            if args.json:
                print(json.dumps(row))
            elif "skipped" in row:
                print(
                    f"[sim] recovery world={row['world']:>3} "
                    f"SKIP ({row['skipped']})"
                )
            else:
                star = "*" if row["overhead_ok"] else "!"
                print(
                    f"[sim] recovery world={row['world']:>3} "
                    f"{row['size_bytes']:>12}B k={row['replicas']}{star} "
                    f"overhead={row['replication_overhead_ratio']*100:>6.2f}% "
                    f"repair={row['replica_repair_us']:>10.1f}us  "
                    f"reload={row['ckpt_reload_us']:>12.1f}us  "
                    f"speedup={row['repair_speedup']:>8.1f}x"
                )
        return 0
    if args.chaos_sweep:
        if args.hosts > 1:
            # the liveness machine is topology-blind (a heartbeat is a
            # heartbeat): silently accepting --hosts would read as
            # "priced the multi-host layout" when nothing used it
            ap.error("--hosts has no effect on --chaos-sweep")
        rows = chaos_sweep(
            world=args.world,
            sizes=[parse_size(s) for s in args.sizes.split(",")],
            model=model,
            periods=[float(p) for p in args.hb_periods.split(",") if p],
            graces=[int(g) for g in args.hb_graces.split(",") if g],
        )
        for row in rows:
            if args.json:
                print(json.dumps(row))
            elif row["phase"] == "detection":
                print(
                    f"[sim] chaos {row['size_bytes']:>12}B "
                    f"period={row['heartbeat_period_s']:>5}s "
                    f"grace={row['grace']} "
                    f"detect={row['detection_us']:>12.1f}us  "
                    f"confirm_window={row['confirm_window_s']:>6.2f}s  "
                    f"swap={row['swap_cached_us']:>7.1f}us"
                )
            else:
                print(
                    f"[sim] chaos {row['size_bytes']:>12}B schedule "
                    f"{row['actions']} actions ({row['kills']} kill, "
                    f"{row['stops']} stop/{row['conts']} cont) "
                    f"first_kill={row['first_kill_s']}s"
                )
        return 0
    if args.adapt_sweep:
        rows = adapt_sweep(
            world=args.world,
            sizes=[parse_size(s) for s in args.sizes.split(",")],
            hosts=args.hosts,
            model=model,
            degrade=args.degrade_factor,
        )
        for row in rows:
            if args.json:
                print(json.dumps(row))
            elif row["phase"] == "timeline":
                star = "*" if row["fired"] else " "
                print(
                    f"[sim] adapt {row['size_bytes']:>12}B "
                    f"step={row['step']:>2}{star} "
                    f"obs={row['observed_us']:>10.1f}us  "
                    f"ratio={row['ratio'] if row['ratio'] else 0:>7.3f}"
                )
            else:
                print(
                    f"[sim] adapt {row['size_bytes']:>12}B summary "
                    f"lag={row['detection_lag_steps']} steps  "
                    f"swap={row['hot_swap_stall_us']:>8.1f}us vs "
                    f"rebuild={row['full_rebuild_stall_us']:>12.1f}us  "
                    f"stale={row['stale_steady_us']:>10.1f}us -> "
                    f"adapted={row['adapted_steady_us']:>10.1f}us "
                    f"({row['adapted_label']})"
                )
        return 0
    if args.fault_sweep:
        rows = fault_sweep(
            world=args.world,
            sizes=[parse_size(s) for s in args.sizes.split(",")],
            hosts=args.hosts,
            model=model,
            heartbeat_timeout_s=args.heartbeat_timeout_s,
        )
        for row in rows:
            if args.json:
                print(json.dumps(row))
            elif row["phase"] == "failover":
                print(
                    f"[sim] fault {row['size_bytes']:>12}B "
                    f"{row['scenario']:<10} "
                    f"detect={row['detection_us']:>10.1f}us  "
                    f"swap={row['swap_cached_us']:>7.1f}us "
                    f"(cold {row['swap_cold_us']:>10.1f}us)  "
                    f"degraded_ratio={row['degraded_ratio']:.3f}"
                )
            else:
                star = "*" if row["swapped"] else " "
                print(
                    f"[sim] fault {row['size_bytes']:>12}B "
                    f"step={row['step']:>2} epoch={row['epoch']}{star} "
                    f"alive={len(row['alive'])} relays={len(row['relays'])} "
                    f"pred={row['pred_time_us']:>10.1f}us"
                )
        return 0
    if args.schedule_sweep:
        if args.hosts > 1:
            # the program grid prices the flat --world mesh; silently
            # accepting --hosts would read as "priced that host split"
            # when nothing used it (the --hier-sweep precedent)
            ap.error("--hosts has no effect on --schedule-sweep (programs "
                     "price the flat --world mesh)")
        rows = schedule_sweep(
            world=args.world,
            sizes=[parse_size(s) for s in args.sizes.split(",")],
            programs=[p.strip() for p in args.programs.split(",") if p.strip()],
            model=model,
        )
        for row in rows:
            if args.json:
                print(json.dumps(row))
            else:
                legacy = row["legacy_pred_time_us"]
                legacy_str = (
                    f"legacy={legacy:>10.1f}us" if legacy is not None
                    else f"lockstep={row['lockstep_ring_us']:>8.1f}us"
                    + ("*" if row.get("beats_lockstep_ring") else " ")
                )
                print(
                    f"[sim] schedule {row['size_bytes']:>12}B "
                    f"{row['strategy']:<20} "
                    f"pred={row['pred_time_us']:>10.1f}us  {legacy_str}  "
                    f"busbw={row['busbw_gbps']:>8.3f}GB/s  "
                    f"rounds={row['rounds']:>2} chunks={row['chunks']:>2}"
                )
        return 0
    if args.latency_sweep:
        rows = latency_sweep(
            world=args.world,
            sizes=[parse_size(s) for s in args.sizes.split(",")],
            algos=[a.strip() for a in args.algos.split(",") if a.strip()],
            model=model,
        )
        for row in rows:
            if args.json:
                print(json.dumps(row))
            else:
                star = "*" if row["chosen"] else " "
                print(
                    f"[sim] latency {row['size_bytes']:>12}B "
                    f"algo={row['algo']:<5}{star} "
                    f"pred={row['pred_time_us']:>10.1f}us  "
                    f"busbw={row['busbw_gbps']:>8.3f}GB/s  "
                    f"crossover={row['crossover_bytes']}"
                )
        return 0
    if args.overlap_sweep:
        rows = overlap_sweep(
            world=args.world,
            sizes=[parse_size(s) for s in args.sizes.split(",")],
            accums=[int(a) for a in args.accums.split(",") if a],
            bucket_caps_mb=[
                float(c) for c in args.bucket_caps_mb.split(",") if c
            ],
            model=model,
        )
        for row in rows:
            if args.json:
                print(json.dumps(row))
            else:
                print(
                    f"[sim] overlap {row['size_bytes']:>12}B "
                    f"accum={row['accum']} cap={row['bucket_cap_mb']:>5}MB "
                    f"ratio={row['compute_ratio']:>5} "
                    f"{row['overlap']:<10} "
                    f"step={row['pred_step_us']:>10.1f}us  "
                    f"exposed={row['exposed_comm_us']:>10.1f}us"
                )
        return 0
    if args.fused_sweep:
        rows = fused_wire_sweep(
            world=args.world,
            sizes=[parse_size(s) for s in args.sizes.split(",")],
            chunk_sizes=[parse_size(c) for c in args.chunks.split(",") if c],
            wire_dtypes=[
                w.strip() for w in args.fused_wire.split(",") if w.strip()
            ],
            model=model,
        )
        for row in rows:
            if args.json:
                print(json.dumps(row))
            else:
                star = "*" if row["fused_faster"] else " "
                print(
                    f"[sim] fused {row['size_bytes']:>12}B "
                    f"wire={row['wire_dtype']:<5} "
                    f"chunk={row['chunk_bytes']:>9}B{star} "
                    f"fused={row['pred_fused_us']:>10.1f}us  "
                    f"unfused={row['pred_unfused_us']:>10.1f}us  "
                    f"crossover={row['crossover_bytes']}"
                )
        return 0
    if args.tune_replay:
        rows = tune_replay_sweep(
            world=args.world,
            sizes=[parse_size(s) for s in args.sizes.split(",")],
            chunk_grid=[parse_size(c) for c in args.chunks.split(",") if c],
            model=model,
        )
        for row in rows:
            if args.json:
                print(json.dumps(row))
            else:
                star = "*" if row["chosen"] else (
                    "!" if row["surface_best"] else " "
                )
                med = row["median_us"]
                print(
                    f"[sim] tune {row['size_bytes']:>12}B "
                    f"{row['path']:<11} chunk={row['chunk_bytes']:>9} "
                    f"wire={row['wire_dtype']:<5}{star} "
                    f"n={row['samples']:>3}  "
                    f"median={med if med is not None else '-':>10}us  "
                    f"true={row['surface_us']:>10}us"
                )
        return 0
    if args.wire_dtype:
        rows = wire_dtype_sweep(
            world=args.world,
            sizes=[parse_size(s) for s in args.sizes.split(",")],
            wire_dtypes=[w.strip() for w in args.wire_dtype.split(",") if w.strip()],
            model=model,
        )
        for row in rows:
            if args.json:
                print(json.dumps(row))
            else:
                star = "*" if row["chosen"] else " "
                print(
                    f"[sim] quant {row['size_bytes']:>12}B "
                    f"wire={row['wire_dtype']:<5}{star} "
                    f"({row['wire_bytes_per_elem']:.3f} B/elem)  "
                    f"pred={row['pred_time_us']:>10.1f}us  "
                    f"busbw={row['busbw_gbps']:>8.3f}GB/s"
                )
        return 0
    if args.ring_sweep:
        rows = ring_chunk_sweep(
            world=args.world,
            sizes=[parse_size(s) for s in args.sizes.split(",")],
            chunk_sizes=[parse_size(c) for c in args.chunks.split(",") if c],
            model=model,
        )
        for row in rows:
            if args.json:
                print(json.dumps(row))
            else:
                print(
                    f"[sim] ring {row['size_bytes']:>12}B chunk="
                    f"{row['chunk_bytes']:>10}B  path={row['ring_path']:<10} "
                    f"pred={row['pred_time_us']:>10.1f}us  "
                    f"busbw={row['busbw_gbps']:>8.3f}GB/s"
                )
        return 0
    rows = sweep(
        world=args.world,
        sizes=[parse_size(s) for s in args.sizes.split(",")],
        collectives=[c.strip() for c in args.collectives.split(",") if c.strip()],
        strategies=[s.strip() for s in args.strategies.split(",") if s.strip()],
        model=model,
        hosts=args.hosts,
        degree=args.degree,
        flow_lp=not args.no_flow_lp,
    )
    for row in rows:
        if args.json:
            print(json.dumps(row))
        else:
            print(
                f"[sim] {row['collective']:<14} {row['strategy']:<10} "
                f"{row['size_bytes']:>12}B  pred={row['pred_time_us']:>10.1f}us  "
                f"busbw={row['busbw_gbps']:>8.3f}GB/s  ({row['calibration']})"
            )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
