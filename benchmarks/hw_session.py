"""One-shot hardware evidence battery for the flaky-tunnel regime.

The axon tunnel has been mostly wedged this round; when a liveness window
opens it may close again within minutes.  This driver runs the whole
measurement battery in priority order, each phase in its own subprocess
with a hard deadline, appending one JSON line per phase to
``benchmarks/results/hw_<tag>.jsonl`` as soon as it finishes — so a tunnel
death mid-battery keeps everything measured so far.

Phases (priority order — headline first, round-5 levers next, the already-
proven round-4 A/Bs last):
  1. probe         — tiny jit; records device kind (seconds)
  2. bench         — flagship bench.py, default config (flash + bf16 + scan).
                     FIRST after the probe: even a minutes-long window must
                     yield the canonical headline number (VERDICT r4 item 1)
  3. bench_best24  — the >= 0.52 MFU attempt (VERDICT r4 item 2): 24 layers
                     (measured 0.504 at static tiles) + autotuned flash tile
                     + chunked CE + bf16 adam moments
  4. profile       — benchmarks/profile_step.py attribution (dispatch floor,
                     MXU rate, forward/grad/train MFU)
  5. bench_auto    — flagship + BENCH_FLASH_BLOCK=auto: the measured tile
                     sweep vs the static 256 default
  6. bench_bf16m   — flagship + bf16 adam first moment (optimizer HBM lever)
  7. bench_t8k     — long context: T=8192, flash + chunked CE (batch 2)
  8. bench_t16k    — long context: T=16384, flash + chunked CE + remat dots
  9. bench_t8k_xla — T=8192 with DENSE attention: documents the memory wall
                     flash removes (expected OOM/fallback — rc may be != 0)
 10. longcontext   — benchmarks/longcontext.py world=1: ring-flash attention
                     ms + score-memory curve at 2K/8K/16K
 11. longcontext_single — the dense single-device baseline at 2K/8K, in its
                     own process (the 8K score tensor may OOM — that IS the
                     memory-wall row, isolated so it can't kill flash rows)
 12. zero1_ab      — benchmarks/zero1_ab.py: ZeRO-1 step, XLA vs Pallas
                     ring data plane (world=1: plumbing-cost statement)
 12b. multi-chip entries (device-count-gated; explicit skip rows at world=1):
      busbw_ici_128m — ICI busbw at 128 MB, Pallas ring vs XLA psum
      ring_smoke     — Pallas ring world>1 on-chip smoke (1 MB)
      ring_chunk_sweep — staged ring at 128 MB across chunk_bytes
                     (ADAPCC_RING_CHUNK_BYTES 1M/4M/16M)
 13. bench_chunk   — bench.py with BENCH_LOSS=chunked
 14. bench_remat   — bench.py with BENCH_REMAT=dots
 15. bench_loop    — bench.py with BENCH_SCAN=0: per-step dispatch instead of
                     the scanned window; (bench_loop.step_ms - bench.step_ms)
                     IS the tunnel's per-dispatch tax (PERF_NOTES hyp. 2/5)
 16. bench_fblk128 — bench.py with BENCH_FLASH_BLOCK=128: flash tile A/B vs
                     the 256 default (VMEM residency vs grid parallelism)
 17. busbw         — benchmarks/collectives.py on the real chip (world=1)

Usage::

    python -m benchmarks.hw_session [tag]
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Single source of truth for "is the backend live" — shared with
# scripts/hw_watch.py so the watcher and the battery can never disagree
# about what a live window means.  Honors JAX_PLATFORMS when set (the
# axon sitecustomize overrides the env var; unset = the real TPU default).
PROBE_CODE = (
    "import os, jax; p = os.environ.get('JAX_PLATFORMS'); "
    "p and jax.config.update('jax_platforms', p); "
    "import jax.numpy as jnp, json; d = jax.devices(); "
    "jax.jit(lambda a: a + 1)(jnp.ones(8)).block_until_ready(); "
    "print(json.dumps({'device': str(d[0]), "
    "'kind': getattr(d[0], 'device_kind', '?'), "
    "'platform': d[0].platform, "
    "'num_devices': len(d)}))"
)


def hw_env() -> dict:
    """Child env for hardware runs: strip the virtual-CPU-pod pins."""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    return env


def _run(
    name: str, cmd, timeout: int, out_path: str, extra_env=None, rec_extra=None
) -> dict:
    # base on hw_env(), not raw os.environ: a leaked JAX_PLATFORMS=cpu /
    # XLA_FLAGS pin from the test regime must not silently turn the
    # hardware battery into a CPU battery when invoked directly
    env = {**hw_env(), **(extra_env or {})}
    t0 = time.time()
    rec: dict = {"phase": name, "cmd": " ".join(cmd), **(rec_extra or {})}
    try:
        p = subprocess.run(
            cmd, capture_output=True, text=True, timeout=timeout, cwd=REPO, env=env
        )
        rec["rc"] = p.returncode
        rec["secs"] = round(time.time() - t0, 1)
        tail = (p.stdout or "").strip().splitlines()
        rec["last_line"] = tail[-1] if tail else ""
        # bench/profile print one JSON line last — keep it parsed when possible
        try:
            rec["parsed"] = json.loads(rec["last_line"])
        except (json.JSONDecodeError, ValueError):
            rec["stderr_tail"] = (p.stderr or "")[-400:]
        # sweep phases (longcontext, zero1_ab, busbw --json) print one JSON
        # row per measurement — persist them ALL, not just the last line
        # (tunnel time must never produce rows the artifact then drops)
        rows = []
        for line in tail:
            try:
                rows.append(json.loads(line))
            except (json.JSONDecodeError, ValueError):
                continue
        if len(rows) > 1:
            rec["rows"] = rows
    except subprocess.TimeoutExpired:
        rec["rc"] = -1
        rec["secs"] = round(time.time() - t0, 1)
        rec["error"] = f"timeout after {timeout}s"
    with open(out_path, "a") as f:
        f.write(json.dumps(rec) + "\n")
    print(f"[hw] {name}: rc={rec.get('rc')} ({rec['secs']}s)", flush=True)
    return rec


def _skip(name: str, reason: str, out_path: str) -> dict:
    """Record a battery entry that was present but gated off — the artifact
    must show the phase *exists* (so a future multi-chip window is known to
    auto-capture it) without pretending it ran."""
    rec = {"phase": name, "skipped": reason, "rc": None, "secs": 0.0}
    with open(out_path, "a") as f:
        f.write(json.dumps(rec) + "\n")
    print(f"[hw] {name}: skipped ({reason})", flush=True)
    return rec


def run_multichip_phases(py: str, out_path: str, world: int) -> None:
    """Device-count-gated entries (VERDICT r5 weak #2): the multi-chip ICI
    evidence the single-chip rounds could never produce.  Present in every
    battery; at world=1 each is recorded as skipped so the artifact shows
    a future multi-chip window will capture them automatically.

    - ``busbw_ici_128m`` — ICI busbw at the 128 MB north-star payload,
      Pallas ring vs the XLA psum on the same sweep (the ring's bandwidth
      case needs a real pod);
    - ``ring_smoke`` — Pallas ring world>1 on-chip smoke at 1 MB (the
      kernels have only ever run multi-device under the interpreter);
    - ``ring_chunk_sweep`` — the staged ring at 128 MB across staging
      granularities via ``ADAPCC_RING_CHUNK_BYTES`` (the hardware twin of
      ``make ring-sweep``);
    - ``busbw_wire_dtype`` — the ring at 128 MB across wire codecs via
      ``ADAPCC_WIRE_DTYPE`` (int8 vs bf16 vs fp32: the hardware twin of
      ``make quant-bench``; off rides the Pallas kernels, the codecs ride
      the fused staged kernels where supported);
    - ``busbw_fused_wire`` — the int8 ring at 128 MB with the codec fused
      into the staged Pallas kernel (``ADAPCC_FUSED_WIRE=auto``) vs the
      unfused ppermute reroute (``=off``): the hardware twin of ``make
      fused-bench``'s fused-vs-unfused pricing;
    - ``tuner_convergence`` — the autotuner closing its loop on real
      hardware: ``ADAPCC_TUNER=choose`` over a repeated 128 MB allreduce
      sweep, the tuning database appended under ``benchmarks/results`` so
      the artifact holds both the measured cells and what the policy
      settled on (the hardware twin of ``make tune-bench``).  Allreduce
      only: it is the one primitive the tuner steers.
    - ``overlap_ab`` — the overlapped gradient sync A/B on a real DDP
      step (the hardware twin of ``make overlap-bench``): the same
      train_ddp workload under overlap off / bucket / microbatch, walltime
      per schedule in the artifact (docs/OVERLAP.md).  Needs real
      multi-chip comm or the "overlap" measures only dispatch noise.
    - ``small_msg_crossover`` — the latency-bound regime A/B (the hardware
      twin of ``make latency-bench``): the same small-to-medium allreduce
      size grid under ``ADAPCC_COLL_ALGO=ring`` vs ``=rd``, locating the
      measured ring ↔ recursive-doubling crossover the cost model predicts
      (docs/LATENCY.md).  Needs a power-of-two multi-chip world; explicit
      skip row otherwise.
    - ``ir_parity`` — the schedule-compiler parity A/B (the hardware twin
      of ``make compiler-bench``, docs/COMPILER.md): the same 128 MB
      allreduce once under ``ADAPCC_COLL_ALGO=ir`` (the xla impl row
      reroutes through the compiled ScheduleProgram executor, program
      fingerprint in the dispatch trace) and once unpinned (the XLA psum
      and Pallas ring baselines) — the IR lowering's ppermute rounds vs
      the hand-written planes on real ICI.
    - ``two_level_synth`` — the composed-vs-flat two-level A/B (the
      hardware twin of ``make hier-bench``, docs/HIERARCHY.md): the
      synthesized RS→AR→AG plan vs the ParTrees projection vs the flat
      psum on a 2×(world/2) virtual pod mesh.  Explicit skip row at
      world=1 and odd/small worlds; single-host worlds are ordering
      evidence only (the DCN axis rides ICI).
    - ``supervised_failover`` — the autonomous supervisor driving the
      elastic_failover fault plan out of band (the hardware twin of
      ``make chaos-bench``, docs/SUPERVISOR.md): daemon-journaled
      detection + standby swap while the training loop only observes
      epoch bumps; the decision journal rides beside the battery output.
    - ``fabric_contention`` — the congestion-triage A/B (the hardware
      twin of ``make fabric-bench``, docs/FABRIC.md): the SAME injected
      congestion profile (a bounded DCN window mid-run) under
      ``--adapt detect`` (triage reports, never swaps) vs ``--adapt
      swap`` (congestion re-routes through the standby cache and the
      incumbent restores after the window) — the phase walltimes price
      what the re-route buys, and the printed outcomes record the
      triage's verdicts on real hardware.
    - ``elastic_rejoin`` — replicated ZeRO-1 shard upkeep priced live
      (the hardware twin of ``make recovery-bench``, docs/RECOVERY.md):
      the SAME zero1 workload with ``ADAPCC_SHARD_REPLICAS`` 0 vs 1 —
      the per-step walltime delta UPPER-BOUNDS the piggyback overhead
      the sim's < 5 % bound predicts: the single-process replica store
      is a host-materialized twin (a blocking D2H state copy per step),
      so the measured delta includes that copy, where a real multi-host
      deployment pays only the k·state/world ring-neighbor wire transfer
      (the rejoin protocol itself is process-level and drilled by
      tests/test_chaos_drill.py).
    - ``decode_slo`` — the serving-plane tail A/B (the hardware twin of
      ``make serve-bench``, docs/SERVING.md): the continuous batcher
      serving one seeded Poisson trace with the per-token decode
      allreduce under ``--algo ring`` vs ``rd`` vs ``auto`` — serving
      payloads sit far below the ring ↔ recursive-doubling crossover, so
      the arms measure what the small-message plane buys the p50/p99
      decode-step tail and SLO attainment on real ICI, and the ``auto``
      arm records which plane the size-adaptive selector picks live.
    - ``disagg_transfer`` — disaggregated prefill/decode serving on real
      chips (the hardware twin of ``make disagg-bench``, docs/SERVING.md
      §7): the SAME arrival trace served colocated vs split into equal
      prefill/decode pods with every KV migration riding the traced
      ``kv_transfer`` stream — the summaries pin the measured TTFT/
      sojourn split per pool and the kv_stream wire ledger against the
      simulator's two-pool frontier; needs an even world ≥ 2.
    - ``pipeline_ab`` — the pipeline-schedule A/B (the hardware twin of
      ``make pipe-bench``, docs/PIPELINE.md): the SAME train_gpt2
      pipeline cell (2 stages × 4 microbatches) under ``--pp-schedule
      gpipe`` vs ``1f1b`` — identical tick count, so the walltime delta
      isolates the schedules' dispatch/stash behavior on real ICI.
    """
    gate = f"world={world} (needs multi-chip ICI)"
    if world < 2:
        for name in (
            "busbw_ici_128m", "ring_smoke", "ring_chunk_sweep",
            "busbw_wire_dtype", "busbw_fused_wire", "tuner_convergence",
            "overlap_ab", "small_msg_crossover", "ir_parity",
            "two_level_synth",
            "elastic_failover", "online_adaptation", "supervised_failover",
            "fabric_contention", "elastic_rejoin", "decode_slo",
            "disagg_transfer", "pipeline_ab",
        ):
            _skip(name, gate, out_path)
        return
    _run(
        "busbw_ici_128m",
        [py, "-m", "benchmarks.collectives", "--world", str(world),
         "--sizes", "128M", "--impls", "xla,pallas_ring"],
        900, out_path,
    )
    _run(
        "ring_smoke",
        [py, "-m", "benchmarks.collectives", "--world", str(world),
         "--sizes", "1M", "--impls", "pallas_ring"],
        600, out_path,
    )
    for chunk in ("1048576", "4194304", "16777216"):
        _run(
            "ring_chunk_sweep",
            [py, "-m", "benchmarks.collectives", "--world", str(world),
             "--sizes", "128M", "--impls", "pallas_ring"],
            900, out_path,
            extra_env={"ADAPCC_RING_CHUNK_BYTES": chunk},
            rec_extra={"chunk_bytes": int(chunk)},
        )
    # wire-codec A/B on the same 128 MB ring payload: "off" is the fp32
    # Pallas path, "bf16"/"int8" reroute engine.ring_allreduce onto the
    # quantized ppermute ring via the env override — one knob, same sweep.
    # Allreduce ONLY: the override affects no other primitive, so RS/AG
    # rows here would measure the identical fp32 path under a codec label
    for wire in ("off", "bf16", "int8"):
        _run(
            "busbw_wire_dtype",
            [py, "-m", "benchmarks.collectives", "--world", str(world),
             "--sizes", "128M", "--impls", "pallas_ring",
             "--collectives", "allreduce"],
            900, out_path,
            extra_env={"ADAPCC_WIRE_DTYPE": wire},
            rec_extra={"wire_dtype": wire},
        )
    # fused-wire A/B on the same 128 MB int8 ring payload: ADAPCC_FUSED_WIRE
    # auto runs the codec INSIDE the staged Pallas kernel (PR-6), off pins
    # the unfused ppermute reroute — same payload, same codec, the two data
    # planes `make fused-bench` prices head to head.  Allreduce ONLY (the
    # A/B's unfused arm exists for no other primitive)
    for fused in ("auto", "off"):
        _run(
            "busbw_fused_wire",
            [py, "-m", "benchmarks.collectives", "--world", str(world),
             "--sizes", "128M", "--impls", "pallas_ring",
             "--collectives", "allreduce"],
            900, out_path,
            extra_env={"ADAPCC_WIRE_DTYPE": "int8", "ADAPCC_FUSED_WIRE": fused},
            rec_extra={"wire_dtype": "int8", "fused_wire": fused},
        )
    # tuner convergence: ADAPCC_TUNER=choose on a repeated allreduce-only
    # sweep — every dispatch is timed into the tuning database (walltime,
    # compile warmup discarded) and the policy's epsilon-greedy pass fills
    # the (chunk x codec) grid, then settles.  The database file IS the
    # artifact: its medians per cell plus the last chosen plan.  Allreduce
    # ONLY — the tuner steers no other primitive, so extra rows would
    # measure untuned paths under a tuner label
    db_path = os.path.join(
        os.path.dirname(out_path), f"tuning_{os.path.basename(out_path)}"
    )
    _run(
        "tuner_convergence",
        [py, "-m", "benchmarks.collectives", "--world", str(world),
         "--sizes", "128M", "--impls", "pallas_ring",
         "--collectives", "allreduce", "--iters", "40"],
        1200, out_path,
        extra_env={"ADAPCC_TUNER": "choose", "ADAPCC_TUNER_DB": db_path},
        rec_extra={"tuner": "choose", "tuner_db": db_path},
    )
    # overlapped-sync A/B: one real DDP workload per overlap schedule,
    # identical flags otherwise (accum=2 so the microbatch pipeline has a
    # later microbatch to hide behind).  The phase walltime per schedule is
    # the measurement; gradients are parity-pinned by the tier-1 tests, so
    # a schedule can only move time, not results
    for overlap in ("off", "bucket", "microbatch"):
        _run(
            "overlap_ab",
            [py, "-m", "adapcc_tpu.workloads.train_ddp", "--model", "mlp",
             "--steps", "12", "--batch", "64", "--accum", "2",
             "--overlap", overlap, "--world", str(world)],
            900, out_path,
            rec_extra={"overlap": overlap, "accum": 2},
        )
    # small-message crossover A/B (the hardware twin of `make
    # latency-bench`): the SAME allreduce size grid spanning the
    # sim-predicted ring <-> recursive-doubling crossover (~100 KB on
    # default v5e coefficients), once per pinned algorithm via
    # ADAPCC_COLL_ALGO — the measured curves locate the real crossover the
    # cost model only predicts.  xla impl (engine.all_reduce honors the
    # env); rd needs a power-of-two world, so non-pow2 pods record an
    # explicit skip row instead of a loud failure mid-battery
    if world & (world - 1):
        _skip(
            "small_msg_crossover",
            f"world={world} is not a power of two (recursive doubling "
            "pairs ranks by XOR)",
            out_path,
        )
    else:
        for algo in ("ring", "rd"):
            _run(
                "small_msg_crossover",
                [py, "-m", "benchmarks.collectives", "--world", str(world),
                 "--sizes", "4K,64K,256K,4M", "--impls", "xla",
                 "--collectives", "allreduce"],
                900, out_path,
                extra_env={"ADAPCC_COLL_ALGO": algo},
                rec_extra={"coll_algo": algo},
            )
    # schedule-compiler parity A/B (the hardware twin of `make
    # compiler-bench`, docs/COMPILER.md): the same 128 MB allreduce once
    # with ADAPCC_COLL_ALGO=ir — engine.all_reduce reroutes the xla impl
    # row through the compiled ScheduleProgram executor (strategy-derived
    # ring program; fingerprint stamped in the dispatch trace) — and once
    # unpinned, where the xla row is the fused psum and pallas_ring is the
    # staged kernel: the IR lowering priced against both hand-written
    # planes on the same payload.  Allreduce ONLY: "ir" steers no other
    # primitive (RS/AG keep their legacy planes under the pin)
    # the ir arm family also carries the optimizer A/B (ADAPCC_IR_OPT on
    # vs off on the same payload — the hardware answer to `make
    # compiler-bench`'s opt_faster flag) and the fused-int8-IR arm, where
    # the optimizer's fuse_codec pass ships the codec's real transport
    # arrays (int8 + block scales) through the compiled program
    for arm, env, impls, extra_args in (
        ("ir", {"ADAPCC_COLL_ALGO": "ir"}, "xla", []),
        ("ir_opt", {"ADAPCC_COLL_ALGO": "ir", "ADAPCC_IR_OPT": "on"}, "xla",
         []),
        ("ir_naive", {"ADAPCC_COLL_ALGO": "ir", "ADAPCC_IR_OPT": "off"},
         "xla", []),
        # the strategy carries int8 so the compiled program's wire_dtype
        # agrees with the env pin (a bare pin against an "off" program is
        # the conflict the engine rejects by design)
        ("ir_fused_int8",
         {"ADAPCC_COLL_ALGO": "ir", "ADAPCC_IR_OPT": "on",
          "ADAPCC_WIRE_DTYPE": "int8"}, "xla", ["--wire-dtype", "int8"]),
        ("baseline", None, "xla,pallas_ring", []),
    ):
        _run(
            "ir_parity",
            [py, "-m", "benchmarks.collectives", "--world", str(world),
             "--sizes", "128M", "--impls", impls,
             "--collectives", "allreduce"] + extra_args,
            900, out_path,
            extra_env=env,
            rec_extra={"arm": arm},
        )
    # composed-vs-flat two-level A/B (the hardware twin of `make
    # hier-bench`, docs/HIERARCHY.md): one run on a 2x(world/2) virtual
    # pod mesh with the SYNTHESIZED composed plan (--hier emits ONE
    # 'two_level_composed' allreduce row — RS-within-pod ->
    # AR-across-leaders -> AG-within-pod; the composed plan outranks the
    # GSPMD fastpath, so that invocation has no honest 'xla' baseline),
    # one with the ParTrees projection (whose 'xla' row IS the flat psum
    # baseline and whose 'strategy' row is the replicate-first fixed
    # schedule) — three arms of the same 128 MB allreduce across the two
    # invocations.  Single-host worlds route the "DCN" axis over ICI, so
    # the numbers are ordering evidence for the schedule shapes, not a
    # DCN measurement; a multi-host window upgrades them automatically.
    if world < 4 or world % 2:
        _skip(
            "two_level_synth",
            f"world={world} (a 2x{max(world // 2, 1)} virtual pod needs an "
            "even world >= 4)",
            out_path,
        )
    else:
        for arm in ("composed", "projected"):
            _run(
                "two_level_synth",
                [py, "-m", "benchmarks.collectives",
                 "--two-level", f"2x{world // 2}",
                 "--collectives", "allreduce", "--sizes", "128M"]
                + (["--hier"] if arm == "composed" else []),
                900, out_path,
                rec_extra={"two_level": f"2x{world // 2}", "plan": arm},
            )
    # elastic failover drill on real chips (the hardware twin of
    # `make elastic-bench`): a deterministic fault plan — the last rank
    # dies mid-run, then recovers — injected via ADAPCC_FAULT_PLAN into the
    # DDP workload; the workload derives per-step relay masks from the
    # plan, so the run measures masked-step walltime through a real world
    # shrink + recovery (the phase walltime vs overlap_ab's healthy run is
    # the failover overhead).  The plan artifact rides next to the battery
    # output so the injected schedule is part of the evidence.
    plan_path = os.path.join(
        os.path.dirname(out_path),
        f"fault_plan_{os.path.basename(out_path)}.json",
    )
    with open(plan_path, "w") as f:
        json.dump(
            {
                "world": world,
                "label": "battery-failover",
                "events": [
                    {"step": 4, "kind": "down", "rank": world - 1},
                    {"step": 8, "kind": "recover", "rank": world - 1},
                ],
            },
            f,
        )
    _run(
        "elastic_failover",
        [py, "-m", "adapcc_tpu.workloads.train_ddp", "--model", "mlp",
         "--steps", "12", "--batch", "64", "--world", str(world),
         "--sync-mode", "schedule"],
        900, out_path,
        extra_env={"ADAPCC_FAULT_PLAN": plan_path},
        rec_extra={"fault_plan": plan_path},
    )
    # online adaptation on real chips (the hardware twin of `make
    # adapt-bench`, docs/ADAPT.md): the passive loop live inside a real
    # DDP workload.  ADAPCC_ADAPT=swap arms the plane; the tight
    # factor/window make a real drift (thermal, a congested ICI neighbor)
    # *detectable* within the phase.  What the phase proves on hardware:
    # a healthy run records zero swaps (the false-positive guard, live),
    # and a step-time drift surfaces as the loud "uninvertible" line —
    # step walltimes alone carry no link algebra, so the swap half needs
    # link-attributable samples (tuner-recorded engine dispatches; the
    # drift_loop benchmark and the CI drill pin that half on priced
    # feeds).  The decay-merged calibration artifact, when a swap-capable
    # feed exists, lands beside the run's other topology products
    # (topology/calibration.json).
    _run(
        "online_adaptation",
        [py, "-m", "adapcc_tpu.workloads.train_ddp", "--model", "mlp",
         "--steps", "24", "--batch", "64", "--world", str(world),
         "--sync-mode", "schedule", "--adapt", "swap",
         "--adapt-every", "8"],
        900, out_path,
        extra_env={
            "ADAPCC_DRIFT_FACTOR": "1.5",
            "ADAPCC_DRIFT_WINDOW": "4",
        },
        rec_extra={"adapt": "swap"},
    )
    # supervised failover on real chips (the hardware twin of `make
    # chaos-bench`, docs/SUPERVISOR.md): the SAME fault plan as
    # elastic_failover, but driven by the autonomous daemon — the
    # supervisor (not the training loop) folds the plan, journals every
    # decision (fsync'd, the artifact lands beside the battery output as
    # the run's decision record), and actuates the standby swap while the
    # loop only observes epoch bumps.  Against elastic_failover's phase
    # walltime this prices the out-of-band detour; tight heartbeat knobs
    # keep the daemon's confirmation window inside the phase.
    sup_plan_path = os.path.join(
        os.path.dirname(out_path),
        f"sup_fault_plan_{os.path.basename(out_path)}.json",
    )
    with open(sup_plan_path, "w") as f:
        json.dump(
            {
                "world": world,
                "label": "battery-supervised-failover",
                "events": [
                    {"step": 4, "kind": "down", "rank": world - 1},
                    {"step": 8, "kind": "recover", "rank": world - 1},
                ],
            },
            f,
        )
    _run(
        "supervised_failover",
        [py, "-m", "adapcc_tpu.workloads.train_ddp", "--model", "mlp",
         "--steps", "12", "--batch", "64", "--world", str(world),
         "--sync-mode", "schedule", "--supervisor",
         "--supervisor-period", "0.1"],
        900, out_path,
        extra_env={
            "ADAPCC_FAULT_PLAN": sup_plan_path,
            "ADAPCC_HEARTBEAT_TIMEOUT_S": "1.0",
            "ADAPCC_HEARTBEAT_PERIOD_S": "0.25",
        },
        rec_extra={"fault_plan": sup_plan_path, "supervisor": True},
    )
    # congestion-triage A/B on real chips (the hardware twin of `make
    # fabric-bench`, docs/FABRIC.md): a bounded DCN congestion window
    # injected via ADAPCC_CONGESTION_PROFILE into the adaptation
    # controller's PRICED observation funnel (the congestion analog of the
    # fault-plan injection above — the run is real, the neighbor traffic
    # is injected, and the artifact says so).  detect arm: the triage
    # classifies and reports, zero swaps; swap arm: congestion re-routes
    # through the standby cache inside the window and the incumbent is
    # restored after it clears — calibration.json must come back
    # byte-identical (congestion never re-calibrates).  Tight drift knobs
    # keep detection inside the phase.
    cong_path = os.path.join(
        os.path.dirname(out_path),
        f"congestion_profile_{os.path.basename(out_path)}.json",
    )
    with open(cong_path, "w") as f:
        json.dump(
            {
                "world": world,
                "label": "battery-fabric-contention",
                "windows": [
                    {"start": 6, "until": 14, "link_class": "dcn",
                     "factor": 4.0},
                ],
            },
            f,
        )
    for arm in ("detect", "swap"):
        _run(
            "fabric_contention",
            [py, "-m", "adapcc_tpu.workloads.train_ddp", "--model", "mlp",
             "--steps", "20", "--batch", "64", "--world", str(world),
             "--sync-mode", "schedule", "--adapt", arm,
             "--adapt-every", "4"],
            900, out_path,
            extra_env={
                "ADAPCC_CONGESTION_PROFILE": cong_path,
                "ADAPCC_DRIFT_FACTOR": "1.5",
                "ADAPCC_DRIFT_WINDOW": "4",
            },
            rec_extra={"congestion_profile": cong_path, "adapt": arm},
        )
    # replicated-shard upkeep A/B on real chips (the hardware twin of
    # `make recovery-bench`, docs/RECOVERY.md): the same ZeRO-1 workload
    # with replication off vs k=1 — every step's freshly-written shard
    # rows ride to their ring-neighbor holders inside the post-step
    # window, and the phase-walltime delta is the measured piggyback
    # overhead the sim prices (< 5% of step comm at the default config).
    for k in ("0", "1"):
        _run(
            "elastic_rejoin",
            [py, "-m", "adapcc_tpu.workloads.train_ddp", "--model", "mlp",
             "--steps", "12", "--batch", "64", "--world", str(world),
             "--dp-mode", "zero1"],
            900, out_path,
            extra_env={"ADAPCC_SHARD_REPLICAS": k},
            rec_extra={"shard_replicas": int(k)},
        )
    # decode-SLO serving A/B on real chips (the hardware twin of `make
    # serve-bench`, docs/SERVING.md): the continuous batcher serving one
    # seeded Poisson trace with the decode-step allreduce pinned to the
    # ring plane vs the small-message rd plane — per-token payloads sit
    # far below the crossover, so the A/B measures what the latency plane
    # buys the serving tail (p50/p99 step ms + SLO attainment in the
    # printed summary).  One head per rank; the final auto arm records
    # which plane the size-adaptive selector picks live.
    for algo in ("ring", "rd", "auto"):
        _run(
            "decode_slo",
            [py, "-m", "adapcc_tpu.workloads.serve_gpt2",
             "--requests", "16", "--rate", "0.25", "--slots", "4",
             "--world", str(world), "--heads", str(world),
             "--dmodel", str(64 * world), "--seq", "64",
             "--max-new-tokens", "16", "--algo", algo,
             "--slo-ms", "2000", "--json"],
            900, out_path,
            rec_extra={"algo": algo, "serve": True},
        )
    # disaggregated prefill/decode A/B on real chips (the hardware twin
    # of `make disagg-bench`, docs/SERVING.md §7): the SAME seeded
    # arrival trace served colocated, then split into two equal pods
    # with KV pages migrating over the traced kv_transfer DCN stream —
    # the two summaries put the measured per-pool TTFT/sojourn split and
    # the kv_stream wire ledger next to the colocated baseline the
    # simulator's frontier (simulate_disagg_queue) prices.  Pod split
    # needs an even world.
    if world % 2:
        _skip("disagg_transfer",
              f"world={world} (the pod split needs an even world)",
              out_path)
    else:
        for arm in ("colocated", "disagg"):
            _run(
                "disagg_transfer",
                [py, "-m", "adapcc_tpu.workloads.serve_gpt2",
                 "--requests", "16", "--rate", "0.25", "--slots", "4",
                 "--world", str(world), "--heads", str(world),
                 "--dmodel", str(64 * world), "--seq", "64",
                 "--max-new-tokens", "16", "--slo-ms", "2000", "--json"]
                + (["--disagg"] if arm == "disagg" else []),
                900, out_path,
                rec_extra={"arm": arm, "serve": True},
            )
    # pipeline-schedule A/B on real chips (the hardware twin of `make
    # pipe-bench`, docs/PIPELINE.md): the SAME train_gpt2 pipeline run at
    # a fixed (stages × microbatches) cell under --pp-schedule gpipe vs
    # 1f1b — identical tick count, so the phase walltime delta isolates
    # the schedules' dispatch/stash behavior on real ICI while the
    # printed reports pin the stash high-water the closed form predicts.
    pp_stages = 2
    for pp_schedule in ("gpipe", "1f1b"):
        _run(
            "pipeline_ab",
            [py, "-m", "adapcc_tpu.workloads.train_gpt2",
             "--epochs", "1", "--corpus-tokens", "40000",
             "--batch", "8", "--world", str(world),
             "--pp-stages", str(pp_stages), "--pp-microbatches", "4",
             "--pp-schedule", pp_schedule,
             "--layers", "2", "--dmodel", "64", "--heads", "2"],
            900, out_path,
            rec_extra={
                "pp_schedule": pp_schedule, "pp_stages": pp_stages,
                "pp_microbatches": 4,
            },
        )


def run_simulated_fallback(py: str, out_path: str, world: int = 8) -> dict:
    """Dead-tunnel fallback: record *model-predicted* collective rows so the
    round still ranks its schedule levers (docs/SIMULATION.md).

    The phase record and every row inside it are stamped ``"mode":
    "simulated"`` — the reader contract that a prediction can never be
    mistaken for a measurement.  Pinned to CPU (the simulator is analytic;
    it must not race a half-alive tunnel for the chip) and deterministic:
    the same calibration artifact reproduces byte-identical rows.
    """
    return _run(
        "sim_busbw",
        [py, "-m", "benchmarks.sim_collectives", "--world", str(world),
         "--sizes", "4K,1M,16M,128M", "--json"],
        600, out_path,
        extra_env={"JAX_PLATFORMS": "cpu"},
        rec_extra={"mode": "simulated"},
    )


def main() -> int:
    tag = sys.argv[1] if len(sys.argv) > 1 else "r03"
    out = os.path.join(REPO, "benchmarks", "results", f"hw_{tag}.jsonl")
    os.makedirs(os.path.dirname(out), exist_ok=True)
    py = sys.executable

    probe = _run("probe", [py, "-c", PROBE_CODE], 120, out)
    if probe.get("rc") != 0:
        print("[hw] tunnel dead at probe; recording simulated rows instead",
              flush=True)
        run_simulated_fallback(py, out)
        return 1
    # a CPU-fallback probe must not masquerade as a hardware window
    # (HW_EXPECT_PLATFORM=any opts out, e.g. for harness smoke tests)
    expect = os.environ.get("HW_EXPECT_PLATFORM", "tpu")
    got = (probe.get("parsed") or {}).get("platform", "?")
    if expect != "any" and got != expect:
        print(f"[hw] probe platform {got!r} != expected {expect!r}; "
              "aborting battery (simulated rows recorded instead)", flush=True)
        run_simulated_fallback(py, out)
        return 1

    # headline number first: a short window must still yield the canonical
    # bench row before any of the longer attribution phases get a chance
    # to eat the window (VERDICT r4, "What's weak" #1)
    _run("bench", [py, "bench.py"], 1600, out, {"BENCH_DEADLINE": "1500"})
    # the >= 0.52 MFU attempt: every identified lever at once on a
    # flagship-class (24-layer) config (VERDICT r4 item 2)
    _run(
        "bench_best24", [py, "bench.py"], 1600, out,
        {"BENCH_DEADLINE": "1500", "BENCH_LAYERS": "24",
         "BENCH_FLASH_BLOCK": "auto", "BENCH_LOSS": "chunked",
         "BENCH_OPT_MOMENTS": "bf16"},
    )
    trace_dir = os.path.join(REPO, "benchmarks", "results", f"trace_{tag}")
    _run(
        "profile", [py, "-m", "benchmarks.profile_step"], 900, out,
        {"PROFILE_TRACE_DIR": trace_dir},
    )
    _run(
        "bench_auto", [py, "bench.py"], 1600, out,
        {"BENCH_DEADLINE": "1500", "BENCH_FLASH_BLOCK": "auto"},
    )
    _run(
        "bench_bf16m", [py, "bench.py"], 1600, out,
        {"BENCH_DEADLINE": "1500", "BENCH_OPT_MOMENTS": "bf16"},
    )
    # long-context rows (VERDICT r4 item 7): flash + chunked CE where the
    # dense path hits the [B,H,T,T] memory wall
    _run(
        "bench_t8k", [py, "bench.py"], 1600, out,
        {"BENCH_DEADLINE": "1500", "BENCH_SEQ": "8192", "BENCH_BATCH": "2",
         "BENCH_LOSS": "chunked", "BENCH_STEPS": "5"},
    )
    _run(
        "bench_t16k", [py, "bench.py"], 1600, out,
        {"BENCH_DEADLINE": "1500", "BENCH_SEQ": "16384", "BENCH_BATCH": "1",
         "BENCH_LOSS": "chunked", "BENCH_REMAT": "dots", "BENCH_STEPS": "3"},
    )
    _run(
        "bench_t8k_xla", [py, "bench.py"], 700, out,
        {"BENCH_DEADLINE": "600", "BENCH_SEQ": "8192", "BENCH_BATCH": "2",
         "BENCH_LOSS": "chunked", "BENCH_ATTN": "xla", "BENCH_STEPS": "5"},
    )
    # flash rows first and in their own process: the dense "single" scheme
    # at 8K materializes a ~4 GB score tensor and may OOM — that row is the
    # memory-wall documentation and must not take the flash rows with it
    _run(
        "longcontext",
        [py, "-m", "benchmarks.longcontext", "--world", "1",
         "--seqs", "2K,8K,16K", "--schemes", "ring-flash",
         "--heads", "16", "--head-dim", "64", "--batch", "1", "--json"],
        900, out,
    )
    _run(
        "longcontext_single",
        [py, "-m", "benchmarks.longcontext", "--world", "1",
         "--seqs", "2K,8K", "--schemes", "single",
         "--heads", "16", "--head-dim", "64", "--batch", "1", "--json"],
        700, out,
    )
    _run(
        "zero1_ab", [py, "-m", "benchmarks.zero1_ab", "--json"], 900, out,
    )
    # multi-chip ICI entries, gated on the probe's device count: at world=1
    # each records an explicit skip row; a future multi-chip window captures
    # busbw-vs-psum at 128 MB, the ring smoke, and the chunk sweep with no
    # battery change
    world = int((probe.get("parsed") or {}).get("num_devices", 1) or 1)
    run_multichip_phases(py, out, world)
    _run(
        "bench_chunk", [py, "bench.py"], 1600, out,
        {"BENCH_DEADLINE": "1500", "BENCH_LOSS": "chunked"},
    )
    _run(
        "bench_remat", [py, "bench.py"], 1600, out,
        {"BENCH_DEADLINE": "1500", "BENCH_REMAT": "dots"},
    )
    _run(
        "bench_loop", [py, "bench.py"], 1600, out,
        {"BENCH_DEADLINE": "1500", "BENCH_SCAN": "0"},
    )
    _run(
        "bench_fblk128", [py, "bench.py"], 1600, out,
        {"BENCH_DEADLINE": "1500", "BENCH_FLASH_BLOCK": "128"},
    )
    _run(
        "busbw",
        [py, "-m", "benchmarks.collectives", "--world", "1", "--sizes", "4K,1M,16M,128M"],
        900, out,
    )
    print(f"[hw] battery complete → {out}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
