"""Collective busbw/algbw sweep — the nccl-tests analog for the TPU engine.

Measures every primitive the engine exposes across a message-size sweep and
reports nccl-tests-style numbers (nccl-perf/benchmark/PERFORMANCE.md):

    algbw = bytes_moved / time
    busbw = algbw × correction_factor

with the standard per-collective correction factors — AllReduce ``2(n-1)/n``,
ReduceScatter/AllGather/AllToAll ``(n-1)/n``, Broadcast/Reduce ``1`` — so
numbers are directly comparable to the reference's NCCL baselines
(nccl-perf/tree/report_allreduce.txt) and to any nccl-tests run.

Three allreduce implementations are swept side by side:

* ``xla`` — the ``lax.psum`` fast path (XLA's own ICI schedule),
* ``strategy`` — the synthesized masked-ppermute tree schedule,
* ``pallas_ring`` — the hand-written Pallas ring kernel.

Bytes accounting per collective (``b`` = per-rank payload bytes =
elements × dtype itemsize, ``w`` = world): allreduce/broadcast/reduce move
``b`` bytes per rank; all_gather's and all_to_all's payload is the full
``b·w`` exchanged volume; reduce_scatter's is its ``b`` input per rank.
``--dtype`` sets the payload element type (default float32, the
nccl-tests convention).

Usage (real TPU or the virtual CPU pod)::

    python -m benchmarks.collectives --world 8 --sizes 4K,1M,16M --iters 20
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import statistics
import time
from typing import Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

#: busbw = algbw × factor(world); nccl-perf/benchmark/PERFORMANCE.md:1-140
BUS_FACTORS: Dict[str, Callable[[int], float]] = {
    "allreduce": lambda w: 2 * (w - 1) / w,
    "reduce_scatter": lambda w: (w - 1) / w,
    "all_gather": lambda w: (w - 1) / w,
    "all_to_all": lambda w: (w - 1) / w,
    "broadcast": lambda w: 1.0,
    "reduce": lambda w: 1.0,
}


@dataclasses.dataclass
class BenchResult:
    collective: str
    impl: str
    size_bytes: int  # bytes moved (see module docstring accounting)
    world: int
    time_us: float  # median per-op wall time
    algbw_gbps: float
    busbw_gbps: float
    dtype: str = "float32"
    #: strategy shape behind "strategy"-impl rows, e.g. "ring x8 (merged)";
    #: "" for strategy-independent impls (xla, pallas_ring)
    strategy: str = ""

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self))


def parse_size(text: str) -> int:
    """``"4K" → 4096``; accepts K/M/G suffixes (powers of 1024) or raw ints."""
    text = text.strip().upper()
    mult = 1
    if text and text[-1] in "KMG":
        mult = {"K": 1024, "M": 1024**2, "G": 1024**3}[text[-1]]
        text = text[:-1]
    return int(float(text) * mult)


def _format_size(nbytes: int) -> str:
    for unit, div in (("G", 1024**3), ("M", 1024**2), ("K", 1024)):
        if nbytes >= div and nbytes % div == 0:
            return f"{nbytes // div}{unit}"
    return str(nbytes)


def _time_op(fn: Callable[[], jnp.ndarray], iters: int, warmup: int) -> float:
    """Median wall-clock seconds per op, after ``warmup`` compile/cache calls."""
    for _ in range(warmup):
        jax.block_until_ready(fn())
    samples = []
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready(fn())
        samples.append(time.perf_counter() - t0)
    return statistics.median(samples)


def _make_ops(engine, elems: int, dtype=jnp.float32) -> Dict[str, tuple]:
    """(callable, bytes_moved) per (collective, impl) for one message size.

    On a two-level mesh the engine routes reduce/broadcast through the
    hierarchical schedule regardless of ``active_gpus`` (no XLA fastpath
    there), so emitting both an "xla" and a "strategy" row would time the
    SAME compiled function twice and present the copy as a baseline — only
    the genuinely distinct surfaces are swept.
    """
    world = engine.world_size
    itemsize = jnp.dtype(dtype).itemsize
    rng = np.random.default_rng(elems)
    # pre-place the payload with the engine's sharding: the timed region must
    # cover the collective alone, not a per-call reshard of the input
    sharding = NamedSharding(engine.mesh, P(engine.axis_name))
    if jnp.issubdtype(dtype, jnp.integer):
        host = rng.integers(-8, 8, size=(world, elems))
    else:
        host = rng.normal(size=(world, elems))
    flat = jax.device_put(jnp.asarray(host, dtype), sharding)
    per_rank = elems * itemsize
    total = per_rank * world

    two_level = getattr(engine, "two_level", False)
    # gather/scatter route hierarchically on a (dcn, ici) mesh — label the
    # rows with the impl that actually runs, not the flat default
    gs_impl = "two_level" if two_level else "xla"
    composed = False
    if two_level:
        from adapcc_tpu.strategy.hierarchy import plan_of

        plan = plan_of(engine.strategy)
        composed = plan is not None and plan.pod_algo == "rs-ag"
    ops: Dict[str, tuple] = {}
    if composed:
        # a composed two-level plan outranks the GSPMD fastpath by design
        # (DCN-volume control is the point), so the bare call IS the
        # composed plan — an "xla" row here would time the same program
        # under a baseline label.  The flat-baseline arm comes from the
        # projected (non --hier) invocation.
        ops[("allreduce", "two_level_composed")] = (
            lambda: engine.all_reduce(flat, active_gpus=list(range(world))),
            per_rank,
        )
    else:
        ops[("allreduce", "xla")] = (lambda: engine.all_reduce(flat), per_rank)
        ops[("allreduce", "strategy")] = (
            lambda: engine.all_reduce(flat, active_gpus=list(range(world))),
            per_rank,
        )
    ops[("all_gather", gs_impl)] = (lambda: engine.all_gather(flat), total)
    ops[("reduce_scatter", gs_impl)] = (
        lambda: engine.reduce_scatter(flat), per_rank,
    )
    # subset rows: one rank masked out — regression-pins the cost of the
    # active-mask relay path on the gather/scatter primitives (VERDICT r4
    # item 3); same bytes accounting as the full-world rows.  world >= 2
    # only: at world=1 the "subset" would be empty and the rows would time
    # an all-zeros identity program masquerading as the relay path
    subset = list(range(world - 1))
    if world >= 2:
        ops[("all_gather", "subset")] = (
            lambda: engine.all_gather(flat, active_gpus=subset), total,
        )
        if elems % world == 0:
            ops[("reduce_scatter", "subset")] = (
                lambda: engine.reduce_scatter(flat, active_gpus=subset), per_rank,
            )
    if not two_level:
        from adapcc_tpu.compat import ring_kernels_supported

        # the ring kernels need Mosaic (real TPU) or the TPU interpret mode
        # (jax >= 0.5); on builds with neither, emitting the rows would turn
        # the whole sweep into a crash instead of a sweep minus three rows
        if ring_kernels_supported():
            ops[("allreduce", "pallas_ring")] = (
                lambda: engine.ring_allreduce(flat), per_rank,
            )
            if elems % world == 0:
                ops[("reduce_scatter", "pallas_ring")] = (
                    lambda: engine.ring_reduce_scatter(flat), per_rank,
                )
            from adapcc_tpu.comm.pallas_ring import _tile_elems

            if elems % _tile_elems(dtype) == 0:
                ops[("all_gather", "pallas_ring")] = (
                    lambda: engine.ring_all_gather(flat), total,
                )
        # active_gpus pins the schedule path; bare calls ride the XLA
        # fastpath (flat meshes only — see docstring)
        ops[("reduce", "xla")] = (lambda: engine.reduce(flat), per_rank)
        ops[("broadcast", "xla")] = (lambda: engine.broadcast(flat), per_rank)
    ops[("reduce", "strategy")] = (
        lambda: engine.reduce(flat, active_gpus=list(range(world))), per_rank,
    )
    ops[("broadcast", "strategy")] = (
        lambda: engine.broadcast(flat, active_gpus=list(range(world))), per_rank,
    )
    if elems % world == 0:
        blocked = jax.device_put(
            np.asarray(flat).reshape(world, world, elems // world), sharding
        )
        ops[("all_to_all", gs_impl)] = (lambda: engine.all_to_all(blocked), total)
        if world >= 2:
            ops[("all_to_all", "subset")] = (
                lambda: engine.all_to_all(blocked, active_gpus=subset), total,
            )
    return ops


def _strategy_label(engine) -> str:
    """Self-describing artifact rows: strategy shape + whether the engine's
    schedule path runs merged multi-tree rounds (both the flat and the
    two-level plan respect the ADAPCC_MERGE_ROUNDS kill-switch, so A/B rows
    are distinguishable)."""
    strat = engine.strategy
    label = f"{strat.synthesis or 'unnamed'} x{strat.num_trans}"
    if getattr(engine, "two_level", False):
        from adapcc_tpu.comm.two_level import _two_level_merged_plan

        merged = _two_level_merged_plan(
            strat, engine.num_slices, engine.ici_size
        ) is not None
    else:
        from adapcc_tpu.comm.engine import _merged_plan

        merged = _merged_plan(strat) is not None
    return label + (" (merged)" if merged else "")


def run_sweep(
    engine,
    sizes_bytes: Sequence[int],
    collectives: Optional[Sequence[str]] = None,
    impls: Optional[Sequence[str]] = None,
    iters: int = 20,
    warmup: int = 2,
    dtype=jnp.float32,
) -> List[BenchResult]:
    """Sweep ``sizes_bytes`` (per-rank payload bytes) over the engine's ops."""
    world = engine.world_size
    results: List[BenchResult] = []
    itemsize = jnp.dtype(dtype).itemsize
    for nbytes in sizes_bytes:
        elems = max(1, nbytes // itemsize)
        for (coll, impl), (fn, moved) in _make_ops(engine, elems, dtype).items():
            if collectives and coll not in collectives:
                continue
            if impls and impl not in impls:
                continue
            sec = _time_op(fn, iters, warmup)
            algbw = moved / sec / 1e9
            results.append(
                BenchResult(
                    collective=coll,
                    impl=impl,
                    size_bytes=moved,
                    world=world,
                    time_us=sec * 1e6,
                    algbw_gbps=algbw,
                    busbw_gbps=algbw * BUS_FACTORS[coll](world),
                    dtype=jnp.dtype(dtype).name,
                    strategy=(
                        _strategy_label(engine)
                        if impl in ("strategy", "two_level_composed")
                        else ""
                    ),
                )
            )
    return results


def format_table(results: Sequence[BenchResult]) -> str:
    """nccl-tests-style report table."""
    lines = [
        f"{'collective':<15}{'impl':<13}{'size':>8}{'time(us)':>12}"
        f"{'algbw(GB/s)':>13}{'busbw(GB/s)':>13}"
    ]
    for r in results:
        lines.append(
            f"{r.collective:<15}{r.impl:<13}{_format_size(r.size_bytes):>8}"
            f"{r.time_us:>12.1f}{r.algbw_gbps:>13.3f}{r.busbw_gbps:>13.3f}"
        )
    return "\n".join(lines)


def main(argv: Optional[Sequence[str]] = None) -> None:
    from adapcc_tpu.comm.engine import CollectiveEngine
    from adapcc_tpu.comm.mesh import build_world_mesh
    from adapcc_tpu.launch.launcher import apply_platform_env
    from adapcc_tpu.strategy.ir import Strategy

    apply_platform_env()  # honor JAX_PLATFORMS despite the site customization

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--world", type=int, default=0, help="mesh size (default: all devices)")
    ap.add_argument("--sizes", default="4K,64K,1M,16M", help="comma list, K/M/G suffixes")
    ap.add_argument("--collectives", default="", help="comma subset (default: all)")
    ap.add_argument(
        "--impls", default="",
        help="comma subset of xla,strategy,pallas_ring,subset "
        "(plus two_level on a --two-level mesh)",
    )
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--warmup", type=int, default=2)
    ap.add_argument("--strategy", choices=["ring", "binary"], default="binary")
    ap.add_argument("--trans", type=int, default=1,
                    help="num_trans parallel trees (the reference's parallel-"
                    "transmission axis; >1 engages merged-round execution)")
    ap.add_argument("--dtype", choices=["f32", "bf16", "int8"], default="f32",
                    help="payload dtype (pallas_ring has per-dtype tiling)")
    ap.add_argument(
        "--wire-dtype", choices=["off", "bf16", "int8"], default="off",
        help="strategy wire codec for the IR path (the compiled program "
        "carries it, so an ADAPCC_WIRE_DTYPE pin of the same codec agrees "
        "instead of tripping the engine's conflict guard)",
    )
    ap.add_argument(
        "--two-level", default="",
        help='"DxI" (e.g. 2x4): hierarchical (dcn, ici) mesh — the strategy '
        "is ParTrees-synthesized over the slice layout and executes as "
        "ICI-collective + DCN master-tree rounds (comm/two_level.py)",
    )
    ap.add_argument(
        "--hier", action="store_true",
        help="under --two-level: synthesize the composed two-level plan "
        "(strategy/hierarchy.py — RS-within-pod, AR-across-leaders, "
        "AG-within-pod) instead of the ParTrees projection.  Allreduce "
        "then emits a single 'two_level_composed' row (the composed plan "
        "outranks the GSPMD fastpath, so there is no honest in-invocation "
        "'xla' baseline); the flat/projected arms come from a separate "
        "non --hier invocation — the A/B the hw battery's "
        "two_level_synth entry assembles (docs/HIERARCHY.md)",
    )
    ap.add_argument("--json", action="store_true", help="emit JSON lines instead of a table")
    args = ap.parse_args(argv)

    impls = [i for i in args.impls.split(",") if i] or None
    if impls and "pallas_ring" in impls:
        from adapcc_tpu.compat import ring_kernels_supported

        if not ring_kernels_supported():
            # an explicitly requested impl must fail loudly, not produce a
            # zero-row sweep that reads as "ran fine, no data"
            ap.error(
                "pallas_ring was requested but this build can't run the "
                "ring kernels (needs a real TPU or the Mosaic TPU "
                "interpret mode, jax >= 0.5); drop it from --impls"
            )
    if args.two_level:
        import re

        from adapcc_tpu.comm.mesh import mesh_ip_table
        from adapcc_tpu.comm.two_level import build_two_level_mesh
        from adapcc_tpu.primitives import ALLREDUCE
        from adapcc_tpu.strategy.synthesizer import Synthesizer

        m = re.fullmatch(r"([1-9]\d*)x([1-9]\d*)", args.two_level.lower())
        if not m or int(m.group(1)) < 2 or int(m.group(2)) < 2:
            ap.error(
                f'--two-level expects "DxI" with D, I >= 2 (e.g. 2x4), '
                f"got {args.two_level!r}"
            )
        if args.world or args.strategy != "binary":
            ap.error(
                "--two-level is exclusive with --world/--strategy: the mesh "
                "size is DxI and the hierarchy is ParTrees-synthesized "
                "(--trans feeds the synthesizer's parallel_degree)"
            )
        if impls and "pallas_ring" in impls:
            ap.error(
                "pallas_ring is a flat-mesh kernel; drop it from --impls "
                "under --two-level"
            )
        dcn, ici = int(m.group(1)), int(m.group(2))
        world = dcn * ici
        mesh = build_two_level_mesh(dcn, ici)
        if args.hier:
            # the synthesized composed plan (docs/HIERARCHY.md): the
            # engine dispatches its RS→AR→AG phases for the strategy rows
            from adapcc_tpu.strategy.hierarchy import (
                HierarchySketch,
                synthesize_two_level,
            )

            plan = synthesize_two_level(
                HierarchySketch(dcn, ici, tuple(mesh_ip_table(mesh))),
                nbytes=4 << 20,
                num_trans=args.trans,
            )
            strategy = plan.strategy
        else:
            # uniform profile → ParTrees emits the masters-plus-chains
            # hierarchy that the two-level executor splits into ICI + DCN
            # phases
            ones = [[1.0] * world for _ in range(world)]
            strategy = Synthesizer(None, mesh_ip_table(mesh)).synthesize(
                ALLREDUCE, args.trans, 4 << 20, ones, ones
            )
        # impls stays None (no filter): _make_ops already emits only the
        # surfaces a two-level mesh supports (no pallas_ring rows there),
        # and a hardcoded label list would silently drop any future impl —
        # exactly the bug that once hid the two_level/subset rows
    else:
        if args.hier:
            ap.error(
                "--hier synthesizes a two-level plan; it needs --two-level "
                '"DxI" to name the pod layout'
            )
        world = args.world or len(jax.devices())
        mesh = build_world_mesh(world)
        strategy = (
            Strategy.ring(world, args.trans)
            if args.strategy == "ring"
            else Strategy.binary(world, args.trans)
        )
    if args.wire_dtype != "off":
        strategy.wire_dtype = args.wire_dtype
    engine = CollectiveEngine(mesh, strategy)

    results = run_sweep(
        engine,
        [parse_size(s) for s in args.sizes.split(",") if s],
        collectives=[c for c in args.collectives.split(",") if c] or None,
        impls=impls,
        iters=args.iters,
        warmup=args.warmup,
        dtype={"f32": jnp.float32, "bf16": jnp.bfloat16, "int8": jnp.int8}[args.dtype],
    )
    if args.json:
        for r in results:
            print(r.to_json())
    else:
        print(f"# world={world} platform={jax.devices()[0].platform} dtype={args.dtype}")
        print(format_table(results))


if __name__ == "__main__":
    main()
