"""Pod-scale synthesizer benchmark: milp vs partrees vs ring vs the
hierarchical sketch policy, world 32 → 4096.

The reference ships strategy fixtures up to 24 GPUs (`strategy/`, 17 files)
and its Gurobi study compares solver vs heuristic makespans
(gurobi/solver.py:190-208).  This sweep reproduces that comparison at pod
scale on synthetic two-level topologies, putting all three synthesis
policies on one modeled scale:

- **policy wall time** — synthesis latency with the solver's own runtime
  budget (`ROUTING_MILP_TIME_LIMIT_S`) in force, i.e. what topology
  reconstruction would actually stall;
- **modeled makespan** — the routing MILP's pipeline-aware bottleneck
  objective evaluated on every policy's output
  (:func:`adapcc_tpu.strategy.solver.modeled_makespan`);
- **lowering** — rounds per tree through ``reduce_rounds`` /
  ``broadcast_rounds``; at >= ``Tree.NATIVE_LOWERING_THRESHOLD`` (64) ranks
  this exercises the native C++ lowering engine when ``libadapcc_rt.so`` is
  built (strategy/ir.py:162);
- optional ``--exec``: relative busbw of each policy's allreduce executed on
  a virtual CPU pod of the same world size (NOT a hardware number — an
  ordering regression pin, like busbw_virtual8).

The degraded-link topologies are where the policies genuinely diverge: one
host pair's DCN bandwidth is cut to a fraction, so bandwidth-aware synthesis
(milp / partrees BDP sort) should beat the oblivious ring on the modeled
makespan.

The ``hier`` policy rows (docs/HIERARCHY.md) are the pod-cluster
extension: matrix-free per-level solves whose wall time stays inside
``MILP_SYNTH_BUDGET_S`` all the way to world=4096, recorded next to the
flat policies' blowout — every row stamps ``synth_budget_s`` /
``within_synth_budget`` so the scaling curve is pinned, not eyeballed.

Usage::

    python -m benchmarks.synthesis_scale --worlds 32,64 --json
    XLA_FLAGS=--xla_force_host_platform_device_count=32 JAX_PLATFORMS=cpu \
        python -m benchmarks.synthesis_scale --worlds 32 --exec --json
"""

from __future__ import annotations

import argparse
import json
import time
from typing import List, Optional, Sequence, Tuple

from adapcc_tpu.primitives import ALLREDUCE

#: intra-host (ICI) / healthy inter-host (DCN) link model, in GB/s and s —
#: the same two-tier shape the reference's cluster profiles have
#: (strategy/cluster_*.xml: NVLink vs 100GbE)
ICI_BW, ICI_LAT = 400.0, 1e-6
DCN_BW, DCN_LAT = 25.0, 5e-5

#: largest world the dense-matrix (flat) policies run at in the default
#: sweep: the flat MILP measures ~5.9 s at 1024 (already 6x the budget —
#: the row records the blowout) and minutes at 4096; the hierarchical
#: sketch policy carries the curve beyond this, matrix-free
MATRIX_POLICY_MAX_WORLD = 1024

#: replay-scaling world grid (--replay-scale): the vectorized engine's
#: scaling curve, recorded next to the synthesis curve it unblocks
REPLAY_WORLDS = (1024, 4096, 16384, 65536, 131072)

#: replay wall-clock budgets the scaling rows pin, mirroring
#: ``synth_budget_s``: a world<=16384 strategy must replay in < 2 s (the
#: controller's re-rank window) and even 131072 in < 30 s
REPLAY_BUDGET_S = 2.0
REPLAY_BUDGET_LARGE_S = 30.0
REPLAY_BUDGET_MAX_WORLD = 16384


def replay_budget_s(world: int) -> float:
    """The wall-clock budget a ``world``-rank replay is pinned against."""
    return REPLAY_BUDGET_S if world <= REPLAY_BUDGET_MAX_WORLD else REPLAY_BUDGET_LARGE_S


def bench_replay(
    world: int,
    transmission_size: int = 64 << 20,
    collective: str = "allreduce",
) -> dict:
    """Replay-scaling row: build + cold replay + warm re-price wall times
    for a ``world``-rank binary strategy on a uniform synthetic topology,
    stamped ``replay_budget_s`` / ``within_replay_budget_s`` (the replay
    twin of ``synth_budget_s`` / ``within_synth_budget``).

    The cold replay includes column lowering; the re-price row shows what
    the adaptation loop actually pays once the structure cache is warm
    (docs/SIMULATION.md §7).  Wall times are measured, so these rows are
    NOT byte-identical across runs — the deterministic priced grid lives
    in ``sim_collectives --scale-sweep``.
    """
    from adapcc_tpu.sim.cost_model import (
        LinkCostModel, collective_lower_bound, optimality_gap,
    )
    from adapcc_tpu.sim.replay import simulate_strategy
    from adapcc_tpu.sim.vector import clear_lowering_cache, resolve_sim_engine
    from adapcc_tpu.strategy.ir import Strategy

    model = LinkCostModel.uniform(world)
    t0 = time.perf_counter()
    strategy = Strategy.binary(world, 2)
    build_s = time.perf_counter() - t0

    clear_lowering_cache()  # the cold number must include column lowering
    t0 = time.perf_counter()
    timeline = simulate_strategy(
        strategy, model, transmission_size, collective, keep_transfers=False
    )
    replay_s = time.perf_counter() - t0

    t0 = time.perf_counter()  # warm: cached columns, pricing only
    simulate_strategy(
        strategy, model, transmission_size, collective, keep_transfers=False
    )
    reprice_s = time.perf_counter() - t0

    lb = collective_lower_bound(model, transmission_size, collective, world)
    budget = replay_budget_s(world)
    return {
        "world": world,
        "policy": "replay",
        "strategy": "binary",
        "engine": resolve_sim_engine(None, world),
        "size_bytes": int(transmission_size),
        "build_ms": round(build_s * 1e3, 2),
        "replay_ms": round(replay_s * 1e3, 2),
        "reprice_ms": round(reprice_s * 1e3, 2),
        "pred_time_us": round(timeline.seconds * 1e6, 3),
        "lower_bound_us": round(lb * 1e6, 3),
        "optimality_gap": round(optimality_gap(timeline.seconds, lb), 6),
        "replay_budget_s": budget,
        "within_replay_budget_s": replay_s <= budget,
    }


def synthetic_ip_table(num_hosts: int, per_host: int) -> List[str]:
    """The matrix-free half of :func:`synthetic_topology` — all the
    hierarchical sketch policy needs, so pod-cluster worlds never pay the
    world² matrix build just to benchmark an O(pod)+O(hosts) solve."""
    return [f"10.8.{h}.1" for h in range(num_hosts) for _ in range(per_host)]


def synthetic_topology(
    num_hosts: int, per_host: int, degraded_pair: Optional[Tuple[int, int]] = (0, 1),
    degrade_factor: float = 0.25,
):
    """(ip_table, bandwidth_graph, latency_graph) for a two-level pod.

    ``degraded_pair`` cuts one host pair's DCN bandwidth by
    ``degrade_factor`` — the adaptive-routing case the synthesizers exist
    for (reference README: "adapts to dynamic network conditions").
    Vectorized: the pod-scale worlds the default grid now reaches would
    spend longer building matrices in a Python loop than synthesizing.
    """
    import numpy as np

    world = num_hosts * per_host
    ip_table = synthetic_ip_table(num_hosts, per_host)
    host_of = np.arange(world) // per_host
    same = host_of[:, None] == host_of[None, :]
    bw = np.where(same, ICI_BW, DCN_BW)
    lat = np.where(same, ICI_LAT, DCN_LAT)
    if degraded_pair is not None:
        a, b = degraded_pair
        pair = (
            (host_of[:, None] == a) & (host_of[None, :] == b)
        ) | (
            (host_of[:, None] == b) & (host_of[None, :] == a)
        )
        bw = np.where(pair, DCN_BW * degrade_factor, bw)
        lat = np.where(pair, DCN_LAT * 4, lat)
    np.fill_diagonal(bw, 0.0)
    np.fill_diagonal(lat, 0.0)
    return ip_table, bw.tolist(), lat.tolist()


def crosshost_makespan(
    strategy,
    bw: Sequence[Sequence[float]],
    lat: Sequence[Sequence[float]],
    transmission_size: int,
) -> float:
    """Policy-agnostic bottleneck-edge time in SECONDS: max over every tree
    edge of ``lat + share·size/(bw·1e9)`` (bw in GB/s, the profiler's
    convention).  Unlike :func:`modeled_makespan` — which projects to
    inter-master edges and so scores a master-chain ring as zero — this
    walks ALL edges, making ring vs tree strategies comparable."""
    import numpy as np

    b = np.asarray(bw, float)
    l = np.asarray(lat, float)
    worst = 0.0
    for tree, share in zip(strategy.trees, strategy.tree_shares()):
        if share <= 0.0:
            continue
        for p, cs in tree.children.items():
            for c in cs:
                t = l[p][c] + share * transmission_size / (max(b[p][c], 1e-9) * 1e9)
                worst = max(worst, float(t))
    return worst


def bench_policy(
    policy: str,
    ip_table: Sequence[str],
    bw: Sequence[Sequence[float]],
    lat: Sequence[Sequence[float]],
    parallel_degree: int = 2,
    transmission_size: int = 4 << 20,
) -> dict:
    """Synthesize + score one policy; returns one artifact row.

    Every row carries ``synth_budget_s`` / ``within_synth_budget`` (the
    reconstruction budget the pruned MILP earned at 64 ranks, PR 2), so
    the pod-scale curve is pinned per policy rather than eyeballed.  The
    ``hier`` policy (docs/HIERARCHY.md) needs no profile matrices — pass
    ``bw=lat=None`` and the row prices off the sketch's class
    coefficients; matrix policies reject None loudly.
    """
    from adapcc_tpu import native
    from adapcc_tpu.strategy.solver import MILP_SYNTH_BUDGET_S, modeled_makespan
    from adapcc_tpu.strategy.synthesizer import Synthesizer, _infer_local_rank0s

    world = len(ip_table)
    masters = _infer_local_rank0s(ip_table)
    have_matrices = bw is not None and lat is not None
    if policy != "hier" and not have_matrices:
        raise ValueError(
            f"policy {policy!r} synthesizes from profile matrices; only "
            "'hier' runs matrix-free (the sketch's class coefficients)"
        )
    t0 = time.perf_counter()
    strategy = Synthesizer(None, ip_table, policy).synthesize(
        ALLREDUCE, parallel_degree, transmission_size, bw, lat
    )
    synth_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    rounds = sum(
        len(t.reduce_rounds()) + len(t.broadcast_rounds()) for t in strategy.trees
    )
    lower_s = time.perf_counter() - t0
    row = {
        "world": world,
        "hosts": len(masters),
        "policy": policy,
        "synthesis": strategy.synthesis,
        "num_trees": len(strategy.trees),
        "synth_ms": round(synth_s * 1e3, 2),
        "lowering_ms": round(lower_s * 1e3, 2),
        "rounds": rounds,
        "native_lowering": bool(
            native.available()
            and world >= type(strategy.trees[0]).NATIVE_LOWERING_THRESHOLD
        ),
        "synth_budget_s": MILP_SYNTH_BUDGET_S,
        "within_synth_budget": synth_s <= MILP_SYNTH_BUDGET_S,
    }
    if have_matrices:
        # raw model units (reference gurobi objective) — inter-master edges
        # only, comparable between milp and partrees
        row["modeled_makespan"] = float(
            modeled_makespan(
                strategy, masters, ALLREDUCE, transmission_size, bw, lat
            )
        )
        # seconds → ms, every edge scored — comparable across ALL policies
        row["crosshost_makespan_ms"] = round(
            crosshost_makespan(strategy, bw, lat, transmission_size) * 1e3, 4
        )
    if policy == "hier":
        from adapcc_tpu.strategy.hierarchy import plan_of

        plan = plan_of(strategy)
        row.update({
            "hier_pods": plan.sketch.num_pods,
            "hier_pod_size": plan.sketch.pod_size,
            "pod_algo": plan.pod_algo,
            "leader_algo": plan.leader_algo,
            "ici_solve_ms": round(plan.ici_solve.solve_s * 1e3, 4),
            "dcn_solve_ms": round(plan.dcn_solve.solve_s * 1e3, 4),
            "pred_two_level_us": round(plan.predicted_s * 1e6, 3),
            "pred_flat_us": round(plan.flat_pred_s * 1e6, 3),
            "chosen_vs_flat": plan.chosen_vs_flat,
        })
    return row


def exec_relative_busbw(
    policy: str,
    ip_table: Sequence[str],
    bw,
    lat,
    elems: int = 16384,
    iters: int = 3,
) -> dict:
    """Execute the policy's allreduce on a virtual pod of the same world
    size; returns a timing row (ordering evidence only, not hardware)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from adapcc_tpu.comm.engine import CollectiveEngine
    from adapcc_tpu.comm.mesh import build_world_mesh
    from adapcc_tpu.strategy.synthesizer import Synthesizer

    world = len(ip_table)
    if len(jax.devices()) < world:
        raise RuntimeError(
            f"--exec needs {world} devices "
            f"(set XLA_FLAGS=--xla_force_host_platform_device_count={world})"
        )
    strategy = Synthesizer(None, ip_table, policy).synthesize(
        ALLREDUCE, 2, 4 << 20, bw, lat
    )
    mesh = build_world_mesh(world)
    eng = CollectiveEngine(mesh, strategy, use_xla_fastpath=False)
    x = jnp.asarray(
        np.random.default_rng(0).normal(size=(world, elems)), jnp.float32
    )
    active = list(range(world))
    jax.block_until_ready(eng.all_reduce(x, active_gpus=active))  # compile
    t0 = time.perf_counter()
    for _ in range(iters):
        jax.block_until_ready(eng.all_reduce(x, active_gpus=active))
    per_op = (time.perf_counter() - t0) / iters
    return {
        "world": world,
        "policy": policy,
        "exec_virtual_ms": round(per_op * 1e3, 2),
        "elems": elems,
    }


def main(argv: Optional[Sequence[str]] = None) -> int:
    # honor JAX_PLATFORMS despite the axon sitecustomize platform pin —
    # without this the --exec path tries the TPU tunnel even on a cpu run
    from adapcc_tpu.launch.launcher import apply_platform_env

    apply_platform_env()

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--worlds", default="32,64,256,1024,4096",
                    help="comma list of world sizes (8 ranks per host)")
    ap.add_argument("--per-host", type=int, default=8)
    ap.add_argument("--policies", default="par-trees,milp,ring,hier")
    ap.add_argument("--degrade", type=float, default=0.25,
                    help="bandwidth factor for the degraded host pair (1.0 = healthy)")
    ap.add_argument("--exec", action="store_true", dest="exec_",
                    help="also execute each policy's allreduce on a virtual pod")
    ap.add_argument("--replay-scale", action="store_true",
                    help="also emit replay-scaling rows (--replay-worlds x "
                    "replay wall-ms on the vectorized engine, budget-stamped)")
    ap.add_argument("--replay-worlds",
                    default=",".join(str(w) for w in REPLAY_WORLDS),
                    help="replay-scaling world grid")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    rows: List[dict] = []
    if args.replay_scale:
        for world in (int(w) for w in args.replay_worlds.split(",") if w):
            rows.append(bench_replay(world))
    for world in (int(w) for w in args.worlds.split(",") if w):
        if world % args.per_host:
            raise SystemExit(f"world {world} must divide per-host {args.per_host}")
        hosts = world // args.per_host
        degraded = (0, 1) if args.degrade < 1.0 and hosts >= 2 else None
        policies = [p for p in args.policies.split(",") if p]
        # matrix policies stop at MATRIX_POLICY_MAX_WORLD: beyond it the
        # flat synthesis (and the world² matrix build feeding it) is
        # minutes of wall time — the sketch policy exists exactly because
        # that does not scale.  Explicit skip rows keep the curve honest.
        need_matrices = any(p != "hier" for p in policies)
        if need_matrices and world <= MATRIX_POLICY_MAX_WORLD:
            ip_table, bw, lat = synthetic_topology(
                hosts, args.per_host, degraded_pair=degraded,
                degrade_factor=args.degrade,
            )
        else:
            ip_table, bw, lat = synthetic_ip_table(hosts, args.per_host), None, None
        for policy in policies:
            if policy != "hier" and bw is None:
                rows.append({
                    "world": world, "hosts": hosts, "policy": policy,
                    "skipped": (
                        f"world {world} > {MATRIX_POLICY_MAX_WORLD}: flat "
                        "synthesis over dense profile matrices exceeds the "
                        "budget by orders of magnitude at this scale "
                        "(the hier rows carry the curve)"
                    ),
                })
                continue
            if policy == "hier" and hosts < 2:
                rows.append({
                    "world": world, "hosts": hosts, "policy": policy,
                    "skipped": "single host: no hierarchy to sketch",
                })
                continue
            row = bench_policy(policy, ip_table, bw, lat)
            row["degrade_factor"] = args.degrade if degraded else 1.0
            rows.append(row)
            if args.exec_:
                rows.append(exec_relative_busbw(policy, ip_table, bw, lat))

    for r in rows:
        if args.json:
            print(json.dumps(r))
        else:
            print(r)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
