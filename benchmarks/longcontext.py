"""Long-context SP attention sweep: ring vs Ulysses vs single-device.

The sequence-parallel story in numbers (the reference has no SP at all,
SURVEY §5.7): per (scheme, T) this measures one forward+backward of the
attention program with the sequence sharded over the world axis, reporting
ms/call and the peak per-device *score memory* the dense single-device path
would need (``[B, H, T, T]`` fp32) versus what the SP schemes actually
materialize — the reason long context needs SP even before speed does.

Schemes:

* ``single``     — dense attention on one device (the memory wall baseline)
* ``ring``       — K/V blocks rotate over the axis; ``[Tl, Tl]`` scores
* ``ring-flash`` — ring with the Pallas blockwise kernel; O(Tl) memory
* ``ulysses``    — all-to-all head exchange; full-T scores on H/world heads

Usage::

    python -m benchmarks.longcontext --world 4 --seqs 1K,4K --heads 4 --json
"""

from __future__ import annotations

import argparse
import dataclasses
import json
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from benchmarks.collectives import _time_op, parse_size


@dataclasses.dataclass
class LCResult:
    scheme: str
    seq: int
    world: int
    heads: int
    head_dim: int
    fwd_bwd_ms: float
    #: fp32 bytes of attention scores materialized per device at once
    score_bytes_per_device: int

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self))


def _score_bytes(scheme: str, B: int, H: int, T: int, world: int, block: int) -> int:
    if scheme == "single":
        return 4 * B * H * T * T
    Tl = T // world
    if scheme == "ring":
        return 4 * B * H * Tl * Tl
    if scheme == "ring-flash":
        bq = min(block, Tl)
        return 4 * B * H * bq * bq  # one [bq, bq] tile in VMEM per head
    if scheme == "ulysses":
        return 4 * B * (H // world) * T * T
    raise ValueError(scheme)


def run_sweep(
    world: int,
    seqs: Sequence[int],
    heads: int = 4,
    head_dim: int = 32,
    batch: int = 1,
    iters: int = 3,
    warmup: int = 1,
    schemes: Optional[Sequence[str]] = None,
    block: int = 128,
):
    from adapcc_tpu.parallel import ring_attention, ulysses_attention
    from adapcc_tpu.parallel.ring_attention import reference_attention

    known = ("single", "ring", "ring-flash", "ulysses")
    if schemes:
        unknown = [s for s in schemes if s not in known]
        if unknown:
            raise ValueError(f"unknown schemes {unknown}; choose from {known}")
    if len(jax.devices()) < world:
        raise ValueError(f"need {world} devices, have {len(jax.devices())}")
    mesh = Mesh(np.array(jax.devices()[:world]), ("ranks",))
    results = []
    for T in seqs:
        if T % world:
            raise ValueError(f"seq {T} must divide by world {world}")
        rng = np.random.default_rng(T)
        q, k, v = (
            jnp.asarray(rng.normal(size=(batch, T, heads, head_dim)) * 0.5, jnp.float32)
            for _ in range(3)
        )

        progs = {
            "single": lambda q, k, v: reference_attention(q, k, v),
            "ring": lambda q, k, v: ring_attention(mesh, q, k, v, block_impl="dense"),
            "ring-flash": lambda q, k, v: ring_attention(
                mesh, q, k, v, block_impl="flash", block_q=block, block_k=block
            ),
            "ulysses": lambda q, k, v: ulysses_attention(mesh, q, k, v),
        }
        for scheme, prog in progs.items():
            if schemes and scheme not in schemes:
                continue

            def loss(q, k, v, prog=prog):
                return jnp.sum(prog(q, k, v).astype(jnp.float32) ** 2)

            step = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
            sec = _time_op(lambda: step(q, k, v), iters, warmup)
            results.append(
                LCResult(
                    scheme=scheme,
                    seq=T,
                    world=world,
                    heads=heads,
                    head_dim=head_dim,
                    fwd_bwd_ms=round(sec * 1e3, 2),
                    score_bytes_per_device=_score_bytes(
                        scheme, batch, heads, T, world, block
                    ),
                )
            )
    return results


def main(argv: Optional[Sequence[str]] = None) -> None:
    # honor JAX_PLATFORMS despite the axon sitecustomize platform pin —
    # a cpu run must not stall on a dead TPU tunnel's backend init
    from adapcc_tpu.launch.launcher import apply_platform_env

    apply_platform_env()

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--world", type=int, default=0)
    ap.add_argument("--seqs", default="1K,4K")
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--head-dim", type=int, default=32)
    ap.add_argument("--batch", type=int, default=1)
    ap.add_argument("--iters", type=int, default=3)
    ap.add_argument("--schemes", default="", help="comma subset")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    world = args.world or len(jax.devices())
    if not args.json:
        print(f"# world={world} platform={jax.devices()[0].platform}")
        print(f"{'scheme':<12}{'seq':>8}{'fwd+bwd(ms)':>14}{'score-bytes/dev':>18}")
    # one run_sweep per seq, rows flushed as they land: an OOM at a later
    # sequence length (the dense path's expected fate at 8K+) must not eat
    # the measurements already taken at the shorter ones
    for seq in (parse_size(s) for s in args.seqs.split(",") if s):
        results = run_sweep(
            world,
            [seq],
            heads=args.heads,
            head_dim=args.head_dim,
            batch=args.batch,
            iters=args.iters,
            schemes=[s for s in args.schemes.split(",") if s] or None,
        )
        for r in results:
            if args.json:
                print(r.to_json(), flush=True)
            else:
                print(
                    f"{r.scheme:<12}{r.seq:>8}{r.fwd_bwd_ms:>14.1f}"
                    f"{r.score_bytes_per_device:>18,}",
                    flush=True,
                )


if __name__ == "__main__":
    main()
