"""Attribute the GPT-2 train-step time to components on the live backend.

The round-1 hardware number (3,265 tok/s ≈ 0.4% MFU on a v5e chip) was never
explained; this harness produces the attribution (VERDICT r2 #3).  It times,
on the same device and sizes as bench.py:

1. ``dispatch``   — a trivial jitted op in a loop: per-call host→device
                    dispatch latency (the remote-tunnel tax);
2. ``matmul``     — a large bf16 matmul chain: achievable MXU TFLOP/s
                    (the realistic ceiling, vs the advertised peak);
3. ``forward``    — GPT-2 forward only;
4. ``grad``       — value_and_grad (forward + backward);
5. ``train``      — the full DDPTrainer step (grad + allreduce + adamw).

Each phase prints one line immediately (the tunnel can die mid-run); the
final JSON line carries the whole breakdown plus derived MFU per phase.
Optionally dumps a Perfetto/XPlane trace: ``PROFILE_TRACE_DIR=/tmp/trace``.

Usage::

    python -m benchmarks.profile_step            # bench.py default sizes
    BENCH_LAYERS=8 BENCH_DMODEL=512 python -m benchmarks.profile_step
"""

from __future__ import annotations

import contextlib
import json
import os
import sys
import time


def _progress(msg: str) -> None:
    print(f"[profile] {msg}", file=sys.stderr, flush=True)


def _first_scalar(out):
    """A scalar host read of one output element — closes the timing window
    even on remote-tunnel backends where ``block_until_ready`` can return
    before execution completes (same methodology as bench.py time_steps)."""
    import jax
    import jax.numpy as jnp

    leaf = jax.tree_util.tree_leaves(out)[0]
    return float(jax.device_get(jnp.ravel(leaf)[0]))


def _timed(fn, iters: int = 10, warmup: int = 2) -> float:
    """Mean seconds per call over one timed window, compile excluded; the
    window is closed by a scalar device_get (not block_until_ready)."""
    for _ in range(warmup):
        _first_scalar(fn())
    t0 = time.perf_counter()
    out = None
    for _ in range(iters):
        out = fn()
    _first_scalar(out)
    return (time.perf_counter() - t0) / iters


def main() -> None:
    from adapcc_tpu.launch.launcher import apply_platform_env

    apply_platform_env()

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import bench as bench_mod
    from bench import _env_int  # shared env knob parsing
    from adapcc_tpu.comm.mesh import build_world_mesh
    from adapcc_tpu.ddp import DDPTrainer, TrainState
    from adapcc_tpu.models.gpt2 import GPT2, GPT2Config, lm_loss
    from adapcc_tpu.strategy.ir import Strategy

    out = {"device": str(jax.devices()[0]), "phases": {}}
    trace_dir = os.environ.get("PROFILE_TRACE_DIR")
    trace = (
        jax.profiler.trace(trace_dir) if trace_dir else contextlib.nullcontext()
    )

    world = _env_int("BENCH_WORLD", 0) or len(jax.devices())
    mesh = build_world_mesh(world)
    cfg = GPT2Config(
        vocab_size=16384,
        max_seq=_env_int("BENCH_SEQ", 512),
        n_layer=_env_int("BENCH_LAYERS", 12),
        n_head=_env_int("BENCH_HEADS", 16),
        d_model=_env_int("BENCH_DMODEL", 1024),
        attention=os.environ.get("BENCH_ATTN", "xla"),
    )
    batch = _env_int("BENCH_BATCH", 16) * world
    tokens_per_step = batch * cfg.max_seq
    # phases 1-4 run unsharded on ONE device (the whole global batch), so
    # their utilization divides by the single-chip peak; only the sharded
    # train phase sees the world-scaled peak
    chip_peak = bench_mod.chip_peak_tflops() * 1e12
    peak = chip_peak * world
    flops_tok = bench_mod.train_flops_per_token(cfg)

    with trace:
        # 1. dispatch latency: the per-call floor every step pays
        one = jnp.ones((8, 8))
        tiny = jax.jit(lambda a: a + 1.0)
        t = _timed(lambda: tiny(one), iters=20)
        out["phases"]["dispatch"] = {"ms": round(t * 1e3, 3)}
        _progress(f"dispatch floor {t * 1e3:.2f} ms/call")

        # 2. achievable MXU rate: 8 chained 4096^3 bf16 matmuls
        n, chain = 4096, 8
        a = jnp.ones((n, n), jnp.bfloat16)

        @jax.jit
        def mm(a):
            x = a
            for _ in range(chain):
                x = x @ a
            return x

        t = _timed(lambda: mm(a), iters=5)
        mm_tflops = chain * 2 * n**3 / t / 1e12
        out["phases"]["matmul"] = {
            "ms": round(t * 1e3, 2),
            "tflops": round(mm_tflops, 1),
            "fraction_of_peak": round(mm_tflops * 1e12 / chip_peak, 3),
        }
        _progress(
            f"matmul {mm_tflops:.0f} TFLOP/s "
            f"({mm_tflops * 1e12 / chip_peak:.0%} of one-chip peak)"
        )

        # model + data (bench.py sizes)
        rng = np.random.default_rng(0)
        toks = jnp.asarray(
            rng.integers(0, cfg.vocab_size, size=(batch, cfg.max_seq)), jnp.int32
        )
        model = GPT2(cfg)
        params = model.init(jax.random.PRNGKey(0), toks[:1])
        if os.environ.get("BENCH_PARAM_DTYPE", "bf16") == "bf16":
            params = jax.tree_util.tree_map(
                lambda p: p.astype(jnp.bfloat16)
                if jnp.issubdtype(p.dtype, jnp.floating) else p,
                params,
            )

        def loss_fn(p, b):
            return lm_loss(model.apply(p, b), b)

        # 3. forward only (1/3 of the analytic train FLOPs)
        fwd = jax.jit(loss_fn)
        t = _timed(lambda: fwd(params, toks), iters=5)
        out["phases"]["forward"] = {
            "ms": round(t * 1e3, 1),
            "mfu": round(tokens_per_step * (flops_tok / 3) / t / chip_peak, 4),
        }
        _progress(f"forward {t * 1e3:.0f} ms (mfu {out['phases']['forward']['mfu']:.3f})")

        # 4. forward + backward
        vg = jax.jit(lambda p, b: jax.value_and_grad(loss_fn)(p, b))
        t = _timed(lambda: vg(params, toks), iters=5)
        out["phases"]["grad"] = {
            "ms": round(t * 1e3, 1),
            "mfu": round(tokens_per_step * flops_tok / t / chip_peak, 4),
        }
        _progress(f"grad {t * 1e3:.0f} ms (mfu {out['phases']['grad']['mfu']:.3f})")

        # 5. full framework step
        tx = optax.adamw(3e-4)
        trainer = DDPTrainer(
            loss_fn, tx, mesh, Strategy.ring(world),
            donate_state=False, use_xla_fastpath=True,
        )
        state = TrainState.create(params, tx)
        t = _timed(lambda: trainer.step(state, toks), iters=5)
        train_s = t
        out["phases"]["train"] = {
            "ms": round(t * 1e3, 1),
            "mfu": round(tokens_per_step * flops_tok / t / peak, 4),
            "tokens_per_s": round(tokens_per_step / t, 1),
        }
        _progress(f"train {t * 1e3:.0f} ms (mfu {out['phases']['train']['mfu']:.3f})")

        # 6. roofline attribution from XLA's own cost model: where does the
        # gap between measured step time and the hardware bound actually
        # live?  cost_analysis() counts the compiled program's real FLOPs
        # and HBM bytes; the roofline lower bound is
        # max(flops/peak, bytes/bandwidth), and (measured - bound) is the
        # residual no analytic MFU number can attribute (VERDICT r4 weak #2)
        try:
            # AOT lower+compile does NOT reuse the jit cache, so this pays a
            # second compile of the step — acceptable inside the battery's
            # profile phase (900 s budget), and the only documented way to
            # read the partitioned module's cost model
            compiled = trainer._compiled.lower(state, toks).compile()
            ca = compiled.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0]
            # post-SPMD cost_analysis counts are PER DEVICE (hence ca[0]):
            # the bound divides by single-chip peak/bandwidth — each device
            # runs its 1/world share in the same wall-clock window
            xla_flops = float(ca.get("flops", 0.0))
            xla_bytes = float(ca.get("bytes accessed", 0.0))
            if xla_flops <= 0.0 and xla_bytes <= 0.0:
                raise RuntimeError(
                    "cost_analysis returned no flops/bytes counts on this "
                    "backend — refusing to emit a bogus all-overhead roofline"
                )
            hbm_bw = bench_mod.chip_hbm_gbps() * 1e9
            t_mxu = xla_flops / chip_peak
            t_hbm = xla_bytes / hbm_bw
            bound_s = max(t_mxu, t_hbm)
            out["phases"]["roofline"] = {
                "xla_tflops_counted": round(xla_flops / 1e12, 2),
                # same per-device basis as the XLA counts
                "analytic_tflops": round(
                    tokens_per_step * flops_tok / world / 1e12, 2
                ),
                "hbm_gbytes": round(xla_bytes / 1e9, 2),
                "mxu_bound_ms": round(t_mxu * 1e3, 2),
                "hbm_bound_ms": round(t_hbm * 1e3, 2),
                "bound": "mxu" if t_mxu >= t_hbm else "hbm",
                "roofline_ms": round(bound_s * 1e3, 2),
                "measured_ms": round(train_s * 1e3, 1),
                "residual_ms": round((train_s - bound_s) * 1e3, 1),
                "roofline_fraction": round(bound_s / train_s, 3),
            }
            _progress(
                f"roofline: {out['phases']['roofline']['bound']}-bound "
                f"{bound_s * 1e3:.1f} ms of {train_s * 1e3:.0f} ms measured "
                f"({bound_s / train_s:.0%} of step is hardware-bound)"
            )
        except Exception as e:  # noqa: BLE001 — cost model varies by backend
            out["phases"]["roofline"] = {"error": f"{type(e).__name__}: {e}"[:200]}

    if trace_dir:
        out["trace_dir"] = trace_dir
    print(json.dumps(out))


if __name__ == "__main__":
    main()
