"""Drift → re-adaptation loop: the committed closure of the variability story.

The reference ships long-horizon variability traces
(cloud/trace/bandwidth-hw.txt: iperf readings dipping 14.7 → 1.7-scale) as
the *motivation* for periodic re-adaptation, but never a committed run of
the loop itself.  This harness drives the whole loop on the virtual pod:

1. :class:`VariabilityMonitor` samples neighbor-ring probes over a
   ``--slices x --lanes`` two-level (DCN × ICI) world and appends the
   ``ts value`` trace files (the cloud/trace artifact shape);
2. a **synthetic inter-host degradation** (every host-0 ↔ host-1 link's
   bandwidth scaled by ``--factor`` from sample ``--degrade-at`` — the
   inter-VM drift the reference's study measures) is injected at the
   physical seam — the probe timing and the profiler's measured matrices —
   leaving every downstream stage real;
3. the monitor's drift detector fires ``on_drift``, which calls the real
   ``AdapCC.reconstruct_topology`` (clear contexts → detect → profile →
   ParTrees re-synthesis → rebuild engines);
4. the re-synthesized strategy re-routes its master trees around the
   degraded DCN path — its fingerprint changes — and a post-rebuild
   allreduce oracle proves the contexts came back alive.

The intra-host chain order is deliberately profile-insensitive (ParTrees
chain policy, like the reference's fixed intra-node device order), so the
degradation targets the master level, where routing decisions live.

Attribution control: before the degradation, the harness runs one
re-adaptation with the link healthy and asserts the strategy fingerprint is
*unchanged* — so the post-drift change is attributable to the drift, not to
re-synthesis nondeterminism.  (The injected profile matrices are
deterministic for the same reason.)

Usage::

    python -m benchmarks.drift_loop --world 8 --samples 24 --degrade-at 10
"""

from __future__ import annotations

import argparse
import json
import os
from typing import Dict, List, Optional, Sequence


def main(argv: Optional[Sequence[str]] = None) -> Dict:
    from adapcc_tpu.launch.launcher import apply_platform_env

    apply_platform_env()

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--slices", type=int, default=4,
                    help="DCN hosts (needs >= 3 for master re-routing)")
    ap.add_argument("--lanes", type=int, default=2, help="ICI lanes per host")
    ap.add_argument("--samples", type=int, default=24)
    ap.add_argument("--degrade-at", type=int, default=10)
    ap.add_argument("--factor", type=float, default=0.1,
                    help="degraded host-0<->host-1 bandwidth multiplier")
    ap.add_argument("--threshold", type=float, default=0.6,
                    help="drift threshold: above CPU-box probe noise "
                    "(~30-50%% swings under load), far below the injected "
                    "10x drop")
    ap.add_argument("--consecutive", type=int, default=2,
                    help="sustained-drop requirement: single noisy probes "
                    "on a loaded host must not fire a re-synthesis")
    ap.add_argument("--out-dir", default=None,
                    help="trace-file directory (cloud/trace analog)")
    ap.add_argument("--out", default=None, help="append the JSON summary here")
    ap.add_argument("--workdir", default=None,
                    help="bootstrap artifact dir (default: a temp dir)")
    args = ap.parse_args(argv)

    import tempfile

    import jax
    import jax.numpy as jnp
    import numpy as np

    from adapcc_tpu import ALLREDUCE, DETECT, AdapCC
    from adapcc_tpu.comm.two_level import build_two_level_mesh
    from adapcc_tpu.config import CommArgs
    from adapcc_tpu.strategy.xml_io import parse_strategy_xml
    from adapcc_tpu.topology.profile import NetworkProfiler
    from adapcc_tpu.topology.variability import VariabilityMonitor

    world = args.slices * args.lanes
    mesh = build_two_level_mesh(args.slices, args.lanes)
    link = {"factor": 1.0}  # emulated physical state of the host0<->host1 path
    h0 = list(range(args.lanes))
    h1 = list(range(args.lanes, 2 * args.lanes))

    # -- injection seam: what the profiler *measures* ----------------------
    # Deterministic matrices (uniform 10 GB/s, 10 us) with the degraded
    # inter-host links scaled — deterministic so a fingerprint change is
    # attributable to the drift, not to probe noise between re-synthesis
    # runs.
    def synthetic_profile(self):
        w = self.world
        lat = np.full((w, w), 1e-5)
        bw = np.full((w, w), 10.0)
        np.fill_diagonal(lat, 0.0)
        np.fill_diagonal(bw, 0.0)
        for a in h0:
            for b in h1:
                bw[a, b] = bw[b, a] = 10.0 * link["factor"]
        return lat, bw

    orig_profile = NetworkProfiler.profile
    NetworkProfiler.profile = synthetic_profile
    try:
        workdir = args.workdir or tempfile.mkdtemp(prefix="adapcc-drift-")
        comm_args = CommArgs(
            strategy_file=os.path.join(workdir, "strategy.xml"),
            logical_graph=os.path.join(workdir, "logical_graph.xml"),
            topology_dir=workdir,
            entry_point=DETECT,
            parallel_degree=2,
        )
        AdapCC.init(comm_args, mesh=mesh)
        AdapCC.setup(ALLREDUCE)
        fp_initial = parse_strategy_xml(comm_args.strategy_file).fingerprint()

        # -- attribution control: healthy re-adaptation is a no-op ---------
        AdapCC.reconstruct_topology(comm_args, ALLREDUCE)
        fp_control = parse_strategy_xml(comm_args.strategy_file).fingerprint()
        if fp_control != fp_initial:
            raise RuntimeError(
                "control re-adaptation changed the strategy on a healthy "
                f"fabric ({fp_initial} -> {fp_control}); drift attribution "
                "would be unsound"
            )

        # -- monitored run with mid-run degradation ------------------------
        drift_events: List[Dict] = []

        def on_drift(gbps: float) -> None:
            if drift_events:
                return  # re-adapt once per incident
            drift_events.append({"sample": state["i"], "bw_gbps": gbps})
            AdapCC.reconstruct_topology(comm_args, ALLREDUCE)

        # on_drift attaches after warmup — compile-time spikes must not
        # consume the one re-adaptation
        monitor = VariabilityMonitor(
            mesh,
            interval_s=0.0,
            probe_floats=1 << 14,
            drift_threshold=args.threshold,
            drift_consecutive=args.consecutive,
            drift_direction="down",  # re-adaptation exists for degradations
        )
        # probe-timing seam: the neighbor-ring probe slows when the path does
        orig_probe = monitor._bw_probe
        monitor._bw_probe = lambda: orig_probe() / link["factor"]

        # warm the probe programs OUTSIDE the measured trace: first-call
        # compile time reads as a huge upward bandwidth step and would trip
        # the (direction-agnostic) drift detector at sample 1
        state = {"i": -1}
        for _ in range(3):
            monitor.sample()
        monitor.bandwidth_trace.clear()
        monitor.latency_trace.clear()
        monitor.on_drift = on_drift
        monitor.out_dir = args.out_dir
        if args.out_dir:
            os.makedirs(args.out_dir, exist_ok=True)
            for name in ("bandwidth.txt", "latency.txt"):
                # trace files are append-mode; a refreshed artifact must not
                # inherit a previous run's rows
                try:
                    os.remove(os.path.join(args.out_dir, name))
                except FileNotFoundError:
                    pass

        for i in range(args.samples):
            state["i"] = i
            if i == args.degrade_at:
                link["factor"] = args.factor
            monitor.sample()

        fp_after = parse_strategy_xml(comm_args.strategy_file).fingerprint()

        # -- post-rebuild liveness oracle ----------------------------------
        x = jnp.stack([jnp.ones(16) * 3.0 for _ in range(world)])
        out = AdapCC.allreduce(x, size=16)
        assert np.allclose(np.asarray(out), 3.0 * world), "post-rebuild allreduce"
        AdapCC.clear(ALLREDUCE)

        bw_values = [v for _, v in monitor.bandwidth_trace]
        summary = {
            "world": world,
            "samples": args.samples,
            "degrade_at": args.degrade_at,
            "factor": args.factor,
            "drift_detected_at": drift_events[0]["sample"] if drift_events else None,
            "bw_before_median": round(
                float(np.median(bw_values[: args.degrade_at])), 4
            ),
            "bw_after_median": round(
                float(np.median(bw_values[args.degrade_at :])), 4
            ),
            "fingerprint_initial": fp_initial,
            "fingerprint_control": fp_control,
            "fingerprint_after_drift": fp_after,
            "strategy_changed": fp_after != fp_initial,
            "backend": jax.devices()[0].platform,
        }
        print(json.dumps(summary), flush=True)
        if args.out:
            with open(args.out, "a") as f:
                f.write(json.dumps(summary) + "\n")
        return summary
    finally:
        NetworkProfiler.profile = orig_profile


if __name__ == "__main__":
    main()
