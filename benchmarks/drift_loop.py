"""Drift → re-adaptation loop: the committed closure of the variability story.

The reference ships long-horizon variability traces
(cloud/trace/bandwidth-hw.txt: iperf readings dipping 14.7 → 1.7-scale) as
the *motivation* for periodic re-adaptation, but never a committed run of
the loop itself.  This harness drives the whole loop on the virtual pod,
A/B-ing BOTH re-adaptation paths against the same injected inter-host
degradation:

**Full-rebuild arm** (the reference's loop):

1. :class:`VariabilityMonitor` samples neighbor-ring probes over a
   ``--slices x --lanes`` two-level (DCN × ICI) world and appends the
   ``ts value`` trace files (the cloud/trace artifact shape);
2. a **synthetic inter-host degradation** (every host-0 ↔ host-1 link's
   bandwidth scaled by ``--factor`` from sample ``--degrade-at`` — the
   inter-VM drift the reference's study measures) is injected at the
   physical seam — the probe timing and the profiler's measured matrices —
   leaving every downstream stage real;
3. the monitor's drift detector fires ``on_drift``, which calls the real
   ``AdapCC.reconstruct_topology`` (clear contexts → detect → profile →
   ParTrees re-synthesis → rebuild engines) — active probe traffic plus a
   cold recompile, with the walltime recorded as the **rebuild stall**;
4. the re-synthesized strategy re-routes its master trees around the
   degraded DCN path — its fingerprint changes — and a post-rebuild
   allreduce oracle proves the contexts came back alive.

**Hot-swap arm** (docs/ADAPT.md — this PR's headline):

the same degradation factor drives the *passive* loop instead: a
:class:`DriftDetector` is fed the degraded timing series (what the flows
already measure — zero probe traffic), fires, the α-β model re-calibrates
with decay, sim-rank re-ranks the candidate strategies under the corrected
costs, the winner is AOT-compiled through the standby cache, and adoption
is one ``advance_epoch`` — the **epoch-swap stall**, measured next to the
rebuild arm's.  The A/B row (``hotswap_stall_s`` vs ``rebuild_stall_s``)
prices what the closed loop buys.

Attribution control (both arms): with the link healthy, a full
re-adaptation leaves the strategy fingerprint *unchanged* and the passive
loop performs *zero* swaps — so each arm's post-drift change is
attributable to the drift, not to re-synthesis nondeterminism.

Usage::

    python -m benchmarks.drift_loop --world 8 --samples 24 --degrade-at 10
"""

from __future__ import annotations

import argparse
import json
import os
import time
from typing import Dict, List, Optional, Sequence


def main(argv: Optional[Sequence[str]] = None) -> Dict:
    from adapcc_tpu.launch.launcher import apply_platform_env

    apply_platform_env()

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--slices", type=int, default=4,
                    help="DCN hosts (needs >= 3 for master re-routing)")
    ap.add_argument("--lanes", type=int, default=2, help="ICI lanes per host")
    ap.add_argument("--samples", type=int, default=24)
    ap.add_argument("--degrade-at", type=int, default=10)
    ap.add_argument("--factor", type=float, default=0.1,
                    help="degraded host-0<->host-1 bandwidth multiplier")
    ap.add_argument("--threshold", type=float, default=0.6,
                    help="drift threshold: above CPU-box probe noise "
                    "(~30-50%% swings under load), far below the injected "
                    "10x drop")
    ap.add_argument("--consecutive", type=int, default=2,
                    help="sustained-drop requirement: single noisy probes "
                    "on a loaded host must not fire a re-synthesis")
    ap.add_argument("--drift-window", type=int, default=4,
                    help="hot-swap arm: passive detector window (samples)")
    ap.add_argument("--out-dir", default=None,
                    help="trace-file directory (cloud/trace analog)")
    ap.add_argument("--out", default=None, help="append the JSON summary here")
    ap.add_argument("--workdir", default=None,
                    help="bootstrap artifact dir (default: a temp dir)")
    args = ap.parse_args(argv)

    import tempfile

    import jax
    import jax.numpy as jnp
    import numpy as np

    from adapcc_tpu import ALLREDUCE, DETECT, AdapCC
    from adapcc_tpu.comm.two_level import build_two_level_mesh
    from adapcc_tpu.config import CommArgs
    from adapcc_tpu.strategy.xml_io import parse_strategy_xml
    from adapcc_tpu.topology.profile import NetworkProfiler
    from adapcc_tpu.topology.variability import VariabilityMonitor

    world = args.slices * args.lanes
    mesh = build_two_level_mesh(args.slices, args.lanes)
    link = {"factor": 1.0}  # emulated physical state of the host0<->host1 path
    h0 = list(range(args.lanes))
    h1 = list(range(args.lanes, 2 * args.lanes))

    # -- injection seam: what the profiler *measures* ----------------------
    # Deterministic matrices (uniform 10 GB/s, 10 us) with the degraded
    # inter-host links scaled — deterministic so a fingerprint change is
    # attributable to the drift, not to probe noise between re-synthesis
    # runs.  ONE definition feeds both arms: the profiler seam (rebuild)
    # and the passive calibration (hot-swap).
    def degraded_matrices(factor: float):
        lat = np.full((world, world), 1e-5)
        bw = np.full((world, world), 10.0)
        np.fill_diagonal(lat, 0.0)
        np.fill_diagonal(bw, 0.0)
        for a in h0:
            for b in h1:
                bw[a, b] = bw[b, a] = 10.0 * factor
        return lat, bw

    def synthetic_profile(self):
        return degraded_matrices(link["factor"])

    orig_profile = NetworkProfiler.profile
    NetworkProfiler.profile = synthetic_profile
    try:
        workdir = args.workdir or tempfile.mkdtemp(prefix="adapcc-drift-")
        comm_args = CommArgs(
            strategy_file=os.path.join(workdir, "strategy.xml"),
            logical_graph=os.path.join(workdir, "logical_graph.xml"),
            topology_dir=workdir,
            entry_point=DETECT,
            parallel_degree=2,
        )
        AdapCC.init(comm_args, mesh=mesh)
        AdapCC.setup(ALLREDUCE)
        fp_initial = parse_strategy_xml(comm_args.strategy_file).fingerprint()

        # -- attribution control: healthy re-adaptation is a no-op ---------
        AdapCC.reconstruct_topology(comm_args, ALLREDUCE)
        fp_control = parse_strategy_xml(comm_args.strategy_file).fingerprint()
        if fp_control != fp_initial:
            raise RuntimeError(
                "control re-adaptation changed the strategy on a healthy "
                f"fabric ({fp_initial} -> {fp_control}); drift attribution "
                "would be unsound"
            )

        # -- hot-swap arm (docs/ADAPT.md): the passive closed loop ---------
        hotswap = _hot_swap_arm(
            AdapCC.communicator, world, degraded_matrices, args.factor,
            window=args.drift_window, workdir=workdir,
        )

        # -- monitored run with mid-run degradation (full-rebuild arm) -----
        drift_events: List[Dict] = []
        rebuild = {"stall_s": None}

        def on_drift(gbps: float) -> None:
            if drift_events:
                return  # re-adapt once per incident
            drift_events.append({"sample": state["i"], "bw_gbps": gbps})
            t0 = time.perf_counter()
            AdapCC.reconstruct_topology(comm_args, ALLREDUCE)
            rebuild["stall_s"] = time.perf_counter() - t0

        # on_drift attaches after warmup — compile-time spikes must not
        # consume the one re-adaptation
        monitor = VariabilityMonitor(
            mesh,
            interval_s=0.0,
            probe_floats=1 << 14,
            drift_threshold=args.threshold,
            drift_consecutive=args.consecutive,
            drift_direction="down",  # re-adaptation exists for degradations
        )
        # probe-timing seam: the neighbor-ring probe slows when the path does
        orig_probe = monitor._bw_probe
        monitor._bw_probe = lambda: orig_probe() / link["factor"]

        # warm the probe programs OUTSIDE the measured trace: first-call
        # compile time reads as a huge upward bandwidth step and would trip
        # the (direction-agnostic) drift detector at sample 1
        state = {"i": -1}
        for _ in range(3):
            monitor.sample()
        monitor.bandwidth_trace.clear()
        monitor.latency_trace.clear()
        monitor.on_drift = on_drift
        monitor.out_dir = args.out_dir
        if args.out_dir:
            os.makedirs(args.out_dir, exist_ok=True)
            for name in ("bandwidth.txt", "latency.txt"):
                # trace files are append-mode; a refreshed artifact must not
                # inherit a previous run's rows
                try:
                    os.remove(os.path.join(args.out_dir, name))
                except FileNotFoundError:
                    pass

        for i in range(args.samples):
            state["i"] = i
            if i == args.degrade_at:
                link["factor"] = args.factor
            monitor.sample()

        fp_after = parse_strategy_xml(comm_args.strategy_file).fingerprint()

        # -- post-rebuild liveness oracle ----------------------------------
        x = jnp.stack([jnp.ones(16) * 3.0 for _ in range(world)])
        out = AdapCC.allreduce(x, size=16)
        assert np.allclose(np.asarray(out), 3.0 * world), "post-rebuild allreduce"
        AdapCC.clear(ALLREDUCE)

        bw_values = [v for _, v in monitor.bandwidth_trace]
        summary = {
            "world": world,
            "samples": args.samples,
            "degrade_at": args.degrade_at,
            "factor": args.factor,
            "drift_detected_at": drift_events[0]["sample"] if drift_events else None,
            "bw_before_median": round(
                float(np.median(bw_values[: args.degrade_at])), 4
            ),
            "bw_after_median": round(
                float(np.median(bw_values[args.degrade_at :])), 4
            ),
            "fingerprint_initial": fp_initial,
            "fingerprint_control": fp_control,
            "fingerprint_after_drift": fp_after,
            "strategy_changed": fp_after != fp_initial,
            # the A/B headline: what one re-adaptation STALLS the job for
            # on each arm — the full-rebuild teardown walltime vs the
            # epoch-swap's advance_epoch walltime (hot-swap AOT warm runs
            # off the critical path and is reported separately)
            "rebuild_stall_s": (
                round(rebuild["stall_s"], 6)
                if rebuild["stall_s"] is not None else None
            ),
            "hotswap_stall_s": hotswap["stall_s"],
            "rebuild": rebuild["stall_s"] and {
                "stall_s": round(rebuild["stall_s"], 6),
                "fingerprint_changed": fp_after != fp_initial,
            },
            "hotswap": hotswap,
            "backend": jax.devices()[0].platform,
        }
        print(json.dumps(summary), flush=True)
        if args.out:
            with open(args.out, "a") as f:
                f.write(json.dumps(summary) + "\n")
        return summary
    finally:
        NetworkProfiler.profile = orig_profile


def _hot_swap_arm(
    communicator, world: int, degraded_matrices, factor: float,
    window: int, workdir: str,
) -> Dict:
    """Run the passive closed loop (docs/ADAPT.md) against the same
    injected degradation: healthy control first (zero swaps pinned), then
    the degraded timing series → detect → re-calibrate → re-rank →
    epoch-swap, with the swap stall measured next to the rebuild arm's.

    The arm runs on its own flat engine over the same devices, starting
    from the flat default ring (the pre-synthesis incumbent a world runs
    before any strategy artifact exists) — the stale strategy the loop
    must route around.  Timings fed to the detector are the calibrated
    model's own predictions under the healthy/degraded matrices: exactly
    what a live run's DispatchTimer medians converge to, deterministic so
    the A/B is attributable.
    """
    import jax.numpy as jnp
    import numpy as np

    from adapcc_tpu.adapt import AdaptationController
    from adapcc_tpu.comm.engine import CollectiveEngine
    from adapcc_tpu.comm.mesh import build_world_mesh
    from adapcc_tpu.sim.calibrate import calibrate_from_matrices
    from adapcc_tpu.strategy.ir import Strategy
    from adapcc_tpu.strategy.synthesizer import Synthesizer
    from adapcc_tpu.tuner.db import TuningDatabase, TuningKey, size_bucket
    from adapcc_tpu.tuner.policy import TuningPolicy
    from adapcc_tpu.utils.observability import CollectiveTrace

    ips = {r: ip for r, ip in enumerate(communicator.ip_table)}
    lat_h, bw_h = degraded_matrices(1.0)
    healthy = calibrate_from_matrices(lat_h, bw_h, ips, source="drift-healthy")
    lat_d, bw_d = degraded_matrices(factor)
    degraded = calibrate_from_matrices(
        lat_d, bw_d, ips, source="drift-degraded"
    )

    mesh = build_world_mesh(world)
    trace = CollectiveTrace()
    incumbent = Strategy.ring(world, 1, ips)
    engine = CollectiveEngine(mesh, incumbent, trace=trace)
    synthesizer = Synthesizer(None, list(communicator.ip_table))
    cal_path = os.path.join(workdir, "calibration.json")
    from adapcc_tpu.adapt import DriftDetector
    from adapcc_tpu.tuner.db import topology_fingerprint

    fingerprint = topology_fingerprint(world, ips)
    controller = AdaptationController(
        engine,
        synthesizer,
        mode="swap",
        cost_model=healthy.cost_model(),
        calibration_path=cal_path,
        nbytes=1 << 20,
        parallel_degree=2,
        fingerprint=fingerprint,
        detector=DriftDetector(
            world, fingerprint, cost_model=healthy.cost_model(),
            window=window,
        ),
        warm_shape=(64,),
    )

    nb = 1 << 20
    key = TuningKey(
        "allreduce", size_bucket(nb), world, controller.fingerprint,
        "xla", 0, "off",
    )
    healthy_pred = controller.detector.predicted_s(key)
    deg_policy = TuningPolicy(
        TuningDatabase(persist=False), world, "drift-loop",
        cost_model=degraded.cost_model(),
    )
    degraded_obs = deg_policy.prior_time(key, key.size_bucket)

    # attribution control: a healthy series must produce ZERO swaps
    for i in range(window):
        controller.observe(key, healthy_pred * (1.05 if i % 2 else 0.95))
    control_report = controller.maybe_adapt()
    if control_report.swapped:
        raise RuntimeError(
            "hot-swap control adapted on a healthy series; drift "
            "attribution would be unsound"
        )

    # the degradation lands in the measured series — nothing else
    detection_samples = 0
    fired = False
    for i in range(window):
        controller.observe(key, degraded_obs * (1.02 if i % 2 else 0.98))
        detection_samples = i + 1
        if controller.check().drifted:
            fired = True
            break
    report = controller.maybe_adapt()

    # the post-swap dispatch must replay a warm program (cache-key switch)
    x = jnp.ones((world, 64), jnp.float32)
    engine.all_reduce(x, active_gpus=list(range(world)))
    cache_hit = bool(trace.events()[-1].extra.get("cache_hit"))

    from adapcc_tpu.sim.cost_model import adaptation_cost, bottleneck_ring_coeffs

    priced = None
    if report.swapped and report.incumbent_pred_s is not None:
        cost = adaptation_cost(
            world, nb,
            bottleneck_ring_coeffs(healthy.cost_model(), world),
            stale_steady_s=report.incumbent_pred_s,
            adapted_steady_s=report.winner_pred_s,
        )
        priced = {
            k: (round(v, 9) if np.isfinite(v) else None)
            for k, v in cost.items()
        }
    return {
        "control_swapped": bool(control_report.swapped),
        "fired": fired,
        "detection_samples": detection_samples,
        "window": window,
        "outcome": report.outcome,
        "swapped": bool(report.swapped),
        "winner_label": report.winner_label,
        "fingerprint_before": incumbent.fingerprint(),
        "fingerprint_after": engine.strategy.fingerprint(),
        "strategy_changed": (
            engine.strategy.fingerprint() != incumbent.fingerprint()
        ),
        "cache_hit": cache_hit,
        "stall_s": round(report.stall_s, 6) if report.stall_s else None,
        "aot_warm_s": (
            round(report.aot_warm_s, 6) if report.aot_warm_s else None
        ),
        "recalibrated": report.recalibrated,
        "ranked": report.ranked,
        "priced": priced,
    }


if __name__ == "__main__":
    main()
