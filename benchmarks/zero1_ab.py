"""ZeRO-1 data-plane A/B: XLA psum_scatter/all_gather vs the Pallas ring.

One JSON line per variant: steady-state step ms of the full
``zero1_train_step`` program (grad → reduce-scatter → sharded adam →
all-gather) on an MLP sized by ``--params`` (default ~8M), with the
transient-aware warmup the tunnel requires (PERF_NOTES methodology).

At world=1 (one real chip) both collectives are degenerate, so the A/B
measures the ring path's *plumbing* cost (tile-aligned padding + the
chunk-order roll) — the honest single-chip statement; the ring's bandwidth
case needs a real pod and is pinned functionally by the interpret-mode
parity tests (tests/test_fsdp.py).

Usage::

    python -m benchmarks.zero1_ab --steps 20 --json
"""

from __future__ import annotations

import argparse
import json
import time
from typing import Optional, Sequence


def main(argv: Optional[Sequence[str]] = None) -> int:
    from adapcc_tpu.launch.launcher import apply_platform_env

    apply_platform_env()

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from adapcc_tpu.comm.mesh import build_world_mesh
    from adapcc_tpu.parallel.fsdp import Zero1Optimizer, zero1_train_step

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--params", type=int, default=8 << 20,
                    help="approx parameter count (two square layers)")
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--world", type=int, default=0)
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    world = args.world or len(jax.devices())
    mesh = build_world_mesh(world)
    d = int(np.sqrt(args.params / 2))
    rng = np.random.default_rng(0)
    params = {
        "w1": jnp.asarray(rng.normal(size=(d, d)) * 0.02, jnp.float32),
        "w2": jnp.asarray(rng.normal(size=(d, d)) * 0.02, jnp.float32),
    }
    x = jnp.asarray(rng.normal(size=(args.batch * world, d)), jnp.float32)
    y = jnp.asarray(rng.normal(size=(args.batch * world, d)), jnp.float32)

    def loss_fn(p, batch):
        bx, by = batch
        h = jnp.tanh(bx @ p["w1"])
        return jnp.mean((h @ p["w2"] - by) ** 2)

    platform = jax.devices()[0].platform
    rows = []
    for ring in (False, True):
        opt = Zero1Optimizer(optax.adam(1e-3), mesh, ring=ring)
        master, opt_state = opt.init(params)
        step = zero1_train_step(loss_fn, opt, mesh)
        p = jax.tree_util.tree_map(jnp.array, params)
        for _ in range(max(args.warmup, 2)):  # tunnel migration transient
            p, master, opt_state, losses = step(p, master, opt_state, (x, y))
            jax.block_until_ready(losses)
        t0 = time.perf_counter()
        for _ in range(args.steps):
            p, master, opt_state, losses = step(p, master, opt_state, (x, y))
            jax.device_get(losses)  # forced sync closes the window
        per_step = (time.perf_counter() - t0) / args.steps
        rows.append({
            "metric": "zero1_step_ms",
            "data_plane": "pallas_ring" if ring else "xla",
            "world": world,
            "platform": platform,
            "params": 2 * d * d,
            "step_ms": round(per_step * 1e3, 3),
        })

    for r in rows:
        print(json.dumps(r) if args.json else r, flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
