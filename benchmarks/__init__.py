"""Benchmark suite (the analog of the reference's nccl-perf/ tree).

The reference ships a fork of NVIDIA nccl-tests plus hand-written chunked-tree
prototypes as its baseline/competitive harness (SURVEY.md §4.4, §6).  Here the
same role is played by :mod:`benchmarks.collectives`: a message-size sweep over
every collective the engine provides, reporting algbw/busbw with the standard
nccl-tests correction factors (nccl-perf/benchmark/PERFORMANCE.md), comparing
the framework's strategy-shaped schedules against raw XLA collectives and the
Pallas ring kernel on the same mesh.
"""

from benchmarks.collectives import (
    BUS_FACTORS,
    BenchResult,
    format_table,
    parse_size,
    run_sweep,
)

__all__ = [
    "BUS_FACTORS",
    "BenchResult",
    "format_table",
    "parse_size",
    "run_sweep",
]
